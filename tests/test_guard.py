"""graftguard tests: launch deadlines, wedge detection, the degradation
ladder (host-fallback masks bit-identical to verify_batch, BUSY for
bulk), crash-only reboot + canary, poison-record bisection, the chaos
``wedge`` drill, OP_STATS/parser round trips, and the kill-proof bench
emit.
"""

import json
import threading
import time
from datetime import datetime
from types import SimpleNamespace

import numpy as np
import pytest

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
from hotstuff_tpu.sidecar import protocol as proto
from hotstuff_tpu.sidecar import sched as vsched
from hotstuff_tpu.sidecar.guard import (BusyReply, GuardStats,
                                        LaunchDeadlines, LaunchGuard,
                                        Quarantine, WedgedLaunch,
                                        bisect_poison)
from hotstuff_tpu.sidecar.service import (ChaosState, SidecarServer,
                                          VerifyEngine)

# Tight real-time deadlines: the monitor must actually preempt a hung
# thread, so tests use tens of milliseconds instead of a virtual clock.
# warm_boot=True keeps launch deadlines on the 0.15 s warm grace; the
# compile budget stays generous enough that a CONTENDED host's canary
# (real work: 8 host verifies after a cache teardown) never false-wedges
# the recovery the tests assert on.
FAST = dict(warm_boot=True, compile_budget_s=2.0, warm_grace_s=0.15,
            min_deadline_s=0.05)


def _sigs(n, tamper=(), seed=7):
    rng = np.random.default_rng(seed)
    msgs, pks, sigs = [], [], []
    for i in range(n):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in tamper:
            sig = sig[:1] + bytes([sig[1] ^ 0xFF]) + sig[2:]
        msgs.append(msg)
        pks.append(pk)
        sigs.append(sig)
    return msgs, pks, sigs


def _wait(pred, timeout=20.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.005)
    return False


@pytest.fixture
def guard():
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    yield g
    g.close()


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

def test_deadline_cold_boot_gets_compile_budget():
    d = LaunchDeadlines(warm_boot=False, compile_budget_s=180.0,
                        warm_grace_s=30.0)
    assert d.deadline_s("launch:512") == 180.0


def test_deadline_warm_boot_gets_grace():
    d = LaunchDeadlines(warm_boot=True, compile_budget_s=180.0,
                        warm_grace_s=30.0)
    assert d.deadline_s("launch:512") == 30.0


def test_deadline_tightens_to_p99_multiple():
    d = LaunchDeadlines(warm_boot=True, warm_grace_s=30.0,
                        p99_multiple=8.0, min_deadline_s=0.5)
    for _ in range(LaunchDeadlines.MIN_OBSERVATIONS):
        d.observe("launch:64", 0.25)
    assert d.deadline_s("launch:64") == pytest.approx(2.0)
    # other shapes keep the fallback
    assert d.deadline_s("launch:512") == 30.0


def test_deadline_floor_under_fast_shapes():
    d = LaunchDeadlines(warm_boot=True, p99_multiple=8.0,
                        min_deadline_s=1.0)
    for _ in range(LaunchDeadlines.MIN_OBSERVATIONS):
        d.observe("launch:8", 0.001)
    assert d.deadline_s("launch:8") == 1.0


def test_deadline_env_knobs(monkeypatch):
    monkeypatch.setenv("HOTSTUFF_TPU_GUARD_COMPILE_BUDGET_S", "77")
    monkeypatch.setenv("HOTSTUFF_TPU_GUARD_WARM_GRACE_S", "11")
    assert LaunchDeadlines(warm_boot=False).deadline_s("x") == 77.0
    assert LaunchDeadlines(warm_boot=True).deadline_s("x") == 11.0


def test_deadlines_from_manifest(tmp_path):
    from hotstuff_tpu.utils.xla_cache import CompileManifest

    path = str(tmp_path / "manifest.json")
    m = CompileManifest(path)
    d = LaunchDeadlines.from_manifest(m, "kern123")
    assert not d.warm_boot  # empty manifest = cold boot
    m.record("kern123", "warmup:512", 12.5, cache_dir="/x")
    d = LaunchDeadlines.from_manifest(m, "kern123")
    assert d.warm_boot
    assert m.shape_walls("kern123") == {"warmup:512": 12.5}
    # a different kernel hash is still cold
    assert not LaunchDeadlines.from_manifest(m, "other").warm_boot


def test_manifest_cold_wall(tmp_path):
    from hotstuff_tpu.utils.xla_cache import CompileManifest

    m = CompileManifest(str(tmp_path / "manifest.json"))
    assert m.cold_wall_s() is None
    m.record_run("k", hits=0, misses=4, wall_s=149.0, now=1.0)
    m.record_run("k", hits=4, misses=0, wall_s=38.0, now=2.0)
    assert m.cold_wall_s() == 149.0  # warm runs never count as cold


# ---------------------------------------------------------------------------
# the guard itself
# ---------------------------------------------------------------------------

def test_guard_returns_result_and_observes(guard):
    assert guard.call("k", lambda: 41 + 1) == 42
    assert guard.deadlines.snapshot()["k"]["n"] == 1


def test_guard_wedges_a_hung_launch_within_deadline(guard):
    release = threading.Event()
    t0 = time.monotonic()
    with pytest.raises(WedgedLaunch):
        guard.call("k", release.wait)
    wall = time.monotonic() - t0
    assert wall < 2.0  # deadline 0.15s + monitor poll slack
    assert guard.stats.snapshot()["wedges"] == 1
    release.set()  # let the abandoned thread exit


def test_guard_propagates_exceptions(guard):
    with pytest.raises(RuntimeError, match="boom"):
        guard.call("k", lambda: (_ for _ in ()).throw(RuntimeError("boom")))


def test_guard_late_completion_is_discarded(guard):
    release = threading.Event()
    finished = threading.Event()

    def thunk():
        release.wait()
        finished.set()
        return "late"

    with pytest.raises(WedgedLaunch):
        guard.call("k", thunk)
    release.set()
    assert finished.wait(5.0)
    assert _wait(lambda: guard.stats.snapshot()["late_completions"] == 1)
    # a fresh launch on a fresh disposable thread still works
    assert guard.call("k", lambda: "fresh") == "fresh"


def test_guard_snapshot_is_json_safe(guard):
    with pytest.raises(WedgedLaunch):
        guard.call("k", threading.Event().wait)
    json.dumps(guard.snapshot())


# ---------------------------------------------------------------------------
# quarantine + bisection
# ---------------------------------------------------------------------------

def test_quarantine_repeat_offenders_flow():
    q = Quarantine()
    recs = [("m%d" % i, "p", "s") for i in range(4)]
    assert q.note_wedged(recs) == 0          # first wedge: weather
    assert q.pending() == []
    assert q.note_wedged(recs[:2]) == 2      # repeat: pending bisection
    assert set(q.pending()) == set(recs[:2])
    assert q.resolve([recs[0]]) == 1
    assert q.is_poisoned(recs[0]) and not q.is_poisoned(recs[1])
    assert q.has_poison()
    json.dumps(q.snapshot())


def test_bisect_poison_isolates_single_record():
    recs = list(range(8))
    probes = []

    def probe(subset):
        probes.append(list(subset))
        return 5 not in subset

    assert bisect_poison(recs, probe) == [5]
    assert len(probes) <= 2 * len(recs)


def test_bisect_poison_finds_two_records():
    recs = list(range(8))
    assert sorted(bisect_poison(
        recs, lambda s: not ({1, 6} & set(s)))) == [1, 6]


def test_bisect_poison_interaction_set_stays_quarantined():
    # The pair wedges only together: neither half wedges alone, so the
    # whole set is returned (never silently released).
    recs = [0, 1]
    assert sorted(bisect_poison(
        recs, lambda s: not {0, 1} <= set(s))) == [0, 1]


def test_bisect_poison_probe_budget_leaves_rest_quarantined():
    recs = list(range(16))
    out = bisect_poison(recs, lambda s: 3 not in s, max_probes=1)
    # one probe (the full set, wedges) -> everything stays quarantined
    assert sorted(out) == recs


# ---------------------------------------------------------------------------
# the engine ladder
# ---------------------------------------------------------------------------

def _engine(**kw):
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    return VerifyEngine(use_host=True, guard=g, **kw), g


def _collector():
    done = {}
    cond = threading.Condition()

    def reply_to(rid):
        def _reply(mask):
            with cond:
                done[rid] = mask
                cond.notify_all()
        return _reply

    def wait_for(*rids, timeout=20.0):
        with cond:
            return cond.wait_for(lambda: all(r in done for r in rids),
                                 timeout=timeout)
    return done, reply_to, wait_for


def test_wedge_ladder_masks_and_busy_direct():
    """Direct ladder execution on a mixed batch: latency answered from
    the host path bit-identical to verify_batch, bulk answered BUSY."""
    engine, guard = _engine()
    try:
        msgs, pks, sigs = _sigs(6, tamper={1, 4}, seed=3)
        done, reply_to, wait_for = _collector()
        batch = [
            vsched.Pending(proto.VerifyRequest(1, msgs[:3], pks[:3],
                                               sigs[:3]),
                           reply_to(1), vsched.LATENCY),
            vsched.Pending(proto.VerifyRequest(2, msgs[3:], pks[3:],
                                               sigs[3:]),
                           reply_to(2), vsched.BULK),
        ]
        engine._wedge_ladder(batch, "launch:8", stage="test")
        # ladder replies land async (the host fallback runs off the
        # engine thread so queued verifies drain concurrently)
        assert wait_for(1, 2)
        expect = [bool(b) for b in eddsa.verify_batch(msgs, pks, sigs)]
        assert done[1] == expect[:3]
        assert isinstance(done[2], BusyReply)
        assert done[2].retry_after_ms >= 0
        snap = engine.stats_snapshot()["guard"]
        assert snap["host_fallback_records"] == 3
        assert snap["busy_replies"] == 1
        assert snap["suspect_records"] == 6
        assert _wait(lambda: not engine._rebooting and engine._device_ok)
        assert engine.stats_snapshot()["guard"]["reboots"] == 1
    finally:
        engine.stop()
        guard.close()


def test_chaos_wedge_end_to_end_and_recovery():
    """The full drill through submit(): OP_CHAOS-shaped wedge -> ladder
    host-fallback mask -> async crash-only reboot (bulk BUSY, rewarm,
    canary) -> device routing resumes."""
    chaos = ChaosState()
    rewarmed = []
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    engine = VerifyEngine(
        use_host=True, guard=g, chaos=chaos,
        rewarm_fn=lambda: (rewarmed.append(1), time.sleep(0.15)))
    try:
        msgs, pks, sigs = _sigs(8, tamper={3}, seed=5)
        expect = [bool(b) for b in eddsa.verify_batch(msgs, pks, sigs)]
        done, reply_to, wait_for = _collector()
        chaos.configure({"wedge": 1})
        assert engine.submit(proto.VerifyRequest(1, msgs, pks, sigs),
                             reply_to(1), cls=vsched.LATENCY)
        assert wait_for(1)
        assert done[1] == expect  # bit-identical host fallback
        # bulk offered during the reboot window sheds to BUSY
        assert _wait(lambda: engine._rebooting, timeout=5.0)
        assert not engine.submit(proto.VerifyRequest(2, msgs, pks, sigs),
                                 reply_to(2), cls=vsched.BULK)
        assert _wait(lambda: engine._device_ok and not engine._rebooting)
        assert rewarmed
        snap = engine.stats_snapshot()["guard"]
        assert snap["wedges"] == 1 and snap["reboots"] == 1
        assert snap["canary_passes"] >= 1
        assert snap["busy_replies"] >= 1
        # post-recovery traffic serves normally again
        assert engine.submit(proto.VerifyRequest(3, msgs, pks, sigs),
                             reply_to(3), cls=vsched.LATENCY)
        assert wait_for(3)
        assert done[3] == expect
    finally:
        engine.stop()
        g.close()


def test_chaos_wedge_bls_launch_transient_reply_and_reboot():
    """BLS launches ride the guard (ROADMAP item 3 closed): a wedged
    pairing answers TRANSIENT (None — never a cacheable [False] for a
    verdict nobody computed) and starts the crash-only reboot instead of
    parking the engine thread; the shared verdict cache stays empty and
    the recovered engine serves traffic normally."""
    from hotstuff_tpu.offchain import bls12381 as bls

    chaos = ChaosState()
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    engine = VerifyEngine(use_host=True, guard=g, chaos=chaos)
    try:
        msg = b"qc digest under bls".ljust(32, b"\0")
        keys = [bls.key_gen(bytes([i]) * 32) for i in range(1, 4)]
        pks = [bls.g1_encode(pk) for _, pk in keys]
        sigs = [bls.g2_encode(bls.sign(sk, msg)) for sk, _ in keys]
        done, reply_to, wait_for = _collector()
        chaos.configure({"wedge": 1})
        assert engine.submit(proto.BlsVotesRequest(1, msg, pks, sigs),
                             reply_to(1), is_bls=True)
        assert wait_for(1)
        assert done[1] is None  # transient form, not a verdict mask
        snap = engine.stats_snapshot()["guard"]
        assert snap["wedges"] == 1
        assert _wait(lambda: not engine._rebooting and engine._device_ok)
        assert engine.stats_snapshot()["guard"]["reboots"] == 1
        # Nothing entered the shared verdict cache — a wedge must never
        # record a [False] other replicas would then share.
        assert not engine._verdicts
        # ... and the recovered engine serves verify traffic normally
        # (a real pairing would overrun FAST's test deadlines, so the
        # health probe is an Ed25519 batch).
        msgs, vpks, vsigs = _sigs(4, tamper={2}, seed=9)
        expect = [bool(b) for b in eddsa.verify_batch(msgs, vpks, vsigs)]
        assert engine.submit(proto.VerifyRequest(2, msgs, vpks, vsigs),
                             reply_to(2), cls=vsched.LATENCY)
        assert wait_for(2)
        assert done[2] == expect
    finally:
        engine.stop()
        g.close()


def test_repeat_wedge_triggers_poison_bisection():
    """A cursed record that wedges every launch carrying it: after the
    second wedge the bisection isolates EXACTLY that record, and later
    batches verify it on the host poison lane while co-batched records
    ride the device leg again — no third wedge."""
    engine, g = _engine()
    msgs, pks, sigs = _sigs(6, tamper={2}, seed=9)
    cursed = (msgs[2], pks[2], sigs[2])
    real_submit = VerifyEngine._verify_submit

    def hang_on_cursed(self, m, p, s, force_device=False):
        if cursed[0] in m:
            return lambda: threading.Event().wait()
        return real_submit(self, m, p, s, force_device=force_device)

    engine._verify_submit = hang_on_cursed.__get__(engine)
    try:
        expect = [True, True, False, True, True, True]
        done, reply_to, wait_for = _collector()
        for rid in (1, 2):
            assert engine.submit(proto.VerifyRequest(rid, msgs, pks, sigs),
                                 reply_to(rid), cls=vsched.LATENCY)
            assert wait_for(rid)
            assert done[rid] == expect
            assert _wait(
                lambda: engine._device_ok and not engine._rebooting)
        snap = engine.stats_snapshot()["guard"]
        assert snap["poisoned_records"] == 1
        assert g.quarantine.is_poisoned(cursed)
        wedges_after_bisect = snap["wedges"]
        assert engine.submit(proto.VerifyRequest(3, msgs, pks, sigs),
                             reply_to(3), cls=vsched.LATENCY)
        assert wait_for(3)
        assert done[3] == expect
        snap = engine.stats_snapshot()["guard"]
        assert snap["wedges"] == wedges_after_bisect  # poison lane held
        assert snap["poison_host_verified"] >= 1
        assert snap["device_ok"]
    finally:
        engine.stop()
        g.close()


def test_guard_key_uses_deduped_record_count():
    """Deadline history must attach to the shape the launch EXECUTES:
    N replicas submitting the same QC dedup to one bucket, so the raw
    total can never train (and then tighten) the deadline of a
    genuinely-large unique batch."""
    engine, g = _engine()
    try:
        msgs, pks, sigs = _sigs(8, seed=15)
        same = proto.VerifyRequest(1, msgs, pks, sigs)
        batch = [vsched.Pending(proto.VerifyRequest(rid, msgs, pks,
                                                    sigs),
                                lambda m: None, vsched.LATENCY)
                 for rid in range(4)]  # raw total 32, unique 8
        assert engine._guard_key(batch) == "launch:8"
        assert engine._guard_key(
            [vsched.Pending(same, lambda m: None,
                            vsched.LATENCY)]) == "launch:8"
    finally:
        engine.stop()
        g.close()


def test_rewarm_runs_on_the_device_path():
    """The crash-only reboot's re-warm must reach the DEVICE path even
    while live routing is host-only (_device_ok False): a rewarm that
    silently host-verified would compile nothing and leave the first
    post-canary launch to re-wedge on a fresh trace."""
    from unittest import mock

    # A device-mode engine on the CPU jax backend (what tier-1 runs):
    # _verify_submit's non-host branch is the real jitted path.
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    seen = []

    def rewarm():
        # what _warm_shapes does: engine._verify through the engine's
        # own staged entry — with ref.verify forbidden, only the
        # device branch can answer.  The flag is THREAD-LOCAL: live
        # traffic on other threads must keep host-routing meanwhile.
        m, p, s = _sigs(4, seed=25)
        assert engine._rewarm_tls.active
        live = engine._verify_submit(m, p, s)  # another thread's view:
        with mock.patch(
                "hotstuff_tpu.crypto.ref_ed25519.verify",
                side_effect=AssertionError("rewarm took the host path")):
            mask = engine._verify(m, p, s)
        seen.append([bool(b) for b in mask])
        # ...checked from a fresh thread: host-routed, not device
        host_routed = []

        def probe_live():
            import hotstuff_tpu.crypto.ref_ed25519 as refmod
            calls = []
            real = refmod.verify

            def spy(pk, msg, sig):
                calls.append(1)
                return real(pk, msg, sig)
            with mock.patch.object(refmod, "verify", spy):
                engine._verify_submit(m, p, s)()
            host_routed.append(bool(calls))

        t = threading.Thread(target=probe_live)
        t.start()
        t.join(30.0)
        assert host_routed == [True], \
            "live traffic leaked onto the device mid-rewarm"
        del live

    engine = VerifyEngine(use_host=False, guard=g, rewarm_fn=rewarm)
    try:
        engine._wedge_ladder([], "launch:8", stage="test")
        assert _wait(lambda: engine._device_ok and not engine._rebooting)
        assert seen == [[True, True, True, True]]
        assert not getattr(engine._rewarm_tls, "active", False)
    finally:
        engine.stop()
        g.close()


def test_engine_without_guard_is_unchanged():
    """Legacy embedders (no guard): no guard section, no supervision
    hop, identical verdicts."""
    engine = VerifyEngine(use_host=True)
    try:
        msgs, pks, sigs = _sigs(4, tamper={1}, seed=13)
        done, reply_to, wait_for = _collector()
        engine.submit(proto.VerifyRequest(1, msgs, pks, sigs),
                      reply_to(1), cls=vsched.LATENCY)
        assert wait_for(1)
        assert done[1] == [True, False, True, True]
        assert "guard" not in engine.stats_snapshot()
    finally:
        engine.stop()


def test_chaos_wedge_knob_configure_roundtrip():
    c = ChaosState()
    applied = c.configure({"wedge": 2})
    assert applied["wedge"] == 2
    assert c.take_wedge() and c.take_wedge() and not c.take_wedge()
    c.configure({"wedge": 1})
    c.configure({"clear": True})
    assert not c.take_wedge()
    with pytest.raises(ValueError):
        c.configure({"wedge": -1})
    with pytest.raises(ValueError):
        c.configure({"wedge": True})


def test_guard_stats_wire_roundtrip():
    """The OP_STATS ``guard`` section survives the wire encoding."""
    engine, g = _engine()
    try:
        g.stats.note_wedge("launch:8")
        g.stats.note_reboot(1.25)
        g.stats.note_canary(True)
        frame = proto.encode_stats_reply(9, engine.stats_snapshot())
        opcode, rid, body = proto.decode_reply_raw(frame[4:])
        assert (opcode, rid) == (proto.OP_STATS, 9)
        snap = proto.decode_stats_body(body)
        assert snap["guard"]["wedges"] == 1
        assert snap["guard"]["reboots"] == 1
        assert snap["guard"]["canary_passes"] == 1
        assert snap["guard"]["device_ok"] is True
    finally:
        engine.stop()
        g.close()


# ---------------------------------------------------------------------------
# plan / SLO / injector
# ---------------------------------------------------------------------------

def test_plan_parses_sidecar_wedge():
    from hotstuff_tpu.chaos import parse_plan

    plan = parse_plan("5 sidecar wedge; 10 sidecar wedge n=2")
    assert [e.action for e in plan.events] == ["wedge", "wedge"]
    assert plan.events[0].params == {}
    assert plan.events[1].params == {"n": 2}


def test_plan_rejects_bad_wedge():
    from hotstuff_tpu.chaos import parse_plan
    from hotstuff_tpu.chaos.plan import PlanError

    with pytest.raises(PlanError):
        parse_plan("5 sidecar wedge n=0")
    with pytest.raises(PlanError):
        parse_plan("5 sidecar wedge x=2")
    with pytest.raises(PlanError):
        parse_plan("5 node:0 wedge")
    with pytest.raises(PlanError):  # wedge needs a live sidecar
        parse_plan("1 sidecar kill; 2 sidecar wedge")


def test_slo_judges_wedge_class():
    from hotstuff_tpu.chaos import judge, summarize_recovery

    events = [{"t": 1.0, "target": "sidecar", "action": "wedge",
               "wall": 100.0, "ok": True}]
    summary = summarize_recovery(events, [100.5])
    verdict = judge(summary)
    (v,) = verdict["verdicts"]
    assert v["class"] == "sidecar-wedge"
    assert v["ok"] and v["slo_ms"] == 20_000.0


def test_local_injector_drives_wedge_through_opchaos():
    """LocalFaultInjector 'sidecar wedge' -> OP_CHAOS -> the engine's
    next launch wedges and the CLIENT still gets the right mask (the
    ladder's host fallback is transparent on the wire)."""
    from hotstuff_tpu.chaos.plan import FaultEvent
    from hotstuff_tpu.harness.faults import LocalFaultInjector
    from hotstuff_tpu.sidecar.client import SidecarClient

    chaos = ChaosState()
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    engine = VerifyEngine(use_host=True, guard=g, chaos=chaos)
    srv = SidecarServer(("127.0.0.1", 0), engine, chaos=chaos)
    t = threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.1), daemon=True)
    t.start()
    try:
        port = srv.server_address[1]
        injector = LocalFaultInjector(
            SimpleNamespace(SIDECAR_PORT=port))
        injector.apply(FaultEvent(0.0, "sidecar", "wedge", {"n": 1}))
        msgs, pks, sigs = _sigs(5, tamper={2}, seed=21)
        with SidecarClient(port=port, timeout=30.0) as client:
            mask = client.verify_batch(msgs, pks, sigs)
        assert mask == [True, True, False, True, True]
        assert _wait(lambda: engine.stats_snapshot()
                     ["guard"]["wedges"] >= 1)
    finally:
        srv.shutdown()
        engine.stop()
        g.close()
        srv.server_close()


# ---------------------------------------------------------------------------
# parser notes
# ---------------------------------------------------------------------------

GOLDEN_CLIENT = """\
[2026-07-29T14:54:56.456Z INFO client] Node address: 127.0.0.1:9701
[2026-07-29T14:54:56.456Z INFO client] Transactions size: 512 B
[2026-07-29T14:54:56.456Z INFO client] Transactions rate: 2000 tx/s
[2026-07-29T14:54:56.525Z INFO client] Start sending transactions
"""

GOLDEN_NODE = """\
[2026-07-29T14:54:55.100Z INFO mempool::config] Garbage collection depth set to 50 rounds
[2026-07-29T14:54:55.100Z INFO mempool::config] Sync retry delay set to 5000 ms
[2026-07-29T14:54:55.100Z INFO mempool::config] Sync retry nodes set to 3 nodes
[2026-07-29T14:54:55.100Z INFO mempool::config] Batch size set to 15000 B
[2026-07-29T14:54:55.100Z INFO mempool::config] Max batch delay set to 100 ms
[2026-07-29T14:54:55.101Z INFO consensus::config] Timeout delay set to 1000 ms
[2026-07-29T14:54:55.101Z INFO consensus::config] Sync retry delay set to 10000 ms
[2026-07-29T14:54:56.577Z INFO mempool::batch_maker] Batch aaa= contains sample tx 0
[2026-07-29T14:54:56.578Z INFO mempool::batch_maker] Batch aaa= contains 15360 B
[2026-07-29T14:54:56.700Z INFO consensus::proposer] Created B2 -> aaa=
[2026-07-29T14:54:57.000Z INFO consensus::core] Committed B2 -> aaa=
"""


def _golden_parser():
    from hotstuff_tpu.harness import LogParser

    return LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)


def test_parser_notes_guard_section():
    parser = _golden_parser()
    parser.note_sidecar_stats({
        "launches": 4,
        "guard": {"wedges": 2, "reboots": 1, "canary_passes": 1,
                  "canary_failures": 0, "last_reboot_wall_s": 0.8,
                  "suspect_records": 8, "poisoned_records": 1,
                  "host_fallback_records": 8, "busy_replies": 3,
                  "device_ok": True, "rebooting": False},
    })
    note = next(n for n in parser.notes if n.startswith("Sidecar guard:"))
    assert "2 wedge(s)" in note
    assert "1 crash-only reboot(s)" in note
    assert "1 poisoned" in note
    assert "8 host-fallback verdict(s)" in note
    assert not any("device leg DOWN" in n for n in parser.notes)


def test_parser_notes_guard_device_down():
    parser = _golden_parser()
    parser.note_sidecar_stats({
        "launches": 4,
        "guard": {"wedges": 1, "reboots": 0, "canary_passes": 0,
                  "canary_failures": 3, "suspect_records": 4,
                  "poisoned_records": 0, "host_fallback_records": 4,
                  "busy_replies": 0, "device_ok": False,
                  "rebooting": False},
    })
    assert any("device leg DOWN" in n for n in parser.notes)


def test_parser_quiet_without_guard_activity():
    parser = _golden_parser()
    parser.note_sidecar_stats({
        "launches": 4,
        "guard": {"wedges": 0, "reboots": 0, "poisoned_records": 0,
                  "device_ok": True},
    })
    assert not any(n.startswith("Sidecar guard:") for n in parser.notes)


# ---------------------------------------------------------------------------
# bench: kill-proof emit + guard headline
# ---------------------------------------------------------------------------

def test_bench_guard_headline_probe_passes_its_bar():
    import bench

    out = bench.guard_headline_probe()
    assert out["ok"], out
    assert out["masks_bit_identical"]
    assert out["busy_during_reboot"] is True
    assert out["wedges"] >= 1 and out["reboots"] >= 1
    assert out["recovered"]
    json.dumps(out)


def test_bench_emit_writes_line_cache_first(tmp_path, monkeypatch,
                                            capsys):
    import bench

    cache = tmp_path / "last_line.json"
    monkeypatch.setattr(bench, "_LINE_CACHE_PATH", str(cache))
    monkeypatch.setattr(bench, "_LAST_LINE", None)
    bench.emit(123.0, 4.5, rlc={"n4": {"skipped": True}})
    # the disk artifact exists and matches stdout
    on_disk = json.loads(cache.read_text())
    printed = json.loads(capsys.readouterr().out.strip())
    assert on_disk == printed
    assert on_disk["value"] == 123.0
    assert bench._LAST_LINE == on_disk


def test_bench_kill_handler_reemits_wedged_stage_partial(
        tmp_path, monkeypatch, capfd):
    """The kill-proof emit regression (VERDICT top-next): a stage
    wedges forever on a virtual clock, the driver's window closes
    (SIGTERM), and the handler re-emits the partial line already
    measured — an rc=124 round still yields a parseable artifact."""
    import signal

    import bench

    monkeypatch.setattr(bench, "_LINE_CACHE_PATH",
                        str(tmp_path / "last_line.json"))
    monkeypatch.setattr(bench, "_LAST_LINE", None)
    exits = []
    handler = bench.install_kill_handlers(exit=exits.append)
    # restore default handlers after the test
    try:
        # A fake wedged stage on a virtual clock: the stage never
        # finishes, the virtual clock races past the driver's budget,
        # and the only thing that ever ran is the partial emit below.
        now = [0.0]

        def clock():
            return now[0]

        def wedged_stage():
            now[0] += 10_000.0  # the stage "hangs" past any budget
            return None

        bench.emit(77.0, 2.0, rlc={"n4": {"skipped": True}},
                   note="partial: rlc stage only")
        wedged_stage()
        assert clock() > bench.bench_budget_s()  # the window is gone
        handler(signal.SIGTERM, None)  # what the driver's timeout sends
        assert exits == [0]
        # fd-level capture: the handler writes fd 1 directly (one
        # os.write — a torn interrupted print can never weld onto it)
        lines = [json.loads(ln) for ln in
                 capfd.readouterr().out.strip().splitlines() if ln]
        final = lines[-1]
        assert final["killed"] == "SIGTERM"
        assert final["value"] == 77.0  # the partial measurement survived
        assert final["rlc"] == {"n4": {"skipped": True}}
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGALRM, signal.SIG_DFL)


def test_bench_kill_handler_without_any_line_emits_error(
        tmp_path, monkeypatch, capfd):
    import signal

    import bench

    monkeypatch.setattr(bench, "_LINE_CACHE_PATH",
                        str(tmp_path / "last_line.json"))
    monkeypatch.setattr(bench, "_LAST_LINE", None)
    monkeypatch.setattr(bench, "load_cache", lambda: None)
    exits = []
    handler = bench.install_kill_handlers(exit=exits.append)
    try:
        handler(signal.SIGALRM, None)
        assert exits == [0]
        line = json.loads(capfd.readouterr().out.strip())
        assert line["killed"] == "SIGALRM"
        assert line["value"] == 0
        assert "error" in line
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGALRM, signal.SIG_DFL)


def test_bench_kill_handler_line_survives_a_torn_print(
        tmp_path, monkeypatch, capfd):
    """A SIGTERM mid-print must never weld the re-emitted line onto the
    torn prefix: the handler's leading newline closes the partial line,
    so the LAST line always parses."""
    import signal
    import sys

    import bench

    monkeypatch.setattr(bench, "_LINE_CACHE_PATH",
                        str(tmp_path / "last_line.json"))
    monkeypatch.setattr(bench, "_LAST_LINE",
                        {"metric": "ed25519-batch-verify",
                         "value": 9.0, "unit": "sigs/sec",
                         "vs_baseline": 1.0})
    exits = []
    handler = bench.install_kill_handlers(exit=exits.append)
    try:
        # the interrupted print: a torn prefix with no newline
        sys.stdout.write('{"metric": "ed25')
        sys.stdout.flush()
        handler(signal.SIGTERM, None)
        out = capfd.readouterr().out
        last = [ln for ln in out.splitlines() if ln][-1]
        line = json.loads(last)  # must parse despite the torn prefix
        assert line["killed"] == "SIGTERM" and line["value"] == 9.0
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGALRM, signal.SIG_DFL)


# ---------------------------------------------------------------------------
# slow e2e: the acceptance bar
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_wedge_recovery_e2e(tmp_path):
    """Acceptance: a chaos-plan run with ``sidecar wedge`` injected
    mid-traffic commits every in-flight consensus verify via the host
    fallback (masks bit-identical to verify_batch), reboots the engine
    off the warm cache in under half the cold-warmup wall, and the
    parser emits the wedge/reboot notes with the recovery SLO PASS."""
    from hotstuff_tpu.chaos import PlanRunner, parse_plan
    from hotstuff_tpu.chaos.plan import FaultEvent  # noqa: F401
    from hotstuff_tpu.harness import LogParser
    from hotstuff_tpu.harness.faults import LocalFaultInjector
    from hotstuff_tpu.sidecar.client import SidecarClient
    from hotstuff_tpu.utils.xla_cache import CompileManifest

    # The warm cache story: a manifest with a recorded COLD warmup run
    # (the 149 s boot PR 11 measured) against which the reboot's wall
    # must come in under half.
    manifest = CompileManifest(str(tmp_path / "manifest.json"))
    manifest.record_run("kern", hits=0, misses=4, wall_s=149.0, now=1.0)
    cold_wall = manifest.cold_wall_s()
    assert cold_wall == 149.0

    chaos = ChaosState()
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    rewarm_walls = []
    engine = VerifyEngine(
        use_host=True, guard=g, chaos=chaos,
        rewarm_fn=lambda: (time.sleep(0.1), rewarm_walls.append(1)))
    srv = SidecarServer(("127.0.0.1", 0), engine, chaos=chaos)
    st = threading.Thread(target=srv.serve_forever,
                          kwargs=dict(poll_interval=0.1), daemon=True)
    st.start()
    port = srv.server_address[1]

    masks = []
    expects = []
    errors = []
    stop = threading.Event()

    def traffic(seed):
        # Distinct records per request so verifies hit the engine, not
        # the verdict cache — every one must come back CORRECT whether
        # it rode the device leg, the ladder, or the reboot window.
        # The loop runs until the main thread has SEEN the wedge land
        # (stop event), so there is always traffic in flight when the
        # plan fires, regardless of scheduling weather.
        try:
            with SidecarClient(port=port, timeout=30.0) as client:
                i = 0
                while not stop.is_set() and i < 500:
                    m, p, s = _sigs(4, tamper={i % 4},
                                    seed=seed * 1000 + i)
                    expect = [bool(b) for b in
                              eddsa.verify_batch(m, p, s)]
                    mask = client.verify_batch(m, p, s)
                    masks.append(mask)
                    expects.append(expect)
                    i += 1
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=traffic, args=(k,), daemon=True)
               for k in range(2)]
    for t in threads:
        t.start()

    plan = parse_plan("0.2 sidecar wedge")
    injector = LocalFaultInjector(SimpleNamespace(SIDECAR_PORT=port))
    base_wall = LogParser._to_posix("2026-07-29T14:54:56.900Z")
    runner = PlanRunner(plan, injector, wall=lambda: base_wall)
    runner.start()
    runner.join(timeout=30.0)

    def _guard_snap():
        return engine.stats_snapshot()["guard"]

    assert _wait(lambda: _guard_snap()["wedges"] >= 1, timeout=60.0), \
        "the scripted wedge never caught a launch"
    assert _wait(lambda: _guard_snap()["reboots"] >= 1, timeout=60.0)
    stop.set()
    for t in threads:
        t.join(timeout=60.0)
    assert not errors, errors
    assert masks and all(m == e for m, e in zip(masks, expects)), \
        "a verify answered with a non-bit-identical mask"
    stats = engine.stats_snapshot()
    snap = stats["guard"]
    assert snap["wedges"] >= 1
    assert snap["canary_passes"] >= 1
    assert snap["device_ok"] and not snap["rebooting"]
    # "under half the cold-warmup wall": the reboot re-warms off the
    # populated cache, so its wall must beat cold/2 by a mile.
    assert snap["last_reboot_wall_s"] < 0.5 * cold_wall

    # The parser round trip: guard notes + the sidecar-wedge recovery
    # SLO PASS, exactly what a harness run's summary would carry.
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_sidecar_stats(json.loads(json.dumps(stats)))
    events = json.loads(json.dumps(runner.events()))
    assert events and events[0]["ok"], events
    parser.note_chaos_events(events, strict=True)
    guard_note = next(n for n in parser.notes
                      if n.startswith("Sidecar guard:"))
    assert "wedge(s)" in guard_note and "crash-only reboot(s)" in \
        guard_note
    slo_note = next(n for n in parser.notes
                    if n.startswith("Chaos SLO sidecar-wedge:"))
    assert slo_note.endswith("PASS")

    srv.shutdown()
    engine.stop()
    g.close()
    srv.server_close()

"""Device BLS12-381 engine tests: Fq Montgomery arithmetic, the Fq12
tower, Frobenius/inversion, and (behind HOTSTUFF_TPU_SLOW_TESTS=1, ~4 min
of XLA compile on CPU) the full aggregate pairing check against the host
reference (offchain/bls12381.py).
"""


import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from hotstuff_tpu.offchain import bls12381 as host
from hotstuff_tpu.ops import bls381 as D
from hotstuff_tpu.ops import field381 as F

RNG = np.random.default_rng(11)


def rand_fq() -> int:
    return int.from_bytes(RNG.bytes(48), "little") % F.Q


def rand_fq12():
    return tuple(rand_fq() for _ in range(12))


def to_dev(x):
    return jnp.asarray(D.host_fq12_to_mont_limbs(x))[None]


def from_dev(d):
    return tuple(F.from_limbs(r) for r in np.asarray(F.from_mont(d))[0])


def test_field381_mont_roundtrip_and_ops():
    F.mul_selfcheck()
    xs = [rand_fq() for _ in range(8)]
    ys = [rand_fq() for _ in range(8)]
    a = jnp.asarray(np.stack([F.to_limbs(x * F.R % F.Q) for x in xs]))
    b = jnp.asarray(np.stack([F.to_limbs(y * F.R % F.Q) for y in ys]))
    assert [F.from_limbs(v) for v in np.asarray(F.from_mont(F.add(a, b)))] \
        == [(x + y) % F.Q for x, y in zip(xs, ys)]
    assert [F.from_limbs(v) for v in np.asarray(F.from_mont(F.sub(a, b)))] \
        == [(x - y) % F.Q for x, y in zip(xs, ys)]
    assert [F.from_limbs(v) for v in np.asarray(F.from_mont(F.inv(a)))] \
        == [pow(x, F.Q - 2, F.Q) for x in xs]


def test_field381_mul_chain_stability():
    """Digit bounds must hold over arbitrarily long mul/sub chains."""
    x, y = rand_fq(), rand_fq()
    a = jnp.asarray(F.to_limbs(x * F.R % F.Q))[None]
    b = jnp.asarray(F.to_limbs(y * F.R % F.Q))[None]
    acc, want = a, x
    for _ in range(50):
        acc = F.mont_mul(F.sub(acc, b), b)
        want = (want - y) * y % F.Q
    assert F.from_limbs(np.asarray(F.from_mont(acc))[0]) == want


def test_fq12_mul_matches_host():
    x, y = rand_fq12(), rand_fq12()
    assert from_dev(D.fq12_mul(to_dev(x), to_dev(y))) == host.fq12_mul(x, y)


def test_fq12_mul_deep_chain():
    """The reduce_sum invariant: 20 chained tower muls stay exact (without
    it the top limb creeps past the f32 conv bound and results corrupt
    silently)."""
    x, y = rand_fq12(), rand_fq12()
    acc, hacc = to_dev(x), x
    for _ in range(20):
        acc = D.fq12_mul(acc, to_dev(y))
        hacc = host.fq12_mul(hacc, y)
    assert from_dev(acc) == hacc


def test_fq12_frobenius_and_inverse():
    x = rand_fq12()
    dx = to_dev(x)
    assert from_dev(D.fq12_frobenius(dx, 1)) == host.fq12_pow(x, host.Q)
    assert from_dev(D.fq12_frobenius(dx, 6)) == host.fq12_pow(x, host.Q ** 6)
    assert from_dev(D.fq12_inv(dx)) == host.fq12_inv(x)


def test_miller_lines_match_host_miller():
    """Accumulating the host-precomputed lines reproduces the host Miller
    value (up to the BLS_X-sign inversion the device skips)."""
    sk, pk = host.key_gen(b"\x07" * 32)
    sig = host.sign(sk, b"m")
    lines = D.miller_lines(pk, sig)
    f_dev = from_dev(D.miller_accumulate(jnp.asarray(lines)[None]))
    f_host = host.miller_loop(host._twist(sig), host._cast_g1_fq12(pk))
    assert f_dev == host.fq12_inv(f_host)  # host returns the inverse


@pytest.mark.slow  # ~4 min XLA compile
def test_aggregate_verify_device_end_to_end():
    msg = b"quorum certificate digest"
    sks, pks = zip(*[host.key_gen(bytes([i]) * 32) for i in range(1, 5)])
    sigs = [host.sign(s, msg) for s in sks]
    agg = host.aggregate(sigs)
    assert D.verify_aggregate_common(list(pks), msg, agg)
    bad = host.aggregate(sigs[:3] + [host.sign(sks[0], b"other")])
    assert not D.verify_aggregate_common(list(pks), msg, bad)


@pytest.mark.slow  # ~4 min XLA compile
def test_aggregate_verify_multi_device_end_to_end():
    """Distinct-digest product-of-pairings (the TC verify shape)."""
    sks, pks = zip(*[host.key_gen(bytes([i]) * 32) for i in range(1, 4)])
    msgs = [bytes([i]) * 32 for i in range(3)]
    sigs = [host.sign(s, m) for s, m in zip(sks, msgs)]
    agg = host.aggregate(sigs)
    assert D.verify_aggregate_multi(list(pks), msgs, agg)
    # wrong digest on one vote breaks the product
    bad = host.aggregate(sigs[:2] + [host.sign(sks[2], b"x" * 32)])
    assert not D.verify_aggregate_multi(list(pks), msgs, bad)
    # mismatched lengths and empty input reject without device work
    assert not D.verify_aggregate_multi(list(pks), msgs[:2], agg)
    assert not D.verify_aggregate_multi([], [], agg)

"""graftkern tests: Pallas kernel bit-identity vs the lax reference,
the kernel-route plumbing, the MSM window-chunk re-pin, the compile
manifest / tracker, and the bench roofline surface.

Everything here runs the kernels in INTERPRET mode (CPU backend —
conftest pins it), i.e. the exact kernel bodies a TPU would compile.
The expensive full-program paths (engine RLC bisection under
HOTSTUFF_TPU_KERN=pallas, the B=1024 window-accumulator agreement) are
slow-marked; scripts/kern_gate.sh runs them inside its stated budget.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref  # noqa: E402
from hotstuff_tpu.ops import ed25519 as E  # noqa: E402
from hotstuff_tpu.ops import field25519 as F  # noqa: E402
from hotstuff_tpu.ops import kern  # noqa: E402
from hotstuff_tpu.ops import scalar25519 as S  # noqa: E402
from hotstuff_tpu.utils.intmath import L, P  # noqa: E402
from hotstuff_tpu.utils.xla_cache import (  # noqa: E402
    CompileManifest, CompileTracker, kernel_fingerprint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _arr(x):
    return np.asarray(x)


# ---------------------------------------------------------------------------
# Kernel 1: field_mul
# ---------------------------------------------------------------------------


class TestFieldMulKernel:
    def test_random_weak_sweep_bit_identical(self):
        rng = np.random.default_rng(11)
        for seed in range(3):
            a = rng.integers(0, 512, (32, 32)).astype(np.int32)
            b = rng.integers(0, 512, (32, 32)).astype(np.int32)
            got = _arr(kern.field_mul(jnp.asarray(a), jnp.asarray(b)))
            want = _arr(F._mul_lax(jnp.asarray(a), jnp.asarray(b)))
            assert np.array_equal(got, want), f"seed {seed}"

    def test_edge_limbs_bit_identical(self):
        # Maximal weak limbs (all 511 — the worst wrap-38 carry chains),
        # canonical p-1, zero, and one: the carry-structure edges.
        cases = [
            np.full((32,), 511, np.int32),
            F.to_limbs(P - 1),
            F.to_limbs(0),
            F.to_limbs(1),
            F.to_limbs((1 << 255) - 19 - 38),  # wrap-fold boundary
        ]
        a = np.stack([c for c in cases for _ in cases])
        b = np.stack([c for _ in cases for c in cases])
        got = _arr(kern.field_mul(jnp.asarray(a), jnp.asarray(b)))
        want = _arr(F._mul_lax(jnp.asarray(a), jnp.asarray(b)))
        assert np.array_equal(got, want)
        # And the values are right, not just mutually consistent.
        got_vals = F.batch_from_limbs(_arr(F.canonical(jnp.asarray(got))))
        want_vals = [(x * y) % P
                     for x, y in zip(F.batch_from_limbs(a),
                                     F.batch_from_limbs(b))]
        assert got_vals == want_vals

    def test_batch_shapes_and_broadcast(self):
        rng = np.random.default_rng(3)
        a = rng.integers(0, 512, (3, 4, 32)).astype(np.int32)
        b = rng.integers(0, 512, (3, 4, 32)).astype(np.int32)
        got = _arr(kern.field_mul(jnp.asarray(a), jnp.asarray(b)))
        want = _arr(F._mul_lax(jnp.asarray(a), jnp.asarray(b)))
        assert got.shape == (3, 4, 32)
        assert np.array_equal(got, want)
        # 1-D (single element) and broadcast (4,32) x (32,)
        a1 = rng.integers(0, 512, (32,)).astype(np.int32)
        b1 = rng.integers(0, 512, (32,)).astype(np.int32)
        assert np.array_equal(
            _arr(kern.field_mul(jnp.asarray(a1), jnp.asarray(b1))),
            _arr(F._mul_lax(jnp.asarray(a1), jnp.asarray(b1))))


# ---------------------------------------------------------------------------
# Kernel 3: scalar_mont_mul
# ---------------------------------------------------------------------------


class TestScalarMontKernel:
    def test_random_and_boundary_scalars_bit_identical(self):
        rng = np.random.default_rng(7)
        vals_a = [int.from_bytes(rng.bytes(32), "little") % L
                  for _ in range(12)]
        vals_b = [int.from_bytes(rng.bytes(32), "little") % L
                  for _ in range(12)]
        # Order-L boundaries, zero, one.
        vals_a[:4] = [L - 1, L - 1, 0, 1]
        vals_b[:4] = [L - 1, 1, L - 1, L - 1]
        a = np.stack([F.to_limbs(v) for v in vals_a])
        b = np.stack([F.to_limbs(v) for v in vals_b])
        got = _arr(kern.scalar_mont_mul(jnp.asarray(a), jnp.asarray(b)))
        want = _arr(S._mont_mul_lax(jnp.asarray(a), jnp.asarray(b)))
        assert np.array_equal(got, want)
        # Against python ints: mont_mul computes a*b*R^-1 mod L.
        r_inv = pow(1 << 256, -1, L)
        got_vals = F.batch_from_limbs(got)
        assert got_vals == [(x * y * r_inv) % L
                            for x, y in zip(vals_a, vals_b)]

    def test_headroom_path_bit_identical(self):
        # One input up to 2^256 - 1 while the other stays < L — the
        # reduce512_mod_l high-half contract.
        rng = np.random.default_rng(9)
        big = [2**256 - 1, 2**255 + 12345,
               int.from_bytes(rng.bytes(32), "little")]
        small = [L - 1, 7, int.from_bytes(rng.bytes(32), "little") % L]
        a = np.stack([F.to_limbs(v) for v in big])
        b = np.stack([F.to_limbs(v) for v in small])
        got = _arr(kern.scalar_mont_mul(jnp.asarray(a), jnp.asarray(b)))
        want = _arr(S._mont_mul_lax(jnp.asarray(a), jnp.asarray(b)))
        assert np.array_equal(got, want)


# ---------------------------------------------------------------------------
# Kernel 2: msm_window_accum
# ---------------------------------------------------------------------------


def _real_points(n, seed=1):
    pts = []
    for i in range(n):
        _, pk = ref.generate_keypair(bytes([seed]) * 31 + bytes([i + 1]))
        y, s = E.split_y_sign(jnp.asarray(
            np.frombuffer(pk, np.uint8)[None, :].astype(np.int32)))
        p, ok = E.decompress(y, s)
        assert bool(_arr(ok)[0])
        pts.append(_arr(p)[0])
    return jnp.asarray(np.stack(pts))


class TestMsmWindowAccumKernel:
    def test_window_sums_bit_identical(self):
        pts = _real_points(8)
        table = E.msm_table(pts)
        rng = np.random.default_rng(5)
        digits = jnp.asarray(rng.integers(0, 16, (8, 64)).astype(np.int32))
        got = _arr(kern.msm_window_accum(table, digits))
        want = _arr(E._window_sums_lax(table, digits))
        assert got.shape == (64, 4, 32)
        assert np.array_equal(got, want)

    def test_zero_digit_rows_and_b1(self):
        pts = _real_points(8)
        table = E.msm_table(pts)
        rng = np.random.default_rng(6)
        digits = rng.integers(0, 16, (8, 64)).astype(np.int32)
        digits[3, :] = 0  # excluded row: selects only identity entries
        digits[7, :] = 0
        dj = jnp.asarray(digits)
        assert np.array_equal(_arr(kern.msm_window_accum(table, dj)),
                              _arr(E._window_sums_lax(table, dj)))
        t1 = E.msm_table(pts[:1])
        d1 = jnp.zeros((1, 64), jnp.int32)
        assert np.array_equal(_arr(kern.msm_window_accum(t1, d1)),
                              _arr(E._window_sums_lax(t1, d1)))

    def test_rejects_non_pow2_batch(self):
        pts = _real_points(2)
        table = jnp.concatenate([E.msm_table(pts)] * 3, axis=0)[:3]
        with pytest.raises(ValueError, match="power of two"):
            kern.msm_window_accum(table, jnp.zeros((3, 64), jnp.int32))

    @pytest.mark.slow
    def test_n1024_agreement_sweep(self):
        # The kern_gate slow lane: the window accumulator at the B=1024
        # launch cap (10 tree levels — the deepest in-kernel fold the
        # engine can ever launch) agrees with the lax path limb for
        # limb.  Identity-padded like the real MSM: 8 real points, the
        # rest identity rows with digit 0.
        pts = _real_points(8)
        b = 1024
        full = jnp.concatenate([pts, E.identity_ext((b - 8,))], axis=0)
        table = E.msm_table(full)
        rng = np.random.default_rng(13)
        digits = np.zeros((b, 64), np.int32)
        digits[:8] = rng.integers(0, 16, (8, 64))
        dj = jnp.asarray(digits)
        assert np.array_equal(_arr(kern.msm_window_accum(table, dj)),
                              _arr(E._window_sums_lax(table, dj)))


# ---------------------------------------------------------------------------
# Route plumbing (HOTSTUFF_TPU_KERN) + the interpret probe
# ---------------------------------------------------------------------------


class TestKernRoute:
    def test_mode_default_and_validation(self):
        assert kern.mode() in ("lax", "pallas")
        with pytest.raises(ValueError):
            kern.set_mode("mosaic")

    def test_interpret_probe_and_default(self):
        # CPU backend (conftest): production kernels must interpret.
        assert kern.interpret_default() is True
        assert kern.interpret_probe() is True

    def test_field_mul_routes_through_kernel(self):
        rng = np.random.default_rng(21)
        a = jnp.asarray(rng.integers(0, 512, (8, 32)).astype(np.int32))
        b = jnp.asarray(rng.integers(0, 512, (8, 32)).astype(np.int32))
        want = _arr(F._mul_lax(a, b))
        ambient = kern.mode()
        try:
            kern.set_mode("pallas")
            assert np.array_equal(_arr(F.mul(a, b)), want)
            kern.set_mode("lax")
            assert np.array_equal(_arr(F.mul(a, b)), want)
        finally:
            kern.set_mode(ambient)

    @pytest.mark.slow
    def test_engine_rlc_bisection_mask_bit_identical(self):
        # The acceptance path: HOTSTUFF_TPU_KERN=pallas forced through
        # verify_batch_rlc, including the bisection slow path (one
        # corrupted signature), must return the exact mask the lax
        # reference computes.  Compile-bound (~2 min interpreted) —
        # kern_gate's lane.
        rng = np.random.default_rng(17)
        msgs, pks, sigs = [], [], []
        for _ in range(6):
            sk = rng.bytes(32)
            msg = rng.bytes(32)
            _, pk = ref.generate_keypair(sk)
            msgs.append(msg)
            pks.append(pk)
            sigs.append(ref.sign(sk, msg))
        bad = list(sigs)
        bad[2] = bad[2][:63] + bytes([bad[2][63] ^ 1])
        want_ok = eddsa.verify_batch(msgs, pks, sigs)
        want_bad = eddsa.verify_batch(msgs, pks, bad)
        assert want_ok.all() and not want_bad[2] and want_bad.sum() == 5
        ambient = kern.mode()
        try:
            kern.set_mode("pallas")
            got_ok = eddsa.verify_batch_rlc(msgs, pks, sigs)
            got_bad = eddsa.verify_batch_rlc(msgs, pks, bad)
        finally:
            kern.set_mode(ambient)
        assert got_ok.tolist() == want_ok.tolist()
        assert got_bad.tolist() == want_bad.tolist()


# ---------------------------------------------------------------------------
# MSM window-chunk plumbing
# ---------------------------------------------------------------------------


class TestMsmWindowChunk:
    def test_get_set_validate(self):
        default = E.msm_window_chunk()
        assert 64 % default == 0
        try:
            E.set_msm_window_chunk(16)
            assert E.msm_window_chunk() == 16
        finally:
            E.set_msm_window_chunk(default)
        for bad in (0, 5, 3, -4, 128, "8"):
            with pytest.raises(ValueError):
                E.set_msm_window_chunk(bad)
        assert E.msm_window_chunk() == default

    def test_window_sums_bit_identical_across_chunks(self):
        pts = _real_points(4, seed=2)
        rng = np.random.default_rng(8)
        digits = jnp.asarray(rng.integers(0, 16, (4, 64)).astype(np.int32))
        default = E.msm_window_chunk()
        try:
            E.set_msm_window_chunk(4)
            w4 = _arr(E.msm_window_sums(pts, digits))
            E.set_msm_window_chunk(8)
            w8 = _arr(E.msm_window_sums(pts, digits))
        finally:
            E.set_msm_window_chunk(default)
        assert np.array_equal(w4, w8)


# ---------------------------------------------------------------------------
# Compile manifest + tracker (the persistent-cache accounting)
# ---------------------------------------------------------------------------


class TestCompileManifest:
    def test_cold_then_warm_roundtrip(self, tmp_path):
        path = str(tmp_path / "manifest.json")
        cache_dir = str(tmp_path / "xla")
        os.makedirs(cache_dir)
        clock = [0.0]

        def tick():
            return clock[0]

        # Cold boot: every shape is a miss and costs 5 "seconds".
        cold = CompileTracker(cache_dir=cache_dir, manifest_path=path,
                              clock=tick, kernel="k1")
        for key in ("warmup:8", "warmup:16", "rlc:8"):
            def thunk():
                clock[0] += 5.0
            cold.warm(key, thunk)
        cold.finish()
        assert cold.misses == 3 and cold.hits == 0
        snap = cold.snapshot()
        assert snap["warm_boot"] is False
        assert snap["shapes"] == {"rlc:8": 5.0, "warmup:8": 5.0,
                                  "warmup:16": 5.0}
        json.dumps(snap)  # OP_STATS section must be JSON-safe

        # Warm boot against the SAME manifest + cache dir: zero misses,
        # lower wall.
        warm = CompileTracker(cache_dir=cache_dir, manifest_path=path,
                              clock=tick, kernel="k1")
        for key in ("warmup:8", "warmup:16", "rlc:8"):
            def thunk():
                clock[0] += 0.2
            warm.warm(key, thunk)
        warm.finish()
        assert warm.misses == 0 and warm.hits == 3
        assert warm.snapshot()["warm_boot"] is True
        runs = CompileManifest(path).data["runs"]
        assert len(runs) == 2
        assert runs[0]["misses"] == 3 and runs[1]["misses"] == 0
        assert runs[1]["wall_s"] < runs[0]["wall_s"]
        # A DIFFERENT (or wiped) cache dir must NOT read as warm: the
        # manifest alone cannot prove the compiled programs survived.
        other = CompileTracker(cache_dir=str(tmp_path / "elsewhere"),
                               manifest_path=path, clock=tick,
                               kernel="k1")
        other.warm("warmup:8", lambda: None)
        assert other.misses == 1 and other.hits == 0
        # Cache disabled (None) is always a cold boot.
        off = CompileTracker(cache_dir=None, manifest_path=path,
                             clock=tick, kernel="k1")
        off.warm("warmup:16", lambda: None)
        assert off.misses == 1

    def test_kernel_edit_invalidates(self, tmp_path):
        path = str(tmp_path / "manifest.json")
        cache_dir = str(tmp_path / "xla")
        os.makedirs(cache_dir)
        t1 = CompileTracker(cache_dir=cache_dir, manifest_path=path,
                            kernel="old")
        t1.warm("warmup:8", lambda: None)
        t1.finish()
        t2 = CompileTracker(cache_dir=cache_dir, manifest_path=path,
                            kernel="new")
        t2.warm("warmup:8", lambda: None)
        assert t2.misses == 1  # same shape, different kernel: a miss
        # Same kernel + same dir stays a hit (the control).
        t3 = CompileTracker(cache_dir=cache_dir, manifest_path=path,
                            kernel="old")
        t3.warm("warmup:8", lambda: None)
        assert t3.hits == 1

    def test_corrupt_manifest_starts_empty(self, tmp_path):
        path = tmp_path / "manifest.json"
        path.write_text("{torn")
        m = CompileManifest(str(path))
        assert m.data["kernels"] == {} and m.data["runs"] == []

    def test_fingerprint_covers_kern_sources(self):
        base = kernel_fingerprint()
        assert len(base) == 16
        # bench's variant (extra sources) must differ from the base.
        assert kernel_fingerprint(extra=("bench.py",)) != base


class TestWarmupWiring:
    class _Shapes:
        def __init__(self):
            self.buckets, self.chunks, self.rlc = [], [], []

        def mark_bucket(self, n):
            self.buckets.append(n)

        def mark_chunks(self, g):
            self.chunks.append(g)

        def mark_rlc(self, n):
            self.rlc.append(n)

    class _Engine:
        def __init__(self, tracker):
            self.compile_tracker = tracker
            self._shapes = TestWarmupWiring._Shapes()

        def _verify(self, msgs, pks, sigs):
            return [True] * len(msgs)

    def test_warm_shapes_records_per_shape(self, tmp_path):
        from hotstuff_tpu.sidecar import service

        cache_dir = str(tmp_path / "xla")
        os.makedirs(cache_dir)
        tracker = CompileTracker(
            cache_dir=cache_dir,
            manifest_path=str(tmp_path / "m.json"), kernel="k")
        engine = self._Engine(tracker)
        service._warm_shapes(engine, 8, 32, "warmup")
        assert engine._shapes.buckets == [8, 16, 32]
        assert set(tracker.shapes) == {"warmup:8", "warmup:16",
                                       "warmup:32"}
        assert tracker.misses == 3
        tracker.finish()
        # A tracker-less engine (host mode, tests) still warms.
        bare = self._Engine(None)
        service._warm_shapes(bare, 8, 8, "warmup")
        assert bare._shapes.buckets == [8]
        # Second boot, same manifest + cache dir: all hits.
        t2 = CompileTracker(cache_dir=cache_dir,
                            manifest_path=str(tmp_path / "m.json"),
                            kernel="k")
        service._warm_shapes(self._Engine(t2), 8, 32, "warmup")
        assert (t2.hits, t2.misses) == (3, 0)

    def test_stats_snapshot_carries_compile_section(self, tmp_path):
        from hotstuff_tpu.sidecar.service import VerifyEngine

        engine = VerifyEngine(use_host=True)
        try:
            assert "compile" not in engine.stats_snapshot()
            tracker = CompileTracker(
                manifest_path=str(tmp_path / "m.json"), kernel="k")
            tracker.warm("warmup:8", lambda: None)
            engine.compile_tracker = tracker
            snap = engine.stats_snapshot()
            assert snap["compile"]["misses"] == 1
            json.dumps(snap)
        finally:
            engine.stop()


# ---------------------------------------------------------------------------
# warmup_report + bench roofline surfaces
# ---------------------------------------------------------------------------


class TestWarmupReport:
    def _load(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "warmup_report", os.path.join(REPO, "scripts",
                                          "warmup_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_report_compares_latest_cold_and_warm(self):
        wr = self._load()
        manifest = {"runs": [
            {"t": 1.0, "kernel": "old", "hits": 0, "misses": 9,
             "wall_s": 100.0},
            {"t": 2.0, "kernel": "k", "hits": 0, "misses": 12,
             "wall_s": 62.0},
            {"t": 3.0, "kernel": "k", "hits": 12, "misses": 0,
             "wall_s": 3.5},
        ]}
        doc = wr.report(manifest)
        cmp_ = doc["comparison"]
        assert cmp_["kernel"] == "k"
        assert cmp_["cold_wall_s"] == 62.0
        assert cmp_["warm_wall_s"] == 3.5
        assert cmp_["saved_pct"] == pytest.approx(94.4, abs=0.1)

    def test_report_without_pair(self):
        wr = self._load()
        doc = wr.report({"runs": [
            {"t": 1.0, "kernel": "k", "hits": 0, "misses": 2,
             "wall_s": 10.0}]})
        assert doc["comparison"] is None

    def test_cli_missing_manifest(self, tmp_path):
        wr = self._load()
        assert wr.main(["--manifest", str(tmp_path / "none.json")]) == 1


class TestRooflineHeadline:
    def test_estimate_shape(self):
        sys.path.insert(0, REPO)
        import bench

        est = bench.roofline_estimate()
        for key in ("int_ops_per_sig", "chip", "chip_int_ops_per_s",
                    "roofline_sigs_per_s_chip", "field_muls_per_sig"):
            assert key in est
        assert est["int_ops_per_sig"] > 1e6
        assert est["roofline_sigs_per_s_chip"] > 0
        json.dumps(est)

    def test_headline_budget_zero_skips(self):
        sys.path.insert(0, REPO)
        import bench

        out = bench.roofline_headline(budget_s=0)
        assert out["skipped"] is True
        assert out["est"]["roofline_sigs_per_s_chip"] > 0
        assert out["kern_default"] in ("lax", "pallas")
        json.dumps(out)

    @pytest.mark.slow
    def test_headline_measures_both_routes(self):
        # kern_gate lane: one small size through BOTH routes (the
        # pallas entry is interpreter-flagged on this backend).
        sys.path.insert(0, REPO)
        import bench

        out = bench.roofline_headline(sizes=(8,), repeats=1,
                                      budget_s=600.0)
        stats = out["n8"]
        assert stats["lax"]["sigs_per_s_chip"] > 0
        assert stats["pallas"]["sigs_per_s_chip"] > 0
        assert stats["pallas"].get("interpreted") is True
        assert "pallas_speedup" in stats
        json.dumps(out)


class TestMsmChunkSweep:
    @pytest.mark.slow
    def test_sweep_in_process(self):
        sys.path.insert(0, REPO)
        import bench
        from hotstuff_tpu.ops import ed25519 as E2

        default = E2.msm_window_chunk()
        out = bench.msm_chunk_sweep(chunks=(4, 8), n=8, budget_s=300.0)
        assert E2.msm_window_chunk() == default  # restored
        for key in ("chunk4", "chunk8"):
            assert out[key].get("rlc_sigs_per_s", 0) > 0 or \
                "error" in out[key]
        json.dumps(out)

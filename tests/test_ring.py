"""graftcadence tests: the resident continuous-batching ring.

Covers the depth trainer (clamp to {2,4,8}, manifest seeding, env pin),
the scheduler's per-tick quota assembly, the ``tick:`` guard deadline
class, the generation-tag lifecycle on a virtual clock (stale fetch
discarded, expiry re-resolve answers exactly once, slot wrap-around),
the clean-stop drain, corpus bit-identity through a real cadence
engine, and the forced-wedge drill proving the ladder drops the ring
back to the staged engine with bit-identical masks and no double
reply.  This file is a guard-gate lane (scripts/guard_gate.sh).
"""

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
from hotstuff_tpu.obs.spans import Tracer
from hotstuff_tpu.sidecar import protocol as proto
from hotstuff_tpu.sidecar import sched as vsched
from hotstuff_tpu.sidecar.guard import (BusyReply, LaunchDeadlines,
                                        LaunchGuard, WedgedLaunch)
from hotstuff_tpu.sidecar.ring import (ENV_CADENCE, ENV_DEPTH,
                                       CadenceRing, RingDepth,
                                       cadence_enabled)
from hotstuff_tpu.sidecar.service import ChaosState, VerifyEngine

# Same real-time guard posture as test_guard.py: warm grace in tens of
# milliseconds so a wedge is caught fast, compile budget generous enough
# that a contended host's canary never false-wedges the recovery.
FAST = dict(warm_boot=True, compile_budget_s=2.0, warm_grace_s=0.15,
            min_deadline_s=0.05)


def _sigs(n, tamper=(), seed=7):
    rng = np.random.default_rng(seed)
    msgs, pks, sigs = [], [], []
    for i in range(n):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in tamper:
            sig = sig[:1] + bytes([sig[1] ^ 0xFF]) + sig[2:]
        msgs.append(msg)
        pks.append(pk)
        sigs.append(sig)
    return msgs, pks, sigs


def _wait(pred, timeout=20.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.005)
    return False


def _collector():
    """Reply recorder that keeps EVERY reply per rid — the double-reply
    assertions ride on the list lengths."""
    done = {}
    cond = threading.Condition()

    def reply_to(rid):
        def _reply(mask):
            with cond:
                done.setdefault(rid, []).append(mask)
                cond.notify_all()
        return _reply

    def wait_for(*rids, timeout=20.0):
        with cond:
            return cond.wait_for(lambda: all(r in done for r in rids),
                                 timeout=timeout)
    return done, reply_to, wait_for


# ---------------------------------------------------------------------------
# env opt-in + depth trainer
# ---------------------------------------------------------------------------

def test_cadence_env_opt_in(monkeypatch):
    monkeypatch.delenv(ENV_CADENCE, raising=False)
    assert not cadence_enabled()
    assert cadence_enabled(default=True)
    for raw, want in (("1", True), ("true", True), ("ON", True),
                      ("yes", True), ("0", False), ("off", False),
                      ("garbage", False)):
        monkeypatch.setenv(ENV_CADENCE, raw)
        assert cadence_enabled() is want


def test_ring_depth_clamps_to_supported_depths():
    assert RingDepth._clamp(1) == 2
    assert RingDepth._clamp(3) == 4
    assert RingDepth._clamp(8) == 8
    assert RingDepth._clamp(9) == 8
    assert RingDepth(pinned=3).depth() == 4


def test_ring_depth_conservative_until_trained():
    d = RingDepth(pinned=None)
    assert d.depth() == 2  # no evidence -> minimum
    for _ in range(RingDepth.MIN_OBSERVATIONS - 1):
        d.observe(0.01, 0.002)
    assert d.depth() == 2  # still short of MIN_OBSERVATIONS


def test_ring_depth_trains_from_dispatch_vs_wall():
    deep = RingDepth(pinned=None)
    for _ in range(RingDepth.MIN_OBSERVATIONS):
        deep.observe(0.010, 0.002)  # o/w = 5 -> 1+5 -> clamp 8
    assert deep.depth() == 8
    mid = RingDepth(pinned=None)
    for _ in range(RingDepth.MIN_OBSERVATIONS):
        mid.observe(0.009, 0.003)   # o/w = 3 -> 1+3 = 4
    assert mid.depth() == 4
    shallow = RingDepth(pinned=None)
    for _ in range(RingDepth.MIN_OBSERVATIONS):
        shallow.observe(0.001, 0.010)  # dispatch hides under one wall
    assert shallow.depth() == 2
    snap = shallow.snapshot()
    assert snap["k"] == 2 and not snap["pinned"]
    assert snap["dispatch_samples"] >= RingDepth.MIN_OBSERVATIONS
    json.dumps(snap)


def test_ring_depth_env_pin(monkeypatch):
    monkeypatch.setenv(ENV_DEPTH, "3")
    d = RingDepth()
    assert d.pinned == 4 and d.depth() == 4
    monkeypatch.setenv(ENV_DEPTH, "not-a-number")
    assert RingDepth().pinned is None


def test_ring_depth_from_manifest_seeds_and_tolerates_garbage(tmp_path):
    from hotstuff_tpu.utils.xla_cache import CompileManifest

    m = CompileManifest(str(tmp_path / "manifest.json"))
    m.record("kern1", "warmup:64", 0.004, cache_dir="/x")
    d = RingDepth.from_manifest(m, "kern1")
    assert d.snapshot()["wall_samples"] == 1

    class Hostile:
        def shape_walls(self, kernel):
            raise RuntimeError("corrupt manifest")

    d = RingDepth.from_manifest(Hostile(), "kern1")
    assert d.depth() == 2  # tolerated: trainer starts at the minimum


# ---------------------------------------------------------------------------
# scheduler per-tick quota
# ---------------------------------------------------------------------------

def _sched():
    return vsched.Scheduler(shapes=vsched.ShapeRegistry(use_host=True),
                            latency_cap_sigs=4096, bulk_cap_sigs=4096)


def _offer(sched, rid, n, cls=vsched.LATENCY, reply=None, seed=None):
    msgs, pks, sigs = _sigs(n, seed=seed if seed is not None else rid)
    assert sched.offer(proto.VerifyRequest(rid, msgs, pks, sigs),
                       reply if reply is not None else (lambda m: None),
                       cls=cls)


def test_next_tick_caps_the_coalesce_run():
    sched = _sched()
    for rid in range(1, 6):
        _offer(sched, rid, 4)
    launch = sched.next_tick(8)
    assert launch is not None and launch.kind == "verify"
    # the quota caps the coalesce run: 2 of the 5 four-sig requests
    assert sum(len(p) for p in
               launch.items[:len(launch.items) - launch.fill_count]) <= 8
    assert sched.queued_sigs(vsched.LATENCY) == 12


def test_next_tick_pad_fills_from_bulk_backlog():
    # Device shapes, not host: host mode verifies exactly n records so
    # bucket_capacity(n) == n and fill never happens; the single-chip
    # registry pads 3 sigs up to its compiled bucket, and next_tick
    # only ASSEMBLES (no dispatch), so no device is touched here.
    sched = vsched.Scheduler(shapes=vsched.ShapeRegistry(),
                             latency_cap_sigs=4096, bulk_cap_sigs=4096)
    _offer(sched, 1, 3)
    _offer(sched, 2, 1, cls=vsched.BULK)
    launch = sched.next_tick(64)
    assert launch is not None
    assert launch.fill_count >= 1  # the partial tick padded from bulk
    assert launch.cls == vsched.LATENCY


def test_next_tick_idle_semantics():
    sched = _sched()
    assert sched.next_tick(64) is None  # non-blocking by default
    t0 = time.monotonic()
    assert sched.next_tick(64, timeout=0.05) is None
    assert time.monotonic() - t0 < 5.0


def test_next_tick_timeout_park_wakes_on_offer():
    sched = _sched()
    got = []

    def park():
        got.append(sched.next_tick(64, timeout=10.0))

    t = threading.Thread(target=park, daemon=True)
    t.start()
    time.sleep(0.05)
    _offer(sched, 1, 4)
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert got and got[0] is not None and got[0].total_sigs == 4


# ---------------------------------------------------------------------------
# the ``tick:`` guard deadline class
# ---------------------------------------------------------------------------

def test_tick_class_gets_warm_grace_even_on_cold_boot():
    d = LaunchDeadlines(warm_boot=False, compile_budget_s=180.0,
                        warm_grace_s=30.0)
    # The ring only launches warmed shapes: a cold-boot tick key must
    # never inherit the minutes-long compile budget.
    assert d.deadline_s("tick:64") == 30.0
    assert d.deadline_s("launch:64") == 180.0


def test_tick_class_trained_p99_wins():
    d = LaunchDeadlines(warm_boot=False, warm_grace_s=30.0,
                        p99_multiple=8.0, min_deadline_s=0.5)
    for _ in range(LaunchDeadlines.MIN_OBSERVATIONS):
        d.observe("tick:64", 0.25)
    assert d.deadline_s("tick:64") == pytest.approx(2.0)
    assert d.deadline_s("tick:512") == 30.0  # untrained keys keep grace


# ---------------------------------------------------------------------------
# generation-tag lifecycle on a virtual clock (FakeEngine-driven)
# ---------------------------------------------------------------------------

class FakeEngine:
    """The minimal engine surface CadenceRing touches, with host-mask
    packs and a controllable guard so the lifecycle tests can drive
    ``_tick_once`` on a virtual clock."""

    def __init__(self):
        self._stopped = threading.Event()
        self._shapes = vsched.ShapeRegistry(use_host=True)
        self._sched = vsched.Scheduler(shapes=self._shapes,
                                       latency_cap_sigs=4096,
                                       bulk_cap_sigs=4096)
        self._pack_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="test-pack")
        self._tracer = Tracer.disabled()
        self._guard = None
        self.wedge_next_guarded = False
        self.laddered = []  # (batch, key, stage) from _wedge_ladder

    def _pack(self, batch):
        msgs = [m for p in batch for m in p.request.msgs]
        pks = [k for p in batch for k in p.request.pks]
        sigs = [s for p in batch for s in p.request.sigs]

        def dispatch():
            def fetch():
                return [bool(ref.verify(pk, m, s))
                        for m, pk, s in zip(msgs, pks, sigs)]
            return fetch
        return dispatch

    def _guarded(self, key, thunk):
        if self.wedge_next_guarded:
            self.wedge_next_guarded = False
            raise WedgedLaunch(key, 0.0)
        return thunk()

    def _guard_key(self, batch):
        return "launch:%d" % max(
            1, sum(len(p.request.msgs) for p in batch))

    def retry_after_ms(self, cls):
        return 50

    def _wedge_ladder(self, batch, key, stage):
        self.laddered.append((batch, key, stage))
        for p in batch:
            p.reply_fn([False] * len(p.request.msgs))

    def _trace_queue_waits(self, launch):
        pass

    def _trace_replies(self, batch):
        pass

    def close(self):
        self._pack_pool.shutdown(wait=False)


@pytest.fixture
def fake_ring():
    now = [100.0]
    engine = FakeEngine()
    ring = CadenceRing(engine, depth=RingDepth(pinned=2), expiry_s=1.0,
                       clock=lambda: now[0], wait=lambda t: False)
    yield engine, ring, now
    engine.close()


def test_expiry_re_resolves_once_then_drops_the_late_fetch(fake_ring):
    engine, ring, now = fake_ring
    msgs, pks, sigs = _sigs(5, tamper={2}, seed=11)
    expect = [bool(b) for b in
              [ref.verify(pk, m, s)
               for m, pk, s in zip(msgs, pks, sigs)]]
    done, reply_to, _ = _collector()
    assert engine._sched.offer(proto.VerifyRequest(1, msgs, pks, sigs),
                               reply_to(1), cls=vsched.LATENCY)
    launch = engine._sched.next_tick(ring._quota_sigs())
    assert ring._arm(launch)
    assert len(ring._pending) == 1
    # Past the injected expiry window: the host re-resolve answers the
    # batch exactly once (bit-identical) and invalidates the generation.
    now[0] += 2.0
    ring._expire_overdue(now[0])
    assert done[1] == [expect]
    snap = ring.stats.snapshot(enabled=True, depth=2)
    assert snap["generation"]["expiries"] == 1
    assert snap["generation"]["expired_sigs"] == 5
    # The late device verdict is a COUNTED drop, never a second reply.
    ring._collect_oldest()
    assert done[1] == [expect]
    snap = ring.stats.snapshot(enabled=True, depth=2)
    assert snap["generation"]["drops"] == 1
    assert not ring._pending


def test_expiry_answers_bulk_with_busy(fake_ring):
    engine, ring, now = fake_ring
    msgs, pks, sigs = _sigs(3, seed=12)
    done, reply_to, _ = _collector()
    assert engine._sched.offer(proto.VerifyRequest(7, msgs, pks, sigs),
                               reply_to(7), cls=vsched.BULK)
    assert ring._arm(engine._sched.next_tick(ring._quota_sigs()))
    now[0] += 2.0
    ring._expire_overdue(now[0])
    (reply,) = done[7]
    assert isinstance(reply, BusyReply)
    assert reply.retry_after_ms == 50
    ring._collect_oldest()
    assert len(done[7]) == 1  # still exactly one reply


def test_slot_wraparound_keeps_generations_straight(fake_ring):
    """More arms than physical slots (> max depth 8): every slot is
    reused, every verdict still lands exactly once — the generation tag
    is what makes reuse safe."""
    engine, ring, now = fake_ring
    done, reply_to, _ = _collector()
    expects = {}
    n_reqs = 2 * len(ring._slots) + 4  # 20 arms over 8 slots
    for rid in range(1, n_reqs + 1):
        msgs, pks, sigs = _sigs(2, tamper={rid % 2}, seed=rid)
        expects[rid] = [bool(ref.verify(pk, m, s))
                        for m, pk, s in zip(msgs, pks, sigs)]
        assert engine._sched.offer(
            proto.VerifyRequest(rid, msgs, pks, sigs), reply_to(rid),
            cls=vsched.LATENCY)
        armed = ring._tick_once(now[0])
        now[0] += 0.01
        assert armed or done  # either armed or collected forward
    while ring._pending:
        ring._collect_oldest()
    assert set(done) == set(expects)
    for rid, masks in done.items():
        assert masks == [expects[rid]], f"rid {rid}"
    snap = ring.stats.snapshot(enabled=True, depth=2)
    assert snap["generation"]["drops"] == 0
    assert snap["generation"]["expiries"] == 0
    # Slots actually cycled: 20 arms over 8 slots bump generations > 1.
    assert max(s.generation for s in ring._slots) >= 2


def test_wedged_fetch_invalidates_and_rides_the_ladder(fake_ring):
    engine, ring, now = fake_ring
    msgs, pks, sigs = _sigs(4, seed=13)
    done, reply_to, _ = _collector()
    assert engine._sched.offer(proto.VerifyRequest(1, msgs, pks, sigs),
                               reply_to(1), cls=vsched.LATENCY)
    assert ring._arm(engine._sched.next_tick(ring._quota_sigs()))
    engine.wedge_next_guarded = True
    ring._collect_oldest()
    assert ring.enabled is False
    assert engine.laddered and engine.laddered[0][2] == "fetch"
    assert len(done[1]) == 1  # the ladder answered, exactly once
    assert ring.stats.snapshot(enabled=False, depth=2)["fallbacks"] == 1


def test_clean_stop_drains_every_inflight_verdict(fake_ring):
    engine, ring, now = fake_ring
    done, reply_to, _ = _collector()
    expects = {}
    for rid in (1, 2):
        msgs, pks, sigs = _sigs(3, tamper={rid}, seed=20 + rid)
        expects[rid] = [bool(ref.verify(pk, m, s))
                        for m, pk, s in zip(msgs, pks, sigs)]
        assert engine._sched.offer(
            proto.VerifyRequest(rid, msgs, pks, sigs), reply_to(rid),
            cls=vsched.LATENCY)
        assert ring._arm(engine._sched.next_tick(ring._quota_sigs()))
    assert len(ring._pending) == 2
    engine._stopped.set()
    ring.run()  # returns immediately, draining both flights
    assert done[1] == [expects[1]] and done[2] == [expects[2]]
    assert not ring._pending


def test_idle_interval_backs_off_and_resets(fake_ring):
    engine, ring, now = fake_ring
    first = ring._interval(False, 0)
    assert first == pytest.approx(2 * CadenceRing.MIN_TICK_S)
    for _ in range(20):
        last = ring._interval(False, 0)
    assert last == CadenceRing.MAX_TICK_S  # capped backoff
    assert ring._interval(True, 1) == CadenceRing.MIN_TICK_S
    assert ring._interval(False, 0) == \
        pytest.approx(2 * CadenceRing.MIN_TICK_S)  # streak reset


def test_pinned_tick_interval_wins(fake_ring):
    engine, _, now = fake_ring
    ring = CadenceRing(engine, depth=RingDepth(pinned=2), tick_s=0.033,
                       clock=lambda: now[0], wait=lambda t: False)
    assert ring._interval(True, 1) == 0.033
    assert ring._interval(False, 0) == 0.033


def test_tick_key_rides_the_staged_bucket(fake_ring):
    engine, ring, _ = fake_ring
    msgs, pks, sigs = _sigs(3, seed=30)
    batch = [vsched.Pending(proto.VerifyRequest(1, msgs, pks, sigs),
                            lambda m: None, vsched.LATENCY)]
    assert ring._tick_key(batch) == "tick:3"


# ---------------------------------------------------------------------------
# the real engine: bit-identity, wedge fallback, OP_STATS round trip
# ---------------------------------------------------------------------------

def _cadence_engine(**kw):
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    engine = VerifyEngine(
        use_host=True, guard=g,
        ring_factory=lambda e: CadenceRing(e, depth=RingDepth(pinned=2)),
        **kw)
    return engine, g


def test_cadence_engine_masks_bit_identical_and_supervised():
    """Corpus bit-identity THROUGH the engine: ring verdicts equal
    verify_batch masks, every dispatch supervised under the ``tick:``
    guard class, and the OP_STATS cadence section reports the traffic."""
    engine, g = _cadence_engine()
    try:
        done, reply_to, wait_for = _collector()
        expects = {}
        for rid in range(1, 6):
            msgs, pks, sigs = _sigs(8, tamper={3}, seed=40 + rid)
            expects[rid] = [bool(b) for b in
                            eddsa.verify_batch(msgs, pks, sigs)]
            assert engine.submit(proto.VerifyRequest(rid, msgs, pks,
                                                     sigs),
                                 reply_to(rid), cls=vsched.LATENCY)
        assert wait_for(*expects)
        for rid, expect in expects.items():
            assert done[rid] == [expect], f"rid {rid}"
        snap = engine.stats_snapshot()
        cad = snap["cadence"]
        assert cad["enabled"] and cad["depth"] == 2
        assert cad["ticks"] >= 1 and cad["dispatch_ticks"] >= 1
        assert cad["queue_wait"]["n"] >= 5
        assert cad["generation"]["drops"] == 0
        json.dumps(cad)
        # guard supervision evidence: the tick class trained deadlines
        assert any(k.startswith("tick:") and v["n"] >= 1
                   for k, v in g.snapshot()["deadlines"].items())
    finally:
        engine.stop()
        g.close()


def test_cadence_wedge_falls_back_to_staged_no_double_reply():
    """The forced-wedge drill: a wedged cadence launch answers through
    the ladder bit-identically, the ring disengages, the crash-only
    reboot completes, and the STAGED loop serves the next request —
    with exactly one reply per rid throughout."""
    chaos = ChaosState()
    g = LaunchGuard(deadlines=LaunchDeadlines(**FAST))
    engine = VerifyEngine(
        use_host=True, guard=g, chaos=chaos,
        ring_factory=lambda e: CadenceRing(e, depth=RingDepth(pinned=2)))
    try:
        msgs, pks, sigs = _sigs(8, tamper={3}, seed=5)
        expect = [bool(b) for b in eddsa.verify_batch(msgs, pks, sigs)]
        done, reply_to, wait_for = _collector()
        # Healthy cadence traffic first, so the wedge hits a warm ring.
        assert engine.submit(proto.VerifyRequest(1, msgs, pks, sigs),
                             reply_to(1), cls=vsched.LATENCY)
        assert wait_for(1)
        assert done[1] == [expect]
        chaos.configure({"wedge": 1})
        assert engine.submit(proto.VerifyRequest(2, msgs, pks, sigs),
                             reply_to(2), cls=vsched.LATENCY)
        assert wait_for(2)
        assert done[2] == [expect]  # ladder host mask, bit-identical
        assert engine._ring.enabled is False
        cad = engine.stats_snapshot()["cadence"]
        assert cad["fallbacks"] == 1 and not cad["enabled"]
        assert _wait(lambda: engine._device_ok and not engine._rebooting)
        assert engine.stats_snapshot()["guard"]["reboots"] == 1
        # The staged loop now owns the engine thread: traffic serves.
        assert engine.submit(proto.VerifyRequest(3, msgs, pks, sigs),
                             reply_to(3), cls=vsched.LATENCY)
        assert wait_for(3)
        assert done[3] == [expect]
        assert all(len(v) == 1 for v in done.values()), \
            "a rid was answered more than once across the fallback"
    finally:
        engine.stop()
        g.close()


GOLDEN_CLIENT = """\
[2026-07-29T14:54:56.456Z INFO client] Transactions size: 512 B
[2026-07-29T14:54:56.456Z INFO client] Transactions rate: 2000 tx/s
[2026-07-29T14:54:56.525Z INFO client] Start sending transactions
[2026-07-29T14:54:56.577Z INFO client] Sending sample transaction 0
"""

GOLDEN_NODE = """\
[2026-07-29T14:54:55.100Z INFO mempool::config] Garbage collection depth set to 50 rounds
[2026-07-29T14:54:55.100Z INFO mempool::config] Sync retry delay set to 5000 ms
[2026-07-29T14:54:55.100Z INFO mempool::config] Sync retry nodes set to 3 nodes
[2026-07-29T14:54:55.100Z INFO mempool::config] Batch size set to 15000 B
[2026-07-29T14:54:55.100Z INFO mempool::config] Max batch delay set to 100 ms
[2026-07-29T14:54:55.101Z INFO consensus::config] Timeout delay set to 1000 ms
[2026-07-29T14:54:55.101Z INFO consensus::config] Sync retry delay set to 10000 ms
[2026-07-29T14:54:56.577Z INFO mempool::batch_maker] Batch aaa= contains sample tx 0
[2026-07-29T14:54:56.578Z INFO mempool::batch_maker] Batch aaa= contains 15360 B
[2026-07-29T14:54:56.700Z INFO consensus::proposer] Created B2 -> aaa=
[2026-07-29T14:54:57.000Z INFO consensus::core] Committed B2 -> aaa=
"""


def test_cadence_stats_round_trip_wire_to_parser():
    """OP_STATS ``cadence`` section -> JSON wire round trip ->
    LogParser CONFIG note + machine-readable ``parser.cadence``."""
    from hotstuff_tpu.harness import LogParser

    engine, g = _cadence_engine()
    try:
        msgs, pks, sigs = _sigs(6, tamper={1}, seed=55)
        done, reply_to, wait_for = _collector()
        assert engine.submit(proto.VerifyRequest(1, msgs, pks, sigs),
                             reply_to(1), cls=vsched.LATENCY)
        assert wait_for(1)
        stats = engine.stats_snapshot()
        assert stats["launches"] >= 1
        wire = json.loads(json.dumps(stats))  # the wire is JSON verbatim
        parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
        parser.note_sidecar_stats(wire)
        note = next(n for n in parser.notes
                    if n.startswith("Sidecar cadence ring:"))
        assert "depth 2" in note
        assert "tick(s)" in note and "queue wait p50" in note
        assert "FELL BACK TO STAGED" not in note
        assert parser.cadence == wire["cadence"]
    finally:
        engine.stop()
        g.close()


def test_cadence_fallback_note_names_the_disengage():
    from hotstuff_tpu.harness import LogParser

    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_sidecar_stats({
        "launches": 3,
        "cadence": {"enabled": False, "depth": 4, "ticks": 12,
                    "dispatch_ticks": 9, "idle_ticks": 3,
                    "tick_rate_hz": 480.0,
                    "pad_fill": {"sigs": 16, "launched_sigs": 128,
                                 "ratio": 0.125},
                    "generation": {"drops": 1, "expiries": 1,
                                   "expired_sigs": 8},
                    "fallbacks": 1,
                    "queue_wait": {"n": 9, "p50_ms": 0.4,
                                   "p99_ms": 2.2}},
    })
    note = next(n for n in parser.notes
                if n.startswith("Sidecar cadence ring:"))
    assert "FELL BACK TO STAGED" in note
    assert "1 generation drop(s)" in note

"""Ed25519 verification tests: device verifier vs pure-python reference and
the `cryptography` library as independent ground truth.

Parity model: crypto/src/tests/crypto_tests.rs (verify_valid_signature,
verify_invalid_signature, verify_valid_batch, verify_invalid_batch) in the
reference repo.
"""


import numpy as np
import pytest

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref


def make_sigs(n, msg_len=32, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        sk = rng.bytes(32)
        msg = rng.bytes(msg_len)
        _, pk = ref.generate_keypair(sk)
        out.append((msg, pk, ref.sign(sk, msg)))
    return out


def test_ref_impl_against_cryptography_lib():
    """Anchor the pure-python reference to an independent implementation."""
    pytest.importorskip(
        "cryptography",
        reason="third-party `cryptography` (OpenSSL) not installed on "
               "this image; the cross-check needs an independent "
               "implementation to anchor against")
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    rng = np.random.default_rng(7)
    for _ in range(4):
        seed = rng.bytes(32)
        lib_sk = Ed25519PrivateKey.from_private_bytes(seed)
        lib_pk = lib_sk.public_key().public_bytes_raw()
        msg = rng.bytes(100)
        lib_sig = lib_sk.sign(msg)
        _, pk = ref.generate_keypair(seed)
        assert pk == lib_pk
        assert ref.sign(seed, msg) == lib_sig  # Ed25519 is deterministic
        assert ref.verify(pk, msg, lib_sig)


def test_device_verify_valid():
    triples = make_sigs(4)
    msgs, pks, sigs = zip(*triples)
    mask = eddsa.verify_batch(list(msgs), list(pks), list(sigs))
    assert mask.all()


def test_device_verify_invalid():
    triples = make_sigs(6, seed=1)
    msgs, pks, sigs = map(list, zip(*triples))
    # corrupt in distinct ways
    sigs[0] = sigs[0][:10] + bytes([sigs[0][10] ^ 1]) + sigs[0][11:]   # R bits
    sigs[1] = sigs[1][:40] + bytes([sigs[1][40] ^ 1]) + sigs[1][41:]   # S bits
    msgs[2] = msgs[2] + b"!"                                           # message
    pks[3] = pks[0]                                                    # wrong key
    sigs[4] = b"\x00" * 64                                             # garbage
    mask = eddsa.verify_batch(msgs, pks, sigs)
    assert list(mask) == [False, False, False, False, False, True]


def test_noncanonical_rejected():
    (msg, pk, sig), = make_sigs(1, seed=2)
    # S >= L
    s = int.from_bytes(sig[32:], "little") + ref.L
    bad_s = sig[:32] + s.to_bytes(32, "little")
    # y >= p in R encoding
    r = int.from_bytes(sig[:32], "little")
    bad_r = ((r | ((1 << 255) - 1)) & ~(1 << 255)).to_bytes(32, "little") + sig[32:]
    mask = eddsa.verify_batch([msg, msg], [pk, pk], [bad_s, bad_r])
    assert not mask.any()


def test_small_order_universal_forgery_rejected():
    """verify_strict parity (crypto/src/lib.rs:204-208): with pk A = the
    identity encoding, sig = ([S]B || S) satisfies [S]B == R + [k]A for ANY
    message — a universal forgery unless small-order keys are rejected."""
    s = 12345
    r_enc = ref.encode_point(ref.scalar_mult(s, ref.B))
    forged = r_enc + s.to_bytes(32, "little")
    identity_pk = (1).to_bytes(32, "little")
    for msg in (b"any message at all", b"another one"):
        # cofactorless equation holds...
        a_pt = ref.decode_point(identity_pk)
        r_pt = ref.decode_point(forged[:32])
        k = ref._h(forged[:32] + identity_pk + msg) % ref.L
        assert ref.pt_equal(ref.scalar_mult(s, ref.B),
                            ref.pt_add(r_pt, ref.scalar_mult(k, a_pt)))
        # ...but both verifiers must reject it.
        assert not ref.verify(identity_pk, msg, forged)
        assert not eddsa.verify(identity_pk, msg, forged)


def test_small_order_r_identity_forgery_rejected():
    """R = identity with S = k*a mod L satisfies the cofactorless equation
    ([S]B == [k]A) for an honest key — the one R-side case the small-order
    check changes from accept to reject."""
    seed = b"\x09" * 32
    sk, pk = ref.generate_keypair(seed)
    import hashlib
    a = ref._clamp(int.from_bytes(hashlib.sha512(seed).digest()[:32],
                                  "little"))
    ident = ref.encode_point(ref.IDENT)
    msg = b"r-identity forgery"
    k = ref._h(ident + pk + msg) % ref.L
    s = k * a % ref.L
    forged = ident + s.to_bytes(32, "little")
    assert ref.pt_equal(ref.scalar_mult(s, ref.B),
                        ref.scalar_mult(k, ref.decode_point(pk)))
    assert not ref.verify(pk, msg, forged)
    assert not eddsa.verify(pk, msg, forged)


def test_small_order_table_matches_derived_torsion():
    """Pin _SMALL_ORDER_Y to the 8-torsion subgroup derived from reference
    arithmetic: a typo'd or missing row fails here, not in production."""
    # Find an order-8 generator: [L]P for any curve point lies in the
    # torsion subgroup; scan deterministic y encodings until one has
    # full order 8, then enumerate its multiples.
    gen = None
    y = 2
    while gen is None:
        pt = ref.decode_point(y.to_bytes(32, "little"))
        y += 1
        if pt is None:
            continue
        t = ref.scalar_mult(ref.L, pt)
        if not ref.pt_equal(ref.scalar_mult(4, t), ref.IDENT):
            gen = t
    derived = set()
    for i in range(8):
        enc = bytearray(ref.encode_point(ref.scalar_mult(i, gen)))
        enc[31] &= 0x7F
        derived.add(bytes(enc))
    assert derived == {bytes(row) for row in eddsa._SMALL_ORDER_Y}


def test_small_order_encodings_rejected_everywhere():
    """All 14 canonical-or-sign-flipped small-order encodings are rejected
    as A and as R, on host prep and in the reference verifier."""
    torsion = []
    for row in eddsa._SMALL_ORDER_Y:
        for sign in (0, 0x80):
            enc = bytearray(bytes(row))
            enc[31] |= sign
            if ref.decode_point(bytes(enc)) is not None:
                torsion.append(bytes(enc))
    assert len(torsion) >= 8
    (msg, pk, sig), = make_sigs(1, seed=7)
    for enc in torsion:
        prep = eddsa.prepare_batch([msg, msg], [enc, pk],
                                   [sig, enc + sig[32:]])
        assert not prep["host_ok"].any(), enc.hex()
        assert not ref.verify(enc, msg, sig)


def test_batch_padding_and_single():
    triples = make_sigs(3, seed=3)
    msgs, pks, sigs = map(list, zip(*triples))
    mask = eddsa.verify_batch(msgs, pks, sigs)  # pads 3 -> 8
    assert mask.all() and mask.shape == (3,)
    assert eddsa.verify(pks[0], msgs[0], sigs[0])
    assert not eddsa.verify(pks[0], msgs[1], sigs[0])


def test_empty_and_wrong_lengths():
    assert eddsa.verify_batch([], [], []).shape == (0,)
    (msg, pk, sig), = make_sigs(1, seed=4)
    assert not eddsa.verify_batch([msg], [pk[:31]], [sig])[0]
    assert not eddsa.verify_batch([msg], [pk], [sig[:63]])[0]


def test_fuzz_device_matches_reference():
    """Randomized agreement: valid sigs, bit flips, random keys."""
    rng = np.random.default_rng(11)
    msgs, pks, sigs, expect = [], [], [], []
    for i in range(12):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(int(rng.integers(0, 64)))
        sig = ref.sign(sk, msg)
        if i % 3 == 1:
            pos = int(rng.integers(0, 64))
            sig = sig[:pos] + bytes([sig[pos] ^ (1 << int(rng.integers(8)))]) + sig[pos + 1:]
        elif i % 3 == 2:
            pk = rng.bytes(32)
        msgs.append(msg); pks.append(pk); sigs.append(sig)
        expect.append(ref.verify(pk, msg, sig))
    mask = eddsa.verify_batch(msgs, pks, sigs)
    assert list(mask) == expect


def test_chunked_batch_over_subbatch_cap():
    """n > MAX_SUBBATCH runs as a chunked-scan single dispatch; the chunk
    count rounds to the next power of two (1500 -> g=2), not the row
    bucket's minimum of 8."""
    n = eddsa.MAX_SUBBATCH + 476
    triples = make_sigs(4, seed=13)
    msgs, pks, sigs = [], [], []
    for i in range(n):
        m, p, s = triples[i % 4]
        msgs.append(m); pks.append(p); sigs.append(s)
    sigs[eddsa.MAX_SUBBATCH + 7] = bytes(64)  # invalid, lands in chunk 2
    mask = eddsa.verify_batch(msgs, pks, sigs)
    assert mask.shape == (n,)
    assert not mask[eddsa.MAX_SUBBATCH + 7]
    assert mask.sum() == n - 1


@pytest.mark.slow  # ~44 s: recompiles the ladder per flag combination
def test_ab_flag_variants_match_reference():
    """Every import-time A/B switch (scripts/eval_device.py knobs) must
    produce reference-identical verdicts: a correctness bug in a flagged
    code path would otherwise surface only mid-A/B on a live device."""
    import importlib
    import os

    from hotstuff_tpu.ops import ed25519 as E

    flags = {
        "HOTSTUFF_TPU_STACK_MULS": "0",
        "HOTSTUFF_TPU_ONEHOT_SELECT": "0",
        "HOTSTUFF_TPU_TUPLE_POINTS": "0",
        "HOTSTUFF_TPU_JOINT_DECOMPRESS": "1",
    }
    triples = make_sigs(6, seed=31)
    msgs, pks, sigs = map(list, zip(*triples))
    sigs[2] = sigs[2][:40] + bytes([sigs[2][40] ^ 4]) + sigs[2][41:]
    msgs[4] = b"tampered"
    expect = [ref.verify(pk, m, s) for m, pk, s in zip(msgs, pks, sigs)]
    assert expect == [True, True, False, True, False, True]
    prep = eddsa.prepare_batch(msgs, pks, sigs)
    assert prep["host_ok"].all()

    saved = {k: os.environ.get(k) for k in flags}
    try:
        for flag, default in flags.items():
            os.environ[flag] = "0" if default == "1" else "1"
            E2 = importlib.reload(E)
            got = eddsa.verify_prepared_rows(prep["packed"], len(msgs))
            assert list(got) == expect, f"{flag} variant diverges"
            os.environ[flag] = default
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        importlib.reload(E)

"""graftwan tests: the WAN link-shape layer (chaos/netem.py), the
per-fault-class recovery SLO table (chaos/slo.py), and Twins-style
equivocation (config.twin_committee + the LogParser's STRICT safety
assertion) — all exercised without root, real ssh, or a device.  The
remote/tc compilation side is covered from the orchestration angle in
test_remote.py; here the spec grammar, the userspace WanProxy executor,
the SLO verdicts, and the safety assertion get direct coverage.
"""

import json
import socket
import threading
import time

import pytest

from hotstuff_tpu.chaos.netem import (
    LinkShape, WanError, WanProxy, host_links, netem_args, parse_wan,
    tc_heal_commands, tc_partition_commands, tc_setup_commands,
    tc_teardown_command,
)
from hotstuff_tpu.chaos.slo import (
    DEFAULT_SLO_MS, SloError, fault_class, judge, parse_slos,
)
from hotstuff_tpu.harness.logs import LogParser, ParseError

from test_harness import GOLDEN_CLIENT, GOLDEN_NODE


# ---------------------------------------------------------------------------
# WAN spec grammar
# ---------------------------------------------------------------------------


def test_parse_wan_inline_dsl():
    spec = parse_wan("node:0>node:1 latency_ms=200 loss_pct=0.5 name=wan01; "
                     "*>sidecar latency_ms=20 jitter_ms=5 name=sc; "
                     "default latency_ms=50")
    assert spec.link_names() == ["wan01", "sc"]
    wan01 = spec.by_name("wan01")
    assert (wan01.src, wan01.dst) == ("node:0", "node:1")
    assert wan01.shape.latency_ms == 200 and wan01.shape.loss_pct == 0.5
    assert spec.by_name("sc").src == "*"
    assert spec.default.latency_ms == 50
    # asymmetric pair: each direction is its OWN link (partial
    # partitions of a shared sidecar need exactly this)
    asym = parse_wan("node:0>sidecar latency_ms=10; "
                     "sidecar>node:0 loss_pct=100")
    assert [l.label() for l in asym.links] == \
        ["node:0>sidecar", "sidecar>node:0"]


def test_parse_wan_file_dict_and_roundtrip(tmp_path):
    data = {"links": [{"src": "node:0", "dst": "node:1",
                       "latency_ms": 40, "name": "ab"}],
            "default": {"latency_ms": 80, "rate_mbit": 100}}
    path = tmp_path / "wan.json"
    path.write_text(json.dumps(data))
    from_file = parse_wan(str(path))
    from_dict = parse_wan(data)
    assert from_file == from_dict
    # to_json is the logs/wan.json contract: parse(to_json(x)) == x
    assert parse_wan(from_dict.to_json()) == from_dict
    # a bare link list is accepted too
    assert parse_wan(data["links"]).link_names() == ["ab"]


@pytest.mark.parametrize("spec,fragment", [
    ("", "empty WAN spec"),
    ("node:0 latency_ms=5", "bad WAN entry"),
    ("node:0>node:0 latency_ms=5", "must differ"),
    ("node:0>* latency_ms=5", "bad dst"),
    ("oven:0>node:1 latency_ms=5", "bad src"),
    ("node:0>node:1 latency_ms=-5", "finite number"),
    ("node:0>node:1 loss_pct=150", "<= 100"),
    ("node:0>node:1 jitter_ms=5", "needs latency_ms"),
    ("node:0>node:1 warp=9", "unknown link key"),
    ("node:0>node:1 name=x; node:1>node:0 name=x", "duplicate link"),
    # Overlapping coverage of one (src, dst) pair is unrealizable: tc
    # installs two same-priority filters for one dst IP and only the
    # first band carries traffic; the second link silently no-ops.
    ("node:0>node:1 latency_ms=5 name=a; node:0>node:1 loss_pct=1 name=b",
     "both shape"),
    ("node:0>sidecar latency_ms=5 name=a; *>sidecar loss_pct=1 name=b",
     "both shape"),
    ({"links": "nope"}, "'links' must be a list"),
    ({"flinks": []}, "unknown WAN spec key"),
    ({"links": []}, "shapes nothing"),
])
def test_parse_wan_rejects(spec, fragment):
    with pytest.raises(WanError) as exc:
        parse_wan(spec)
    assert fragment in str(exc.value)


# ---------------------------------------------------------------------------
# tc/netem compilation (string-level; execution is test_remote.py's job)
# ---------------------------------------------------------------------------


def test_tc_setup_compiles_per_host_egress():
    spec = parse_wan("node:0>node:1 latency_ms=40 name=ab; "
                     "node:1>node:0 latency_ms=40 loss_pct=1 name=ba")
    peers = {"node:0": "10.0.0.1", "node:1": "10.0.0.2"}
    cmds = tc_setup_commands(spec, "node:0", peers)
    # teardown-first (idempotent re-setup), one root prio qdisc, then a
    # netem band + dst-ip filter for THIS host's single egress link.
    assert cmds[0] == tc_teardown_command()
    assert "tc qdisc add dev eth0 root handle 1: prio" in cmds[1]
    assert any("netem delay 40ms" in c for c in cmds)
    assert any("match ip dst 10.0.0.2/32" in c for c in cmds)
    assert not any("10.0.0.1/32" in c for c in cmds)  # own egress only
    # node:1's view carries the reverse link (with its loss term)
    back = tc_setup_commands(spec, "node:1", peers)
    assert any("delay 40ms loss 1%" in c for c in back)
    # an endpoint with no shaped egress installs nothing
    assert tc_setup_commands(spec, "sidecar", peers) == []


def test_tc_partition_heal_restore_spec_shape():
    spec = parse_wan("node:0>node:1 latency_ms=40 name=ab")
    peers = {"node:0": "10.0.0.1", "node:1": "10.0.0.2"}
    (part,) = tc_partition_commands(spec, "ab", "node:0", peers)
    assert "netem loss 100%" in part and "change" in part
    (heal,) = tc_heal_commands(spec, "ab", "node:0", peers)
    assert "netem delay 40ms" in heal
    # hosts whose egress does not carry the link compile to no-ops
    assert tc_partition_commands(spec, "ab", "node:1", peers) == []


def test_host_links_default_fills_unnamed_pairs():
    spec = parse_wan("node:0>node:1 latency_ms=40 name=ab; "
                     "default latency_ms=80")
    peers = {"node:0": "10.0.0.1", "node:1": "10.0.0.2",
             "node:2": "10.0.0.3"}
    links = host_links(spec, "node:0", peers)
    # explicit link first, then default-shaped fills in sorted peer
    # order; bands count up from 4 deterministically (setup and mid-run
    # partition/heal must agree on them).
    assert [(l.label(), ip, band) for l, ip, band in links] == [
        ("ab", "10.0.0.2", 4), ("node:0>node:2", "10.0.0.3", 5)]
    assert links[1][0].shape.latency_ms == 80
    assert netem_args(LinkShape(latency_ms=40, jitter_ms=5,
                                loss_pct=1, rate_mbit=100)) == \
        "delay 40ms 5ms loss 1% rate 100mbit"


def test_tc_band_references_are_hex():
    """tc parses classid minors and handle majors as HEX: band 10
    written "1:10" would address minor 0x10 = 16, a class the prio root
    never created — every tc add on a host with 7+ shaped links would
    fail mid-provisioning.  All band references must render in hex."""
    spec = parse_wan("default latency_ms=10")
    peers = {f"node:{i}": f"10.0.0.{i + 1}" for i in range(11)}
    cmds = tc_setup_commands(spec, "node:0", peers)  # bands 4..13
    joined = "\n".join(cmds)
    assert "parent 1:a " in joined and "flowid 1:a" in joined  # band 10
    assert "parent 1:d " in joined  # band 13
    assert "1:10" not in joined and "1:11" not in joined
    # partition/heal agree with setup on the hex numbering
    named = parse_wan(
        "; ".join(f"node:0>node:{i} latency_ms=10 name=l{i}"
                  for i in range(1, 11)))
    (part,) = tc_partition_commands(named, "l10", "node:0", peers)
    assert "parent 1:d " in part  # 10th link = band 13 = 0xd


def test_host_links_rejects_prio_band_overflow():
    """The prio qdisc caps at 16 bands (13 shaped links per egress);
    an overfull spec must fail at compile time — which the remote
    pre-flight runs before any host boots — not mid-fleet at tc time."""
    spec = parse_wan("default latency_ms=10")
    ok_peers = {f"node:{i}": f"10.0.0.{i + 1}" for i in range(14)}
    assert len(host_links(spec, "node:0", ok_peers)) == 13  # at the cap
    too_many = {f"node:{i}": f"10.0.0.{i + 1}" for i in range(15)}
    with pytest.raises(WanError) as exc:
        host_links(spec, "node:0", too_many)
    assert "16 bands" in str(exc.value)
    with pytest.raises(WanError):
        tc_setup_commands(spec, "node:0", too_many)


# ---------------------------------------------------------------------------
# WanProxy — the root-free executor, over real loopback sockets
# ---------------------------------------------------------------------------


def _echo_server():
    """One-shot echo server; returns (port, stop)."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    srv.settimeout(0.25)
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(5.0)

            def pump(c=conn):
                try:
                    while True:
                        data = c.recv(65536)
                        if not data:
                            return
                        c.sendall(data)
                except OSError:
                    pass
                finally:
                    c.close()

            threading.Thread(target=pump, daemon=True).start()

    threading.Thread(target=loop, daemon=True).start()
    return srv.getsockname()[1], lambda: (stop.set(), srv.close())


def _roundtrip(port, payload=b"ping", timeout=5.0):
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(payload)
        got = b""
        while len(got) < len(payload):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        return got


def test_wanproxy_forwards_and_pays_latency():
    port, stop_srv = _echo_server()
    proxy = WanProxy(("127.0.0.1", port),
                     shape=LinkShape(latency_ms=120))
    try:
        proxy.start()
        assert proxy.wait_ready(5.0)
        t0 = time.monotonic()
        assert _roundtrip(proxy.port, b"payload-xyz") == b"payload-xyz"
        elapsed = time.monotonic() - t0
        # The shape applies to BOTH pump directions (like netem on both
        # hosts' egress): one echo round trip pays >= 2 x 120 ms.
        assert elapsed >= 0.24, f"latency not applied ({elapsed:.3f}s)"
    finally:
        proxy.stop()
        stop_srv()


def test_token_bucket_charges_rate_not_chunks():
    """The ROADMAP item-5 follow-up: rate caps must be accurate at any
    rate.  The bucket sleeps only for the DEFICIT — idle time between
    chunks earns byte credit at the link rate — where the old per-chunk
    charge slept ``len * 8 / rate`` regardless of elapsed time."""
    from hotstuff_tpu.chaos.netem import _TokenBucket

    now = [0.0]
    bucket = _TokenBucket(0.8, clock=lambda: now[0])  # 100 KB/s
    # First chunk rides the burst allowance (8 KiB floor).
    assert bucket.delay(8192) == 0.0
    # An immediate second chunk pays its full serialization time.
    d = bucket.delay(65536)
    assert d == pytest.approx(65536 / 100_000, rel=0.01)
    # Idle time earns the credit back: after 2 s the debt (and more) is
    # repaid, so a burst-sized chunk is free again — the old model would
    # have charged it ~0.66 s regardless.
    now[0] = 2.0
    assert bucket.delay(8192) == 0.0
    # Sustained sending converges on exactly the cap: 10 chunks of
    # 10 KB with the clock advancing by each returned delay.
    bucket2 = _TokenBucket(0.8, clock=lambda: now[0])
    sent = 0
    t_start = now[0]
    for _ in range(10):
        d = bucket2.delay(10_000)
        now[0] += d
        sent += 10_000
    elapsed = now[0] - t_start
    # 100 KB at 100 KB/s minus the 8 KiB burst: ~0.92 s.
    assert elapsed == pytest.approx((sent - 8192) / 100_000, rel=0.05)
    # Uncapped rate never delays.
    assert _TokenBucket(0.0, clock=lambda: now[0]).delay(1 << 20) == 0.0


def test_wanproxy_rate_cap_accurate_below_one_mbit():
    """Regression with a real socket pair: a 0.8 Mbit (100 KB/s) cap
    must deliver ~100 KB/s — the per-chunk model over-shaped low caps
    (every chunk paid serialization + latency with no credit for the
    gaps in between)."""
    port, stop_srv = _echo_server()
    proxy = WanProxy(("127.0.0.1", port),
                     shape=LinkShape(rate_mbit=0.8))
    try:
        proxy.start()
        assert proxy.wait_ready(5.0)
        payload = b"\x07" * 40_000
        t0 = time.monotonic()
        assert _roundtrip(proxy.port, payload) == payload
        elapsed = time.monotonic() - t0
        # Forward direction spends (40000 - burst)/100000 ~ 0.32 s; the
        # echoed bytes pay the reverse bucket too -> ~0.64 s total.
        # Bound generously for CI scheduling noise, but tight enough
        # that the old double-charging (or no shaping) would fail.
        assert 0.35 <= elapsed <= 2.5, f"rate cap off ({elapsed:.3f}s)"
    finally:
        proxy.stop()
        stop_srv()


def test_wanproxy_partition_heal_and_loss():
    port, stop_srv = _echo_server()

    class LossyRng:
        """random() = 0.999 -> below a 100% loss threshold only."""

        def random(self):
            return 0.999

        def uniform(self, a, b):
            return 0.0

    proxy = WanProxy(("127.0.0.1", port), shape=LinkShape())
    try:
        proxy.start()
        assert proxy.wait_ready(5.0)
        assert _roundtrip(proxy.port) == b"ping"
        proxy.partition()
        # A dialing peer sees a black-holed route: connect may succeed
        # (the listener is up) but no byte ever comes back.
        with pytest.raises((OSError, AssertionError)):
            got = _roundtrip(proxy.port, timeout=1.0)
            assert got == b"ping"
        proxy.heal()
        assert _roundtrip(proxy.port) == b"ping"
        # 100% loss drops the CONNECTION (TCP can't lose single
        # segments): the proxied conversation dies mid-flight.
        proxy.set_shape(LinkShape(loss_pct=100.0))
        proxy._rng = LossyRng()
        with pytest.raises((OSError, AssertionError)):
            got = _roundtrip(proxy.port, timeout=1.0)
            assert got == b"ping"
    finally:
        proxy.stop()
        stop_srv()


def test_wan_headline_probe_tolerates_lossy_spec():
    """A user --wan with loss_pct drops connections BY DESIGN; the bench
    probe must report roundtrip_ok/healed False on a link lossy enough
    to defeat its retries — never collapse the whole wan sub-field to an
    error on exactly the shapes it claims to prove."""
    import bench

    out = bench.wan_headline_probe(
        "node:0>sidecar latency_ms=1 loss_pct=100 name=lossy")
    assert out["roundtrip_ok"] is False
    assert out["partition_enforced"] is True
    assert out["healed"] is False
    assert out["links"] == ["lossy"]


# ---------------------------------------------------------------------------
# SLO table + verdicts
# ---------------------------------------------------------------------------


def test_parse_slos_defaults_overlay_and_rejects(tmp_path):
    assert parse_slos(None) == DEFAULT_SLO_MS
    table = parse_slos("node-kill=8000; link-heal=3000")
    assert table["node-kill"] == 8000 and table["link-heal"] == 3000
    assert table["sidecar-degrade"] == DEFAULT_SLO_MS["sidecar-degrade"]
    path = tmp_path / "slo.json"
    path.write_text(json.dumps({"node-pause": 12000}))
    assert parse_slos(str(path))["node-pause"] == 12000
    for bad, fragment in [("warp-drive=1", "unknown fault class"),
                          ("node-kill=zero", "must be a number"),
                          ("node-kill=-5", "finite > 0"),
                          ("node-kill", "want class=ms"),
                          ("", "empty SLO spec"),
                          (42, "unsupported SLO spec type")]:
        with pytest.raises(SloError) as exc:
            parse_slos(bad)
        assert fragment in str(exc.value)


def test_fault_class_and_judge_verdicts():
    assert fault_class({"target": "node:3", "action": "kill"}) == "node-kill"
    assert fault_class({"target": "sidecar", "action": "degrade"}) == \
        "sidecar-degrade"
    assert fault_class({"target": "link:ab", "action": "heal"}) == "link-heal"

    summary = {"events": [
        {"target": "node:0", "action": "kill", "t": 5.0, "ok": True,
         "recovered": True, "recovery_ms": 800.0},
        {"target": "link:ab", "action": "heal", "t": 9.0, "ok": True,
         "recovered": True, "recovery_ms": 9_000.0},
        {"target": "node:1", "action": "pause", "t": 11.0, "ok": True,
         "recovered": False, "recovery_ms": None},
        {"target": "sidecar", "action": "kill", "t": 13.0, "ok": False,
         "error": "ssh died", "recovered": False, "recovery_ms": None},
    ]}
    verdict = judge(summary, {"link-heal": 3_000.0})
    by_class = {v["class"]: v for v in verdict["verdicts"]}
    assert by_class["node-kill"]["ok"]
    assert not by_class["link-heal"]["ok"]
    assert "recovery 9000 ms > SLO 3000 ms" in by_class["link-heal"]["reason"]
    assert by_class["node-pause"]["reason"] == "no commit after event"
    assert by_class["sidecar-kill"]["reason"] == "injection failed"
    assert not verdict["ok"]
    # headroom only counts RECOVERED events; worst is the heal's miss
    assert verdict["worst_headroom_ms"] == 3_000.0 - 9_000.0
    # all-green plans are ok with the default table
    green = {"events": [summary["events"][0]]}
    assert judge(green)["ok"] and judge(green)["worst_headroom_ms"] > 0


# ---------------------------------------------------------------------------
# Twins: committee view + the STRICT safety assertion
# ---------------------------------------------------------------------------


def _node_log_committing(height_digests):
    """Minimal node log committing {height: digest} (the lenient
    commit-view grammar: 'Committed B<h> -> <digest>=')."""
    lines = [GOLDEN_NODE]
    for h, d in sorted(height_digests.items()):
        lines.append(f"[2026-07-29T14:54:58.000Z INFO consensus::core] "
                     f"Committed B{h}\n")
        lines.append(f"[2026-07-29T14:54:58.000Z INFO consensus::core] "
                     f"Committed B{h} -> {d}=\n")
    return "".join(lines)


def test_twin_committee_shares_identity_remaps_ports():
    from hotstuff_tpu.harness.config import LocalCommittee, twin_committee

    names = ["a=", "b=", "c=", "d="]
    committee = LocalCommittee(names, 9000)
    view = twin_committee(committee, 0, 9900)
    # same identities — the twin SIGNS as its sibling
    assert set(view["consensus"]["authorities"]) == set(names)
    # ... but its own entry binds three fresh consecutive ports
    assert view["consensus"]["authorities"]["a="]["address"] == \
        "127.0.0.1:9900"
    memp = view["mempool"]["authorities"]["a="]
    assert memp["transactions_address"] == "127.0.0.1:9901"
    assert memp["mempool_address"] == "127.0.0.1:9902"
    # every OTHER entry is untouched (both views dial the same peers)
    assert view["consensus"]["authorities"]["b="] == \
        committee.json["consensus"]["authorities"]["b="]
    # and the original committee object was not mutated
    assert committee.json["consensus"]["authorities"]["a="]["address"] == \
        "127.0.0.1:9000"


def test_parser_safety_rejects_conflicting_commits():
    """Two honest logs committing DIFFERENT digests at the same height
    is a fork: hard ParseError, chaos plan or not."""
    a = _node_log_committing({7: "forkA"})
    b = _node_log_committing({7: "forkB"})
    with pytest.raises(ParseError) as exc:
        LogParser([GOLDEN_CLIENT], [a, b], faults=0)
    assert "SAFETY VIOLATION" in str(exc.value)
    assert "height 7" in str(exc.value)


def test_parser_safety_allows_prefix_views():
    """A node killed mid-write commits a PREFIX of the chain: subset
    views at a height are agreement, not a fork."""
    ahead = _node_log_committing({7: "same", 8: "later"})
    behind = _node_log_committing({7: "same"})
    parser = LogParser([GOLDEN_CLIENT], [ahead, behind], faults=0)
    assert parser._commit_views  # parsed, no violation


def test_parser_twin_fork_is_contained_not_survived():
    """A twin whose log forks the honest chain MUST fail the run even
    though every honest node agrees — equivocation has to be contained
    by the protocol, and the parser is the assertion."""
    honest = _node_log_committing({7: "agreed"})
    twin_forked = _node_log_committing({7: "equivocated"})
    with pytest.raises(ParseError) as exc:
        LogParser([GOLDEN_CLIENT], [honest, honest], faults=0,
                  twins=[twin_forked])
    assert "SAFETY VIOLATION" in str(exc.value)

    # A twin ABSORBED into the agreed chain passes, surfaces the note,
    # and stays out of the throughput numbers.
    twin_behind = _node_log_committing({7: "agreed"})
    parser = LogParser([GOLDEN_CLIENT], [honest, honest], faults=0,
                      twins=[twin_behind])
    assert any("Twins: 1 equivocating replica(s) active" in n
               for n in parser.notes)
    # twin commits never count toward committee throughput: B7 appears
    # once via the honest logs regardless of the twin's copy.
    assert "agreed=" in " ".join(parser.commits)


def test_parser_process_reads_twin_and_wan_slo_files(tmp_path):
    """LogParser.process folds the whole on-disk graftwan contract:
    twin-*.log into the safety assertion, wan.json into the WAN note,
    slo.json into the verdict table."""
    (tmp_path / "client-0.log").write_text(GOLDEN_CLIENT)
    (tmp_path / "node-0.log").write_text(_node_log_committing({7: "agreed"}))
    (tmp_path / "twin-0.log").write_text(
        _node_log_committing({7: "equivocated"}))
    (tmp_path / "wan.json").write_text(json.dumps(
        parse_wan("node:0>sidecar latency_ms=40 name=sc").to_json()))
    with pytest.raises(ParseError) as exc:
        LogParser.process(str(tmp_path), faults=0)
    assert "SAFETY VIOLATION" in str(exc.value)

    # contained twin: the run parses and carries the WAN + SLO context
    (tmp_path / "twin-0.log").write_text(
        _node_log_committing({7: "agreed"}))
    wall = time.mktime(time.strptime("2026-07-29T14:54:57",
                                     "%Y-%m-%dT%H:%M:%S")) \
        - time.timezone - 0.5
    (tmp_path / "chaos-events.json").write_text(json.dumps(
        [{"t": 5.0, "target": "node:0", "action": "kill",
          "wall": wall, "ok": True}]))
    (tmp_path / "slo.json").write_text(json.dumps({"node-kill": 9_000}))
    parser = LogParser.process(str(tmp_path), faults=0)
    out = parser.result()
    assert "Twins: 1 equivocating replica(s)" in out
    assert "WAN: 1 shaped link(s)" in out
    assert "Chaos SLO node-kill" in out and "PASS" in out
    assert parser.chaos["slo"]["ok"]
    # ... and a too-tight SLO table flips the verdict AND the strict
    # assertion (chaos mode): "recovered" must mean "fast enough".
    (tmp_path / "slo.json").write_text(json.dumps({"node-kill": 0.001}))
    with pytest.raises(ParseError) as exc:
        LogParser.process(str(tmp_path), faults=0)
    assert "SLO breached" in str(exc.value)


# ---------------------------------------------------------------------------
# Local bench wiring
# ---------------------------------------------------------------------------


def test_bench_parameters_carry_graftwan_fields():
    from hotstuff_tpu.harness.config import BenchParameters

    params = BenchParameters({
        "faults": 0, "nodes": 4, "rate": 1000, "tx_size": 512,
        "duration": 30, "twins": True,
        "wan": "node:0>sidecar latency_ms=40 name=sc",
        "slo": "node-kill=9000"})
    assert params.twins is True
    assert params.wan and params.slo
    assert BenchParameters({
        "faults": 0, "nodes": 4, "rate": 1000, "tx_size": 512,
        "duration": 30}).twins is False


def test_local_bench_rejects_unshapeable_wan():
    from hotstuff_tpu.harness.config import BenchParameters
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import BenchError

    def bench(wan, **extra):
        return LocalBench(BenchParameters({
            "faults": 1, "nodes": 4, "rate": 1000, "tx_size": 512,
            "duration": 30, "wan": wan, **extra}))

    # sidecar + alive-node fronts are locally shapeable
    bench("node:0>sidecar latency_ms=40; client>node:2 latency_ms=10",
          sidecar_host_crypto=True)._check_wan()
    # ... but shaping the sidecar link requires a sidecar in the run
    with pytest.raises(BenchError) as exc:
        bench("node:0>sidecar latency_ms=40")
    assert "boots no sidecar" in str(exc.value)
    # a dead replica's front is not (faults=1 -> node:3 never boots)
    with pytest.raises(BenchError) as exc:
        bench("client>node:3 latency_ms=10")._check_wan()
    assert "not locally shapeable" in str(exc.value)
    # inter-replica consensus links need real egress shaping (fleet)
    with pytest.raises(BenchError) as exc:
        bench("node:0>client latency_ms=10")._check_wan()
    assert "remote harness" in str(exc.value)
    # malformed specs die at construction, before any boot
    with pytest.raises(BenchError):
        bench("nonsense")


# ---------------------------------------------------------------------------
# End-to-end chaos matrix (slow lane; needs the native build)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_matrix_e2e_local(tmp_path, monkeypatch):
    """The whole graftwan pipeline against REAL processes: a 4-node
    committee behind a loopback WanProxy, a scripted mid-run node kill,
    and per-fault SLO verdicts out of the parser — no root, no ssh.
    The strict assertions inside LocalBench.run make this self-judging:
    a stalled recovery, an SLO miss, or a safety violation raises."""
    import os

    from conftest import NODE_BIN, REPO
    from hotstuff_tpu.harness.config import BenchParameters, NodeParameters
    from hotstuff_tpu.harness.local import LocalBench

    if not os.path.exists(NODE_BIN):
        pytest.skip("native binaries not built (cmake --build native/build)")
    monkeypatch.chdir(tmp_path)
    # reuse the repo's build: compile() is an up-to-date no-op through
    # the symlink, and alias_binaries links node/client from it
    os.symlink(os.path.join(REPO, "native"), tmp_path / "native")

    params = BenchParameters({
        "faults": 0, "nodes": 4, "rate": 500, "tx_size": 64,
        "duration": 10,
        "fault_plan": "3 node:1 kill",
        "wan": "client>node:0 latency_ms=30 name=c0",
        "slo": "node-kill=9000"})
    node_params = NodeParameters.default()
    node_params.json["consensus"]["timeout_delay"] = 1_000
    node_params.timeout_delay = 1_000
    parser = LocalBench(params, node_params).run()

    out = parser.result()
    # the kill was injected, recovery was measured, and the verdict is
    # a PASS against the run's own SLO table (note label = the
    # recovery.event_label spelling: "t=<t>s <action> <target>" — this
    # assertion had rotted against an older ordering and the slow lane
    # carried it silently)
    assert "Chaos t=3s kill node:1" in out
    assert "Chaos SLO node-kill" in out and "PASS" in out
    assert parser.chaos["slo"]["ok"], parser.chaos["slo"]
    assert "WAN: 1 shaped link(s)" in out
    # the on-disk contract a re-parse (or the aggregator) consumes
    events = json.load(open("logs/chaos-events.json"))
    assert [e["action"] for e in events] == ["kill"] and events[0]["ok"]
    assert json.load(open("logs/wan.json"))["links"][0]["name"] == "c0"
    assert json.load(open("logs/slo.json"))["node-kill"] == 9_000


@pytest.mark.slow
def test_twins_e2e_contained(tmp_path, monkeypatch):
    """Twins scenario against real processes: replica 0's keypair runs
    in TWO node processes with the honest committee split across the
    views.  The run passes only if equivocation was CONTAINED — the
    parser's safety assertion raises on any conflicting commit."""
    import os

    from conftest import NODE_BIN, REPO
    from hotstuff_tpu.harness.config import BenchParameters, NodeParameters
    from hotstuff_tpu.harness.local import LocalBench

    if not os.path.exists(NODE_BIN):
        pytest.skip("native binaries not built (cmake --build native/build)")
    monkeypatch.chdir(tmp_path)
    os.symlink(os.path.join(REPO, "native"), tmp_path / "native")

    params = BenchParameters({
        "faults": 0, "nodes": 4, "rate": 500, "tx_size": 64,
        "duration": 10, "twins": True})
    node_params = NodeParameters.default()
    node_params.json["consensus"]["timeout_delay"] = 1_000
    node_params.timeout_delay = 1_000
    parser = LocalBench(params, node_params).run()

    out = parser.result()
    assert "Twins: 1 equivocating replica(s) active; safety held" in out
    # the twin's log exists and fed the assertion
    assert os.path.exists("logs/twin-0.log")

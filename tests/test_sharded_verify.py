"""Multi-chip sharded verification tests on the 8-device virtual CPU mesh."""

import numpy as np

import jax

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
from hotstuff_tpu.parallel.mesh import make_mesh
from hotstuff_tpu.parallel.sharded_verify import (verify_batch_sharded,
                                                  verify_rlc_sharded)


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_matches_single_device():
    rng = np.random.default_rng(5)
    msgs, pks, sigs = [], [], []
    for i in range(16):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in (3, 11):
            sig = sig[:8] + bytes([sig[8] ^ 1]) + sig[9:]
        msgs.append(msg); pks.append(pk); sigs.append(sig)

    expect = eddsa.verify_batch(msgs, pks, sigs)
    mesh = make_mesh(8)
    prep = eddsa.prepare_batch(msgs, pks, sigs)
    got = verify_batch_sharded(mesh, prep)
    assert list(got) == list(expect)
    assert not got[3] and not got[11] and got.sum() == 14


def test_sharded_pads_ragged_batch():
    rng = np.random.default_rng(6)
    msgs, pks, sigs = [], [], []
    for _ in range(11):  # not a multiple of 8
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(16)
        msgs.append(msg); pks.append(pk); sigs.append(ref.sign(sk, msg))
    mesh = make_mesh(8)
    got = verify_batch_sharded(mesh, eddsa.prepare_batch(msgs, pks, sigs))
    assert got.shape == (11,) and got.all()


def test_sharded_chunked_large_batch():
    """Per-shard batches beyond the sub-batch cap run as a chunked scan
    inside each shard (one program, conv groups bounded) — exercised with a
    small cap so 8 devices x 4 chunks x 64 = 2048 votes cover the path."""
    rng = np.random.default_rng(21)
    base = []
    for _ in range(12):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        base.append((msg, pk, ref.sign(sk, msg)))
    n = 2048
    msgs = [base[i % 12][0] for i in range(n)]
    pks = [base[i % 12][1] for i in range(n)]
    sigs = [base[i % 12][2] for i in range(n)]
    # One invalid vote that survives host canonicality (valid encodings,
    # wrong equation) so the DEVICE must find it: flip a bit in S.
    sigs[777] = sigs[777][:33] + bytes([sigs[777][33] ^ 1]) + sigs[777][34:]
    mesh = make_mesh(8)
    prep = eddsa.prepare_batch(msgs, pks, sigs)
    mask, bad = verify_batch_sharded(mesh, prep, return_bad_total=True,
                                     max_subbatch=64)
    assert mask.shape == (n,)
    assert not mask[777] and mask.sum() == n - 1
    assert bad == 1


def test_sharded_rlc_matches_per_signature():
    """The mesh-sharded RLC combined check: one dispatch for a valid
    (ragged) quorum, per-signature fallback agreement when a vote is
    corrupted or host-rejected."""
    rng = np.random.default_rng(31)
    msgs, pks, sigs = [], [], []
    for _ in range(13):  # ragged: pads per-shard buckets with zero-z rows
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        msgs.append(msg); pks.append(pk); sigs.append(ref.sign(sk, msg))
    mesh = make_mesh(8)
    got = verify_rlc_sharded(mesh, eddsa.prepare_batch(msgs, pks, sigs))
    assert got.shape == (13,) and got.all()

    sigs[5] = sigs[5][:40] + bytes([sigs[5][40] ^ 1]) + sigs[5][41:]
    pks[9] = b"\xff" * 32  # host-rejected encoding (y >= p)
    prep = eddsa.prepare_batch(msgs, pks, sigs)
    got = verify_rlc_sharded(mesh, prep)
    want = eddsa.verify_batch(msgs, pks, sigs)
    assert got.tolist() == want.tolist()
    assert not got[5] and not got[9] and got.sum() == 11


def test_shard_shapes_alignment_rule():
    """THE shard-alignment arithmetic: per-shard power-of-two buckets
    with the warmup floor, whole-chunk growth beyond the sub-batch cap,
    and global rows always divisible by the device count."""
    from hotstuff_tpu.parallel.shard_shapes import (shard_aligned_rows,
                                                    shard_bucket)

    assert shard_bucket(16, 8) == 2
    assert shard_bucket(17, 8) == 4          # ceil(17/8)=3 -> pow2 4
    assert shard_bucket(1, 8) == 1           # floor: _MIN_BUCKET/8
    assert shard_bucket(1, 2) == 4           # floor: _MIN_BUCKET/2
    assert shard_bucket(3000, 8) == 512      # NOT 375
    # Beyond the per-shard cap: whole max_subbatch chunks, pow2 count.
    assert shard_bucket(8 * 3000, 8, max_subbatch=1024) == 4 * 1024
    assert shard_bucket(100, 8, max_subbatch=4) == 16  # ceil=13 -> g=4
    for n in (1, 7, 16, 100, 3000, 50_000):
        for n_dev in (2, 4, 8):
            rows = shard_aligned_rows(n, n_dev)
            assert rows % n_dev == 0 and rows >= n
            assert rows == n_dev * shard_bucket(n, n_dev)
    import pytest

    with pytest.raises(ValueError):
        shard_bucket(8, 0)


def test_whole_backlog_scan_matches_sliced_path():
    """graftscale: the whole-backlog chunked mesh scan
    (verify_sharded_chunked) returns a mask bit-identical to the sliced
    per-signature path (verify_batch_sharded == verify_batch) for the
    same backlog — including device-detected invalid rows and
    host-rejected encodings — through both the eager and the staged
    pack -> dispatch -> fetch entries."""
    from hotstuff_tpu.parallel.sharded_verify import (
        verify_sharded_chunked, verify_sharded_chunked_pack)

    rng = np.random.default_rng(53)
    msgs, pks, sigs = [], [], []
    for i in range(40):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in (7, 33):
            sig = sig[:8] + bytes([sig[8] ^ 1]) + sig[9:]
        msgs.append(msg); pks.append(pk); sigs.append(sig)
    pks[21] = b"\xff" * 32  # host-rejected encoding (y >= p)
    mesh = make_mesh(8)
    want = eddsa.verify_batch(msgs, pks, sigs)
    prep = eddsa.prepare_batch(msgs, pks, sigs)
    # rows=2 -> per-shard demand ceil(40/8)=5 -> g=4 chunks of 2 rows.
    mask, bad = verify_sharded_chunked(mesh, prep, rows=2,
                                       return_bad_total=True)
    assert mask.tolist() == want.tolist()
    assert not mask[7] and not mask[33] and not mask[21]
    assert bad == 2  # device-detected; the host rejection is excluded
    # The staged production entry lands on the SAME (g, rows) program.
    dispatch = verify_sharded_chunked_pack(
        mesh, eddsa.prepare_batch(msgs, pks, sigs), rows=2)
    assert dispatch()().tolist() == want.tolist()


def test_mesh_chunk_count_arithmetic():
    """The scan's (g, rows) rule: pow2 chunk counts covering per-shard
    demand, agreeing with the aligned-rows capacity whenever demand
    exceeds one chunk — incl. the 3000-on-8-devices case, which scans
    as 4 chunks of 128 rows = the 8x512 shard-aligned shape (never a
    375-row shard)."""
    import pytest

    from hotstuff_tpu.parallel.shard_shapes import (mesh_chunk_count,
                                                    shard_aligned_rows)

    assert mesh_chunk_count(3000, 8, 128) == 4
    assert 8 * 4 * 128 == shard_aligned_rows(3000, 8) == 8 * 512
    assert mesh_chunk_count(40, 8, 2) == 4      # ceil(5/2) -> pow2 4
    assert mesh_chunk_count(16, 8, 4) == 1      # fits one chunk
    assert mesh_chunk_count(16 * 1024, 8, 128) == 16
    # Beyond-one-chunk demand always pads to the aligned-rows capacity
    # (both grow in powers of two over the same floor).
    for n in (300, 1500, 3000, 20_000):
        for n_dev in (2, 8):
            for rows in (4, 128):
                g = mesh_chunk_count(n, n_dev, rows)
                total = n_dev * g * rows
                assert total >= n
                if -(-n // n_dev) >= rows:
                    assert total == shard_aligned_rows(n, n_dev)
    with pytest.raises(ValueError):
        mesh_chunk_count(100, 0, 4)
    with pytest.raises(ValueError):
        mesh_chunk_count(100, 8, 3)   # rows must be a power of two


def test_sharded_pack_stages_match_eager():
    """The pack -> dispatch -> fetch split (the engine's double-buffered
    launch shape) returns the same masks as the eager entry points, for
    both the ladder and the RLC mesh programs."""
    from hotstuff_tpu.parallel.sharded_verify import (
        verify_batch_sharded_pack, verify_rlc_sharded_pack)

    rng = np.random.default_rng(47)
    msgs, pks, sigs = [], [], []
    for i in range(21):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in (2, 19):
            sig = sig[:8] + bytes([sig[8] ^ 1]) + sig[9:]
        msgs.append(msg); pks.append(pk); sigs.append(sig)
    mesh = make_mesh(8)
    want = eddsa.verify_batch(msgs, pks, sigs)

    dispatch = verify_batch_sharded_pack(
        mesh, eddsa.prepare_batch(msgs, pks, sigs))
    assert dispatch()().tolist() == want.tolist()

    bisected = []
    dispatch = verify_rlc_sharded_pack(
        mesh, eddsa.prepare_batch(msgs, pks, sigs),
        on_bisect=lambda: bisected.append(1))
    assert dispatch()().tolist() == want.tolist()
    assert bisected == [1]  # tampered rows forced the bisection path

    # All-valid: the combined check passes in one dispatch, no bisection.
    ok_prep = eddsa.prepare_batch(msgs[3:19], pks[3:19], sigs[3:19])
    bisected.clear()
    assert verify_rlc_sharded_pack(
        mesh, ok_prep, on_bisect=lambda: bisected.append(1))()().all()
    assert bisected == []

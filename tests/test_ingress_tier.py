"""graftingress tests: the signed-transaction ingress tier's python
half — per-user key derivation + the bounded keyring LRU, the signed
frame round trip against the documented preimage construction, the
wirecheck ``txframe-mismatch`` constant extractors and the repo-clean
gate, the LogParser's signed-ingress accounting (verified goodput,
strict zero-forged-committed and shard-fairness assertions), the node
METRICS admission-verify suffix, and the bench ``users`` headline
probe's schema + budget-skip contract."""

import hashlib
import importlib.util
import os

import pytest

from conftest import REPO
from hotstuff_tpu.analysis import wirecheck
from hotstuff_tpu.crypto import txsign
from hotstuff_tpu.harness.logs import LogParser, ParseError
from hotstuff_tpu.obs.sampler import parse_node_metrics
from test_harness import GOLDEN_CLIENT, GOLDEN_NODE

# ---------------------------------------------------------------------------
# key derivation + frame construction (python twin of tx_frame.hpp)
# ---------------------------------------------------------------------------


def test_user_key_derivation_is_deterministic_and_documented():
    # The derivation IS the documented construction: SHA-512(domain ||
    # seed u64 BE || user u64 BE)[:32] — recomputed here from hashlib so
    # a refactor cannot silently change what the C++ side must mirror.
    want = hashlib.sha512(
        txsign.TX_KEY_DOMAIN + (5).to_bytes(8, "big")
        + (9).to_bytes(8, "big")).digest()[:32]
    assert txsign.derive_user_seed(5, 9) == want
    assert txsign.derive_user_keypair(5, 9) == txsign.derive_user_keypair(5, 9)
    assert txsign.derive_user_keypair(5, 9)[1] != \
        txsign.derive_user_keypair(5, 10)[1]
    assert txsign.derive_user_keypair(6, 9)[1] != \
        txsign.derive_user_keypair(5, 9)[1]


def test_keyring_lru_is_bounded_and_rederives_identically():
    ring = txsign.UserKeyring(seed=5, capacity=2)
    pk1 = ring.get(1)[1]
    ring.get(2)
    assert len(ring) == 2 and ring.derivations == 2
    ring.get(2)                       # hit: no new derivation
    assert ring.derivations == 2
    ring.get(3)                       # evicts user 1 (LRU)
    assert len(ring) == 2 and ring.derivations == 3
    assert ring.get(1)[1] == pk1      # re-derived, same key
    assert ring.derivations == 4


def test_frame_preimage_matches_documented_construction():
    kp = txsign.derive_user_keypair(5, 0)
    payload = txsign.build_payload(txsign.TX_MARKER_SAMPLE, 7, size=16)
    frame = txsign.build_signed_tx(kp, nonce=3, payload=payload)
    assert len(frame) == txsign.TX_FRAME_OVERHEAD + len(payload)
    tx = txsign.parse_signed_tx(frame)
    assert tx.pk == kp[1] and tx.nonce == 3 and tx.payload == payload
    # Preimage: SHA-512/32 over the domain tag + the frame with the
    # signature stripped — byte-for-byte, not via the library helper.
    digest, pk, sig = txsign.admission_record(frame)
    assert digest == hashlib.sha512(
        txsign.TX_SIGN_DOMAIN + frame[:-txsign.TX_SIG_LEN]).digest()[:32]
    assert txsign.verify_tx(frame)
    flipped = txsign.build_signed_tx(kp, nonce=3, payload=payload,
                                     flip_sig_bit=True)
    # A forged frame parses identically and dies only at verify.
    assert txsign.parse_signed_tx(flipped)[:3] == tx[:3]
    assert not txsign.verify_tx(flipped)


# ---------------------------------------------------------------------------
# wirecheck: the txframe-mismatch rule's extractors + the repo-clean gate
# ---------------------------------------------------------------------------


def test_wirecheck_txframe_extractors_read_cpp_idioms():
    src = (
        "constexpr size_t kTxMaxPayload = 1u << 20;\n"
        "constexpr size_t kTxFrameHeaderLen = 1 + kTxPkLen;\n"
        "static_assert(kTxFrameHeaderLen == 45, \"drifted\");\n"
        "constexpr char kTxSignDomain[] = \"graftingress-tx-v1\";\n")
    assert wirecheck.cpp_shift_constants(src) == {"kTxMaxPayload": 1 << 20}
    assert wirecheck.cpp_static_assert_values(src) == {
        "kTxFrameHeaderLen": 45}
    assert wirecheck.cpp_char_string_constants(src) == {
        "kTxSignDomain": "graftingress-tx-v1"}
    py = 'TX_SIGN_DOMAIN = b"graftingress-tx-v1"\nOTHER = "not-bytes"\n'
    assert wirecheck.py_bytes_constants(py) == {
        "TX_SIGN_DOMAIN": "graftingress-tx-v1"}


def test_wirecheck_txframe_rule_is_clean_on_repo():
    findings = [f for f in wirecheck.check(REPO)
                if f.rule == "txframe-mismatch"]
    assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------------------------------
# LogParser: signed-ingress accounting + the two strict assertions
# ---------------------------------------------------------------------------

_SIGNED_CLIENT_LINES = (
    "[2026-07-29T14:54:56.456Z INFO client] Signed ingress enabled "
    "(seed 5, forge 1%, user offset 0, sample offset 0)\n"
    "[2026-07-29T14:54:57.100Z INFO client] Forged transaction sent "
    "(3 total)\n"
    "[2026-07-29T14:55:01.500Z INFO client] Sent 1000 transactions\n")

_VERIFY_NODE_LINES = (
    "[2026-07-29T14:54:55.100Z INFO mempool::config] Ingress signature "
    "verification enabled with batch 64 txs\n"
    "[2026-07-29T14:54:56.900Z WARN mempool::tx_verify] Rejected 2 "
    "forged transaction(s) at ingress admission (2 total)\n"
    "[2026-07-29T14:54:57.000Z WARN mempool::tx_verify] Admission "
    "verify busy; shed 2 tx(s) with retry-after 7 ms (2 total)\n"
    "[2026-07-29T14:54:58.000Z INFO node::metrics] METRICS commits=5 "
    "commit_rate=2.50 ingress_tx=100 ingress_bytes=5000 busy=0 "
    "breaker=closed verified=98 forged=2 vq=1\n")


def test_parser_signed_ingress_accounting_and_note():
    parser = LogParser([GOLDEN_CLIENT + _SIGNED_CLIENT_LINES],
                       [GOLDEN_NODE + _VERIFY_NODE_LINES], faults=0)
    ing = parser.ingress
    assert ing["signed"] and ing["verify_on"]
    assert ing["forge_pct"] == 1.0
    assert ing["forged_sent"] == 3
    assert ing["sent"] == 1000
    assert ing["verified"] == 98
    assert ing["forged_rejected"] == 2
    assert ing["busy_shed"] == 2
    assert ing["forged_committed"] == 0
    assert ing["shards"] == 0           # one client process, no shards
    assert parser.configs[0]["mempool"]["verify_batch"] == 64
    assert any(n.startswith("Signed ingress:") for n in parser.notes)


def test_parser_legacy_unsigned_logs_parse_unchanged():
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    assert not parser.ingress["signed"]
    assert not parser.ingress["verify_on"]
    assert parser.ingress["forged_committed"] == 0
    assert not any("Signed ingress" in n for n in parser.notes)


def test_parser_rejects_forged_commit_on_verify_run_only():
    forged_batch = (
        "[2026-07-29T14:54:56.950Z WARN mempool::batch_maker] Batch "
        "2hHolx56fF0YIblphIzIeT2IHMTpt2ISKPP/4qqCsaU= contains forged "
        "tx 9\n")
    # verify-ingress ON + a forged tx inside a sealed batch: the run is
    # meaningless and the parser must say so loudly.
    with pytest.raises(ParseError, match="forged transaction"):
        LogParser([GOLDEN_CLIENT + _SIGNED_CLIENT_LINES],
                  [GOLDEN_NODE + _VERIFY_NODE_LINES + forged_batch],
                  faults=0)
    # verify-ingress OFF (unsigned A/B leg): the same line is counted
    # but not fatal — there was no admission stage to blame.
    parser = LogParser([GOLDEN_CLIENT],
                       [GOLDEN_NODE + forged_batch], faults=0)
    assert parser.ingress["forged_committed"] == 1


def _shard_client(sample_offset, sent):
    return GOLDEN_CLIENT + (
        "[2026-07-29T14:54:56.456Z INFO client] Signed ingress enabled "
        f"(seed 5, forge 1%, user offset 0, sample offset {sample_offset})\n"
        f"[2026-07-29T14:55:01.500Z INFO client] Sent {sent} "
        "transactions\n")


def test_parser_shard_fairness_strict_and_noted():
    # Balanced shards: accepted, with a per-shard note.
    parser = LogParser([_shard_client(0, 1000), _shard_client(100000, 900)],
                       [GOLDEN_NODE + _VERIFY_NODE_LINES], faults=0)
    assert parser.ingress["shards"] == 2
    assert sorted(parser.ingress["shard_sent"]) == [900, 1000]
    assert any(n.startswith("Client shards: 2") for n in parser.notes)
    # A starved shard (beyond 4x divergence) is a parse-level failure.
    with pytest.raises(ParseError, match="fairness"):
        LogParser([_shard_client(0, 1000), _shard_client(100000, 100)],
                  [GOLDEN_NODE + _VERIFY_NODE_LINES], faults=0)


def test_sampler_metrics_verify_suffix_is_optional():
    with_suffix = (
        "[2026-07-29T14:54:58.000Z INFO node] METRICS commits=5 "
        "commit_rate=2.50 ingress_tx=100 ingress_bytes=5000 busy=0 "
        "breaker=closed verified=98 forged=2 vq=1\n")
    legacy = (
        "[2026-07-29T14:54:59.000Z INFO node] METRICS commits=6 "
        "commit_rate=2.60 ingress_tx=120 ingress_bytes=6000 busy=1 "
        "breaker=closed\n")
    recs = parse_node_metrics(with_suffix + legacy)
    assert len(recs) == 2
    assert recs[0]["metrics"]["verified"] == 98
    assert recs[0]["metrics"]["forged"] == 2
    assert recs[0]["metrics"]["vq"] == 1
    assert "verified" not in recs[1]["metrics"]
    assert recs[1]["metrics"]["commits"] == 6


# ---------------------------------------------------------------------------
# bench: the ``users`` headline probe + trend flattening
# ---------------------------------------------------------------------------


def test_users_probe_schema_and_acceptance_at_small_populations():
    import bench

    out = bench.users_headline_probe(populations=(50, 120),
                                     txs_per_point=24)
    assert out["ok"], out
    assert out["mix_forge_pct"] == 1.0
    assert out["txs_per_point"] == 24
    for pop in (50, 120):
        pt = out[f"u{pop}"]
        assert pt["point_ok"], pt
        assert pt["users"] == pop
        assert pt["txs"] == 24 and pt["answered"] == 24
        assert 1 <= pt["distinct_users"] <= pop
        # derive-on-first-arrival: exactly one derivation per user seen
        assert pt["key_derivations"] == pt["distinct_users"]
        assert pt["forged_sent"] >= 1          # floored at one forgery
        assert pt["forgery_rejection_rate"] == 1.0
        assert pt["verified"] == 24 - pt["forged_sent"]
        assert pt["verified_goodput_sigs_per_s"] > 0
        assert pt["bulk_ingress_share"] == 1.0  # lane fully ingress-fed
        assert pt["bulk_ingress_sigs"] == 24


def test_users_probe_skips_points_past_budget():
    import bench

    out = bench.users_headline_probe(populations=(50, 120),
                                     budget_s=-1.0)
    assert out["u50"] == {"skipped": True}
    assert out["u120"] == {"skipped": True}
    assert out["ok"] is False


@pytest.mark.slow
def test_signed_ingress_e2e_local(tmp_path, monkeypatch):
    """The graftingress acceptance drill against REAL processes: a
    4-node committee with ``verify_ingress`` on, sharded signing
    clients (``client_shards=2`` per node) streaming per-user-signed
    frames with a seeded 1% forgery mix.  The run must commit, the
    admission stage must reject forgeries, and the parser's strict
    invariants (zero forged txs in any sealed batch, shard fairness)
    must hold — LogParser raises otherwise, so a clean return IS the
    assertion; the checks below pin the machine-readable evidence."""
    from conftest import NODE_BIN
    from hotstuff_tpu.harness.config import BenchParameters, NodeParameters
    from hotstuff_tpu.harness.local import LocalBench

    if not os.path.exists(NODE_BIN):
        pytest.skip("native binaries not built (scripts/native_build.sh)")
    monkeypatch.chdir(tmp_path)
    os.symlink(os.path.join(REPO, "native"), tmp_path / "native")

    params = BenchParameters({
        "faults": 0, "nodes": 4, "rate": 400, "tx_size": 64,
        "duration": 20, "verify_ingress": True, "forge_pct": 1.0,
        "client_shards": 2})
    node_params = NodeParameters.default()
    parser = LocalBench(params, node_params).run()

    ing = parser.ingress
    assert ing["signed"] and ing["verify_on"]
    assert ing["forged_sent"] >= 1, ing
    assert ing["forged_rejected"] >= 1, ing
    assert ing["forged_committed"] == 0
    assert ing["shards"] >= 2, ing      # 4 nodes x 2 shard processes
    assert any(n.startswith("Signed ingress:") for n in parser.notes)
    # The run still commits real throughput under the signed stream.
    assert "TPS:" in parser.result()


def test_bench_trend_flattens_users_leaves():
    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(REPO, "scripts", "bench_trend.py"))
    bt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bt)
    flat = bt.flatten_numeric({"users": {
        "mix_forge_pct": 1.0,
        "u100000": {"verified_goodput_sigs_per_s": 171.5,
                    "forgery_rejection_rate": 1.0,
                    "point_ok": True},
        "u1000000": {"skipped": True},
        "ok": True,
    }})
    assert flat["users.mix_forge_pct"] == 1.0
    assert flat["users.u100000.verified_goodput_sigs_per_s"] == 171.5
    assert flat["users.u100000.forgery_rejection_rate"] == 1.0
    # booleans are flags, not measurements
    assert "users.ok" not in flat
    assert "users.u100000.point_ok" not in flat
    assert "users.u1000000.skipped" not in flat

"""InstanceManager execution coverage via a stub boto3 EC2 model.

This image has neither boto3 nor cloud credentials, so the cloud
lifecycle (the reference's benchmark/benchmark/instance.py:18-263
capability) has historically been complete-as-code but unproven-as-runs.
The stub below implements just enough of the EC2 client surface
(describe/run/start/stop/terminate/describe_images, security-group
calls) to drive every InstanceManager method for real: filter logic,
state partitioning, per-region fan-out, newest-AMI selection, and the
host listing the remote harness consumes.
"""

import sys
import types

import pytest

from hotstuff_tpu.harness.settings import Settings
from hotstuff_tpu.harness.utils import BenchError


def make_settings(regions):
    return Settings("testbed", "key", "/tmp/key.pem", 9000, "repo",
                    "file:///repo", "main", "m5.xlarge", regions)


class StubEC2:
    """Minimal in-memory EC2 regional endpoint."""

    class exceptions:
        class ClientError(Exception):
            pass

    def __init__(self, region):
        self.region = region
        self.instances = []  # dicts: InstanceId, State, PublicIpAddress, Tags
        self.security_groups = {}
        self.calls = []

    # -- queries ---------------------------------------------------------

    def describe_instances(self, Filters):
        assert Filters == [{"Name": "tag:Name",
                            "Values": ["hotstuff-tpu-node"]}]
        insts = [i for i in self.instances
                 if {"Key": "Name", "Value": "hotstuff-tpu-node"}
                 in i["Tags"]]
        return {"Reservations": [{"Instances": insts}]}

    def describe_images(self, Owners, Filters):
        assert Owners == ["099720109477"]
        return {"Images": [
            {"ImageId": "ami-old", "CreationDate": "2023-01-01"},
            {"ImageId": "ami-new", "CreationDate": "2024-06-01"},
            {"ImageId": "ami-mid", "CreationDate": "2023-12-01"},
        ]}

    # -- mutations -------------------------------------------------------

    def create_security_group(self, GroupName, Description):
        if GroupName in self.security_groups:
            raise self.exceptions.ClientError("exists")
        self.security_groups[GroupName] = []
        return {"GroupId": f"sg-{GroupName}"}

    def authorize_security_group_ingress(self, GroupId, IpPermissions):
        self.security_groups[GroupId.removeprefix("sg-")] = IpPermissions

    def run_instances(self, **kw):
        self.calls.append(("run", kw))
        for i in range(kw["MinCount"]):
            n = len(self.instances)
            self.instances.append({
                "InstanceId": f"i-{self.region}-{n}",
                "State": {"Name": "pending"},
                "PublicIpAddress": f"198.51.100.{n + 1}",
                "Tags": kw["TagSpecifications"][0]["Tags"],
            })

    def _set_state(self, ids, state):
        for i in self.instances:
            if i["InstanceId"] in ids:
                i["State"] = {"Name": state}

    def start_instances(self, InstanceIds):
        self.calls.append(("start", InstanceIds))
        self._set_state(InstanceIds, "running")

    def stop_instances(self, InstanceIds):
        self.calls.append(("stop", InstanceIds))
        self._set_state(InstanceIds, "stopped")

    def terminate_instances(self, InstanceIds):
        self.calls.append(("terminate", InstanceIds))
        self._set_state(InstanceIds, "terminated")


@pytest.fixture
def stub_boto3(monkeypatch):
    endpoints = {}

    def client(service, region_name):
        assert service == "ec2"
        return endpoints.setdefault(region_name, StubEC2(region_name))

    mod = types.ModuleType("boto3")
    mod.client = client
    monkeypatch.setitem(sys.modules, "boto3", mod)
    return endpoints


def test_lifecycle_across_regions(stub_boto3):
    from hotstuff_tpu.harness.instance import InstanceManager

    mgr = InstanceManager(make_settings(["eu-north-1", "us-west-1"]))
    mgr.create_instances(2)
    eu = stub_boto3["eu-north-1"]
    us = stub_boto3["us-west-1"]
    assert len(eu.instances) == 2 and len(us.instances) == 2
    # newest AMI picked, security group ports opened (22 + the 3 bench
    # ports derived from base_port)
    assert eu.calls[0][1]["ImageId"] == "ami-new"
    ports = sorted(p["FromPort"]
                   for p in eu.security_groups["hotstuff-tpu"])
    assert ports == [22, 7000, 8000, 9000]

    # pending instances are visible hosts
    assert len(mgr.hosts()) == 4
    assert mgr.hosts(flat=False)["eu-north-1"] == ["198.51.100.1",
                                                   "198.51.100.2"]

    # stop targets pending/running; start brings stopped back
    mgr.stop_instances()
    assert all(i["State"]["Name"] == "stopped" for i in eu.instances)
    assert mgr.hosts() == []
    mgr.start_instances()
    assert all(i["State"]["Name"] == "running" for i in us.instances)
    assert len(mgr.hosts()) == 4

    mgr.terminate_instances()
    assert all(i["State"]["Name"] == "terminated" for i in eu.instances)
    assert mgr.hosts() == []

    # idempotent security group creation on a second create pass
    mgr.create_instances(1)
    assert len(eu.instances) == 3


def test_untagged_instances_invisible(stub_boto3):
    from hotstuff_tpu.harness.instance import InstanceManager

    mgr = InstanceManager(make_settings(["eu-north-1"]))
    ec2 = stub_boto3["eu-north-1"]
    ec2.instances.append({
        "InstanceId": "i-other", "State": {"Name": "running"},
        "PublicIpAddress": "203.0.113.9",
        "Tags": [{"Key": "Name", "Value": "unrelated"}],
    })
    assert mgr.hosts() == []


def test_missing_boto3_is_a_bench_error(monkeypatch):
    import builtins

    real_import = builtins.__import__

    def no_boto3(name, *a, **kw):
        if name == "boto3":
            raise ImportError("No module named 'boto3'")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_boto3)
    monkeypatch.delitem(sys.modules, "boto3", raising=False)
    from hotstuff_tpu.harness.instance import InstanceManager

    with pytest.raises(BenchError):
        InstanceManager(make_settings(["eu-north-1"]))

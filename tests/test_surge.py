"""graftsurge tests: the heavy-tailed load generator (seeded, virtual
clock), the overlap-driven admission controller, the scheduler's
bulk-before-latency + derated-cap policy, the OP_BUSY/retry-after wire
round trip, the metrics-driven recovery-to-baseline SLO judge, surge
fault-plan events, the LogParser's overload notes + strict fairness
assertion, the bounded-ingress lint rule, and the bench ``surge``
headline probe."""

import threading

import pytest

from hotstuff_tpu.chaos import (
    PlanError,
    client_index,
    fault_class,
    judge_baseline_recovery,
    parse_plan,
    throughput_series,
)
from hotstuff_tpu.harness.loadgen import PARETO, UserLoad
from hotstuff_tpu.harness.logs import LogParser, ParseError
from hotstuff_tpu.sidecar import protocol as proto
from hotstuff_tpu.sidecar import sched as vsched
from hotstuff_tpu.sidecar.client import SidecarClient, SidecarOverloaded
from hotstuff_tpu.sidecar.sched.surge import (
    DERATE_FLOOR,
    MIN_PACKS,
    RETRY_DEFAULT_MS,
    RETRY_MAX_MS,
    AdmissionController,
)
from test_harness import GOLDEN_CLIENT, GOLDEN_NODE


def _request(rid, n):
    recs = [rid.to_bytes(6, "big") + i.to_bytes(2, "big")
            for i in range(n)]
    return proto.VerifyRequest(rid, recs, recs, recs)


# ---------------------------------------------------------------------------
# load generator (python twin of the C++ UserLoadModel)
# ---------------------------------------------------------------------------


def _drive(load, from_s, to_s, tick_s=0.05):
    total = 0
    t = from_s + tick_s
    while t <= to_s + 1e-9:
        total += load.arrivals(t)
        t += tick_s
    return total


def test_loadgen_deterministic_and_aggregate_rate():
    a = UserLoad(rate=2000, users=300, seed=5)
    b = UserLoad(rate=2000, users=300, seed=5)
    for k in range(1, 101):
        assert a.arrivals(k * 0.05) == b.arrivals(k * 0.05)
    total = _drive(a, 5.0, 30.0) + a.sent - a.sent  # continue a's clock
    # 30 virtual seconds at 2000 tx/s: within +-10% despite heavy tails.
    assert 0.9 * 60_000 < a.sent < 1.1 * 60_000
    c = UserLoad(rate=2000, users=300, seed=6)
    _drive(c, 0.0, 30.0)
    assert c.sent != a.sent  # a different world, not a constant


def test_loadgen_gaps_are_heavy_tailed_and_pareto_mean_one():
    lg = UserLoad(rate=100, users=1, seed=7, sigma=1.5)
    gaps = [lg.sample_gap(0.0) for _ in range(20_000)]
    mean = sum(gaps) / len(gaps)
    var = sum(g * g for g in gaps) / len(gaps) - mean * mean
    assert 0.0085 < mean < 0.0115          # user mean gap 10 ms
    assert var ** 0.5 / mean > 1.2         # heavy tail (true CV ~2.9)
    pa = UserLoad(rate=100, users=1, seed=7, dist=PARETO, alpha=2.5)
    gaps = [pa.sample_gap(0.0) for _ in range(20_000)]
    assert 0.0085 < sum(gaps) / len(gaps) < 0.0115


def test_loadgen_busy_defers_per_user_then_recovers():
    lg = UserLoad(rate=1000, users=20, seed=3)
    assert _drive(lg, 0.0, 1.0, 0.01) > 0
    lg.busy(1.0, 0.5)
    assert _drive(lg, 1.0, 1.5, 0.01) == 0  # everything defers
    assert lg.deferred > 0 and lg.busy_events == 1
    assert _drive(lg, 1.5, 6.0, 0.01) > 0   # open loop: load comes back


def test_loadgen_diurnal_profile_means_one():
    lg = UserLoad(rate=2000, users=100, seed=9, diurnal_amp=0.5,
                  diurnal_period_s=100.0)
    acc = sum(lg.profile(100.0 * i / 1000) for i in range(1000)) / 1000
    assert abs(acc - 1.0) < 0.01
    assert lg.profile(25.0) > 1.4 and lg.profile(75.0) < 0.6
    _drive(lg, 0.0, 200.0)
    assert 0.9 * 400_000 < lg.sent < 1.1 * 400_000


def test_loadgen_rejects_bad_config():
    with pytest.raises(ValueError):
        UserLoad(rate=100, users=1, dist="uniform")
    with pytest.raises(ValueError):
        UserLoad(rate=0, users=1)


# ---------------------------------------------------------------------------
# admission controller
# ---------------------------------------------------------------------------


def test_admission_derate_tracks_overlap_with_hysteresis_counts():
    now = [0.0]
    adm = AdmissionController(clock=lambda: now[0])
    # Not enough evidence: full cap regardless of the few packs seen.
    for _ in range(MIN_PACKS - 1):
        adm.note_pack(0.01, hidden=False)
    assert adm.bulk_derate() == 1.0
    # Overlap collapsed: derate engages once, down to the floor.
    for _ in range(64):
        adm.note_pack(0.01, hidden=False)
    assert adm.bulk_derate() == pytest.approx(DERATE_FLOOR)
    assert adm.snapshot()["derate"]["engagements"] == 1
    # Pipeline healthy again: back to full cap, engagement count fixed.
    for _ in range(64):
        adm.note_pack(0.01, hidden=True)
    assert adm.bulk_derate() == 1.0
    snap = adm.snapshot()
    assert snap["derate"]["engagements"] == 1
    assert not snap["derate"]["engaged"]
    # A second collapse is a second engagement (watermark-style count).
    for _ in range(64):
        adm.note_pack(0.01, hidden=False)
    assert adm.snapshot()["derate"]["engagements"] == 2
    # Partial overlap lands between the floor and 1.
    for _ in range(32):
        adm.note_pack(0.01, hidden=True)
    assert DERATE_FLOOR < adm.bulk_derate() < 1.0


def test_admission_overlap_window_is_time_bounded():
    """The satellite regression: the derate judges RECENT packs — a
    lifetime average would let hours of healthy history outvote the
    collapse in front of it.  Healthy evidence older than PACK_WINDOW_S
    ages out; with no fresh evidence at all the controller answers the
    full cap, never a verdict off stale telemetry."""
    from hotstuff_tpu.sidecar.sched.surge import PACK_WINDOW_S

    now = [0.0]
    adm = AdmissionController(clock=lambda: now[0])
    for _ in range(64):
        adm.note_pack(0.01, hidden=True)
    assert adm.bulk_derate() == 1.0
    # The surge arrives after a quiet stretch: only fresh packs decide.
    now[0] += PACK_WINDOW_S + 1.0
    for _ in range(MIN_PACKS):
        adm.note_pack(0.01, hidden=False)
    assert adm.recent_overlap() == 0.0
    assert adm.bulk_derate() == pytest.approx(DERATE_FLOOR)
    # ... and once THAT evidence ages out, no evidence -> full cap.
    now[0] += PACK_WINDOW_S + 1.0
    assert adm.recent_overlap() is None
    assert adm.bulk_derate() == 1.0


def test_admission_ring_occupancy_rules_while_fresh_then_goes_stale():
    """graftcadence: while ring occupancy samples are fresh they REPLACE
    the overlap rule (the resident pipeline hides pack time by
    construction); a full ring derates toward the floor, headroom keeps
    the full cap, and stale occupancy (ring disengaged) falls back to
    the overlap rule."""
    from hotstuff_tpu.sidecar.sched.surge import (RING_OCC_KNEE,
                                                  RING_OCC_WINDOW_S)

    now = [100.0]
    adm = AdmissionController(clock=lambda: now[0])
    # Occupancy at the knee or below: headroom, full cap.
    for _ in range(16):
        adm.note_ring_occupancy(2, 4)
    assert adm.bulk_derate() == 1.0
    # Every tick full: the device cannot drain what is admitted.
    for _ in range(64):
        adm.note_ring_occupancy(4, 4)
    derated = adm.bulk_derate()
    assert DERATE_FLOOR <= derated < 1.0
    snap = adm.snapshot()["derate"]
    assert snap["engaged"] and snap["engagements"] >= 1
    assert snap["ring_occupancy_recent"] > RING_OCC_KNEE
    # Fresh ring evidence WINS over a perfectly healthy overlap.
    for _ in range(MIN_PACKS):
        adm.note_pack(0.01, hidden=True)
    assert adm.bulk_derate() == pytest.approx(derated)
    # Ring disengaged (wedge fallback/stop): occupancy goes stale within
    # RING_OCC_WINDOW_S and the healthy overlap rule takes back over.
    now[0] += RING_OCC_WINDOW_S + 1.0
    for _ in range(MIN_PACKS):
        adm.note_pack(0.01, hidden=True)
    snap = adm.snapshot()["derate"]
    assert snap["ring_occupancy_recent"] is None
    assert adm.bulk_derate() == 1.0


def test_admission_retry_after_drain_rate_and_clamps():
    now = [100.0]
    adm = AdmissionController(clock=lambda: now[0])
    # No drain evidence: per-class defaults.
    assert adm.retry_after_ms(vsched.LATENCY, 500) == \
        RETRY_DEFAULT_MS[vsched.LATENCY]
    assert adm.retry_after_ms(vsched.BULK, 500) == \
        RETRY_DEFAULT_MS[vsched.BULK]
    # 1000 sigs/s drain, 500 queued -> ~500 ms.
    adm.note_launch(1000, now=100.0)
    adm.note_launch(1000, now=101.0)
    now[0] = 102.0
    assert 400 <= adm.retry_after_ms(vsched.BULK, 500) <= 600
    # Huge backlog clamps at the max.
    assert adm.retry_after_ms(vsched.BULK, 10_000_000) == RETRY_MAX_MS


def test_admission_fairness_counter_and_pressure_window():
    now = [10.0]
    adm = AdmissionController(clock=lambda: now[0])
    adm.note_latency_shed()
    assert adm.latency_pressure()
    # Bulk admitted inside the pressure window: the violation the
    # scheduler's lock makes unreachable, counted here as proof.
    adm.note_admitted(vsched.BULK)
    assert adm.snapshot()["fairness_violations"] == 1
    now[0] = 12.0  # pressure expired
    assert not adm.latency_pressure()
    adm.note_admitted(vsched.BULK)
    assert adm.snapshot()["fairness_violations"] == 1


# ---------------------------------------------------------------------------
# scheduler policy: bulk-before-latency + derated bulk cap
# ---------------------------------------------------------------------------


def test_scheduler_sheds_bulk_before_latency():
    sched = vsched.Scheduler(latency_cap_sigs=32, bulk_cap_sigs=1024)
    assert sched.offer(_request(1, 32), lambda m: None,
                       cls=vsched.LATENCY)
    # Latency full -> latency shed -> pressure window opens.
    assert not sched.offer(_request(2, 32), lambda m: None,
                           cls=vsched.LATENCY)
    # Bulk has a near-empty queue but is shed FIRST while latency is
    # under pressure.
    assert not sched.offer(_request(3, 8), lambda m: None,
                           cls=vsched.BULK)
    snap = sched.stats.snapshot()["surge"]
    assert snap["shed"]["latency"] == 1
    assert snap["shed"]["bulk"] == 1
    assert snap["bulk_before_latency_sheds"] == 1
    assert snap["fairness_violations"] == 0


def test_scheduler_bulk_admits_against_derated_cap():
    sched = vsched.Scheduler(latency_cap_sigs=1024, bulk_cap_sigs=1000)
    # Collapse the overlap: effective bulk cap becomes 250.
    for _ in range(64):
        sched.admission.note_pack(0.01, hidden=False)
    assert sched.offer(_request(1, 100), lambda m: None, cls=vsched.BULK)
    assert sched.offer(_request(2, 100), lambda m: None, cls=vsched.BULK)
    # 200 queued + 100 > 250: shed — the PLAIN cap (1000) would admit.
    assert not sched.offer(_request(3, 100), lambda m: None,
                           cls=vsched.BULK)
    snap = sched.stats.snapshot()["surge"]
    assert snap["derate"]["engaged"]
    assert snap["shed"]["bulk"] == 1
    # Healthy overlap restores the full cap.
    for _ in range(64):
        sched.admission.note_pack(0.01, hidden=True)
    assert sched.offer(_request(4, 100), lambda m: None, cls=vsched.BULK)


def test_scheduler_retry_after_reflects_queue_depth():
    sched = vsched.Scheduler(latency_cap_sigs=1024, bulk_cap_sigs=1024)
    base = sched.retry_after_ms(vsched.BULK)
    assert base == RETRY_DEFAULT_MS[vsched.BULK]
    assert sched.retry_after_ms(vsched.LATENCY) == \
        RETRY_DEFAULT_MS[vsched.LATENCY]


# ---------------------------------------------------------------------------
# OP_BUSY wire round trip
# ---------------------------------------------------------------------------


def test_busy_reply_roundtrip_and_typed_client_error():
    # v4 introduced OP_BUSY; the protocol has since moved to v6
    # (graftfleet HELLO/tenant) without touching the BUSY layout.
    assert proto.PROTOCOL_VERSION == 6 and proto.OP_BUSY == 10
    frame = proto.encode_busy_reply(9, 137)
    opcode, rid, body = proto.decode_reply_raw(frame[4:])
    assert opcode == proto.OP_BUSY and rid == 9
    assert proto.decode_busy_body(body) == 137
    with pytest.raises(SidecarOverloaded) as exc:
        SidecarClient._unwrap(opcode, body)
    assert exc.value.retry_after_ms == 137
    # Hint clamps to the u16 range; garbage bodies raise.
    big = proto.encode_busy_reply(1, 10_000_000)
    assert proto.decode_busy_body(
        proto.decode_reply_raw(big[4:])[2]) == 0xFFFF
    with pytest.raises(ValueError):
        proto.decode_busy_body(b"\x01\x02\x03")
    # The legacy empty-body shed still reads as overload (no hint).
    legacy = proto.encode_reply(proto.OP_VERIFY_BATCH, 2, [])
    op2, _rid2, body2 = proto.decode_reply_raw(legacy[4:])
    assert SidecarClient._unwrap(op2, body2) == b""  # caller's len check


def test_server_shed_carries_retry_after_hint():
    """End to end through a real served socket: a chaos-forced shed
    answers OP_BUSY and the python client surfaces the typed overload
    with the hint attached."""
    from hotstuff_tpu.sidecar.service import ChaosState, SidecarServer, \
        VerifyEngine

    engine = VerifyEngine(use_host=True)
    srv = SidecarServer(("127.0.0.1", 0), engine, chaos=ChaosState())
    t = threading.Thread(target=srv.serve_forever,
                         kwargs={"poll_interval": 0.05}, daemon=True)
    t.start()
    try:
        port = srv.server_address[1]
        with SidecarClient(port=port, timeout=10.0) as client:
            assert client.chaos(shed=1)
            msgs = [b"\x00" * 32]
            with pytest.raises(SidecarOverloaded) as exc:
                client.verify_batch(msgs, [b"\x01" * 32], [b"\x02" * 64])
            assert isinstance(exc.value.retry_after_ms, int)
            assert exc.value.retry_after_ms >= 0
    finally:
        srv.shutdown()
        srv.server_close()
        engine.stop()


# ---------------------------------------------------------------------------
# metrics-driven recovery-to-baseline judge
# ---------------------------------------------------------------------------


def _series(rates, t0=1000.0):
    """ok samples at 1 Hz whose sigs_launched deltas equal ``rates``
    (None = a failed tick)."""
    out = []
    launched = 0
    for i, r in enumerate(rates):
        t = t0 + i
        if r is None:
            out.append({"t": t, "ok": False, "error": "down"})
            continue
        launched += r
        out.append({"t": t, "ok": True,
                    "stats": {"sigs_launched": launched}})
    return out


def test_throughput_series_clamps_counter_resets():
    samples = _series([1000, 1000, 1000])
    # A restart resets the cumulative counter: negative delta -> 0.
    samples.append({"t": 1003.0, "ok": True,
                    "stats": {"sigs_launched": 50}})
    series = throughput_series(samples)
    assert series[-1][1] == 0.0
    assert all(r >= 0 for _, r in series)


def test_judge_baseline_recovery_pass_fail_unjudged():
    event = {"t": 10.0, "target": "sidecar", "action": "kill",
             "wall": 1010.0, "ok": True}
    # PASS: blackout then full recovery.
    rates = [1000] * 10 + [None] * 3 + [1000] * 10
    out = judge_baseline_recovery(_series(rates), [event])
    assert out["ok"] and out["judged"] == 1
    v = out["verdicts"][0]
    assert v["judged"] and v["baseline_sigs_per_s"] == 1000.0
    assert v["recovered_ms"] is not None
    # FAIL: throughput never returns to 70% of baseline, with the
    # series covering the whole 30 s node-kill recovery budget.
    rates = [1000] * 10 + [100] * 45
    out = judge_baseline_recovery(_series(rates), [event])
    assert not out["ok"]
    assert "never returned" in out["verdicts"][0]["reason"]
    # Unjudged: too little pre-event telemetry (not a failure).
    out = judge_baseline_recovery(_series([1000, 1000]),
                                  [dict(event, wall=1001.5)])
    assert out["ok"] and out["judged"] == 0
    assert not out["verdicts"][0]["judged"]
    # Unjudged: the sampled series ends BEFORE the recovery budget
    # elapsed — the event had no fair chance to recover, so absence of
    # evidence is surfaced, never failed.
    rates = [1000] * 10 + [100] * 5
    out = judge_baseline_recovery(_series(rates), [event])
    assert out["ok"] and out["judged"] == 0
    assert "before the recovery budget" in out["verdicts"][0]["reason"]


def test_judge_baseline_surge_measures_from_window_end():
    # Surge [1010, 1015): depressed during the window, instant recovery
    # after.  Judged from the END, recovery is ~1 s; judged from the
    # injection it would read ~6 s.
    event = {"t": 10.0, "target": "client:0", "action": "surge",
             "wall": 1010.0, "ok": True, "params": {"x": 5, "for": 5}}
    rates = [1000] * 10 + [200] * 5 + [1000] * 10
    out = judge_baseline_recovery(_series(rates), [event])
    assert out["ok"]
    assert out["verdicts"][0]["class"] == "client-surge"
    assert out["verdicts"][0]["recovered_ms"] <= 2000.0


# ---------------------------------------------------------------------------
# surge fault-plan events
# ---------------------------------------------------------------------------


def test_plan_surge_dsl_validation_and_window():
    plan = parse_plan("10 client:0 surge x5 for 20")
    e = plan.events[0]
    assert e.params == {"x": 5.0, "for": 20.0}
    assert client_index(e.target) == 0
    assert fault_class(e.to_json()) == "client-surge"
    assert plan.max_time() == 30.0  # the surge END bounds the window
    # k=v spelling parses to the same plan.
    again = parse_plan("10 client:0 surge x=5 for=20")
    assert again.events[0].params == {"x": 5, "for": 20}
    with pytest.raises(PlanError):
        parse_plan("10 client:0 surge x0.5 for 20")  # x must be > 1
    with pytest.raises(PlanError):
        parse_plan("10 client:0 surge x2 for 0")     # window must be > 0
    with pytest.raises(PlanError):
        parse_plan("10 client:0 kill")               # clients only surge
    with pytest.raises(PlanError):                   # overlapping surges
        parse_plan("10 client:0 surge x2 for 20; "
                   "15 client:0 surge x2 for 1")
    # Back to back (and on another client) is fine.
    parse_plan("10 client:0 surge x2 for 5; 16 client:0 surge x2 for 1; "
               "12 client:1 surge x3 for 2")


def test_plan_surge_omitted_for_means_the_same_default_everywhere():
    """An omitted ``for`` must mean ONE thing across validation, window
    math, the SLO judge, and the injector: plan.SURGE_DEFAULT_FOR_S."""
    from hotstuff_tpu.chaos.plan import SURGE_DEFAULT_FOR_S, \
        surge_window_s
    from hotstuff_tpu.chaos.slo import event_window_end

    plan = parse_plan("10 client:0 surge x3")
    assert plan.max_time() == 10.0 + SURGE_DEFAULT_FOR_S
    assert surge_window_s(plan.events[0].params) == SURGE_DEFAULT_FOR_S
    assert event_window_end(
        {"action": "surge", "wall": 100.0, "params": {"x": 3}}) == \
        100.0 + SURGE_DEFAULT_FOR_S
    # Overlap validation uses the same default: a second surge inside
    # the implied window is rejected.
    with pytest.raises(PlanError):
        parse_plan("10 client:0 surge x3; 15 client:0 surge x2 for 1")


# ---------------------------------------------------------------------------
# LogParser: overload notes + strict fairness / baseline assertions
# ---------------------------------------------------------------------------

# Golden commits land at 14:54:57.000Z and .200Z (test_chaos.py).
from datetime import datetime, timezone  # noqa: E402

_COMMIT0 = datetime(2026, 7, 29, 14, 54, 57, 0,
                    tzinfo=timezone.utc).timestamp()


def _surge_event(wall, dur=0.1):
    return {"t": 5.0, "target": "client:0", "action": "surge",
            "wall": wall, "ok": True, "params": {"x": 4, "for": dur}}


def test_parser_surge_goodput_and_backpressure_notes():
    client = GOLDEN_CLIENT + (
        "[2026-07-29T14:54:58.000Z INFO client] Node busy (retry-after "
        "200 ms); backing off (1 total)\n")
    node = GOLDEN_NODE + (
        "[2026-07-29T14:54:58.100Z WARN mempool::ingress] Ingress "
        "paused: 20000 txs / 1048576 B queued after 256 consecutive "
        "busy sheds (crossing 1); resuming at 10000 txs\n"
        "[2026-07-29T14:54:58.200Z INFO mempool::ingress] Ingress "
        "resumed at 9800 queued txs (low-water mark)\n")
    parser = LogParser([client], [node], faults=0,
                       chaos_events=[_surge_event(_COMMIT0 + 0.05)],
                       strict_chaos=True)
    assert any("Ingress backpressure: 1 receiver pause(s) / 1 "
               "resume(s)" in n for n in parser.notes)
    assert any("busy backoff line(s)" in n for n in parser.notes)
    assert any("goodput retained" in n for n in parser.notes)
    surge = [e for e in parser.chaos["events"]
             if e["action"] == "surge"][0]
    assert "goodput" in surge and surge["goodput"]["before_tps"] > 0


def test_parser_strict_fairness_violation_raises():
    stats = {"launches": 3, "launches_by_class": {"latency": 3},
             "surge": {"admitted": {"latency": 3, "bulk": 1},
                       "shed": {"latency": 2, "bulk": 0},
                       "busy_replies": {}, "derate": {},
                       "bulk_before_latency_sheds": 0,
                       "fairness_violations": 1}}
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                       chaos_events=[_surge_event(_COMMIT0 + 0.05)],
                       strict_chaos=True)
    with pytest.raises(ParseError) as exc:
        parser.note_sidecar_stats(stats)
    assert "fairness" in str(exc.value)
    # Non-strict: surfaced as a note, not a failure.
    lax = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    lax.note_sidecar_stats(stats)
    assert any("VIOLATION" in n for n in lax.notes)
    # A clean surge section reads as fairness held.
    clean = dict(stats, surge=dict(stats["surge"],
                                   fairness_violations=0))
    ok = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    ok.note_sidecar_stats(clean)
    assert any("bulk-before-latency held" in n for n in ok.notes)


def test_parser_metrics_baseline_verdict_strict_and_notes():
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                       chaos_events=[_surge_event(_COMMIT0 + 0.05,
                                                  dur=3.0)],
                       strict_chaos=True)
    wall = _COMMIT0 + 0.05
    # PASS: baseline, surge-window dip, recovery.
    good = _series([1000] * 12 + [200] * 3 + [1000] * 8, t0=wall - 12)
    parser.note_metrics(good)
    assert parser.chaos["slo_metrics"]["ok"]
    assert any("back to baseline" in n for n in parser.notes)
    # FAIL under strict: the curve never comes back.
    parser2 = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                        chaos_events=[_surge_event(_COMMIT0 + 0.05,
                                                   dur=3.0)],
                        strict_chaos=True)
    # The series must cover the client-surge SLO budget past the
    # window end, or the judge (rightly) calls it unjudged.
    bad = _series([1000] * 12 + [100] * 45, t0=wall - 12)
    with pytest.raises(ParseError) as exc:
        parser2.note_metrics(bad)
    assert "recovery SLO breached" in str(exc.value) or \
        "metrics-driven" in str(exc.value)


# ---------------------------------------------------------------------------
# bounded-ingress lint rule
# ---------------------------------------------------------------------------


def _run_ingress(tmp_path, source, name="mod.py"):
    from hotstuff_tpu.analysis import ingress

    (tmp_path / name).write_text(source)
    return ingress.check(str(tmp_path), targets=(name,))


def test_ingress_rule_flags_bypass_enqueues(tmp_path):
    findings = _run_ingress(tmp_path, (
        "class Helper:\n"
        "    def stash(self, p):\n"
        "        self.items.append(p)\n"))
    assert len(findings) == 1
    assert findings[0].rule == "bounded-ingress"
    assert "Helper.stash" in findings[0].message


def test_ingress_rule_allows_admission_scopes(tmp_path):
    assert _run_ingress(tmp_path, (
        "class Q:\n"
        "    def offer(self, p):\n"
        "        self.items.append(p)\n"
        "    def _offer_locked(self, p):\n"
        "        self.items.append(p)\n"
        "class AdmissionController:\n"
        "    def requeue(self, p):\n"
        "        self.backlog.append(p)\n")) == []


def test_ingress_rule_subscripted_queues_and_locals(tmp_path):
    findings = _run_ingress(tmp_path, (
        "class S:\n"
        "    def push(self, cls, p):\n"
        "        self._queues[cls].put(p)\n"))
    assert len(findings) == 1
    # Bare locals named like queues are function-private, not shared.
    assert _run_ingress(tmp_path, (
        "def collect(xs):\n"
        "    items = []\n"
        "    for x in xs:\n"
        "        items.append(x)\n"
        "    return items\n")) == []


def test_ingress_rule_honors_suppressions(tmp_path):
    assert _run_ingress(tmp_path, (
        "class Helper:\n"
        "    def stash(self, p):\n"
        "        # justified: test fixture, never a live queue\n"
        "        # graftlint: disable=bounded-ingress\n"
        "        self.items.append(p)\n")) == []


def test_real_tree_is_ingress_clean():
    import os

    from hotstuff_tpu.analysis import ingress

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert ingress.check(root) == []


# ---------------------------------------------------------------------------
# bench surge headline probe
# ---------------------------------------------------------------------------


def test_bench_surge_headline_probe_meets_acceptance_bar():
    import bench

    out = bench.surge_headline_probe(seconds=1.5)
    assert out["ok"]
    assert out["offered_x"] >= 3.0
    assert out["latency"]["shed"] == 0
    assert out["latency"]["wait_p99_ms"] <= 30.0
    assert out["bulk"]["shed"] > 0
    assert out["bulk"]["deferred_by_busy"] > 0  # BUSY loop closed
    assert out["fairness_violations"] == 0
    assert out["busy_roundtrip"]["ok"]
    assert out["baseline_slo"]["ok"]

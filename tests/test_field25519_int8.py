"""Exactness tests for the int8 radix-2^5 field engine (the PROFILE.md
lever-#1 A/B candidate; scripts/ab_int8_mul.py measures its speed)."""

import numpy as np
import jax.numpy as jnp

from hotstuff_tpu.ops import field25519_int8 as F


def test_limb_roundtrip():
    rng = np.random.default_rng(0)
    for _ in range(20):
        x = int.from_bytes(rng.bytes(32), "little") % (2**255)
        assert F.from_limbs(F.to_limbs(x)) == x


def test_mul_selfcheck_passes():
    F.mul_selfcheck()


def test_mul_random_and_adversarial():
    rng = np.random.default_rng(3)
    xs = [int.from_bytes(rng.bytes(32), "little") % F.P for _ in range(64)]
    ys = [int.from_bytes(rng.bytes(32), "little") % F.P for _ in range(64)]
    xs[0], ys[0] = F.P - 1, F.P - 1
    xs[1], ys[1] = 0, 12345
    a = jnp.asarray(F.batch_to_limbs(xs))
    b = jnp.asarray(F.batch_to_limbs(ys))
    got = F.batch_from_limbs(np.asarray(F.canonical(F.mul(a, b))))
    assert got == [(x * y) % F.P for x, y in zip(xs, ys)]


def test_mul_chain_stays_weak_and_exact():
    """Deep mul chains: outputs must keep satisfying the weak invariant
    (limbs <= 63, losslessly int8-castable) at every step."""
    rng = np.random.default_rng(4)
    xs = [int.from_bytes(rng.bytes(32), "little") % F.P for _ in range(16)]
    acc_dev = jnp.asarray(F.batch_to_limbs(xs))
    acc_host = list(xs)
    for _ in range(12):
        acc_dev = F.mul(acc_dev, acc_dev)
        arr = np.asarray(acc_dev)
        assert arr.max() <= 63 and arr.min() >= 0, "weak invariant broken"
        acc_host = [(v * v) % F.P for v in acc_host]
    got = F.batch_from_limbs(np.asarray(F.canonical(acc_dev)))
    assert got == acc_host


def test_canonical_reduces_mod_p():
    # The representation spans exactly 255 bits (5 * 51), so candidates
    # must be < 2^255 (unlike the r8 engine's 256-bit space).
    vals = [0, 1, F.P - 1, F.P, F.P + 1, 2**255 - 1]
    a = jnp.asarray(F.batch_to_limbs(vals))
    got = F.batch_from_limbs(np.asarray(F.canonical(a)))
    assert got == [v % F.P for v in vals]

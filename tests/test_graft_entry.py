"""Driver-contract tests: entry() compile-checks, dryrun_multichip executes."""

import jax
import numpy as np
import pytest


def test_entry_jits():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    mask = np.asarray(jax.jit(fn)(*args))
    # every 5th example signature is corrupted by _example_prep
    assert mask.shape == (8,)
    assert list(mask) == [True, True, True, True, False, True, True, True]


@pytest.mark.slow  # ~84 s; the driver runs dryrun_multichip itself every round
def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


@pytest.mark.slow  # ~87 s
def test_dryrun_multichip_subprocess_reexec():
    """Cover the branch the driver actually hits: this process has only 8
    virtual devices, so asking for 16 must re-exec a fresh child with
    --xla_force_host_platform_device_count=16 and propagate its success."""
    import __graft_entry__ as ge

    ge.dryrun_multichip(16)

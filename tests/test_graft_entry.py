"""Driver-contract tests: entry() compile-checks, dryrun_multichip executes."""

import jax
import numpy as np
import pytest


def test_entry_jits():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    mask = np.asarray(jax.jit(fn)(*args))
    # every 5th example signature is corrupted by _example_prep
    assert mask.shape == (8,)
    assert list(mask) == [True, True, True, True, False, True, True, True]


def test_dryrun_lane_diagnostics_classify_disagreements():
    """The dryrun's per-lane check must say WHICH lanes disagreed and
    why — false-reject vs escaped-invalid — and pass silently when the
    mask matches the injected fault pattern exactly."""
    import __graft_entry__ as ge

    expect = np.array([True, True, False, True])
    ge._check_lanes("test", expect.copy(), expect)  # exact match: quiet
    with pytest.raises(AssertionError) as exc:
        ge._check_lanes("test", np.array([True, False, False, True]),
                        expect)
    assert "lane 1: false-reject" in str(exc.value)
    with pytest.raises(AssertionError) as exc:
        ge._check_lanes("test", np.array([True, True, True, True]), expect)
    assert "lane 2: escaped-invalid" in str(exc.value)


@pytest.mark.slow  # ~84 s; the driver runs dryrun_multichip itself every round
def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


@pytest.mark.slow  # ~87 s
def test_dryrun_multichip_subprocess_reexec():
    """Cover the branch the driver actually hits: this process has only 8
    virtual devices, so asking for 16 must re-exec a fresh child with
    --xla_force_host_platform_device_count=16 and propagate its success."""
    import __graft_entry__ as ge

    ge.dryrun_multichip(16)

"""RLC batch-verification soundness tests.

Contract under test (crypto/eddsa.verify_batch_rlc): the mask it returns
is bit-identical to the per-signature verify_batch on EVERY input —
all-valid batches ride the one-MSM fast path, any failure bisects down
to the per-signature floor, so a bad vote is always pinpointed.  Parity
model: the reference's verify_valid_batch / verify_invalid_batch
(crypto/src/tests/crypto_tests.rs) plus the batch-forgery cases a
combined check uniquely has to survive.
"""

import numpy as np
import pytest

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref

RNG = np.random.default_rng(42)


def sig_pool(n, seed=7, msg_len=32):
    """n distinct (msg, pk, sig) triples from the reference signer."""
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        sk = r.bytes(32)
        msg = r.bytes(msg_len)
        _, pk = ref.generate_keypair(sk)
        out.append((msg, pk, ref.sign(sk, msg)))
    return out


POOL = sig_pool(16)


def corrupt_sig(sig: bytes, where: int = 40) -> bytes:
    return sig[:where] + bytes([sig[where] ^ 1]) + sig[where + 1:]


def test_all_valid_batch_passes_fast_path():
    msgs, pks, sigs = map(list, zip(*POOL[:6]))
    mask = eddsa.verify_batch_rlc(msgs, pks, sigs)
    assert mask.all() and len(mask) == 6


def test_each_single_corrupted_index_is_pinpointed():
    """For every index of a 6-vote batch: corrupt exactly that vote; the
    combined check must fail and bisection must blame exactly it."""
    for bad in range(6):
        msgs, pks, sigs = map(list, zip(*POOL[:6]))
        sigs[bad] = corrupt_sig(sigs[bad])
        mask = eddsa.verify_batch_rlc(msgs, pks, sigs)
        want = [i != bad for i in range(6)]
        assert mask.tolist() == want, f"index {bad}: {mask.tolist()}"


def test_rlc_agrees_with_per_signature_on_200_random_batches():
    """Randomized agreement sweep: batch sizes 1..8 sampled from the
    pool, ~1/4 of batches with one corrupted signature, plus occasional
    garbage keys / non-canonical encodings — the mask must match
    verify_batch exactly on every one."""
    r = np.random.default_rng(1234)
    for trial in range(200):
        n = int(r.integers(1, 9))
        take = r.integers(0, len(POOL), n)
        msgs = [POOL[i][0] for i in take]
        pks = [POOL[i][1] for i in take]
        sigs = [POOL[i][2] for i in take]
        if n and trial % 4 == 0:
            k = int(r.integers(0, n))
            sigs[k] = corrupt_sig(sigs[k], int(r.integers(0, 64)))
        if n and trial % 17 == 0:
            pks[int(r.integers(0, n))] = bytes(r.bytes(32))
        if n and trial % 23 == 0:
            sigs[int(r.integers(0, n))] = b"\xff" * 64  # S >= L
        got = eddsa.verify_batch_rlc(msgs, pks, sigs)
        want = eddsa.verify_batch(msgs, pks, sigs)
        assert got.tolist() == want.tolist(), \
            f"trial {trial}: rlc {got.tolist()} != per-sig {want.tolist()}"


def test_wrong_message_and_swapped_sigs_fail():
    msgs, pks, sigs = map(list, zip(*POOL[:4]))
    msgs[2] = b"not the signed message............"
    got = eddsa.verify_batch_rlc(msgs, pks, sigs)
    assert got.tolist() == [True, True, False, True]
    msgs, pks, sigs = map(list, zip(*POOL[:4]))
    sigs[0], sigs[1] = sigs[1], sigs[0]
    got = eddsa.verify_batch_rlc(msgs, pks, sigs)
    assert got.tolist() == [False, False, True, True]


def test_empty_and_tiny_batches():
    assert eddsa.verify_batch_rlc([], [], []).shape == (0,)
    m, p, s = POOL[0]
    assert eddsa.verify_batch_rlc([m], [p], [s]).tolist() == [True]
    assert eddsa.verify_batch_rlc(
        [m], [p], [corrupt_sig(s)]).tolist() == [False]


def test_coefficients_are_deterministic_nonzero_128bit():
    rows = np.frombuffer(RNG.bytes(8 * 128), np.uint8).reshape(8, 128)
    z1 = eddsa._rlc_coeffs(rows, b"")
    z2 = eddsa._rlc_coeffs(rows, b"")
    assert (z1 == z2).all()                       # deterministic per call
    assert z1.shape == (8, 32)
    assert (z1[:, 16:] == 0).all()                # < 2^128 < L
    assert z1[:, :16].any(axis=1).all()           # never excluded
    # content-keyed: flipping one bit of one row changes coefficients
    rows2 = rows.copy()
    rows2[3, 60] ^= 1
    assert (eddsa._rlc_coeffs(rows2, b"") != z1).any()
    # path-keyed: bisection halves draw fresh coefficients
    assert (eddsa._rlc_coeffs(rows, b"L") != z1).any()


def test_msm_matches_reference_scalar_mults():
    """msm_straus against the python-int reference on random points and
    scalars (the raw device primitive, no RLC wrapping)."""
    import jax.numpy as jnp

    from hotstuff_tpu.ops import ed25519 as E, field25519 as F
    from hotstuff_tpu.utils.intmath import L, P

    r = np.random.default_rng(5)
    n = 5  # deliberately not a power of two: exercises identity padding
    pts, scalars = [], []
    arr = np.zeros((n, 4, 32), np.int32)
    for i in range(n):
        k = int.from_bytes(r.bytes(32), "little") % L or 1
        s = int.from_bytes(r.bytes(32), "little") % L
        pt = ref.scalar_mult(k, ref.B)
        zi = pow(pt[2], P - 2, P)
        x, y = pt[0] * zi % P, pt[1] * zi % P
        arr[i, 0] = F.to_limbs(x)
        arr[i, 1] = F.to_limbs(y)
        arr[i, 2] = F.to_limbs(1)
        arr[i, 3] = F.to_limbs(x * y % P)
        pts.append((x, y, 1, x * y % P))
        scalars.append(s)
    digits = E.unpack_nibbles_msb(jnp.asarray(np.stack([
        np.frombuffer(s.to_bytes(32, "little"), np.uint8) for s in
        scalars]).astype(np.int32)))
    out = E.msm_straus(jnp.asarray(arr), digits)
    got = tuple(F.from_limbs(np.asarray(F.canonical(out[c])))
                for c in range(3))
    want = ref.IDENT
    for s, pt in zip(scalars, pts):
        want = ref.pt_add(want, ref.scalar_mult(s, pt))
    assert ref.pt_equal((got[0], got[1], got[2], 0),
                        (want[0], want[1], want[2], 0))


def test_mixed_order_pubkey_agrees_with_per_signature():
    """Torsion-exactness regression: a pubkey A' + T (T of order 8, so A
    passes the host small-order screen) signed honestly with A''s secret
    is accepted by the cofactorless per-signature check iff
    k = H(R||A||M) ≡ 0 (mod 8).  The RLC path must agree on EVERY
    message — before the CRT lift to exponent 8L, reducing z*k mod L
    scrambled the torsion coefficient and a grinding adversary could
    split the two paths in a handful of attempts."""
    import hashlib

    from hotstuff_tpu.utils.intmath import L

    ty = int.from_bytes(eddsa._SMALL_ORDER_Y[3].tobytes(), "little")
    t_pt = ref.decode_point(ty.to_bytes(32, "little"))
    assert ref.is_small_order(t_pt)

    seed = b"\x09" * 32
    h = hashlib.sha512(seed).digest()
    a = ref._clamp(int.from_bytes(h[:32], "little"))
    prefix = h[32:]
    pk = ref.encode_point(ref.pt_add(ref.scalar_mult(a, ref.B), t_pt))

    filler = POOL[:3]
    accepted = rejected = 0
    for trial in range(24):
        msg = b"grind-%d" % trial
        r = ref._h(prefix + msg) % L
        r_enc = ref.encode_point(ref.scalar_mult(r, ref.B))
        k = ref._h(r_enc + pk + msg) % L
        sig = r_enc + ((r + k * a) % L).to_bytes(32, "little")
        msgs = [msg] + [f[0] for f in filler]
        pks = [pk] + [f[1] for f in filler]
        sigs = [sig] + [f[2] for f in filler]
        per = eddsa.verify_batch(msgs, pks, sigs).tolist()
        rlc = eddsa.verify_batch_rlc(msgs, pks, sigs).tolist()
        assert per == rlc, f"trial {trial}: per={per} rlc={rlc}"
        accepted += per[0]
        rejected += not per[0]
    # both branches of the torsion behavior were actually exercised
    # (k ≡ 0 mod 8 happens ~1/8 of the time; 24 tries miss it with
    # probability ~0.04 — seeds above are fixed, so this is stable)
    assert accepted >= 1 and rejected >= 1


def test_torsion_in_r_rejected_by_both_paths():
    m, pk, sig = POOL[0]
    ty = int.from_bytes(eddsa._SMALL_ORDER_Y[3].tobytes(), "little")
    t_pt = ref.decode_point(ty.to_bytes(32, "little"))
    r_mix = ref.pt_add(ref.decode_point(sig[:32]), t_pt)
    sig2 = ref.encode_point(r_mix) + sig[32:]
    assert eddsa.verify_batch([m], [pk], [sig2]).tolist() == [False]
    assert eddsa.verify_batch_rlc([m], [pk], [sig2]).tolist() == [False]


@pytest.mark.slow
def test_rlc_at_quorum_256_matches_and_is_measured():
    """The n=256 MSM bench shape: one combined check over a full large
    quorum, valid and with one corrupted vote (slow lane: this compiles
    the bucket-256 MSM program)."""
    pool = sig_pool(256, seed=99)
    msgs, pks, sigs = map(list, zip(*pool))
    assert eddsa.verify_batch_rlc(msgs, pks, sigs).all()
    sigs[137] = corrupt_sig(sigs[137])
    mask = eddsa.verify_batch_rlc(msgs, pks, sigs)
    assert not mask[137] and mask.sum() == 255

"""verifysched scheduler invariants: strict latency priority under a
mixed-class soak, bounded backpressure (queue-full replies), carry-over
fairness and bulk pad-fill, and the RLC-vs-per-signature verdict-mask
equivalence asserted through the FULL engine path (not the crypto
layer).
"""

import itertools
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
from hotstuff_tpu.sidecar import protocol as proto
from hotstuff_tpu.sidecar import sched as vsched
from hotstuff_tpu.sidecar import service
from hotstuff_tpu.sidecar.client import SidecarClient, SidecarOverloaded
from hotstuff_tpu.sidecar.service import SidecarServer, VerifyEngine


def _req(n, tag):
    """A fake verify request of n records with distinct msg bytes (the
    engine dedups identical (msg, pk, sig) records, so scheduling tests
    must not reuse them)."""
    msgs = [b"%16d|%16d" % (tag, i) for i in range(n)]
    return SimpleNamespace(request_id=tag, msgs=msgs,
                           pks=[b"p" * 32] * n, sigs=[b"s" * 64] * n)


def _sigs(n, tamper=(), seed=7):
    rng = np.random.default_rng(seed)
    msgs, pks, sigs = [], [], []
    for i in range(n):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in tamper:
            sig = sig[:1] + bytes([sig[1] ^ 0xFF]) + sig[2:]
        msgs.append(msg)
        pks.append(pk)
        sigs.append(sig)
    return msgs, pks, sigs


# ---------------------------------------------------------------------------
# scheduler-level policy (deterministic, single-threaded driving)
# ---------------------------------------------------------------------------

def test_latency_strict_priority_and_bulk_only_behind():
    s = vsched.Scheduler()
    order = []
    for i in range(3):
        assert s.offer(_req(600, 100 + i), order.append, cls=vsched.BULK)
    assert s.offer(_req(100, 1), order.append, cls=vsched.LATENCY)
    first = s.next_launch(block=False)
    assert first.cls == vsched.LATENCY
    assert [p.request.request_id for p in first.items] == [1]
    # bucket(100) = 128; no 600-sig bulk request fits the 28 pad slots
    assert first.fill_count == 0
    for want in (100, 101, 102):
        launch = s.next_launch(block=False)
        assert launch.cls == vsched.BULK
        assert [p.request.request_id for p in launch.items] == [want]
    assert s.next_launch(block=False) is None


def test_carry_over_keeps_fifo_and_leads_next_launch():
    s = vsched.Scheduler()
    assert s.shapes.launch_cap == eddsa.MAX_SUBBATCH
    s.offer(_req(700, 1), lambda m: None, cls=vsched.BULK)
    s.offer(_req(700, 2), lambda m: None, cls=vsched.BULK)
    s.offer(_req(100, 3), lambda m: None, cls=vsched.BULK)
    first = s.next_launch(block=False)
    # 700 + 700 > 1024: request 2 is carried over, and request 3 must
    # NOT jump the queue into the first launch (FIFO is the fairness
    # token).
    assert [p.request.request_id for p in first.items] == [1]
    second = s.next_launch(block=False)
    assert [p.request.request_id for p in second.items] == [2, 3]
    assert s.stats.snapshot()["carries"] == {"bulk": 1}


def test_oversized_single_request_still_ships():
    s = vsched.Scheduler()
    s.offer(_req(3000, 9), lambda m: None, cls=vsched.BULK)
    launch = s.next_launch(block=False)
    # Bigger than the launch cap: admitted whole (the engine dispatch
    # slices it into warmed shapes); the coalescer only bounds additions.
    assert launch.total_sigs == 3000


def test_bulk_pad_fill_drains_under_sustained_latency_load():
    s = vsched.Scheduler()
    done_bulk = []
    for i in range(10):
        assert s.offer(_req(2, 200 + i), done_bulk.append,
                       cls=vsched.BULK)
    launches = []
    # Sustained latency load: the latency queue is never empty when the
    # engine asks for work, so no bulk-only launch can ever be
    # assembled — pad-fill is the only drain.
    for i in range(12):
        s.offer(_req(4, i), lambda m: None, cls=vsched.LATENCY)
        launch = s.next_launch(block=False)
        assert launch.cls == vsched.LATENCY
        launches.append(launch)
        if s.queued_sigs(vsched.BULK) == 0:
            break
    assert s.queued_sigs(vsched.BULK) == 0, \
        "bulk starved under sustained latency load"
    # bucket(4) = 8 leaves 4 pad slots -> two 2-sig bulk requests ride
    # each latency launch for free.
    filled = [l for l in launches if l.fill_count]
    assert filled and all(l.total_sigs <= 8 for l in launches)
    snap = s.stats.snapshot()
    assert snap["bulk_fill_sigs"] == 20
    assert snap["launches_by_class"].get("bulk", 0) == 0


def test_pad_fill_room_uses_deduped_records():
    """N replicas submitting the SAME QC coalesce into one launch whose
    device shape is bucket(unique records) — fill room must be sized off
    that, or fill would grow the compiled shape and charge latency for
    bulk's ride (the raw total here is 10 -> bucket 16 -> room 6, which
    would push the unique count past bucket 8)."""
    s = vsched.Scheduler()
    s.offer(_req(5, 1), lambda m: None, cls=vsched.LATENCY)
    s.offer(_req(5, 1), lambda m: None, cls=vsched.LATENCY)  # same records
    for i in range(3):
        s.offer(_req(3, 300 + i), lambda m: None, cls=vsched.BULK)
    launch = s.next_launch(block=False)
    assert launch.cls == vsched.LATENCY
    # unique = 5 -> bucket 8 -> room 3: exactly one 3-sig bulk fill fits,
    # and unique-after-fill (8) still rides the latency batch's bucket.
    assert launch.fill_count == 1
    assert launch.total_sigs == 13  # 10 raw latency + 3 fill
    uniq = {rec for p in launch.items
            for rec in zip(p.request.msgs, p.request.pks, p.request.sigs)}
    assert len(uniq) <= 8


def test_queue_full_offer_rejects_and_counts():
    s = vsched.Scheduler(bulk_cap_sigs=8)
    assert s.offer(_req(8, 1), lambda m: None, cls=vsched.BULK)
    assert not s.offer(_req(4, 2), lambda m: None, cls=vsched.BULK)
    # the other class is unaffected by bulk saturation
    assert s.offer(_req(4, 3), lambda m: None, cls=vsched.LATENCY)
    snap = s.stats.snapshot()
    assert snap["queue_full"] == {"bulk": 1}
    assert snap["admitted"] == {"bulk": 1, "latency": 1}


# ---------------------------------------------------------------------------
# mixed-priority soak through the full engine
# ---------------------------------------------------------------------------

def test_mixed_priority_soak_through_engine():
    """Every latency-class request is launched before any bulk batch
    assembled after it.  The engine's verify is stubbed (scheduling is
    under test, not curve math) and slowed slightly so a real backlog
    forms while requests stream in."""
    engine = VerifyEngine(use_host=True)
    admit_idx = {}
    seq = itertools.count()
    launches = []

    def fake_verify_submit(msgs, pks, sigs):
        time.sleep(0.02)  # dispatch cost: lets the queues build up
        res = np.ones(len(msgs), bool)
        return lambda: res

    orig_pack = engine._pack

    def spying_pack(batch):
        # _pack is the launch-admission surface of the double-buffered
        # engine (the single pack worker preserves scheduler assembly
        # order, so this records the true launch order).
        launches.append([(p.cls, admit_idx[p.request.request_id])
                         for p in batch])
        return orig_pack(batch)

    engine._verify_submit = fake_verify_submit
    engine._pack = spying_pack
    try:
        replies = []
        cond = threading.Condition()

        def reply(mask):
            with cond:
                replies.append(mask)
                cond.notify()

        total = 0
        rid = itertools.count(1)
        for wave in range(6):
            for _ in range(3):
                r = _req(8, next(rid))
                admit_idx[r.request_id] = next(seq)
                assert engine.submit(r, reply, cls=vsched.BULK)
                total += 1
            for _ in range(2):
                r = _req(3, next(rid))
                admit_idx[r.request_id] = next(seq)
                assert engine.submit(r, reply, cls=vsched.LATENCY)
                total += 1
        with cond:
            assert cond.wait_for(lambda: len(replies) == total,
                                 timeout=60.0)
        # Reconstruct the invariant from the observed launch order:
        # for every latency item, no bulk-ONLY launch consisting purely
        # of later-admitted items may have launched before it.
        for i, launch in enumerate(launches):
            lat_admits = [a for cls, a in launch if cls == vsched.LATENCY]
            if not lat_admits:
                continue
            for j in range(i):
                earlier = launches[j]
                if any(cls == vsched.LATENCY for cls, _ in earlier):
                    continue
                assert min(a for _, a in earlier) < min(lat_admits), \
                    (j, earlier, i, launch)
        snap = engine.stats_snapshot()
        assert snap["launches"] == len(launches)
        assert snap["launches_by_class"].get("latency", 0) >= 1
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# wire-level backpressure
# ---------------------------------------------------------------------------

def test_queue_full_backpressure_reply_over_the_wire():
    """A saturated bulk queue is an immediate empty-mask reply that the
    client surfaces as SidecarOverloaded — never a blocked connection."""
    engine = VerifyEngine(use_host=True)

    def slow_verify_submit(msgs, pks, sigs):
        time.sleep(0.8)  # hold the engine thread so the queue stays full
        res = np.ones(len(msgs), bool)
        return lambda: res

    engine._verify_submit = slow_verify_submit
    engine._sched._queues[vsched.BULK].cap_sigs = 8
    srv = SidecarServer(("127.0.0.1", 0), engine)
    t = threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.1), daemon=True)
    t.start()
    port = srv.server_address[1]
    try:
        msgs, pks, sigs = _sigs(4, seed=31)
        results = {}

        def bg_verify(name, records, bulk):
            with SidecarClient(port=port, timeout=30.0) as c:
                m, p, s = records
                results[name] = c.verify_batch(m, p, s, bulk=bulk)

        # Plug the engine (latency launch dispatches, then sleeps)...
        plug = threading.Thread(
            target=bg_verify, args=("plug", _sigs(2, seed=32), False))
        plug.start()
        time.sleep(0.3)
        # ...fill the bulk queue to its 8-sig cap...
        filler = threading.Thread(
            target=bg_verify, args=("filler", _sigs(8, seed=33), True))
        filler.start()
        time.sleep(0.2)
        # ...and the next bulk request must shed, not block.
        with SidecarClient(port=port, timeout=30.0) as c:
            t0 = time.monotonic()
            with pytest.raises(SidecarOverloaded):
                c.verify_batch(msgs, pks, sigs, bulk=True)
            assert time.monotonic() - t0 < 5.0, \
                "queue-full reply must be immediate, not engine-paced"
        plug.join(timeout=30)
        filler.join(timeout=30)
        assert len(results["plug"]) == 2 and len(results["filler"]) == 8
        assert engine.stats_snapshot()["queue_full"].get("bulk", 0) >= 1
    finally:
        srv.shutdown()
        engine.stop()
        srv.server_close()


def test_stats_roundtrip_over_the_wire():
    engine = VerifyEngine(use_host=True)
    srv = SidecarServer(("127.0.0.1", 0), engine)
    t = threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.1), daemon=True)
    t.start()
    try:
        with SidecarClient(port=srv.server_address[1]) as c:
            msgs, pks, sigs = _sigs(5, tamper={2}, seed=41)
            assert c.verify_batch(msgs, pks, sigs) == \
                [i != 2 for i in range(5)]
            assert c.verify_batch(*_sigs(3, seed=42), bulk=True) == \
                [True] * 3
            snap = c.stats()
        assert snap["launches"] >= 2
        assert snap["launches_by_class"].get("latency", 0) >= 1
        assert set(snap["launches_by_class"]) <= {"latency", "bulk"}
        assert snap["paths"].get("host", 0) >= 2
        assert snap["queue_wait"]["latency"]["n"] >= 1
        assert snap["shapes"]["launch_cap"] == eddsa.MAX_SUBBATCH
    finally:
        srv.shutdown()
        engine.stop()
        srv.server_close()


# ---------------------------------------------------------------------------
# RLC routing through the full engine path
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def rlc_engine():
    """Device-path engine (CPU backend) with per-signature and RLC
    shapes warmed up to 32 — the real warmup entry points, so the
    registry state matches what `--warm-rlc` produces."""
    engine = VerifyEngine()
    service._warmup(engine, warm_max=32)
    service._warmup_rlc(engine, warm_max=32)
    yield engine
    engine.stop()


def _engine_mask(engine, msgs, pks, sigs):
    done = []
    cond = threading.Condition()

    def reply(mask):
        with cond:
            done.append(mask)
            cond.notify()

    assert engine.submit(proto.VerifyRequest(1, msgs, pks, sigs), reply)
    with cond:
        assert cond.wait_for(lambda: done, timeout=120.0)
    return done[0]


def test_engine_routes_rlc_and_masks_match_per_sig(rlc_engine):
    """Batches of n >= 16 valid-shape signatures route through
    verify_batch_rlc with verdict masks bit-identical to verify_batch —
    asserted through the engine (submit -> scheduler -> routed launch ->
    reply), across all-valid AND tampered batches (bisection path)."""
    engine = rlc_engine
    assert engine._shapes.route(16) == vsched.PATH_RLC
    assert engine._shapes.route(15) == vsched.PATH_PER_SIG
    before = engine.stats_snapshot()["paths"].get("rlc", 0)
    cases = [(16, set(), 50), (20, {3, 17}, 51), (31, {0}, 52)]
    for n, tamper, seed in cases:
        msgs, pks, sigs = _sigs(n, tamper=tamper, seed=seed)
        got = _engine_mask(engine, msgs, pks, sigs)
        want = eddsa.verify_batch(msgs, pks, sigs)
        assert got == [bool(b) for b in want], (n, tamper)
        assert got == [i not in tamper for i in range(n)]
    snap = engine.stats_snapshot()
    assert snap["paths"].get("rlc", 0) - before == len(cases)
    assert snap["paths"].get("rlc_bisect", 0) >= 2  # the tampered cases


def test_engine_small_batches_stay_per_sig(rlc_engine):
    engine = rlc_engine
    before = engine.stats_snapshot()["paths"].get("per_sig", 0)
    msgs, pks, sigs = _sigs(10, tamper={4}, seed=60)
    got = _engine_mask(engine, msgs, pks, sigs)
    assert got == [i != 4 for i in range(10)]
    assert engine.stats_snapshot()["paths"].get("per_sig", 0) == before + 1


# ---------------------------------------------------------------------------
# Mesh routing through the full engine path (8-device forced-host CPU
# mesh from conftest): sharded-RLC route selection, shard-aligned launch
# shapes, and mask bit-identity vs verify_batch incl. forced bisection.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh_engine():
    """Mesh engine with the per-signature AND sharded one-MSM warmups
    run through the real entry points (what `--mesh 8 --warm-rlc-sharded`
    produces), capped to small shapes to bound compile time.

    graftscale knobs exercised: the committee (40 -> quorum 27) floors
    the RLC warmup cap ABOVE warm_max=16, so the quorum's per-shard
    bucket (4) is warmed even though warm_max alone would stop at 2 —
    the giant-committee threshold discipline at fixture scale; and the
    warmup's scan leg (chunk counts 2 and 4 of the top bucket) raises
    the launch cap through the gated enable_bulk to the scan capacity
    8 dev x 4 chunks x 4 rows = 128 sigs."""
    engine = VerifyEngine(mesh_devices=8, committee=40)
    service._warmup(engine, warm_max=32)
    service._warmup_rlc_sharded(engine, warm_max=16, scan_chunks=4)
    yield engine
    engine.stop()


def test_mesh_route_selection(mesh_engine):
    shapes = mesh_engine._shapes
    # Warmed + >= RLC_MIN_LAUNCH -> the sharded one-MSM path; below the
    # floor the ladder path, even though its per-shard bucket is warmed.
    assert shapes.route(16) == vsched.PATH_RLC_SHARDED
    assert shapes.route(32) == vsched.PATH_RLC_SHARDED
    assert shapes.route(15) == vsched.PATH_LADDER_SHARDED
    # An unwarmed per-shard bucket must NOT route to the MSM.
    cold = vsched.ShapeRegistry(n_devices=8)
    assert cold.route(64) == vsched.PATH_LADDER_SHARDED
    # Warming is keyed per-shard: marking any size on the same bucket
    # unlocks every size that lands on it.
    cold.mark_rlc_sharded(64)
    assert cold.route(64) == vsched.PATH_RLC_SHARDED
    assert cold.route(57) == vsched.PATH_RLC_SHARDED   # same bucket (8)
    assert cold.route(128) == vsched.PATH_LADDER_SHARDED


def test_mesh_shard_aligned_capacity():
    from hotstuff_tpu.parallel.shard_shapes import (shard_aligned_rows,
                                                    shard_bucket)

    reg = vsched.ShapeRegistry(n_devices=8)
    for n in (1, 5, 16, 20, 100, 375 * 8, 3000):
        cap = reg.bucket_capacity(n)
        assert cap == shard_aligned_rows(n, 8)
        assert cap % 8 == 0, "mesh capacity must divide across devices"
        per = cap // 8
        assert per == shard_bucket(n, 8)
        assert per & (per - 1) == 0 or per % eddsa.MAX_SUBBATCH == 0, \
            "per-shard rows must be a pow2 bucket or whole chunks"
        assert cap >= n
    # The 375-row-shard regression: 3000 records on 8 devices must pad
    # to a power-of-two per-shard bucket, not ceil(3000/8)=375.
    assert reg.bucket_capacity(3000) == 8 * 512


def test_mesh_pad_fill_room_uses_shard_aligned_capacity():
    s = vsched.Scheduler(shapes=vsched.ShapeRegistry(n_devices=8))
    s.offer(_req(5, 1), lambda m: None, cls=vsched.LATENCY)
    for i in range(4):
        s.offer(_req(3, 300 + i), lambda m: None, cls=vsched.BULK)
    launch = s.next_launch(block=False)
    assert launch.cls == vsched.LATENCY
    # 5 unique -> shard-aligned capacity 8 (8 devices x 1-row bucket):
    # room for exactly one 3-sig bulk fill without growing any shard.
    assert launch.fill_count == 1
    assert launch.total_sigs == 8


def test_mesh_engine_masks_match_verify_batch(mesh_engine):
    """Engine-routed mesh launches of >= 16 unique records take the
    rlc_sharded path (visible in OP_STATS route counters), produce masks
    bit-identical to verify_batch — all-valid AND tampered (forced
    bisection) — and every launch's padded bucket divides evenly by the
    device count, landing only on warmup-marked shapes."""
    engine = mesh_engine
    before = engine.stats_snapshot()["paths"].get("rlc_sharded", 0)
    cases = [(16, set(), 70), (20, {3, 17}, 71), (31, {0}, 72)]
    for n, tamper, seed in cases:
        msgs, pks, sigs = _sigs(n, tamper=tamper, seed=seed)
        got = _engine_mask(engine, msgs, pks, sigs)
        want = eddsa.verify_batch(msgs, pks, sigs)
        assert got == [bool(b) for b in want], (n, tamper)
        assert got == [i not in tamper for i in range(n)]
    snap = engine.stats_snapshot()
    assert snap["paths"].get("rlc_sharded", 0) - before == len(cases)
    assert snap["paths"].get("rlc_bisect", 0) >= 2  # the tampered cases
    # Shard-aligned discipline, asserted via the shape registry: every
    # mesh launch's per-shard bucket must have been warmed — no shape
    # can have compiled cold after warmup.
    mesh_stats = snap["mesh"]
    assert mesh_stats["sharded_launches"] >= len(cases)
    warmed = set(snap["shapes"]["rlc_shard_buckets"]) \
        | set(snap["shapes"]["shard_buckets"])
    launched = {int(b) for b in mesh_stats["shard_buckets"]}
    assert launched and launched <= warmed, (launched, warmed)
    # pipeline telemetry exists and is consistent
    pipe = snap["pipeline"]
    assert pipe["pack_ms"] > 0
    assert 0.0 <= pipe["overlap_ratio"] <= 1.0


def test_mesh_engine_small_batches_take_ladder_path(mesh_engine):
    engine = mesh_engine
    before = engine.stats_snapshot()["paths"].get("ladder_sharded", 0)
    msgs, pks, sigs = _sigs(10, tamper={4}, seed=73)
    got = _engine_mask(engine, msgs, pks, sigs)
    assert got == [i != 4 for i in range(10)]
    snap = engine.stats_snapshot()
    assert snap["paths"].get("ladder_sharded", 0) == before + 1


# ---------------------------------------------------------------------------
# graftscale: whole-backlog chunked mesh scans + giant-committee routing
# ---------------------------------------------------------------------------


def test_warmup_scan_leg_raises_launch_cap_and_covers_quorum(mesh_engine):
    """--warm-rlc-sharded's graftscale legs, observed on the fixture:
    the scan shapes are marked and the launch cap rose through the
    gated enable_bulk to the scan capacity; the committee floor (40 ->
    quorum 27) warmed the quorum's per-shard bucket even though
    warm_max=16 alone would have stopped one bucket short."""
    shapes = mesh_engine._shapes
    assert shapes.committee == 40 and shapes.qc_sigs == 27
    snap = mesh_engine.stats_snapshot()["shapes"]
    assert snap["mesh_chunks"] == [2, 4]
    assert snap["scan_rows"] == 4
    # Raise-only enable_bulk: the fixture's scan capacity (128) sits
    # BELOW the MAX_SUBBATCH default, so the cap stays put (production
    # capacities — 16 chunks of 128 rows on 8 devices — raise it).
    assert shapes.scan_capacity() == 8 * 4 * 4
    assert snap["launch_cap"] == eddsa.MAX_SUBBATCH
    assert snap["committee"] == 40
    # The quorum's per-shard bucket (shard_bucket(27, 8) = 4) is RLC
    # warmed, so the committee's own QC batches route one-MSM.
    assert shapes.route(27) == vsched.PATH_RLC_SHARDED


def test_engine_whole_backlog_scan_one_launch(mesh_engine):
    """A coalesced bulk backlog bigger than every warmed ladder bucket
    dispatches as ONE whole-backlog scan launch: the OP_STATS ``scan``
    section shows it (and zero per-slice ladder launches), the chunk
    count is warmup-marked, and the mask is bit-identical to
    verify_batch — including device-detected invalid rows."""
    engine = mesh_engine
    before = engine.stats_snapshot()
    msgs, pks, sigs = _sigs(100, tamper={3, 77}, seed=80)
    got = _engine_mask(engine, msgs, pks, sigs)
    want = eddsa.verify_batch(msgs, pks, sigs)
    assert got == [bool(b) for b in want]
    assert got == [i not in (3, 77) for i in range(100)]
    snap = engine.stats_snapshot()
    scan = snap["scan"]
    assert scan["launches"] - before["scan"]["launches"] == 1
    assert scan["sigs"] - before["scan"]["sigs"] == 100
    # ceil(100/8)=13 rows/shard over 4-row chunks -> g=4, a warmed
    # chunk count (launched-scan-shapes subset of warmed, the scan
    # twin of the ladder buckets assertion below).
    assert scan["chunk_hist"].get("4", 0) >= 1
    launched_chunks = {int(g) for g in scan["chunk_hist"]}
    assert launched_chunks <= set(snap["shapes"]["mesh_chunks"])
    assert snap["paths"].get("scan_sharded", 0) \
        - before["paths"].get("scan_sharded", 0) == 1
    # Zero per-slice ladder launches for the backlog.
    assert snap["mesh"]["sharded_launches"] \
        == before["mesh"]["sharded_launches"]


def test_scan_route_falls_back_to_slicing_when_unwarmed():
    """An unwarmed chunk count must NOT take the scan route (it would
    be a cold XLA compile on the engine thread): the router answers the
    sliced ladder instead, and scan_shape_of says why (None)."""
    reg = vsched.ShapeRegistry(n_devices=8)
    reg.mark_bucket(8)                      # shard bucket 1 warmed
    for g in (2, 4):
        reg.mark_mesh_chunks(g, 4)
    assert reg.scan_shape_of(100) == (4, 4)
    assert reg.route(100) == vsched.PATH_SCAN_SHARDED
    # 3000 records need g=128 chunks of 4 rows — never warmed.
    assert reg.scan_shape_of(3000) is None
    assert reg.route(3000) == vsched.PATH_LADDER_SHARDED
    # A batch whose ladder bucket IS warmed keeps the ladder path.
    assert reg.route(8) == vsched.PATH_LADDER_SHARDED
    # No scan warmup at all: every size slices, as before graftscale.
    cold = vsched.ShapeRegistry(n_devices=8)
    assert cold.scan_shape_of(100) is None
    assert cold.route(100) == vsched.PATH_LADDER_SHARDED


def test_enable_bulk_gated_on_scan_shapes():
    """On a mesh registry the launch cap only rises once the
    whole-backlog scan shapes are warmed — to the warmed scan capacity,
    raise-only (a small capacity never LOWERS the cap below its current
    value); single-chip registries keep the old contract."""
    reg = vsched.ShapeRegistry(n_devices=8)
    reg.enable_bulk(16 * 1024)
    assert reg.launch_cap == eddsa.MAX_SUBBATCH  # gated: nothing warmed
    # Production-scale scan shapes (16 chunks of 128 rows on 8 devices
    # = 16384 capacity): the cap rises to min(bound, capacity).
    for g in (2, 4, 8, 16):
        reg.mark_mesh_chunks(g, 128)
    reg.enable_bulk(16 * 1024)
    assert reg.launch_cap == 16 * 1024
    # The caller's bound still wins when it is tighter.
    big = vsched.ShapeRegistry(n_devices=8)
    for g in (2, 4, 8, 16):
        big.mark_mesh_chunks(g, 1024)
    big.enable_bulk(2048)
    assert big.launch_cap == 2048
    # Single chip: ungated, as before.
    single = vsched.ShapeRegistry()
    single.enable_bulk(4096)
    assert single.launch_cap == 4096
    # Raise-only: a SMALL warmed scan capacity (8 devices x 4 chunks x
    # 4 rows = 128, the test-fixture scale) must never LOWER the cap
    # below the MAX_SUBBATCH default.
    small = vsched.ShapeRegistry(n_devices=8)
    for g in (2, 4):
        small.mark_mesh_chunks(g, 4)
    small.enable_bulk(16 * 1024)
    assert small.launch_cap == eddsa.MAX_SUBBATCH
    assert small.scan_capacity() == 8 * 4 * 4
    # One rows value per registry: a second would mean two scan
    # ladders the router cannot tell apart.
    with pytest.raises(ValueError):
        reg.mark_mesh_chunks(2, 8)


def test_ladder_slices_stay_on_warmed_buckets(mesh_engine):
    """The sliced-ladder fallback must slice at the WARMED ladder cap,
    not the scan-raised launch_cap: an oversized request whose chunk
    count is unwarmed (g=16 here) slices into launches whose per-shard
    buckets the warmup compiled — never a cold mid-run shape — and the
    whole sliced backlog records as ONE mesh launch with its per-slice
    buckets."""
    engine = mesh_engine
    shapes = engine._shapes
    # The registry arithmetic: the coalescer cap (MAX_SUBBATCH at
    # fixture scale — raise-only enable_bulk) never leaks into ladder
    # slicing, which stays at n_dev x top warmed bucket = 32.
    assert shapes.launch_cap == eddsa.MAX_SUBBATCH
    assert shapes.ladder_cap() == 8 * 4
    assert shapes.route(300) == vsched.PATH_LADDER_SHARDED
    before = engine.stats_snapshot()
    msgs, pks, sigs = _sigs(300, tamper={7, 250}, seed=81)
    got = _engine_mask(engine, msgs, pks, sigs)
    assert got == [i not in (7, 250) for i in range(300)]
    snap = engine.stats_snapshot()
    assert snap["mesh"]["sharded_launches"] \
        - before["mesh"]["sharded_launches"] == 1
    assert snap["scan"]["launches"] == before["scan"]["launches"]
    launched = {int(b) for b in snap["mesh"]["shard_buckets"]}
    warmed = set(snap["shapes"]["shard_buckets"]) \
        | set(snap["shapes"]["rlc_shard_buckets"])
    assert launched and launched <= warmed, (launched, warmed)


def test_giant_committee_threshold_routing():
    """QC-shaped latency batches for N in {100, 300, 1000} route
    through the sharded one-MSM path once their quorum bucket is
    warmed (the committee-floored warmup guarantees it is), and stay
    on the safe ladder when it is not."""
    from hotstuff_tpu.parallel.shard_shapes import shard_bucket

    assert vsched.quorum_sigs(1000) == 667
    for committee in (100, 300, 1000):
        q = vsched.quorum_sigs(committee)
        reg = vsched.ShapeRegistry(n_devices=8, committee=committee)
        assert reg.qc_sigs == q
        assert q >= vsched.RLC_MIN_LAUNCH
        assert shard_bucket(q, 8) <= eddsa.MAX_SUBBATCH, \
            "quorum must fit the one-dispatch RLC envelope"
        assert reg.route(q) == vsched.PATH_LADDER_SHARDED  # unwarmed
        reg.mark_rlc_sharded(q)
        assert reg.route(q) == vsched.PATH_RLC_SHARDED
    # N=1000: ~667 signatures land on the 128-row per-shard bucket.
    assert shard_bucket(667, 8) == 128


def test_scan_and_mesh_launch_stats_accounting():
    """note_mesh_launch counts ONE launch with per-slice buckets in the
    histogram; note_scan_launch feeds the ``scan`` section including
    the slices the old per-launch_cap path would have paid."""
    stats = vsched.SchedStats()
    stats.note_mesh_launch([4, 4, 8, None])
    snap = stats.snapshot()
    assert snap["mesh"]["sharded_launches"] == 1
    assert snap["mesh"]["shard_buckets"] == {"4": 2, "8": 1}
    stats.note_scan_launch(16, 16384, 15)
    stats.note_scan_launch(4, 300, 0)
    snap = stats.snapshot()
    assert snap["scan"] == {"launches": 2, "sigs": 16684,
                            "chunk_hist": {"4": 1, "16": 1},
                            "slices_avoided": 15}


def test_pipeline_overlap_is_a_rolling_window():
    """graftcadence satellite: the OP_STATS ``pipeline`` section answers
    for RECENT pack-boundedness (entries older than PIPE_WINDOW_S age
    out), while the lifetime accumulators survive under ``lifetime_*``
    for trend tooling."""
    from hotstuff_tpu.sidecar.sched.stats import PIPE_WINDOW_S

    now = [1000.0]
    stats = vsched.SchedStats(clock=lambda: now[0])
    for _ in range(8):
        stats.note_pack(0.010, hidden=False)
    pipe = stats.snapshot()["pipeline"]
    assert pipe["overlap_ratio"] == 0.0
    assert pipe["pack_ms"] == pytest.approx(80.0)
    # The unhealthy history ages out; only the recent packs report.
    now[0] += PIPE_WINDOW_S + 1.0
    for _ in range(4):
        stats.note_pack(0.010, hidden=True)
    pipe = stats.snapshot()["pipeline"]
    assert pipe["overlap_ratio"] == 1.0
    assert pipe["pack_ms"] == pytest.approx(40.0)
    assert pipe["window_s"] == PIPE_WINDOW_S
    # Lifetime keeps the whole story for bench_trend.
    assert pipe["lifetime_pack_ms"] == pytest.approx(120.0)
    assert pipe["lifetime_overlap_ratio"] == pytest.approx(0.333,
                                                           abs=1e-3)


@pytest.mark.slow
def test_giant_quorum_engine_path_n1000():
    """The N=1000 acceptance shape through the REAL engine: a
    667-signature latency batch routes sharded-RLC with its mask
    bit-identical to verify_batch, incl. a forced bisection.  Slow
    lane: the quorum floor warms per-shard buckets up to 128 (each a
    fresh XLA compile of both mesh programs on the CPU backend)."""
    engine = VerifyEngine(mesh_devices=8, committee=1000)
    service._warmup(engine, warm_max=8)
    # scan_chunks=0 skips the scan leg: this test is about the RLC
    # threshold, and the scan programs at rows=128 are another minute
    # of CPU compile the assertion doesn't need.
    service._warmup_rlc_sharded(engine, warm_max=8, scan_chunks=0)
    try:
        assert engine._shapes.qc_sigs == 667
        assert engine._shapes.route(667) == vsched.PATH_RLC_SHARDED
        msgs, pks, sigs = _sigs(667, tamper={13, 600}, seed=90)
        got = _engine_mask(engine, msgs, pks, sigs)
        want = eddsa.verify_batch(msgs, pks, sigs)
        assert got == [bool(b) for b in want]
        assert got == [i not in (13, 600) for i in range(667)]
        snap = engine.stats_snapshot()
        assert snap["paths"].get("rlc_sharded", 0) >= 1
        assert snap["paths"].get("rlc_bisect", 0) >= 1
        warmed = set(snap["shapes"]["rlc_shard_buckets"])
        assert 128 in warmed, "quorum bucket must be warmed"
        launched = {int(b) for b in snap["mesh"]["shard_buckets"]}
        assert launched and launched <= warmed \
            | set(snap["shapes"]["shard_buckets"])
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# parameter-sized admission caps (ROADMAP follow-up: committee/rate sizing
# replaces the static constants; env overrides win over everything)
# ---------------------------------------------------------------------------


def test_queue_caps_sized_from_committee_and_rate(monkeypatch):
    monkeypatch.delenv("HOTSTUFF_TPU_LATENCY_QUEUE_CAP_SIGS",
                       raising=False)
    monkeypatch.delenv("HOTSTUFF_TPU_BULK_QUEUE_CAP_SIGS", raising=False)
    # No parameters: the static defaults.
    assert vsched.size_queue_caps() == (64 * 1024, 128 * 1024)
    # Committee sizing: n * quorum * per-replica pipeline depth (64),
    # clamped to [default/4, 16x default].
    lat, blk = vsched.size_queue_caps(committee=20, client_rate=100_000)
    assert lat == 20 * (2 * 20 // 3 + 1) * 64
    assert blk == 2 * 100_000
    # Clamps: a 4-node committee floors, a silly rate ceilings.
    lat, _ = vsched.size_queue_caps(committee=4)
    assert lat == 64 * 1024 // 4
    _, blk = vsched.size_queue_caps(client_rate=10 ** 9)
    assert blk == 16 * 128 * 1024


def test_queue_caps_env_override_wins(monkeypatch):
    monkeypatch.setenv("HOTSTUFF_TPU_LATENCY_QUEUE_CAP_SIGS", "777")
    monkeypatch.setenv("HOTSTUFF_TPU_BULK_QUEUE_CAP_SIGS", "888")
    assert vsched.size_queue_caps(committee=100, client_rate=10 ** 6) \
        == (777, 888)
    # Malformed / non-positive env values fall back cleanly.
    monkeypatch.setenv("HOTSTUFF_TPU_LATENCY_QUEUE_CAP_SIGS", "soon")
    monkeypatch.setenv("HOTSTUFF_TPU_BULK_QUEUE_CAP_SIGS", "-2")
    assert vsched.size_queue_caps() == (64 * 1024, 128 * 1024)


def test_engine_applies_sized_caps_and_reports_them(monkeypatch):
    monkeypatch.delenv("HOTSTUFF_TPU_LATENCY_QUEUE_CAP_SIGS",
                       raising=False)
    monkeypatch.delenv("HOTSTUFF_TPU_BULK_QUEUE_CAP_SIGS", raising=False)
    engine = VerifyEngine(use_host=True, committee=20, client_rate=50_000)
    try:
        caps = engine.stats_snapshot()["queue_caps"]
        assert caps["latency"] == 20 * (2 * 20 // 3 + 1) * 64
        assert caps["bulk"] == 100_000
    finally:
        engine.stop()

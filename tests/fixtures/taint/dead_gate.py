"""taint fixture: a declared sanitizer nothing calls.

``check_frame`` promises a signature gate, but every handler bypasses
it — the annotation protects nothing (the classic outcome of deleting
the one call site during a refactor)."""


# graftlint: sanitizes=sig
def check_frame(payload):
    return len(payload) >= 16


def handle(sock):
    payload = sock.recv(4096)
    return payload

"""taint fixture: wire bytes reach verdict emission with no gate.

``parse`` is neither a declared sanitizer nor verify-shaped, so the
frame flows from the socket straight into an OP reply carrying a
non-literal verdict mask."""
import protocol as proto


def parse(payload):
    return payload[0], payload


def handle(sock):
    payload = proto.read_frame(sock)
    opcode, req = parse(payload)
    verdicts = [True] * len(req)
    return proto.encode_reply(opcode, 1, verdicts)

"""taint fixture: a verify-shaped call with an unannotated definition.

``verify_payload`` looks like a gate and is used like a gate, but its
definition declares no label — the analysis cannot credit it, and the
author must either annotate it or rename it."""


def verify_payload(payload):
    return len(payload) > 0


def handle(sock):
    payload = sock.recv(4096)
    if not verify_payload(payload):
        return None
    return payload

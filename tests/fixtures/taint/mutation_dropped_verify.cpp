// taint fixture: the "delete the verify call" mutation.  handle_vote
// admits the QC without calling Vote::verify — so the wire bytes reach
// process_qc ungated AND the declared sanitizer goes dark (two rules
// from one deleted line, which is exactly the review signal wanted).
#include "messages.hpp"

// VERIFIES(sig)
VerifyResult Vote::verify(const Committee& committee) const {
  return VerifyResult::good();
}

VerifyResult Core::handle_vote(const Bytes& raw) {
  Vote vote = Vote::deserialize(raw);
  // MUTATION: the `vote.verify(committee_)` admission check was here.
  process_qc(vote.qc);
  return VerifyResult::good();
}

"""taint fixture: the "reorder admission before verification" mutation.

The request is packed for device launch BEFORE decode_request's
frame-structure gate runs, so hostile lengths reach the packer."""
import protocol as proto


# graftlint: sanitizes=frame-structure
def decode_request(payload):
    return payload[0], payload


def handle(sock, engine):
    payload = proto.read_frame(sock)
    engine.submit(payload, None)
    opcode, req = decode_request(payload)
    return opcode

// taint fixture: deserialized wire bytes reach the commit sink with no
// verification gate anywhere on the path.
#include "messages.hpp"

VerifyResult Core::receive(const Bytes& msg) {
  ConsensusMessage m = ConsensusMessage::deserialize(msg);
  return commit(m.block);
}

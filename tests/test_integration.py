"""Node-quartet <-> sidecar integration: the flagship path of the framework.

Asserts (a) a 4-node committee routes QC verification through the verify
sidecar and commits blocks, and (b) killing the sidecar mid-run degrades to
host verification instead of stalling consensus (the bounded-read fallback
in native/src/crypto/sidecar_client.cpp).

Reference parity: QC::verify -> Signature::verify_batch
(consensus/src/messages.rs:197 -> crypto/src/lib.rs:210-223). CI-safe: the
sidecar runs --host-crypto so no accelerator or jit warmup is involved.
Process scaffolding (testbed fixture, log helpers) lives in conftest.py.
"""

import os
import sys

import pytest

from conftest import (
    CLIENT_BIN, NODE_BIN, count_in_log, free_port, make_committee,
    wait_commits, wait_sidecar_ping,
)

pytestmark = pytest.mark.skipif(
    not (os.path.exists(NODE_BIN) and os.path.exists(CLIENT_BIN)),
    reason="native binaries not built (cmake --build native/build)")

NODES = 4
TIMEOUT_DELAY_MS = 1000


def test_sidecar_backed_consensus_and_failover(testbed):
    tmp_path, spawn = testbed
    sidecar_port = free_port()
    _, committee, _ = make_committee(tmp_path, NODES, TIMEOUT_DELAY_MS,
                                     sidecar_port=sidecar_port)

    # -- sidecar first; nodes boot only once it answers PING --------------
    sidecar = spawn(
        [sys.executable, "-m", "hotstuff_tpu.sidecar", "--port",
         str(sidecar_port), "--host-crypto"],
        "sidecar.log")
    assert wait_sidecar_ping(sidecar_port), "sidecar never became ready"

    node_logs = []
    for i in range(NODES):
        spawn([NODE_BIN, "run", "--keys", f".node-{i}.json",
               "--committee", ".committee.json", "--store", f".db-{i}",
               "--parameters", ".parameters.json", "-v"],
              f"node-{i}.log")
        node_logs.append(tmp_path / f"node-{i}.log")
    for i, addr in enumerate(committee.front_addresses()):
        spawn([CLIENT_BIN, addr, "--size", "64", "--rate", "250",
               "--timeout", str(TIMEOUT_DELAY_MS),
               "--nodes", *committee.front_addresses()],
              f"client-{i}.log")

    # -- phase 1: commits flow through the sidecar ------------------------
    counts = wait_commits(node_logs, minimum=3, deadline_s=60)
    assert all(c >= 3 for c in counts), f"no commits with sidecar: {counts}"
    assert all(count_in_log(p, "connected to verify sidecar") >= 1
               for p in node_logs), "a node never used the sidecar"

    # -- phase 2: kill the sidecar; consensus must keep committing --------
    sidecar.kill()
    sidecar.wait()
    before = [count_in_log(p, "Committed B") for p in node_logs]
    after = wait_commits(node_logs, minimum=max(before) + 3, deadline_s=30)
    assert all(a > b for a, b in zip(after, before)), (
        f"consensus stalled after sidecar death: {before} -> {after}")

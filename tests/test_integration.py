"""Node-quartet <-> sidecar integration: the flagship path of the framework.

Asserts (a) a 4-node committee routes QC verification through the verify
sidecar and commits blocks, and (b) killing the sidecar mid-run degrades to
host verification instead of stalling consensus (the bounded-read fallback
in native/src/crypto/sidecar_client.cpp).

Reference parity: QC::verify -> Signature::verify_batch
(consensus/src/messages.rs:197 -> crypto/src/lib.rs:210-223). CI-safe: the
sidecar runs --host-crypto so no accelerator or jit warmup is involved.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from hotstuff_tpu.harness.config import Key, LocalCommittee, NodeParameters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NODE_BIN = os.path.join(REPO, "native", "build", "node")
CLIENT_BIN = os.path.join(REPO, "native", "build", "client")

pytestmark = pytest.mark.skipif(
    not (os.path.exists(NODE_BIN) and os.path.exists(CLIENT_BIN)),
    reason="native binaries not built (cmake --build native/build)")

NODES = 4
TIMEOUT_DELAY_MS = 1000


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_ping(port, deadline_s=30):
    from hotstuff_tpu.sidecar.client import SidecarClient

    start = time.monotonic()
    while time.monotonic() - start < deadline_s:
        try:
            with SidecarClient(port=port, timeout=2.0) as c:
                c.ping()
            return True
        except (OSError, ConnectionError):
            time.sleep(0.2)
    return False


def _count(path, needle):
    try:
        with open(path, "r", errors="replace") as f:
            return f.read().count(needle)
    except OSError:
        return 0


def _wait_commits(log_files, minimum, deadline_s):
    start = time.monotonic()
    while time.monotonic() - start < deadline_s:
        counts = [_count(p, "Committed B") for p in log_files]
        if all(c >= minimum for c in counts):
            return counts
        time.sleep(0.5)
    return [_count(p, "Committed B") for p in log_files]


@pytest.fixture
def testbed(tmp_path):
    procs = []

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(cmd, log_name):
        log = open(tmp_path / log_name, "w")
        p = subprocess.Popen(cmd, cwd=tmp_path, stdout=log, stderr=log,
                             env=env)
        procs.append((p, log))
        return p

    yield tmp_path, spawn
    for p, log in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p, log in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        log.close()


def test_sidecar_backed_consensus_and_failover(testbed):
    tmp_path, spawn = testbed
    sidecar_port = _free_port()

    # -- config (same layout LocalBench writes) ---------------------------
    keys = []
    for i in range(NODES):
        subprocess.run([NODE_BIN, "keys", "--filename", f".node-{i}.json"],
                       cwd=tmp_path, check=True)
        keys.append(Key.from_file(str(tmp_path / f".node-{i}.json")))
    committee = LocalCommittee([k.name for k in keys], _free_port())
    committee.print(str(tmp_path / ".committee.json"))
    params = NodeParameters.default(
        tpu_sidecar=f"127.0.0.1:{sidecar_port}")
    params.json["consensus"]["timeout_delay"] = TIMEOUT_DELAY_MS
    params.json["mempool"]["batch_size"] = 1000
    params.print(str(tmp_path / ".parameters.json"))

    # -- sidecar first; nodes boot only once it answers PING --------------
    sidecar = spawn(
        [sys.executable, "-m", "hotstuff_tpu.sidecar", "--port",
         str(sidecar_port), "--host-crypto"],
        "sidecar.log")
    assert _wait_ping(sidecar_port), "sidecar never became ready"

    node_logs = []
    for i in range(NODES):
        spawn([NODE_BIN, "run", "--keys", f".node-{i}.json",
               "--committee", ".committee.json", "--store", f".db-{i}",
               "--parameters", ".parameters.json", "-v"],
              f"node-{i}.log")
        node_logs.append(tmp_path / f"node-{i}.log")
    for i, addr in enumerate(committee.front_addresses()):
        spawn([CLIENT_BIN, addr, "--size", "64", "--rate", "250",
               "--timeout", str(TIMEOUT_DELAY_MS),
               "--nodes", *committee.front_addresses()],
              f"client-{i}.log")

    # -- phase 1: commits flow through the sidecar ------------------------
    counts = _wait_commits(node_logs, minimum=3, deadline_s=60)
    assert all(c >= 3 for c in counts), f"no commits with sidecar: {counts}"
    assert all(_count(p, "connected to verify sidecar") >= 1
               for p in node_logs), "a node never used the sidecar"

    # -- phase 2: kill the sidecar; consensus must keep committing --------
    sidecar.kill()
    sidecar.wait()
    before = [_count(p, "Committed B") for p in node_logs]
    after = _wait_commits(node_logs, minimum=max(before) + 3, deadline_s=30)
    assert all(a > b for a, b in zip(after, before)), (
        f"consensus stalled after sidecar death: {before} -> {after}")

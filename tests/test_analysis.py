"""graftlint tests: every rule fires on a known-bad fixture and stays
quiet on a known-good one; the repaired tree lints clean; the sanitizer
wiring builds and runs (tier-2, slow-marked).
"""

import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from hotstuff_tpu.analysis import (hotpath, padshape, sanitize, sockets,
                                   timing, wirecheck)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src: str):
    return hotpath.check_sources(
        {"mod.py": textwrap.dedent(src)})


def rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# hot-path rules
# ---------------------------------------------------------------------------

def test_host_sync_in_jit_fires_on_item_and_casts():
    findings = lint("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def verify_mask(x):
            n = int(x.sum())          # host round trip
            y = x * 2
            host = np.asarray(y)      # device->host copy
            return host[:n], y.max().item()
        """)
    assert rules(findings) == {"host-sync-in-jit"}
    assert len(findings) == 3


def test_host_sync_quiet_on_host_helpers_and_static_shapes():
    findings = lint("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        def to_limbs(x: int):
            return np.array([int(x) >> i for i in range(4)],
                            dtype=np.int32)

        @jax.jit
        def verify_mask(x, table=()):
            n = x.shape[0]            # static: .shape launders
            rows = int(n // 2)        # python int math, not traced
            return x.reshape(rows, -1).astype(jnp.int32)
        """)
    assert findings == []


def test_traced_branch_fires_and_static_branch_is_quiet():
    bad = lint("""
        import jax

        @jax.jit
        def f(x):
            if x.sum() > 0:           # concretization error / retrace
                return x
            return -x
        """)
    assert rules(bad) == {"traced-branch"}
    good = lint("""
        import jax

        def dbl(p, with_t: bool = True):
            if with_t:                # static python config param
                return p + p
            return p

        @jax.jit
        def f(x):
            if x.ndim == 2:           # laundered: shape metadata
                return dbl(x, with_t=False)
            return dbl(x)
        """)
    assert good == []


def test_mutable_default_arg_fires_only_on_hot_functions():
    bad = lint("""
        import jax

        @jax.jit
        def f(x, opts={}):
            return x
        """)
    assert rules(bad) == {"mutable-default-arg"}
    good = lint("""
        def host_helper(x, opts={}):   # not jit-reachable
            return x
        """)
    assert good == []


def test_f64_literal_fires_on_promotion_and_dtype():
    bad = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = x * 1.5                       # f64 under x64
            return jnp.zeros(4, dtype=jnp.float64), y
        """)
    assert rules(bad) == {"f64-literal"}
    assert len(bad) == 2
    good = lint("""
        import jax
        import jax.numpy as jnp

        SCALE = 1.5  # host-side constant, folded at trace time

        @jax.jit
        def f(x):
            return x.astype(jnp.float32) * jnp.float32(2)
        """)
    assert good == []


def test_implicit_limb_dtype_fires_on_bare_constant_lists():
    bad = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            bias = jnp.asarray([237, 255, 127])   # backend-dependent dtype
            return x + bias
        """)
    assert rules(bad) == {"implicit-limb-dtype"}
    good = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            bias = jnp.asarray([237, 255, 127], dtype=jnp.int32)
            return x + bias
        """)
    assert good == []


def test_nondonated_buffer_fires_on_verify_entry_points():
    bad = lint("""
        import jax

        def verify_packed(packed):
            return packed.sum(-1)

        verify_packed_jit = jax.jit(verify_packed)
        """)
    assert rules(bad) == {"nondonated-buffer"}
    good = lint("""
        import jax

        def verify_packed(packed):
            return packed.sum(-1)

        def helper(fn):
            return jax.jit(fn)        # not a verify_* symbol

        verify_packed_jit = jax.jit(verify_packed, donate_argnums=0)
        """)
    assert good == []


def test_suppression_comment_silences_a_rule():
    findings = lint("""
        import jax

        def verify_packed(packed):
            return packed.sum(-1)

        # profiling scripts re-time one device-resident input
        # graftlint: disable=nondonated-buffer
        verify_packed_jit = jax.jit(verify_packed)
        """)
    assert findings == []


def test_taint_follows_cross_module_calls():
    """A hot function calling into a field module taints the callee's
    params — the rule fires in the callee file."""
    findings = hotpath.check_sources({
        "field.py": textwrap.dedent("""
            def mul(a, b):
                return int(a) * b       # host sync on a traced value
            """),
        "curve.py": textwrap.dedent("""
            import jax
            from . import field as F

            @jax.jit
            def verify_mask(x):
                return F.mul(x, x)
            """),
    })
    assert [(f.path, f.rule) for f in findings] == \
        [("field.py", "host-sync-in-jit")]


def test_except_handler_bodies_are_linted():
    findings = lint("""
        import jax

        @jax.jit
        def f(x):
            try:
                return x * 2
            except ValueError:
                return int(x.sum())   # host sync hidden in an error path
        """)
    assert rules(findings) == {"host-sync-in-jit"}


def test_from_jax_import_numpy_spelling_is_covered():
    findings = lint("""
        import jax
        from jax import numpy as jnp

        @jax.jit
        def f(x):
            return x + jnp.asarray([237, 255, 127])
        """)
    assert rules(findings) == {"implicit-limb-dtype"}


def test_scan_and_shard_map_bodies_are_hot():
    findings = lint("""
        import jax
        from jax import shard_map

        def _make_body(cap: int):
            def _body(a, present):
                if a.sum() > cap:     # traced branch in a shard body
                    return a
                return a * present
            return _body

        fn = shard_map(_make_body(4), in_specs=None, out_specs=None)
        checker = jax.jit(fn)
        """)
    assert rules(findings) == {"traced-branch"}


# ---------------------------------------------------------------------------
# wire/constants cross-checker (fixture trees under tmp_path)
# ---------------------------------------------------------------------------

WIRE_FILES = (wirecheck.PROTOCOL, wirecheck.SIDECAR_CLIENT,
              wirecheck.CRYPTO_HPP, wirecheck.FIELD25519,
              wirecheck.INTMATH, wirecheck.FIELD381, wirecheck.BLS12381,
              wirecheck.TXSIGN, wirecheck.TX_FRAME_HPP)


@pytest.fixture()
def wire_tree(tmp_path):
    """Copy of the real tree's cross-checked files: the known-good base
    every bad fixture mutates — so the tests track the real sources."""
    for rel in WIRE_FILES:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, rel), dst)
    return tmp_path


def _mutate(tree, rel, old, new):
    path = tree / rel
    text = path.read_text()
    assert old in text, f"fixture drift: {old!r} not in {rel}"
    path.write_text(text.replace(old, new))


def test_wire_checker_quiet_on_consistent_tree(wire_tree):
    assert wirecheck.check(str(wire_tree)) == []


def test_wire_tag_mismatch_fires_on_one_sided_opcode_edit(wire_tree):
    _mutate(wire_tree, wirecheck.SIDECAR_CLIENT,
            "kOpBlsSign = 4", "kOpBlsSign = 9")
    findings = wirecheck.check(str(wire_tree))
    assert rules(findings) == {"wire-tag-mismatch"}
    assert "kOpBlsSign" in findings[0].message


def test_wire_length_mismatch_fires_on_bls_and_digest_drift(wire_tree):
    _mutate(wire_tree, wirecheck.PROTOCOL,
            "BLS_SIG_LEN = 192", "BLS_SIG_LEN = 96")
    _mutate(wire_tree, wirecheck.SIDECAR_CLIENT,
            "kDigestLen = 32", "kDigestLen = 20")
    findings = wirecheck.check(str(wire_tree))
    assert rules(findings) == {"wire-length-mismatch"}
    assert len(findings) >= 2  # kBlsSigLen drift + digest drift


def test_field_modulus_mismatch_fires_on_one_sided_edit(wire_tree):
    _mutate(wire_tree, wirecheck.FIELD25519,
            "P = 2**255 - 19", "P = 2**255 - 21")
    findings = wirecheck.check(str(wire_tree))
    assert rules(findings) == {"field-modulus-mismatch"}
    assert any(f.path == wirecheck.FIELD25519 for f in findings)


def test_field_modulus_mismatch_fires_on_cpp_hex_edit(wire_tree):
    _mutate(wire_tree, wirecheck.CRYPTO_HPP,
            "b9feffffffffaaab", "b9feffffffffaaad")
    findings = wirecheck.check(str(wire_tree))
    assert rules(findings) == {"field-modulus-mismatch"}


def test_wire_header_mismatch_fires_on_request_header_drift(wire_tree):
    """Widening msg_len to u32 in protocol.py without touching
    write_header: the exact one-sided edit the rule exists for."""
    _mutate(wire_tree, wirecheck.PROTOCOL,
            '_HDR = struct.Struct("<BIIH")',
            '_HDR = struct.Struct("<BIII")')
    findings = wirecheck.check(str(wire_tree))
    assert rules(findings) == {"wire-header-mismatch"}
    assert any("write_header" in f.message for f in findings)


def test_wire_header_mismatch_fires_on_reply_layout_drift(wire_tree):
    """Shrinking the reply request id breaks the C++ reader's raw-offset
    rid parse (reply[1..4])."""
    _mutate(wire_tree, wirecheck.PROTOCOL,
            '_REPLY_HDR = struct.Struct("<BII")',
            '_REPLY_HDR = struct.Struct("<BHI")')
    findings = wirecheck.check(str(wire_tree))
    assert rules(findings) == {"wire-header-mismatch"}


def test_wire_header_mismatch_fires_on_big_endian_format(wire_tree):
    _mutate(wire_tree, wirecheck.PROTOCOL,
            '_HDR = struct.Struct("<BIIH")',
            '_HDR = struct.Struct(">BIIH")')
    findings = wirecheck.check(str(wire_tree))
    assert "wire-header-mismatch" in rules(findings)
    assert any("little-endian" in f.message for f in findings)


# ---------------------------------------------------------------------------
# padded-bucket (launch-shape discipline)
# ---------------------------------------------------------------------------

def test_padded_bucket_fires_on_unbucketed_launch():
    findings = padshape.check_sources({"mod.py": textwrap.dedent("""
        import numpy as np

        def dispatch(rows):
            return verify_packed_donated(rows)
        """)})
    assert rules(findings) == {"padded-bucket"}


def test_padded_bucket_quiet_on_bucketed_launch_and_factories():
    findings = padshape.check_sources({"mod.py": textwrap.dedent("""
        def dispatch(rows, n):
            m = next_pow2(n)
            rows = pad(rows, m)
            return verify_packed_donated(rows)

        def cached_launch(mesh, arrays):
            m = _bucket(len(arrays))
            return _cached_verifier(mesh)(arrays[:m])

        verify_packed_donated = _jit_donated(verify_packed)
        """)})
    assert findings == []


def test_padded_bucket_quiet_on_real_tree():
    assert padshape.check(REPO) == []


# ---------------------------------------------------------------------------
# shard-misaligned-launch (mesh launch-size discipline)
# ---------------------------------------------------------------------------

MESH_MOD = padshape.MESH_TARGETS[0]


def test_shard_misaligned_fires_on_handrolled_device_math():
    findings = padshape.check_sources({MESH_MOD: textwrap.dedent("""
        import numpy as np

        def verify_over_mesh(mesh, prep, n_dev):
            n = prep.shape[0]
            m = n_dev * next_pow2(-(-n // n_dev))
            rows = np.pad(prep, m - n)
            return _cached_verifier(mesh)(rows)
        """)})
    assert rules(findings) == {"shard-misaligned-launch"}
    assert any("size math against n_dev" in f.message for f in findings)


def test_shard_misaligned_fires_on_unaligned_mesh_launch():
    # A mesh launch with NO size math at all still needs the helper —
    # whoever shaped the buffers must have aligned them.
    findings = padshape.check_sources({MESH_MOD: textwrap.dedent("""
        def launch(mesh, rows, z, n):
            m = next_pow2(n)
            return _cached_rlc_verifier(mesh)(rows[:m], z[:m])
        """)})
    assert rules(findings) == {"shard-misaligned-launch"}
    assert any("mesh launch _cached_rlc_verifier" in f.message
               for f in findings)


def test_shard_misaligned_quiet_on_helper_routed_launch():
    findings = padshape.check_sources({MESH_MOD: textwrap.dedent("""
        import numpy as np

        def verify_over_mesh(mesh, prep):
            n = prep.shape[0]
            m = shard_aligned_rows(n, mesh.devices.size)
            rows = np.pad(prep, m - n)
            return _cached_verifier(mesh)(rows)

        def registry_capacity(self, n):
            return shard_aligned_rows(n, self.n_devices)
        """)})
    assert findings == []


def test_shard_misaligned_fires_on_handrolled_scan_chunks():
    """graftscale: a whole-backlog scan launch whose chunk count comes
    from hand-rolled n_dev division instead of mesh_chunk_count is a
    finding — the (g, rows) scan shapes are warmed exactly like the
    buckets, so a free-hand g can land a never-compiled program."""
    findings = padshape.check_sources({MESH_MOD: textwrap.dedent("""
        def scan_backlog(mesh, rows_in, present, n_dev, rows):
            g = next_pow2(-(-rows_in.shape[0] // n_dev) // rows)
            return _cached_chunk_verifier(mesh, g, rows)(rows_in,
                                                         present)
        """)})
    assert rules(findings) == {"shard-misaligned-launch"}
    assert any("size math against n_dev" in f.message for f in findings)


def test_shard_misaligned_quiet_on_mesh_chunk_count_routed_scan():
    """mesh_chunk_count is one of THE shard helpers: a scan launch
    routed through it is clean."""
    findings = padshape.check_sources({MESH_MOD: textwrap.dedent("""
        import numpy as np

        def scan_backlog(mesh, prep, rows):
            n = prep.shape[0]
            n_dev = mesh.devices.size
            g = mesh_chunk_count(n, n_dev, rows)
            m = n_dev * g * rows
            padded = np.pad(prep, m - n)
            return _cached_chunk_verifier(mesh, g, rows)(padded)
        """)})
    assert findings == []


def test_shard_misaligned_quiet_on_factories_and_non_mesh_modules():
    # The donated-cache factory REFERENCES _cached_verifier without
    # launching it; a non-mesh module may do n_dev math freely (the rule
    # is scoped to the mesh-path targets).
    factory = textwrap.dedent("""
        def _cached_verifier_donated(mesh, max_subbatch):
            if backend() == "cpu":
                return _cached_verifier(mesh, max_subbatch)
            return make_sharded_verifier(mesh, max_subbatch, donate=True)
        """)
    assert padshape.check_sources({MESH_MOD: factory}) == []
    elsewhere = textwrap.dedent("""
        def partition(n, n_dev):
            return n // n_dev
        """)
    assert padshape.check_sources({"mod.py": elsewhere}) == []


# ---------------------------------------------------------------------------
# pallas-interpret-in-prod (graftkern interpreter-pin discipline)
# ---------------------------------------------------------------------------


def test_pallas_interpret_fires_on_literal_true():
    findings = padshape.check_sources({
        "hotstuff_tpu/ops/kern/fake.py": textwrap.dedent("""
            def my_kernel_entry(x):
                return pl.pallas_call(
                    body,
                    out_shape=shape,
                    interpret=True,
                )(x)
            """)})
    assert rules(findings) == {"pallas-interpret-in-prod"}
    assert "my_kernel_entry" in findings[0].message


def test_pallas_interpret_quiet_on_backend_probe_and_helper_call():
    # interpret selected off the backend probe: clean.
    clean = textwrap.dedent("""
        def entry(x):
            return pl.pallas_call(
                body, out_shape=shape,
                interpret=interpret_default(),
            )(x)
        """)
    assert padshape.check_sources(
        {"hotstuff_tpu/ops/kern/fake.py": clean}) == []
    # The backend-probe helper itself may pin the literal.
    probe = textwrap.dedent("""
        def interpret_default():
            return pl.pallas_call(k, out_shape=s, interpret=True)(x)
        """)
    assert padshape.check_sources(
        {"hotstuff_tpu/ops/kern/backend.py": probe}) == []
    # ... but ONLY in backend.py: a shim merely NAMED interpret_default
    # in another kernel module cannot claim the exemption.
    findings = padshape.check_sources(
        {"hotstuff_tpu/ops/kern/msm_accum.py": probe})
    assert rules(findings) == {"pallas-interpret-in-prod"}


def test_pallas_interpret_suppression_comment():
    src = textwrap.dedent("""
        def probe(x):
            return pl.pallas_call(
                body, out_shape=shape,
                # graftlint: disable=pallas-interpret-in-prod
                interpret=True,
            )(x)
        """)
    assert padshape.check_sources(
        {"hotstuff_tpu/ops/kern/fake.py": src}) == []


def test_pallas_interpret_quiet_on_real_kern_tree():
    # The real kern package carries exactly one forced literal — the
    # interpreter probe — behind its worked suppression.
    findings = [f for f in padshape.check(REPO)
                if f.rule == "pallas-interpret-in-prod"]
    assert findings == []


def test_padded_bucket_fires_on_warmup_floor_drift(tmp_path):
    for rel in (padshape.EDDSA, padshape.SERVICE):
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, rel), dst)
    _mutate(tmp_path, padshape.SERVICE,
            "_warm_shapes(engine, 8, warm_max",
            "_warm_shapes(engine, 16, warm_max")
    findings = padshape.check(str(tmp_path), targets=())
    assert rules(findings) == {"padded-bucket"}
    assert any("_MIN_BUCKET" in f.message for f in findings)


def test_padded_bucket_fires_on_non_pow2_coalesce(tmp_path):
    for rel in (padshape.EDDSA, padshape.SERVICE):
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, rel), dst)
    _mutate(tmp_path, padshape.SERVICE,
            "MAX_COALESCED = 16 * MAX_SUBBATCH",
            "MAX_COALESCED = 12 * MAX_SUBBATCH")
    findings = padshape.check(str(tmp_path), targets=())
    assert rules(findings) == {"padded-bucket"}
    assert any("power-of-two" in f.message for f in findings)


def test_must_cover_gate():
    from hotstuff_tpu.analysis.__main__ import check_coverage

    # the lint_gate pins: the RLC scalar module, the verifysched package
    # (directory target), and the newly-covered crypto/BLS modules
    assert check_coverage(REPO, [
        "hotpath:hotstuff_tpu/ops/scalar25519.py",
        "hotpath:hotstuff_tpu/crypto/eddsa.py",
        "hotpath:hotstuff_tpu/offchain/bls12381.py",
        "hotpath:hotstuff_tpu/sidecar/sched/scheduler.py",
        "hotpath:hotstuff_tpu/sidecar/sched/shapes.py",
        "hotpath:hotstuff_tpu/sidecar/sched/stats.py",
        "hotpath:hotstuff_tpu/sidecar/sched/classes.py",
        # graftkern pins: the Pallas kernel modules sit inside BOTH the
        # hotpath and padshape scans
        "hotpath:hotstuff_tpu/ops/kern/field_mul.py",
        "hotpath:hotstuff_tpu/ops/kern/msm_accum.py",
        "padshape:hotstuff_tpu/ops/kern/backend.py",
        "padshape:hotstuff_tpu/ops/kern/scalar_mont.py",
        # graftchaos pins (the sockets checker's targets)
        "sockets:hotstuff_tpu/chaos/plan.py",
        "sockets:hotstuff_tpu/chaos/runner.py",
        "sockets:hotstuff_tpu/chaos/recovery.py",
        "sockets:hotstuff_tpu/harness/faults.py",
        # bare pins accept any checker — including timing (exact file
        # and glob targets) and padshape
        "hotstuff_tpu/sidecar/protocol.py",
        "bench.py",
        "scripts/exp_xfer_streams.py",
        "timing:bench.py",
    ]) == []
    # Checker qualification is load-bearing: the sockets checker scans
    # sidecar/ too, but a hotpath-qualified pin on a file only sockets
    # covers must FAIL (a union would let the hot-path lint silently
    # lose a file another checker's prefix still matches).
    out = check_coverage(REPO, ["hotpath:hotstuff_tpu/sidecar/client.py"])
    assert [f.rule for f in out] == ["must-cover"]
    assert "hotpath scan targets" in out[0].message
    # an unknown checker name fails loudly, never passes silently
    out = check_coverage(REPO, ["typo:hotstuff_tpu/sidecar/client.py"])
    assert [f.rule for f in out] == ["must-cover"]
    assert "unknown checker" in out[0].message
    # a file outside every checker's targets fails the gate
    out = check_coverage(REPO, ["hotstuff_tpu/utils/intmath.py"])
    assert [f.rule for f in out] == ["must-cover"]
    # a missing file fails the gate
    out = check_coverage(REPO, ["hotstuff_tpu/ops/nonexistent.py"])
    assert [f.rule for f in out] == ["must-cover"]


# ---------------------------------------------------------------------------
# timing rule (block_until_ready inside a timed region)
# ---------------------------------------------------------------------------

def tlint(src: str):
    return timing.check_sources({"prof.py": textwrap.dedent(src)})


def test_timing_rule_fires_between_timer_reads():
    findings = tlint("""
        import time

        def stage(fn, x):
            t0 = time.perf_counter()
            out = fn(x)
            out.block_until_ready()      # lies through the tunnel
            return time.perf_counter() - t0
        """)
    assert rules(findings) == {"block-until-ready-in-timing"}


def test_timing_rule_quiet_on_asarray_fence_and_warmup():
    findings = tlint("""
        import time
        import numpy as np

        def stage(fn, x):
            fn(x).block_until_ready()    # warmup fence, before the timer
            t0 = time.perf_counter()
            out = fn(x)
            np.asarray(out)              # forced D2H: the honest fence
            return time.perf_counter() - t0

        def helper(x):
            return x.block_until_ready() # never times anything
        """)
    assert findings == []


def test_timing_rule_scopes_exclude_nested_functions():
    # The nested put() blocks, but only the OUTER scope times — and the
    # block sits outside the outer scope's timed region (the
    # exp_xfer_streams.py shape: per-stream put workers are fenced
    # individually, the outer loop times the whole fan-out).
    findings = tlint("""
        import time

        def main(bufs, put_raw):
            def put(buf):
                x = put_raw(buf)
                x.block_until_ready()
                return x
            put(bufs[0])                 # warm
            t0 = time.perf_counter()
            outs = [put(b) for b in bufs]
            dt = time.perf_counter() - t0
            return outs, dt
        """)
    assert findings == []


def test_timing_rule_suppression_comment():
    findings = tlint("""
        import time

        def stage(fn, x):
            t0 = time.perf_counter()
            # CPU backend: block_until_ready is exact here
            # graftlint: disable=block-until-ready-in-timing
            fn(x).block_until_ready()
            return time.perf_counter() - t0
        """)
    assert findings == []


def test_timing_rule_quiet_on_real_profiling_scripts():
    assert timing.check(REPO) == []


# ---------------------------------------------------------------------------
# sanitizer wiring
# ---------------------------------------------------------------------------

def test_sanitizer_wiring_quiet_on_real_tree():
    assert sanitize.check(REPO) == []


def test_sanitizer_wiring_fires_when_preset_or_script_missing(tmp_path):
    native = tmp_path / "native"
    native.mkdir()
    (native / "CMakeLists.txt").write_text(
        "project(x CXX)\n")  # no GRAFT_SANITIZE, no -fsanitize
    findings = sanitize.check(str(tmp_path))
    assert rules(findings) == {"sanitizer-wiring"}
    assert any("native_sanitize.sh missing" in f.message for f in findings)


# ---------------------------------------------------------------------------
# the gate itself
# ---------------------------------------------------------------------------

def test_gate_exits_clean_on_repaired_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "hotstuff_tpu.analysis", "--root", REPO],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint: clean" in proc.stdout


def test_gate_exits_nonzero_on_findings(tmp_path):
    # An empty tree is missing every anchor: the gate must fail loudly,
    # not skip silently.
    proc = subprocess.run(
        [sys.executable, "-m", "hotstuff_tpu.analysis",
         "--root", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "finding" in proc.stderr


# ---------------------------------------------------------------------------
# tier-2: native sanitizer build-and-run
# ---------------------------------------------------------------------------

@pytest.mark.slow  # full native rebuild per sanitizer: minutes
@pytest.mark.parametrize("mode", ["address", "undefined"])
def test_native_sanitize_builds_and_runs(mode):
    script = os.path.join(REPO, "scripts", "native_sanitize.sh")
    proc = subprocess.run(
        [script, mode, "serde", "store"], cwd=REPO,
        capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-4000:]
    assert f"all tests clean under {mode}" in proc.stdout


# ---------------------------------------------------------------------------
# sockets rule (unbounded-socket-op over sidecar/, harness/, chaos/)
# ---------------------------------------------------------------------------

def slint(src: str):
    return sockets.check_sources({"net.py": textwrap.dedent(src)})


def test_unbounded_socket_op_fires_on_bare_ops():
    findings = slint("""
        import socket
        def dial():
            c = socket.create_connection(("127.0.0.1", 7100))
            return c
        def pump(sock):
            return sock.recv(4)
        def serve(listen_sock):
            conn, _ = listen_sock.accept()
            return conn
    """)
    assert [(f.rule, f.line) for f in findings] == [
        ("unbounded-socket-op", 4),
        ("unbounded-socket-op", 7),
        ("unbounded-socket-op", 9),
    ]
    assert "create_connection" in findings[0].message
    assert ".recv()" in findings[1].message


def test_unbounded_socket_op_quiet_on_bounded_ops():
    findings = slint("""
        import socket
        def dial(timeout):
            a = socket.create_connection(("h", 1), timeout=timeout)
            b = socket.create_connection(("h", 1), 5.0)
            return a, b
        def pump(sock):
            sock.settimeout(2.0)
            return sock.recv(4)
        def serve(listen_sock):
            listen_sock.settimeout(1.0)
            conn, _ = listen_sock.accept()
            return conn
        def not_a_socket(db):
            return db.connect()
    """)
    assert findings == []


def test_unbounded_socket_op_scopes_do_not_leak():
    # A settimeout in one function does not bound another function's
    # socket of the same name.
    findings = slint("""
        def a(sock):
            sock.settimeout(1.0)
            return sock.recv(4)
        def b(sock):
            return sock.recv(4)
    """)
    assert [(f.rule, f.line) for f in findings] == [
        ("unbounded-socket-op", 6)]


def test_unbounded_socket_op_timeout_none_still_fires():
    findings = slint("""
        import socket
        def dial():
            return socket.create_connection(("h", 1), timeout=None)
    """)
    assert [f.rule for f in findings] == ["unbounded-socket-op"]


def test_unbounded_socket_op_suppression():
    findings = slint("""
        def pump(sock):
            # callers bound the socket; server readers idle by design
            # graftlint: disable=unbounded-socket-op
            return sock.recv(4)
    """)
    assert findings == []


def test_sockets_rule_quiet_on_real_tree():
    assert sockets.check(REPO) == []


# ---------------------------------------------------------------------------
# obsspan rules (grafttrace instrumentation discipline)
# ---------------------------------------------------------------------------

from hotstuff_tpu.analysis import obsspan


def olint(src: str, path: str = "hotstuff_tpu/obs/mod.py"):
    return obsspan.check_sources({path: textwrap.dedent(src)})


def test_unclosed_span_fires_without_finally():
    findings = olint("""
        def pack(tracer):
            tok = tracer.begin_span("pack")
            do_work()
            tracer.end_span(tok)   # an exception above leaks the span
    """)
    assert [f.rule for f in findings] == ["unclosed-span"]


def test_unclosed_span_quiet_on_try_finally_and_with():
    assert olint("""
        def pack(tracer):
            tok = tracer.begin_span("pack")
            try:
                do_work()
            finally:
                tracer.end_span(tok)

        def bls(tracer):
            with tracer.span("device"):
                do_work()
    """) == []


def test_unclosed_span_exempts_context_manager_enter():
    # The _SpanCtx protocol: __enter__ begins, __exit__ ends — the
    # pairing is the interpreter's job, not a finally block's.
    assert olint("""
        class Ctx:
            def __enter__(self):
                self._tok = self._tracer.begin_span(self._stage)
                return self._tok

            def __exit__(self, *exc):
                self._tracer.end_span(self._tok)
    """) == []


def test_unclosed_span_scopes_are_per_function():
    # An end_span in a DIFFERENT function does not close this one.
    findings = olint("""
        def a(tracer):
            tok = tracer.begin_span("x")
            return tok

        def b(tracer, tok):
            try:
                pass
            finally:
                tracer.end_span(tok)
    """)
    assert [f.rule for f in findings] == ["unclosed-span"]


def test_span_inline_clock_fires_in_obs_modules_only():
    src = """
        import time
        def sample(self):
            return time.time()
    """
    findings = olint(src)
    assert [f.rule for f in findings] == ["span-inline-clock"]
    # the engine module may read monotonic() for OP_STATS; the clock
    # rule is scoped to obs/
    assert olint(src, path="hotstuff_tpu/sidecar/service.py") == []


def test_span_inline_clock_allows_injected_default():
    # A clock REFERENCE as a default parameter is the sanctioned idiom.
    assert olint("""
        from time import time as _wall_clock

        class Tracer:
            def __init__(self, clock=_wall_clock):
                self._clock = clock

            def now(self):
                return self._clock()
    """) == []


def test_span_inline_clock_catches_bare_imported_names():
    findings = olint("""
        from time import monotonic
        def tick(self):
            return monotonic()
    """)
    assert [f.rule for f in findings] == ["span-inline-clock"]


def test_obsspan_suppression_comment():
    assert olint("""
        import time
        def sample(self):
            # graftlint: disable=span-inline-clock
            return time.time()
    """) == []


def test_obsspan_quiet_on_real_tree():
    assert obsspan.check(REPO) == []


def test_obs_modules_pinned_to_span_and_timing_scans():
    from hotstuff_tpu.analysis.__main__ import check_coverage

    assert check_coverage(REPO, [
        "obsspan:hotstuff_tpu/obs/__init__.py",
        "obsspan:hotstuff_tpu/obs/spans.py",
        "obsspan:hotstuff_tpu/obs/trace.py",
        "obsspan:hotstuff_tpu/obs/sampler.py",
        "obsspan:hotstuff_tpu/sidecar/service.py",
        "timing:hotstuff_tpu/obs/trace.py",
        "timing:hotstuff_tpu/obs/sampler.py",
    ]) == []
    # a module outside the obsspan targets fails its qualified pin
    out = check_coverage(REPO, ["obsspan:hotstuff_tpu/harness/logs.py"])
    assert [f.rule for f in out] == ["must-cover"]


# ---------------------------------------------------------------------------
# graftscope: obsgrammar rules (Python<->C++ log-line grammar pins)
# ---------------------------------------------------------------------------

from hotstuff_tpu.analysis import obsgrammar

_GOOD_METRICS_PY = '''
_NODE_METRICS_RE = (r"\\[(\\S+Z) \\w+ [^\\]]+\\] METRICS "
                    r"commits=(\\d+) commit_rate=([0-9.]+) "
                    r"ingress_tx=(\\d+) ingress_bytes=(\\d+) "
                    r"busy=(\\d+) breaker=(\\w+)")
'''

_GOOD_METRICS_CPP = '''
void NodeMetrics::emit_sample(double dt_s) {
  LOG_INFO("node::metrics")
      << "METRICS commits=" << commits << " commit_rate=" << rate_buf
      << " ingress_tx=" << ingress_tx << " ingress_bytes=" << ingress_bytes
      << " busy=" << busy << " breaker=" << breaker_name(tpu);
}
'''


def test_obsgrammar_clean_fixture_pair():
    assert obsgrammar.check_sources({
        "hotstuff_tpu/obs/sampler.py": _GOOD_METRICS_PY,
        "native/src/common/metrics.cpp": _GOOD_METRICS_CPP}) == []


def test_obsgrammar_renamed_cpp_key_fires():
    bad = _GOOD_METRICS_CPP.replace('" busy="', '" busyx="')
    findings = obsgrammar.check_sources({
        "hotstuff_tpu/obs/sampler.py": _GOOD_METRICS_PY,
        "native/src/common/metrics.cpp": bad})
    assert [f.rule for f in findings] == ["metrics-grammar-mismatch"]
    assert "busyx" in findings[0].message


def test_obsgrammar_reordered_keys_fire_despite_same_set():
    bad = _GOOD_METRICS_CPP.replace(
        '" ingress_tx=" << ingress_tx << " ingress_bytes=" << ingress_bytes',
        '" ingress_bytes=" << ingress_bytes << " ingress_tx=" << ingress_tx')
    findings = obsgrammar.check_sources({
        "hotstuff_tpu/obs/sampler.py": _GOOD_METRICS_PY,
        "native/src/common/metrics.cpp": bad})
    assert [f.rule for f in findings] == ["metrics-grammar-mismatch"]


def test_obsgrammar_missing_anchor_is_a_finding():
    # A python side whose regex vanished cannot be silently ignored.
    findings = obsgrammar.check_sources({
        "hotstuff_tpu/obs/sampler.py": "X = 1\n",
        "native/src/common/metrics.cpp": _GOOD_METRICS_CPP})
    assert findings and all(f.rule == "metrics-grammar-mismatch"
                            for f in findings)
    # Same for an emit site that disappeared from the C++.
    findings = obsgrammar.check_sources({
        "hotstuff_tpu/obs/sampler.py": _GOOD_METRICS_PY,
        "native/src/common/metrics.cpp": "int x;\n"})
    assert findings and "emit site" in findings[0].message


def test_obsgrammar_trace_pair_fixture():
    py = ('_NODE_TRACE_RE = (r"\\[(\\S+Z) \\w+ [^\\]]+\\] TRACE "\n'
          '                  r"stage=(\\w+) block=(\\S+) round=(\\d+)")\n')
    cpp = ('void trace_stage(const char* stage, const Block& block) {\n'
           '  LOG_INFO("consensus::core")\n'
           '      << "TRACE stage=" << stage << " block=" << d\n'
           '      << " round=" << block.round;\n'
           '}\n')
    assert obsgrammar.check_sources({
        "hotstuff_tpu/obs/trace.py": py,
        "native/src/consensus/core.cpp": cpp}) == []
    findings = obsgrammar.check_sources({
        "hotstuff_tpu/obs/trace.py": py,
        "native/src/consensus/core.cpp":
            cpp.replace('" round="', '" rnd="')})
    assert [f.rule for f in findings] == ["trace-grammar-mismatch"]


def test_obsgrammar_quiet_on_real_tree():
    assert obsgrammar.check(REPO) == []


def test_obsgrammar_pins_cover_both_grammar_sides():
    from hotstuff_tpu.analysis.__main__ import check_coverage

    assert check_coverage(REPO, [
        "obsgrammar:hotstuff_tpu/obs/trace.py",
        "obsgrammar:hotstuff_tpu/obs/sampler.py",
        "obsgrammar:native/src/consensus/core.cpp",
        "obsgrammar:native/src/common/metrics.cpp",
    ]) == []
    out = check_coverage(REPO, ["obsgrammar:hotstuff_tpu/harness/logs.py"])
    assert [f.rule for f in out] == ["must-cover"]


# ---------------------------------------------------------------------------
# graftsync: threads rules (cross-thread sharing discipline)
# ---------------------------------------------------------------------------

from hotstuff_tpu.analysis import threads as threads_checker


def thlint(src: str):
    return threads_checker.check_sources({"mod.py": textwrap.dedent(src)})


def test_unlocked_shared_write_fires_on_cross_thread_attr():
    findings = thlint("""
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def bump(self):
                self.count += 1

            def _run(self):
                while True:
                    self.count += 1
    """)
    assert [f.rule for f in findings] == ["unlocked-shared-write"] * 2
    assert {f.line for f in findings} == {14, 18}  # bump and _run sites
    assert "self.count" in findings[0].message
    # self._thread is written from ONE side only (start) — not flagged
    assert all("_thread" not in f.message for f in findings)


def test_unlocked_shared_write_quiet_when_one_lock_covers_all_sites():
    assert thlint("""
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._lock = threading.Lock()
                self._stop = threading.Event()

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def bump(self):
                with self._lock:
                    self.count += 1

            def _run(self):
                while not self._stop.is_set():
                    with self._lock:
                        self.count += 1
    """) == []


def test_unlocked_shared_write_fires_when_sites_disagree_on_lock():
    findings = thlint("""
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._a = threading.Lock()
                self._b = threading.Lock()

            def start(self):
                threading.Thread(target=self._run).start()

            def bump(self):
                with self._a:
                    self.count += 1

            def _run(self):
                with self._b:
                    self.count += 1
    """)
    assert [f.rule for f in findings] == ["unlocked-shared-write"] * 2


def test_unlocked_shared_write_init_writes_are_exempt():
    # construction happens-before Thread.start(): __init__-only writes
    # plus thread-side writes are NOT cross-thread
    assert thlint("""
        import threading

        class Sampler:
            def __init__(self):
                self.samples = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.samples += 1
    """) == []


def test_unlocked_shared_write_fires_across_two_entries():
    # a pool worker (submit) and a dedicated thread are distinct
    # threads; a shared container written by both needs the lock
    findings = thlint("""
        import threading

        class Engine:
            def __init__(self, pool):
                self._pool = pool
                self.jobs = []

            def start(self):
                threading.Thread(target=self._run).start()
                self._pool.submit(self._pack)

            def _run(self):
                self.jobs.append("run")

            def _pack(self):
                self.jobs.append("pack")
    """)
    assert [f.rule for f in findings] == ["unlocked-shared-write"] * 2
    assert {f.line for f in findings} == {14, 17}


def test_unlocked_shared_write_worked_suppression():
    assert thlint("""
        import threading

        class Worker:
            def __init__(self):
                self.count = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def bump(self):
                # single-threaded test helper, never called live
                # graftlint: disable=unlocked-shared-write
                self.count += 1

            def _run(self):
                # graftlint: disable=unlocked-shared-write
                self.count += 1
    """) == []


def test_daemon_thread_without_stop_flag_fires():
    findings = thlint("""
        import threading

        class Poller:
            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while True:
                    pass
    """)
    assert [f.rule for f in findings] == ["daemon-thread-without-stop-flag"]
    assert findings[0].line == 6


def test_daemon_thread_with_derived_stop_flag_is_quiet():
    # the sampler idiom: the loop consults an attribute DERIVED from the
    # Event in __init__ (self._wait = wait or self._stop.wait)
    assert thlint("""
        import threading

        class Sampler:
            def __init__(self, wait=None):
                self._stop = threading.Event()
                self._wait = wait if wait is not None else self._stop.wait

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while True:
                    if self._wait(1.0):
                        return
    """) == []


def test_thread_loop_inline_clock_fires_only_in_clock_injected_classes():
    injected = """
        import threading
        from time import monotonic

        class Runner:
            def __init__(self, clock=monotonic):
                self._clock = clock

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                return monotonic()
    """
    findings = thlint(injected)
    assert [f.rule for f in findings] == ["thread-loop-inline-clock"]
    # a class with NO injectable clock is out of scope (the engine's
    # monotonic() telemetry reads are the documented legitimate use)
    assert thlint("""
        import threading
        from time import monotonic

        class Engine:
            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                return monotonic()
    """) == []


def test_threads_rules_quiet_on_real_tree():
    # the one worked suppression lives in sidecar/service._cache_verdict
    assert threads_checker.check(REPO) == []


# ---------------------------------------------------------------------------
# graftsync: cxxsync rules (GUARDED_BY discipline + atomic orders)
# ---------------------------------------------------------------------------

from hotstuff_tpu.analysis import cxxsync

GUARD_HPP = textwrap.dedent("""
    #include <mutex>
    struct Box {
      std::mutex m;
      int value = 0;  // GUARDED_BY(m)
    };
""")


def cxlint(cpp: str, hpp: str = GUARD_HPP):
    return cxxsync.check_sources({
        "guard.hpp": hpp,
        "guard.cpp": textwrap.dedent(cpp),
    })


def test_guarded_member_unlocked_fires_outside_lock_scope():
    findings = cxlint("""
        #include "guard.hpp"
        void good(Box* b) {
          std::lock_guard<std::mutex> lk(b->m);
          b->value = 1;
        }
        void bad(Box* b) {
          b->value = 2;
        }
    """)
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("guarded-member-unlocked", "guard.cpp", 8)]
    assert "GUARDED_BY(m)" in findings[0].message


def test_guarded_member_locked_suffix_function_is_exempt():
    assert cxlint("""
        #include "guard.hpp"
        void tweak_locked(Box* b) {
          b->value = 3;
        }
        static void poke_locked_(Box* b) {
          b->value = 4;
        }
    """) == []


def test_guarded_member_unique_lock_unlock_window_fires():
    findings = cxlint("""
        #include "guard.hpp"
        void window(Box* b) {
          std::unique_lock<std::mutex> lk(b->m);
          b->value = 1;
          lk.unlock();
          b->value = 2;
          lk.lock();
          b->value = 3;
        }
    """)
    assert [(f.rule, f.line) for f in findings] == [
        ("guarded-member-unlocked", 7)]


def test_guarded_member_wrong_mutex_fires():
    hpp = textwrap.dedent("""
        #include <mutex>
        struct Box {
          std::mutex m;
          std::mutex m2;
          int value = 0;   // GUARDED_BY(m)
          int extra = 0;   // GUARDED_BY(m2)
        };
    """)
    findings = cxlint("""
        #include "guard.hpp"
        void bad(Box* b) {
          std::lock_guard<std::mutex> lk(b->m2);
          b->value = 1;
        }
    """, hpp=hpp)
    assert [f.rule for f in findings] == ["guarded-member-unlocked"]
    assert "GUARDED_BY(m)" in findings[0].message


def test_guarded_member_cpp_suppression_comment():
    assert cxlint("""
        #include "guard.hpp"
        void init(Box* b) {
          // pre-thread construction: the thread-start edge orders this
          // graftlint: disable=guarded-member-unlocked
          b->value = 0;
        }
    """) == []


def test_unannotated_mutex_fires_for_members_not_locals():
    findings = cxxsync.check_sources({"bare.hpp": textwrap.dedent("""
        #include <mutex>
        struct Bare {
          std::mutex m_;
          int x = 0;
        };
        inline void local_is_fine() {
          std::mutex scratch_;
          (void)scratch_;
        }
    """)})
    assert [(f.rule, f.line) for f in findings] == [("unannotated-mutex", 4)]


def test_atomic_missing_order_fires_and_explicit_is_quiet():
    findings = cxxsync.check_sources({"at.cpp": textwrap.dedent("""
        #include <atomic>
        std::atomic<int> g{0};
        int bad() { return g.load(); }
        int bad2(std::atomic<int>* p) { return p->fetch_sub(1); }
        void good() { g.store(1, std::memory_order_relaxed); }
        int good2() { return g.load(std::memory_order_acquire); }
    """)})
    assert [(f.rule, f.line) for f in findings] == [
        ("atomic-missing-order", 4), ("atomic-missing-order", 5)]


def test_cxxsync_quiet_on_real_tree():
    # every GUARDED_BY access in the annotated subsystems is either
    # under its lock, inside a *_locked function, or carries a worked
    # suppression; every atomic op states its memory order
    assert cxxsync.check(REPO) == []


def test_graftsync_modules_pinned_to_their_scans():
    from hotstuff_tpu.analysis.__main__ import check_coverage

    assert check_coverage(REPO, [
        "threads:hotstuff_tpu/sidecar/service.py",
        "threads:hotstuff_tpu/obs/sampler.py",
        "threads:hotstuff_tpu/chaos/runner.py",
        "cxxsync:native/src/crypto/sidecar_client.cpp",
        "cxxsync:native/src/network/event_loop.hpp",
    ]) == []
    out = check_coverage(REPO, ["threads:hotstuff_tpu/ops/ed25519.py"])
    assert [f.rule for f in out] == ["must-cover"]


# ---------------------------------------------------------------------------
# graftsync: machine-readable findings (--json / --json-out)
# ---------------------------------------------------------------------------

def test_json_output_clean_tree(tmp_path):
    out = tmp_path / "findings.json"
    proc = subprocess.run(
        [sys.executable, "-m", "hotstuff_tpu.analysis", "--root", REPO,
         "--json", "--json-out", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json as _json

    doc = _json.loads(proc.stdout)
    assert doc == _json.loads(out.read_text())
    assert doc["schema"] == "graftlint-findings-v1"
    assert doc["clean"] is True and doc["findings"] == []
    assert "threads" in doc["checkers"] and "cxxsync" in doc["checkers"]


def test_json_output_carries_findings(tmp_path):
    # an empty tree is missing every anchor: the JSON document must
    # carry the findings with the documented keys, and the exit status
    # must still be the findings truth
    proc = subprocess.run(
        [sys.executable, "-m", "hotstuff_tpu.analysis",
         "--root", str(tmp_path), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    import json as _json

    doc = _json.loads(proc.stdout)
    assert doc["clean"] is False and doc["findings"]
    assert set(doc["findings"][0]) == {"rule", "file", "line", "evidence"}


# ---------------------------------------------------------------------------
# graftsync: shared parse/read caches
# ---------------------------------------------------------------------------

def test_parse_cache_returns_one_tree_per_path_source_pair():
    from hotstuff_tpu.analysis import common

    common.clear_caches()
    src_a = "x = 1\n"
    t1 = common.parse_source(src_a, "a.py")
    assert common.parse_source(src_a, "a.py") is t1
    # a DIFFERENT source under the same path (test fixtures do this
    # constantly) must not collide
    t2 = common.parse_source("x = 2\n", "a.py")
    assert t2 is not t1
    # nor the same source under a different path
    assert common.parse_source(src_a, "b.py") is not t1
    common.clear_caches()
    assert common.parse_source(src_a, "a.py") is not t1


# ---------------------------------------------------------------------------
# tier-2: the TSan gate (curated subset + clockwait shim)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # instrumented native build: minutes when cold
def test_tsan_gate_runs_curated_test_clean():
    if shutil.which("g++") is None and shutil.which("cmake") is None:
        pytest.skip("no C++ toolchain in this environment")
    script = os.path.join(REPO, "scripts", "tsan_gate.sh")
    proc = subprocess.run(
        [script, "serde", "store"], cwd=REPO,
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-4000:]
    # the gate's own success line (the "all tests clean" line below it
    # is printed only by the no-cmake g++ fallback, not the ctest path)
    assert "tsan_gate: clean in" in proc.stdout


# ---------------------------------------------------------------------------
# guard rules (graftguard: unsupervised-launch)
# ---------------------------------------------------------------------------

def _guard_findings(src, path="hotstuff_tpu/sidecar/service.py"):
    from hotstuff_tpu.analysis import guardlint

    return guardlint.check_sources({path: textwrap.dedent(src)})


def test_unsupervised_launch_flags_bare_future_wait():
    findings = _guard_findings("""
        def _dispatch_one(self, packing, inflight):
            batch, fut = packing.popleft()
            fetch = fut.result()()
    """)
    assert [f.rule for f in findings] == ["unsupervised-launch"]
    assert ".result()" in findings[0].message


def test_unsupervised_launch_flags_unbounded_event_wait():
    findings = _guard_findings("""
        def _drain(self, ev):
            ev.wait()
    """)
    assert [f.rule for f in findings] == ["unsupervised-launch"]


def test_unsupervised_launch_clean_through_guard_helper():
    assert _guard_findings("""
        def _dispatch_one(self, packing, inflight):
            batch, fut = packing.popleft()
            fetch = self._guarded("k", lambda: fut.result()())

        def _drain_one(self, inflight):
            batch, fetch, t0, key = inflight.popleft()
            mask = self._guarded(key, fetch)
    """) == []


def test_unsupervised_launch_clean_through_guard_call():
    assert _guard_findings("""
        def _canary(self):
            return self._guard.call("canary:8", lambda: fut.result())
    """) == []


def test_unsupervised_launch_bounded_waits_are_legal():
    assert _guard_findings("""
        def _run(self, packing, ev):
            packing[0][1].exception(timeout=0.25)
            ev.wait(0.2)
            fut.result(timeout=1.0)
    """) == []


def test_unsupervised_launch_suppression_needs_justification():
    src = """
        def call(self, call):
            # bounded by construction: the monitor sets the event
            # graftlint: disable=unsupervised-launch
            call.done.wait()
    """
    assert _guard_findings(src) == []
    # the same wait WITHOUT the suppression is a finding
    bare = src.replace("# graftlint: disable=unsupervised-launch\n", "")
    assert [f.rule for f in _guard_findings(bare)] == \
        ["unsupervised-launch"]


def test_unsupervised_launch_dot_call_on_non_guard_not_exempt():
    # .call on something that is not a guard supervises nothing
    findings = _guard_findings("""
        def f(self, runner, fut):
            runner.call("k", lambda: 1)
            return fut.result()
    """)
    assert [f.rule for f in findings] == ["unsupervised-launch"]


def test_guard_checker_real_tree_is_clean():
    from hotstuff_tpu.analysis import guardlint

    assert guardlint.check(REPO) == []


# ---------------------------------------------------------------------------
# ring rules (graftcadence: blocking-call-in-ring-tick)
# ---------------------------------------------------------------------------

def _ring_findings(src, path="hotstuff_tpu/sidecar/ring.py"):
    from hotstuff_tpu.analysis import ringlint

    return ringlint.check_sources({path: textwrap.dedent(src)})


def test_ring_rule_flags_unbounded_wait_in_tick_body():
    findings = _ring_findings("""
        class CadenceRing:
            def _collect_oldest(self):
                fl = self._pending.popleft()
                return fl.fetch.result()
    """)
    assert [f.rule for f in findings] == ["blocking-call-in-ring-tick"]
    assert ".result()" in findings[0].message


def test_ring_rule_flags_fresh_compile_entry_in_tick_body():
    findings = _ring_findings("""
        class CadenceRing:
            def _arm(self, launch):
                from ..crypto import eddsa
                return eddsa.verify_batch(msgs, pks, sigs)
    """)
    assert [f.rule for f in findings] == ["blocking-call-in-ring-tick"]
    assert "verify_batch" in findings[0].message
    assert "compile" in findings[0].message


def test_ring_rule_guard_entry_subtrees_are_supervised():
    assert _ring_findings("""
        class CadenceRing:
            def _arm(self, launch):
                fut = self.engine._pack_pool.submit(self.engine._pack,
                                                    launch.items)
                return self.engine._guarded("tick:8",
                                            lambda: fut.result()())

            def _collect_oldest(self):
                fl = self._pending.popleft()
                return self.engine._guarded(fl.key, fl.fetch)
    """) == []


def test_ring_rule_bounded_waits_are_legal():
    assert _ring_findings("""
        class CadenceRing:
            def run(self):
                self._wait(0.002)
                self.engine._stopped.wait(timeout=0.25)
    """) == []


def test_ring_rule_ignores_non_ring_classes():
    # The staged engine may block (its deadline class tolerates it);
    # the rule scopes to ring classes only.
    assert _ring_findings("""
        class VerifyEngine:
            def _dispatch_one(self, fut):
                return fut.result()

        def module_level(fut):
            return fut.result()
    """) == []


def test_ring_checker_registered_and_real_tree_is_clean():
    from hotstuff_tpu.analysis import ringlint
    from hotstuff_tpu.analysis.__main__ import CHECKERS

    assert "ring" in CHECKERS
    assert ringlint.check(REPO) == []


# ---------------------------------------------------------------------------
# grafttaint: verification-gate provenance (wire -> gate -> consensus sink)
# ---------------------------------------------------------------------------

from hotstuff_tpu.analysis import taint
from hotstuff_tpu.analysis.__main__ import findings_json

TAINT_FIXTURES = os.path.join(REPO, "tests", "fixtures", "taint")


def _taint_fixture(name):
    with open(os.path.join(TAINT_FIXTURES, name), encoding="utf-8") as fh:
        src = fh.read()
    if name.endswith(".py"):
        return taint.check_sources({name: src})
    return taint.check_sources({}, {name: src})


def test_taint_wire_to_verdict_sink_without_gate():
    findings = _taint_fixture("bad_sink.py")
    assert [f.rule for f in findings] == ["unverified-flow-to-sink"]
    assert "verdict-emission" in findings[0].message
    assert "bad_sink.py:14" in findings[0].message  # the read_frame origin


def test_taint_dead_gate_is_unreachable_sanitizer():
    findings = _taint_fixture("dead_gate.py")
    assert [(f.rule, f.line) for f in findings] == \
        [("unreachable-sanitizer", 9)]
    assert "check_frame" in findings[0].message


def test_taint_verify_shaped_call_needs_annotation():
    findings = _taint_fixture("unannotated.py")
    assert [f.rule for f in findings] == ["unannotated-gate"]
    assert "verify_payload" in findings[0].message


def test_taint_cxx_deserialize_to_commit_without_gate():
    findings = _taint_fixture("bad_core.cpp")
    assert [f.rule for f in findings] == ["unverified-flow-to-sink"]
    assert "commit" in findings[0].message


def test_taint_mutation_dropped_verify_fires_both_rules():
    # Deleting the one verify call produces BOTH signals: the QC flows
    # to process_qc ungated, and the declared gate is never called.
    findings = _taint_fixture("mutation_dropped_verify.cpp")
    assert sorted(f.rule for f in findings) == \
        ["unreachable-sanitizer", "unverified-flow-to-sink"]


def test_taint_mutation_reordered_admission_before_gate():
    findings = _taint_fixture("mutation_reordered.py")
    assert [f.rule for f in findings] == ["unverified-flow-to-sink"]
    assert "device-launch-pack" in findings[0].message


def test_taint_gate_call_clears_the_same_flow():
    # The un-mutated shape of mutation_reordered.py: gate first, then
    # pack — the identical sink call is now a PROVEN path, not a finding.
    with open(os.path.join(TAINT_FIXTURES, "mutation_reordered.py"),
              encoding="utf-8") as fh:
        src = fh.read()
    fixed = src.replace(
        "    engine.submit(payload, None)\n"
        "    opcode, req = decode_request(payload)\n",
        "    opcode, req = decode_request(payload)\n"
        "    engine.submit(payload, None)\n")
    assert fixed != src
    findings, mapdoc = taint.analyze_sources(
        {"mutation_reordered.py": fixed}, {})
    assert findings == []
    assert mapdoc["sinks_covered"] == {"device-launch-pack": 1}
    (path,) = mapdoc["paths"]
    assert path["gates"] == ["frame-structure"]


def test_taint_suppression_silences_with_rationale():
    with open(os.path.join(TAINT_FIXTURES, "bad_sink.py"),
              encoding="utf-8") as fh:
        src = fh.read()
    suppressed = src.replace(
        "    return proto.encode_reply(",
        "    # graftlint: disable=unverified-flow-to-sink (fixture)\n"
        "    return proto.encode_reply(")
    assert suppressed != src
    assert taint.check_sources({"bad_sink.py": suppressed}) == []


def test_taint_cxx_suppression_contract_matches_python():
    with open(os.path.join(TAINT_FIXTURES, "bad_core.cpp"),
              encoding="utf-8") as fh:
        src = fh.read()
    suppressed = src.replace(
        "  return commit(m.block);",
        "  // graftlint: disable=unverified-flow-to-sink (fixture)\n"
        "  return commit(m.block);")
    assert suppressed != src
    assert taint.check_sources({}, {"bad_core.cpp": suppressed}) == []


def test_taint_findings_json_golden():
    findings = _taint_fixture("mutation_dropped_verify.cpp")
    doc = findings_json(findings, ("taint",))
    assert doc["schema"] == "graftlint-findings-v1"
    assert doc["checkers"] == ["taint"]
    assert doc["clean"] is False
    assert [(f["rule"], f["file"], f["line"]) for f in doc["findings"]] == [
        ("unreachable-sanitizer", "mutation_dropped_verify.cpp", 8),
        ("unverified-flow-to-sink", "mutation_dropped_verify.cpp", 15),
    ]
    assert all(f["evidence"] for f in doc["findings"])


def test_taint_literal_reply_masks_are_exempt():
    # PING/CHAOS echoes reply with literal masks — not verdicts.
    assert taint.check_sources({"svc.py": textwrap.dedent("""\
        def handle(sock):
            payload = read_frame(sock)
            send(encode_reply(1, 2, []))
            send(encode_reply(1, 2, [0]))
    """)}) == []


def test_taint_cxx_digit_separator_does_not_eat_the_file():
    # 20'000 is a number, not a char literal: the functions after it
    # must still be scanned (regression: ingress.hpp lost its admit gate
    # to exactly this).
    findings = taint.check_sources({}, {"g.cpp": (
        "const size_t kBudget = 20'000;\n"
        "void Core::receive(const Bytes& raw) {\n"
        "  auto m = Message::deserialize(raw);\n"
        "  commit(m.block);\n"
        "}\n")})
    assert [f.rule for f in findings] == ["unverified-flow-to-sink"]


def test_taint_entry_meet_one_ungated_caller_poisons():
    # Two callers reach the same helper; only one gates.  The meet is
    # AND over verified-ness, so the helper's sink stays a finding.
    src = textwrap.dedent("""\
        # graftlint: sanitizes=device-verdict
        def check(req):
            return True

        def emit(req):
            return encode_reply(1, 2, req.verdicts)

        def gated(sock):
            req = read_frame(sock)
            check(req)
            return emit(req)

        def ungated(sock):
            req = read_frame(sock)
            return emit(req)
    """)
    findings = taint.check_sources({"svc.py": src})
    assert [f.rule for f in findings] == ["unverified-flow-to-sink"]
    # removing the ungated caller clears it
    clean = src[:src.index("def ungated")]
    assert taint.check_sources({"svc.py": clean}) == []


def test_taint_real_tree_is_clean():
    assert taint.check(REPO) == []


def test_taint_map_proves_the_required_sink_paths():
    py_sources, cxx_sources = {}, {}
    for rel in taint.DEFAULT_TARGETS:
        with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
            src = fh.read()
        (py_sources if rel.endswith(".py") else cxx_sources)[rel] = src
    findings, mapdoc = taint.analyze_sources(py_sources, cxx_sources)
    assert findings == []
    assert mapdoc["schema"] == "grafttaint-map-v1"
    assert mapdoc["clean"] is True
    # the PR's acceptance bar: at least one PROVEN wire->gate->sink path
    # through each consensus-critical sink
    for sink in ("qc-accept", "tc-assembly", "mempool-admission",
                 "verdict-emission", "commit", "store-write",
                 "device-launch-pack"):
        assert mapdoc["sinks_covered"].get(sink, 0) >= 1, sink
    # every path names its gates and its wire origin
    for p in mapdoc["paths"]:
        assert p["gates"], p
        assert ":" in p["source"], p
        assert p["via"], p


def test_taint_must_cover_pins():
    from hotstuff_tpu.analysis.__main__ import check_coverage

    assert check_coverage(REPO, [
        "taint:native/src/consensus/core.cpp",
        "taint:hotstuff_tpu/sidecar/protocol.py",
    ]) == []
    bad = check_coverage(REPO, ["taint:hotstuff_tpu/obs.py"])
    assert [f.rule for f in bad] == ["must-cover"]


# ---------------------------------------------------------------------------
# tenant-unscoped-queue (graftfleet DRR lane discipline)
# ---------------------------------------------------------------------------

SCHED_MOD = "hotstuff_tpu/sidecar/sched/classes.py"


def test_tenant_queue_fires_on_raw_deque_ops_and_head_peek():
    from hotstuff_tpu.analysis import tenantlint

    findings = tenantlint.check_sources({SCHED_MOD: textwrap.dedent("""
        class ClassQueue:
            def pop(self):
                return self.items.popleft()

            def requeue(self, p):
                self._order.appendleft(p)

            def peek_second(self):
                return self.items[1]
        """)})
    assert [f.rule for f in findings] == ["tenant-unscoped-queue"] * 3
    assert "DRR tenant lanes" in findings[0].message
    assert "peeks past the DRR head" in findings[2].message


def test_tenant_queue_quiet_on_lane_routed_scheduler():
    from hotstuff_tpu.analysis import tenantlint

    # The real discipline: class-queue SELECTION is a dict subscript
    # (fine), ordering decisions route through the tenantq helpers,
    # and value-object containers (launch.items) are data plumbing.
    findings = tenantlint.check_sources({SCHED_MOD: textwrap.dedent("""
        class Scheduler:
            def next_launch(self):
                q = self._queues[LATENCY]
                head = q.lanes.head_locked()
                if head is None:
                    return None
                return q.lanes.pop_next_locked()

            def pad_accounting(self, launch):
                return len(launch.items[0].request.msgs)
        """)})
    assert findings == []


def test_tenant_queue_exempts_tenantq_and_honors_suppression():
    from hotstuff_tpu.analysis import tenantlint

    raw = textwrap.dedent("""
        class TenantLanes:
            def pop_next_locked(self):
                return self.order.popleft()
        """)
    # tenantq.py IS the audited lane implementation: exempt wholesale.
    assert tenantlint.check_sources(
        {"hotstuff_tpu/sidecar/sched/tenantq.py": raw}) == []
    # Elsewhere the same code fires...
    assert len(tenantlint.check_sources({SCHED_MOD: raw})) == 1
    # ...unless carrying a worked inline suppression.
    suppressed = textwrap.dedent("""
        class Drain:
            def flush(self):
                # graftlint: disable=tenant-unscoped-queue (shutdown drain-all: fairness moot)
                return self.order.popleft()
        """)
    assert tenantlint.check_sources({SCHED_MOD: suppressed}) == []


def test_tenant_queue_quiet_on_real_tree():
    from hotstuff_tpu.analysis import tenantlint

    assert tenantlint.check(REPO) == []

"""Remote-surface tests: command/config generation and CLI wiring for the
multi-host benchmark (benchmark/benchmark/remote.py:31-300 capability) —
no ssh is performed; the RemoteRunner is stubbed to record commands.
"""

import json

import pytest

from hotstuff_tpu.harness.aggregate import LogAggregator
from hotstuff_tpu.harness.remote import Bench, RemoteRunner
from hotstuff_tpu.harness.settings import Settings, SettingsError


SETTINGS = {
    "testbed": "t",
    "key": {"name": "k", "path": "/tmp/k.pem"},
    "ports": {"consensus": 8000, "mempool": 7000, "front": 6000},
    "repo": {"name": "repo", "url": "https://x/r.git", "branch": "main"},
    "instances": {"type": "m5d.8xlarge", "regions": ["us-east-1"]},
    "hosts": ["10.0.0.1", "10.0.0.2", "10.0.0.3", "10.0.0.4"],
}


@pytest.fixture
def settings(tmp_path):
    path = tmp_path / "settings.json"
    path.write_text(json.dumps(SETTINGS))
    return Settings.load(str(path))


def test_settings_load_and_validation(settings, tmp_path):
    assert settings.base_port == 8000
    assert settings.repo_name == "repo"
    assert settings.aws_regions == ["us-east-1"]
    with pytest.raises(SettingsError):
        Settings.load(str(tmp_path / "missing.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    with pytest.raises(SettingsError):
        Settings.load(str(bad))


class RecordingRunner(RemoteRunner):
    """Records every command instead of ssh-ing."""

    def __init__(self):
        super().__init__("ubuntu", "/tmp/k.pem")
        self.commands = []   # (host, command)
        self.uploads = []    # (host, local, remote)

    def run(self, host, command, check=True, hide=True):
        self.commands.append((host, command))

    def run_background(self, host, command, log_file):
        self.commands.append((host, f"BG[{log_file}] {command}"))

    def put(self, host, local, remote):
        self.uploads.append((host, local, remote))

    def get(self, host, remote, local):
        pass


def test_install_and_update_commands(settings):
    bench = Bench(settings, SETTINGS["hosts"])
    bench.runner = runner = RecordingRunner()
    bench.install()
    assert len(runner.commands) == 4
    assert all("apt-get" in c and "git clone" in c
               for _, c in runner.commands)
    runner.commands.clear()
    bench.update()
    assert all("git checkout -f main" in c and "cmake" in c
               for _, c in runner.commands)


def test_run_single_spawns_nodes_and_clients(settings, tmp_path, monkeypatch):
    """One node + one client per alive host; faulty hosts run nothing;
    clients wait only on alive fronts (remote.py:179-225 analogue)."""
    monkeypatch.chdir(tmp_path)
    hosts = SETTINGS["hosts"]
    bench = Bench(settings, hosts)
    bench.runner = runner = RecordingRunner()

    class FakeCommittee:
        def front_addresses(self):
            return [f"{h}:6000" for h in hosts]

    import hotstuff_tpu.harness.remote as remote_mod
    monkeypatch.setattr(remote_mod, "sleep", lambda s: None, raising=False)
    # _run_single sleeps for the bench duration; neutralize it.
    import time as _time
    monkeypatch.setattr(_time, "sleep", lambda s: None)

    bench._run_single(hosts, FakeCommittee(), rate=1000, tx_size=512,
                      faults=1, duration=0, timeout=5_000)
    bg = [c for _, c in runner.commands if c.startswith("BG[")]
    node_cmds = [c for c in bg if "./node run" in c]
    client_cmds = [c for c in bg if "./client " in c]
    assert len(node_cmds) == 3 and len(client_cmds) == 3  # 4 hosts - 1 fault
    # Clients split the rate over alive nodes (ceil(1000/3) = 334) and wait
    # only on alive fronts.
    assert all("--rate 334" in c for c in client_cmds)
    assert all("10.0.0.4" not in c for c in client_cmds)
    # The kill sweep hits every host, including the faulty one.
    kills = [h for h, c in runner.commands if "pkill" in c]
    assert set(kills) == set(hosts)


def test_cli_parses_remote_subcommands():
    """CLI surface parity with the reference fabfile (fabfile.py:92-155):
    remote/install/kill/create/destroy/start/stop/info all parse."""
    from hotstuff_tpu.harness.__main__ import main

    # argparse exits with code 2 on unknown commands; these must all parse
    # and then fail cleanly on the missing settings file (exit 1, not a
    # traceback).
    for cmd in ("remote", "install", "kill", "create", "destroy", "start",
                "stop", "info"):
        with pytest.raises(SystemExit) as e:
            main([cmd, "--settings", "/nonexistent.json"])
        assert e.value.code == 1, cmd


def test_cli_invalid_bench_parameters_exit_cleanly(tmp_path):
    """ConfigError from BenchParameters must exit 1, not traceback."""
    from hotstuff_tpu.harness.__main__ import main

    path = tmp_path / "settings.json"
    path.write_text(json.dumps(SETTINGS))
    with pytest.raises(SystemExit) as e:
        main(["remote", "--settings", str(path), "--nodes", "1"])
    assert e.value.code == 1


def test_aggregator_rejects_zero_runs(tmp_path, monkeypatch):
    """Failed runs (Execution time: 0 s / 0 TPS) must not poison series."""
    monkeypatch.chdir(tmp_path)
    (tmp_path / "results").mkdir()
    good = (
        "-----------------------------------------\n SUMMARY:\n"
        " + CONFIG:\n Faults: 0 nodes\n Committee size: 4 nodes\n"
        " Input rate: 1,000 tx/s\n Transaction size: 512 B\n"
        " Execution time: 10 s\n\n + RESULTS:\n"
        " End-to-end TPS: 900 tx/s\n End-to-end latency: 50 ms\n"
    )
    dead = good.replace("Execution time: 10 s", "Execution time: 0 s") \
               .replace("End-to-end TPS: 900", "End-to-end TPS: 0")
    (tmp_path / "results" / "bench-0-4-1000-512.txt").write_text(good + dead)
    agg = LogAggregator()
    assert len(agg.records) == 1
    (result,) = agg.records.values()
    assert result.mean_tps == 900  # the dead run did not drag the mean down

"""Remote-surface tests: command/config generation, CLI wiring, and the
graftwan orchestration of the multi-host benchmark
(benchmark/benchmark/remote.py:31-300 capability) — no real ssh is
performed; the RemoteRunner is either stubbed to record commands or
pointed at a local ``sh -c`` transport that executes them for real.
"""

import json
import shlex
import subprocess

import pytest

from hotstuff_tpu.harness.aggregate import LogAggregator
from hotstuff_tpu.harness.remote import Bench, ExecutionError, RemoteRunner
from hotstuff_tpu.harness.settings import Settings, SettingsError


SETTINGS = {
    "testbed": "t",
    "key": {"name": "k", "path": "/tmp/k.pem"},
    "ports": {"consensus": 8000, "mempool": 7000, "front": 6000},
    "repo": {"name": "repo", "url": "https://x/r.git", "branch": "main"},
    "instances": {"type": "m5d.8xlarge", "regions": ["us-east-1"]},
    "hosts": ["10.0.0.1", "10.0.0.2", "10.0.0.3", "10.0.0.4"],
}


@pytest.fixture
def settings(tmp_path):
    path = tmp_path / "settings.json"
    path.write_text(json.dumps(SETTINGS))
    return Settings.load(str(path))


def test_settings_load_and_validation(settings, tmp_path):
    assert settings.base_port == 8000
    assert settings.repo_name == "repo"
    assert settings.aws_regions == ["us-east-1"]
    with pytest.raises(SettingsError):
        Settings.load(str(tmp_path / "missing.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    with pytest.raises(SettingsError):
        Settings.load(str(bad))


class RecordingRunner(RemoteRunner):
    """Records every command instead of ssh-ing (kwargs mirror the real
    signatures so orchestration code can pass timeouts/append)."""

    def __init__(self):
        super().__init__("ubuntu", "/tmp/k.pem")
        self.commands = []   # (host, command)
        self.uploads = []    # (host, local, remote)

    def run(self, host, command, check=True, hide=True, timeout=None):
        self.commands.append((host, command))

    def run_background(self, host, command, log_file, append=False,
                       timeout=None):
        tag = "BGA" if append else "BG"
        self.commands.append((host, f"{tag}[{log_file}] {command}"))
        self.last_background_timeout = timeout

    def put(self, host, local, remote, timeout=None):
        self.uploads.append((host, local, remote))

    def get(self, host, remote, local, timeout=None):
        pass


class LocalShellRunner(RemoteRunner):
    """Fake ssh transport that really executes: `_ssh_base` resolves to
    a local ``sh -c`` instead of an ssh argv, so the quoting/timeout
    behavior of run/run_background is tested against a real shell."""

    def __init__(self):
        super().__init__("nobody", "/dev/null")

    def _ssh_base(self, host):
        return ["sh", "-c"]


# ---------------------------------------------------------------------------
# RemoteRunner transport discipline (quoting + timeouts)
# ---------------------------------------------------------------------------


def test_run_background_quoting_survives_single_quotes(tmp_path):
    """The graftwan regression: boot commands legitimately carry single
    quotes (pkill patterns, --nodes lists); the old ``sh -c '{cmd}'``
    wrapper broke on every one.  Through a REAL shell, the quoted
    wrapper must execute the command verbatim."""
    runner = LocalShellRunner()
    out = tmp_path / "out.log"
    runner.run_background(
        "h", f"printf '%s' \"it's quoted\"", str(out))
    deadline = __import__("time").monotonic() + 5
    while __import__("time").monotonic() < deadline:
        if out.exists() and out.read_text() == "it's quoted":
            break
        __import__("time").sleep(0.05)
    assert out.read_text() == "it's quoted"


def test_run_background_append_mode_preserves_prior_log(tmp_path):
    """Fault-plan restarts reboot on the same log in APPEND mode: the
    pre-fault log is parser evidence and must survive."""
    runner = LocalShellRunner()
    out = tmp_path / "node.log"
    out.write_text("before-fault\n")
    runner.run_background("h", "echo after-fault", str(out), append=True)
    deadline = __import__("time").monotonic() + 5
    while __import__("time").monotonic() < deadline:
        if "after-fault" in (out.read_text() if out.exists() else ""):
            break
        __import__("time").sleep(0.05)
    assert out.read_text() == "before-fault\nafter-fault\n"


def test_run_times_out_on_hung_remote_command():
    """ssh ConnectTimeout bounds the dial, not a hung remote command;
    the subprocess timeout must surface a wedged host as an error."""
    runner = LocalShellRunner()
    with pytest.raises(ExecutionError) as exc:
        runner.run("h", "sleep 30", timeout=0.2)
    assert "hung past" in str(exc.value)
    # A healthy command inside the bound returns its result.
    result = runner.run("h", "echo ok")
    assert result.returncode == 0 and "ok" in result.stdout


def test_run_background_wrapper_is_shell_parseable():
    """The wrapped background command must stay ONE well-formed shell
    word list even for hostile payloads (quotes, globs, redirects)."""

    class WrapperRecorder(RemoteRunner):
        """Real run_background wrapper; only the transport is stubbed."""

        def __init__(self):
            super().__init__("ubuntu", "/tmp/k.pem")
            self.commands = []

        def run(self, host, command, check=True, hide=True, timeout=None):
            self.commands.append((host, command))

    runner = WrapperRecorder()
    cmd = "pkill -f './node run' && echo \"done\" ; ls *"
    runner.run_background("h", cmd, "/tmp/l.log")
    _, wrapped = runner.commands[-1]
    # Strip only the TRAILING backgrounding '&' (the payload's own '&&'
    # must survive inside the quoted argv element), then shlex round
    # trip: the command is a single sh -c argument, bit-identical.
    assert wrapped.rstrip().endswith("&")
    words = shlex.split(wrapped.rstrip().rstrip("&"))
    assert words[0] == "nohup" and words[3] == "-c"
    assert words[4] == cmd


# ---------------------------------------------------------------------------
# Bench orchestration
# ---------------------------------------------------------------------------


def test_install_and_update_commands(settings):
    bench = Bench(settings, SETTINGS["hosts"])
    bench.runner = runner = RecordingRunner()
    bench.install()
    assert len(runner.commands) == 4
    assert all("apt-get" in c and "git clone" in c
               for _, c in runner.commands)
    runner.commands.clear()
    bench.update()
    assert all("git checkout -f main" in c and "cmake" in c
               for _, c in runner.commands)


class FakeCommittee:
    def __init__(self, hosts):
        self.hosts = hosts

    def front_addresses(self):
        return [f"{h}:6000" for h in self.hosts]


def test_run_single_spawns_nodes_and_clients(settings, tmp_path, monkeypatch):
    """One node + one client per alive host; faulty hosts run nothing;
    clients wait only on alive fronts (remote.py:179-225 analogue)."""
    monkeypatch.chdir(tmp_path)
    hosts = SETTINGS["hosts"]
    bench = Bench(settings, hosts)
    bench.runner = runner = RecordingRunner()

    import hotstuff_tpu.harness.remote as remote_mod
    monkeypatch.setattr(remote_mod, "sleep", lambda s: None)

    bench._run_single(hosts, FakeCommittee(hosts), rate=1000, tx_size=512,
                      faults=1, duration=0, timeout=5_000)
    bg = [c for _, c in runner.commands if c.startswith("BG")]
    node_cmds = [c for c in bg if "./node run" in c]
    client_cmds = [c for c in bg if "./client " in c]
    assert len(node_cmds) == 3 and len(client_cmds) == 3  # 4 hosts - 1 fault
    # Clients split the rate over alive nodes (ceil(1000/3) = 334) and wait
    # only on alive fronts.
    assert all("--rate 334" in c for c in client_cmds)
    assert all("10.0.0.4" not in c for c in client_cmds)
    # The kill sweep hits every host, including the faulty one.
    kills = [h for h, c in runner.commands if "pkill" in c]
    assert set(kills) == set(hosts)


def test_run_single_executes_fault_plan_and_wan(settings, tmp_path,
                                                monkeypatch):
    """graftwan ordering: tc shaping installs BEFORE any node boots,
    plan events run inside the run window (after boot, before the kill
    sweep), executed events come back for the log step, and teardown
    clears the qdiscs even though the plan faulted a link mid-run."""
    monkeypatch.chdir(tmp_path)
    hosts = SETTINGS["hosts"]
    bench = Bench(settings, hosts,
                  fault_plan="0.05 node:1 kill; 0.1 link:ab partition; "
                             "0.15 link:ab heal",
                  wan="node:0>node:1 latency_ms=40 name=ab")
    bench.runner = runner = RecordingRunner()

    import hotstuff_tpu.harness.remote as remote_mod
    real_sleep = __import__("time").sleep
    monkeypatch.setattr(remote_mod, "sleep",
                        lambda s: real_sleep(min(s, 0.6)))

    events = bench._run_single(hosts, FakeCommittee(hosts), rate=1000,
                               tx_size=512, faults=0, duration=1,
                               timeout=100)
    assert [e["action"] for e in events] == ["kill", "partition", "heal"]
    assert all(e["ok"] for e in events), events
    cmds = [c for _, c in runner.commands]

    def first(pred, start=0):
        return next(i for i, c in enumerate(cmds) if i >= start and pred(c))

    setup_tc = first(lambda c: "tc qdisc add" in c and "netem" in c)
    first_boot = first(lambda c: c.startswith("BG") and "./node run" in c)
    plan_kill = first(lambda c: "pkill -KILL" in c)
    partition = first(lambda c: "tc qdisc change" in c and "loss 100%" in c)
    heal = first(lambda c: "tc qdisc change" in c and "delay 40ms" in c)
    # setup itself opens with a best-effort del; the teardown we want is
    # the sweep-time one AFTER the heal.
    teardown_tc = first(lambda c: "tc qdisc del" in c, start=heal + 1)
    sweep = first(lambda c: "pkill -f '[.]/node run'" in c, start=heal + 1)
    assert setup_tc < first_boot < plan_kill < partition < heal
    assert heal < teardown_tc and heal < sweep
    # The plan's node kill targeted node 1's host, and only it.
    kill_hosts = [h for h, c in runner.commands if "pkill -KILL" in c]
    assert kill_hosts == ["10.0.0.2"]


def test_check_fault_plan_rejects_unexecutable_matrix(settings):
    """The LocalBench contract on the fleet: a scripted scenario the
    deployment cannot deliver fails BEFORE any host is touched."""
    from hotstuff_tpu.harness.utils import BenchError

    hosts = SETTINGS["hosts"]

    def check(plan=None, wan=None, duration=30, faults=0):
        bench = Bench(settings, hosts, fault_plan=plan, wan=wan)
        bench._check_fault_plan(hosts, duration, 5_000, faults=faults)

    check(plan="5 node:1 kill")  # executable: passes
    with pytest.raises(BenchError) as exc:
        check(plan="5 node:3 kill", faults=1)
    assert "crash-fault hosts run nothing" in str(exc.value)
    with pytest.raises(BenchError) as exc:
        check(plan="29 node:1 kill", duration=30)
    assert "headroom" in str(exc.value)
    with pytest.raises(BenchError) as exc:
        check(plan="5 sidecar kill; 8 sidecar restart")
    assert "local-harness only" in str(exc.value)
    with pytest.raises(BenchError) as exc:
        check(plan="5 link:xx partition; 8 link:xx heal",
              wan="node:0>node:1 latency_ms=10 name=ab")
    assert "does not name" in str(exc.value)
    with pytest.raises(BenchError):
        Bench(settings, hosts, fault_plan="nonsense")
    with pytest.raises(BenchError):
        Bench(settings, hosts, wan="nonsense")
    with pytest.raises(BenchError):
        Bench(settings, hosts, slos="warp-drive=1")


def test_check_wan_rejects_unrealizable_endpoints(settings):
    """tc shapes only node:<i> egress on the fleet; a spec naming
    sidecar/client (or a replica that will not boot) would compile to
    zero commands yet still be recorded as WAN-shaped (wan.json +
    parser notes) — a clean-LAN run published as a shaped measurement.
    The pre-flight must reject it before any host is touched."""
    from hotstuff_tpu.harness.utils import BenchError

    hosts = SETTINGS["hosts"]

    def check(wan, faults=0):
        Bench(settings, hosts, wan=wan)._check_wan(hosts, faults=faults)

    check("node:0>node:1 latency_ms=40 name=ab")  # realizable: passes
    check("*>node:1 latency_ms=40 name=wild")     # wildcard src is fine
    with pytest.raises(BenchError) as exc:
        check("node:0>sidecar latency_ms=100 name=sc")
    assert "local-harness only" in str(exc.value)
    with pytest.raises(BenchError):
        check("client>node:0 latency_ms=100 name=cl")
    with pytest.raises(BenchError) as exc:  # dst beyond the alive fleet
        check("node:0>node:3 latency_ms=40 name=dead", faults=1)
    assert "node:0..node:2" in str(exc.value)


def test_run_keeps_matrix_going_and_evidence_when_plan_stalls(
        settings, monkeypatch):
    """A stalled fault plan in one cell must not abort the whole
    matrix, and the under-executed run's logs are STILL downloaded —
    the partial chaos-events.json is the diagnosis evidence."""
    from hotstuff_tpu.harness.config import BenchParameters, NodeParameters

    hosts = SETTINGS["hosts"]
    bench = Bench(settings, hosts, fault_plan="1 node:0 kill")
    bench.runner = RecordingRunner()
    calls = {"run_single": 0, "logs": 0, "printed": 0}

    def fake_run_single(*a, **k):
        calls["run_single"] += 1
        return []  # plan stalled: 0 of 1 events executed

    class FakeParser:
        def print(self, filename):
            calls["printed"] += 1

    def fake_logs(hosts, faults, chaos_events=None):
        calls["logs"] += 1
        assert chaos_events == []  # the partial evidence is persisted
        return FakeParser()

    monkeypatch.setattr(bench, "_config",
                        lambda hosts, params: FakeCommittee(hosts))
    monkeypatch.setattr(bench, "_run_single", fake_run_single)
    monkeypatch.setattr(bench, "_logs", fake_logs)
    bench_params = BenchParameters({
        "nodes": [4], "rate": [1_000, 2_000], "tx_size": 512,
        "faults": 0, "duration": 30})
    node_params = NodeParameters({
        "consensus": {"timeout_delay": 1_000, "sync_retry_delay": 5_000},
        "mempool": {"gc_depth": 50, "sync_retry_delay": 5_000,
                    "sync_retry_nodes": 3, "batch_size": 100,
                    "max_batch_delay": 100}})
    bench.run(bench_params, node_params)  # must NOT raise
    # Both rate cells ran despite the first one's stalled plan, every
    # cell's logs were downloaded before the verdict — and NO result
    # file was published (a run whose scenario never finished must not
    # aggregate as a passing chaos cell).
    assert calls == {"run_single": 2, "logs": 2, "printed": 0}


def test_logs_persists_chaos_context(settings, tmp_path, monkeypatch):
    """The downloaded logs dir gets the same on-disk contract the local
    harness writes (chaos-events.json / wan.json / slo.json), and the
    parser judges the fleet run through it — recovery latencies AND SLO
    verdicts from golden logs."""
    from test_harness import GOLDEN_CLIENT, GOLDEN_NODE
    from datetime import datetime, timezone

    monkeypatch.chdir(tmp_path)
    hosts = SETTINGS["hosts"][:1]
    bench = Bench(settings, hosts,
                  wan="node:0>node:1 latency_ms=40 name=ab",
                  slos={"node-kill": 9_000})

    class GetRunner(RecordingRunner):
        def get(self, host, remote, local, timeout=None):
            content = GOLDEN_NODE if "node" in local else GOLDEN_CLIENT
            with open(local, "w") as f:
                f.write(content)

    bench.runner = GetRunner()
    wall = datetime(2026, 7, 29, 14, 54, 57, 0,
                    tzinfo=timezone.utc).timestamp() - 0.1
    events = [{"t": 5.0, "target": "node:0", "action": "kill",
               "wall": wall, "ok": True}]
    parser = bench._logs(hosts, faults=0, chaos_events=events)
    out = parser.result()
    assert "Chaos SLO node-kill" in out and "PASS" in out
    assert "WAN: 1 shaped link(s)" in out
    assert json.load(open("logs/chaos-events.json")) == events
    assert json.load(open("logs/wan.json"))["links"][0]["name"] == "ab"
    assert json.load(open("logs/slo.json"))["node-kill"] == 9_000


# ---------------------------------------------------------------------------
# RemoteFaultInjector
# ---------------------------------------------------------------------------


def _injector(runner, wan=None, **kwargs):
    from hotstuff_tpu.chaos import parse_wan
    from hotstuff_tpu.harness.faults import RemoteFaultInjector

    hosts = SETTINGS["hosts"]
    return RemoteFaultInjector(
        runner, hosts, "repo",
        {i: (f"./node run --keys .node-{i}.json", f"repo/logs/node-{i}.log")
         for i in range(len(hosts))},
        wan=parse_wan(wan) if wan else None,
        peers={f"node:{i}": h for i, h in enumerate(hosts)}, **kwargs)


def _ev(target, action, params=None):
    from hotstuff_tpu.chaos.plan import FaultEvent

    return FaultEvent(t=0.0, target=target, action=action,
                      params=params or {})


def test_remote_injector_node_signals_and_restart():
    runner = RecordingRunner()
    inj = _injector(runner)
    inj.apply(_ev("node:2", "kill"))
    inj.apply(_ev("node:1", "pause"))
    inj.apply(_ev("node:0", "restart"))
    cmds = dict(host=[h for h, _ in runner.commands],
                text=[c for _, c in runner.commands])
    # The bracketed-dot pattern must never match the ssh wrapper
    # shell's own cmdline (a -STOP that hits the wrapper parks the
    # ssh session until the transport timeout).
    assert ("10.0.0.3", "pkill -KILL -f '[.]/node run'") in runner.commands
    assert ("10.0.0.2", "pkill -STOP -f '[.]/node run'") in runner.commands
    import re

    for _, c in runner.commands:
        if "pkill" in c:
            pat = c.split("-f ", 1)[1].strip("'")
            assert not re.search(pat, c), f"self-matching pkill: {c}"
    # restart re-runs the recorded boot in APPEND mode on its own host,
    # under the injection bound — never the transport's install-sized
    # default (a wedged host must fail the EVENT, not park the runner).
    assert any(h == "10.0.0.1" and c.startswith("BGA[repo/logs/node-0.log]")
               for h, c in runner.commands)
    assert runner.last_background_timeout == inj.INJECT_TIMEOUT_S
    # cleanup SIGCONTs the paused straggler
    inj.cleanup()
    assert ("10.0.0.2", "pkill -CONT -f '[.]/node run'") in runner.commands


def test_remote_injector_failures_are_injection_errors():
    from hotstuff_tpu.harness.faults import InjectionError

    class FailingRunner(RecordingRunner):
        def run(self, host, command, check=True, hide=True, timeout=None):
            raise ExecutionError(f"[{host}] boom")

    inj = _injector(FailingRunner())
    with pytest.raises(InjectionError):
        inj.apply(_ev("node:0", "kill"))
    with pytest.raises(InjectionError):  # out-of-fleet index
        _injector(RecordingRunner()).apply(_ev("node:9", "kill"))
    with pytest.raises(InjectionError):  # restart without a boot record
        from hotstuff_tpu.harness.faults import RemoteFaultInjector

        RemoteFaultInjector(RecordingRunner(), ["10.0.0.1"], "repo",
                            {}).apply(_ev("node:0", "restart"))


def test_remote_injector_link_partition_heal_compiles_tc():
    runner = RecordingRunner()
    inj = _injector(runner, wan="node:0>node:1 latency_ms=40 name=ab")
    inj.apply(_ev("link:ab", "partition"))
    # Only node 0's egress carries the directed link.
    assert runner.commands == [
        ("10.0.0.1", "sudo tc qdisc change dev eth0 parent 1:4 "
                     "handle 40: netem loss 100%")]
    runner.commands.clear()
    inj.apply(_ev("link:ab", "heal"))
    assert runner.commands == [
        ("10.0.0.1", "sudo tc qdisc change dev eth0 parent 1:4 "
                     "handle 40: netem delay 40ms")]


def test_remote_injector_link_and_sidecar_need_configuration():
    from hotstuff_tpu.harness.faults import InjectionError

    inj = _injector(RecordingRunner())  # no wan, no sidecar host
    with pytest.raises(InjectionError) as exc:
        inj.apply(_ev("link:ab", "partition"))
    assert "shapes no WAN" in str(exc.value)
    with pytest.raises(InjectionError) as exc:
        inj.apply(_ev("sidecar", "kill"))
    assert "runs none" in str(exc.value)

    runner = RecordingRunner()
    inj = _injector(runner, sidecar_host="10.0.0.9",
                    sidecar_boot=("python -m hotstuff_tpu.sidecar",
                                  "repo/logs/sidecar.log"))
    inj.apply(_ev("sidecar", "kill"))
    inj.apply(_ev("sidecar", "restart"))
    inj.apply(_ev("sidecar", "degrade", {"delay_ms": 100}))
    texts = [c for _, c in runner.commands]
    assert any("pkill -KILL" in c for c in texts)
    assert any(c.startswith("BGA[repo/logs/sidecar.log]") for c in texts)
    # the chaos RPC originates next to the sidecar, on its host
    rpc = [c for h, c in runner.commands if h == "10.0.0.9"
           and "SidecarClient" in c]
    assert rpc and "delay_ms" in rpc[0]


# ---------------------------------------------------------------------------
# CLI + aggregation
# ---------------------------------------------------------------------------


def test_cli_parses_remote_subcommands():
    """CLI surface parity with the reference fabfile (fabfile.py:92-155):
    remote/install/kill/create/destroy/start/stop/info all parse."""
    from hotstuff_tpu.harness.__main__ import main

    # argparse exits with code 2 on unknown commands; these must all parse
    # and then fail cleanly on the missing settings file (exit 1, not a
    # traceback).
    for cmd in ("remote", "install", "kill", "create", "destroy", "start",
                "stop", "info"):
        with pytest.raises(SystemExit) as e:
            main([cmd, "--settings", "/nonexistent.json"])
        assert e.value.code == 1, cmd
    # the graftwan surface parses too
    with pytest.raises(SystemExit) as e:
        main(["remote", "--settings", "/nonexistent.json",
              "--fault-plan", "5 node:0 kill",
              "--wan", "node:0>node:1 latency_ms=40 name=ab",
              "--slo", "node-kill=9000"])
    assert e.value.code == 1


def test_cli_invalid_bench_parameters_exit_cleanly(tmp_path):
    """ConfigError from BenchParameters must exit 1, not traceback."""
    from hotstuff_tpu.harness.__main__ import main

    path = tmp_path / "settings.json"
    path.write_text(json.dumps(SETTINGS))
    with pytest.raises(SystemExit) as e:
        main(["remote", "--settings", str(path), "--nodes", "1"])
    assert e.value.code == 1


def test_aggregator_rejects_zero_runs(tmp_path, monkeypatch):
    """Failed runs (Execution time: 0 s / 0 TPS) must not poison series."""
    monkeypatch.chdir(tmp_path)
    (tmp_path / "results").mkdir()
    good = (
        "-----------------------------------------\n SUMMARY:\n"
        " + CONFIG:\n Faults: 0 nodes\n Committee size: 4 nodes\n"
        " Input rate: 1,000 tx/s\n Transaction size: 512 B\n"
        " Execution time: 10 s\n\n + RESULTS:\n"
        " End-to-end TPS: 900 tx/s\n End-to-end latency: 50 ms\n"
    )
    dead = good.replace("Execution time: 10 s", "Execution time: 0 s") \
               .replace("End-to-end TPS: 900", "End-to-end TPS: 0")
    (tmp_path / "results" / "bench-0-4-1000-512.txt").write_text(good + dead)
    agg = LogAggregator()
    assert len(agg.records) == 1
    (result,) = agg.records.values()
    assert result.mean_tps == 900  # the dead run did not drag the mean down


def test_aggregator_matrix_and_chaos_columns(tmp_path, monkeypatch):
    """print_matrix emits the nodes×rate grid + §6-shaped peak table and
    matrix.json, with chaos/SLO/WAN columns mined from result notes."""
    monkeypatch.chdir(tmp_path)
    (tmp_path / "results").mkdir()

    def summary(nodes, rate, tps, latency, notes=""):
        return (
            "-----------------------------------------\n SUMMARY:\n"
            " + CONFIG:\n Faults: 0 nodes\n"
            f" Committee size: {nodes} nodes\n"
            f" Input rate: {rate:,} tx/s\n Transaction size: 512 B\n"
            " Execution time: 10 s\n"
            f"{notes}"
            "\n + RESULTS:\n"
            f" End-to-end TPS: {tps:,} tx/s\n"
            f" End-to-end latency: {latency:,} ms\n"
        )

    chaos_notes = (" WAN: 1 shaped link(s): ab (latency 40)\n"
                   " Chaos plan: 2 event(s), max recovery 800 ms\n"
                   " Chaos SLO node-kill: 800 ms <= 30000 ms PASS\n"
                   " Chaos SLO link-heal: FAIL (recovery 99999 ms > SLO"
                   " 20000 ms)\n")
    (tmp_path / "results" / "bench-0-4-1000-512.txt").write_text(
        summary(4, 1000, 900, 50))
    (tmp_path / "results" / "bench-0-4-2000-512.txt").write_text(
        summary(4, 2000, 1800, 60))
    (tmp_path / "results" / "bench-0-10-1000-512.txt").write_text(
        summary(10, 1000, 700, 90, notes=chaos_notes))
    agg = LogAggregator()
    agg.print_matrix()

    matrix = json.load(open("plots/matrix.json"))
    group = matrix["0-512"]
    assert group["nodes"] == [4, 10] and group["rates"] == [1000, 2000]
    assert group["cells"]["4-2000"]["tps"] == 1800
    chaos = group["cells"]["10-1000"]["chaos"]
    assert chaos["slo_pass"] == 1 and chaos["slo_fail"] == 1
    assert chaos["wan"].startswith("1 shaped link")
    assert "chaos" not in group["cells"]["4-1000"]

    text = open("plots/matrix-0-512.txt").read()
    assert "| Nodes | Faults | Input rate |" in text  # §6 table shape
    assert "| 4 | 0 | 2,000 | 1,800 |" in text
    assert "1 SLO pass, 1 FAIL" in text
    assert "C!" in text  # breached cell marked in the grid


def test_aggregator_keeps_clean_and_chaos_runs_apart(tmp_path, monkeypatch):
    """The no-masquerade contract: a clean and a faulted/shaped run of
    the SAME configuration must never be averaged into one mean.  The
    clean aggregate owns the matrix grid slot; the chaos aggregate
    rides along un-averaged under "chaos_run"."""
    monkeypatch.chdir(tmp_path)
    (tmp_path / "results").mkdir()

    def summary(tps, latency, notes=""):
        return (
            "-----------------------------------------\n SUMMARY:\n"
            " + CONFIG:\n Faults: 0 nodes\n Committee size: 4 nodes\n"
            " Input rate: 1,000 tx/s\n Transaction size: 512 B\n"
            " Execution time: 10 s\n"
            f"{notes}"
            "\n + RESULTS:\n"
            f" End-to-end TPS: {tps:,} tx/s\n"
            f" End-to-end latency: {latency:,} ms\n"
        )

    chaos_notes = (" WAN: 1 shaped link(s): ab (latency 40)\n"
                   " Chaos plan: 1 event(s), max recovery 800 ms\n"
                   " Chaos SLO node-kill: 800 ms <= 30000 ms PASS\n")
    (tmp_path / "results" / "bench-0-4-1000-512.txt").write_text(
        summary(1000, 50) + summary(400, 200, notes=chaos_notes))
    agg = LogAggregator()
    # Two records — not one record with a 700-TPS mixed mean.
    assert len(agg.records) == 2
    assert sorted(r.mean_tps for r in agg.records.values()) == [400, 1000]

    agg.print_matrix()
    matrix = json.load(open("plots/matrix.json"))
    cell = matrix["0-512"]["cells"]["4-1000"]
    assert cell["tps"] == 1000 and "chaos" not in cell  # clean owns the slot
    assert cell["chaos_run"]["tps"] == 400
    assert cell["chaos_run"]["chaos"]["slo_pass"] == 1
    text = open("plots/matrix-0-512.txt").read()
    assert "+C" in text  # the grid points at the separate chaos run


def test_plot_matrix_draws_from_matrix_json(tmp_path, monkeypatch):
    matplotlib = pytest.importorskip("matplotlib")  # noqa: F841
    from hotstuff_tpu.harness.plot import Ploter, PlotError

    monkeypatch.chdir(tmp_path)
    with pytest.raises(PlotError):
        Ploter().plot_matrix()  # no aggregate yet
    (tmp_path / "plots").mkdir()
    (tmp_path / "plots" / "matrix.json").write_text(json.dumps({
        "0-512": {"faults": 0, "tx_size": 512, "nodes": [4, 10],
                  "rates": [1000], "cells": {
                      "4-1000": {"tps": 900, "latency_ms": 50},
                      "10-1000": {"tps": 700, "latency_ms": 90,
                                  "chaos": {"slo_pass": 1, "slo_fail": 0,
                                            "runs_with_chaos": 1,
                                            "wan": None}}}}}))
    Ploter().plot_matrix()
    assert (tmp_path / "plots" / "matrix.png").exists()
    assert (tmp_path / "plots" / "matrix.pdf").exists()


# ---------------------------------------------------------------------------
# grafttrace: per-host clock offsets through the ssh transport (PR 7)
# ---------------------------------------------------------------------------


def test_clock_offsets_probed_and_persisted(tmp_path, monkeypatch):
    """_clock_offsets: one RTT-midpoint probe per alive host through the
    runner, persisted keyed by log file name for the trace merger."""
    import os
    import time

    monkeypatch.chdir(tmp_path)
    os.makedirs("logs")
    skew = 2.0

    class FakeRunner:
        def run(self, host, command, timeout=None):
            assert command == "date +%s.%N"
            assert timeout is not None  # transport discipline holds

            class R:
                stdout = f"{time.time() + skew:.9f}\n"

            return R()

    bench = Bench.__new__(Bench)
    bench.runner = FakeRunner()
    bench._clock_offsets(["10.0.0.1", "10.0.0.2"])
    with open("logs/clock-offsets.json") as f:
        offsets = json.load(f)
    assert set(offsets) == {"node-0.log", "node-1.log"}
    assert all(1.5 < v < 2.5 for v in offsets.values())


def test_clock_offsets_tolerates_dead_hosts(tmp_path, monkeypatch):
    import os

    monkeypatch.chdir(tmp_path)
    os.makedirs("logs")

    class DeadRunner:
        def run(self, host, command, timeout=None):
            raise ExecutionError("unreachable")

    bench = Bench.__new__(Bench)
    bench.runner = DeadRunner()
    bench._clock_offsets(["10.0.0.1"])  # must not raise
    assert not os.path.exists("logs/clock-offsets.json")

"""grafttrace tests: span writer/parser, clock-offset alignment,
per-block critical-path stitching (including dropped/partial spans),
Chrome trace JSON schema round trip, the live metrics sampler on a
virtual clock across a sidecar kill/restart, and the directory-level
trace build the harness + LogParser drive.

graftscope additions: the protocol-v5 context-tag round trip (legacy
zero-tag frames included), the per-block node<->sidecar span join
(partial chains degrade join_rate, never the trace), the C++ node's
METRICS line reader + per-replica divergence, and the bench-trajectory
regression ledger.

All CPU-only and fast (no jax, no device, no sleeps beyond thread
joins) — the suite runs in tier-1.
"""

import json
import threading

import pytest

from hotstuff_tpu.obs import (
    MetricsSampler,
    Tracer,
    build_run_trace,
    chain_spans,
    chrome_trace,
    clock_offset,
    commit_rate_divergence,
    critical_path,
    join_blocks,
    merge_node_series,
    parse_node_metrics,
    parse_node_trace,
    parse_spans,
    persistent_fetch,
    read_samples,
    recovery_curve,
    split_samples,
    stitch_blocks,
    write_run_trace,
)
from hotstuff_tpu.obs.trace import (
    DEVICE_SEGMENT,
    apply_offset,
    device_subsegment,
    estimate_offset,
    probe_host_offset,
    sidecar_breakdown,
)


def _trace_line(sec, stage, block="aaa=", rnd=2, ms="000"):
    return (f"[2026-08-03T12:00:{sec:02d}.{ms}Z INFO consensus::core] "
            f"TRACE stage={stage} block={block} round={rnd}")


# ---------------------------------------------------------------------------
# span writer / parser
# ---------------------------------------------------------------------------


def test_tracer_writes_jsonl_spans(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    now = [100.0]
    tracer = Tracer(path, clock=lambda: now[0])
    tok = tracer.begin_span("pack", rid=7, cls="latency")
    now[0] += 0.005
    tracer.end_span(tok)
    tracer.event("device", dur_ms=18.5, rid=7)
    with tracer.span("bls", rid=9):
        now[0] += 0.002
    tracer.close()
    spans, malformed = parse_spans((tmp_path / "spans.jsonl").read_text())
    assert malformed == 0
    assert [s["stage"] for s in spans] == ["pack", "device", "bls"]
    assert spans[0]["rid"] == 7 and spans[0]["cls"] == "latency"
    assert spans[0]["dur_ms"] == pytest.approx(5.0)
    assert spans[1]["dur_ms"] == 18.5
    assert spans[2]["dur_ms"] == pytest.approx(2.0)


def test_disabled_tracer_is_noop(tmp_path):
    tracer = Tracer.disabled()
    tok = tracer.begin_span("pack")
    tracer.end_span(tok)
    tracer.event("device", dur_ms=1.0)
    with tracer.span("x"):
        pass
    assert not tracer.enabled and tracer.dropped == 0


def test_tracer_survives_dead_sink(tmp_path):
    # A directory as the sink path: open() fails -> tracer disables
    # itself and the caller never sees an exception.
    tracer = Tracer(str(tmp_path))
    tracer.event("pack", dur_ms=1.0)
    assert not tracer.enabled and tracer.dropped == 1
    tracer.event("pack", dur_ms=1.0)  # still silent


def test_parse_spans_skips_torn_lines():
    text = (json.dumps({"stage": "pack", "t": 1.0, "dur_ms": 2.0})
            + "\n{\"stage\": \"dev"              # torn mid-write
            + "\nnot json at all\n"
            + json.dumps({"no_stage": True, "t": 2.0}) + "\n"
            + json.dumps({"stage": "device", "t": "bad"}) + "\n"
            + json.dumps({"stage": "device", "t": 3.0, "dur_ms": 1.0})
            + "\n")
    spans, malformed = parse_spans(text)
    assert [s["stage"] for s in spans] == ["pack", "device"]
    assert malformed == 4


# ---------------------------------------------------------------------------
# node TRACE parsing + clock alignment
# ---------------------------------------------------------------------------


def test_parse_node_trace_mines_trace_lines():
    log = "\n".join([
        "[2026-08-03T12:00:01.000Z INFO node::node] Node abc= booted",
        _trace_line(1, "proposal"),
        _trace_line(1, "verify_submit", ms="010"),
        _trace_line(1, "bogus_stage"),          # unknown stage: skipped
        _trace_line(2, "commit"),
    ])
    spans = parse_node_trace(log, host="node-0.log")
    assert [s["stage"] for s in spans] == \
        ["proposal", "verify_submit", "commit"]
    assert all(s["block"] == "aaa=" and s["round"] == 2 for s in spans)
    assert spans[1]["t"] - spans[0]["t"] == pytest.approx(0.010)


def test_clock_offset_two_fake_hosts_with_known_skew():
    """The satellite test: two hosts, one running 2.5 s ahead; the
    RTT-midpoint estimator recovers the skew and alignment makes the
    merged trace causally consistent."""
    skew = 2.5
    rtt = 0.010
    probes = [(t, t + rtt / 2 + skew, t + rtt) for t in (10.0, 11.0, 12.0)]
    offset = estimate_offset(probes)
    assert offset == pytest.approx(skew, abs=1e-9)

    # Host A (reference) sees proposal at 100.0; host B's stamps carry
    # the skew.  After alignment the earliest-wins merge must order the
    # stages causally: B's commit observation lands AFTER A's proposal.
    spans_a = [{"host": "a", "stage": "proposal", "t": 100.0,
                "block": "x=", "round": 4}]
    spans_b = [{"host": "b", "stage": "commit", "t": 100.2 + skew,
                "block": "x=", "round": 4}]
    aligned = spans_a + apply_offset(spans_b, offset)
    traces = stitch_blocks(aligned)
    stages = traces[("x=", 4)]
    assert stages["commit"] - stages["proposal"] == pytest.approx(0.2)


def test_estimate_offset_median_discards_outlier():
    skew = 1.0
    probes = [(0.0, 0.005 + skew, 0.01),
              (1.0, 1.005 + skew, 1.01),
              (2.0, 2.9 + skew, 3.8)]  # one delayed round trip
    assert estimate_offset(probes) == pytest.approx(skew, abs=1e-6)
    assert estimate_offset([]) == 0.0
    assert clock_offset(0.0, 5.05, 0.1) == pytest.approx(5.0)


def test_probe_host_offset_through_fake_transport():
    skew = 0.75
    local = [50.0]

    def clock():
        local[0] += 0.002  # 4 ms RTT (clock read before and after)
        return local[0]

    def run_fn(host, command):
        assert command == "date +%s.%N"
        return f"{local[0] + 0.002 + skew:.9f}\n"

    off = probe_host_offset(run_fn, "host-b", clock, samples=3)
    assert off == pytest.approx(skew, abs=1e-3)

    def broken_run(host, command):
        raise OSError("unreachable")

    assert probe_host_offset(broken_run, "host-b", clock) == 0.0


# ---------------------------------------------------------------------------
# stitching + critical path (incl. dropped/partial spans)
# ---------------------------------------------------------------------------


def _full_block(block, rnd, t0, host="node-0.log"):
    return [
        {"host": host, "stage": "proposal", "t": t0, "block": block,
         "round": rnd},
        {"host": host, "stage": "verify_submit", "t": t0 + 0.010,
         "block": block, "round": rnd},
        {"host": host, "stage": "verify_reply", "t": t0 + 0.030,
         "block": block, "round": rnd},
        {"host": host, "stage": "commit", "t": t0 + 0.050,
         "block": block, "round": rnd},
    ]


def test_critical_path_stitching_with_dropped_span():
    spans = _full_block("a=", 2, 100.0)
    # Partial trace: the verify_reply span was dropped (chaos-killed
    # replica mid-write) — the block still counts for the segments whose
    # endpoints exist, and for the total.
    partial = [s for s in _full_block("b=", 3, 101.0)
               if s["stage"] != "verify_reply"]
    traces = stitch_blocks(spans + partial)
    out = critical_path(traces)
    assert out["blocks"] == 2 and out["complete"] == 1
    segs = out["segments"]
    assert segs["proposal->verify_submit"]["n"] == 2
    assert segs["verify_submit->verify_reply"]["n"] == 1
    assert segs["verify_reply->commit"]["n"] == 1
    assert segs["proposal->commit"]["n"] == 2
    assert segs["proposal->commit"]["p50_ms"] == pytest.approx(50.0)


def test_stitch_merges_earliest_across_replicas():
    # Two replicas observe the same block; the earliest stamp per stage
    # wins (the committee's critical path, the LogParser convention).
    a = _full_block("a=", 2, 100.0, host="node-0.log")
    b = _full_block("a=", 2, 100.020, host="node-1.log")
    stages = stitch_blocks(a + b)[("a=", 2)]
    assert stages["proposal"] == pytest.approx(100.0)
    assert stages["commit"] == pytest.approx(100.050)


def test_sidecar_breakdown_percentiles():
    spans = [{"stage": "queue", "t": 1.0, "dur_ms": d}
             for d in (1.0, 2.0, 3.0, 100.0)]
    spans.append({"stage": "device", "t": 1.0, "dur_ms": 20.0})
    spans.append({"stage": "reply", "t": 1.0})  # no dur: skipped
    out = sidecar_breakdown(spans)
    assert out["queue"]["n"] == 4
    assert out["queue"]["p99_ms"] == pytest.approx(100.0)
    assert out["device"]["p50_ms"] == pytest.approx(20.0)
    assert "reply" not in out


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------


def test_chrome_trace_schema_roundtrip():
    traces = stitch_blocks(_full_block("a=", 2, 100.0))
    sc = [{"stage": "device", "t": 100.015, "dur_ms": 12.0, "rid": 3,
           "cls": "latency"}]
    chrome = chrome_trace(traces, sc)
    decoded = json.loads(json.dumps(chrome))
    assert decoded["displayTimeUnit"] == "ms"
    events = decoded["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    metas = [e for e in events if e["ph"] == "M"]
    assert len(xs) == 4 and len(metas) == 2  # 3 segments + 1 sidecar
    for e in xs:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["name"]
    # Timestamps are normalized to the earliest span.
    assert min(e["ts"] for e in xs) == 0
    # The sidecar event carries its tags through args.
    dev = next(e for e in xs if e["name"] == "device")
    assert dev["args"] == {"rid": 3, "cls": "latency"}


def test_build_and_write_run_trace_directory(tmp_path):
    log0 = "\n".join([_trace_line(1, "proposal"),
                      _trace_line(1, "verify_submit", ms="010"),
                      _trace_line(1, "verify_reply", ms="030"),
                      _trace_line(1, "commit", ms="050")])
    # Replica 1 observed the same block 0.2 s "later" on a clock the
    # offsets file says runs 0.2 s ahead: after alignment its stamps
    # coincide with replica 0's, so the breakdown is unchanged.
    log1 = "\n".join([_trace_line(1, "proposal", ms="200"),
                      _trace_line(1, "commit", ms="250")])
    (tmp_path / "node-0.log").write_text(log0 + "\n")
    (tmp_path / "node-1.log").write_text(log1 + "\n")
    (tmp_path / "clock-offsets.json").write_text(
        json.dumps({"node-1.log": 0.2}))
    (tmp_path / "sidecar-spans.jsonl").write_text(
        json.dumps({"stage": "pack", "t": 1785751201.0, "dur_ms": 3.0})
        + "\ntorn lin")
    summary, chrome = build_run_trace(str(tmp_path))
    assert summary["blocks"] == 1 and summary["complete"] == 1
    assert summary["malformed_spans"] == 1
    assert summary["segments"]["proposal->commit"]["p50_ms"] == \
        pytest.approx(50.0)
    assert summary["sidecar"]["pack"]["p50_ms"] == pytest.approx(3.0)
    assert summary["chrome_events"] == len(chrome["traceEvents"])

    assert write_run_trace(str(tmp_path))["blocks"] == 1
    with open(tmp_path / "trace.json") as f:
        assert json.load(f)["traceEvents"]


def test_write_run_trace_without_spans_writes_nothing(tmp_path):
    (tmp_path / "node-0.log").write_text(
        "[2026-08-03T12:00:01.000Z INFO consensus::core] Committed B2\n")
    assert write_run_trace(str(tmp_path)) is None
    assert not (tmp_path / "trace.json").exists()


# ---------------------------------------------------------------------------
# metrics sampler (virtual clock; sidecar kill/restart)
# ---------------------------------------------------------------------------


class _FlakySidecar:
    """fetch() stand-in: healthy, then dead (kill), then healthy again
    (restart) — the exact sequence a chaos plan scripts."""

    def __init__(self, fail_from, fail_until):
        self.calls = 0
        self.fail_from = fail_from
        self.fail_until = fail_until

    def __call__(self):
        self.calls += 1
        if self.fail_from <= self.calls <= self.fail_until:
            raise ConnectionRefusedError("sidecar down")
        return {"launches": self.calls, "sigs_launched": 100 * self.calls}


def test_sampler_keeps_flowing_across_kill_restart(tmp_path):
    """The satellite test: on a virtual clock, samples keep flowing
    across a sidecar kill/restart — failed ticks are recorded, the last
    good snapshot survives, and the gap is visible in the series."""
    path = str(tmp_path / "metrics.jsonl")
    now = [1000.0]
    fetch = _FlakySidecar(fail_from=3, fail_until=4)
    sampler = MetricsSampler(fetch, path, interval_s=1.0,
                             wall=lambda: now[0])
    for _ in range(6):
        sampler.sample_once()
        now[0] += 1.0
    sampler.stop()
    samples, malformed = read_samples(path)
    assert malformed == 0
    assert [s["ok"] for s in samples] == \
        [True, True, False, False, True, True]
    assert sampler.samples == 6 and sampler.ok_samples == 4
    # The failure ticks carry the error, the good ticks the snapshot.
    assert "sidecar down" in samples[2]["error"]
    assert samples[5]["stats"]["launches"] == 6
    # Last good snapshot survives for the stats-file fallback.
    t_last, snap = sampler.last
    assert t_last == pytest.approx(1005.0)
    assert snap["launches"] == 6


def test_sampler_thread_lifecycle(tmp_path):
    """The real thread path (no virtual clock): ticks flow until stop().
    The injected wait hooks the stop event so the test never sleeps."""
    path = str(tmp_path / "metrics.jsonl")
    ticked = threading.Event()

    def fetch():
        ticked.set()
        return {"launches": 1}

    sampler = MetricsSampler(fetch, path, interval_s=0.01)
    sampler.start()
    assert ticked.wait(5.0)
    sampler.stop()
    samples, _ = read_samples(path)
    assert samples and all(s["ok"] for s in samples)
    assert sampler.last is not None


class _Conn:
    """SidecarClient stand-in for the persistent-fetch contract."""

    def __init__(self, broken=False):
        self.broken = broken
        self.closed = False
        self.stats_calls = 0

    def stats(self):
        self.stats_calls += 1
        if self.broken:
            raise ConnectionResetError("sidecar died mid-call")
        return {"launches": self.stats_calls}

    def close(self):
        self.closed = True


def test_persistent_fetch_reuses_one_connection():
    """The satellite regression: ONE dial serves every healthy tick (the
    1 Hz series stops paying a TCP dial per sample); a call failure
    drops the connection before re-raising, and the NEXT call re-dials."""
    conns = []

    def dial():
        conns.append(_Conn())
        return conns[-1]

    fetch = persistent_fetch(dial)
    assert fetch() == {"launches": 1}
    assert fetch() == {"launches": 2}
    assert len(conns) == 1  # reused, never re-dialed while healthy
    # the live connection dies mid-call: dropped (closed) + re-raised
    conns[0].broken = True
    with pytest.raises(ConnectionResetError):
        fetch()
    assert conns[0].closed
    # the next tick re-dials a fresh connection
    assert fetch() == {"launches": 1}
    assert len(conns) == 2
    # teardown closes the held connection
    fetch.close()
    assert conns[1].closed


def test_persistent_fetch_dead_dial_leaves_no_connection():
    calls = [0]

    def dial():
        calls[0] += 1
        raise ConnectionRefusedError("sidecar down")

    fetch = persistent_fetch(dial)
    for _ in range(2):
        with pytest.raises(ConnectionRefusedError):
            fetch()
    assert calls[0] == 2  # every failed tick re-dials, none leaks
    fetch.close()  # nothing held; must not raise


def test_sampler_gap_semantics_with_persistent_connection(tmp_path):
    """Through the sampler: a mid-run kill is exactly one ok-false tick
    (the dropped connection), the restart tick re-dials and records ok
    again — byte-identical gap semantics to the old dial-per-tick
    sampler — and stop() closes the held connection."""
    conns = []

    def dial():
        conns.append(_Conn())
        return conns[-1]

    path = str(tmp_path / "metrics.jsonl")
    now = [50.0]
    sampler = MetricsSampler(persistent_fetch(dial), path,
                             wall=lambda: now[0])
    sampler.sample_once()
    sampler.sample_once()
    conns[0].broken = True  # the kill
    sampler.sample_once()   # the gap tick
    sampler.sample_once()   # the restart: re-dial, healthy again
    sampler.stop()
    samples, malformed = read_samples(path)
    assert malformed == 0
    assert [s["ok"] for s in samples] == [True, True, False, True]
    assert "sidecar died" in samples[2]["error"]
    assert len(conns) == 2
    assert all(c.closed for c in conns)


def test_read_samples_tolerates_garbage(tmp_path):
    path = tmp_path / "metrics.jsonl"
    path.write_text(json.dumps({"t": 1.0, "ok": True, "stats": {}})
                    + "\n{\"t\": 2.0, \"ok\"\ngarbage\n"
                    + json.dumps({"no_t": True, "ok": True}) + "\n")
    samples, malformed = read_samples(str(path))
    assert len(samples) == 1 and malformed == 3
    assert read_samples(str(tmp_path / "absent.jsonl")) == ([], 0)


def test_recovery_curve_cites_the_gap():
    samples = [
        {"t": 10.0, "ok": True},
        {"t": 11.0, "ok": True},
        {"t": 12.0, "ok": False},   # kill at 11.5
        {"t": 13.0, "ok": False},
        {"t": 14.0, "ok": True},    # restart visible here
    ]
    curve = recovery_curve(samples, 11.5)
    assert curve["resumed"] is True
    assert curve["resume_ms"] == pytest.approx(2500.0)
    assert curve["failed_ticks"] == 2
    assert curve["samples_after"] == 3
    dead = recovery_curve(samples[:4], 11.5)
    assert dead["resumed"] is False and dead["resume_ms"] is None
    assert dead["failed_ticks"] == 2


# ---------------------------------------------------------------------------
# engine integration: the sidecar emits the full stage chain
# ---------------------------------------------------------------------------


def test_verify_engine_emits_stage_spans(tmp_path):
    """A host-mode VerifyEngine with a live tracer: one latency verify
    must leave the whole admit -> queue -> pack -> dispatch -> device ->
    reply chain in the span file, tagged with the rid and class."""
    from hotstuff_tpu.crypto import ref_ed25519 as ref
    from hotstuff_tpu.sidecar import protocol as proto
    from hotstuff_tpu.sidecar.service import VerifyEngine

    sk = bytes(range(32))
    _, pk = ref.generate_keypair(sk)
    msg = b"\x05" * 32
    sig = ref.sign(sk, msg)

    path = str(tmp_path / "spans.jsonl")
    engine = VerifyEngine(use_host=True, tracer=Tracer(path))
    try:
        done = []
        cond = threading.Condition()

        def reply(mask):
            with cond:
                done.append(mask)
                cond.notify()

        assert engine.submit(
            proto.VerifyRequest(42, [msg], [pk], [sig]), reply)
        with cond:
            assert cond.wait_for(lambda: done, timeout=60.0)
        assert done[0] == [True]
    finally:
        engine.stop()
        engine._tracer.close()
    spans, malformed = parse_spans((tmp_path / "spans.jsonl").read_text())
    assert malformed == 0
    stages = [s["stage"] for s in spans]
    for stage in ("admit", "queue", "pack", "dispatch", "device", "reply"):
        assert stage in stages, f"missing {stage} span in {stages}"
    admit = next(s for s in spans if s["stage"] == "admit")
    assert admit["rid"] == 42 and admit["cls"] == "latency" \
        and admit["ok"] is True
    queue = next(s for s in spans if s["stage"] == "queue")
    assert queue["rid"] == 42 and queue["dur_ms"] >= 0
    pack = next(s for s in spans if s["stage"] == "pack")
    assert pack["path"] == "host" and pack["uniq"] == 1


# ---------------------------------------------------------------------------
# graftscope: protocol v5 context tag round trip
# ---------------------------------------------------------------------------


def _make_records(n=2):
    msgs = [bytes([i]) * 32 for i in range(n)]
    pks = [bytes([0x10 + i]) * 32 for i in range(n)]
    sigs = [bytes([0x20 + i]) * 64 for i in range(n)]
    return msgs, pks, sigs


def test_protocol_v5_ctx_round_trip():
    from hotstuff_tpu.sidecar import protocol as proto

    msgs, pks, sigs = _make_records()
    ctx = bytes(range(32))
    frame = proto.encode_request(7, msgs, pks, sigs, ctx=ctx)
    opcode, req = proto.decode_request(frame[4:])
    assert opcode == proto.OP_VERIFY_BATCH
    assert req.ctx == ctx
    assert req.msgs == msgs and req.pks == pks and req.sigs == sigs
    # Bulk class carries the tag identically.
    frame = proto.encode_request(8, msgs, pks, sigs,
                                 opcode=proto.OP_VERIFY_BULK, ctx=ctx)
    opcode, req = proto.decode_request(frame[4:])
    assert opcode == proto.OP_VERIFY_BULK and req.ctx == ctx


def test_protocol_v5_legacy_and_zero_tag_frames():
    """Legacy tag-less frames AND all-zero tags (the C++ client's 'no
    context' form) both decode as ctx None — a version-skewed peer can
    never desync on the tag."""
    from hotstuff_tpu.sidecar import protocol as proto

    msgs, pks, sigs = _make_records()
    legacy = proto.encode_request(1, msgs, pks, sigs)  # no ctx at all
    _, req = proto.decode_request(legacy[4:])
    assert req.ctx is None
    zero = proto.encode_request(2, msgs, pks, sigs, ctx=proto.ZERO_CTX)
    assert len(zero) == len(legacy) + proto.CTX_LEN
    _, req = proto.decode_request(zero[4:])
    assert req.ctx is None
    # A frame whose length matches neither form still raises.
    bad = legacy[4:] + b"\x01" * 7
    with pytest.raises(ValueError):
        proto.decode_request(bad)


def test_protocol_v5_ctx_rides_bls_ops():
    """scheme=bls trace parity (ROADMAP item 2): the v5 context tag
    rides OP_BLS_VERIFY_VOTES / OP_BLS_VERIFY_MULTI exactly like the
    Ed25519 verifies — optional, length-discriminated (a BLS record is
    >= 288 bytes, so the 32 tag bytes can never alias one), all-zero
    tag decodes as 'no context'."""
    from hotstuff_tpu.sidecar import protocol as proto

    ctx = bytes(range(32))
    msg = b"d" * 32
    pks = [b"k" * 96] * 2
    sigs = [b"g" * 192] * 2

    votes = proto.encode_bls_votes_request(5, msg, pks, sigs, ctx=ctx)
    opcode, req = proto.decode_request(votes[4:])
    assert opcode == proto.OP_BLS_VERIFY_VOTES
    assert req.ctx == ctx
    assert req.msg == msg and req.pks == pks and req.sigs == sigs
    legacy = proto.encode_bls_votes_request(5, msg, pks, sigs)
    assert len(votes) == len(legacy) + proto.CTX_LEN
    _, req = proto.decode_request(legacy[4:])
    assert req.ctx is None
    zero = proto.encode_bls_votes_request(5, msg, pks, sigs,
                                          ctx=proto.ZERO_CTX)
    _, req = proto.decode_request(zero[4:])
    assert req.ctx is None

    msgs = [b"a" * 32, b"b" * 32]
    multi = proto.encode_bls_multi_request(6, msgs, pks, sigs, ctx=ctx)
    opcode, req = proto.decode_request(multi[4:])
    assert opcode == proto.OP_BLS_VERIFY_MULTI
    assert req.ctx == ctx
    assert req.msgs == msgs and req.pks == pks and req.sigs == sigs
    _, req = proto.decode_request(
        proto.encode_bls_multi_request(6, msgs, pks, sigs)[4:])
    assert req.ctx is None


def test_verify_engine_spans_carry_ctx(tmp_path):
    """An engine-path verify tagged with a block digest must leave the
    ctx on its per-request spans (admit/queue/reply) and the b64 tag in
    the per-launch ctxs lists (pack/dispatch/device) — the exact schema
    obs/trace.py joins on."""
    from base64 import b64encode

    from hotstuff_tpu.crypto import ref_ed25519 as ref
    from hotstuff_tpu.sidecar import protocol as proto
    from hotstuff_tpu.sidecar.service import VerifyEngine

    sk = bytes(range(32))
    _, pk = ref.generate_keypair(sk)
    msg = b"\x06" * 32
    sig = ref.sign(sk, msg)
    ctx = bytes(range(32))
    ctx_b64 = b64encode(ctx).decode()

    path = str(tmp_path / "spans.jsonl")
    engine = VerifyEngine(use_host=True, tracer=Tracer(path))
    try:
        done = []
        cond = threading.Condition()

        def reply(mask):
            with cond:
                done.append(mask)
                cond.notify()

        assert engine.submit(
            proto.VerifyRequest(9, [msg], [pk], [sig], ctx=ctx), reply)
        with cond:
            assert cond.wait_for(lambda: done, timeout=60.0)
        assert done[0] == [True]
    finally:
        engine.stop()
        engine._tracer.close()
    spans, malformed = parse_spans((tmp_path / "spans.jsonl").read_text())
    assert malformed == 0
    by_stage = {s["stage"]: s for s in spans}
    for stage in ("admit", "queue", "reply"):
        assert by_stage[stage]["ctx"] == ctx_b64, by_stage[stage]
    for stage in ("pack", "dispatch", "device"):
        assert by_stage[stage]["ctxs"] == [ctx_b64], by_stage[stage]
    # The chain machinery joins them all onto the one tag.
    chains = chain_spans(spans)
    assert set(s["stage"] for s in chains[ctx_b64]) == \
        {"admit", "queue", "pack", "dispatch", "device", "reply"}


# ---------------------------------------------------------------------------
# graftscope: per-block node<->sidecar joins
# ---------------------------------------------------------------------------


def _chain(block, t0, rid=1):
    return [
        {"stage": "admit", "t": t0, "dur_ms": 0.0, "rid": rid,
         "cls": "latency", "ctx": block},
        {"stage": "queue", "t": t0 + 0.001, "dur_ms": 1.0, "rid": rid,
         "cls": "latency", "ctx": block},
        {"stage": "pack", "t": t0 + 0.002, "dur_ms": 2.0, "reqs": 1,
         "ctxs": [block]},
        {"stage": "device", "t": t0 + 0.005, "dur_ms": 12.0, "reqs": 1,
         "ctxs": [block]},
        {"stage": "reply", "t": t0 + 0.02, "dur_ms": 0.0, "rid": rid,
         "cls": "latency", "ctx": block},
    ]


def test_join_blocks_full_and_missing_chain():
    """The satellite case: one committed block's sidecar chain is
    missing — its trace stays (partial), the join rate degrades to 0.5,
    and the device sub-segment reports only the joined block."""
    traces = stitch_blocks(_full_block("a=", 2, 100.0)
                           + _full_block("c=", 4, 102.0))
    spans = _chain("a=", 100.012)
    join, joined = join_blocks(traces, chain_spans(spans))
    assert join == {"committed": 2, "with_verify": 2, "joined": 1,
                    "rate": 0.5}
    assert list(joined) == [("a=", 2)]
    dev = device_subsegment(joined)
    assert dev["n"] == 1 and dev["p50_ms"] == pytest.approx(12.0)


def test_join_blocks_requires_verify_segment():
    # A block that committed off the cached-certificate path (no verify
    # stages) is out of the join denominator entirely.
    partial = [s for s in _full_block("b=", 3, 101.0)
               if s["stage"] in ("proposal", "commit")]
    traces = stitch_blocks(partial)
    join, joined = join_blocks(traces, chain_spans(_chain("b=", 101.0)))
    assert join == {"committed": 1, "with_verify": 0, "joined": 0,
                    "rate": None}
    assert not joined


def test_join_shared_launch_spans_both_blocks():
    # One coalesced launch carrying two blocks' requests: its pack/
    # device spans list both ctxs and land in BOTH chains.
    traces = stitch_blocks(_full_block("a=", 2, 100.0)
                           + _full_block("b=", 3, 100.5))
    shared = {"stage": "device", "t": 100.02, "dur_ms": 9.0,
              "ctxs": ["a=", "b="]}
    join, joined = join_blocks(traces, chain_spans([shared]))
    assert join["joined"] == 2 and join["rate"] == 1.0
    assert all(shared in chain for chain in joined.values())


def test_build_run_trace_with_ctx_join(tmp_path):
    """Directory-level: ctx-tagged sidecar spans join onto the mined
    node trace — summary grows join + verify:device, and the Chrome
    artifact nests the chain in the block's consensus row."""
    log = "\n".join([_trace_line(1, "proposal"),
                     _trace_line(1, "verify_submit", ms="010"),
                     _trace_line(1, "verify_reply", ms="030"),
                     _trace_line(1, "commit", ms="050"),
                     _trace_line(2, "proposal", block="xxx=", rnd=3),
                     _trace_line(2, "verify_submit", block="xxx=",
                                 rnd=3, ms="010"),
                     _trace_line(2, "verify_reply", block="xxx=",
                                 rnd=3, ms="030"),
                     _trace_line(2, "commit", block="xxx=", rnd=3,
                                 ms="050")])
    (tmp_path / "node-0.log").write_text(log + "\n")
    t0 = 1785751201.0  # block aaa='s chain only; xxx= stays unjoined
    (tmp_path / "sidecar-spans.jsonl").write_text(
        "\n".join(json.dumps(s) for s in _chain("aaa=", t0)) + "\n")
    summary, chrome = build_run_trace(str(tmp_path))
    assert summary["join"] == {"committed": 2, "with_verify": 2,
                               "joined": 1, "rate": 0.5}
    assert summary["segments"][DEVICE_SEGMENT]["n"] == 1
    assert summary["segments"][DEVICE_SEGMENT]["p50_ms"] == \
        pytest.approx(12.0)
    nested = [e for e in chrome["traceEvents"]
              if e.get("name", "").startswith("sidecar:")]
    assert nested and all(e["args"]["block"] == "aaa=" and e["pid"] == 1
                          for e in nested)
    # The flat sidecar-process timeline is still there for the chain.
    flat = [e for e in chrome["traceEvents"]
            if e.get("cat") == "sidecar" and e.get("pid") == 2]
    assert flat


# ---------------------------------------------------------------------------
# graftscope: node METRICS series + divergence
# ---------------------------------------------------------------------------


def _metrics_line(sec, commits, rate, busy=0, breaker="closed",
                  itx=5, ibytes=2048):
    return (f"[2026-08-03T12:00:{sec:02d}.000Z INFO node::metrics] "
            f"METRICS commits={commits} commit_rate={rate} "
            f"ingress_tx={itx} ingress_bytes={ibytes} busy={busy} "
            f"breaker={breaker}")


def test_parse_node_metrics_and_torn_lines():
    log = "\n".join([
        "[2026-08-03T12:00:01.000Z INFO node::node] Node abc= booted",
        _metrics_line(1, 10, "5.0"),
        _metrics_line(2, 15, "5.0", busy=3, breaker="open"),
        # torn mid-write: missing keys simply don't match
        "[2026-08-03T12:00:03.000Z INFO node::metrics] METRICS commi",
        "garbage line",
        _metrics_line(4, 20, "2.5"),
    ])
    recs = parse_node_metrics(log, host="node-0.log")
    assert len(recs) == 3
    assert all(r["node"] == "node-0.log" and r["ok"] for r in recs)
    assert recs[0]["metrics"] == {
        "commits": 10, "commit_rate": 5.0, "ingress_tx": 5,
        "ingress_bytes": 2048, "busy": 0, "breaker": "closed"}
    assert recs[1]["metrics"]["busy"] == 3
    assert recs[1]["metrics"]["breaker"] == "open"
    assert recs[2]["t"] - recs[0]["t"] == pytest.approx(3.0)


def test_merge_node_series_idempotent(tmp_path):
    (tmp_path / "node-0.log").write_text(_metrics_line(1, 10, "5.0")
                                         + "\n")
    (tmp_path / "node-1.log").write_text(_metrics_line(1, 9, "4.5")
                                         + "\n")
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"t": 1.0, "ok": True, "stats": {}}) + "\n")
    assert merge_node_series(str(tmp_path)) == 2
    samples, malformed = read_samples(str(tmp_path / "metrics.jsonl"))
    assert malformed == 0
    sidecar, node = split_samples(samples)
    assert len(sidecar) == 1 and len(node) == 2
    # Re-merging the same directory must not duplicate the series.
    assert merge_node_series(str(tmp_path)) == 0
    samples, _ = read_samples(str(tmp_path / "metrics.jsonl"))
    assert len(samples) == 3


def test_commit_rate_divergence_flags_straggler():
    def rec(host, rate):
        return {"t": 1.0, "ok": True, "node": host,
                "metrics": {"commit_rate": rate}}

    samples = [rec("node-0.log", 10.0), rec("node-1.log", 10.5),
               rec("node-2.log", 9.8), rec("node-3.log", 3.0)]
    div = commit_rate_divergence(samples, threshold=0.7)
    assert div["median"] == pytest.approx(9.9)
    assert [s["host"] for s in div["stragglers"]] == ["node-3.log"]
    assert div["stragglers"][0]["ratio"] < 0.7
    # A healthy committee flags nothing; one replica is unjudgeable.
    assert commit_rate_divergence(samples[:3])["stragglers"] == []
    assert commit_rate_divergence(samples[:1])["median"] is None


def test_log_parser_notes_divergence_and_splits_series():
    from test_harness import GOLDEN_CLIENT, GOLDEN_NODE

    from hotstuff_tpu.harness import LogParser

    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    samples = [
        {"t": 1.0, "ok": True, "stats": {"launches": 1}},
        {"t": 2.0, "ok": True, "stats": {"launches": 2}},
    ]
    for host, rate in (("node-0.log", 10.0), ("node-1.log", 9.5),
                       ("node-2.log", 1.0)):
        samples.append({"t": 1.5, "ok": True, "node": host,
                        "metrics": {"commit_rate": rate}})
    parser.note_metrics(samples)
    # The sidecar note counts only sidecar samples.
    assert any("Sidecar metrics: 2 sample(s)" in n for n in parser.notes)
    assert any("Node metrics: 3 sample(s) across 3 replica(s)" in n
               for n in parser.notes)
    straggler = [n for n in parser.notes
                 if "Replica commit-rate divergence" in n]
    assert len(straggler) == 1 and "node-2.log" in straggler[0]
    assert parser.node_metrics["divergence"]["stragglers"]


def test_note_trace_includes_join_rate():
    from test_harness import GOLDEN_CLIENT, GOLDEN_NODE

    from hotstuff_tpu.harness import LogParser

    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_trace({
        "blocks": 4, "complete": 4,
        "join": {"committed": 4, "with_verify": 4, "joined": 3,
                 "rate": 0.75},
        "segments": {
            "proposal->commit": {"n": 4, "p50_ms": 50.0, "p99_ms": 80.0},
            DEVICE_SEGMENT: {"n": 3, "p50_ms": 12.0, "p99_ms": 18.0},
        }})
    note = next(n for n in parser.notes if "Commit critical path" in n)
    assert "sidecar join 75% of 4 verify-traced" in note
    assert "verify:device p50 12 ms / p99 18 ms" in note


# ---------------------------------------------------------------------------
# graftscope: bench-trajectory regression ledger
# ---------------------------------------------------------------------------


def _bench_trend():
    import importlib.util
    import os

    from conftest import REPO

    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(REPO, "scripts", "bench_trend.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_artifacts(tmp_path, *runs):
    for name, doc in runs:
        (tmp_path / name).write_text(json.dumps(doc))


def test_bench_trend_best_latest_and_degraded_flags(tmp_path):
    bt = _bench_trend()
    _write_artifacts(
        tmp_path,
        ("BENCH_r01.json", {"n": 1, "rc": 0,
                            "parsed": {"metric": "m", "value": 100.0,
                                       "rlc": {"n64": {"speedup": 2.0}}}}),
        ("BENCH_r02.json", {"n": 2, "rc": 0,
                            "parsed": {"metric": "m", "value": 95.0,
                                       "rlc": {"n64": {"speedup": 2.5}}}}),
        # wedged round: no line at all
        ("BENCH_r03.json", {"n": 3, "rc": 124, "parsed": None}),
        # bare-headline degraded artifact (the surge_degraded shape)
        ("BENCH_zz_degraded.json", {"metric": "m", "value": 5.0,
                                    "degraded": True}),
    )
    trend = bt.build_trend(sorted(str(p) for p in
                                  tmp_path.glob("BENCH_*.json")))
    runs = {r["file"]: r for r in trend["runs"]}
    assert not runs["BENCH_r01.json"]["degraded"]
    assert not runs["BENCH_r02.json"]["degraded"]
    assert runs["BENCH_r03.json"]["degraded"]
    assert runs["BENCH_zz_degraded.json"]["degraded"]
    v = trend["fields"]["value"]
    assert v["best"] == 100.0 and v["best_run"] == "BENCH_r01.json"
    assert v["latest_live"] == 95.0
    # Degraded values stay visible as "latest" but never become best.
    assert v["latest"] == 5.0 and v["latest_degraded"] is True
    assert trend["fields"]["rlc.n64.speedup"]["best"] == 2.5
    # 5% drop inside the default 20% threshold: ok.
    assert bt.judge(trend, 0.2)["ok"] is True
    # A 1% threshold turns the same history into a regression.
    verdict = bt.judge(trend, 0.01)
    assert verdict["ok"] is False and "below best" in verdict["reason"]


def test_bench_trend_flattens_committee_scale(tmp_path):
    """graftscale: the committee_scale headline's numeric leaves land in
    the ledger like every other field — per-committee per-route
    sigs/sec/chip tracked best/latest, degraded runs still excluded
    from best."""
    bt = _bench_trend()
    cs = {"N100": {"quorum": 67, "per_sig_sharded_sigs_per_s_chip": 50.0,
                   "rlc_sharded_sigs_per_s_chip": 120.0,
                   "scan_sigs_per_s_chip": 60.0, "rlc_speedup": 2.4},
          "N1000": {"quorum": 667, "skipped": True}}
    _write_artifacts(
        tmp_path,
        ("BENCH_r01.json", {"n": 1, "rc": 0,
                            "parsed": {"metric": "m", "value": 100.0,
                                       "committee_scale": cs}}),
        # A degraded line carrying larger CPU-backend numbers must not
        # claim "best".
        ("BENCH_zz_degraded.json", {
            "metric": "m", "value": 5.0, "degraded": True,
            "committee_scale": {
                "N100": {"quorum": 67,
                         "rlc_sharded_sigs_per_s_chip": 999.0}}}),
    )
    trend = bt.build_trend(sorted(str(p) for p in
                                  tmp_path.glob("BENCH_*.json")))
    f = trend["fields"]
    assert f["committee_scale.N100.rlc_sharded_sigs_per_s_chip"]["best"] \
        == 120.0
    assert f["committee_scale.N100.rlc_sharded_sigs_per_s_chip"][
        "latest"] == 999.0
    assert f["committee_scale.N100.rlc_speedup"]["best"] == 2.4
    assert f["committee_scale.N100.quorum"]["best"] == 67
    # The skipped committee contributes only its quorum (bools and the
    # skipped flag are not measurements).
    assert "committee_scale.N1000.skipped" not in f
    assert f["committee_scale.N1000.quorum"]["latest"] == 667


def test_bench_trend_flattens_cadence(tmp_path):
    """graftcadence: the cadence headline's numeric leaves (ring-vs-
    staged sigs/sec per depth, queue-wait p99, pad-fill ratio) land in
    the ledger like every other field, and a degraded line's larger
    CPU-backend cadence numbers never claim best."""
    bt = _bench_trend()
    cad = {"staged_sigs_per_s": 2000.0,
           "ring_k2": {"sigs_per_s": 2100.0, "queue_wait_p99_ms": 40.0,
                       "pad_fill_ratio": 0.25},
           "ring_k8": {"skipped": True},
           "surge_wait": {"queue_wait_p99_ms": 150.0},
           "ok": True}
    _write_artifacts(
        tmp_path,
        ("BENCH_r01.json", {"n": 1, "rc": 0,
                            "parsed": {"metric": "m", "value": 100.0,
                                       "cadence": cad}}),
        ("BENCH_zz_degraded.json", {
            "metric": "m", "value": 5.0, "degraded": True,
            "cadence": {"staged_sigs_per_s": 9999.0,
                        "ring_k2": {"sigs_per_s": 9999.0}}}),
    )
    trend = bt.build_trend(sorted(str(p) for p in
                                  tmp_path.glob("BENCH_*.json")))
    f = trend["fields"]
    assert f["cadence.ring_k2.sigs_per_s"]["best"] == 2100.0
    assert f["cadence.staged_sigs_per_s"]["best"] == 2000.0
    # Degraded cadence values stay visible as latest, never best.
    assert f["cadence.ring_k2.sigs_per_s"]["latest"] == 9999.0
    assert f["cadence.ring_k2.sigs_per_s"]["latest_degraded"] is True
    assert f["cadence.surge_wait.queue_wait_p99_ms"]["latest"] == 150.0
    # Flags are not measurements: ok/skipped never become fields.
    assert "cadence.ok" not in f
    assert "cadence.ring_k8.skipped" not in f


def test_bench_trend_flattens_fleet(tmp_path):
    """graftfleet: the fleet headline's numeric leaves (goodput on both
    sides of the kill, re-home wall, dedup hit rate, flood p99s) land
    in the ledger, and a degraded line's fleet numbers never claim
    best."""
    bt = _bench_trend()
    fleet = {"endpoints": 2,
             "live_goodput_sigs_per_s": 60000.0,
             "failover_goodput_sigs_per_s": 80000.0,
             "rehome_ms": 120.0,
             "rehomes": 1, "host_fallbacks": 0,
             "masks_bit_identical": True,
             "dedup": {"cache_hits": 500, "hit_rate": 0.9},
             "flood": {"starvation": 0, "pre_p99_ms": 100.0,
                       "post_p99_ms": 130.0, "judged": True,
                       "ok": True},
             "ok": True}
    _write_artifacts(
        tmp_path,
        ("BENCH_r01.json", {"n": 1, "rc": 0,
                            "parsed": {"metric": "m", "value": 100.0,
                                       "fleet": fleet}}),
        ("BENCH_zz_degraded.json", {
            "metric": "m", "value": 5.0, "degraded": True,
            "fleet": {"failover_goodput_sigs_per_s": 99999.0,
                      "rehome_ms": 999.0}}),
    )
    trend = bt.build_trend(sorted(str(p) for p in
                                  tmp_path.glob("BENCH_*.json")))
    f = trend["fields"]
    assert f["fleet.failover_goodput_sigs_per_s"]["best"] == 80000.0
    assert f["fleet.live_goodput_sigs_per_s"]["best"] == 60000.0
    assert f["fleet.dedup.hit_rate"]["best"] == 0.9
    assert f["fleet.flood.post_p99_ms"]["latest"] == 130.0
    # Degraded fleet values stay visible as latest, never best.
    assert f["fleet.failover_goodput_sigs_per_s"]["latest"] == 99999.0
    assert f["fleet.failover_goodput_sigs_per_s"]["latest_degraded"] \
        is True
    # Flags are not measurements: ok/masks booleans never become fields.
    assert "fleet.ok" not in f
    assert "fleet.masks_bit_identical" not in f
    assert "fleet.flood.ok" not in f


def test_bench_trend_flattens_dag_and_namespaces_foreign_metric(tmp_path):
    """graftdag: the dag headline declares its OWN metric (consensus
    tx/s, not verify sigs/s), so its numeric leaves land in the ledger
    under a ``<metric>:``-prefixed lane — tracked best/latest with
    degraded-excluded-from-best like every field — while the primary
    sigs/s headline lane (and the --check judgement) never sees the
    foreign value."""
    bt = _bench_trend()
    dag = {"n4": {"payload_tps": 900.0, "cert_tps": 1600.0},
           "n10": {"payload_tps": 700.0, "cert_tps": 2500.0,
                   "eventloop_ceiling_tps": 1000.0},
           "chain_depth": 4, "ok": True}
    _write_artifacts(
        tmp_path,
        ("BENCH_r01.json", {"n": 1, "rc": 0,
                            "parsed": {"metric": "m", "value": 100.0}}),
        ("BENCH_r02.json", {"n": 2, "rc": 0,
                            "parsed": {"metric": "m", "value": 95.0}}),
        # a LIVE dag headline with its own metric
        ("BENCH_dag.json", {"metric": "dag-commit-tps", "value": 2500.0,
                            "dag": dag}),
        # a degraded dag line with larger numbers must not claim best
        # in the dag lane either
        ("BENCH_dag_degraded.json", {
            "metric": "dag-commit-tps", "value": 9999.0, "degraded": True,
            "dag": {"n10": {"cert_tps": 9999.0}}}),
    )
    trend = bt.build_trend(sorted(str(p) for p in
                                  tmp_path.glob("BENCH_*.json")))
    f = trend["fields"]
    assert trend["headline_metric"] == "m"
    # The dag leaves trend in their own namespaced lane.
    assert f["dag-commit-tps:dag.n10.cert_tps"]["best"] == 2500.0
    assert f["dag-commit-tps:dag.n4.payload_tps"]["best"] == 900.0
    assert f["dag-commit-tps:value"]["best"] == 2500.0
    # Degraded dag values stay visible as latest, never best.
    assert f["dag-commit-tps:dag.n10.cert_tps"]["latest"] == 9999.0
    assert f["dag-commit-tps:dag.n10.cert_tps"]["latest_degraded"] is True
    assert f["dag-commit-tps:value"]["best_run"] == "BENCH_dag.json"
    # Flags are not measurements.
    assert "dag-commit-tps:dag.ok" not in f
    # The PRIMARY headline lane is untouched by the foreign metric: the
    # 2500 tx/s dag number must neither become the latest live value nor
    # trip the regression judge against the 100-sigs/s-scale history.
    v = f["value"]
    assert v["best"] == 100.0 and v["latest_live"] == 95.0
    assert v["latest_live_run"] == "BENCH_r02.json"
    verdict = bt.judge(trend, 0.2)
    assert verdict["ok"] is True
    assert verdict["latest"] == 95.0 and verdict["best"] == 100.0


def test_bench_trend_committed_history_keeps_sigs_headline():
    """The committed repo history itself: the graftdag artifacts ride
    the real BENCH_*.json glob, so pin — against the actual files —
    that the primary headline lane still belongs to the verify metric
    and still judges clean."""
    import os

    from conftest import REPO

    bt = _bench_trend()
    paths = sorted(
        os.path.join(REPO, p) for p in os.listdir(REPO)
        if p.startswith("BENCH_") and p.endswith(".json"))
    assert paths, "committed BENCH_*.json artifacts missing"
    trend = bt.build_trend(paths)
    assert trend["headline_metric"] == "ed25519-batch-verify"
    assert bt.judge(trend, 0.2)["ok"] is True


def test_bench_trend_unjudgeable_histories_pass(tmp_path):
    bt = _bench_trend()
    # Only degraded runs: nothing to judge, never a failure.
    _write_artifacts(
        tmp_path,
        ("BENCH_r01.json", {"n": 1, "rc": 3,
                            "parsed": {"value": 0, "error": "wedged"}}))
    trend = bt.build_trend([str(tmp_path / "BENCH_r01.json")])
    verdict = bt.judge(trend, 0.2)
    assert verdict["ok"] is True and verdict["judged"] is False
    # One live run that IS the best: also unjudged, ok.
    _write_artifacts(
        tmp_path,
        ("BENCH_r02.json", {"n": 2, "rc": 0, "parsed": {"value": 50.0}}))
    trend = bt.build_trend(sorted(str(p) for p in
                                  tmp_path.glob("BENCH_*.json")))
    verdict = bt.judge(trend, 0.2)
    assert verdict["ok"] is True and verdict["judged"] is False


def test_bench_trend_cli_writes_ledger_and_exits_on_regression(tmp_path):
    bt = _bench_trend()
    _write_artifacts(
        tmp_path,
        ("BENCH_r01.json", {"n": 1, "rc": 0, "parsed": {"value": 100.0}}),
        ("BENCH_r02.json", {"n": 2, "rc": 0, "parsed": {"value": 10.0}}))
    out = tmp_path / "results" / "trend.json"
    assert bt.main(["--root", str(tmp_path), "--out", str(out)]) == 0
    ledger = json.loads(out.read_text())
    assert ledger["schema"] == "bench-trend-v1"
    assert ledger["check"]["ok"] is False  # recorded even without --check
    # --check makes the 90% drop fatal.
    assert bt.main(["--root", str(tmp_path), "--out", str(out),
                    "--check"]) == 1
    # No artifacts at all: usage error, not a crash.
    assert bt.main(["--root", str(tmp_path / "empty")]) == 2


# ---------------------------------------------------------------------------
# End-to-end grafttrace (slow lane; needs the native build)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_grafttrace_e2e_local_bench(tmp_path, monkeypatch):
    """The acceptance run: a real LocalBench (host-crypto sidecar, a
    scripted sidecar kill/restart) must produce logs/trace.json
    (Perfetto-loadable), logs/metrics.jsonl with >= 2 in-window samples
    showing the kill/restart transition, and a 'Commit critical path'
    note with per-stage percentiles.  graftscope: the same run must
    join >= 90% of its verify-traced committed blocks onto their
    sidecar chains (device time nested inside verify), and the node
    METRICS series must land per-replica next to the sidecar's."""
    import os

    from conftest import NODE_BIN, REPO
    from hotstuff_tpu.harness.config import BenchParameters, NodeParameters
    from hotstuff_tpu.harness.local import LocalBench

    if not os.path.exists(NODE_BIN):
        pytest.skip("native binaries not built (cmake --build native/build)")
    monkeypatch.chdir(tmp_path)
    os.symlink(os.path.join(REPO, "native"), tmp_path / "native")

    params = BenchParameters({
        "faults": 0, "nodes": 4, "rate": 500, "tx_size": 64,
        "duration": 12, "sidecar_host_crypto": True,
        "fault_plan": "3 sidecar kill; 5 sidecar restart"})
    node_params = NodeParameters.default(tpu_sidecar="127.0.0.1:7100")
    node_params.json["consensus"]["timeout_delay"] = 1_000
    node_params.timeout_delay = 1_000
    parser = LocalBench(params, node_params).run()

    out = parser.result()
    # critical path out of real node TRACE lines
    assert any("Commit critical path" in n for n in parser.notes), out
    assert parser.trace["segments"]["proposal->commit"]["n"] > 0
    # the Chrome trace artifact
    with open("logs/trace.json") as f:
        chrome = json.load(f)
    assert any(e.get("ph") == "X" for e in chrome["traceEvents"])
    # graftscope acceptance: >= 90% of verify-traced committed blocks
    # carry a joined sidecar chain, and device time rides inside the
    # verify segment of the summary + the Chrome artifact.
    join = parser.trace["join"]
    assert join["with_verify"] > 0, parser.trace
    assert join["rate"] >= 0.9, join
    assert parser.trace["segments"][DEVICE_SEGMENT]["n"] > 0
    assert any(e.get("name") == "sidecar:device"
               and e.get("args", {}).get("block")
               for e in chrome["traceEvents"])
    # >= 2 in-window samples, with the kill/restart visible as a
    # failed->ok transition in the series (sidecar sub-series: the node
    # records merged next to them must not mask the gap)
    samples, _ = read_samples("logs/metrics.jsonl")
    sidecar_series, node_series = split_samples(samples)
    assert len(sidecar_series) >= 2, samples
    assert any("Sidecar metrics:" in n for n in parser.notes)
    oks = [s["ok"] for s in sidecar_series]
    assert False in oks and True in oks[oks.index(False):], \
        "sidecar kill/restart not visible in the sampled series"
    # per-replica node METRICS landed in the same artifact
    assert node_series, "no node METRICS records merged"
    assert len({s["node"] for s in node_series}) >= 2
    assert any("Node metrics:" in n for n in parser.notes)
    # sidecar spans were written and merged
    assert os.path.exists("logs/sidecar-spans.jsonl")
    # the per-event telemetry curve rode into the chaos summary
    assert any("telemetry" in e for e in parser.chaos["events"])


# ---------------------------------------------------------------------------
# plots (per-stage histograms + the metrics time series)
# ---------------------------------------------------------------------------


def test_plot_trace_and_metrics(tmp_path, monkeypatch):
    matplotlib = pytest.importorskip("matplotlib")  # noqa: F841
    from hotstuff_tpu.harness.plot import Ploter, PlotError

    monkeypatch.chdir(tmp_path)
    with pytest.raises(PlotError):
        Ploter().plot_trace()  # no artifact yet
    with pytest.raises(PlotError):
        Ploter().plot_metrics()
    (tmp_path / "logs").mkdir()
    (tmp_path / "plots").mkdir()
    traces = stitch_blocks(_full_block("a=", 2, 100.0)
                           + _full_block("b=", 3, 101.0))
    (tmp_path / "logs" / "trace.json").write_text(
        json.dumps(chrome_trace(traces)))
    lines = []
    for i in range(6):
        ok = i != 3  # one failed tick: the blackout marker path
        rec = {"t": 1000.0 + i, "ok": ok}
        if ok:
            rec["stats"] = {
                "sigs_launched": 100 * i,
                "queue_wait": {"latency": {"n": 4, "p50_ms": 1.0,
                                           "p99_ms": 2.0 + i}}}
        else:
            rec["error"] = "down"
        lines.append(json.dumps(rec))
    (tmp_path / "logs" / "metrics.jsonl").write_text(
        "\n".join(lines) + "\n")
    ploter = Ploter()
    ploter.plot_trace()
    ploter.plot_metrics()
    for name in ("trace-hist", "metrics"):
        assert (tmp_path / "plots" / f"{name}.png").exists()
        assert (tmp_path / "plots" / f"{name}.pdf").exists()

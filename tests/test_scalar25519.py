"""Property tests for the device mod-L scalar arithmetic
(ops/scalar25519) against python big-int ground truth.

The RLC combined check (ops/ed25519.verify_rlc_packed) is only as sound
as these reductions: a single wrong limb in z*S mod L silently turns a
valid quorum into a "failed" combined check (livable — bisection still
resolves it) or, far worse, could mask a defect.  Every public entry
point is exercised on full-range random values AND the boundary cases of
the Montgomery argument bounds.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from hotstuff_tpu.ops import scalar25519 as S  # noqa: E402
from hotstuff_tpu.utils.intmath import L  # noqa: E402

RNG = np.random.default_rng(20240803)


def rand_scalars(n, bits=256, below_l=True):
    out = []
    for _ in range(n):
        v = int.from_bytes(RNG.bytes(bits // 8), "little")
        out.append(v % L if below_l else v)
    return out


# Boundary values for the [0, L) domain.
EDGES = [0, 1, 2, L - 1, L - 2, S.DELTA, S.DELTA - 1,
         (1 << 252) - 1, 1 << 252, (1 << 128) - 1, S.R1, S.R2]


def test_mul_mod_l_matches_python_ints():
    a_int = rand_scalars(100) + EDGES
    b_int = rand_scalars(100) + list(reversed(EDGES))
    a = jnp.asarray(S.batch_to_limbs(a_int))
    b = jnp.asarray(S.batch_to_limbs(b_int))
    got = S.batch_from_limbs(np.asarray(S.mul_mod_l(a, b)))
    assert got == [(x * y) % L for x, y in zip(a_int, b_int)]


def test_mul_mod_l_edge_cross_product():
    import itertools

    pairs = list(itertools.product(EDGES, EDGES))
    a = jnp.asarray(S.batch_to_limbs([p[0] for p in pairs]))
    b = jnp.asarray(S.batch_to_limbs([p[1] for p in pairs]))
    got = S.batch_from_limbs(np.asarray(S.mul_mod_l(a, b)))
    assert got == [(x * y) % L for x, y in pairs]


def test_mont_mul_headroom_accepts_full_2_256_operand():
    """reduce512's high-half path feeds mont_mul an operand up to
    2^256 - 1 (beyond L); the bound a*b < R*L must still hold exactly."""
    big = [(1 << 256) - 1, (1 << 256) - 38, 1 << 255]
    other = [L - 1, S.R2, 1]
    a = jnp.asarray(np.stack([np.frombuffer(
        v.to_bytes(32, "little"), np.uint8).astype(np.int32)
        for v in big]))
    b = jnp.asarray(S.batch_to_limbs(other))
    got = S.batch_from_limbs(np.asarray(S.mont_mul(a, b)))
    rinv = pow(S.R, L - 2, L)
    assert got == [(x * y * rinv) % L for x, y in zip(big, other)]


def test_add_and_sum_mod_l():
    a_int = rand_scalars(64) + EDGES
    b_int = rand_scalars(64) + EDGES
    a = jnp.asarray(S.batch_to_limbs(a_int))
    b = jnp.asarray(S.batch_to_limbs(b_int))
    got = S.batch_from_limbs(np.asarray(S.add_mod_l(a, b)))
    assert got == [(x + y) % L for x, y in zip(a_int, b_int)]
    got_sum = S.from_limbs(np.asarray(S.sum_mod_l(a, axis=0)))
    assert got_sum == sum(a_int) % L


def test_reduce512_mod_l():
    vals = [int.from_bytes(RNG.bytes(64), "little") for _ in range(50)]
    vals += [0, 1, L, L - 1, 2 * L, (1 << 512) - 1, (1 << 256) - 1,
             1 << 256, (L << 256) + L - 1]
    arr = np.zeros((len(vals), 64), np.uint8)
    for i, v in enumerate(vals):
        arr[i] = np.frombuffer(v.to_bytes(64, "little"), np.uint8)
    got = S.batch_from_limbs(np.asarray(S.reduce512_mod_l(jnp.asarray(arr))))
    assert got == [v % L for v in vals]


def test_reduce_limbsum_matches_sum(n=1000):
    """The sharded path psums limb-wise sums across shards before one
    fold; the fold must be exact at the largest supported term count."""
    vals = rand_scalars(n)
    limbs = S.batch_to_limbs(vals).astype(np.int64).sum(axis=0)
    assert limbs.max() < 2 ** 24  # the documented input bound
    got = S.from_limbs(np.asarray(
        S.reduce_limbsum_mod_l(jnp.asarray(limbs, dtype=jnp.int32))))
    assert got == sum(vals) % L


def test_mod_small_reduces_below_l():
    vals = [0, 1, L - 1, L, L + 1, 8 * L - 1, 15 * L + 7, (1 << 256) - 1]
    arr = np.stack([np.frombuffer(v.to_bytes(32, "little"),
                                  np.uint8).astype(np.int32)
                    for v in vals])
    got = S.batch_from_limbs(np.asarray(S.mod_small(jnp.asarray(arr))))
    assert got == [v % L for v in vals]

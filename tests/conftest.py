"""Test configuration: force an 8-device virtual CPU mesh so all sharding /
multi-chip code paths run (and are validated) without TPU hardware, per the
framework's multi-chip design (hotstuff_tpu/parallel/).

Note: this image's sitecustomize imports jax and registers the TPU ("axon")
PJRT plugin at interpreter startup, so env vars set here are too late —
instead we flip the platform through jax.config before any backend is
initialized.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (the same dir the sidecar and bench
# use): the suite's wall-clock is dominated by lax.scan ladder compiles
# that are identical run to run — cache them across sessions.  The
# min-compile-time floor keeps trivial programs out of the cache dir.
from hotstuff_tpu.utils.xla_cache import configure_xla_cache  # noqa: E402

configure_xla_cache()
try:
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # older jax: default threshold applies
    pass


# ---------------------------------------------------------------------------
# Slow-lane gating: tests marked @pytest.mark.slow (the two multichip
# dryruns, which duplicate the driver's own per-round dryrun_multichip
# check, and the exhaustive A/B flag-variant sweep) are skipped unless
# HOTSTUFF_TPU_SLOW_TESTS=1.  They account for ~215 s of a ~385 s
# warm-cache full run; the default lane stays under 5 minutes while CI's
# dedicated job exports the env and runs everything.
# ---------------------------------------------------------------------------


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight test, skipped unless HOTSTUFF_TPU_SLOW_TESTS=1")


def pytest_collection_modifyitems(config, items):
    import pytest

    if os.environ.get("HOTSTUFF_TPU_SLOW_TESTS") == "1":
        return
    skip = pytest.mark.skip(
        reason="slow lane: set HOTSTUFF_TPU_SLOW_TESTS=1 to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


# ---------------------------------------------------------------------------
# Shared integration-test scaffolding (node/client/sidecar process testbed).
# Used by test_integration*.py; lives here so the spawn/teardown and log
# helpers exist exactly once.
# ---------------------------------------------------------------------------

import signal as _signal
import socket as _socket
import subprocess as _subprocess
import time as _time

import pytest as _pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NODE_BIN = os.path.join(REPO, "native", "build", "node")
CLIENT_BIN = os.path.join(REPO, "native", "build", "client")


def free_port():
    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def count_in_log(path, needle):
    try:
        with open(path, "r", errors="replace") as f:
            return f.read().count(needle)
    except OSError:
        return 0


def wait_commits(log_files, minimum, deadline_s):
    start = _time.monotonic()
    while _time.monotonic() - start < deadline_s:
        counts = [count_in_log(p, "Committed B") for p in log_files]
        if all(c >= minimum for c in counts):
            return counts
        _time.sleep(0.5)
    return [count_in_log(p, "Committed B") for p in log_files]


def wait_sidecar_ping(port, deadline_s=30):
    from hotstuff_tpu.sidecar.client import SidecarClient

    start = _time.monotonic()
    while _time.monotonic() - start < deadline_s:
        try:
            with SidecarClient(port=port, timeout=2.0) as c:
                c.ping()
            return True
        except (OSError, ConnectionError):
            _time.sleep(0.2)
    return False


def make_committee(tmp_path, nodes, timeout_delay_ms, batch_size=1000,
                   sidecar_port=None, scheme=None):
    """Generate keys + committee + parameters files; returns (keys,
    committee, params)."""
    from hotstuff_tpu.harness.config import Key, LocalCommittee, NodeParameters

    keys = []
    for i in range(nodes):
        _subprocess.run([NODE_BIN, "keys", "--filename", f".node-{i}.json"],
                        cwd=tmp_path, check=True)
        keys.append(Key.from_file(str(tmp_path / f".node-{i}.json")))
    committee = LocalCommittee([k.name for k in keys], free_port())
    committee.print(str(tmp_path / ".committee.json"))
    params = NodeParameters.default(
        tpu_sidecar=(f"127.0.0.1:{sidecar_port}" if sidecar_port else None),
        scheme=scheme)
    params.json["consensus"]["timeout_delay"] = timeout_delay_ms
    params.json["mempool"]["batch_size"] = batch_size
    params.print(str(tmp_path / ".parameters.json"))
    return keys, committee, params


@_pytest.fixture
def testbed(tmp_path):
    procs = []

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(cmd, log_name):
        log = open(tmp_path / log_name, "w")
        p = _subprocess.Popen(cmd, cwd=tmp_path, stdout=log, stderr=log,
                              env=env)
        procs.append((p, log))
        return p

    yield tmp_path, spawn
    for p, log in procs:
        if p.poll() is None:
            p.send_signal(_signal.SIGTERM)
    for p, log in procs:
        try:
            p.wait(timeout=10)
        except _subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        log.close()

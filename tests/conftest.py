"""Test configuration: force an 8-device virtual CPU mesh so all sharding /
multi-chip code paths run (and are validated) without TPU hardware, per the
framework's multi-chip design (hotstuff_tpu/parallel/).

Note: this image's sitecustomize imports jax and registers the TPU ("axon")
PJRT plugin at interpreter startup, so env vars set here are too late —
instead we flip the platform through jax.config before any backend is
initialized.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

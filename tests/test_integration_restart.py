"""Crash-restart integration: a replica killed mid-run (SIGKILL) restarts on
its own store, restores its persisted voting state ("Restored consensus
state" from native/src/consensus/core.cpp), resyncs via the pull-based sync
path, and the committee keeps committing with it back.

Capability beyond the reference: its benchmarks only model crash faults by
never booting nodes (benchmark/local.py:77); restarted replicas are possible
but untested there, and their volatile round state is lost
(core.rs:112 TODO).  Host-verify mode: no sidecar or accelerator involved.
"""

import os
import time

import pytest

from conftest import (
    CLIENT_BIN, NODE_BIN, count_in_log, make_committee, wait_commits,
)

pytestmark = pytest.mark.skipif(
    not (os.path.exists(NODE_BIN) and os.path.exists(CLIENT_BIN)),
    reason="native binaries not built (cmake --build native/build)")

NODES = 4
TIMEOUT_DELAY_MS = 1000


def test_killed_node_restarts_with_state_and_rejoins(testbed):
    tmp_path, spawn = testbed
    _, committee, _ = make_committee(tmp_path, NODES, TIMEOUT_DELAY_MS)

    def start_node(i, log_name=None):
        return spawn([NODE_BIN, "run", "--keys", f".node-{i}.json",
                      "--committee", ".committee.json", "--store", f".db-{i}",
                      "--parameters", ".parameters.json", "-v"],
                     log_name or f"node-{i}.log")

    node_logs = [tmp_path / f"node-{i}.log" for i in range(NODES)]
    node_procs = [start_node(i) for i in range(NODES)]
    for i, addr in enumerate(committee.front_addresses()):
        spawn([CLIENT_BIN, addr, "--size", "64", "--rate", "250",
               "--timeout", str(TIMEOUT_DELAY_MS),
               "--nodes", *committee.front_addresses()],
              f"client-{i}.log")

    # Phase 1: healthy committee commits.
    counts = wait_commits(node_logs, minimum=3, deadline_s=60)
    assert all(c >= 3 for c in counts), f"no commits before crash: {counts}"

    # Phase 2: SIGKILL replica 3 (no clean shutdown); the other 2f+1 = 3
    # keep committing through its leader slots via view changes.
    node_procs[3].kill()
    node_procs[3].wait()
    healthy_before = [count_in_log(p, "Committed B") for p in node_logs[:3]]
    time.sleep(2 * TIMEOUT_DELAY_MS / 1000)

    # Phase 3: restart replica 3 on the SAME store with a fresh log; it
    # must restore its persisted round state and commit again.
    restart_log = tmp_path / "node-3-restart.log"
    start_node(3, "node-3-restart.log")

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if count_in_log(restart_log, "Restored consensus state") >= 1:
            break
        time.sleep(0.5)
    assert count_in_log(restart_log, "Restored consensus state") >= 1, (
        "restarted node did not restore persisted state")

    before = count_in_log(restart_log, "Committed B")
    after = wait_commits([restart_log], minimum=before + 3, deadline_s=60)
    assert after[0] >= before + 3, (
        f"restarted node stopped committing: {before} -> {after[0]}")

    # The healthy replicas made progress through the crash AND the restart.
    healthy_after = wait_commits(node_logs[:3],
                                 minimum=max(healthy_before) + 1,
                                 deadline_s=30)
    assert all(a > b for a, b in zip(healthy_after, healthy_before)), (
        f"healthy replicas stalled: {healthy_before} -> {healthy_after}")

"""Crash-restart integration: a replica killed mid-run (SIGKILL) restarts on
its own store, restores its persisted voting state ("Restored consensus
state" from native/src/consensus/core.cpp), resyncs via the pull-based sync
path, and the committee keeps committing with it back.  The sidecar case
(graftchaos): the verify sidecar SIGKILLed mid-run keeps consensus
committing via host fallback behind an OPEN circuit breaker, and every
node re-attaches within a backoff probe of its restart.

Capability beyond the reference: its benchmarks only model crash faults by
never booting nodes (benchmark/local.py:77); restarted replicas are possible
but untested there, and their volatile round state is lost
(core.rs:112 TODO).  Replica test runs host-verify (no sidecar); the
sidecar test boots a --host-crypto sidecar (no accelerator either way).
"""

import os
import signal
import sys
import time

import pytest

from conftest import (
    CLIENT_BIN, NODE_BIN, count_in_log, free_port, make_committee,
    wait_commits, wait_sidecar_ping,
)

pytestmark = pytest.mark.skipif(
    not (os.path.exists(NODE_BIN) and os.path.exists(CLIENT_BIN)),
    reason="native binaries not built (cmake --build native/build)")

NODES = 4
TIMEOUT_DELAY_MS = 1000


def test_killed_node_restarts_with_state_and_rejoins(testbed):
    tmp_path, spawn = testbed
    _, committee, _ = make_committee(tmp_path, NODES, TIMEOUT_DELAY_MS)

    def start_node(i, log_name=None):
        return spawn([NODE_BIN, "run", "--keys", f".node-{i}.json",
                      "--committee", ".committee.json", "--store", f".db-{i}",
                      "--parameters", ".parameters.json", "-v"],
                     log_name or f"node-{i}.log")

    node_logs = [tmp_path / f"node-{i}.log" for i in range(NODES)]
    node_procs = [start_node(i) for i in range(NODES)]
    for i, addr in enumerate(committee.front_addresses()):
        spawn([CLIENT_BIN, addr, "--size", "64", "--rate", "250",
               "--timeout", str(TIMEOUT_DELAY_MS),
               "--nodes", *committee.front_addresses()],
              f"client-{i}.log")

    # Phase 1: healthy committee commits.
    counts = wait_commits(node_logs, minimum=3, deadline_s=60)
    assert all(c >= 3 for c in counts), f"no commits before crash: {counts}"

    # Phase 2: SIGKILL replica 3 (no clean shutdown); the other 2f+1 = 3
    # keep committing through its leader slots via view changes.
    node_procs[3].kill()
    node_procs[3].wait()
    healthy_before = [count_in_log(p, "Committed B") for p in node_logs[:3]]
    time.sleep(2 * TIMEOUT_DELAY_MS / 1000)

    # Phase 3: restart replica 3 on the SAME store with a fresh log; it
    # must restore its persisted round state and commit again.
    restart_log = tmp_path / "node-3-restart.log"
    start_node(3, "node-3-restart.log")

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if count_in_log(restart_log, "Restored consensus state") >= 1:
            break
        time.sleep(0.5)
    assert count_in_log(restart_log, "Restored consensus state") >= 1, (
        "restarted node did not restore persisted state")

    before = count_in_log(restart_log, "Committed B")
    after = wait_commits([restart_log], minimum=before + 3, deadline_s=60)
    assert after[0] >= before + 3, (
        f"restarted node stopped committing: {before} -> {after[0]}")

    # The healthy replicas made progress through the crash AND the restart.
    healthy_after = wait_commits(node_logs[:3],
                                 minimum=max(healthy_before) + 1,
                                 deadline_s=30)
    assert all(a > b for a, b in zip(healthy_after, healthy_before)), (
        f"healthy replicas stalled: {healthy_before} -> {healthy_after}")


def _count_all(paths, needle):
    return sum(count_in_log(p, needle) for p in paths)


def test_sidecar_sigkill_midrun_host_fallback_and_reattach(testbed):
    """graftchaos acceptance: SIGKILL the verify sidecar mid-run — the
    committee keeps committing via the C++ host-verify fallback (circuit
    breaker OPEN: no per-verify connect penalty) — then restart it on the
    same port and watch every node's breaker re-attach within a backoff
    probe, with commits continuing throughout."""
    tmp_path, spawn = testbed
    port = free_port()
    _, committee, _ = make_committee(tmp_path, NODES, TIMEOUT_DELAY_MS,
                                     sidecar_port=port)

    def start_sidecar(log_name):
        return spawn([sys.executable, "-m", "hotstuff_tpu.sidecar",
                      "--port", str(port), "--host-crypto"], log_name)

    sidecar = start_sidecar("sidecar.log")
    assert wait_sidecar_ping(port, deadline_s=60), "sidecar never ready"

    node_logs = [tmp_path / f"node-{i}.log" for i in range(NODES)]
    for i in range(NODES):
        spawn([NODE_BIN, "run", "--keys", f".node-{i}.json",
               "--committee", ".committee.json", "--store", f".db-{i}",
               "--parameters", ".parameters.json", "-v"],
              f"node-{i}.log")
    for i, addr in enumerate(committee.front_addresses()):
        spawn([CLIENT_BIN, addr, "--size", "64", "--rate", "250",
               "--timeout", str(TIMEOUT_DELAY_MS),
               "--nodes", *committee.front_addresses()],
              f"client-{i}.log")

    # Phase 1: healthy committee commits THROUGH the sidecar.
    counts = wait_commits(node_logs, minimum=3, deadline_s=60)
    assert all(c >= 3 for c in counts), f"no commits pre-fault: {counts}"
    connects_before = _count_all(node_logs, "connected to verify sidecar")
    assert connects_before >= NODES

    # Phase 2: SIGKILL the sidecar. Consensus must keep committing on
    # the host path, and every node's breaker must OPEN (three
    # consecutive transport failures at ~2 s backoff each).
    sidecar.send_signal(signal.SIGKILL)
    sidecar.wait()
    before = [count_in_log(p, "Committed B") for p in node_logs]
    after = wait_commits(node_logs, minimum=max(before) + 3, deadline_s=60)
    assert all(a > b for a, b in zip(after, before)), (
        f"consensus stalled without the sidecar: {before} -> {after}")
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if _count_all(node_logs, "circuit breaker OPEN") >= NODES:
            break
        time.sleep(0.5)
    assert _count_all(node_logs, "circuit breaker OPEN") >= NODES, (
        "breakers never opened on the dead sidecar")

    # Phase 3: restart the sidecar on the same port; every breaker
    # re-attaches on a probe and commits continue.
    start_sidecar("sidecar-restart.log")
    assert wait_sidecar_ping(port, deadline_s=60), "restart never ready"
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        if _count_all(node_logs, "circuit breaker CLOSED") >= NODES:
            break
        time.sleep(0.5)
    assert _count_all(node_logs, "circuit breaker CLOSED") >= NODES, (
        "breakers never re-attached after the sidecar restart")
    before = [count_in_log(p, "Committed B") for p in node_logs]
    after = wait_commits(node_logs, minimum=max(before) + 3, deadline_s=60)
    assert all(a > b for a, b in zip(after, before)), (
        f"consensus stalled after re-attach: {before} -> {after}")

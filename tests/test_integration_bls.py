"""scheme=bls consensus integration: a 4-node C++ committee signs votes
and verifies QCs under BLS12-381 through the sidecar (the reference's bls
branch capability, selected at runtime via node parameters).

Pairing verification runs ~1 s per check in the sidecar's host-crypto
mode, so rounds take several seconds — the test asserts liveness (blocks
commit), not throughput. Gated behind HOTSTUFF_TPU_SLOW_TESTS=1.
"""

import base64
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from hotstuff_tpu.harness.config import (Key, LocalCommittee, NodeParameters,
                                         add_bls_keys)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NODE_BIN = os.path.join(REPO, "native", "build", "node")
CLIENT_BIN = os.path.join(REPO, "native", "build", "client")

pytestmark = [
    pytest.mark.skipif(
        not (os.path.exists(NODE_BIN) and os.path.exists(CLIENT_BIN)),
        reason="native binaries not built"),
    pytest.mark.skipif(
        os.environ.get("HOTSTUFF_TPU_SLOW_TESTS") != "1",
        reason="multi-minute BLS consensus run; set HOTSTUFF_TPU_SLOW_TESTS=1"),
]

NODES = 4
TIMEOUT_DELAY_MS = 30_000


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_ping(port, deadline_s=30):
    from hotstuff_tpu.sidecar.client import SidecarClient

    start = time.monotonic()
    while time.monotonic() - start < deadline_s:
        try:
            with SidecarClient(port=port, timeout=2.0) as c:
                c.ping()
            return True
        except (OSError, ConnectionError):
            time.sleep(0.2)
    return False


def _count(path, needle):
    try:
        with open(path, "r", errors="replace") as f:
            return f.read().count(needle)
    except OSError:
        return 0


@pytest.fixture
def testbed(tmp_path):
    procs = []
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(cmd, log_name):
        log = open(tmp_path / log_name, "w")
        p = subprocess.Popen(cmd, cwd=tmp_path, stdout=log, stderr=log,
                             env=env)
        procs.append((p, log))
        return p

    yield tmp_path, spawn
    for p, log in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p, log in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        log.close()


def test_bls_committee_commits(testbed):
    tmp_path, spawn = testbed
    sidecar_port = _free_port()

    key_files = []
    keys = []
    for i in range(NODES):
        subprocess.run([NODE_BIN, "keys", "--filename", f".node-{i}.json"],
                       cwd=tmp_path, check=True)
        key_files.append(str(tmp_path / f".node-{i}.json"))
        keys.append(Key.from_file(key_files[-1]))
    names = [k.name for k in keys]
    bls_pubkeys = add_bls_keys(key_files, names)
    committee = LocalCommittee(names, _free_port(), bls_pubkeys=bls_pubkeys)
    committee.print(str(tmp_path / ".committee.json"))
    params = NodeParameters.default(
        tpu_sidecar=f"127.0.0.1:{sidecar_port}", scheme="bls")
    params.json["consensus"]["timeout_delay"] = TIMEOUT_DELAY_MS
    params.json["mempool"]["batch_size"] = 1000
    params.print(str(tmp_path / ".parameters.json"))

    sidecar = spawn(
        [sys.executable, "-m", "hotstuff_tpu.sidecar", "--port",
         str(sidecar_port), "--host-crypto"],
        "sidecar.log")
    assert _wait_ping(sidecar_port), "sidecar never became ready"

    node_logs = []
    for i in range(NODES):
        spawn([NODE_BIN, "run", "--keys", f".node-{i}.json",
               "--committee", ".committee.json", "--store", f".db-{i}",
               "--parameters", ".parameters.json", "-v"],
              f"node-{i}.log")
        node_logs.append(tmp_path / f"node-{i}.log")
    for i, addr in enumerate(committee.front_addresses()):
        spawn([CLIENT_BIN, addr, "--size", "64", "--rate", "50",
               "--timeout", str(TIMEOUT_DELAY_MS),
               "--nodes", *committee.front_addresses()],
              f"client-{i}.log")

    # Liveness under BLS: every node commits at least one payload block.
    deadline = time.monotonic() + 420
    while time.monotonic() < deadline:
        counts = [_count(p, "Committed B") for p in node_logs]
        if all(c >= 1 for c in counts):
            break
        time.sleep(5)
    counts = [_count(p, "Committed B") for p in node_logs]
    assert all(c >= 1 for c in counts), (
        f"BLS committee failed to commit: {counts}; "
        f"scheme lines: {[_count(p, 'Signature scheme: bls') for p in node_logs]}")
    assert all(_count(p, "Signature scheme: bls") == 1 for p in node_logs)

"""scheme=bls consensus integration: a 4-node C++ committee signs votes
and verifies QCs under BLS12-381 through the sidecar (the reference's bls
branch capability, selected at runtime via node parameters).

Pairing verification runs ~1 s per check in the sidecar's host-crypto
mode, so rounds take several seconds — the test asserts liveness (blocks
commit), not throughput. Gated behind HOTSTUFF_TPU_SLOW_TESTS=1.
Process scaffolding (testbed fixture, log helpers) lives in conftest.py.
"""

import os
import subprocess
import sys
import time

import pytest

from hotstuff_tpu.harness.config import (Key, LocalCommittee, NodeParameters,
                                         add_bls_keys)
from hotstuff_tpu.obs import (chain_spans, join_blocks, parse_node_trace,
                              parse_spans, stitch_blocks)

from conftest import (
    CLIENT_BIN, NODE_BIN, count_in_log, free_port, wait_sidecar_ping,
)

pytestmark = [
    pytest.mark.skipif(
        not (os.path.exists(NODE_BIN) and os.path.exists(CLIENT_BIN)),
        reason="native binaries not built"),
    pytest.mark.slow,  # multi-minute BLS consensus run
]

NODES = 4
TIMEOUT_DELAY_MS = 30_000


def test_bls_committee_commits(testbed):
    tmp_path, spawn = testbed
    sidecar_port = free_port()

    # BLS needs per-node G1 pubkeys injected into the committee, so the
    # config block stays bespoke rather than using conftest.make_committee.
    key_files = []
    keys = []
    for i in range(NODES):
        subprocess.run([NODE_BIN, "keys", "--filename", f".node-{i}.json"],
                       cwd=tmp_path, check=True)
        key_files.append(str(tmp_path / f".node-{i}.json"))
        keys.append(Key.from_file(key_files[-1]))
    names = [k.name for k in keys]
    bls_pubkeys = add_bls_keys(key_files, names)
    committee = LocalCommittee(names, free_port(), bls_pubkeys=bls_pubkeys)
    committee.print(str(tmp_path / ".committee.json"))
    params = NodeParameters.default(
        tpu_sidecar=f"127.0.0.1:{sidecar_port}", scheme="bls")
    params.json["consensus"]["timeout_delay"] = TIMEOUT_DELAY_MS
    params.json["mempool"]["batch_size"] = 1000
    params.json["trace"] = True
    params.print(str(tmp_path / ".parameters.json"))

    spans_file = tmp_path / ".sidecar-spans.jsonl"
    sidecar = spawn(
        [sys.executable, "-m", "hotstuff_tpu.sidecar", "--port",
         str(sidecar_port), "--host-crypto", "--trace", str(spans_file)],
        "sidecar.log")
    assert wait_sidecar_ping(sidecar_port), "sidecar never became ready"

    node_logs = []
    for i in range(NODES):
        spawn([NODE_BIN, "run", "--keys", f".node-{i}.json",
               "--committee", ".committee.json", "--store", f".db-{i}",
               "--parameters", ".parameters.json", "-v"],
              f"node-{i}.log")
        node_logs.append(tmp_path / f"node-{i}.log")
    for i, addr in enumerate(committee.front_addresses()):
        spawn([CLIENT_BIN, addr, "--size", "64", "--rate", "50",
               "--timeout", str(TIMEOUT_DELAY_MS),
               "--nodes", *committee.front_addresses()],
              f"client-{i}.log")

    # Liveness under BLS: every node commits at least one payload block.
    deadline = time.monotonic() + 420
    while time.monotonic() < deadline:
        counts = [count_in_log(p, "Committed B") for p in node_logs]
        if all(c >= 1 for c in counts):
            break
        time.sleep(5)
    counts = [count_in_log(p, "Committed B") for p in node_logs]
    assert all(c >= 1 for c in counts), (
        f"BLS committee failed to commit: {counts}; scheme lines: "
        f"{[count_in_log(p, 'Signature scheme: bls') for p in node_logs]}")
    assert all(count_in_log(p, "Signature scheme: bls") == 1
               for p in node_logs)

    # join_rate parity with the EdDSA e2e: the v5 block-digest context tag
    # now rides OP_BLS_VERIFY_VOTES/MULTI, so sidecar device spans must
    # stitch into node block traces under scheme=bls too.  `with_verify`
    # counts only async-dispatched blocks (verify_submit traced), which is
    # exactly the population whose BLS verifies carried a ctx tag.
    time.sleep(2)  # let the sidecar tracer flush its last spans
    traces = stitch_blocks(
        [s for p in node_logs for s in parse_node_trace(p.read_text())])
    spans, malformed = parse_spans(
        spans_file.read_text() if spans_file.exists() else "")
    assert not malformed, f"malformed sidecar spans: {malformed}"
    join, _joined = join_blocks(traces, chain_spans(spans))
    assert join["with_verify"] > 0, (
        f"no BLS block rode the traced async verify path: {join}")
    assert join["rate"] >= 0.9, (
        f"BLS join_rate below EdDSA parity bar: {join}")

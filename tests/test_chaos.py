"""graftchaos tests: plan parsing/validation, the runner's scheduling and
error capture (virtual clock — tier-1 fast), recovery-latency math, the
LogParser integration (notes, strict liveness assertion, chaos-events.json
round trip, client-failure tolerance), and bench.py's chaos headline
probe."""

import json
import threading
from datetime import datetime, timezone

import pytest

from hotstuff_tpu.chaos import (
    FaultPlan,
    PlanError,
    PlanRunner,
    parse_plan,
    summarize_recovery,
)
from hotstuff_tpu.harness.logs import LogParser, ParseError
from test_harness import GOLDEN_CLIENT, GOLDEN_NODE


# ---------------------------------------------------------------------------
# plan parsing + validation
# ---------------------------------------------------------------------------


def test_parse_inline_dsl_sorts_and_validates():
    plan = parse_plan("10 sidecar restart; 5 sidecar kill; "
                      "3 node:1 pause; 6 node:1 resume")
    assert [e.t for e in plan.events] == [3.0, 5.0, 6.0, 10.0]
    assert plan.node_indices() == {1}
    assert plan.max_time() == 10.0
    # round-trips through JSON and back through the parser
    again = parse_plan(plan.to_json())
    assert again.to_json() == plan.to_json()


def test_parse_dict_list_and_degrade_params():
    plan = parse_plan([
        {"t": 1, "target": "sidecar", "action": "degrade",
         "params": {"delay_ms": 100, "shed": 2}},
        {"t": 2, "target": "sidecar", "action": "degrade",
         "params": {"clear": True}},
    ])
    assert plan.events[0].params == {"delay_ms": 100, "shed": 2}
    # DSL spelling of params
    plan = parse_plan("1 sidecar degrade delay_ms=50 drop=1")
    assert plan.events[0].params == {"delay_ms": 50, "drop": 1}


def test_parse_plan_file(tmp_path):
    path = tmp_path / "plan.json"
    path.write_text(json.dumps({"events": [
        {"t": 5, "target": "sidecar", "action": "kill"},
        {"t": 10, "target": "sidecar", "action": "restart"},
    ]}))
    plan = parse_plan(str(path))
    assert isinstance(plan, FaultPlan) and len(plan.events) == 2
    path.write_text("{not json")
    with pytest.raises(PlanError):
        parse_plan(str(path))


@pytest.mark.parametrize("spec,fragment", [
    ("5 sidecar explode", "unknown action"),
    ("5 moon:1 kill", "target must be"),
    ("-1 sidecar kill", "finite >= 0"),
    ("5 sidecar restart", "must follow a kill"),
    ("5 node:0 resume", "must follow a pause"),
    ("5 node:0 kill; 6 node:0 kill", "already down"),
    ("5 node:0 kill; 6 node:0 pause", "needs a live target"),
    ("5 sidecar kill; 6 sidecar degrade shed=1", "needs a live sidecar"),
    ("5 sidecar pause", "does not support"),
    ("5 node:0 degrade", "does not support"),
    ("5 sidecar degrade zap=1", "unknown degrade param"),
    ("5 sidecar degrade delay_ms=oops", "must be an int >= 0"),
    ("5 sidecar degrade shed=-3", "must be an int >= 0"),
    ("5 node:0 kill extra=1", "only degrade, surge, wedge, and "
                              "leader-cascade take params"),
    ("5 leader-cascade restart", "does not support"),
    ("5 leader-cascade kill k=0", "must be an int >= 1"),
    ("5 leader-cascade kill k=oops", "must be an int >= 1"),
    ("5 leader-cascade kill zap=2", "unknown leader-cascade param"),
    ("5 leader-cascade kill k=2; 8 node:1 kill",
     "mixing leader-cascade with node:<i> events"),
    ("2 node:1 pause; 5 leader-cascade kill; 8 node:1 resume",
     "mixing leader-cascade with node:<i> events"),
    ("nonsense", "want '<t> <target> <action>'"),
    ("", "empty fault plan"),
])
def test_plan_validation_rejects(spec, fragment):
    with pytest.raises(PlanError) as exc:
        parse_plan(spec)
    assert fragment in str(exc.value)


# ---------------------------------------------------------------------------
# runner (virtual clock: instant, deterministic ordering)
# ---------------------------------------------------------------------------


class _Recorder:
    def __init__(self, fail_on=()):
        self.applied = []
        self.fail_on = set(fail_on)

    def apply(self, event):
        if event.action in self.fail_on:
            raise RuntimeError(f"boom on {event.action}")
        self.applied.append((event.t, event.target, event.action))


def _run_virtual(plan, injector, until=None):
    now = [0.0]
    runner = PlanRunner(plan, injector, clock=lambda: now[0],
                        sleep=lambda dt: now.__setitem__(0, now[0] + dt),
                        wall=lambda: 1000.0 + now[0])
    runner.start(t0=0.0)
    runner.join(timeout=30.0)
    return runner


def test_runner_executes_in_order_with_wall_stamps():
    plan = parse_plan("2 sidecar kill; 1 node:0 pause; 3 node:0 resume")
    rec = _Recorder()
    runner = _run_virtual(plan, rec)
    assert rec.applied == [(1.0, "node:0", "pause"),
                           (2.0, "sidecar", "kill"),
                           (3.0, "node:0", "resume")]
    events = runner.events()
    assert [e["wall"] for e in events] == [1001.0, 1002.0, 1003.0]
    assert runner.all_ok()
    # JSON-safe (the logs/chaos-events.json contract)
    json.dumps(events)


def test_runner_records_injection_failure_and_continues():
    plan = parse_plan("1 sidecar kill; 2 sidecar restart")
    rec = _Recorder(fail_on={"kill"})
    runner = _run_virtual(plan, rec)
    events = runner.events()
    assert [e["ok"] for e in events] == [False, True]
    assert "boom on kill" in events[0]["error"]
    assert not runner.all_ok()
    assert rec.applied == [(2.0, "sidecar", "restart")]


def test_runner_stop_skips_pending_events():
    plan = parse_plan("1 sidecar kill; 500 sidecar restart")
    rec = _Recorder()
    now = [0.0]
    stopper = {}

    def sleep(dt):
        now[0] += dt
        if now[0] > 2.0:
            stopper["runner"].stop()

    runner = PlanRunner(plan, rec, clock=lambda: now[0], sleep=sleep,
                        wall=lambda: 1000.0 + now[0])
    stopper["runner"] = runner
    runner.start(t0=0.0)
    runner.join(timeout=30.0)
    assert [e["action"] for e in runner.events()] == ["kill"]


def test_runner_real_clock_smoke():
    """One tiny plan on the real clock: the thread plumbing works."""
    plan = parse_plan("0.01 sidecar kill; 0.03 sidecar restart")
    rec = _Recorder()
    runner = PlanRunner(plan, rec)
    done = threading.Event()
    runner.start()
    runner.join(timeout=10.0)
    done.set()
    assert len(runner.events()) == 2 and runner.all_ok()


# ---------------------------------------------------------------------------
# recovery math
# ---------------------------------------------------------------------------


def test_summarize_recovery_first_commit_after_event():
    events = [
        {"t": 5, "target": "sidecar", "action": "kill", "wall": 100.0,
         "ok": True},
        {"t": 10, "target": "sidecar", "action": "restart", "wall": 105.0,
         "ok": True},
    ]
    commits = [99.0, 100.8, 104.0, 105.4]
    out = summarize_recovery(events, commits)
    assert out["recovered"] and out["injected_ok"]
    assert out["events"][0]["recovery_ms"] == 800.0
    assert out["events"][1]["recovery_ms"] == 400.0
    assert out["max_recovery_ms"] == 800.0


def test_summarize_recovery_flags_stall_and_failed_injection():
    events = [
        {"t": 5, "action": "kill", "target": "node:2", "wall": 100.0,
         "ok": False, "error": "no such pid"},
        {"t": 9, "action": "restart", "target": "node:2", "wall": 104.0,
         "ok": True},
    ]
    out = summarize_recovery(events, [99.0, 101.0])  # nothing after 104
    assert not out["recovered"] and not out["injected_ok"]
    assert out["unrecovered"] == ["t=9s restart node:2"]
    assert out["events"][0]["error"] == "no such pid"


# ---------------------------------------------------------------------------
# LogParser integration
# ---------------------------------------------------------------------------

# Golden commits land at 2026-07-29T14:54:57.000Z and .200Z.
_COMMIT0 = datetime(2026, 7, 29, 14, 54, 57, 0,
                    tzinfo=timezone.utc).timestamp()


def _event(dt_s, action="kill", target="sidecar", ok=True):
    return {"t": 5.0, "target": target, "action": action,
            "wall": _COMMIT0 + dt_s, "ok": ok}


def test_parser_reports_recovery_latency_in_notes():
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                       chaos_events=[_event(-0.1)], strict_chaos=True)
    out = parser.result()
    assert "Chaos plan: 1 event(s), max recovery 100 ms" in out
    assert "Chaos t=5s kill sidecar: recovery 100 ms" in out
    assert parser.chaos["recovered"]
    # labelled RESULTS grammar untouched
    assert "End-to-end TPS" in out and "Consensus latency" in out


def test_parser_strict_chaos_raises_on_stall():
    # Event after the LAST golden commit: nothing ever commits again.
    with pytest.raises(ParseError) as exc:
        LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                  chaos_events=[_event(+10.0)], strict_chaos=True)
    assert "did not resume" in str(exc.value)
    # ... and a failed injection is a hard error too.
    with pytest.raises(ParseError) as exc:
        LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                  chaos_events=[dict(_event(-0.1), ok=False,
                                     error="nope")],
                  strict_chaos=True)
    assert "injection failed" in str(exc.value)
    # non-strict: reported, not raised
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                       chaos_events=[_event(+10.0)], strict_chaos=False)
    assert not parser.chaos["recovered"]
    assert any("UNCONFIRMED" in n for n in parser.notes)


def test_parser_tolerates_client_death_only_under_chaos():
    dead_client = GOLDEN_CLIENT + \
        "[2026-07-29T14:54:58.000Z WARN client] Failed to send transaction\n"
    with pytest.raises(ParseError):
        LogParser([dead_client], [GOLDEN_NODE], faults=0)
    parser = LogParser([dead_client], [GOLDEN_NODE], faults=0,
                       chaos_events=[_event(-0.1, action="pause",
                                            target="node:0")],
                       strict_chaos=True)
    assert any("died with its faulted replica" in n for n in parser.notes)
    # Tolerance is SCOPED: a plan that faults no replica excuses nothing
    # (a sidecar-only plan must not mask a genuine client bug) ...
    with pytest.raises(ParseError):
        LogParser([dead_client], [GOLDEN_NODE], faults=0,
                  chaos_events=[_event(-0.1, action="kill",
                                       target="sidecar")],
                  strict_chaos=True)
    # ... and is bounded by the count of distinct faulted replicas.
    with pytest.raises(ParseError):
        LogParser([dead_client, dead_client], [GOLDEN_NODE], faults=0,
                  chaos_events=[_event(-0.1, action="pause",
                                       target="node:0")],
                  strict_chaos=True)


def test_parser_counts_circuit_breaker_transitions():
    node = GOLDEN_NODE + (
        "[2026-07-29T14:54:58.000Z WARN crypto::sidecar] circuit breaker "
        "OPEN after 3 consecutive transport failures (connect failed): "
        "verifying on host, probing 127.0.0.1:7100 every 2000+ ms\n"
        "[2026-07-29T14:54:59.000Z INFO crypto::sidecar] circuit breaker "
        "CLOSED: re-attached to verify sidecar 127.0.0.1:7100\n")
    parser = LogParser([GOLDEN_CLIENT], [node], faults=0)
    assert any("circuit breaker: 1 open / 1 re-attach" in n
               for n in parser.notes)


def test_parser_process_reads_chaos_events_file(tmp_path):
    (tmp_path / "client-0.log").write_text(GOLDEN_CLIENT)
    (tmp_path / "node-0.log").write_text(GOLDEN_NODE)
    (tmp_path / "chaos-events.json").write_text(json.dumps([_event(-0.1)]))
    parser = LogParser.process(str(tmp_path), faults=0)
    assert parser.chaos is not None and parser.chaos["recovered"]
    # strict mode is on when the file exists: a stalled chaos run fails
    (tmp_path / "chaos-events.json").write_text(json.dumps([_event(10.0)]))
    with pytest.raises(ParseError):
        LogParser.process(str(tmp_path), faults=0)
    # garbage file: chaos mode simply off, parse survives
    (tmp_path / "chaos-events.json").write_text("{nope")
    parser = LogParser.process(str(tmp_path), faults=0)
    assert parser.chaos is None


# ---------------------------------------------------------------------------
# harness wiring + bench headline probe
# ---------------------------------------------------------------------------


def test_local_bench_rejects_bad_plan_targets():
    from hotstuff_tpu.harness.config import BenchParameters
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import BenchError

    params = {"faults": 1, "nodes": 4, "rate": 1000, "tx_size": 512,
              "duration": 60, "fault_plan": "5 node:3 kill"}
    bench = LocalBench(BenchParameters(params))
    # node 3 is the crash fault (alive = 3): the plan cannot execute
    with pytest.raises(BenchError) as exc:
        bench._check_fault_plan()
    assert "never booted" in str(exc.value)

    params["fault_plan"] = "5 sidecar kill; 8 sidecar restart"
    bench = LocalBench(BenchParameters(params))  # no sidecar in this run
    with pytest.raises(BenchError) as exc:
        bench._check_fault_plan()
    assert "boots none" in str(exc.value)

    # An event too close to teardown would either never fire or fail a
    # healthy run's strict recovery assertion: rejected up front.
    # (default timeout_delay 5000 ms -> grace = 2*5 + 3 = 13 s)
    params["fault_plan"] = "55 node:0 kill"
    bench = LocalBench(BenchParameters(params))
    with pytest.raises(BenchError) as exc:
        bench._check_fault_plan()
    assert "headroom" in str(exc.value)

    # ... and the acceptance-shaped plan passes the pre-boot check.
    params["fault_plan"] = \
        "5 sidecar kill; 10 sidecar restart; 12 node:1 pause; 15 node:1 resume"
    params["sidecar_host_crypto"] = True
    LocalBench(BenchParameters(params))._check_fault_plan()

    params["fault_plan"] = "5 nonsense"
    with pytest.raises(BenchError):
        LocalBench(BenchParameters(params))


def test_local_bench_boot_flags_carry_chaos_and_sizing():
    """The sidecar boot command grows --chaos only when a plan exists,
    and always carries the committee/rate sizing parameters."""
    from hotstuff_tpu.harness.config import BenchParameters
    from hotstuff_tpu.harness.local import LocalBench

    def boot_cmd(extra):
        params = {"faults": 0, "nodes": 4, "rate": 1000, "tx_size": 512,
                  "duration": 10, "sidecar_host_crypto": True, **extra}
        bench = LocalBench(BenchParameters(params))
        booted = []
        bench._background_run = \
            lambda cmd, log, append=False: booted.append(cmd)
        bench._wait_sidecar_ready = lambda deadline_s: None
        bench._boot_sidecar(host_crypto=True)
        return booted[0]

    cmd = boot_cmd({})
    assert "--committee 4" in cmd and "--client-rate 1000" in cmd
    assert "--chaos" not in cmd
    cmd = boot_cmd({"fault_plan": "1 sidecar degrade shed=1"})
    assert "--chaos" in cmd


def test_local_bench_boot_flags_carry_mesh():
    """--sidecar-mesh N boots the sidecar with --mesh N and the sharded
    one-MSM warmup; a host-crypto degrade drops both (no device, no
    mesh)."""
    from hotstuff_tpu.harness.config import BenchParameters
    from hotstuff_tpu.harness.local import LocalBench

    def boot_cmd(host_crypto):
        params = {"faults": 0, "nodes": 4, "rate": 1000, "tx_size": 512,
                  "duration": 10, "tpu_sidecar": True, "sidecar_mesh": 8}
        bench = LocalBench(BenchParameters(params))
        booted = []
        bench._background_run = \
            lambda cmd, log, append=False: booted.append(cmd)
        bench._wait_sidecar_ready = lambda deadline_s: None
        bench._boot_sidecar(host_crypto=host_crypto)
        return booted[0]

    cmd = boot_cmd(host_crypto=False)
    assert "--mesh 8 --warm-rlc-sharded" in cmd
    cmd = boot_cmd(host_crypto=True)
    assert "--mesh" not in cmd and "--warm-rlc-sharded" not in cmd


def test_bench_chaos_headline_probe_round_trips():
    import bench

    out = bench.chaos_headline_probe()
    assert out["recovered"] and out["injected_ok"]
    assert out["executed"] == out["plan_events"]
    json.dumps(out)  # headline-safe
    out = bench.chaos_headline_probe("1 node:0 kill; 2 node:0 restart")
    assert out["plan_events"] == 2 and out["recovered"]
    assert [e["action"] for e in out["events"]] == ["kill", "restart"]


# ---------------------------------------------------------------------------
# bench device probe: the retry loop must respect the OUTER budget (the
# BENCH_r05.json regression — rc=124, nine retries, no JSON at all)
# ---------------------------------------------------------------------------


class _VirtualClock:
    """Deterministic clock for the probe loop: a fake always-failing
    probe advances it by its timeout (a wedge eats the full wait);
    sleeps advance it too.  No real time passes."""

    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t

    def sleep(self, dt):
        self.t += dt

    def wedged_run(self, cmd, timeout=None, **kwargs):
        import subprocess

        self.t += timeout
        raise subprocess.TimeoutExpired(cmd, timeout)


def test_probe_device_caps_window_against_bench_deadline(monkeypatch):
    import bench

    clock = _VirtualClock()
    monkeypatch.setattr(bench, "_BENCH_T0", 0.0)
    monkeypatch.setenv("HOTSTUFF_TPU_BENCH_DEADLINE", "200")
    # The probe's own window (600 s) exceeds the outer budget: without
    # the cap, retries would outlive the driver's timeout and the
    # degraded JSON line would never print.
    ok, reason = bench.probe_device(
        window=600.0, max_attempts=99, run=clock.wedged_run,
        sleep=clock.sleep, now=clock.now)
    assert not ok
    # The loop gave up with at least the emit slack left in the budget.
    assert clock.t <= 200.0 - bench._DEADLINE_SLACK
    assert "outer budget 200s" in reason


def test_probe_device_exhausted_budget_probes_once_briefly(monkeypatch):
    import bench

    clock = _VirtualClock()
    clock.t = 500.0  # already past the whole budget
    monkeypatch.setattr(bench, "_BENCH_T0", 0.0)
    monkeypatch.setenv("HOTSTUFF_TPU_BENCH_DEADLINE", "200")
    calls = []

    def run(cmd, timeout=None, **kwargs):
        import subprocess

        calls.append(timeout)
        clock.t += timeout
        raise subprocess.TimeoutExpired(cmd, timeout)

    ok, _ = bench.probe_device(window=600.0, max_attempts=99, run=run,
                               sleep=clock.sleep, now=clock.now)
    assert not ok
    assert calls == [5.0]  # one floor-timeout attempt, nothing more


def test_probe_device_attempt_cap_and_success(monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_BENCH_T0", 0.0)
    monkeypatch.delenv("HOTSTUFF_TPU_BENCH_DEADLINE", raising=False)
    clock = _VirtualClock()
    ok, reason = bench.probe_device(
        window=600.0, max_attempts=3, run=clock.wedged_run,
        sleep=clock.sleep, now=clock.now)
    assert not ok and "3x (cap 3" in reason

    healthy = _VirtualClock()
    ok, reason = bench.probe_device(
        window=600.0, max_attempts=3,
        run=lambda *a, **k: None, sleep=healthy.sleep, now=healthy.now)
    assert ok and reason == ""


def test_probe_device_deterministic_errors_bail_fast(monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_BENCH_T0", 0.0)
    monkeypatch.delenv("HOTSTUFF_TPU_BENCH_DEADLINE", raising=False)
    clock = _VirtualClock()

    def broken_run(cmd, timeout=None, **kwargs):
        import subprocess

        clock.t += 1.0
        raise subprocess.CalledProcessError(1, cmd,
                                            stderr=b"ImportError: nope")

    ok, reason = bench.probe_device(
        window=600.0, max_attempts=99, run=broken_run,
        sleep=clock.sleep, now=clock.now)
    assert not ok and "not a wedge" in reason and "ImportError" in reason
    assert clock.t < 60.0  # quick retries, no 30 s wedge waits


def test_mesh_rlc_headline_skips_on_zero_budget():
    import bench

    assert bench.mesh_rlc_headline(budget_s=0.0) == {"skipped": True}


def test_local_fault_injector_signals_real_process_groups(tmp_path):
    """The signal plumbing against live (dummy) process groups: kill
    really SIGKILLs the group, pause really SIGSTOPs it (resume undoes),
    restart re-runs the recorded boot command in append mode, and
    cleanup un-pauses stragglers."""
    import os
    import subprocess
    import sys
    import time

    from hotstuff_tpu.chaos import parse_plan
    from hotstuff_tpu.harness.faults import LocalFaultInjector
    from hotstuff_tpu.harness.local import LocalBench

    bench = LocalBench.__new__(LocalBench)
    bench._procs = []
    bench._node_procs = {}
    bench._node_cmds = {}
    bench._sidecar_proc = None
    restarted = []
    bench._background_run = lambda cmd, log, append=False: (
        restarted.append((cmd, log, append)),
        subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"],
                         preexec_fn=os.setsid))[1]

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"],
            preexec_fn=os.setsid)

    bench._node_procs = {0: spawn(), 1: spawn()}
    bench._node_cmds = {0: ("cmd0", "log0"), 1: ("cmd1", "log1")}
    injector = LocalFaultInjector(bench)
    plan = parse_plan("0 node:0 kill; 0 node:0 restart; 0 node:1 pause")
    try:
        injector.apply(plan.events[0])   # kill node 0
        assert bench._node_procs[0].poll() is not None
        injector.apply(plan.events[1])   # restart node 0
        assert restarted == [("cmd0", "log0", True)]
        assert bench._node_procs[0].poll() is None
        injector.apply(plan.events[2])   # pause node 1
        time.sleep(0.1)
        with open(f"/proc/{bench._node_procs[1].pid}/stat") as f:
            assert f.read().split()[2] == "T"  # stopped
        injector.cleanup()               # SIGCONT straggler
        time.sleep(0.1)
        with open(f"/proc/{bench._node_procs[1].pid}/stat") as f:
            assert f.read().split()[2] in ("S", "R")
    finally:
        import signal as sig

        for p in bench._node_procs.values():
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), sig.SIGKILL)
                except ProcessLookupError:
                    pass


# ---------------------------------------------------------------------------
# graftview: leader-cascade drill (plan action, SLO class, injector, parser)
# ---------------------------------------------------------------------------


def test_parse_leader_cascade_plan():
    from hotstuff_tpu.chaos.plan import CASCADE_DEFAULT_K, LEADER_CASCADE, \
        cascade_k

    plan = parse_plan("5 leader-cascade kill k=3")
    (e,) = plan.events
    assert e.target == LEADER_CASCADE and e.action == "kill"
    assert cascade_k(e.params) == 3
    assert plan.node_indices() == set()  # victims are a runtime decision
    # default k, JSON round trip
    plan = parse_plan("5 leader-cascade kill")
    assert cascade_k(plan.events[0].params) == CASCADE_DEFAULT_K
    again = parse_plan(plan.to_json())
    assert again.to_json() == plan.to_json()
    # cascades are stateless: two in one plan are legal, and they mix
    # with non-node targets (whose state machine is unaffected)
    parse_plan("5 leader-cascade kill k=1; 20 leader-cascade kill k=2; "
               "2 sidecar degrade shed=1")


def test_cascade_fault_class_slo_and_judge():
    from hotstuff_tpu.chaos import DEFAULT_SLO_MS, fault_class, judge

    assert fault_class({"target": "leader-cascade",
                        "action": "kill"}) == "view-change"
    assert DEFAULT_SLO_MS["view-change"] == 60_000.0
    events = [{"t": 5, "target": "leader-cascade", "action": "kill",
               "params": {"k": 2}, "wall": 100.0, "ok": True}]
    out = summarize_recovery(events, [99.0, 112.0])
    verdict = judge(out)
    assert verdict["ok"]
    assert verdict["verdicts"][0]["class"] == "view-change"
    assert verdict["verdicts"][0]["recovery_ms"] == 12_000.0
    # a breach of the view-change budget fails like any other class
    late = summarize_recovery(events, [99.0, 200.0])
    assert not judge(late)["ok"]


def test_local_fault_injector_cascade_kills_upcoming_leaders(
        tmp_path, monkeypatch):
    """The cascade injector estimates the live round from the node logs,
    maps the next k round-robin leader slots (sorted-key order, the C++
    LeaderElector's rule) to boot indices, and SIGKILLs exactly those
    process groups — skipping already-dead slots, failing only when no
    live leader remains."""
    import base64
    import os
    import subprocess
    import sys

    from hotstuff_tpu.chaos import parse_plan as pp
    from hotstuff_tpu.harness.faults import InjectionError, \
        LocalFaultInjector
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import PathMaker

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"],
            preexec_fn=os.setsid)

    bench = LocalBench.__new__(LocalBench)
    bench._procs = []
    bench._node_procs = {i: spawn() for i in range(4)}
    bench._node_cmds = {}
    bench._sidecar_proc = None
    # Names whose decoded bytes sort in boot order, so leader(r) =
    # node r % 4 — deterministic mapping for the assertion below.
    bench._node_names = [
        base64.b64encode(bytes([i]) * 32).decode() for i in range(4)]
    monkeypatch.setattr(
        PathMaker, "node_log_file",
        staticmethod(lambda i: str(tmp_path / f"node-{i}.log")))
    # Node 0's log says the committee reached round 10 -> the injector
    # estimates round 11, so a k=2 cascade kills the leaders of rounds
    # 12 and 13 = nodes 0 and 1.
    (tmp_path / "node-0.log").write_text(
        "[2026-07-29T14:54:57.000Z INFO consensus::core] Committed B10\n")
    injector = LocalFaultInjector(bench)
    try:
        injector.apply(pp("0 leader-cascade kill k=2").events[0])
        bench._node_procs[0].wait(timeout=10)
        bench._node_procs[1].wait(timeout=10)
        assert bench._node_procs[0].poll() is not None
        assert bench._node_procs[1].poll() is not None
        assert bench._node_procs[2].poll() is None
        assert bench._node_procs[3].poll() is None
        # A second cascade skips the already-dead slots and kills the
        # next live leaders (rounds 12, 13 again -> dead -> the estimate
        # is unchanged, so k=3 reaches node 2).
        injector.apply(pp("0 leader-cascade kill k=3").events[0])
        bench._node_procs[2].wait(timeout=10)
        assert bench._node_procs[2].poll() is not None
        # No live leader among the next k rounds -> injection failure.
        for p in bench._node_procs.values():
            if p.poll() is None:
                os.killpg(os.getpgid(p.pid), 9)
                p.wait(timeout=10)
        with pytest.raises(InjectionError) as exc:
            injector.apply(pp("0 leader-cascade kill k=2").events[0])
        assert "no live leader" in str(exc.value)
    finally:
        import signal as sig

        for p in bench._node_procs.values():
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), sig.SIGKILL)
                except ProcessLookupError:
                    pass


def test_local_bench_cascade_preflight():
    """A cascade that would kill the quorum is rejected BEFORE boot, and
    the run-window headroom follows the backed-off pacemaker schedule
    the drill will actually execute."""
    from hotstuff_tpu.harness.config import BenchParameters
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import BenchError

    # N=4: quorum 3, so only one replica is expendable — k=2 must fail.
    params = {"faults": 0, "nodes": 4, "rate": 1000, "tx_size": 512,
              "duration": 120, "fault_plan": "5 leader-cascade kill k=2"}
    with pytest.raises(BenchError) as exc:
        LocalBench(BenchParameters(params))._check_fault_plan()
    assert "quorum" in str(exc.value)
    # N=10: quorum 7, k=3 leaves exactly a quorum — legal, given window
    # headroom for 3 backed-off view changes (5+10+20+base ~ 38s grace
    # with the default pacemaker, so duration 120 with t=5 passes ...
    params = {"faults": 0, "nodes": 10, "rate": 1000, "tx_size": 512,
              "duration": 120, "fault_plan": "5 leader-cascade kill k=3"}
    LocalBench(BenchParameters(params))._check_fault_plan()
    # ... and a 30 s window does not).
    params["duration"] = 30
    with pytest.raises(BenchError) as exc:
        LocalBench(BenchParameters(params))._check_fault_plan()
    assert "headroom" in str(exc.value)
    # remote pre-flight: cascades are local-harness only
    from hotstuff_tpu.harness.faults import InjectionError, \
        RemoteFaultInjector

    inj = RemoteFaultInjector(runner=None, hosts=["h0"], repo="/r",
                              node_boots={})
    from hotstuff_tpu.chaos import parse_plan as pp

    with pytest.raises(InjectionError):
        inj.apply(pp("0 leader-cascade kill").events[0])


_VIEWCHANGE_LINES = (
    "[2026-07-29T14:54:56.900Z WARN consensus::core] Timeout reached for "
    "round 2\n"
    "[2026-07-29T14:54:56.910Z WARN consensus::core] Ejected 1 invalid "
    "timeout signer(s) for round 2 (batched TC verify failed; "
    "per-signature fallback)\n"
    "[2026-07-29T14:54:56.950Z INFO consensus::core] Formed TC for round "
    "2 (3 timeouts, batched verify)\n"
    "[2026-07-29T14:54:56.951Z INFO consensus::core] View change: round "
    "2 -> 3 via TC\n"
    "[2026-07-29T14:54:56.960Z WARN consensus::core] Dropped 4 "
    "future-round timeout(s) beyond horizon (round 1000000007 > 3 + "
    "1000)\n")


def test_parser_strict_cascade_requires_viewchange_evidence():
    """Under strict chaos, an executed leader-cascade with NO TC/round
    transition evidence is a drill that drilled nothing — ParseError;
    with the evidence it passes and the view-change notes land."""
    cascade = {"t": 5.0, "target": "leader-cascade", "action": "kill",
               "params": {"k": 1}, "wall": _COMMIT0 - 0.1, "ok": True}
    with pytest.raises(ParseError) as exc:
        LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                  chaos_events=[cascade], strict_chaos=True)
    assert "no view change" in str(exc.value)

    node = GOLDEN_NODE + _VIEWCHANGE_LINES
    parser = LogParser([GOLDEN_CLIENT], [node], faults=0,
                       chaos_events=[cascade], strict_chaos=True)
    out = parser.result()
    assert "Chaos SLO view-change" in out and "PASS" in out
    assert parser.viewchange["tc_rounds"] == [2]
    assert parser.viewchange["transitions"] == 1
    assert parser.viewchange["max_jump"] == 1
    assert parser.viewchange["ejected"] == 1
    assert parser.viewchange["dropped_future"] == 4
    assert any("View change: TC formed for 1 round(s) (2)" in n
               for n in parser.notes)
    assert any("1 invalid timeout signer(s) ejected" in n
               for n in parser.notes)
    assert any("4 future-round timeout(s) dropped" in n
               for n in parser.notes)


def test_parser_tolerates_cascade_client_deaths():
    """A leader-cascade kills up to k replicas chosen at runtime; their
    clients die with them — tolerated, scoped to k like node kills."""
    dead_client = GOLDEN_CLIENT + \
        "[2026-07-29T14:54:58.000Z WARN client] Failed to send transaction\n"
    node = GOLDEN_NODE + _VIEWCHANGE_LINES
    cascade = {"t": 5.0, "target": "leader-cascade", "action": "kill",
               "params": {"k": 2}, "wall": _COMMIT0 - 0.1, "ok": True}
    parser = LogParser([dead_client, dead_client], [node], faults=0,
                       chaos_events=[cascade], strict_chaos=True)
    assert sum("died with its faulted replica" in n
               for n in parser.notes) == 2
    # ... but k bounds it: a third dead client is a real bug.
    with pytest.raises(ParseError):
        LogParser([dead_client] * 3, [node], faults=0,
                  chaos_events=[cascade], strict_chaos=True)


@pytest.mark.slow
def test_leader_cascade_e2e_local(tmp_path, monkeypatch):
    """The graftview acceptance drill against REAL processes: a 10-node
    committee (quorum 7), ``leader-cascade kill 3`` mid-run — three
    leader slots die at once, the committee rides timeout broadcast +
    batched TC assembly + the backoff pacemaker through the chained view
    changes, and the run is judged by the ``view-change`` SLO plus the
    strict parser assertions (recovery after the cascade AND actual
    TC/round-transition evidence: a drill that drilled nothing fails)."""
    import os

    from conftest import NODE_BIN, REPO
    from hotstuff_tpu.harness.config import BenchParameters, NodeParameters
    from hotstuff_tpu.harness.local import LocalBench

    if not os.path.exists(NODE_BIN):
        pytest.skip("native binaries not built (cmake --build native/build)")
    monkeypatch.chdir(tmp_path)
    os.symlink(os.path.join(REPO, "native"), tmp_path / "native")

    params = BenchParameters({
        "faults": 0, "nodes": 10, "rate": 500, "tx_size": 64,
        "duration": 25, "fault_plan": "3 leader-cascade kill k=3"})
    node_params = NodeParameters.default()
    node_params.json["consensus"]["timeout_delay"] = 1_000
    node_params.timeout_delay = 1_000
    parser = LocalBench(params, node_params).run()

    out = parser.result()
    assert "Chaos SLO view-change" in out and "PASS" in out
    assert parser.chaos["slo"]["ok"], parser.chaos["slo"]
    # the strict cascade assertion already enforced this inside run();
    # assert the machine-readable evidence too
    assert parser.viewchange["tc_rounds"], "cascade formed no TC"
    assert any("View change: TC formed" in n for n in parser.notes)
    events = json.load(open("logs/chaos-events.json"))
    assert events[0]["target"] == "leader-cascade" and events[0]["ok"]


def test_bench_viewchange_headline_probe_schema():
    """Schema + acceptance bar of the viewchange headline field on tiny
    committees (budget-bounded shapes compile fast), plus the zero-budget
    skip contract."""
    import bench

    out = bench.viewchange_headline(committees=(6,), repeats=1)
    assert out["n6"]["quorum"] == 5
    assert out["n6"]["batched_ms"] > 0 and out["n6"]["per_sig_ms"] > 0
    assert out["n6"]["speedup"] > 0
    eject = out["eject"]
    assert eject["batch_rejected"] and eject["match_per_sig"]
    assert eject["ejected"] == [eject["tampered_index"]]
    assert out["ok"] is True
    json.dumps(out)  # headline-safe
    assert bench.viewchange_headline(budget_s=0.0)["skipped"] is True


def test_finish_fault_plan_fails_on_skipped_events(tmp_path, monkeypatch):
    """An event the run window closed on (stalled earlier injection) is
    a FAILED chaos run, not a silently shorter one."""
    from hotstuff_tpu.harness.config import BenchParameters
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import BenchError, PathMaker

    monkeypatch.setattr(PathMaker, "chaos_events_file",
                        staticmethod(lambda: str(tmp_path / "ce.json")))
    params = {"faults": 0, "nodes": 4, "rate": 1000, "tx_size": 512,
              "duration": 60, "sidecar_host_crypto": True,
              "fault_plan": "5 sidecar kill; 10 sidecar restart"}
    bench = LocalBench(BenchParameters(params))

    class _Runner:
        def stop(self):
            pass

        def join(self, timeout=None):
            pass

        def events(self):
            return [{"t": 5.0, "target": "sidecar", "action": "kill",
                     "wall": 1.0, "ok": True}]  # second event skipped

    class _Injector:
        def cleanup(self):
            pass

    bench._injector = _Injector()
    with pytest.raises(BenchError) as exc:
        bench._finish_fault_plan(_Runner())
    assert "only 1 of 2" in str(exc.value)
    # the executed events were still persisted for diagnosis
    assert json.load(open(tmp_path / "ce.json"))[0]["action"] == "kill"

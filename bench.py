"""Headline benchmark: Ed25519 batch verification throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "ed25519-batch-verify", "value": <sigs/sec on TPU>,
   "unit": "sigs/sec", "vs_baseline": <TPU / single-core-CPU>}

The baseline is the same machine's single-core CPU verifying the same
signatures one-by-one through the `cryptography` library (OpenSSL's
optimized C/asm Ed25519) — the honest stand-in for the reference's
ed25519-dalek verify path (crypto/src/lib.rs:204-208), measured fresh at
every run.  North star (BASELINE.json): >= 10x single-core CPU, measured
here over rounds of 16 sub-batches of 1024 (the sidecar's own maximum
bulk launch, MAX_COALESCED = 16 * MAX_SUBBATCH).

Measurement shape: G sub-batches of 1024 distinct (key, message, signature)
triples are verified by ONE jitted program (lax.scan over sub-batches) so
the fixed per-dispatch cost of the tunneled TPU is amortized the same way
the sidecar amortizes it in production; every timed round pays the full
host preparation (SHA-512 challenge hashing, canonicality checks) for
every signature, overlapped with the device work of the previous round —
exactly the sidecar's pipelined steady state.
"""

from __future__ import annotations

import json
import time

import numpy as np

N = 1024          # sub-batch size; asserted == eddsa.MAX_SUBBATCH below
G = 16            # sub-batches per device dispatch
ROUNDS = 4        # timed pipelined rounds per trial
TRIALS = 3        # best-of: the tunneled TPU and the shared host CPU both
                  # drift +-40% with neighbor load; best-of-n measures the
                  # hardware, not the neighbors


def make_batch():
    """G*N fully distinct (key, message, signature) triples — no repetition,
    so the headline number is honest about per-signature cost.  Generated
    through OpenSSL (deterministic Ed25519: bit-identical to the pure-python
    reference, ~100x faster for 16k keypairs)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat,
    )

    rng = np.random.default_rng(2024)
    msgs, pks, sigs = [], [], []
    for _ in range(G * N):
        key = Ed25519PrivateKey.from_private_bytes(rng.bytes(32))
        pk = key.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        msg = rng.bytes(64)
        msgs.append(msg)
        pks.append(pk)
        sigs.append(key.sign(msg))
    return msgs, pks, sigs


def cpu_baseline(msgs, pks, sigs) -> float:
    """Single-core verifies/sec via OpenSSL (cryptography lib)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    keys = [Ed25519PublicKey.from_public_bytes(pk) for pk in pks]
    # warmup
    keys[0].verify(sigs[0], msgs[0])
    best = 0.0
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        for k, m, s in zip(keys, msgs, sigs):
            k.verify(s, m)
        dt = time.perf_counter() - t0
        best = max(best, len(msgs) / dt)
    return best


def tpu_throughput(msgs, pks, sigs) -> float:
    """End-to-end pipelined verifies/sec: every timed round pays full host
    preparation for all G*N signatures plus one chunked device dispatch
    (ops/ed25519.verify_packed_chunked — the same launch shape the sidecar
    uses for bulk backlogs); device dispatch is async, so host prep of
    round i+1 overlaps device compute of round i."""
    import jax.numpy as jnp

    from hotstuff_tpu.crypto import eddsa
    from hotstuff_tpu.ops import ed25519 as E

    assert N == eddsa.MAX_SUBBATCH
    verify_chunked = E.verify_packed_chunked_jit  # (G, N, 128) -> (G, N)

    def prep_round():
        rows = []
        for g in range(G):
            prep = eddsa.prepare_batch(msgs[g * N:(g + 1) * N],
                                       pks[g * N:(g + 1) * N],
                                       sigs[g * N:(g + 1) * N])
            assert prep["host_ok"].all()
            rows.append(prep["packed"])
        return np.stack(rows)

    out = verify_chunked(jnp.asarray(prep_round()))   # compile + warmup
    assert np.asarray(out).all(), "benchmark signatures must verify"

    # One prep thread: host preparation of round i+1 overlaps BOTH the
    # device compute and the blocking tunnel transfers of round i (the
    # SHA-512 loop releases the GIL; transfers block in C).  Every round's
    # full prep cost is still paid inside the timed window.
    from concurrent.futures import ThreadPoolExecutor

    best = 0.0
    with ThreadPoolExecutor(1) as pool:
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            fut = pool.submit(prep_round)
            pending = None
            for r in range(ROUNDS):
                arr = fut.result()
                if r + 1 < ROUNDS:
                    fut = pool.submit(prep_round)
                pending = verify_chunked(jnp.asarray(arr))
            final = np.asarray(pending)
            dt = time.perf_counter() - t0
            assert final.all(), "benchmark signatures must verify"
            best = max(best, G * N * ROUNDS / dt)
    return best


def main():
    # Watchdog: the tunneled TPU can wedge indefinitely (observed: a plain
    # 8x8 matmul never returning).  A hung bench is worse than a failed
    # one — the driver's round-end run must always terminate.
    import os
    import threading

    def _fail(reason):
        print(json.dumps({"metric": "ed25519-batch-verify", "value": 0,
                          "unit": "sigs/sec", "vs_baseline": 0,
                          "error": reason}))
        os._exit(3)

    # Probe-with-retry-window: a wedged tunnel hangs ANY device call
    # indefinitely (observed: an 8x8 matmul never returning, outages of
    # ~1h), and only a subprocess can be timed out reliably.  A round-3
    # style instant fail zeroes the whole round on a transient outage, so
    # keep probing every couple of minutes across a bounded window
    # (HOTSTUFF_TPU_PROBE_WINDOW seconds, default 40 min) and only give up
    # when the window is exhausted.  The measurement watchdog starts only
    # after the device answers, so waiting here never eats bench time.
    import subprocess
    import sys

    window = float(os.environ.get("HOTSTUFF_TPU_PROBE_WINDOW", "2400"))
    probe = ("import jax, jax.numpy as jnp, numpy as np;"
             "np.asarray(jnp.ones((8, 8)) @ jnp.ones((8, 8)))")
    deadline = time.monotonic() + window
    attempt = 0
    proc_errors = 0
    last_err = "tunnel wedged (probe timeouts)"
    while True:
        attempt += 1
        retry_sleep = 120.0
        try:
            subprocess.run([sys.executable, "-c", probe], timeout=75,
                           check=True, capture_output=True)
            break
        except subprocess.TimeoutExpired:
            proc_errors = 0
            last_err = "tunnel wedged (probe timeouts)"
        except subprocess.CalledProcessError as e:
            # A probe that exits nonzero (bad install, import error) is
            # deterministic — only timeouts are worth waiting out, so
            # retry these quickly and give up after a few in a row.
            proc_errors += 1
            retry_sleep = 5.0
            last_err = (e.stderr or b"").decode("utf-8", "replace")[-300:]
            if proc_errors >= 4:
                _fail(f"device probe errored {proc_errors}x in a row "
                      f"(not a wedge): {last_err}")
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            _fail(f"device probe failed {attempt}x over {window:.0f}s "
                  f"window: {last_err}")
        print(f"bench: device probe attempt {attempt} failed; retrying "
              f"({remaining:.0f}s left in window)", file=sys.stderr)
        time.sleep(min(retry_sleep, max(0.0, remaining)))

    def _abort():
        _fail("watchdog: TPU unresponsive for 900s after a healthy probe")

    watchdog = threading.Timer(900.0, _abort)
    watchdog.daemon = True
    watchdog.start()

    # Persistent XLA compilation cache (same dir the sidecar uses): the
    # driver runs this script in a cold process, and the chunked-verify
    # program costs 30-60 s to compile through the tunnel.
    from hotstuff_tpu.utils.xla_cache import configure_xla_cache

    configure_xla_cache()

    from hotstuff_tpu.ops import field25519

    field25519.mul_selfcheck()  # trip fast if this backend's conv is inexact
    msgs, pks, sigs = make_batch()
    cpu = cpu_baseline(msgs, pks, sigs)
    tpu = tpu_throughput(msgs, pks, sigs)
    watchdog.cancel()
    print(json.dumps({
        "metric": "ed25519-batch-verify",
        "value": round(tpu, 1),
        "unit": "sigs/sec",
        "vs_baseline": round(tpu / cpu, 3),
    }))


if __name__ == "__main__":
    main()

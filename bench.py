"""Headline benchmark: Ed25519 batch verification throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "ed25519-batch-verify", "value": <sigs/sec on TPU>,
   "unit": "sigs/sec", "vs_baseline": <TPU / single-core-CPU>}

The baseline is the same machine's single-core CPU verifying the same 1024
signatures one-by-one through the `cryptography` library (OpenSSL's
optimized C/asm Ed25519) — the honest stand-in for the reference's
ed25519-dalek verify path (crypto/src/lib.rs:204-208), measured fresh at
every run.  North star (BASELINE.json): >= 10x at N=1024.
"""

from __future__ import annotations

import json
import time

import numpy as np

N = 1024
REPS = 5


def make_batch():
    """N fully distinct (key, message, signature) triples — no repetition,
    so the headline number is honest about per-signature cost."""
    from hotstuff_tpu.crypto import ref_ed25519 as ref

    rng = np.random.default_rng(2024)
    msgs, pks, sigs = [], [], []
    for _ in range(N):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(64)
        msgs.append(msg)
        pks.append(pk)
        sigs.append(ref.sign(sk, msg))
    return msgs, pks, sigs


def cpu_baseline(msgs, pks, sigs) -> float:
    """Single-core verifies/sec via OpenSSL (cryptography lib)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    keys = [Ed25519PublicKey.from_public_bytes(pk) for pk in pks]
    # warmup
    keys[0].verify(sigs[0], msgs[0])
    t0 = time.perf_counter()
    for k, m, s in zip(keys, msgs, sigs):
        k.verify(s, m)
    dt = time.perf_counter() - t0
    return len(msgs) / dt


def tpu_throughput(msgs, pks, sigs) -> float:
    """End-to-end pipelined verifies/sec: every timed iteration pays the full
    host preparation (SHA-512 challenge hashing, canonicality checks, bit
    unpacking) and the device ladder; device dispatch is async, so host prep
    of batch i+1 overlaps device compute of batch i, exactly as the sidecar
    pipeline runs in production."""
    import jax.numpy as jnp

    from hotstuff_tpu.crypto import eddsa
    from hotstuff_tpu.ops import ed25519 as E

    def run(prev):
        prep = eddsa.prepare_batch(msgs, pks, sigs)
        assert prep["host_ok"].all()
        out = E.verify_packed_jit(jnp.asarray(prep["packed"]))
        return out

    mask = run(None)  # compile + warmup
    assert np.asarray(mask).all(), "benchmark signatures must verify"
    t0 = time.perf_counter()
    pending = None
    for _ in range(REPS):
        pending = run(pending)
    pending.block_until_ready()
    dt = time.perf_counter() - t0
    return N * REPS / dt


def main():
    from hotstuff_tpu.ops import field25519

    field25519.mul_selfcheck()  # trip fast if this backend's conv is inexact
    msgs, pks, sigs = make_batch()
    cpu = cpu_baseline(msgs, pks, sigs)
    tpu = tpu_throughput(msgs, pks, sigs)
    print(json.dumps({
        "metric": "ed25519-batch-verify",
        "value": round(tpu, 1),
        "unit": "sigs/sec",
        "vs_baseline": round(tpu / cpu, 3),
    }))


if __name__ == "__main__":
    main()

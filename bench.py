"""Headline benchmark: Ed25519 batch verification throughput on one TPU chip.

Prints ONE JSON line (re-printed, improving, after every timed trial —
the driver's bounded run takes the last):
  {"metric": "ed25519-batch-verify", "value": <sigs/sec on TPU>,
   "unit": "sigs/sec", "vs_baseline": <TPU / single-core-CPU>}

The baseline is the same machine's single-core CPU verifying the same
signatures one-by-one through the `cryptography` library (OpenSSL's
optimized C/asm Ed25519) — the honest stand-in for the reference's
ed25519-dalek verify path (crypto/src/lib.rs:204-208), measured fresh at
every run.  North star (BASELINE.json): >= 10x single-core CPU, measured
here over rounds of 16 sub-batches of 1024 (the sidecar's own maximum
bulk launch, MAX_COALESCED = 16 * MAX_SUBBATCH).

Measurement shape (see scripts/PROFILE.md round-5 notes): G sub-batches
of 1024 distinct (key, message, signature) triples are verified by ONE
jitted program per round (lax.scan over sub-batches, mask all-reduced
in-program so only ONE byte returns per round), with host preparation
AND the host->device transfer of round i+1 running on a prep thread
while the device executes round i — the tunneled chip charges ~13 MB/s
on h2d and ~70 ms per fetch, so overlap and fetch-minimization are what
separate the device's ~124k sigs/s ceiling from a transfer-bound 55k.

Tunnel-outage resilience: every improving trial persists the measured
line to results/headline_cache.json.  If the driver's bounded run hits a
dead tunnel (rounds 3 and 4 both lost their artifacts this way), the
bench emits the best previously MEASURED line, tagged
"source": "cached-measurement" with its timestamp, instead of a zero.

The cache is namespaced by a hash of the kernel sources (bench.py, the
ops/crypto files the measurement exercises): a best recorded by OLD code
can never answer for regressed HEAD — after any kernel edit the cache
starts empty.  When a live run completes, the LIVE measurement is always
the headline `value`; a higher best-on-record (same kernel hash, i.e.
tunnel weather) rides along as `best_on_record` so the artifact shows
both without the ratchet hiding a regression (round-5 ADVICE.md high).

RLC headline (`"rlc"` field): per-signature vs random-linear-combination
verification (crypto/eddsa.verify_batch_rlc — one MSM per quorum) at
quorum sizes n in {4, 16, 64, 256}.  Per size:
  {"per_sig_sigs_per_s": float, "rlc_sigs_per_s": float,
   "speedup": float}          — or {"skipped": true} if the size budget
(HOTSTUFF_TPU_RLC_BUDGET seconds, default 300) ran out first.

Mesh RLC headline (`"mesh_rlc"` field): ENGINE-path mesh verification
throughput — per-signature-sharded (the ladder across every device) vs
RLC-sharded (one Straus MSM whose window sums shard over the mesh) — at
quorum sizes n in {64, 256, 1024}, measured through the same
pack -> dispatch -> fetch stages the sidecar engine drives, in a
subprocess pinned to an 8-device forced-host CPU mesh (this rig has one
tunneled chip; a pod run reuses the same probe).  Per size:
  {"per_sig_sharded_sigs_per_s": float, "rlc_sharded_sigs_per_s": float,
   "speedup": float}         — or {"skipped"/"error": ...}
(HOTSTUFF_TPU_MESH_RLC_BUDGET seconds, default 240, bounds the stage).

Committee-scale headline (`"committee_scale"` field, graftscale —
ROADMAP item 4): QC-shaped verify batches of 2f+1 votes for committee
sizes N in {100, 300, 1000}, measured through the engine-path mesh
entries — per-signature-sharded vs RLC-sharded vs the whole-backlog
chunked scan — in the same forced-host 8-device CPU-mesh subprocess as
mesh_rlc, reported as sigs/sec/CHIP.  Per committee:
  {"NX": {"quorum": int, "per_sig_sharded_sigs_per_s_chip": float,
   "rlc_sharded_sigs_per_s_chip": float, "scan_sigs_per_s_chip": float,
   "rlc_speedup": float}}    — or {"skipped"/"error": ...}
(HOTSTUFF_TPU_COMMITTEE_BUDGET seconds, default 240, bounds the stage;
the field rides BOTH the live and degraded JSON lines under the same
budget-derate/emit-or-die watchdog discipline as mesh_rlc/roofline).

MSM window-chunk sweep (`"msm_window_chunk"` field): RLC throughput at
n=256 with the Straus window chunk re-pinned to 4, 8 and 16 IN-PROCESS
(ops/ed25519.set_msm_window_chunk clears the jit caches per value — no
more subprocess per value).  Per chunk:
  {"chunkC": {"rlc_sigs_per_s": float}}   — or {"skipped"/"error": ...}.
PR 2 chose the default (8) by conv-group arithmetic; this field gives a
real v5e run the measurement to settle it (HOTSTUFF_TPU_MSM_SWEEP_BUDGET
seconds, default 180, bounds the sweep).

graftkern roofline (`"roofline"` field): measured sigs/sec/chip for the
LAX vs PALLAS kernel routes (ops/kern — HOTSTUFF_TPU_KERN) through
verify_batch_rlc at n in {64, 256, 1024}, next to an arithmetic int-op
roofline estimate per chip (roofline_estimate: per-sig op model +
HOTSTUFF_TPU_CHIP_INT_OPS), so kernel speedups are attributable as a
fraction of the same ceiling on every run.  Emitted on BOTH the live
and degraded lines; off-TPU pallas entries carry "interpreted": true
(the Pallas interpreter is not kernel performance and must never read
as it).  HOTSTUFF_TPU_ROOFLINE_BUDGET seconds (default 300) bounds the
stage; sizes/routes that miss it report {"skipped": true}.

graftview (`"viewchange"` field): batched vs per-signature TC assembly
latency at committee sizes N in {20, 100, 300} — the quorum's (2N/3+1)
timeout votes over the SHARED (round, high_qc_round) digest verified as
ONE eddsa.verify_batch launch (the QC-shaped batch the consensus core
now dispatches at view-change time) vs one reference verify per sender
(the old inline handle_timeout path, the N=100 fault-path wall).  Per
committee: {"quorum", "batched_ms", "per_sig_ms", "batched_sigs_per_s",
"per_sig_sigs_per_s", "speedup"} — or {"skipped"/"error": ...}; plus an
"eject" sub-field proving a tampered candidate fails the batch and the
per-signature fallback names exactly the signer set per-sig verification
rejects (acceptance bar in "ok").  HOTSTUFF_TPU_VIEWCHANGE_BUDGET
seconds (default 240) bounds the stage; emitted on BOTH the live and
degraded lines under the usual emit-or-die stage watchdogs.

Scheduler telemetry (`"sched"` field): the verifysched STATS counters of
a tiny in-process host-mode engine exercise (one latency QC + one bulk
batch through the real scheduler), round-tripped through the OP_STATS
wire encoding (protocol.encode_stats_reply -> decode_stats_body) so the
headline proves the telemetry pipeline end to end.  Schema:
sidecar/sched/stats.py snapshot().

graftchaos (`"chaos"` field): the fault timeline + per-event recovery
latencies of a fault plan (--fault-plan PATH|SPEC, or the
HOTSTUFF_TPU_FAULT_PLAN env, else a miniature default) run through the
real plan parser, PlanRunner, the logs/chaos-events.json round trip,
and hotstuff_tpu/chaos/recovery.summarize_recovery — the exact pipeline
a live `harness local --fault-plan` run reports through its summary.
Keys: plan_events, executed, recovered, injected_ok, max_recovery_ms,
events[] (each with t/target/action/wall/recovery_ms).

graftwan rides in the same field: `"chaos"."slo"` judges the probe's
recovery latencies against the per-fault-class SLO table (--slo
PATH|SPEC / HOTSTUFF_TPU_SLO, else chaos/slo.DEFAULT_SLO_MS) through
the same chaos/slo.judge the LogParser raises on, and `"chaos"."wan"`
proves the link-shape pipeline: the WAN spec (--wan PATH|SPEC /
HOTSTUFF_TPU_WAN, else a miniature default link) is parsed, compiled to
its per-host tc-netem command list, and realized by a real loopback
WanProxy whose shaped round trip, partition black-hole, and heal are
measured.  Keys: links, tc_commands, proxy_roundtrip_ms (one successful
shaped round trip; null when the shape defeats every attempt),
roundtrip_ok, partition_enforced, healed.

graftsurge (`"surge"` field): the overload-robustness pipeline proven
end to end — a seeded heavy-tailed multi-user generator
(harness/loadgen.py) offers 4x a modeled drain capacity into the REAL
verifysched scheduler + surge admission controller on a virtual clock,
with shed bulk feeding BUSY backoff hints back into the generator; plus
the OP_BUSY wire round trip (protocol v4) and the metrics-driven
recovery-to-baseline SLO judge on a synthetic blackout series.  Keys:
offered_x, latency {offered, shed, wait_p99_ms}, bulk {offered,
admitted, shed, deferred_by_busy}, fairness_violations,
bulk_before_latency, derate, busy_roundtrip, baseline_slo, and the
acceptance-bar "ok" (>=3x overload, consensus p99 bounded, sheds
bulk-before-latency, baseline SLO PASS).  Emitted on BOTH the live and
degraded lines.

graftguard (`"guard"` field): the supervised-verify-engine ladder proven
end to end — a host-mode VerifyEngine under a real LaunchGuard with
tight deadlines takes a scripted launch wedge (the chaos hook's `wedge`
knob, the same OP_CHAOS path a `sidecar wedge` fault-plan event drives),
answers the wedged latency batch with a mask bit-identical to
verify_batch, sheds bulk to BUSY during the crash-only reboot, re-warms,
passes the canary, and resumes device routing.  Keys: wedges, reboots,
canary_passes, quarantined_records, poisoned_records,
host_fallback_records, busy_during_reboot, busy_retry_after_ms,
masks_bit_identical, rewarmed, reboot_wall_ms, recovered, and the
acceptance bar "ok".  Emitted on BOTH the live and degraded lines.
Kill-proof emit rides with it: every emitted line is written to
results/last_line.json CACHE-FIRST, and SIGTERM/SIGALRM re-emit the best
line already measured before dying — an rc=124 round still yields a
parseable artifact.

grafttrace (`"trace"` field): the cross-layer tracing pipeline proven
end to end — synthetic replica logs with a known clock skew run
through the real node-TRACE parser, the RTT-midpoint offset estimator,
per-block stitching (one deliberately partial trace), the critical-path
p50/p99 breakdown, the graftscope protocol-v5 ctx join (one block with
a full sidecar chain, one verify-traced block without — join_rate 0.5,
verify:device sub-segment present), and a Chrome-trace JSON round trip
(the exact pipeline a live run's logs/trace.json artifact and "Commit
critical path" parser note come from).  Keys: blocks, complete,
segments ({name: {n, p50_ms, p99_ms}}), join ({committed, with_verify,
joined, rate}), join_rate, chrome_events, offset_applied_ms,
roundtrip_ok.

graftingress (`"users"` field): the signed-transaction ingress tier at
population scale — per user-population U in {1e5, 1e6}, the seeded
heavy-tailed generator (harness/loadgen.py, the C++ UserLoadModel's
twin) names which user each arrival belongs to, the probe derives that
user's Ed25519 keypair on first arrival through the bounded
crypto/txsign.UserKeyring LRU (exactly the client's derive-on-demand
discipline: 1e6 users never means 1e6 resident keys), signs each frame
with a seeded ~1% forgery mix, and drives the admission records through
a host-mode VerifyEngine as INGRESS_CTX-tagged OP_VERIFY_BULK batches —
the same (digest, pk, sig) triples and bulk-lane class the mempool
admission stage ships.  Per point: {"users", "txs", "distinct_users",
"key_derivations", "keyring_capacity", "forged_sent",
"forged_rejected", "forgery_rejection_rate", "verified",
"verified_goodput_sigs_per_s", "busy_rejected", "bulk_ingress_requests",
"bulk_ingress_sigs", "bulk_ingress_share"} — or {"skipped": true} past
the budget (HOTSTUFF_TPU_USERS_BUDGET seconds, default 240); acceptance
bar in "ok" (every forged rejected, every honest verified, the bulk
lane 100% ingress-fed).  Emitted on BOTH the live and degraded lines.

Degraded mode (`"degraded": true`): the device probe is capped at
HOTSTUFF_TPU_PROBE_ATTEMPTS tries (default 3) inside a
HOTSTUFF_TPU_PROBE_WINDOW-second window (default 600) AND inside the
remaining outer budget (HOTSTUFF_TPU_BENCH_DEADLINE seconds of total
wall clock, default 3000, minus elapsed and a fixed emit slack — the
round-5 fix: the driver's own hard timeout must never close on probe
retries, BENCH_r05.json rc=124).  When no device answers, the bench
falls back to JAX_PLATFORMS=cpu, measures the RLC + mesh_rlc headlines
there (CPU-backend sigs/sec — NOT comparable to TPU numbers, hence the
flag), and always emits one parseable JSON line before exiting 0.  A
dead tunnel can delay the artifact, never lose it.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

# Outer-budget bookkeeping: the driver wraps this bench in a hard
# `timeout` (rc=124 is the artifact-eating failure mode), so every
# internal retry window must be capped against what is LEFT of that
# budget, not just its own env knob.  HOTSTUFF_TPU_BENCH_DEADLINE is the
# total wall-clock budget in seconds, measured from process start
# (module import); the default assumes the driver's observed ~55-minute
# window minus margin.  _DEADLINE_SLACK is reserved so the degraded
# fallback can still measure and emit its JSON line INSIDE the window —
# the round-5 regression (BENCH_r05.json) was nine probe retries
# consuming the entire budget with nothing printed.
_BENCH_T0 = time.monotonic()
_DEADLINE_SLACK = 120.0


def bench_budget_s() -> float:
    raw = os.environ.get("HOTSTUFF_TPU_BENCH_DEADLINE", "").strip()
    try:
        return float(raw) if raw else 3000.0
    except ValueError:
        return 3000.0


def budget_left_s(now=time.monotonic) -> float:
    """Seconds of the outer budget left (can go negative)."""
    return bench_budget_s() - (now() - _BENCH_T0)

N = 1024          # sub-batch size; asserted == eddsa.MAX_SUBBATCH below
G = 16            # sub-batches per device dispatch
ROUNDS = 20       # timed pipelined rounds per trial: the steady state is
                  # transfer-bound (~155 ms/round h2d through the tunnel),
                  # so pipeline fill + final fetch are pure overhead —
                  # 20 rounds amortizes them to ~5% (6 rounds paid ~18%)
TRIALS = 4        # best-of: the tunneled TPU and the shared host CPU both
                  # drift +-40% with neighbor load; best-of-n measures the
                  # hardware, not the neighbors

CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "results", "headline_cache.json")

def kernel_fingerprint() -> str:
    """Hash of the kernel sources (the shared utils/xla_cache scheme —
    ops + crypto + the graftkern Pallas modules — plus bench.py itself);
    namespaces the headline cache so a stale best can only ever answer
    for the code that produced it.  The compile-cache manifest uses the
    same scheme, so one kernel edit invalidates both records together."""
    from hotstuff_tpu.utils.xla_cache import kernel_fingerprint as _kf

    return _kf(extra=("bench.py",))


def load_cache():
    try:
        with open(CACHE_PATH) as f:
            c = json.load(f)
        if c.get("value", 0) > 0 and \
                c.get("kernel") == kernel_fingerprint():
            return c
    except (OSError, ValueError):
        pass
    return None


def save_cache(value: float, vs_baseline: float, cpu: float):
    cached = load_cache()
    if cached and cached["value"] >= value:
        return
    # Honesty guard: a CPU-contended host (anything else running) starves
    # the single-core baseline and INFLATES the ratio.  Never store a
    # ratio whose baseline is far below the best baseline on record —
    # a contended run can only under-measure the TPU, never over-claim.
    if cached and cpu < 0.8 * cached.get("cpu_baseline", 0):
        return
    tmp = CACHE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "metric": "ed25519-batch-verify",
            "value": round(value, 1),
            "unit": "sigs/sec",
            "vs_baseline": round(vs_baseline, 3),
            "cpu_baseline": round(cpu, 1),
            "kernel": kernel_fingerprint(),
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
        }, f)
    os.replace(tmp, CACHE_PATH)


# Kill-proof emit (graftguard satellite; VERDICT's top-next "kill-proof
# BENCH emit"): every emitted line is remembered in-process AND written
# to disk CACHE-FIRST (before stdout), so a driver timeout that SIGKILLs
# mid-print — or an rc=124 round that never reaches the final emit —
# still leaves results/last_line.json as a parseable artifact, and the
# SIGTERM/SIGALRM handlers re-emit the best line already measured
# before dying (install_kill_handlers, called first thing in main()).
_LINE_CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "results", "last_line.json")
_LAST_LINE = None


def emit(value: float, vs_baseline: float, **extra):
    global _LAST_LINE
    line = {"metric": "ed25519-batch-verify", "value": round(value, 1),
            "unit": "sigs/sec", "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    _LAST_LINE = line
    try:
        tmp = _LINE_CACHE_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(line, f)
        os.replace(tmp, _LINE_CACHE_PATH)
    except OSError:
        pass  # the disk copy is belt-and-braces, never fatal
    print(json.dumps(line), flush=True)


def install_kill_handlers(exit=os._exit, signums=None):
    """SIGTERM/SIGALRM -> re-emit the best headline line this process
    already measured, then exit 0: the driver's bounded window closing
    (its `timeout` sends SIGTERM before the rc=124 SIGKILL) must never
    eat an artifact a wedged stage already earned.  Preference order:
    the last line THIS run emitted (partial stages included), else the
    best cached measurement for this exact kernel, else an explicit
    error line — always exactly one parseable JSON line.  ``exit`` is
    injectable for the regression test; returns the handler."""
    import signal as _signal

    def _handler(signum, frame):
        name = _signal.Signals(signum).name
        if _LAST_LINE is not None:
            out = dict(_LAST_LINE)
            out["killed"] = name
        else:
            cached = load_cache()
            if cached:
                out = {"metric": "ed25519-batch-verify",
                       "value": cached["value"], "unit": "sigs/sec",
                       "vs_baseline": cached["vs_baseline"],
                       "source": "cached-measurement",
                       "measured_at": cached.get("measured_at",
                                                 "unknown"),
                       "note": f"killed by {name} before any emit",
                       "killed": name}
            else:
                out = {"metric": "ed25519-batch-verify", "value": 0,
                       "unit": "sigs/sec", "vs_baseline": 0,
                       "killed": name,
                       "error": f"killed by {name} before any "
                                "measurement"}
        # ONE os.write of pre-encoded bytes, with a LEADING newline:
        # the signal may have interrupted emit() mid-print, and
        # appending to that torn prefix would weld two lines into one
        # unparseable last line.  The newline closes any partial line
        # first, so the handler's line is always whole — the driver
        # takes the last parseable line, and the torn fragment simply
        # fails parse.  (No buffered print here: os._exit would drop
        # it, and print() re-enters the interrupted stream machinery.)
        try:
            os.write(1, ("\n" + json.dumps(out) + "\n").encode("utf-8"))
        except OSError:
            pass
        exit(0)

    if signums is None:
        signums = (_signal.SIGTERM, _signal.SIGALRM)
    for s in signums:
        _signal.signal(s, _handler)
    return _handler


def emit_cached(cached, note: str, **extra):
    """The one shape for a cached-measurement line (dead-tunnel fallback
    AND slow-live-run fallback emit through here)."""
    emit(cached["value"], cached["vs_baseline"],
         source="cached-measurement",
         measured_at=cached.get("measured_at", "unknown"),
         note=note, **extra)


def emit_final(tpu: float, cpu: float, **extra):
    """Final emit after a completed live run: the LIVE measurement is the
    headline `value` — the driver records the last line, and a number
    this run's code did not achieve must never stand in for it.  A
    higher best-on-record (same kernel fingerprint, so the difference is
    tunnel weather, not code) rides along as secondary fields."""
    cached = load_cache()
    if cached and cached["value"] > round(tpu, 1):
        emit(tpu, tpu / cpu,
             best_on_record=cached["value"],
             best_vs_baseline=cached["vs_baseline"],
             best_measured_at=cached.get("measured_at", "unknown"),
             note="live run below best on record for this exact kernel "
                  "(tunnel weather)", **extra)
    else:
        emit(tpu, tpu / cpu, **extra)


def emit_cached_or_fail(reason: str, code: int = 3):
    """A dead tunnel should surface the best MEASURED number on record,
    not a zero: the cache only ever holds values a real run produced."""
    cached = load_cache()
    if cached:
        emit_cached(cached, reason)
        os._exit(0)
    emit(0, 0, error=reason)
    os._exit(code)


def rlc_compare(sizes=(4, 16, 64, 256), repeats: int = 2,
                budget_s: float | None = None) -> dict:
    """Time per-signature vs RLC batch verify at quorum sizes -> the
    headline ``rlc`` dict (see module docstring for the field schema).

    Signatures come from the pure-python reference signer — no external
    dependency, so the degraded CPU path can always run this.  Each
    size's first calls warm/compile both programs OUTSIDE the timed
    region; ``budget_s`` bounds the whole sweep (a cold XLA compile per
    shape is the dominant cost), and sizes that miss the budget report
    ``{"skipped": true}`` instead of stalling the bench window.
    """
    from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref

    t0 = time.perf_counter()
    rng = np.random.default_rng(7)
    nmax = max(sizes)
    msgs, pks, sigs = [], [], []
    for _ in range(nmax):
        sk = rng.bytes(32)
        msg = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msgs.append(msg)
        pks.append(pk)
        sigs.append(ref.sign(sk, msg))

    out = {}
    for n in sizes:
        if budget_s is not None and time.perf_counter() - t0 > budget_s:
            out[f"n{n}"] = {"skipped": True}
            continue
        m, p, s = msgs[:n], pks[:n], sigs[:n]
        stats = {}
        for name, fn in (("per_sig", eddsa.verify_batch),
                         ("rlc", eddsa.verify_batch_rlc)):
            # Explicit raise, not assert: python -O must not strip the
            # warmup call (the first timed round would eat the compile)
            # or the correctness guard.
            if not fn(m, p, s).all():         # warm/compile + correctness
                raise RuntimeError(f"{name} verify failed at n={n}")
            best = 0.0
            for _ in range(repeats):
                t = time.perf_counter()
                mask = fn(m, p, s)
                dt = time.perf_counter() - t
                if not mask.all():
                    raise RuntimeError(f"{name} verify failed at n={n}")
                best = max(best, n / dt)
            stats[f"{name}_sigs_per_s"] = round(best, 1)
        stats["speedup"] = round(
            stats["rlc_sigs_per_s"] / stats["per_sig_sigs_per_s"], 3)
        out[f"n{n}"] = stats
    return out


def _make_ref_sigs(n: int, seed: int = 11):
    """n distinct (msg, pk, sig) triples via the pure-python reference
    signer — no external dependency (the `cryptography` lib is not
    guaranteed on this image), so every bench mode can run this."""
    from hotstuff_tpu.crypto import ref_ed25519 as ref

    rng = np.random.default_rng(seed)
    msgs, pks, sigs = [], [], []
    for _ in range(n):
        sk = rng.bytes(32)
        msg = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msgs.append(msg)
        pks.append(pk)
        sigs.append(ref.sign(sk, msg))
    return msgs, pks, sigs


def _rlc_best_sigs_per_s(msgs, pks, sigs, n: int, repeats: int) -> float:
    """Warm/compile + correctness guard, then best-of-``repeats``
    verify_batch_rlc throughput at quorum size n — the one timing
    discipline the msm_window_chunk and roofline headlines share (a
    future change to it lands in both)."""
    from hotstuff_tpu.crypto import eddsa

    m, p, s = msgs[:n], pks[:n], sigs[:n]
    # Explicit raise, not assert: python -O must not strip the warmup
    # call or the correctness guard.
    if not eddsa.verify_batch_rlc(m, p, s).all():
        raise RuntimeError(f"RLC verify failed at n={n}")
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        mask = eddsa.verify_batch_rlc(m, p, s)
        dt = time.perf_counter() - t0
        if not mask.all():
            raise RuntimeError(f"RLC verify failed at n={n}")
        best = max(best, n / dt)
    return best


def msm_chunk_sweep(chunks=(4, 8, 16), n: int = 256,
                    budget_s: float = 240.0) -> dict:
    """RLC throughput at quorum size n under each MSM window-chunk
    value, IN-PROCESS: ops/ed25519.set_msm_window_chunk re-pins the
    constant and clears the jit caches, so the sweep no longer re-execs
    a subprocess per value (the old shape; the constant used to bind at
    import).  Results are bit-identical across chunk values — only the
    conv-group/scan-depth trade moves — so the sweep is pure timing.
    Chunks that miss the budget report {"skipped": true}; a failed
    measurement reports {"error": ...} and the default chunk is always
    restored — the sweep never takes the headline down with it.

    The sweep PINS the lax kernel route for its duration: the chunk
    knob only exists on the lax chunked-scan path (the pallas window
    accumulator grids single windows — ed25519.msm_window_sums
    documents the knob as inapplicable there), so sweeping under
    HOTSTUFF_TPU_KERN=pallas would measure one identical program three
    times and read as "chunk doesn't matter"."""
    from hotstuff_tpu.ops import ed25519 as E
    from hotstuff_tpu.ops import kern

    t0 = time.perf_counter()
    default_chunk = E.msm_window_chunk()
    ambient_mode = kern.mode()
    msgs, pks, sigs = _make_ref_sigs(n)
    out = {}
    try:
        kern.set_mode("lax")
        for chunk in chunks:
            left = budget_s - (time.perf_counter() - t0)
            if left <= 0:
                out[f"chunk{chunk}"] = {"skipped": True}
                continue
            try:
                E.set_msm_window_chunk(chunk)
                best = _rlc_best_sigs_per_s(msgs, pks, sigs, n, repeats=2)
                out[f"chunk{chunk}"] = {"rlc_sigs_per_s": round(best, 1)}
            except Exception as e:  # noqa: BLE001 — per-chunk isolation
                out[f"chunk{chunk}"] = {"error": f"{e!r:.200}"}
    finally:
        E.set_msm_window_chunk(default_chunk)
        kern.set_mode(ambient_mode)
    return out


def roofline_estimate() -> dict:
    """Arithmetic int-op roofline for one chip — the yardstick the
    ``roofline`` headline measures the lax and pallas paths against.

    Per-signature integer-op model of the RLC verify path (the
    quorum-certificate steady state), from the op counts the ops/
    modules document:

      * one field mul = 32x63 MAC pairs (conv) + the wrap-38 fold +
        4 parallel carry steps over 32 limbs (~4 ops each);
      * decompression: ~265 muls per point (the pow_p58 chain dominates)
        x 2 points (A, R) per signature;
      * MSM: per-point 16-entry table build (14 point adds x 8 muls +
        16 to_cached muls = 128 muls/point) + 64 windows of amortized
        ~1 tree add/point (8 muls + amortized to_cached ~0.5) x
        2 points/sig; scalar mod-L products are noise next to these.

    The per-chip int-op rate defaults to a v5e-class VPU estimate
    (8 x 128 lanes x 2 int ops/cycle x ~0.94 GHz ~= 1.9e12); override
    with HOTSTUFF_TPU_CHIP_INT_OPS (and name the chip via
    HOTSTUFF_TPU_CHIP) when benching other silicon.  An estimate with
    stated knobs, not a measurement — its job is making measured
    sigs/sec/chip numbers attributable as a fraction of the ceiling."""
    ops_per_mul = 32 * 63 * 2 + 63 + 4 * 32 * 4          # ~4.6e3
    muls_decompress = 2 * 265                            # A and R
    muls_table = 2 * (14 * 8 + 16)
    muls_windows = 2 * 64 * (8 + 4)  # tree add + amortized cached/horner
    muls_per_sig = muls_decompress + muls_table + muls_windows
    int_ops_per_sig = muls_per_sig * ops_per_mul
    chip = os.environ.get("HOTSTUFF_TPU_CHIP", "v5e")
    try:
        chip_int_ops = float(
            os.environ.get("HOTSTUFF_TPU_CHIP_INT_OPS", "1.9e12"))
    except ValueError:
        chip_int_ops = 1.9e12
    return {
        "model": "rlc-straus int-op estimate",
        "field_muls_per_sig": muls_per_sig,
        "int_ops_per_sig": int_ops_per_sig,
        "chip": chip,
        "chip_int_ops_per_s": chip_int_ops,
        "roofline_sigs_per_s_chip": round(chip_int_ops / int_ops_per_sig,
                                          1),
    }


def roofline_headline(sizes=(64, 256, 1024), repeats: int = 2,
                      budget_s: float | None = None) -> dict:
    """The headline ``roofline`` field: measured sigs/sec/chip for the
    LAX vs PALLAS kernel routes at quorum sizes n, next to the
    arithmetic roofline estimate — so a graftkern speedup (or
    regression) is attributable against the same ceiling on every run.

    Measures verify_batch_rlc (the QC hot path) per route via
    ops/kern.set_mode, which clears the jit caches between routes so
    each measurement compiles its own programs; the ambient mode is
    restored afterwards.  Off-TPU the pallas route runs the kernel
    INTERPRETER — orders of magnitude slower and flagged per-entry as
    ``interpreted`` so a degraded line can never pass interpreter
    numbers off as kernel performance.  Budget-capped like every
    headline stage (HOTSTUFF_TPU_ROOFLINE_BUDGET, default 300 s):
    sizes/routes that miss the budget report {"skipped": true}; a
    failed route reports {"error": ...}.  Emitted on BOTH the live and
    degraded JSON lines."""
    from hotstuff_tpu.ops import kern

    if budget_s is None:
        budget_s = float(
            os.environ.get("HOTSTUFF_TPU_ROOFLINE_BUDGET", "300"))
    est = roofline_estimate()
    out = {"est": est, "chips": 1, "kern_default": kern.mode()}
    if budget_s <= 0:
        out["skipped"] = True
        return out
    t0 = time.perf_counter()
    msgs, pks, sigs = _make_ref_sigs(max(sizes), seed=29)
    ambient = kern.mode()
    interpreted = kern.interpret_default()
    roof = est["roofline_sigs_per_s_chip"]
    try:
        for n in sizes:
            stats = {}
            for route in ("lax", "pallas"):
                if time.perf_counter() - t0 > budget_s:
                    stats[route] = {"skipped": True}
                    continue
                try:
                    kern.set_mode(route)
                    best = _rlc_best_sigs_per_s(msgs, pks, sigs, n,
                                                repeats)
                    entry = {"sigs_per_s_chip": round(best, 1),
                             "pct_of_roofline": round(100.0 * best / roof,
                                                      2)}
                    if route == "pallas" and interpreted:
                        entry["interpreted"] = True
                    stats[route] = entry
                except Exception as e:  # noqa: BLE001 — route isolation
                    stats[route] = {"error": f"{e!r:.200}"}
            lax_v = stats.get("lax", {}).get("sigs_per_s_chip")
            pal_v = stats.get("pallas", {}).get("sigs_per_s_chip")
            if lax_v and pal_v:
                stats["pallas_speedup"] = round(pal_v / lax_v, 3)
            out[f"n{n}"] = stats
    finally:
        kern.set_mode(ambient)
    return out


def mesh_rlc_probe(n_devices: int = 8, sizes=(64, 256, 1024),
                   repeats: int = 2, budget_s: float = 240.0):
    """Child half of the ``mesh_rlc`` headline: measure ENGINE-path mesh
    throughput — per-signature-sharded (verify_batch_sharded_pack, the
    ladder across every device) vs RLC-sharded (verify_rlc_sharded_pack,
    one Straus MSM whose window sums shard over the mesh) — at quorum
    sizes n, through the same pack -> dispatch -> fetch stages the
    sidecar engine drives (host preparation included in the timed
    region, exactly as the engine pays it).  Prints one JSON line.
    Run via a subprocess pinned to a forced-host CPU mesh (the parent,
    mesh_rlc_headline, sets JAX_PLATFORMS=cpu +
    --xla_force_host_platform_device_count)."""
    from hotstuff_tpu.crypto import eddsa
    from hotstuff_tpu.parallel import sharded_verify as shv
    from hotstuff_tpu.parallel.mesh import make_mesh
    from hotstuff_tpu.utils.xla_cache import configure_xla_cache

    configure_xla_cache()
    t0 = time.perf_counter()
    mesh = make_mesh(n_devices)
    msgs, pks, sigs = _make_ref_sigs(max(sizes), seed=17)
    def emit_progress(out):
        # One line per size (completed OR skipped): if the parent's
        # subprocess timeout kills this child mid-compile, everything
        # decided so far still reaches the headline (the parent parses
        # the LAST parseable line of the partial stdout).
        print(json.dumps({"mesh_rlc": out, "n_devices": n_devices}),
              flush=True)

    out = {}
    for n in sizes:
        if time.perf_counter() - t0 > budget_s:
            out[f"n{n}"] = {"skipped": True}
            emit_progress(out)
            continue
        stats = {}
        for name, pack in (
                ("per_sig_sharded",
                 lambda p: shv.verify_batch_sharded_pack(mesh, p)),
                ("rlc_sharded",
                 lambda p: shv.verify_rlc_sharded_pack(mesh, p))):
            # Warm/compile + correctness guard outside the timed region
            # (explicit raise: python -O must not strip either).
            prep = eddsa.prepare_batch(msgs[:n], pks[:n], sigs[:n])
            if not pack(prep)()().all():
                raise RuntimeError(f"{name} verify failed at n={n}")
            best = 0.0
            for _ in range(repeats):
                t = time.perf_counter()
                prep = eddsa.prepare_batch(msgs[:n], pks[:n], sigs[:n])
                mask = pack(prep)()()
                dt = time.perf_counter() - t
                if not mask.all():
                    raise RuntimeError(f"{name} verify failed at n={n}")
                best = max(best, n / dt)
            stats[f"{name}_sigs_per_s"] = round(best, 1)
        stats["speedup"] = round(stats["rlc_sharded_sigs_per_s"]
                                 / stats["per_sig_sharded_sigs_per_s"], 3)
        out[f"n{n}"] = stats
        emit_progress(out)
    if not out:
        emit_progress(out)


def _forced_host_mesh_headline(field: str, probe_call: str,
                               n_devices: int, budget_s: float) -> dict:
    """Shared parent of the forced-host CPU-mesh probe headlines
    (``mesh_rlc``, ``committee_scale``): run the named probe in a
    subprocess pinned to an n-device virtual mesh (this rig has ONE
    tunneled chip, so mesh-routing wins are measured on the virtual
    mesh — identical program structure, honest relative numbers; a
    real pod run reuses the same probes), parse the LAST parseable
    progress line, and salvage a partial measurement when the child
    times out mid-compile.  Failures degrade to an ``error`` entry,
    never take the headline down."""
    import re
    import subprocess
    import sys

    if budget_s <= 0:
        return {"skipped": True}
    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    # The TPU PJRT plugin (sitecustomize) overrides JAX_PLATFORMS; the
    # child must flip the platform via jax.config before any
    # backend-initializing call (same dance as dryrun_multichip).
    code = ("import jax; jax.config.update('jax_platforms', 'cpu')\n"
            f"import bench; bench.{probe_call}\n")
    def _last_line(stdout):
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        lines = (stdout or "").strip().splitlines()
        return json.loads(lines[-1]) if lines else None

    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], cwd=root, env=env,
            capture_output=True, text=True, timeout=budget_s + 120.0,
            check=True)
        line = _last_line(proc.stdout)
        if line is None:
            return {"error": "probe child printed nothing"}
        return line[field]
    except subprocess.TimeoutExpired as e:
        # The child emits one line per completed size: salvage whatever
        # it finished before the timeout (first-boot XLA compiles can
        # eat the whole budget; the persistent cache makes the next run
        # complete) — a partial measurement beats none.
        try:
            line = _last_line(e.stdout)
            if line is not None:
                out = line[field]
                out["timeout"] = True
                return out
        except (ValueError, KeyError, TypeError):
            pass
        return {"error": f"{e!r:.160}"}
    except Exception as e:  # noqa: BLE001 — headline isolation
        detail = ""
        if isinstance(e, subprocess.CalledProcessError):
            detail = (e.stderr or "")[-200:]
        return {"error": f"{e!r:.120}{detail}"}


def mesh_rlc_headline(n_devices: int = 8,
                      budget_s: float | None = None) -> dict:
    """Parent half of the ``mesh_rlc`` headline field: run
    :func:`mesh_rlc_probe` on the forced-host CPU mesh (see
    :func:`_forced_host_mesh_headline` for the subprocess contract)."""
    if budget_s is None:
        budget_s = float(
            os.environ.get("HOTSTUFF_TPU_MESH_RLC_BUDGET", "240"))
    return _forced_host_mesh_headline(
        "mesh_rlc", f"mesh_rlc_probe({n_devices}, budget_s={budget_s})",
        n_devices, budget_s)


def committee_scale_probe(n_devices: int = 8,
                          committees=(100, 300, 1000),
                          repeats: int = 2,
                          budget_s: float = 240.0) -> dict:
    """Child half of the ``committee_scale`` headline (graftscale):
    sweep QC-shaped verify batches — 2f+1 votes for committee sizes
    N — through the ENGINE-path mesh entries, per route:

      * ``per_sig_sharded``  — verify_batch_sharded_pack, the scalar
        ladder data-parallel across every device;
      * ``rlc_sharded``      — verify_rlc_sharded_pack, ONE Straus MSM
        whose window sums shard over the mesh (the path the scheduler
        routes a warmed giant-committee QC batch down);
      * ``scan``             — verify_sharded_chunked_pack, the
        whole-backlog chunked mesh scan draining the batch in ONE
        dispatch (the graftscale bulk route).

    Each measurement pays the full pack -> dispatch -> fetch stages the
    sidecar engine drives (host preparation included), reported as
    sigs/sec/CHIP so committee sizes compare on one axis.  Prints one
    JSON progress line per completed committee (the parent salvages a
    partial sweep) and returns the dict (the in-process schema test).
    Committee sizes that miss ``budget_s`` report {"skipped": true}."""
    from hotstuff_tpu.crypto import eddsa
    from hotstuff_tpu.parallel import sharded_verify as shv
    from hotstuff_tpu.parallel.mesh import make_mesh
    from hotstuff_tpu.sidecar.sched.shapes import quorum_sigs
    from hotstuff_tpu.utils.xla_cache import configure_xla_cache

    configure_xla_cache()
    t0 = time.perf_counter()
    mesh = make_mesh(n_devices)
    nmax = quorum_sigs(max(committees))
    msgs, pks, sigs = _make_ref_sigs(nmax, seed=19)
    # The scan column must measure the MULTI-chunk whole-backlog
    # structure the engine's scan route dispatches (a rows=None default
    # would collapse every quorum to a degenerate one-chunk scan): pick
    # the chunk rows so the batch drains as SCAN_CHUNKS chunks, the
    # same g-chunks-of-warmed-rows program shape _warmup_mesh_scan
    # compiles.
    SCAN_CHUNKS = 4

    def scan_rows_for(n):
        from hotstuff_tpu.parallel.shard_shapes import shard_bucket

        return shard_bucket(-(-n // SCAN_CHUNKS), n_devices)

    def emit_progress(out):
        print(json.dumps({"committee_scale": out,
                          "n_devices": n_devices}), flush=True)

    out = {}
    for committee in committees:
        n = quorum_sigs(committee)
        if time.perf_counter() - t0 > budget_s:
            out[f"N{committee}"] = {"quorum": n, "skipped": True}
            emit_progress(out)
            continue
        stats = {"quorum": n}
        for name, pack in (
                ("per_sig_sharded",
                 lambda p: shv.verify_batch_sharded_pack(mesh, p)),
                ("rlc_sharded",
                 lambda p: shv.verify_rlc_sharded_pack(mesh, p)),
                ("scan",
                 lambda p, r=scan_rows_for(n):
                 shv.verify_sharded_chunked_pack(mesh, p, rows=r))):
            # Warm/compile + correctness guard outside the timed region
            # (explicit raise: python -O must not strip either).
            prep = eddsa.prepare_batch(msgs[:n], pks[:n], sigs[:n])
            if not pack(prep)()().all():
                raise RuntimeError(
                    f"{name} verify failed at quorum {n}")
            best = 0.0
            for _ in range(repeats):
                t = time.perf_counter()
                prep = eddsa.prepare_batch(msgs[:n], pks[:n], sigs[:n])
                mask = pack(prep)()()
                dt = time.perf_counter() - t
                if not mask.all():
                    raise RuntimeError(
                        f"{name} verify failed at quorum {n}")
                best = max(best, n / dt)
            stats[f"{name}_sigs_per_s_chip"] = round(best / n_devices, 1)
        stats["rlc_speedup"] = round(
            stats["rlc_sharded_sigs_per_s_chip"]
            / stats["per_sig_sharded_sigs_per_s_chip"], 3)
        out[f"N{committee}"] = stats
        emit_progress(out)
    if not out:
        emit_progress(out)
    return out


def committee_scale_headline(n_devices: int = 8,
                             budget_s: float | None = None) -> dict:
    """Parent half of the ``committee_scale`` headline field
    (graftscale, ROADMAP item 4): run :func:`committee_scale_probe`
    for N in {100, 300, 1000} on the forced-host CPU mesh (see
    :func:`_forced_host_mesh_headline` for the subprocess contract;
    HOTSTUFF_TPU_COMMITTEE_BUDGET seconds, default 240, bounds the
    stage)."""
    if budget_s is None:
        budget_s = float(
            os.environ.get("HOTSTUFF_TPU_COMMITTEE_BUDGET", "240"))
    return _forced_host_mesh_headline(
        "committee_scale",
        f"committee_scale_probe({n_devices}, budget_s={budget_s})",
        n_devices, budget_s)


def trace_headline_probe() -> dict:
    """The headline's ``trace`` field: prove the grafttrace pipeline end
    to end without booting a committee.  Synthetic replica logs with a
    KNOWN clock skew run through the REAL node-TRACE parser
    (obs/trace.py — the exact regex that mines live node logs), the
    RTT-midpoint offset estimator, per-block stitching (one block's
    trace is deliberately partial: a dropped span must degrade the
    sample count, not the breakdown), the critical-path percentiles,
    the graftscope ctx join (block aaa= carries a full sidecar chain,
    block ccc= verifies but has none — join_rate must come out 0.5 and
    the device sub-segment must appear), and a Chrome-trace JSON
    serialization round trip.  Keys: blocks, complete, segments
    ({name: {n, p50_ms, p99_ms}}), join ({committed, with_verify,
    joined, rate}), join_rate, chrome_events, offset_applied_ms,
    roundtrip_ok."""
    import json as _json

    from hotstuff_tpu.obs import trace as obstrace

    def line(sec, stage, block, rnd):
        return (f"[2026-08-03T12:00:{sec:06.3f}Z INFO consensus::core] "
                f"TRACE stage={stage} block={block} round={rnd}")

    # Replica 0: the reference clock.  Block bbb='s trace is partial
    # (no verify stages — the cached-certificate path); block ccc=
    # verifies but its sidecar chain is deliberately MISSING (every
    # replica answered from the verdict-cache fast path), so the join
    # rate must degrade, not the trace.
    log_a = "\n".join([
        line(1.000, "proposal", "aaa=", 2),
        line(1.010, "verify_submit", "aaa=", 2),
        line(1.034, "verify_reply", "aaa=", 2),
        line(1.050, "commit", "aaa=", 2),
        line(1.100, "proposal", "bbb=", 3),
        line(1.180, "commit", "bbb=", 3),
        line(1.200, "proposal", "ccc=", 4),
        line(1.210, "verify_submit", "ccc=", 4),
        line(1.230, "verify_reply", "ccc=", 4),
        line(1.260, "commit", "ccc=", 4),
    ])
    # Replica 1: same events observed later, stamped by a clock running
    # a known skew AHEAD — alignment must bring them back onto (not
    # before) the reference observations.
    skew_s = 0.125
    log_b = "\n".join([
        line(1.020 + skew_s, "proposal", "aaa=", 2),
        line(1.060 + skew_s, "commit", "aaa=", 2),
    ])
    spans = obstrace.parse_node_trace(log_a, host="node-0.log")
    spans_b = obstrace.parse_node_trace(log_b, host="node-1.log")
    # Offset probe with synthetic stamps: local sends at t, the skewed
    # host answers mid-flight, local receives at t + rtt.
    rtt = 0.004
    probes = [(t, t + rtt / 2 + skew_s, t + rtt) for t in (5.0, 6.0, 7.0)]
    offset = obstrace.estimate_offset(probes)
    spans += obstrace.apply_offset(spans_b, offset)
    traces = obstrace.stitch_blocks(spans)
    summary = obstrace.critical_path(traces)
    # Sidecar chain for block aaa= only: per-request spans tagged ctx,
    # the launch-level device span tagged ctxs — the protocol-v5 schema
    # the live sidecar emits.
    sidecar_spans = [
        {"stage": "admit", "t": 1785751201.005, "dur_ms": 0.0, "rid": 1,
         "cls": "latency", "ctx": "aaa="},
        {"stage": "queue", "t": 1785751201.01, "dur_ms": 1.5, "rid": 1,
         "cls": "latency", "ctx": "aaa="},
        {"stage": "device", "t": 1785751201.02, "dur_ms": 18.0, "rid": 1,
         "ctxs": ["aaa="]},
        {"stage": "reply", "t": 1785751201.04, "dur_ms": 0.0, "rid": 1,
         "cls": "latency", "ctx": "aaa="},
    ]
    join, joined = obstrace.join_blocks(
        traces, obstrace.chain_spans(sidecar_spans))
    if joined:
        summary["segments"][obstrace.DEVICE_SEGMENT] = \
            obstrace.device_subsegment(joined)
    chrome = obstrace.chrome_trace(traces, sidecar_spans, joined=joined)
    decoded = _json.loads(_json.dumps(chrome))
    events = decoded.get("traceEvents", [])
    roundtrip_ok = (
        len(events) == len(chrome["traceEvents"])
        and all(e.get("ph") in ("X", "M") for e in events)
        and all(isinstance(e.get("ts", 0), (int, float)) for e in events)
        # the joined chain must land nested in the block's row
        and any(e.get("name") == "sidecar:device"
                and e.get("args", {}).get("block") == "aaa="
                for e in events))
    return {
        "blocks": summary["blocks"],
        "complete": summary["complete"],
        "segments": summary["segments"],
        "join": join,
        "join_rate": join["rate"],
        "chrome_events": len(events),
        "offset_applied_ms": round(offset * 1e3, 3),
        "roundtrip_ok": roundtrip_ok,
    }


def sched_headline_probe() -> dict:
    """Round-trip the verifysched STATS counters through the wire
    encoding and return the decoded snapshot for the headline's "sched"
    field: a host-mode VerifyEngine verifies one latency-class QC and one
    bulk-class batch through the real scheduler, then the snapshot goes
    protocol.encode_stats_reply -> decode_reply_raw -> decode_stats_body
    — the exact bytes a sidecar client would see."""
    import threading

    from hotstuff_tpu.sidecar import protocol as proto
    from hotstuff_tpu.sidecar import sched as vsched
    from hotstuff_tpu.sidecar.service import VerifyEngine

    msgs, pks, sigs = _make_ref_sigs(6, seed=23)
    engine = VerifyEngine(use_host=True)
    try:
        done = []
        cond = threading.Condition()

        def reply(mask):
            with cond:
                done.append(mask)
                cond.notify()

        engine.submit(proto.VerifyRequest(1, msgs[:4], pks[:4], sigs[:4]),
                      reply, cls=vsched.LATENCY)
        engine.submit(proto.VerifyRequest(2, msgs[4:], pks[4:], sigs[4:]),
                      reply, cls=vsched.BULK)
        with cond:
            cond.wait_for(lambda: len(done) == 2, timeout=60.0)
        frame = proto.encode_stats_reply(7, engine.stats_snapshot())
        opcode, rid, body = proto.decode_reply_raw(frame[4:])
        if (opcode, rid) != (proto.OP_STATS, 7):
            raise RuntimeError("stats reply framing mismatch")
        return proto.decode_stats_body(body)
    finally:
        engine.stop()


# --fault-plan/--wan/--slo pass-through (set by main(); run_degraded
# reads them so the degraded line carries the same chaos field as a
# healthy one).
_FAULT_PLAN = None
_WAN_SPEC = None
_SLO_SPEC = None

# Miniature default plan for the headline probe: one of every fault
# class — including a graftsurge flash crowd — timed inside a tenth of
# a (virtual) second.
_DEFAULT_CHAOS_SPEC = ("0.01 sidecar kill; 0.04 sidecar restart; "
                       "0.02 node:1 pause; 0.05 node:1 resume; "
                       "0.06 sidecar degrade shed=1; "
                       "0.07 client:0 surge x5 for 0.02")

# Miniature default WAN spec for the headline probe: one shaped
# node->sidecar link, small enough that the loopback proxy round trip
# stays in the tens of milliseconds.
_DEFAULT_WAN_SPEC = "node:0>sidecar latency_ms=5 name=probe-link"


def wan_headline_probe(wan_spec=None) -> dict:
    """The ``chaos.wan`` sub-field: prove the graftwan pipeline end to
    end without a committee or root.  The spec (--wan, or a miniature
    default) runs through the REAL parser, is compiled to the per-host
    ``tc netem`` command list a fleet run would install, and is then
    realized by a real loopback WanProxy: a byte round-trips through the
    shaped link (paying its latency both ways), ``partition()`` must
    black-hole a fresh connection, and ``heal()`` must restore it — the
    exact executors a live ``--wan`` run uses, local and remote."""
    import socket as _socket
    import threading as _threading

    from hotstuff_tpu.chaos import WanProxy, parse_wan
    from hotstuff_tpu.chaos.netem import tc_setup_commands

    spec = parse_wan(wan_spec if wan_spec else _DEFAULT_WAN_SPEC)
    peers = {"node:0": "10.0.0.10", "node:1": "10.0.0.11",
             "sidecar": "10.0.0.99"}
    tc_commands = sum(
        len(tc_setup_commands(spec, f"node:{i}", peers)) for i in range(2))

    # Loopback echo server the proxy forwards to.
    server = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    server.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", 0))
    server.listen(4)
    server.settimeout(10.0)

    def _echo():
        try:
            while True:
                conn, _ = server.accept()
                conn.settimeout(10.0)
                try:
                    data = conn.recv(64)
                    if data:
                        conn.sendall(data)
                finally:
                    conn.close()
        except OSError:
            pass

    _threading.Thread(target=_echo, daemon=True).start()
    shape = spec.links[0].shape if spec.links else None
    proxy = WanProxy(server.getsockname(), shape=shape)
    proxy.start()
    try:
        if not proxy.wait_ready(10.0):
            raise RuntimeError("WanProxy readiness gate never passed")
        def _roundtrip():
            with _socket.create_connection(("127.0.0.1", proxy.port),
                                           timeout=10.0) as c:
                c.settimeout(10.0)
                c.sendall(b"ping")
                return c.recv(64)

        def _try_roundtrip(attempts=5):
            # A lossy shape DROPS connections by design (see WanProxy);
            # a dialing peer just reconnects, so the probe does too.  A
            # spec lossy enough to defeat every attempt reports
            # ok/healed False rather than erroring the whole sub-field.
            # Returns the RTT of the one SUCCESSFUL attempt (None if
            # all fail): timing the whole retry loop would fold failed
            # dials and dropped attempts into the published number.
            for _ in range(attempts):
                try:
                    t0 = time.perf_counter()
                    if _roundtrip() == b"ping":
                        return (time.perf_counter() - t0) * 1e3
                except OSError:
                    pass
            return None

        rtt_ms = _try_roundtrip()
        proxy.partition()
        try:
            partitioned = _roundtrip() != b"ping"
        except OSError:
            partitioned = True  # dropped connection IS the black-hole
        proxy.heal()
        healed = _try_roundtrip() is not None
        return {
            "links": spec.link_names(),
            "tc_commands": tc_commands,
            "proxy_roundtrip_ms": round(rtt_ms, 3)
            if rtt_ms is not None else None,
            "roundtrip_ok": rtt_ms is not None,
            "partition_enforced": partitioned,
            "healed": healed,
        }
    finally:
        proxy.stop()
        server.close()


def chaos_headline_probe(plan_spec=None, wan_spec=None,
                         slo_spec=None) -> dict:
    """The headline's ``chaos`` field: prove the graftchaos pipeline end
    to end without booting a committee.  The fault plan (the passed
    ``--fault-plan``, or a miniature default) runs through the REAL
    parser and PlanRunner against a recording injector on a virtual
    clock (instant, regardless of the plan's timescale); the executed
    events round-trip through the JSON contract the harness writes to
    logs/chaos-events.json; and recovery latencies come from the same
    ``summarize_recovery`` the LogParser folds into a live run summary —
    commits are synthesized 250 ms after each event, so a healthy
    pipeline reports ``recovered: true`` with per-event latencies.

    graftwan: the recoveries are additionally judged against the
    per-fault-class SLO table (``slo`` sub-field, chaos/slo.judge — the
    same verdicts the LogParser raises on), and the WAN link-shape
    pipeline is proven by ``wan_headline_probe`` (``wan`` sub-field)."""
    import json as _json

    from hotstuff_tpu.chaos import PlanRunner, judge, parse_plan, \
        parse_slos, summarize_recovery

    plan = parse_plan(plan_spec if plan_spec else _DEFAULT_CHAOS_SPEC)

    class _NullInjector:
        def apply(self, event):
            pass  # the probe measures the pipeline, not real processes

    base_wall = 1_700_000_000.0
    now = [0.0]

    def clock():
        return now[0]

    def sleep(dt):
        now[0] += dt

    runner = PlanRunner(plan, _NullInjector(), clock=clock, sleep=sleep,
                        wall=lambda: base_wall + now[0])
    runner.start(t0=0.0)
    runner.join(timeout=60.0)
    # The on-disk/wire contract: what the harness persists is what the
    # parser reads back.
    events = _json.loads(_json.dumps(runner.events()))
    commits = [e["wall"] + 0.25 for e in events]
    summary = summarize_recovery(events, commits)
    slo_verdict = judge(summary, parse_slos(slo_spec))
    try:
        wan = wan_headline_probe(wan_spec)
    except Exception as e:  # noqa: BLE001 — sub-probe isolation
        wan = {"error": f"{e!r:.120}"}
    return {
        "plan_events": len(plan.events),
        "executed": len(events),
        "recovered": summary["recovered"],
        "injected_ok": summary["injected_ok"],
        "max_recovery_ms": summary["max_recovery_ms"],
        "events": summary["events"],
        "slo": slo_verdict,
        "wan": wan,
    }


def surge_headline_probe(offered_x: float = 4.0,
                         seconds: float = 3.0) -> dict:
    """The headline's ``surge`` field: prove the graftsurge overload
    pipeline end to end without booting a committee.

    A seeded heavy-tailed multi-user generator (harness/loadgen.py, the
    python twin of the C++ client's UserLoadModel) offers ``offered_x``
    times a modeled drain capacity of BULK verify work, plus a steady
    consensus-class stream, into the REAL verifysched scheduler with its
    REAL surge admission controller on a virtual clock.  Shed bulk
    requests feed BUSY backoff hints back into the generator — the full
    backpressure loop.  The probe then proves the OP_BUSY wire round
    trip (protocol v4 encode -> decode -> SidecarOverloaded with the
    retry hint attached) and the metrics-driven recovery-to-baseline SLO
    judge on a synthetic sampled series with a blackout.

    The acceptance bar rides in ``ok``: at >= 3x offered overload the
    consensus-class wait p99 stays bounded (no queue collapse), sheds
    are bulk-before-latency (zero latency sheds, zero fairness
    violations), and the surge event is judged PASS by the
    recovery-to-baseline judge."""
    from hotstuff_tpu.chaos import judge_baseline_recovery
    from hotstuff_tpu.harness.loadgen import UserLoad
    from hotstuff_tpu.sidecar import protocol as proto
    from hotstuff_tpu.sidecar import sched as vsched
    from hotstuff_tpu.sidecar.client import SidecarClient, \
        SidecarOverloaded

    TICK_S = 0.01
    CAP_SIGS_PER_TICK = 128       # modeled device drain per tick
    QC_SIGS = 16                  # one consensus verify
    LAT_PER_TICK = 2              # consensus offers per tick (well
                                  # under capacity: it must never shed)
    BULK_REQ_SIGS = 32
    cap_sigs_per_s = CAP_SIGS_PER_TICK / TICK_S
    bulk_req_rate = offered_x * cap_sigs_per_s / BULK_REQ_SIGS

    sched = vsched.Scheduler(latency_cap_sigs=4 * 1024,
                             bulk_cap_sigs=8 * 1024)
    # Coalesce at the modeled per-tick drain so launch granularity and
    # drain capacity speak the same units (the real engine's cap is the
    # compiled-shape budget; here the "device" IS the tick budget).
    sched.shapes.launch_cap = CAP_SIGS_PER_TICK
    adm = sched.admission
    load = UserLoad(rate=bulk_req_rate, users=200, seed=11)

    rid = [0]

    def request(n):
        rid[0] += 1
        recs = [rid[0].to_bytes(6, "big") + i.to_bytes(2, "big")
                for i in range(n)]
        return proto.VerifyRequest(rid[0], recs, recs, recs)

    offered_at = {}
    lat_waits = []
    lat_offered = bulk_offered = 0
    ticks = int(round(seconds / TICK_S))
    for k in range(1, ticks + 1):
        t = k * TICK_S
        for _ in range(LAT_PER_TICK):
            req = request(QC_SIGS)
            offered_at[req.request_id] = t
            lat_offered += 1
            sched.offer(req, lambda m: None, cls=vsched.LATENCY)
        for _ in range(load.arrivals(t)):
            bulk_offered += 1
            if not sched.offer(request(BULK_REQ_SIGS), lambda m: None,
                               cls=vsched.BULK):
                # The generator honors the BUSY hint: per-user backoff.
                load.busy(t, sched.retry_after_ms(vsched.BULK) / 1e3)
        budget = CAP_SIGS_PER_TICK
        while budget > 0:
            launch = sched.next_launch(block=False)
            if launch is None:
                break
            for p in launch.items:
                if p.cls == vsched.LATENCY:
                    lat_waits.append(
                        (t - offered_at.pop(p.request.request_id, t))
                        * 1e3)
            budget -= launch.total_sigs
            # Pipeline evidence for the derate controller: a tick whose
            # offered load exceeds drain capacity packs in the open
            # (overlap collapsed) — exactly the surge regime.
            adm.note_pack(0.001, hidden=offered_x <= 1.0)
    snap = adm.snapshot()
    lat_waits.sort()
    wait_p99 = lat_waits[int(0.99 * (len(lat_waits) - 1))] \
        if lat_waits else 0.0

    # OP_BUSY wire round trip: server encode -> client decode -> the
    # typed overload error with the retry hint attached.
    frame = proto.encode_busy_reply(9, 137)
    opcode, brid, body = proto.decode_reply_raw(frame[4:])
    try:
        SidecarClient._unwrap(opcode, body)
        busy_ok, hint = False, None
    except SidecarOverloaded as e:
        hint = e.retry_after_ms
        busy_ok = brid == 9 and hint == 137

    # Metrics-driven recovery-to-baseline judge on a synthetic series:
    # steady 1000 sigs/s, a surge-window blackout, then recovery.
    base_wall = 1_700_000_000.0
    samples = []
    launched = 0
    for s in range(31):
        t = base_wall + s
        if 10 <= s < 13:
            samples.append({"t": t, "ok": False, "error": "surge"})
            continue
        launched += 1000
        samples.append({"t": t, "ok": True,
                        "stats": {"sigs_launched": launched}})
    surge_event = {"t": 10.0, "target": "client:0", "action": "surge",
                   "wall": base_wall + 10, "ok": True,
                   "params": {"x": 5, "for": 3}}
    baseline = judge_baseline_recovery(samples, [surge_event])

    ok = (offered_x >= 3.0
          and wait_p99 <= 3 * TICK_S * 1e3
          and snap["shed"].get(vsched.LATENCY, 0) == 0
          and snap["shed"].get(vsched.BULK, 0) > 0
          and snap["fairness_violations"] == 0
          and busy_ok
          and baseline["ok"] and baseline["judged"] == 1)
    return {
        "offered_x": offered_x,
        "ticks": ticks,
        "latency": {
            "offered": lat_offered,
            "shed": snap["shed"].get(vsched.LATENCY, 0),
            "wait_p99_ms": round(wait_p99, 3),
        },
        "bulk": {
            "offered": bulk_offered,
            "admitted": snap["admitted"].get(vsched.BULK, 0),
            "shed": snap["shed"].get(vsched.BULK, 0),
            "deferred_by_busy": load.deferred,
        },
        "fairness_violations": snap["fairness_violations"],
        "bulk_before_latency": snap["shed"].get(vsched.LATENCY, 0) == 0,
        "derate": snap["derate"],
        "busy_roundtrip": {"ok": busy_ok, "retry_after_ms": hint},
        "baseline_slo": baseline,
        "ok": ok,
    }


def guard_headline_probe() -> dict:
    """The headline's ``guard`` field: prove the graftguard wedge ->
    recover ladder end to end without a device.

    A host-mode VerifyEngine runs under a REAL LaunchGuard whose
    deadlines are tiny (tens of milliseconds — the virtual-clock
    equivalent for a monitor that must actually preempt a hung thread),
    and the chaos hook's ``wedge`` knob hangs the next launch exactly
    as a ``sidecar wedge`` fault-plan event does over OP_CHAOS.  The
    probe asserts the full ladder: the wedged latency batch is answered
    with a mask BIT-IDENTICAL to ``verify_batch`` (one tampered
    signature pins the comparison), bulk offered during the crash-only
    reboot is shed to BUSY with a retry-after hint, the injected rewarm
    runs, the canary passes, and device routing resumes with the guard
    counters (wedges / reboots / quarantine / canary) accounting for
    all of it.  The acceptance bar rides in ``ok``.  Emitted on BOTH
    the live and degraded JSON lines."""
    import threading

    from hotstuff_tpu.crypto import eddsa
    from hotstuff_tpu.sidecar import protocol as proto
    from hotstuff_tpu.sidecar import sched as vsched
    from hotstuff_tpu.sidecar.guard import LaunchDeadlines, LaunchGuard
    from hotstuff_tpu.sidecar.service import ChaosState, VerifyEngine

    msgs, pks, sigs = _make_ref_sigs(8, seed=31)
    sigs = list(sigs)
    sigs[3] = sigs[3][:1] + bytes([sigs[3][1] ^ 0xFF]) + sigs[3][2:]
    chaos = ChaosState()
    # warm launch deadlines at 0.2 s (the injected hang is infinite, so
    # any deadline catches it fast); the compile-class budget — which
    # the reboot canary always gets — stays generous so a contended
    # host can never false-wedge the recovery the probe asserts on.
    guard = LaunchGuard(deadlines=LaunchDeadlines(
        warm_boot=True, compile_budget_s=5.0, warm_grace_s=0.2,
        min_deadline_s=0.05))
    rewarmed = []

    def rewarm():
        rewarmed.append(1)
        time.sleep(0.2)  # an observable reboot window for the BUSY leg

    engine = VerifyEngine(use_host=True, guard=guard, chaos=chaos,
                          rewarm_fn=rewarm)
    try:
        done = {}
        cond = threading.Condition()

        def reply_to(rid):
            def _reply(mask):
                with cond:
                    done[rid] = mask
                    cond.notify_all()
            return _reply

        expect = [bool(b) for b in eddsa.verify_batch(msgs, pks, sigs)]
        chaos.configure({"wedge": 1})
        engine.submit(proto.VerifyRequest(1, msgs, pks, sigs),
                      reply_to(1), cls=vsched.LATENCY)
        with cond:
            cond.wait_for(lambda: 1 in done, timeout=30.0)
        # Bulk offered while the engine re-warms must shed to BUSY.
        busy_shed = None
        t0 = time.monotonic()
        while time.monotonic() - t0 < 5.0:
            if engine._rebooting:
                busy_shed = not engine.submit(
                    proto.VerifyRequest(2, msgs, pks, sigs),
                    reply_to(2), cls=vsched.BULK)
                break
            time.sleep(0.002)
        retry_ms = engine.retry_after_ms(vsched.BULK)
        t0 = time.monotonic()
        while (engine._rebooting or not engine._device_ok) \
                and time.monotonic() - t0 < 20.0:
            time.sleep(0.01)
        engine.submit(proto.VerifyRequest(3, msgs, pks, sigs),
                      reply_to(3), cls=vsched.LATENCY)
        with cond:
            cond.wait_for(lambda: 3 in done, timeout=30.0)
        snap = engine.stats_snapshot().get("guard", {})
        masks_ok = done.get(1) == expect and done.get(3) == expect
        recovered = bool(snap.get("device_ok")) \
            and not snap.get("rebooting")
        ok = (masks_ok and busy_shed is True and bool(rewarmed)
              and snap.get("wedges", 0) >= 1
              and snap.get("reboots", 0) >= 1
              and snap.get("canary_passes", 0) >= 1
              and snap.get("suspect_records", 0) >= 1
              and recovered)
        return {
            "wedges": snap.get("wedges", 0),
            "reboots": snap.get("reboots", 0),
            "canary_passes": snap.get("canary_passes", 0),
            "quarantined_records": snap.get("suspect_records", 0),
            "poisoned_records": snap.get("poisoned_records", 0),
            "host_fallback_records": snap.get("host_fallback_records", 0),
            "busy_during_reboot": busy_shed,
            "busy_retry_after_ms": retry_ms,
            "masks_bit_identical": masks_ok,
            "rewarmed": bool(rewarmed),
            "reboot_wall_ms": round(
                snap.get("last_reboot_wall_s", 0.0) * 1e3, 1),
            "recovered": recovered,
            "ok": ok,
        }
    finally:
        engine.stop()
        guard.close()


def fleet_headline_probe(window_s: float = 0.8) -> dict:
    """The headline's ``fleet`` field: graftfleet goodput across a
    kill-primary failover plus a seeded greedy-tenant flood, in-process
    and host-mode (no device, no subprocesses).

    Two REAL SidecarServers front two REAL host-mode VerifyEngines; a
    sticky endpoint ladder (the python twin of the C++ TpuVerifier's
    ordered list) drives tenant-tagged verify traffic at the primary,
    the primary is killed mid-run, and the ladder re-homes to the
    survivor — goodput is measured on both sides of the kill, every
    reply held bit-identical to the reference (one tampered signature
    pins the comparison), and the host rung must never fire while a
    fleet member is alive.  A second tenant then replays the SAME
    records at the survivor (cross-tenant verdict-cache sharing: the QC
    gossiped to N replicas is verified once fleet-wide), and a seeded
    greedy-tenant flood runs against the survivor with the REAL
    LogParser holding the strict verdict — ``tenant_starvation == 0``
    and the victim's queue-wait p99 within the 2x bound.  The
    acceptance bar rides in ``ok``.  Emitted on BOTH the live and
    degraded JSON lines."""
    import threading

    from hotstuff_tpu.sidecar.client import SidecarClient
    from hotstuff_tpu.sidecar.service import SidecarServer, VerifyEngine

    # A pool of distinct reference batches, each with one tampered
    # signature so the expected mask is never the trivial all-True.
    POOL, BATCH = 6, 16
    pool, expects = [], []
    for k in range(POOL):
        msgs, pks, sigs = _make_ref_sigs(BATCH, seed=700 + k)
        sigs = list(sigs)
        sigs[k % BATCH] = (sigs[k % BATCH][:1]
                           + bytes([sigs[k % BATCH][1] ^ 0xFF])
                           + sigs[k % BATCH][2:])
        pool.append((msgs, pks, sigs))
        expects.append([i != (k % BATCH) for i in range(BATCH)])

    servers = []
    for _ in range(2):
        eng = VerifyEngine(use_host=True)
        srv = SidecarServer(("127.0.0.1", 0), eng)
        threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.05),
                         daemon=True).start()
        servers.append((srv, eng))
    ports = [srv.server_address[1] for srv, _ in servers]

    class _Ladder:
        """Sticky-until-unhealthy ordered endpoint list; host path is
        the LAST rung and counts as a fallback, never a peer."""

        def __init__(self, tenant):
            self.tenant = tenant
            self.active = 0
            self.rehomes = 0
            self.host_fallbacks = 0
            self._clients = {}

        def _client(self, ix):
            c = self._clients.get(ix)
            if c is None:
                c = SidecarClient(port=ports[ix], timeout=5.0)
                c.hello(self.tenant)
                self._clients[ix] = c
            return c

        def drop(self, ix):
            c = self._clients.pop(ix, None)
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass

        def verify(self, msgs, pks, sigs):
            while self.active < len(ports):
                try:
                    return self._client(self.active).verify_batch(
                        msgs, pks, sigs)
                except OSError:
                    self.drop(self.active)
                    self.active += 1
                    self.rehomes += 1
            self.host_fallbacks += 1
            from hotstuff_tpu.crypto import eddsa
            return [bool(b) for b in
                    eddsa.verify_batch(msgs, pks, sigs)]

        def close(self):
            for ix in list(self._clients):
                self.drop(ix)

    killed = [False]

    def kill_primary():
        srv0, eng0 = servers[0]
        srv0.shutdown()
        eng0.stop()
        srv0.server_close()
        killed[0] = True

    ladder = _Ladder("replica-0")
    masks_ok = True
    try:
        # -- live phase: tenant-tagged goodput at the primary ----------
        live_sigs, i = 0, 0
        t0 = time.monotonic()
        while time.monotonic() - t0 < window_s:
            m, p, s = pool[i % POOL]
            masks_ok &= ladder.verify(m, p, s) == expects[i % POOL]
            live_sigs += BATCH
            i += 1
        live_goodput = live_sigs / max(time.monotonic() - t0, 1e-9)

        # -- kill the primary mid-run ----------------------------------
        # In-process stand-in for SIGKILL: the listener closes AND the
        # established connection dies (the OS closes a dead process's
        # sockets), so the ladder's next send surfaces a transport
        # error and re-homes.  The C++ in-flight-resubmit leg is
        # covered natively (test_crypto: sidecar_fleet_failover).
        t_kill = time.monotonic()
        kill_primary()
        ladder.drop(0)

        # -- failover phase: goodput on the survivor -------------------
        m, p, s = pool[0]
        masks_ok &= ladder.verify(m, p, s) == expects[0]
        rehome_ms = (time.monotonic() - t_kill) * 1e3
        fo_sigs, i = BATCH, 1
        t1 = time.monotonic()
        while time.monotonic() - t1 < window_s:
            m, p, s = pool[i % POOL]
            masks_ok &= ladder.verify(m, p, s) == expects[i % POOL]
            fo_sigs += BATCH
            i += 1
        fo_goodput = fo_sigs / max(time.monotonic() - t1, 1e-9)

        # -- cross-tenant dedup at the survivor ------------------------
        with SidecarClient(port=ports[1], timeout=5.0) as peer:
            peer.hello("replica-1")
            for k in range(POOL):
                m, p, s = pool[k]
                masks_ok &= peer.verify_batch(m, p, s) == expects[k]
        survivor = servers[1][1]
        dedup = survivor.stats_snapshot().get("dedup", {})

        # -- seeded greedy-tenant flood at the survivor ----------------
        flood = _fleet_flood(ports[1], survivor)

        ok = (masks_ok
              and ladder.rehomes >= 1
              and ladder.host_fallbacks == 0
              and ladder.active == 1
              and live_goodput > 0 and fo_goodput > 0
              and dedup.get("hit_rate", 0) > 0
              and flood.get("ok") is True)
        return {
            "endpoints": 2,
            "live_goodput_sigs_per_s": round(live_goodput, 1),
            "failover_goodput_sigs_per_s": round(fo_goodput, 1),
            "rehome_ms": round(rehome_ms, 1),
            "rehomes": ladder.rehomes,
            "host_fallbacks": ladder.host_fallbacks,
            "active_endpoint": ladder.active,
            "masks_bit_identical": masks_ok,
            "dedup": {"cache_hits": dedup.get("cache_hits", 0),
                      "hit_rate": dedup.get("hit_rate", 0.0)},
            "flood": flood,
            "ok": ok,
        }
    finally:
        ladder.close()
        for ix, (srv, eng) in enumerate(servers):
            if ix == 0 and killed[0]:
                continue
            srv.shutdown()
            eng.stop()
            srv.server_close()


# Minimal golden log pair for the fleet probe's LogParser verdict: the
# parser refuses empty inputs by contract, and the flood judge only
# needs its constructor to succeed — these are the shortest client/node
# logs it accepts (start line + node config + one commit).
_FLEET_GOLDEN_CLIENT = """\
[2026-07-29T14:54:56.456Z INFO client] Transactions size: 512 B
[2026-07-29T14:54:56.456Z INFO client] Transactions rate: 2000 tx/s
[2026-07-29T14:54:56.525Z INFO client] Start sending transactions
"""
_FLEET_GOLDEN_NODE = """\
[2026-07-29T14:54:55.100Z INFO mempool::config] Garbage collection depth set to 50 rounds
[2026-07-29T14:54:55.100Z INFO mempool::config] Sync retry delay set to 5000 ms
[2026-07-29T14:54:55.100Z INFO mempool::config] Sync retry nodes set to 3 nodes
[2026-07-29T14:54:55.100Z INFO mempool::config] Batch size set to 15000 B
[2026-07-29T14:54:55.100Z INFO mempool::config] Max batch delay set to 100 ms
[2026-07-29T14:54:55.101Z INFO consensus::config] Timeout delay set to 1000 ms
[2026-07-29T14:54:55.101Z INFO consensus::config] Sync retry delay set to 10000 ms
[2026-07-29T14:54:57.000Z INFO consensus::core] Committed B2
"""


def _fleet_flood(port: int, engine, pre_s: float = 0.8,
                 flood_s: float = 1.2) -> dict:
    """Seeded greedy-tenant flood leg of the ``fleet`` headline: a
    victim tenant keeps a small latency-class cadence while a greedy
    tenant floods bulk batches; the per-tenant DRR quantum and
    admission caps must keep the victim's queue-wait p99 within the
    strict 2x bound with ZERO starvation events — judged by the REAL
    LogParser verdict (``note_tenant_flood``), same as the chaos
    drill."""
    import threading

    from hotstuff_tpu.harness.logs import LogParser
    from hotstuff_tpu.sidecar.client import SidecarClient, \
        SidecarOverloaded

    # One reference batch per role; per-iteration msg mutation keeps
    # every record UNIQUE (so the verdict-cache fast path never
    # short-circuits the queue this leg is measuring) while pks stay
    # valid curve points — full verify work, masks all-False.
    vm, vp, vs = _make_ref_sigs(4, seed=881)
    gm, gp, gs = _make_ref_sigs(32, seed=887)
    errors = []

    def _mut(msgs, tag, i):
        return [tag + i.to_bytes(4, "big") + j.to_bytes(4, "big")
                + m[12:] for j, m in enumerate(msgs)]

    def victim(stop, period_s=0.005):
        try:
            with SidecarClient(port=port, timeout=30.0) as c:
                c.hello("victim")
                i = 0
                while not stop.is_set():
                    mask = c.verify_batch(_mut(vm, b"vict", i), vp, vs)
                    assert len(mask) == len(vm)
                    i += 1
                    time.sleep(period_s)
        except Exception as e:  # noqa: BLE001 — surfaced in the verdict
            errors.append(repr(e))

    def greedy(stop, seed):
        try:
            with SidecarClient(port=port, timeout=30.0) as c:
                c.hello("greedy")
                i = 0
                while not stop.is_set():
                    try:
                        c.verify_batch(_mut(gm, b"gr%02d" % seed, i),
                                       gp, gs)
                    except SidecarOverloaded:
                        time.sleep(0.002)  # honor the tenant-cap BUSY
                    i += 1
        except Exception as e:  # noqa: BLE001 — surfaced in the verdict
            errors.append(repr(e))

    def _phase(n_greedy, seconds, base_seed):
        stop = threading.Event()
        threads = [threading.Thread(target=victim, args=(stop,),
                                    daemon=True)]
        threads += [threading.Thread(target=greedy,
                                     args=(stop, base_seed + k),
                                     daemon=True)
                    for k in range(n_greedy)]
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        return json.loads(json.dumps(engine.stats_snapshot()))

    pre = _phase(1, pre_s, 1)
    post = _phase(3, flood_s, 2)
    if errors:
        return {"ok": False, "errors": errors[:3]}

    parser = LogParser([_FLEET_GOLDEN_CLIENT], [_FLEET_GOLDEN_NODE],
                       faults=0)
    try:
        parser.note_tenant_flood(pre, post, "victim", strict=True)
    except Exception as e:  # noqa: BLE001 — strict ParseError -> not ok
        return {"ok": False, "error": f"{e!r:.200}",
                "verdict": getattr(parser, "tenant_flood", None)}
    verdict = dict(parser.tenant_flood or {})
    verdict["ok"] = bool(verdict.get("ok")) and bool(verdict.get("judged"))
    return verdict


def cadence_probe(n_devices: int = 8, budget_s: float = 240.0) -> dict:
    """Child half of the ``cadence`` headline (graftcadence): ring vs
    staged sigs/sec at a FIXED offered load, swept across ring depth
    k in {2, 4, 8} (knob hygiene: the trained depth-k supersedes the
    staged depth-2 constant, and this sweep is where a measurement pin
    would come from), queue-wait p99 from the OP_STATS ``cadence``
    section under a seeded surge-style load through the REAL cadence
    engine, and the mesh leg: ``ring_slot_pack`` — the pre-donated
    fixed-shape resident entry a mesh ring slot arms — proven
    bit-identical to ``verify_batch`` on the forced-host n-device mesh.

    The engine legs run host-mode (pure-python reference verify), so
    ring-vs-staged numbers measure PIPELINE overheads honestly relative
    to each other but are never comparable to device throughput.  The
    acceptance bar rides in ``ok``: staged stays the default (a
    default-built engine has no ring), every reply bit-identical to the
    reference (one tampered signature pins the comparison), every
    cadence dispatch guard-supervised under the ``tick:`` deadline
    class, queue-wait percentiles present, and the mesh slot
    bit-identical.  Prints one JSON progress line per completed leg
    (the parent salvages partials) and returns the dict."""
    import threading

    from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
    from hotstuff_tpu.harness.loadgen import UserLoad
    from hotstuff_tpu.parallel import sharded_verify as shv
    from hotstuff_tpu.parallel.mesh import make_mesh
    from hotstuff_tpu.sidecar import protocol as proto
    from hotstuff_tpu.sidecar import sched as vsched
    from hotstuff_tpu.sidecar.guard import LaunchDeadlines, LaunchGuard
    from hotstuff_tpu.sidecar.ring import CadenceRing, RingDepth
    from hotstuff_tpu.sidecar.service import VerifyEngine
    from hotstuff_tpu.utils.xla_cache import configure_xla_cache

    t0 = time.perf_counter()
    out = {"n_devices": n_devices}

    def emit_progress():
        print(json.dumps({"cadence": out}), flush=True)

    # Fixed offered load shared by every engine leg: REQS requests of
    # REQ_SIGS records, one tampered signature pinning the bit-identity
    # comparison on every single reply.
    REQS, REQ_SIGS = 12, 8
    msgs, pks, sigs = _make_ref_sigs(REQ_SIGS, seed=41)
    sigs = list(sigs)
    sigs[3] = sigs[3][:1] + bytes([sigs[3][1] ^ 0xFF]) + sigs[3][2:]
    expect = [bool(ref.verify(pk, m, s))
              for m, pk, s in zip(msgs, pks, sigs)]

    def drive(engine):
        """Submit the fixed load, wait out every reply; (sigs/s, ok)."""
        done = {}
        cond = threading.Condition()

        def reply_to(rid):
            def _reply(mask):
                with cond:
                    done.setdefault(rid, []).append(mask)
                    cond.notify_all()
            return _reply

        t = time.perf_counter()
        for rid in range(1, REQS + 1):
            engine.submit(proto.VerifyRequest(rid, msgs, pks, sigs),
                          reply_to(rid), cls=vsched.LATENCY)
        with cond:
            cond.wait_for(lambda: len(done) == REQS, timeout=120.0)
        dt = time.perf_counter() - t
        masks_ok = (len(done) == REQS
                    and all(v == [expect] for v in done.values()))
        return round(REQS * REQ_SIGS / dt, 1), masks_ok

    # Staged stays the DEFAULT: a default-built engine has no ring; the
    # ring engages only behind --cadence / HOTSTUFF_TPU_CADENCE.
    probe_engine = VerifyEngine(use_host=True)
    staged_default = probe_engine._ring is None
    probe_engine.stop()
    out["staged_default"] = staged_default

    masks = {}
    eng = VerifyEngine(use_host=True)
    try:
        rate, masks["staged"] = drive(eng)
    finally:
        eng.stop()
    out["staged_sigs_per_s"] = rate
    emit_progress()

    tick_supervised = True
    for k in RingDepth.DEPTHS:
        if time.perf_counter() - t0 > budget_s:
            out[f"ring_k{k}"] = {"skipped": True}
            continue
        guard = LaunchGuard(deadlines=LaunchDeadlines(warm_boot=True))
        eng = VerifyEngine(
            use_host=True, guard=guard,
            ring_factory=lambda e, k=k: CadenceRing(
                e, depth=RingDepth(pinned=k)))
        try:
            rate, masks[f"ring_k{k}"] = drive(eng)
            snap = eng.stats_snapshot()["cadence"]
            deadlines = guard.snapshot()["deadlines"]
        finally:
            eng.stop()
            guard.close()
        # Supervision evidence: the guard's deadline trainer saw the
        # tick class — every cadence dispatch went through guard.call.
        ticked = any(dkey.startswith("tick:") and v.get("n", 0) >= 1
                     for dkey, v in deadlines.items())
        tick_supervised = tick_supervised and ticked
        out[f"ring_k{k}"] = {
            "sigs_per_s": rate,
            "dispatch_ticks": snap["dispatch_ticks"],
            "tick_rate_hz": snap["tick_rate_hz"],
            "pad_fill_ratio": snap["pad_fill"]["ratio"],
            "queue_wait_p99_ms": snap["queue_wait"]["p99_ms"],
            "generation_drops": snap["generation"]["drops"],
            "guard_tick_launches": ticked,
        }
        emit_progress()
    out["tick_launches_supervised"] = tick_supervised

    # Queue-wait p99 under the seeded surge-style plan: the loadgen's
    # heavy-tailed multi-user generator (the surge headline's seeded
    # twin of the C++ client's UserLoadModel) offers bulk bursts over a
    # steady consensus-class stream into the REAL cadence engine, BUSY
    # backoff honored; the reported percentiles are the OP_STATS
    # ``cadence.queue_wait`` reservoir — admission to cadence dispatch.
    if time.perf_counter() - t0 > budget_s:
        out["surge_wait"] = {"skipped": True}
    else:
        guard = LaunchGuard(deadlines=LaunchDeadlines(warm_boot=True))
        eng = VerifyEngine(
            use_host=True, guard=guard,
            ring_factory=lambda e: CadenceRing(
                e, depth=RingDepth(pinned=4)))
        try:
            done = []
            cond = threading.Condition()

            def _reply(mask):
                with cond:
                    done.append(1)
                    cond.notify_all()

            load = UserLoad(rate=40.0, users=50, seed=11)
            TICK_S, TICKS = 0.02, 25
            rid = 1000
            accepted = 0
            t_load = time.perf_counter()
            for i in range(1, TICKS + 1):
                t_rel = i * TICK_S
                rid += 1
                accepted += 1
                eng.submit(proto.VerifyRequest(rid, msgs, pks, sigs),
                           _reply, cls=vsched.LATENCY)
                for _ in range(load.arrivals(t_rel)):
                    rid += 1
                    if eng.submit(
                            proto.VerifyRequest(rid, msgs, pks, sigs),
                            _reply, cls=vsched.BULK):
                        accepted += 1
                    else:
                        load.busy(t_rel,
                                  eng.retry_after_ms(vsched.BULK) / 1e3)
                sleep_left = t_load + t_rel - time.perf_counter()
                if sleep_left > 0:
                    time.sleep(sleep_left)
            with cond:
                cond.wait_for(lambda: len(done) >= accepted,
                              timeout=120.0)
            snap = eng.stats_snapshot()["cadence"]
        finally:
            eng.stop()
            guard.close()
        out["surge_wait"] = {
            "accepted_reqs": accepted,
            "answered": len(done),
            "deferred_by_busy": load.deferred,
            "queue_wait_p50_ms": snap["queue_wait"]["p50_ms"],
            "queue_wait_p99_ms": snap["queue_wait"]["p99_ms"],
            "occupancy_hist": snap["occupancy_hist"],
        }
        emit_progress()

    # Mesh leg: the fixed-shape pre-donated resident entry a mesh ring
    # slot arms (parallel.sharded_verify.ring_slot_pack), bit-identical
    # to verify_batch on the forced-host n-device mesh.
    if time.perf_counter() - t0 > budget_s:
        out["mesh_ring_slot"] = {"skipped": True}
    else:
        try:
            configure_xla_cache()
            mesh = make_mesh(n_devices)
            n = 16
            mm, mp, ms = _make_ref_sigs(n, seed=43)
            ms = list(ms)
            ms[5] = ms[5][:1] + bytes([ms[5][1] ^ 0xFF]) + ms[5][2:]
            want = [bool(b) for b in eddsa.verify_batch(mm, mp, ms)]
            rows = shv.shard_aligned_rows(n, n_devices,
                                          eddsa.MAX_SUBBATCH)
            prep = eddsa.prepare_batch(mm, mp, ms)
            got = [bool(b)
                   for b in shv.ring_slot_pack(mesh, prep, rows)()()]
            out["mesh_ring_slot"] = {"rows": rows,
                                     "bit_identical": got == want}
        except Exception as e:  # noqa: BLE001 — leg isolation
            out["mesh_ring_slot"] = {"error": f"{e!r:.160}"}
        emit_progress()

    masks_ok = bool(masks) and all(masks.values())
    ring_rates = [v.get("sigs_per_s", 0.0) for kk, v in out.items()
                  if kk.startswith("ring_k") and isinstance(v, dict)
                  and not v.get("skipped")]
    sw = out.get("surge_wait", {})
    wait_ok = bool(sw.get("skipped")) or \
        sw.get("queue_wait_p99_ms") is not None
    mr = out.get("mesh_ring_slot", {})
    mesh_ok = bool(mr.get("skipped")) or mr.get("bit_identical") is True
    out["masks_bit_identical"] = masks_ok
    out["ok"] = (staged_default and masks_ok and tick_supervised
                 and bool(ring_rates)
                 and all(r > 0 for r in ring_rates)
                 and wait_ok and mesh_ok)
    emit_progress()
    return out


def cadence_headline(n_devices: int = 8,
                     budget_s: float | None = None) -> dict:
    """Parent half of the ``cadence`` headline field (graftcadence,
    ROADMAP item 6): run :func:`cadence_probe` on the forced-host CPU
    mesh (see :func:`_forced_host_mesh_headline` for the subprocess
    contract; HOTSTUFF_TPU_CADENCE_BUDGET seconds, default 240, bounds
    the stage).  Emitted on BOTH the live and degraded lines."""
    if budget_s is None:
        budget_s = float(
            os.environ.get("HOTSTUFF_TPU_CADENCE_BUDGET", "240"))
    return _forced_host_mesh_headline(
        "cadence", f"cadence_probe({n_devices}, budget_s={budget_s})",
        n_devices, budget_s)


def users_headline_probe(populations=(100_000, 1_000_000),
                         txs_per_point: int = 96,
                         budget_s: float | None = None) -> dict:
    """The headline ``users`` field (graftingress): the signed ingress
    tier at user-population scale, end to end in process.

    Per population U the seeded generator names which user each arrival
    belongs to (``UserLoad.arrivals(out_users=...)`` — the same contract
    the C++ client's UserLoadModel grew), the probe derives that user's
    Ed25519 keypair on FIRST arrival through the bounded
    ``txsign.UserKeyring`` LRU, builds version-2 signed frames with a
    seeded ~1% forgery mix (at least one forged frame per point, so the
    rejection rate is always a measured number), turns each frame into
    its admission (digest, pk, sig) record, and submits QC-shaped
    batches to a host-mode VerifyEngine as INGRESS_CTX-tagged bulk
    requests — the exact class + ctx tag the mempool admission-verify
    stage uses, so the engine's OP_STATS ``ingress`` section must report
    the lane 100% ingress-fed.  Key generation and signing run OUTSIDE
    the timed region; ``verified_goodput_sigs_per_s`` times only the
    verify drive (host-mode reference verify: honest relative to the
    other points, never comparable to device throughput).

    Populations that miss ``budget_s`` report ``{"skipped": true}``.
    Acceptance bar in ``ok``: every forged frame rejected, every honest
    frame verified, goodput positive, and the bulk lane fully
    ingress-fed on every completed point."""
    import random
    import threading

    from hotstuff_tpu.crypto import txsign
    from hotstuff_tpu.harness.loadgen import UserLoad
    from hotstuff_tpu.sidecar import protocol as proto
    from hotstuff_tpu.sidecar import sched as vsched
    from hotstuff_tpu.sidecar.service import VerifyEngine

    if budget_s is None:
        budget_s = float(
            os.environ.get("HOTSTUFF_TPU_USERS_BUDGET", "240"))
    t0 = time.perf_counter()
    out = {"mix_forge_pct": 1.0, "txs_per_point": txs_per_point}
    BATCH = 32

    for pop in populations:
        key = f"u{pop}"
        if time.perf_counter() - t0 > budget_s:
            out[key] = {"skipped": True}
            continue
        # Arrival stream on a virtual clock: with U users at a fixed
        # aggregate rate, a short window touches ~txs_per_point DISTINCT
        # users (per-user gaps are U/rate seconds) — the population knob
        # stresses the key-derivation path, not the verify path.
        load = UserLoad(rate=64.0, users=pop, seed=13)
        arrivals: list = []
        tick = 0
        while len(arrivals) < txs_per_point and tick < 4096:
            tick += 1
            load.arrivals(tick * 0.025, arrivals)
        arrivals = arrivals[:txs_per_point]
        keyring = txsign.UserKeyring(seed=7, capacity=4096)
        mix = random.Random(2024 + pop)
        frames, forged = [], []
        for i, user in enumerate(arrivals):
            forge = mix.random() < 0.01
            marker = (txsign.TX_MARKER_FORGED if forge
                      else txsign.TX_MARKER_FILLER)
            frames.append(txsign.build_signed_tx(
                keyring.get(user), nonce=i,
                payload=txsign.build_payload(marker, i),
                flip_sig_bit=forge))
            forged.append(forge)
        if not any(forged):  # seeded mix, floored at one forged frame
            frames[-1] = txsign.build_signed_tx(
                keyring.get(arrivals[-1]), nonce=len(arrivals) - 1,
                payload=txsign.build_payload(
                    txsign.TX_MARKER_FORGED, len(arrivals) - 1),
                flip_sig_bit=True)
            forged[-1] = True
        records = [txsign.admission_record(f) for f in frames]

        masks: dict = {}
        cond = threading.Condition()

        def reply_to(rid, masks=masks, cond=cond):
            def _reply(mask):
                with cond:
                    masks[rid] = mask
                    cond.notify_all()
            return _reply

        eng = VerifyEngine(use_host=True)
        busy_rejected = 0
        try:
            t_drive = time.perf_counter()
            rids = []
            for b in range(0, len(records), BATCH):
                chunk = records[b:b + BATCH]
                rid = 1 + b // BATCH
                req = proto.VerifyRequest(
                    rid,
                    [r[0] for r in chunk], [r[1] for r in chunk],
                    [r[2] for r in chunk], ctx=txsign.INGRESS_CTX)
                for attempt in range(8):
                    if eng.submit(req, reply_to(rid), cls=vsched.BULK):
                        rids.append(rid)
                        break
                    busy_rejected += 1
                    time.sleep(eng.retry_after_ms(vsched.BULK) / 1e3)
            with cond:
                cond.wait_for(
                    lambda: all(r in masks for r in rids), timeout=120.0)
            dt = time.perf_counter() - t_drive
            snap = eng.stats_snapshot().get("ingress", {})
        finally:
            eng.stop()

        flat = []
        for rid in rids:
            flat.extend(masks.get(rid) or [])
        answered = len(flat)
        verified = sum(1 for ok, f in zip(flat, forged) if ok and not f)
        forged_sent = sum(forged)
        forged_rejected = sum(
            1 for ok, f in zip(flat, forged) if f and not ok)
        honest = len(frames) - forged_sent
        total_bulk_sigs = (snap.get("bulk_sigs", 0)
                           + snap.get("offchain_sigs", 0))
        out[key] = {
            "users": pop,
            "txs": len(frames),
            "distinct_users": len(set(arrivals)),
            "key_derivations": keyring.derivations,
            "keyring_capacity": keyring.capacity,
            "forged_sent": forged_sent,
            "forged_rejected": forged_rejected,
            "forgery_rejection_rate": round(
                forged_rejected / forged_sent, 3) if forged_sent else 0.0,
            "verified": verified,
            "verified_goodput_sigs_per_s": round(verified / dt, 1)
            if dt > 0 else 0.0,
            "busy_rejected": busy_rejected,
            "bulk_ingress_requests": snap.get("bulk_requests", 0),
            "bulk_ingress_sigs": snap.get("bulk_sigs", 0),
            "bulk_ingress_share": round(
                snap.get("bulk_sigs", 0) / total_bulk_sigs, 3)
            if total_bulk_sigs else 0.0,
            "answered": answered,
            "point_ok": (answered == len(frames)
                         and verified == honest
                         and forged_rejected == forged_sent
                         and snap.get("bulk_sigs", 0) == total_bulk_sigs
                         > 0),
        }
    done = [v for k, v in out.items()
            if k.startswith("u") and isinstance(v, dict)
            and not v.get("skipped")]
    out["ok"] = bool(done) and all(v["point_ok"] for v in done)
    return out


def viewchange_headline(committees=(20, 100, 300), repeats: int = 2,
                        budget_s: float | None = None) -> dict:
    """The headline ``viewchange`` field (graftview): batched vs
    per-signature TC assembly latency at committee sizes N.

    Per committee, the quorum's (2N/3+1) timeout votes of one view
    change — every vote signing the SHARED (round, high_qc_round)
    digest, the QC-shaped batch the consensus core now dispatches as ONE
    sidecar launch — are verified two ways: one signature at a time
    through the pure-python reference verifier (the per-sender host path
    the old handle_timeout ran inline, the N=100 fault-path wall), and
    as one eddsa.verify_batch launch.  The probe also proves the EJECT
    contract once per run: a tampered candidate fails the batch, and the
    per-signature fallback identifies EXACTLY the signers per-signature
    verification rejects (the accept/reject set equivalence the native
    test pins, re-proven through the python engine).

    Budget-capped like every stage (HOTSTUFF_TPU_VIEWCHANGE_BUDGET,
    default 240 s): committees that miss the budget report
    {"skipped": true}.  Emitted on BOTH the live and degraded lines.
    """
    from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
    # The node's own quorum formula, single-homed (sched/shapes; the
    # committee_scale headline uses the same helper).
    from hotstuff_tpu.sidecar.sched.shapes import quorum_sigs

    if budget_s is None:
        budget_s = float(
            os.environ.get("HOTSTUFF_TPU_VIEWCHANGE_BUDGET", "240"))
    out = {"committees": list(committees)}
    if budget_s <= 0:
        out["skipped"] = True
        return out
    t0 = time.perf_counter()
    rng = np.random.default_rng(37)
    # One shared digest: all honest timeouts of a round carry the same
    # (round, high_qc_round), which is what makes the batch QC-shaped.
    shared = rng.bytes(32)
    max_q = quorum_sigs(max(committees))
    pks, sigs = [], []
    for _ in range(max_q):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        pks.append(pk)
        sigs.append(ref.sign(sk, shared))

    for n in committees:
        if time.perf_counter() - t0 > budget_s:
            out[f"n{n}"] = {"skipped": True}
            continue
        q = quorum_sigs(n)
        m, p, s = [shared] * q, pks[:q], sigs[:q]
        try:
            # Batched: warm/compile outside the timed region, then the
            # one-launch path the core's TC batch rides.
            if not eddsa.verify_batch(m, p, s).all():
                raise RuntimeError(f"batched TC verify failed at q={q}")
            batched_ms = None
            for _ in range(repeats):
                t = time.perf_counter()
                mask = eddsa.verify_batch(m, p, s)
                dt = (time.perf_counter() - t) * 1e3
                if not mask.all():
                    raise RuntimeError(f"batched TC verify failed at q={q}")
                batched_ms = dt if batched_ms is None else min(batched_ms,
                                                               dt)
            # Per-signature: the old inline host path, one verify per
            # arriving timeout (single repeat — pure-python point math).
            t = time.perf_counter()
            for mi, pi, si in zip(m, p, s):
                if not ref.verify(pi, mi, si):
                    raise RuntimeError(f"per-sig TC verify failed at q={q}")
            per_sig_ms = (time.perf_counter() - t) * 1e3
            out[f"n{n}"] = {
                "quorum": q,
                "batched_ms": round(batched_ms, 2),
                "per_sig_ms": round(per_sig_ms, 2),
                "batched_sigs_per_s": round(q / (batched_ms / 1e3), 1),
                "per_sig_sigs_per_s": round(q / (per_sig_ms / 1e3), 1),
                "speedup": round(per_sig_ms / batched_ms, 3),
            }
        except Exception as e:  # noqa: BLE001 — per-size isolation
            out[f"n{n}"] = {"error": f"{e!r:.200}"}

    # Eject-path equivalence at the smallest committee: one tampered
    # candidate -> the batch rejects, and the per-sig fallback names
    # exactly the same signer set the batch mask does.
    try:
        q = quorum_sigs(min(committees))
        bad_i = q // 2
        bad_sigs = list(sigs[:q])
        bad_sigs[bad_i] = bad_sigs[bad_i][:1] + \
            bytes([bad_sigs[bad_i][1] ^ 0xFF]) + bad_sigs[bad_i][2:]
        mask = [bool(b) for b in
                eddsa.verify_batch([shared] * q, pks[:q], bad_sigs)]
        per_sig = [ref.verify(pk, shared, sg)
                   for pk, sg in zip(pks[:q], bad_sigs)]
        out["eject"] = {
            "tampered_index": bad_i,
            "batch_rejected": not all(mask),
            "ejected": [i for i, ok in enumerate(mask) if not ok],
            "match_per_sig": mask == per_sig,
        }
    except Exception as e:  # noqa: BLE001
        out["eject"] = {"error": f"{e!r:.200}"}

    measured = [v for k, v in out.items()
                if k.startswith("n") and isinstance(v, dict)
                and "speedup" in v]
    out["ok"] = bool(measured) and \
        out.get("eject", {}).get("match_per_sig") is True and \
        out.get("eject", {}).get("batch_rejected") is True
    return out


def probe_device(window: float | None = None,
                 max_attempts: int | None = None, run=None,
                 sleep=time.sleep, now=time.monotonic):
    """Bounded subprocess probe of the (tunnelable, therefore wedgeable)
    device -> ``(ok, reason)``.

    Caps the retry loop THREE ways: an attempt cap
    (HOTSTUFF_TPU_PROBE_ATTEMPTS, default 3), the probe's own window
    (HOTSTUFF_TPU_PROBE_WINDOW, default 600 s), and — the round-5 fix —
    the REMAINING outer bench budget (HOTSTUFF_TPU_BENCH_DEADLINE minus
    elapsed) less _DEADLINE_SLACK, so the degraded fallback always has
    the slack left to measure and emit its JSON line inside the driver's
    hard timeout.  BENCH_r05.json is the regression this prevents: the
    driver granted a window larger than its own timeout, nine probe
    retries consumed everything, rc=124, no artifact.  ``run``/``sleep``/
    ``now`` are injectable for the regression test (a fake always-failing
    probe on a virtual clock)."""
    import subprocess
    import sys

    if run is None:
        run = subprocess.run
    if window is None:
        window = float(os.environ.get("HOTSTUFF_TPU_PROBE_WINDOW", "600"))
    if max_attempts is None:
        max_attempts = max(
            1, int(os.environ.get("HOTSTUFF_TPU_PROBE_ATTEMPTS", "3")))
    budget_window = max(0.0, budget_left_s(now) - _DEADLINE_SLACK)
    window = min(window, budget_window)
    probe = ("import jax, jax.numpy as jnp, numpy as np;"
             "np.asarray(jnp.ones((8, 8)) @ jnp.ones((8, 8)))")
    deadline = now() + window
    attempt = 0
    proc_errors = 0
    last_err = "tunnel wedged (probe timeouts)"
    while True:
        remaining = deadline - now()
        if remaining <= 0 and attempt > 0:
            break
        attempt += 1
        retry_sleep = 30.0
        try:
            run([sys.executable, "-c", probe],
                timeout=min(75.0, max(5.0, remaining)),
                check=True, capture_output=True)
            return True, ""
        except subprocess.TimeoutExpired:
            proc_errors = 0
            last_err = "tunnel wedged (probe timeouts)"
        except subprocess.CalledProcessError as e:
            # A probe that exits nonzero (bad install, import error) is
            # deterministic — only timeouts are worth waiting out, so
            # retry these quickly and give up after a few in a row.
            proc_errors += 1
            retry_sleep = 5.0
            last_err = (e.stderr or b"").decode("utf-8", "replace")[-300:]
            if proc_errors >= 4:
                return False, (f"device probe errored {proc_errors}x in "
                               f"a row (not a wedge): {last_err}")
        remaining = deadline - now()
        if attempt >= max_attempts or remaining <= 0:
            break
        print(f"bench: device probe attempt {attempt} failed; retrying "
              f"({remaining:.0f}s left in window)", file=sys.stderr)
        sleep(min(retry_sleep, max(0.0, remaining)))
    return False, (f"device probe failed {attempt}x (cap {max_attempts}, "
                   f"window {window:.0f}s, outer budget "
                   f"{bench_budget_s():.0f}s): {last_err}")


def run_degraded(reason: str):
    """No usable accelerator: fall back to JAX_PLATFORMS=cpu, measure the
    RLC headline there, and ALWAYS emit one parseable JSON line tagged
    ``"degraded": true`` before exiting 0 — a degraded measurement of a
    degraded environment is a successful bench run, and the driver's
    bounded window must never close on silence (BENCH_r05.json).
    ``value`` is the largest completed per-signature CPU-backend
    throughput: NOT comparable to TPU numbers, which is what the flag
    says."""
    import threading

    emitted = threading.Event()

    def _bail():
        if emitted.is_set():
            return
        cached = load_cache()
        if cached:
            emit_cached(cached, f"degraded watchdog: {reason}",
                        degraded=True)
        else:
            emit(0, 0, degraded=True,
                 error=f"degraded watchdog: {reason}")
        os._exit(0)

    # The degraded stage itself must fit the REMAINING outer budget with
    # slack for the emit: the whole point of capping the probe window is
    # that this path still lands its line inside the driver's timeout.
    # Cap raised 480 -> 900 with the roofline stage (a pallas-interpret
    # measurement is compile-bound, ~2-4 min for one size on CPU), then
    # 900 -> 1200 with the committee_scale stage (another bounded
    # forced-host-mesh subprocess); the budget_left guard, not the cap,
    # is what keeps the emit inside the driver's window.
    left = max(30.0, budget_left_s() - 60.0)
    watchdog = threading.Timer(min(1200.0, left), _bail)
    watchdog.daemon = True
    watchdog.start()
    try:
        import jax

        # Mirrors tests/conftest.py: this image's sitecustomize registers
        # the TPU PJRT plugin at interpreter startup, so the env var is
        # too late — flip the platform through jax.config before any
        # backend initializes.  If a backend already initialized (the
        # degraded call came after a successful probe), keep it: it is
        # reachable by definition.
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001
            pass
        from hotstuff_tpu.utils.xla_cache import configure_xla_cache

        configure_xla_cache()
        # All four headline sizes; the budget guard marks whatever the
        # CPU backend can't fit as {"skipped": true} instead of stalling.
        rlc = rlc_compare(repeats=1,
                          budget_s=min(300.0, max(20.0, left - 120.0)))
        value = 0.0
        for stats in rlc.values():
            value = max(value, stats.get("per_sig_sigs_per_s", 0.0))
        try:
            mesh_rlc = mesh_rlc_headline(budget_s=min(
                float(os.environ.get("HOTSTUFF_TPU_MESH_RLC_BUDGET",
                                     "240")),
                max(0.0, budget_left_s() - 90.0)))
        except Exception as e:  # noqa: BLE001 — headline isolation
            mesh_rlc = {"error": f"{e!r:.120}"}
        # graftscale committee_scale on the same forced-host mesh: the
        # giant-committee sweep rides the degraded line too (same
        # bounded-subprocess emit-or-die discipline as mesh_rlc) — a
        # degraded environment still proves the N in {100, 300, 1000}
        # routing story, just on CPU-backend numbers.
        try:
            committee_scale = committee_scale_headline(budget_s=min(
                float(os.environ.get("HOTSTUFF_TPU_COMMITTEE_BUDGET",
                                     "240")),
                max(0.0, budget_left_s() - 90.0)))
        except Exception as e:  # noqa: BLE001 — headline isolation
            committee_scale = {"error": f"{e!r:.120}"}
        # graftkern roofline on the CPU backend: the estimate is always
        # present; measured entries are CPU-backend (and the pallas
        # route interpreter-flagged) — comparable to each other, never
        # to TPU numbers, which the degraded flag already says.  One
        # size: a pallas-interpret measurement is compile-bound
        # (~2-4 min) and the larger sizes belong to a live device run
        # (the budget check is per-route, so an in-flight measurement
        # is never preempted — the size list is what bounds this
        # stage under the degraded watchdog).
        try:
            roofline = roofline_headline(
                sizes=(64,), repeats=1,
                budget_s=min(240.0, max(0.0, budget_left_s() - 180.0)))
        except Exception as e:  # noqa: BLE001 — headline isolation
            roofline = {"est": roofline_estimate(),
                        "error": f"{e!r:.120}"}
        # graftview viewchange on the CPU backend: batched vs per-sig TC
        # assembly plus the eject-equivalence check — CPU-backend
        # latencies (never comparable to device numbers, the degraded
        # flag says so), but the eject contract and the field's schema
        # are proven on every line.
        try:
            viewchange = viewchange_headline(
                repeats=1,
                budget_s=min(
                    float(os.environ.get("HOTSTUFF_TPU_VIEWCHANGE_BUDGET",
                                         "240")),
                    max(0.0, budget_left_s() - 90.0)))
        except Exception as e:  # noqa: BLE001 — headline isolation
            viewchange = {"error": f"{e!r:.120}"}
        try:
            sched = sched_headline_probe()
        except Exception as e:  # noqa: BLE001 — telemetry is best-effort
            sched = {"error": f"{e!r:.120}"}
        try:
            chaos = chaos_headline_probe(_FAULT_PLAN, _WAN_SPEC,
                                         _SLO_SPEC)
        except Exception as e:  # noqa: BLE001 — chaos probe is best-effort
            chaos = {"error": f"{e!r:.120}"}
        try:
            trace = trace_headline_probe()
        except Exception as e:  # noqa: BLE001 — trace probe is best-effort
            trace = {"error": f"{e!r:.120}"}
        try:
            surge = surge_headline_probe()
        except Exception as e:  # noqa: BLE001 — surge probe is best-effort
            surge = {"error": f"{e!r:.120}"}
        try:
            guard = guard_headline_probe()
        except Exception as e:  # noqa: BLE001 — guard probe is best-effort
            guard = {"error": f"{e!r:.120}"}
        # graftcadence ring-vs-staged on the forced-host mesh: the same
        # bounded-subprocess emit-or-die discipline as mesh_rlc — the
        # ring story (depth sweep, queue-wait p99, resident-slot
        # bit-identity) is proven on the degraded line too.
        try:
            cadence = cadence_headline(budget_s=min(
                float(os.environ.get("HOTSTUFF_TPU_CADENCE_BUDGET",
                                     "240")),
                max(0.0, budget_left_s() - 90.0)))
        except Exception as e:  # noqa: BLE001 — headline isolation
            cadence = {"error": f"{e!r:.120}"}
        # graftingress user-population sweep: host-mode in-process (no
        # device), so the degraded line proves the same signed-ingress
        # story as the live one.
        try:
            users = users_headline_probe(budget_s=min(
                float(os.environ.get("HOTSTUFF_TPU_USERS_BUDGET",
                                     "240")),
                max(0.0, budget_left_s() - 90.0)))
        except Exception as e:  # noqa: BLE001 — headline isolation
            users = {"error": f"{e!r:.120}"}
        # graftfleet failover + flood isolation: host-mode in-process,
        # so the degraded line carries the same fleet story as the
        # live one.
        try:
            fleet = fleet_headline_probe()
        except Exception as e:  # noqa: BLE001 — fleet probe is best-effort
            fleet = {"error": f"{e!r:.120}"}
        # The watchdog stays armed until the moment of the real emit: a
        # stall anywhere above (including the sched probe) must still
        # produce a parseable line, which is this path's whole contract.
        emitted.set()
        # Report the backend that actually ran (an already-initialized
        # device backend wins over the cpu config flip above).
        emit(value, 0.0, degraded=True, backend=jax.default_backend(),
             note=reason, rlc=rlc, mesh_rlc=mesh_rlc,
             committee_scale=committee_scale, roofline=roofline,
             viewchange=viewchange, sched=sched, chaos=chaos, trace=trace,
             surge=surge, guard=guard, cadence=cadence, users=users,
             fleet=fleet)
    except Exception as e:  # noqa: BLE001 — the line must still be emitted
        emitted.set()
        emit(0, 0, degraded=True,
             error=f"{reason}; degraded run failed: {e!r:.200}")
    os._exit(0)


def make_batch():
    """G*N fully distinct (key, message, signature) triples — no repetition,
    so the headline number is honest about per-signature cost.  Generated
    through OpenSSL (deterministic Ed25519: bit-identical to the pure-python
    reference, ~100x faster for 16k keypairs)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat,
    )

    rng = np.random.default_rng(2024)
    msgs, pks, sigs = [], [], []
    for _ in range(G * N):
        key = Ed25519PrivateKey.from_private_bytes(rng.bytes(32))
        pk = key.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        msg = rng.bytes(64)
        msgs.append(msg)
        pks.append(pk)
        sigs.append(key.sign(msg))
    return msgs, pks, sigs


def cpu_baseline(msgs, pks, sigs) -> float:
    """Single-core verifies/sec via OpenSSL (cryptography lib)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    keys = [Ed25519PublicKey.from_public_bytes(pk) for pk in pks]
    # warmup
    keys[0].verify(sigs[0], msgs[0])
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for k, m, s in zip(keys, msgs, sigs):
            k.verify(s, m)
        dt = time.perf_counter() - t0
        best = max(best, len(msgs) / dt)
    return best


def tpu_throughput(msgs, pks, sigs, on_trial=None) -> float:
    """End-to-end pipelined verifies/sec.  Every timed round pays full host
    preparation AND the h2d transfer for all G*N signatures; both run on a
    prep thread overlapping the device compute of the previous round (the
    SHA-512 loop releases the GIL; the tunnel transfer blocks in C).  The
    (G, N) mask is all-reduced in-program, so each round returns one byte,
    and verdicts are fetched after the last round — per-fetch tunnel
    latency (~70 ms) is paid once per trial, not once per round."""
    import jax
    import jax.numpy as jnp

    from hotstuff_tpu.crypto import eddsa
    from hotstuff_tpu.ops import ed25519 as E

    assert N == eddsa.MAX_SUBBATCH
    verify_chunked = E.verify_packed_chunked  # (G, N, 128) -> (G, N)
    # Donate each round's device buffer (consumed exactly once below), so
    # the headline measures the same donation behavior the sidecar's
    # production launches use; CPU doesn't implement donation (debug runs
    # would only warn per launch).
    donate = {} if jax.default_backend() == "cpu" \
        else dict(donate_argnums=0)
    verify_all = jax.jit(lambda arr: verify_chunked(arr).all(), **donate)

    def prep_round():
        rows = []
        for g in range(G):
            prep = eddsa.prepare_batch(msgs[g * N:(g + 1) * N],
                                       pks[g * N:(g + 1) * N],
                                       sigs[g * N:(g + 1) * N])
            assert prep["host_ok"].all()
            rows.append(prep["packed"])
        return np.stack(rows)

    out = verify_all(jax.device_put(prep_round()))   # compile + warmup
    assert bool(np.asarray(out)), "benchmark signatures must verify"

    from concurrent.futures import ThreadPoolExecutor

    # Three-stage pipeline on two helper threads: prep (CPU-bound SHA-512,
    # ~55 ms/round, releases the GIL) and h2d transfer (tunnel-bound,
    # ~155 ms/round, blocks in C) run as separate stages so the transfer
    # of round i+1 overlaps the device compute of round i WITHOUT waiting
    # behind round i+2's prep — prep+transfer serialized on one thread is
    # exactly the bottleneck that capped the 2-stage pipeline at ~80k.
    best = 0.0
    # HOTSTUFF_TPU_XFER_STREAMS=2 runs two concurrent h2d transfers —
    # worth it ONLY if scripts/exp_xfer_streams.py shows the tunnel's
    # ~13 MB/s is a per-stream (TCP window) limit rather than the link's
    # physical rate; with a physical limit two streams just split it.
    try:
        xfer_streams = max(
            1, int(os.environ.get("HOTSTUFF_TPU_XFER_STREAMS", "1").strip()))
    except ValueError:
        raise SystemExit("HOTSTUFF_TPU_XFER_STREAMS must be an integer")
    with ThreadPoolExecutor(1) as prep_pool, \
         ThreadPoolExecutor(xfer_streams) as xfer_pool:
        lead = xfer_streams  # transfers in flight ahead of compute
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            preps = [prep_pool.submit(prep_round) for _ in range(1 + lead)]
            devs = [xfer_pool.submit(
                        lambda f=preps[i]: jax.device_put(f.result()))
                    for i in range(lead)]
            verdicts = []
            for r in range(ROUNDS):
                if r + 1 + lead < ROUNDS:
                    preps.append(prep_pool.submit(prep_round))
                if r + lead < ROUNDS:
                    devs.append(xfer_pool.submit(
                        lambda f=preps[r + lead]: jax.device_put(f.result())))
                verdicts.append(verify_all(devs[r].result()))
            oks = [bool(np.asarray(v)) for v in verdicts]  # forces the work
            dt = time.perf_counter() - t0
            assert all(oks), "benchmark signatures must verify"
            best = max(best, G * N * ROUNDS / dt)
            if on_trial:
                on_trial(best)
    return best


def main(argv=None):
    # --fault-plan rides through to the chaos headline probe (a path to a
    # JSON plan or an inline DSL spec; the HOTSTUFF_TPU_FAULT_PLAN env is
    # the no-argv channel).  parse_known_args: the driver may pass flags
    # this bench does not own.
    import argparse

    # Kill-proof emit FIRST (graftguard satellite): from here on, the
    # driver's window closing (SIGTERM ahead of the rc=124 SIGKILL) or
    # a stage alarm re-emits the best line already measured instead of
    # dying silently; every emit below also lands cache-first on disk.
    install_kill_handlers()

    global _FAULT_PLAN, _WAN_SPEC, _SLO_SPEC
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--fault-plan", default=None)
    ap.add_argument("--wan", default=None)
    ap.add_argument("--slo", default=None)
    known, _ = ap.parse_known_args(argv)
    _FAULT_PLAN = known.fault_plan \
        or os.environ.get("HOTSTUFF_TPU_FAULT_PLAN") or None
    _WAN_SPEC = known.wan or os.environ.get("HOTSTUFF_TPU_WAN") or None
    _SLO_SPEC = known.slo or os.environ.get("HOTSTUFF_TPU_SLO") or None

    # Watchdog: the tunneled TPU can wedge indefinitely (observed: a plain
    # 8x8 matmul never returning).  A hung bench is worse than a failed
    # one — the driver's round-end run must always terminate.
    import threading

    # Capped probe: a wedged tunnel hangs ANY device call indefinitely
    # (observed: outages of 1-8+ hours), and only a subprocess can be
    # timed out reliably.  probe_device bounds the retry loop by
    # attempts, its own window, AND the remaining outer bench budget
    # (HOTSTUFF_TPU_BENCH_DEADLINE) — round 5 spent its ENTIRE driver
    # window on nine probe retries and emitted nothing (BENCH_r05.json
    # rc=124).  When any cap is hit, fall back to a JAX_PLATFORMS=cpu
    # degraded measurement: a parseable line always lands, with slack to
    # spare inside the driver's hard timeout.
    ok, probe_reason = probe_device()
    if not ok:
        run_degraded(probe_reason)

    # Persistent XLA compilation cache BEFORE anything compiles in this
    # process (the in-process msm sweep below is the first compiler; the
    # old subprocess children configured the cache themselves).
    from hotstuff_tpu.utils.xla_cache import configure_xla_cache

    configure_xla_cache()

    # MSM window-chunk sweep, IN-PROCESS (set_msm_window_chunk re-pins
    # the constant and clears the jit caches — no more subprocess per
    # value; this process now binds the device here, which is fine: the
    # probe subprocesses have exited and every later stage runs in this
    # same process anyway).  Budget-guarded per chunk; failures degrade
    # to per-chunk error entries.  The budget only checks BETWEEN
    # chunks, and the subprocess-per-value timeout that used to bound a
    # wedged compile is gone — so the stage runs under its own watchdog:
    # a stalled tunneled compile emits the best cached measurement (or
    # an error line) instead of eating the whole artifact (the rc=124
    # failure mode the module header documents).
    def _msm_abort():
        emit_cached_or_fail("msm chunk sweep wedged (stage watchdog)")

    msm_budget = float(
        os.environ.get("HOTSTUFF_TPU_MSM_SWEEP_BUDGET", "180"))
    msm_watchdog = threading.Timer(
        min(msm_budget + 120.0,
            max(60.0, budget_left_s() - _DEADLINE_SLACK)), _msm_abort)
    msm_watchdog.daemon = True
    msm_watchdog.start()
    try:
        msm = msm_chunk_sweep(budget_s=msm_budget)
    except Exception as e:  # noqa: BLE001
        msm = {"error": f"{e!r:.200}"}
    msm_watchdog.cancel()

    # mesh_rlc headline: a forced-host CPU-mesh subprocess (no device
    # contention with the stages below), budgeted so the main headline
    # measurement keeps at least its usual window of the outer budget.
    mesh_rlc = mesh_rlc_headline(budget_s=min(
        float(os.environ.get("HOTSTUFF_TPU_MESH_RLC_BUDGET", "240")),
        max(0.0, budget_left_s() - 900.0)))

    # committee_scale headline (graftscale): the giant-committee sweep
    # on the same forced-host mesh — also a bounded subprocess, also
    # budgeted against what the main measurement must keep.
    committee_scale = committee_scale_headline(budget_s=min(
        float(os.environ.get("HOTSTUFF_TPU_COMMITTEE_BUDGET", "240")),
        max(0.0, budget_left_s() - 900.0)))

    def _abort():
        emit_cached_or_fail(
            "watchdog: TPU unresponsive for 900s after a healthy probe")

    watchdog = threading.Timer(
        min(900.0, max(60.0, budget_left_s() - _DEADLINE_SLACK)), _abort)
    watchdog.daemon = True
    watchdog.start()

    from hotstuff_tpu.ops import field25519

    field25519.mul_selfcheck()  # trip fast if this backend's conv is inexact
    try:
        msgs, pks, sigs = make_batch()
        cpu = cpu_baseline(msgs, pks, sigs)
    except Exception as e:  # e.g. `cryptography` missing: no OpenSSL
        watchdog.cancel()   # baseline — degrade rather than die silently
        run_degraded(f"headline prerequisites failed: {e!r:.200}")
        return

    def on_trial(best):
        # Capture-on-every-improving-trial: the line is on stdout (and the
        # cache on disk) the moment the FIRST trial lands, so a mid-run
        # wedge or driver timeout still leaves a parseable measurement.
        save_cache(best, best / cpu, cpu)
        emit(best, best / cpu)

    try:
        tpu = tpu_throughput(msgs, pks, sigs, on_trial=on_trial)
    except Exception as e:  # device died mid-measurement
        watchdog.cancel()
        emit_cached_or_fail(f"measurement aborted: {e!r:.300}")
        return
    save_cache(tpu, tpu / cpu, cpu)
    watchdog.cancel()
    # RLC headline under its OWN bounded watchdog: the headline number is
    # already measured and cached, so a wedge in this stage must neither
    # relabel the run "unresponsive" nor drop the measurement — it just
    # ships the line with the rlc field marked aborted.  (budget_s only
    # checks between sizes; a single stalled compile needs the timer.)
    def _rlc_abort():
        emit_final(tpu, cpu, rlc={"error": "rlc stage watchdog (420s)"},
                   msm_window_chunk=msm, mesh_rlc=mesh_rlc,
                   committee_scale=committee_scale,
                   roofline={"est": roofline_estimate(),
                             "skipped": True,
                             "note": "rlc stage watchdog fired first"})
        os._exit(0)

    rlc_watchdog = threading.Timer(420.0, _rlc_abort)
    rlc_watchdog.daemon = True
    rlc_watchdog.start()
    try:
        rlc = rlc_compare(budget_s=float(
            os.environ.get("HOTSTUFF_TPU_RLC_BUDGET", "300")))
    except Exception as e:  # noqa: BLE001 — headline must not die on rlc
        rlc = {"error": f"{e!r:.200}"}
    rlc_watchdog.cancel()
    # graftkern roofline: lax vs pallas sigs/sec/chip against the
    # arithmetic ceiling, derated against what is left of the outer
    # budget.  A Mosaic failure on new silicon degrades to a per-route
    # error entry; a Mosaic compile that WEDGES needs the timer (the
    # budget only checks between routes) — on fire, the already-measured
    # fields still ship instead of dying with the stage.
    def _roofline_abort():
        emit_final(tpu, cpu, rlc=rlc, msm_window_chunk=msm,
                   mesh_rlc=mesh_rlc, committee_scale=committee_scale,
                   roofline={"est": roofline_estimate(),
                             "error": "roofline stage watchdog"})
        os._exit(0)

    roofline_budget = min(
        float(os.environ.get("HOTSTUFF_TPU_ROOFLINE_BUDGET", "300")),
        max(0.0, budget_left_s() - _DEADLINE_SLACK))
    roofline_watchdog = threading.Timer(
        min(max(60.0, roofline_budget + 180.0),
            max(60.0, budget_left_s() - 60.0)), _roofline_abort)
    roofline_watchdog.daemon = True
    roofline_watchdog.start()
    try:
        roofline = roofline_headline(budget_s=roofline_budget)
    except Exception as e:  # noqa: BLE001 — headline isolation
        roofline = {"error": f"{e!r:.200}"}
    roofline_watchdog.cancel()
    # graftview viewchange: batched vs per-sig TC assembly.  Compile-
    # bound (fresh verify_batch buckets), so it gets the same stage-
    # watchdog discipline as rlc/roofline — on fire, the already-measured
    # fields ship with the stage marked instead of eating the line.
    def _viewchange_abort():
        emit_final(tpu, cpu, rlc=rlc, msm_window_chunk=msm,
                   mesh_rlc=mesh_rlc, committee_scale=committee_scale,
                   roofline=roofline,
                   viewchange={"error": "viewchange stage watchdog"})
        os._exit(0)

    viewchange_budget = min(
        float(os.environ.get("HOTSTUFF_TPU_VIEWCHANGE_BUDGET", "240")),
        max(0.0, budget_left_s() - _DEADLINE_SLACK))
    viewchange_watchdog = threading.Timer(
        min(max(60.0, viewchange_budget + 120.0),
            max(60.0, budget_left_s() - 60.0)), _viewchange_abort)
    viewchange_watchdog.daemon = True
    viewchange_watchdog.start()
    try:
        viewchange = viewchange_headline(budget_s=viewchange_budget)
    except Exception as e:  # noqa: BLE001 — headline isolation
        viewchange = {"error": f"{e!r:.200}"}
    viewchange_watchdog.cancel()
    try:
        sched = sched_headline_probe()
    except Exception as e:  # noqa: BLE001 — telemetry is best-effort
        sched = {"error": f"{e!r:.120}"}
    try:
        chaos = chaos_headline_probe(_FAULT_PLAN, _WAN_SPEC, _SLO_SPEC)
    except Exception as e:  # noqa: BLE001 — chaos probe is best-effort
        chaos = {"error": f"{e!r:.120}"}
    try:
        trace = trace_headline_probe()
    except Exception as e:  # noqa: BLE001 — trace probe is best-effort
        trace = {"error": f"{e!r:.120}"}
    try:
        surge = surge_headline_probe()
    except Exception as e:  # noqa: BLE001 — surge probe is best-effort
        surge = {"error": f"{e!r:.120}"}
    try:
        guard = guard_headline_probe()
    except Exception as e:  # noqa: BLE001 — guard probe is best-effort
        guard = {"error": f"{e!r:.120}"}
    # graftcadence: ring vs staged on the forced-host mesh — a bounded
    # subprocess like mesh_rlc (its own watchdog discipline), budgeted
    # against what is left of the outer window.
    try:
        cadence = cadence_headline(budget_s=min(
            float(os.environ.get("HOTSTUFF_TPU_CADENCE_BUDGET", "240")),
            max(0.0, budget_left_s() - 60.0)))
    except Exception as e:  # noqa: BLE001 — headline isolation
        cadence = {"error": f"{e!r:.120}"}
    # graftingress user-population sweep: in-process host-mode engine,
    # no device contention with anything above.
    try:
        users = users_headline_probe(budget_s=min(
            float(os.environ.get("HOTSTUFF_TPU_USERS_BUDGET", "240")),
            max(0.0, budget_left_s() - 60.0)))
    except Exception as e:  # noqa: BLE001 — headline isolation
        users = {"error": f"{e!r:.120}"}
    # graftfleet: kill-primary failover goodput + greedy-tenant flood
    # isolation, in-process host-mode (no device contention).
    try:
        fleet = fleet_headline_probe()
    except Exception as e:  # noqa: BLE001 — fleet probe is best-effort
        fleet = {"error": f"{e!r:.120}"}
    emit_final(tpu, cpu, rlc=rlc, msm_window_chunk=msm,
               mesh_rlc=mesh_rlc, committee_scale=committee_scale,
               roofline=roofline, viewchange=viewchange, sched=sched,
               chaos=chaos, trace=trace, surge=surge, guard=guard,
               cadence=cadence, users=users, fleet=fleet)


if __name__ == "__main__":
    main()

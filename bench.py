"""Headline benchmark: Ed25519 batch verification throughput on one TPU chip.

Prints ONE JSON line (re-printed, improving, after every timed trial —
the driver's bounded run takes the last):
  {"metric": "ed25519-batch-verify", "value": <sigs/sec on TPU>,
   "unit": "sigs/sec", "vs_baseline": <TPU / single-core-CPU>}

The baseline is the same machine's single-core CPU verifying the same
signatures one-by-one through the `cryptography` library (OpenSSL's
optimized C/asm Ed25519) — the honest stand-in for the reference's
ed25519-dalek verify path (crypto/src/lib.rs:204-208), measured fresh at
every run.  North star (BASELINE.json): >= 10x single-core CPU, measured
here over rounds of 16 sub-batches of 1024 (the sidecar's own maximum
bulk launch, MAX_COALESCED = 16 * MAX_SUBBATCH).

Measurement shape (see scripts/PROFILE.md round-5 notes): G sub-batches
of 1024 distinct (key, message, signature) triples are verified by ONE
jitted program per round (lax.scan over sub-batches, mask all-reduced
in-program so only ONE byte returns per round), with host preparation
AND the host->device transfer of round i+1 running on a prep thread
while the device executes round i — the tunneled chip charges ~13 MB/s
on h2d and ~70 ms per fetch, so overlap and fetch-minimization are what
separate the device's ~124k sigs/s ceiling from a transfer-bound 55k.

Tunnel-outage resilience: every improving trial persists the measured
line to results/headline_cache.json.  If the driver's bounded run hits a
dead tunnel (rounds 3 and 4 both lost their artifacts this way), the
bench emits the best previously MEASURED line, tagged
"source": "cached-measurement" with its timestamp, instead of a zero.

The cache is namespaced by a hash of the kernel sources (bench.py, the
ops/crypto files the measurement exercises): a best recorded by OLD code
can never answer for regressed HEAD — after any kernel edit the cache
starts empty.  When a live run completes, the LIVE measurement is always
the headline `value`; a higher best-on-record (same kernel hash, i.e.
tunnel weather) rides along as `best_on_record` so the artifact shows
both without the ratchet hiding a regression (round-5 ADVICE.md high).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

N = 1024          # sub-batch size; asserted == eddsa.MAX_SUBBATCH below
G = 16            # sub-batches per device dispatch
ROUNDS = 20       # timed pipelined rounds per trial: the steady state is
                  # transfer-bound (~155 ms/round h2d through the tunnel),
                  # so pipeline fill + final fetch are pure overhead —
                  # 20 rounds amortizes them to ~5% (6 rounds paid ~18%)
TRIALS = 4        # best-of: the tunneled TPU and the shared host CPU both
                  # drift +-40% with neighbor load; best-of-n measures the
                  # hardware, not the neighbors

CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "results", "headline_cache.json")

# The sources whose edits can change what this bench measures: a cached
# best is only comparable to a live run built from the same kernel.
_KERNEL_SOURCES = (
    "bench.py",
    "hotstuff_tpu/ops/ed25519.py",
    "hotstuff_tpu/ops/field25519.py",
    "hotstuff_tpu/crypto/eddsa.py",
)


def kernel_fingerprint() -> str:
    """Hash of the kernel sources; namespaces the headline cache so a
    stale best can only ever answer for the code that produced it."""
    import hashlib

    root = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for rel in _KERNEL_SOURCES:
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
        h.update(b"\x00")
    return h.hexdigest()[:16]


def load_cache():
    try:
        with open(CACHE_PATH) as f:
            c = json.load(f)
        if c.get("value", 0) > 0 and \
                c.get("kernel") == kernel_fingerprint():
            return c
    except (OSError, ValueError):
        pass
    return None


def save_cache(value: float, vs_baseline: float, cpu: float):
    cached = load_cache()
    if cached and cached["value"] >= value:
        return
    # Honesty guard: a CPU-contended host (anything else running) starves
    # the single-core baseline and INFLATES the ratio.  Never store a
    # ratio whose baseline is far below the best baseline on record —
    # a contended run can only under-measure the TPU, never over-claim.
    if cached and cpu < 0.8 * cached.get("cpu_baseline", 0):
        return
    tmp = CACHE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "metric": "ed25519-batch-verify",
            "value": round(value, 1),
            "unit": "sigs/sec",
            "vs_baseline": round(vs_baseline, 3),
            "cpu_baseline": round(cpu, 1),
            "kernel": kernel_fingerprint(),
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
        }, f)
    os.replace(tmp, CACHE_PATH)


def emit(value: float, vs_baseline: float, **extra):
    line = {"metric": "ed25519-batch-verify", "value": round(value, 1),
            "unit": "sigs/sec", "vs_baseline": round(vs_baseline, 3)}
    line.update(extra)
    print(json.dumps(line), flush=True)


def emit_cached(cached, note: str, **extra):
    """The one shape for a cached-measurement line (dead-tunnel fallback
    AND slow-live-run fallback emit through here)."""
    emit(cached["value"], cached["vs_baseline"],
         source="cached-measurement",
         measured_at=cached.get("measured_at", "unknown"),
         note=note, **extra)


def emit_final(tpu: float, cpu: float):
    """Final emit after a completed live run: the LIVE measurement is the
    headline `value` — the driver records the last line, and a number
    this run's code did not achieve must never stand in for it.  A
    higher best-on-record (same kernel fingerprint, so the difference is
    tunnel weather, not code) rides along as secondary fields."""
    cached = load_cache()
    if cached and cached["value"] > round(tpu, 1):
        emit(tpu, tpu / cpu,
             best_on_record=cached["value"],
             best_vs_baseline=cached["vs_baseline"],
             best_measured_at=cached.get("measured_at", "unknown"),
             note="live run below best on record for this exact kernel "
                  "(tunnel weather)")
    else:
        emit(tpu, tpu / cpu)


def emit_cached_or_fail(reason: str, code: int = 3):
    """A dead tunnel should surface the best MEASURED number on record,
    not a zero: the cache only ever holds values a real run produced."""
    cached = load_cache()
    if cached:
        emit_cached(cached, reason)
        os._exit(0)
    emit(0, 0, error=reason)
    os._exit(code)


def make_batch():
    """G*N fully distinct (key, message, signature) triples — no repetition,
    so the headline number is honest about per-signature cost.  Generated
    through OpenSSL (deterministic Ed25519: bit-identical to the pure-python
    reference, ~100x faster for 16k keypairs)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat,
    )

    rng = np.random.default_rng(2024)
    msgs, pks, sigs = [], [], []
    for _ in range(G * N):
        key = Ed25519PrivateKey.from_private_bytes(rng.bytes(32))
        pk = key.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        msg = rng.bytes(64)
        msgs.append(msg)
        pks.append(pk)
        sigs.append(key.sign(msg))
    return msgs, pks, sigs


def cpu_baseline(msgs, pks, sigs) -> float:
    """Single-core verifies/sec via OpenSSL (cryptography lib)."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    keys = [Ed25519PublicKey.from_public_bytes(pk) for pk in pks]
    # warmup
    keys[0].verify(sigs[0], msgs[0])
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for k, m, s in zip(keys, msgs, sigs):
            k.verify(s, m)
        dt = time.perf_counter() - t0
        best = max(best, len(msgs) / dt)
    return best


def tpu_throughput(msgs, pks, sigs, on_trial=None) -> float:
    """End-to-end pipelined verifies/sec.  Every timed round pays full host
    preparation AND the h2d transfer for all G*N signatures; both run on a
    prep thread overlapping the device compute of the previous round (the
    SHA-512 loop releases the GIL; the tunnel transfer blocks in C).  The
    (G, N) mask is all-reduced in-program, so each round returns one byte,
    and verdicts are fetched after the last round — per-fetch tunnel
    latency (~70 ms) is paid once per trial, not once per round."""
    import jax
    import jax.numpy as jnp

    from hotstuff_tpu.crypto import eddsa
    from hotstuff_tpu.ops import ed25519 as E

    assert N == eddsa.MAX_SUBBATCH
    verify_chunked = E.verify_packed_chunked  # (G, N, 128) -> (G, N)
    # Donate each round's device buffer (consumed exactly once below), so
    # the headline measures the same donation behavior the sidecar's
    # production launches use; CPU doesn't implement donation (debug runs
    # would only warn per launch).
    donate = {} if jax.default_backend() == "cpu" \
        else dict(donate_argnums=0)
    verify_all = jax.jit(lambda arr: verify_chunked(arr).all(), **donate)

    def prep_round():
        rows = []
        for g in range(G):
            prep = eddsa.prepare_batch(msgs[g * N:(g + 1) * N],
                                       pks[g * N:(g + 1) * N],
                                       sigs[g * N:(g + 1) * N])
            assert prep["host_ok"].all()
            rows.append(prep["packed"])
        return np.stack(rows)

    out = verify_all(jax.device_put(prep_round()))   # compile + warmup
    assert bool(np.asarray(out)), "benchmark signatures must verify"

    from concurrent.futures import ThreadPoolExecutor

    # Three-stage pipeline on two helper threads: prep (CPU-bound SHA-512,
    # ~55 ms/round, releases the GIL) and h2d transfer (tunnel-bound,
    # ~155 ms/round, blocks in C) run as separate stages so the transfer
    # of round i+1 overlaps the device compute of round i WITHOUT waiting
    # behind round i+2's prep — prep+transfer serialized on one thread is
    # exactly the bottleneck that capped the 2-stage pipeline at ~80k.
    best = 0.0
    # HOTSTUFF_TPU_XFER_STREAMS=2 runs two concurrent h2d transfers —
    # worth it ONLY if scripts/exp_xfer_streams.py shows the tunnel's
    # ~13 MB/s is a per-stream (TCP window) limit rather than the link's
    # physical rate; with a physical limit two streams just split it.
    try:
        xfer_streams = max(
            1, int(os.environ.get("HOTSTUFF_TPU_XFER_STREAMS", "1").strip()))
    except ValueError:
        raise SystemExit("HOTSTUFF_TPU_XFER_STREAMS must be an integer")
    with ThreadPoolExecutor(1) as prep_pool, \
         ThreadPoolExecutor(xfer_streams) as xfer_pool:
        lead = xfer_streams  # transfers in flight ahead of compute
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            preps = [prep_pool.submit(prep_round) for _ in range(1 + lead)]
            devs = [xfer_pool.submit(
                        lambda f=preps[i]: jax.device_put(f.result()))
                    for i in range(lead)]
            verdicts = []
            for r in range(ROUNDS):
                if r + 1 + lead < ROUNDS:
                    preps.append(prep_pool.submit(prep_round))
                if r + lead < ROUNDS:
                    devs.append(xfer_pool.submit(
                        lambda f=preps[r + lead]: jax.device_put(f.result())))
                verdicts.append(verify_all(devs[r].result()))
            oks = [bool(np.asarray(v)) for v in verdicts]  # forces the work
            dt = time.perf_counter() - t0
            assert all(oks), "benchmark signatures must verify"
            best = max(best, G * N * ROUNDS / dt)
            if on_trial:
                on_trial(best)
    return best


def main():
    # Watchdog: the tunneled TPU can wedge indefinitely (observed: a plain
    # 8x8 matmul never returning).  A hung bench is worse than a failed
    # one — the driver's round-end run must always terminate.
    import threading

    # Probe-with-retry-window: a wedged tunnel hangs ANY device call
    # indefinitely (observed: outages of 1-8+ hours), and only a
    # subprocess can be timed out reliably.  Keep probing every couple of
    # minutes across a bounded window (HOTSTUFF_TPU_PROBE_WINDOW seconds,
    # default 40 min); when the window is exhausted, fall back to the best
    # cached MEASURED line rather than a zero.  The measurement watchdog
    # starts only after the device answers, so waiting never eats bench
    # time.
    import subprocess
    import sys

    window = float(os.environ.get("HOTSTUFF_TPU_PROBE_WINDOW", "2400"))
    probe = ("import jax, jax.numpy as jnp, numpy as np;"
             "np.asarray(jnp.ones((8, 8)) @ jnp.ones((8, 8)))")
    deadline = time.monotonic() + window
    attempt = 0
    proc_errors = 0
    last_err = "tunnel wedged (probe timeouts)"
    while True:
        attempt += 1
        retry_sleep = 120.0
        try:
            subprocess.run([sys.executable, "-c", probe], timeout=75,
                           check=True, capture_output=True)
            break
        except subprocess.TimeoutExpired:
            proc_errors = 0
            last_err = "tunnel wedged (probe timeouts)"
        except subprocess.CalledProcessError as e:
            # A probe that exits nonzero (bad install, import error) is
            # deterministic — only timeouts are worth waiting out, so
            # retry these quickly and give up after a few in a row.
            proc_errors += 1
            retry_sleep = 5.0
            last_err = (e.stderr or b"").decode("utf-8", "replace")[-300:]
            if proc_errors >= 4:
                emit_cached_or_fail(
                    f"device probe errored {proc_errors}x in a row "
                    f"(not a wedge): {last_err}")
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            emit_cached_or_fail(
                f"device probe failed {attempt}x over {window:.0f}s "
                f"window: {last_err}")
        print(f"bench: device probe attempt {attempt} failed; retrying "
              f"({remaining:.0f}s left in window)", file=sys.stderr)
        time.sleep(min(retry_sleep, max(0.0, remaining)))

    def _abort():
        emit_cached_or_fail(
            "watchdog: TPU unresponsive for 900s after a healthy probe")

    watchdog = threading.Timer(900.0, _abort)
    watchdog.daemon = True
    watchdog.start()

    # Persistent XLA compilation cache (same dir the sidecar uses): the
    # driver runs this script in a cold process, and the chunked-verify
    # program costs 30-60 s to compile through the tunnel.
    from hotstuff_tpu.utils.xla_cache import configure_xla_cache

    configure_xla_cache()

    from hotstuff_tpu.ops import field25519

    field25519.mul_selfcheck()  # trip fast if this backend's conv is inexact
    msgs, pks, sigs = make_batch()
    cpu = cpu_baseline(msgs, pks, sigs)

    def on_trial(best):
        # Capture-on-every-improving-trial: the line is on stdout (and the
        # cache on disk) the moment the FIRST trial lands, so a mid-run
        # wedge or driver timeout still leaves a parseable measurement.
        save_cache(best, best / cpu, cpu)
        emit(best, best / cpu)

    try:
        tpu = tpu_throughput(msgs, pks, sigs, on_trial=on_trial)
    except Exception as e:  # device died mid-measurement
        watchdog.cancel()
        emit_cached_or_fail(f"measurement aborted: {e!r:.300}")
        return
    watchdog.cancel()
    save_cache(tpu, tpu / cpu, cpu)
    emit_final(tpu, cpu)


if __name__ == "__main__":
    main()

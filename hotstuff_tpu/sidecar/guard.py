"""graftguard: the supervised verify engine — launch deadlines, wedge
detection, poison-batch quarantine, and crash-only reboot support.

The repo's most persistent operational failure is the *wedged device
launch*: one hung ``dispatch()``/``fetch()`` through the tunneled device
parks the engine thread — and every queued consensus verify behind it —
until the C++ circuit breaker times the whole sidecar out (BENCH_r03's
wedged compile, the r04/r05 rc=124 rounds).  Production inference
stacks solve exactly this with per-launch deadlines, hung-device
watchdogs, and crash-only restart; the reference's tokio nodes get it
for free from task-level timeouts.  This module is that layer for the
single-threaded verify engine:

    launch ──▶ guard worker thread (disposable, one per launch)
       │            │
       │            ▼ completes within its per-shape deadline
       │        result → engine replies normally
       │
       └──▶ monitor thread sees the deadline overrun → WEDGED
                │
                ▼  the engine's degradation ladder (service._wedge_ladder)
            1. latency-class requests in the wedged batch are answered
               from the HOST path (bit-identical masks — the same
               ref_ed25519 reference verify_batch is property-tested
               against);
            2. bulk-class requests get OP_BUSY with a drain-derived
               retry-after (BusyReply below);
            3. the batch's records are quarantined (repeat offenders
               trigger poison bisection, below);
            4. the engine performs a CRASH-ONLY reboot: tear down the
               device-side caches, re-warm asynchronously off the
               populated XLA cache/manifest (the host path serves
               meanwhile, bulk admission replies BUSY), and resume
               device routing only after a canary launch passes.

Deadlines are per launch shape, derived from the CompileManifest's run
history: a warmed boot (the manifest has entries for this kernel) gets
the tight ``warm_grace_s`` default until the guard has observed enough
launches of a shape to derive ``p99_multiple`` x its measured p99; a
cold boot — where a first-ever compile can legitimately take minutes —
gets the generous ``compile_budget_s``.  Env knobs:

    HOTSTUFF_TPU_GUARD_COMPILE_BUDGET_S   cold/first-compile deadline (180)
    HOTSTUFF_TPU_GUARD_WARM_GRACE_S       warmed-shape fallback deadline (30)
    HOTSTUFF_TPU_GUARD_P99_MULTIPLE      deadline = multiple x observed p99 (8)
    HOTSTUFF_TPU_GUARD_MIN_DEADLINE_S    floor under the p99 rule (1.0)
    HOTSTUFF_TPU_GUARD_MAX_REBOOTS       canary failures before the engine
                                         stays on the host path (3)
    HOTSTUFF_TPU_GUARD_MAX_BISECT_PROBES poison-bisection probe budget (64)

Crash-only discipline: a wedged launch thread is never interrupted (a
hung tunnel read cannot be cancelled from Python) — it is ABANDONED
with its disposable thread (daemon: it dies with the process), its late
completion is discarded, and a fresh thread serves the next launch.
Nothing the abandoned thunk eventually does can reach a client: replies
happen on the engine thread only after a guarded call returns clean.

Poison bisection reuses the RLC bisection discipline (halve, probe,
recurse into the wedging half): repeat wedges on the same records mark
them pending, and after the reboot's canary passes the engine probes
subsets under the guard until the minimal poison set is isolated.  A
poisoned record is host-verified (and counted) forever after — one
adversarial or cursed record can never take the device leg down again.

BLS launches ride the guard too: ``_execute_bls_inner`` RETURNS its
verdict (it never touches the connection), the engine thread replies
only after the guarded call comes back clean, and a wedged pairing gets
the BLS arm of the ladder — transient reply (the C++ client reads
nullopt and runs its own outage handling) plus the crash-only reboot.
The unwarmed-shape host fallback (``_bls_multi_warmed``) remains as the
first line; the guard is what bounds it when the host pairing itself
wedges.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
from time import monotonic

log = logging.getLogger("sidecar.guard")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


class WedgedLaunch(RuntimeError):
    """A guarded launch overran its deadline; the worker was abandoned."""

    def __init__(self, key: str, deadline_s: float):
        super().__init__(
            f"launch {key} overran its {deadline_s:g}s deadline (wedged)")
        self.key = key
        self.deadline_s = deadline_s


class BusyReply:
    """Sentinel reply value for the wedge ladder's bulk lane: the
    connection handler encodes it as an OP_BUSY frame carrying the
    drain-derived retry-after hint instead of a verdict mask (protocol
    v4 — the C++ client reads it as a shed and the breaker reads it as
    a LIVE sidecar, never silence)."""

    __slots__ = ("retry_after_ms",)

    def __init__(self, retry_after_ms: int):
        self.retry_after_ms = int(retry_after_ms)


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class LaunchDeadlines:
    """Per-shape launch deadlines off the compile-manifest run history
    plus the guard's own observed launch walls.

    Until ``MIN_OBSERVATIONS`` launches of a shape key have completed,
    the deadline is the boot-state fallback: ``warm_grace_s`` when the
    manifest says this kernel's shapes were warmed before (the XLA disk
    cache deserializes — nothing should take 30 s), ``compile_budget_s``
    otherwise (a first-ever compile through the tunnel can legitimately
    take minutes and must not read as a wedge).  With enough
    observations the deadline tightens to ``p99_multiple`` x the
    measured p99, floored at ``min_deadline_s``."""

    MIN_OBSERVATIONS = 8
    SAMPLES_CAP = 256
    # Keys that are ALWAYS compile-class regardless of boot state or
    # observed history: the reboot canary and the poison-bisection
    # probes run right after _teardown_device cleared the in-process
    # jit caches, so their first launch re-traces/deserializes — a
    # tight warmed deadline there would false-wedge the recovery
    # itself (observed: a contended host failing every canary).
    COMPILE_CLASS_PREFIXES = ("canary:", "poison-probe:")
    # graftcadence tick launches: the ring only ever dispatches warmed
    # ShapeRegistry buckets (a fresh compile mid-run is the lint rule's
    # whole point), so an unobserved ``tick:`` key gets the warm grace
    # regardless of boot state — the compile budget would let a wedged
    # cadence tick stall the resident pipeline for minutes on a cold
    # manifest that the ring, by construction, never compiles under.
    TICK_CLASS_PREFIX = "tick:"

    def __init__(self, warm_boot: bool = False,
                 compile_budget_s: float | None = None,
                 warm_grace_s: float | None = None,
                 p99_multiple: float | None = None,
                 min_deadline_s: float | None = None):
        self.warm_boot = bool(warm_boot)
        self.compile_budget_s = compile_budget_s if compile_budget_s \
            is not None else _env_float(
                "HOTSTUFF_TPU_GUARD_COMPILE_BUDGET_S", 180.0)
        self.warm_grace_s = warm_grace_s if warm_grace_s is not None \
            else _env_float("HOTSTUFF_TPU_GUARD_WARM_GRACE_S", 30.0)
        self.p99_multiple = p99_multiple if p99_multiple is not None \
            else _env_float("HOTSTUFF_TPU_GUARD_P99_MULTIPLE", 8.0)
        self.min_deadline_s = min_deadline_s if min_deadline_s is not None \
            else _env_float("HOTSTUFF_TPU_GUARD_MIN_DEADLINE_S", 1.0)
        self._lock = threading.Lock()
        self._samples: dict[str, list] = {}

    @classmethod
    def from_manifest(cls, manifest, kernel: str, **kw):
        """Deadline policy for a boot against ``manifest``: warmed when
        the manifest already holds shapes for this kernel hash (the
        same record CompileTracker counts hits against), cold
        otherwise."""
        try:
            warm = bool(manifest.shape_walls(kernel))
        except Exception:  # noqa: BLE001 — a hostile manifest means cold
            warm = False
        return cls(warm_boot=warm, **kw)

    def observe(self, key: str, dur_s: float):
        with self._lock:
            samples = self._samples.setdefault(key, [])
            samples.append(float(dur_s))
            del samples[:-self.SAMPLES_CAP]

    def deadline_s(self, key: str) -> float:
        if key.startswith(self.COMPILE_CLASS_PREFIXES):
            return self.compile_budget_s
        with self._lock:
            samples = self._samples.get(key, ())
            if len(samples) >= self.MIN_OBSERVATIONS:
                p99 = _percentile(sorted(samples), 0.99)
                return max(self.min_deadline_s, self.p99_multiple * p99)
        if key.startswith(self.TICK_CLASS_PREFIX):
            return self.warm_grace_s
        return self.warm_grace_s if self.warm_boot \
            else self.compile_budget_s

    def snapshot(self) -> dict:
        """JSON-safe per-key summary (bounded by SAMPLES_CAP keys in
        practice: keys are padded launch buckets, a handful per boot)."""
        with self._lock:
            keys = dict(self._samples)
        out = {}
        for key, samples in sorted(keys.items()):
            out[key] = {"n": len(samples),
                        "deadline_s": round(self.deadline_s(key), 3)}
        return out


class Quarantine:
    """Wedge bookkeeping per (msg, pk, sig) record.

    First wedge on a record is weather (a tunnel hiccup wedges whatever
    batch was in flight); a REPEAT wedge marks the record a bisection
    candidate (``pending``), and ``resolve`` — fed by bisect_poison
    after the reboot's canary passes — moves the confirmed poison
    records into the permanent host-verified set."""

    POISON_WEDGES = 2
    CAP = 4096  # wedge-count records kept (FIFO; an attacker evicts, never grows)

    def __init__(self):
        self._lock = threading.Lock()
        self._wedges: dict = {}       # record -> wedge count (bounded FIFO)
        self._pending: list = []      # repeat offenders awaiting bisection
        self._poisoned: set = set()   # confirmed poison: host-verified forever

    def note_wedged(self, records) -> int:
        """Bump wedge counts for every record of a wedged batch; records
        reaching POISON_WEDGES join the pending-bisection set.  Returns
        how many records are now pending."""
        with self._lock:
            for rec in records:
                if rec in self._poisoned:
                    continue
                count = self._wedges.get(rec, 0) + 1
                if rec not in self._wedges:
                    while len(self._wedges) >= self.CAP:
                        self._wedges.pop(next(iter(self._wedges)))
                self._wedges[rec] = count
                if count >= self.POISON_WEDGES and \
                        rec not in self._pending:
                    self._pending.append(rec)
            return len(self._pending)

    def pending(self) -> list:
        with self._lock:
            return list(self._pending)

    def resolve(self, poison_records) -> int:
        """Close one bisection round: ``poison_records`` move to the
        permanent poisoned set, everything else pending is released
        (its wedge count survives, so a third wedge re-marks it).
        Returns how many records were newly poisoned."""
        with self._lock:
            before = len(self._poisoned)
            for rec in poison_records:
                self._poisoned.add(rec)
                self._wedges.pop(rec, None)
            self._pending = []
            return len(self._poisoned) - before

    def is_poisoned(self, record) -> bool:
        return record in self._poisoned

    def has_poison(self) -> bool:
        return bool(self._poisoned)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "suspect_records": len(self._wedges),
                "pending_bisection": len(self._pending),
                "poisoned_records": len(self._poisoned),
            }


def bisect_poison(records, probe, max_probes: int = 64) -> list:
    """Isolate the poison records of a wedging batch by bisection — the
    RLC bisection discipline applied to wedges instead of invalid
    masks.  ``probe(subset) -> bool`` launches the subset under the
    guard's deadline and says whether it COMPLETED (True) or wedged
    (False).  Returns the poison records.

    Rules: a completing subset is clean; a wedging singleton is poison;
    a wedging set whose both halves complete is an interaction the
    bisection cannot split — the whole set is returned (quarantined),
    never silently released.  ``max_probes`` bounds the device time one
    recovery spends probing: leftovers past the budget stay quarantined
    (host-verified), which is safe, just conservative."""
    budget = [int(max_probes)]

    def rec(rs):
        if not rs:
            return []
        if budget[0] <= 0:
            return list(rs)  # unprobed leftovers stay quarantined
        budget[0] -= 1
        if probe(list(rs)):
            return []
        if len(rs) == 1:
            return list(rs)
        mid = len(rs) // 2
        left = rec(rs[:mid])
        right = rec(rs[mid:])
        if not left and not right:
            return list(rs)  # both halves clean alone: interaction set
        return left + right

    return rec(list(records))


class GuardStats:
    """Counters behind the OP_STATS ``guard`` section."""

    def __init__(self):
        self._lock = threading.Lock()
        self.wedges = 0
        self.wedges_by_key: dict[str, int] = {}
        self.late_completions = 0
        self.reboots = 0
        self.canary_passes = 0
        self.canary_failures = 0
        self.host_fallback_records = 0
        self.busy_replies = 0
        self.poison_host_verified = 0
        self.last_reboot_wall_s = 0.0
        self.last_rewarm_wall_s = 0.0

    def note_wedge(self, key: str):
        with self._lock:
            self.wedges += 1
            self.wedges_by_key[key] = self.wedges_by_key.get(key, 0) + 1

    def note_late_completion(self, key: str):
        with self._lock:
            self.late_completions += 1

    def note_reboot(self, wall_s: float):
        with self._lock:
            self.reboots += 1
            self.last_reboot_wall_s = float(wall_s)

    def note_rewarm(self, wall_s: float):
        with self._lock:
            self.last_rewarm_wall_s = float(wall_s)

    def note_canary(self, ok: bool):
        with self._lock:
            if ok:
                self.canary_passes += 1
            else:
                self.canary_failures += 1

    def note_host_fallback(self, n: int):
        with self._lock:
            self.host_fallback_records += int(n)

    def note_busy(self):
        with self._lock:
            self.busy_replies += 1

    def note_poison_host(self, n: int):
        with self._lock:
            self.poison_host_verified += int(n)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "wedges": self.wedges,
                "wedges_by_key": dict(self.wedges_by_key),
                "late_completions": self.late_completions,
                "reboots": self.reboots,
                "canary_passes": self.canary_passes,
                "canary_failures": self.canary_failures,
                "host_fallback_records": self.host_fallback_records,
                "busy_replies": self.busy_replies,
                "poison_host_verified": self.poison_host_verified,
                "last_reboot_wall_s": round(self.last_reboot_wall_s, 3),
                "last_rewarm_wall_s": round(self.last_rewarm_wall_s, 3),
            }


class _GuardedCall:
    __slots__ = ("key", "deadline_s", "started_at", "done", "result",
                 "exc", "wedged")

    def __init__(self, key: str, deadline_s: float, started_at: float):
        self.key = key
        self.deadline_s = deadline_s
        self.started_at = started_at
        self.done = threading.Event()
        self.result = None
        self.exc = None
        self.wedged = False


class LaunchGuard:
    """The launch supervisor: every staged device call runs on a
    DISPOSABLE daemon thread while the caller waits; a monitor thread
    declares a deadline overrun WEDGED, wakes the caller (which raises
    :class:`WedgedLaunch` and executes the engine's degradation
    ladder), and the hung thread is abandoned — crash-only, never
    interrupted or reused.  Thread-per-launch costs ~100 us against a
    >=15 ms tunneled dispatch; what it buys is that one wedge can never
    poison a shared worker queue."""

    POLL_S = 0.02
    _ids = itertools.count()

    def __init__(self, deadlines: LaunchDeadlines | None = None,
                 stats: GuardStats | None = None, clock=monotonic,
                 max_reboots: int | None = None,
                 max_bisect_probes: int | None = None):
        self.deadlines = deadlines if deadlines is not None \
            else LaunchDeadlines()
        self.stats = stats if stats is not None else GuardStats()
        self.quarantine = Quarantine()
        self.max_reboots = int(max_reboots) if max_reboots is not None \
            else int(_env_float("HOTSTUFF_TPU_GUARD_MAX_REBOOTS", 3))
        self.max_bisect_probes = int(max_bisect_probes) \
            if max_bisect_probes is not None else int(_env_float(
                "HOTSTUFF_TPU_GUARD_MAX_BISECT_PROBES", 64))
        self._clock = clock
        self._lock = threading.Lock()
        self._calls: set = set()
        self._stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="guard-monitor")
        self._monitor.start()

    def close(self):
        self._stop.set()

    # -- supervision ---------------------------------------------------------

    def _monitor_loop(self):
        """Declares overruns: any in-flight guarded call past its
        deadline is marked wedged and its waiter woken NOW — the waiter
        abandons the launch thread and runs the ladder."""
        while not self._stop.wait(self.POLL_S):
            now = self._clock()
            with self._lock:
                live = list(self._calls)
            for call in live:
                if call.done.is_set():
                    continue
                if now - call.started_at > call.deadline_s:
                    call.wedged = True
                    call.done.set()

    def _run_call(self, call: _GuardedCall, thunk):
        try:
            call.result = thunk()
        except BaseException as e:  # noqa: BLE001 — re-raised by call()
            call.exc = e
        if call.wedged:
            # Late completion of an abandoned launch: the engine already
            # answered its batch from the ladder — the result is
            # DISCARDED here and must have no reachable side effects
            # (dispatch/fetch thunks return data; replies happen on the
            # engine thread, and the verdict cache takes its own lock).
            self.stats.note_late_completion(call.key)
            return
        call.done.set()

    def call(self, key: str, thunk):
        """Run ``thunk`` on a disposable launch thread under the shape's
        deadline; returns its result, re-raises its exception, or
        raises :class:`WedgedLaunch` when the monitor declared an
        overrun (the thread is abandoned — crash-only)."""
        call = _GuardedCall(key, self.deadlines.deadline_s(key),
                            self._clock())
        with self._lock:
            self._calls.add(call)
        # One-shot disposable body, not a service loop: it runs exactly
        # one thunk and exits — a stop flag could not interrupt a hung
        # device call anyway, and ABANDONING the thread on a wedge is
        # the crash-only design (daemon: it dies with the process).
        # graftlint: disable=daemon-thread-without-stop-flag
        t = threading.Thread(target=self._run_call, args=(call, thunk),
                             daemon=True,
                             name=f"guard-launch-{next(self._ids)}")
        t.start()
        # The monitor guarantees a wake-up at the deadline, so this wait
        # is bounded by construction (evidence: _monitor_loop sets
        # call.done on every overrun; the monitor thread is started in
        # __init__ and only close() stops it).
        # graftlint: disable=unsupervised-launch
        call.done.wait()
        with self._lock:
            self._calls.discard(call)
        if call.wedged:
            self.stats.note_wedge(key)
            raise WedgedLaunch(key, call.deadline_s)
        self.deadlines.observe(key, self._clock() - call.started_at)
        if call.exc is not None:
            raise call.exc
        return call.result

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> dict:
        out = self.stats.snapshot()
        out.update(self.quarantine.snapshot())
        out["deadlines"] = self.deadlines.snapshot()
        out["warm_boot"] = self.deadlines.warm_boot
        return out

from .client import SidecarClient  # noqa: F401
from .service import VerifyEngine, SidecarServer, serve  # noqa: F401

from .client import SidecarClient, SidecarOverloaded  # noqa: F401
from .service import VerifyEngine, SidecarServer, serve  # noqa: F401

"""graftfleet tenant-keyed queue lanes: the third scheduling key.

Under a shared sidecar fleet, one class queue no longer serves one node:
every replica of every tenant funnels into the same two class queues, so
a single greedy tenant could fill a class cap and starve everyone else's
requests — the classic noisy-neighbor failure shared accelerator
services hit first.  This module makes the tenant id (protocol v6
OP_HELLO; ``DEFAULT_TENANT`` for legacy connections) a real scheduling
key under each class:

Per-tenant FIFO lanes.
    Each tenant owns a private FIFO inside the class queue.  Arrival
    order is preserved WITHIN a tenant (the carry-over fairness token
    the two-class scheduler is built on), while cross-tenant order is
    policy, not arrival luck.

Deficit-round-robin drain.
    ``pop_next_locked`` serves the active-tenant ring in deficit
    round-robin order: each tenant may drain up to ``quantum_sigs``
    signature records per round before the ring rotates, so a tenant
    with a deep backlog interleaves with — never blockades — the others.
    With exactly one tenant queued (the pre-fleet topology, and every
    legacy test) the ring never rotates and the lane IS the old FIFO,
    byte-for-byte.

Per-tenant admission share.
    ``ClassQueue`` checks the offering tenant's lane occupancy against a
    per-tenant cap BEFORE the class cap, so a flooding tenant saturates
    its own share and sheds while other tenants keep admitting — the
    mechanism behind the ``tenant_starvation == 0`` invariant the strict
    parser mode asserts.

Every queue/coalesce operation in the scheduler routes through these
helpers; graftlint's ``tenant-unscoped-queue`` rule (analysis/
tenantlint.py) fails the gate on any raw deque access that would bypass
the tenant key.
"""

from __future__ import annotations

from collections import deque

# Re-exported wire-side default so scheduler code has one import site.
from ..protocol import DEFAULT_TENANT  # noqa: F401  (part of the API)

# DRR quantum: signature records one tenant may drain per ring round.
# One device sub-batch is the natural unit — a tenant can fill a launch
# it has the backlog for, but cannot hold the ring across launches.
DRR_QUANTUM_SIGS = 2048


class _Lane:
    """One tenant's private FIFO inside a class queue."""

    __slots__ = ("items", "sigs", "deficit")

    def __init__(self):
        self.items = deque()
        self.sigs = 0
        self.deficit = 0


class TenantLanes:
    """All per-tenant lanes of ONE class queue + the DRR drain ring.

    Not locked itself: every method is ``*_locked`` and runs under the
    owning scheduler's condition (the same discipline ClassQueue always
    had).  ``order`` holds the tenants with queued items, in ring order;
    ``order[0]`` is the tenant DRR currently serves.
    """

    __slots__ = ("lanes", "order", "sigs", "quantum_sigs")

    def __init__(self, quantum_sigs: int = DRR_QUANTUM_SIGS):
        self.lanes: dict[str, _Lane] = {}
        self.order: deque[str] = deque()
        self.sigs = 0
        self.quantum_sigs = max(1, quantum_sigs)

    # -- admission (via ClassQueue._offer_locked) ---------------------------

    def _offer_locked(self, pending) -> None:
        """Append to the offering tenant's lane (admission checks are the
        ClassQueue's job; this helper only keeps the lanes coherent)."""
        lane = self.lanes.get(pending.tenant)
        if lane is None:
            lane = self.lanes[pending.tenant] = _Lane()
        if not lane.items:
            self.order.append(pending.tenant)
        lane.items.append(pending)
        lane.sigs += len(pending)
        self.sigs += len(pending)

    # -- drain (engine thread, DRR order) -----------------------------------

    def head_locked(self):
        """The next Pending DRR will serve, or None when empty.  Grants
        the serving tenant its quantum lazily on first peek of a round."""
        if not self.order:
            return None
        lane = self.lanes[self.order[0]]
        if lane.deficit <= 0:
            lane.deficit = self.quantum_sigs
        return lane.items[0]

    def pop_next_locked(self):
        """Pop the DRR-selected head.  Rotates the ring once the serving
        tenant's deficit is spent (and other tenants are waiting), so a
        deep backlog interleaves instead of blockading."""
        head = self.head_locked()  # grants the quantum if fresh
        if head is None:
            raise IndexError("pop from empty tenant lanes")
        tenant = self.order[0]
        lane = self.lanes[tenant]
        p = lane.items.popleft()
        lane.sigs -= len(p)
        lane.deficit -= len(p)
        self.sigs -= len(p)
        if not lane.items:
            self.order.popleft()
            lane.deficit = 0
        elif lane.deficit <= 0 and len(self.order) > 1:
            self.order.rotate(-1)
            lane.deficit = 0
        return p

    # -- introspection ------------------------------------------------------

    def tenant_sigs_locked(self, tenant: str) -> int:
        lane = self.lanes.get(tenant)
        return lane.sigs if lane is not None else 0

    def any_over_cap_locked(self, tenant_cap_sigs: int,
                            exclude: str | None = None) -> bool:
        """True if any tenant other than ``exclude`` occupies more than
        the per-tenant cap — the condition a real starvation event
        requires and per-lane admission makes unreachable."""
        return any(lane.sigs > tenant_cap_sigs
                   for tenant, lane in self.lanes.items()
                   if tenant != exclude)

    def occupancy_locked(self) -> dict:
        """tenant -> queued signature records (telemetry snapshot)."""
        return {t: lane.sigs for t, lane in self.lanes.items()
                if lane.sigs}

    def __len__(self):
        return sum(len(lane.items) for lane in self.lanes.values())

    def __bool__(self):
        return bool(self.order)

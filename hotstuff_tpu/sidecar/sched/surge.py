"""graftsurge: pack-side admission control for the verify scheduler.

The queue caps (`classes.ClassQueue`) bound how much work the sidecar
will *hold*; this module decides how much it should *accept* while the
device pipeline is telling us the pack stage — not the device — is the
bottleneck.  It closes the second half of the control loop ROADMAP
item 4 named: the C++ client's queue-wait-p99 AIMD already shrinks the
per-replica async in-flight budget when the engine is congested
(crypto/sidecar_client.adapt_budget), and the pack-side admission here
derates BULK intake off the pipeline overlap stats, so the two compose —
the client sends less, and what still arrives is shed earlier when the
host cannot hide pack work behind device execution anyway.  Under the
cadence ring (graftcadence) the same derate reads ring occupancy
instead — the resident pipeline hides pack time by construction, so a
full ring, not a collapsed overlap, is the honest congestion signal
there.

Three policies, one controller:

Overlap-driven bulk derate.
    ``note_pack`` feeds the controller the same per-pack (duration,
    hidden) observations the OP_STATS ``pipeline`` section aggregates.
    While the recent overlap ratio is healthy (pack time hidden behind
    device execution), bulk admission runs at the full queue cap.  When
    overlap collapses — pack runs in the open, i.e. the host pack stage
    is the bottleneck — admitting more bulk only grows a queue the pack
    worker cannot drain, so the effective bulk cap scales down linearly
    to ``DERATE_FLOOR``.  Engagement/disengagement transitions are
    counted (``derate.engagements``) the way the native ingress gate
    counts watermark crossings.

Bulk-before-latency shedding.
    A latency-class shed (queue full) opens a pressure window during
    which every bulk offer is shed outright: under overload the
    consensus class must be the LAST to lose capacity.  The
    ``fairness_violations`` counter records any bulk admission that
    slips through while latency is under pressure — the scheduler's
    lock makes that impossible by construction, so a non-zero value is
    a policy regression the LogParser's strict mode fails the run on.

Retry-after hints.
    ``retry_after_ms`` turns queue depth and the recent drain rate into
    the hint a BUSY reply carries (protocol v4): roughly the time the
    backlog needs to drain, clamped so a client neither hammers a
    saturated sidecar every millisecond nor parks for a minute on a
    blip.

Writers: connection threads (offers) and the engine/pack threads
(note_pack / note_launch).  One controller-private lock guards all
mutable state; callers may hold the scheduler's admission lock when
calling in — the order is always scheduler-lock -> controller-lock and
nothing here calls back out, so the nesting cannot invert.
"""

from __future__ import annotations

import threading
from collections import deque
from time import monotonic

from .classes import BULK, LATENCY

# Overlap ratio above which pack work is considered hidden (healthy
# pipeline -> full bulk cap); below it the effective cap scales linearly
# down to DERATE_FLOOR at overlap 0.  0.5 matches the depth-2 pipeline's
# break-even point: below half overlap the engine spends more wall clock
# packing in the open than dispatching.
OVERLAP_KNEE = 0.5
DERATE_FLOOR = 0.25
# Judged over the most recent packs only — a surge decision off minutes-
# old telemetry would derate long after the burst passed.  The window is
# bounded BOTH ways: at most PACK_WINDOW entries, and nothing older than
# PACK_WINDOW_S seconds.  The count bound alone is not enough — on a
# long-running sidecar a quiet hour keeps 64 stale healthy packs alive,
# and exactly when a surge arrives the derate answers off history
# instead of the collapsing overlap in front of it.
PACK_WINDOW = 64
PACK_WINDOW_S = 10.0
# Minimum evidence before derating: a cold engine must not shed bulk off
# one unlucky pack.
MIN_PACKS = 8
MIN_PACK_S = 0.005

# graftcadence: when the ring is running, the freshest congestion signal
# is ring occupancy, not pack overlap (the resident pipeline hides pack
# time by construction — overlap saturates near 1.0 and stops carrying
# information).  Occupancy samples arrive once per tick; evidence older
# than RING_OCC_WINDOW_S means the ring stopped (wedge fallback or
# shutdown) and the controller falls back to the overlap rule.  Above
# RING_OCC_KNEE mean occupancy the bulk cap scales linearly down to
# DERATE_FLOOR at a permanently-full ring: every slot occupied every
# tick means the device cannot drain what is already admitted.
RING_OCC_WINDOW = 256
RING_OCC_WINDOW_S = 2.0
RING_OCC_KNEE = 0.75

# A latency-class shed opens this pressure window (s): while it is open,
# bulk is shed before latency ever is.
LATENCY_PRESSURE_S = 1.0

# Launches contributing to the drain-rate estimate behind retry-after.
LAUNCH_WINDOW = 64
RETRY_MIN_MS = 25
RETRY_MAX_MS = 2000
# Fallbacks when no drain rate is known yet (cold queue): the latency
# class retries fast (its backlog is bounded by design), bulk waits a
# coalesced-launch's worth.
RETRY_DEFAULT_MS = {LATENCY: 50, BULK: 250}


class AdmissionController:
    """Overlap-driven admission state + the OP_STATS ``surge`` section."""

    def __init__(self, clock=monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._packs = deque(maxlen=PACK_WINDOW)     # (t, dur_s, hidden)
        self._launches = deque(maxlen=LAUNCH_WINDOW)  # (t, sigs)
        self._ring_occ = deque(maxlen=RING_OCC_WINDOW)  # (t, occ_frac)
        self._lat_pressure_until = 0.0
        self._derate_engaged = False
        self.admitted = {LATENCY: 0, BULK: 0}
        self.shed = {LATENCY: 0, BULK: 0}
        self.busy_replies = {LATENCY: 0, BULK: 0}
        self.bulk_before_latency_sheds = 0
        self.fairness_violations = 0
        # graftfleet: a latency refusal at the CLASS cap while another
        # tenant sits above its own per-tenant share — i.e. a flooding
        # neighbor displaced this tenant's consensus work.  Per-lane
        # admission (ClassQueue._offer_locked checks the tenant share
        # first) makes this unreachable by construction; like
        # fairness_violations, non-zero is a policy regression the
        # LogParser's strict mode fails the run on.
        self.tenant_starvation = 0
        self.derate_engagements = 0

    # -- pipeline evidence (engine / pack threads) --------------------------

    def note_pack(self, duration_s: float, hidden: bool,
                  now: float | None = None):
        now = self._clock() if now is None else now
        with self._lock:
            self._packs.append((now, duration_s, bool(hidden)))
            self._update_engagement_locked()

    def note_launch(self, sigs: int, now: float | None = None):
        now = self._clock() if now is None else now
        with self._lock:
            self._launches.append((now, sigs))

    def note_ring_occupancy(self, occupied: int, depth: int,
                            now: float | None = None):
        """graftcadence: one per-tick ring occupancy sample (occupied
        slots out of the current depth k).  While these stay fresh the
        derate reads occupancy instead of pack overlap."""
        now = self._clock() if now is None else now
        with self._lock:
            frac = occupied / depth if depth > 0 else 0.0
            self._ring_occ.append((now, min(1.0, max(0.0, frac))))
            self._update_engagement_locked()

    def recent_overlap(self) -> float | None:
        """Hidden share of recent pack time, or None without evidence."""
        with self._lock:
            return self._recent_overlap_locked()

    def _recent_overlap_locked(self, now: float | None = None):
        now = self._clock() if now is None else now
        while self._packs and now - self._packs[0][0] > PACK_WINDOW_S:
            self._packs.popleft()
        if len(self._packs) < MIN_PACKS:
            return None
        total = sum(d for _, d, _ in self._packs)
        if total < MIN_PACK_S:
            return None
        return sum(d for _, d, h in self._packs if h) / total

    def _ring_occupancy_locked(self, now: float | None = None):
        """Mean recent ring occupancy fraction, or None when the ring
        evidence is stale (ring disengaged) or absent."""
        now = self._clock() if now is None else now
        while self._ring_occ and now - self._ring_occ[0][0] > \
                RING_OCC_WINDOW_S:
            self._ring_occ.popleft()
        if not self._ring_occ:
            return None
        return sum(f for _, f in self._ring_occ) / len(self._ring_occ)

    def _derate_factor_locked(self) -> float:
        occ = self._ring_occupancy_locked()
        if occ is not None:
            # Ring evidence wins while fresh: occupancy below the knee
            # means the resident pipeline has headroom — full bulk cap.
            if occ <= RING_OCC_KNEE:
                return 1.0
            span = (occ - RING_OCC_KNEE) / (1.0 - RING_OCC_KNEE)
            return max(DERATE_FLOOR, 1.0 - (1.0 - DERATE_FLOOR) * span)
        o = self._recent_overlap_locked()
        if o is None or o >= OVERLAP_KNEE:
            return 1.0
        return DERATE_FLOOR + (1.0 - DERATE_FLOOR) * (o / OVERLAP_KNEE)

    def _update_engagement_locked(self):
        engaged = self._derate_factor_locked() < 1.0
        if engaged and not self._derate_engaged:
            self.derate_engagements += 1
        self._derate_engaged = engaged

    def bulk_derate(self) -> float:
        """Multiplier on the bulk queue cap, in [DERATE_FLOOR, 1.0]."""
        with self._lock:
            return self._derate_factor_locked()

    # -- admission outcomes (connection threads, under the scheduler lock) --

    def note_latency_shed(self, now: float | None = None):
        now = self._clock() if now is None else now
        with self._lock:
            self._lat_pressure_until = now + LATENCY_PRESSURE_S

    def latency_pressure(self, now: float | None = None) -> bool:
        now = self._clock() if now is None else now
        with self._lock:
            return now < self._lat_pressure_until

    def note_admitted(self, cls: str, now: float | None = None):
        now = self._clock() if now is None else now
        with self._lock:
            self.admitted[cls] = self.admitted.get(cls, 0) + 1
            if cls == BULK and now < self._lat_pressure_until:
                # Bulk slipped in while latency was shedding: the
                # bulk-before-latency policy failed.  The scheduler's
                # admission lock makes this unreachable; the counter is
                # the proof the LogParser's strict fairness check reads.
                self.fairness_violations += 1

    def note_tenant_starvation(self):
        """graftfleet: see ``tenant_starvation`` above (should never
        fire; the scheduler audits every latency class-cap refusal)."""
        with self._lock:
            self.tenant_starvation += 1

    def note_shed(self, cls: str, before_latency: bool = False,
                  busy_reply: bool = True):
        with self._lock:
            self.shed[cls] = self.shed.get(cls, 0) + 1
            if busy_reply:
                self.busy_replies[cls] = self.busy_replies.get(cls, 0) + 1
            if before_latency:
                self.bulk_before_latency_sheds += 1

    # -- retry-after --------------------------------------------------------

    def drain_rate_sigs_per_s(self, now: float | None = None):
        """Recent launch throughput, or None without enough launches."""
        now = self._clock() if now is None else now
        with self._lock:
            if len(self._launches) < 2:
                return None
            t0 = self._launches[0][0]
            span = max(now - t0, 1e-6)
            total = sum(s for _, s in self._launches)
            return total / span

    def retry_after_ms(self, cls: str, queued_sigs: int = 0) -> int:
        rate = self.drain_rate_sigs_per_s()
        if rate is None or rate <= 0 or queued_sigs <= 0:
            ms = RETRY_DEFAULT_MS.get(cls, RETRY_DEFAULT_MS[BULK])
        else:
            ms = queued_sigs / rate * 1e3
        return int(max(RETRY_MIN_MS, min(RETRY_MAX_MS, ms)))

    # -- reporting ----------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-safe ``surge`` section of the OP_STATS reply."""
        with self._lock:
            overlap = self._recent_overlap_locked()
            ring_occ = self._ring_occupancy_locked()
            return {
                "admitted": dict(self.admitted),
                "shed": dict(self.shed),
                "busy_replies": dict(self.busy_replies),
                "bulk_before_latency_sheds": self.bulk_before_latency_sheds,
                "fairness_violations": self.fairness_violations,
                "tenant_starvation": self.tenant_starvation,
                "derate": {
                    "factor": round(self._derate_factor_locked(), 3),
                    "engaged": self._derate_engaged,
                    "engagements": self.derate_engagements,
                    "overlap_recent": round(overlap, 3)
                    if overlap is not None else None,
                    "ring_occupancy_recent": round(ring_occ, 3)
                    if ring_occ is not None else None,
                },
            }

"""Request-class taxonomy for the verify scheduler.

Two classes exist on the wire and in the queues:

``LATENCY``
    QC/TC verifies from the consensus core (``OP_VERIFY_BATCH`` and every
    BLS verify/sign opcode).  HotStuff's responsiveness argument makes
    this the number that bounds commit latency: a replica cannot vote,
    and a leader cannot assemble the next block, until the previous
    certificate's signatures check out.  A latency request therefore
    never waits behind more than the launch already in flight.

``BULK``
    Mempool / offchain batch verifies (``OP_VERIFY_BULK``).  Throughput
    matters, per-request latency does not; bulk batches coalesce up to
    the bulk launch cap and yield to any pending latency work.

The mapping opcode -> class lives here (``class_of_opcode``) so the
connection handler, the scheduler, and the tests agree on one source of
truth.  Classes ride the wire as distinct opcodes rather than a header
flag: existing ``OP_VERIFY_BATCH`` clients keep their (correct)
latency-class behavior without a flag day, and the graftlint wire
cross-checker pins the opcode pair on both sides of the boundary.
"""

from __future__ import annotations

import threading
from time import monotonic

# Class identifiers (also the keys of every per-class stats dict).
LATENCY = "latency"
BULK = "bulk"

CLASSES = (LATENCY, BULK)


def class_of_opcode(opcode: int) -> str:
    """Wire opcode -> scheduling class (one source of truth)."""
    from .. import protocol as proto

    return BULK if opcode == proto.OP_VERIFY_BULK else LATENCY


class Pending:
    """One admitted request: the decoded dataclass, its reply callback,
    its class, its tenant (graftfleet: the third scheduling key; the
    connection's HELLO identity or the default), and the admission
    timestamp (queue-wait telemetry)."""

    __slots__ = ("request", "reply_fn", "cls", "enqueued_at", "is_bls",
                 "tenant")

    def __init__(self, request, reply_fn, cls: str = LATENCY,
                 is_bls: bool = False, tenant: str | None = None):
        from .tenantq import DEFAULT_TENANT

        self.request = request
        self.reply_fn = reply_fn
        self.cls = cls
        self.is_bls = is_bls
        self.tenant = DEFAULT_TENANT if tenant is None else tenant
        self.enqueued_at = monotonic()

    def __len__(self):
        """Signature-record count (BLS requests schedule as one unit)."""
        if self.is_bls:
            return 1
        return len(self.request.msgs)


class Launch:
    """One assembled device launch: ordered items plus bookkeeping the
    engine thread needs to fan replies back out.

    ``kind`` is ``"verify"`` (a coalesced Ed25519 batch — possibly a
    latency batch padded out with bulk fill) or ``"bls"`` (a single BLS
    request, executed alone).  ``fill_count`` counts the trailing items
    that rode along as pad fill (telemetry only — replies are uniform).
    """

    __slots__ = ("kind", "items", "cls", "fill_count", "assembled_at")

    def __init__(self, kind: str, items: list, cls: str,
                 fill_count: int = 0):
        self.kind = kind
        self.items = items
        self.cls = cls
        self.fill_count = fill_count
        self.assembled_at = monotonic()

    @property
    def total_sigs(self) -> int:
        return sum(len(p) for p in self.items)


class ClassQueue:
    """Bounded queue for one class, counted in signature records, with
    per-tenant lanes (graftfleet) drained in deficit round-robin order.

    ``offer`` is called from connection threads and never blocks: a full
    queue returns False and the caller replies queue-full immediately —
    the bounded-backpressure contract that keeps a flooded sidecar from
    wedging every connection thread behind one blocking ``put``.  The
    engine thread is the only consumer.  A lock (shared with the
    scheduler, which needs cross-queue atomicity when assembling) guards
    the lanes + the signature count.

    Two caps govern admission: the CLASS cap (total records queued, as
    before) and the per-TENANT cap — one tenant's lane may hold at most
    ``tenant_cap_sigs`` records, so a flooding tenant saturates its own
    share and sheds while every other tenant keeps admitting.  A single
    tenant (the pre-fleet topology) therefore sees exactly the old
    behavior when its cap equals the class cap.  ``last_refusal``
    records why the most recent ``_offer_locked`` said no
    (``"tenant-cap"`` vs ``"class-cap"``), valid until the lock is
    released — the scheduler reads it to attribute sheds for the
    tenant-starvation invariant.
    """

    __slots__ = ("lanes", "cap_sigs", "tenant_cap_sigs", "last_refusal",
                 "_lock")

    def __init__(self, cap_sigs: int, lock: threading.Condition,
                 tenant_cap_sigs: int | None = None,
                 quantum_sigs: int | None = None):
        from .tenantq import DRR_QUANTUM_SIGS, TenantLanes

        self.lanes = TenantLanes(
            DRR_QUANTUM_SIGS if quantum_sigs is None else quantum_sigs)
        self.cap_sigs = cap_sigs
        self.tenant_cap_sigs = cap_sigs if tenant_cap_sigs is None \
            else min(tenant_cap_sigs, cap_sigs)
        self.last_refusal = None
        self._lock = lock

    @property
    def sigs(self) -> int:
        """Total queued signature records (the lanes own the count)."""
        return self.lanes.sigs

    def offer(self, pending: Pending) -> bool:
        with self._lock:
            return self._offer_locked(pending)

    def _offer_locked(self, pending: Pending, cap_sigs: int | None = None)\
            -> bool:
        # A request is admitted whole or not at all; a single request
        # bigger than the whole cap is still admitted when the queue
        # (respectively its own lane) is empty — it slices inside the
        # engine — so a legal client can never be starved by its own
        # size.  ``cap_sigs`` lets the scheduler admit against a DERATED
        # cap (graftsurge) without the queue itself knowing about
        # admission policy.  The TENANT share is checked first: a
        # flooding tenant must shed on its own cap while the class still
        # has room for everyone else.
        self.last_refusal = None
        cap = self.cap_sigs if cap_sigs is None else cap_sigs
        lane_sigs = self.lanes.tenant_sigs_locked(pending.tenant)
        # The tenant share engages only once a SECOND tenant has been
        # seen: with one tenant (the pre-fleet topology) the class cap
        # is the whole policy and behavior is byte-identical to v5.
        multi_tenant = len(self.lanes.lanes) >= 2 or (
            self.lanes.lanes and pending.tenant not in self.lanes.lanes)
        tenant_cap = min(self.tenant_cap_sigs, cap)
        if multi_tenant and lane_sigs and \
                lane_sigs + len(pending) > tenant_cap:
            self.last_refusal = "tenant-cap"
            return False
        if self.lanes.sigs and self.lanes.sigs + len(pending) > cap:
            self.last_refusal = "class-cap"
            return False
        self.lanes._offer_locked(pending)
        self._lock.notify()
        return True

    def _head_locked(self) -> Pending | None:
        """The DRR-selected next item (None when empty) — the only legal
        way to inspect drain order; raw lane access bypasses the tenant
        key (graftlint: tenant-unscoped-queue)."""
        return self.lanes.head_locked()

    def _pop_locked(self) -> Pending:
        return self.lanes.pop_next_locked()

    def __bool__(self):
        return bool(self.lanes)

    def __len__(self):
        return len(self.lanes)

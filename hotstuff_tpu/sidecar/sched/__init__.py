"""verifysched: the deadline-aware two-class batching scheduler that owns
all launch-shape policy for the verify sidecar's device engine.

Modules:
  classes.py    request classes (latency / bulk), Pending/Launch/queue types
  shapes.py     warmed-shape registry + verify-path routing (per-sig vs RLC)
  scheduler.py  admission, strict-priority coalescing, pad-fill, carry-over
  stats.py      per-launch telemetry behind the OP_STATS wire request
  surge.py      graftsurge pack-side admission: overlap-driven bulk
                derate, bulk-before-latency shedding, retry-after hints

``sidecar/service.VerifyEngine`` consumes launches; policy lives here.
See scheduler.py for the policy rationale and sidecar/README notes.
"""

from .classes import BULK, CLASSES, LATENCY, Launch, Pending, \
    class_of_opcode  # noqa: F401
from .scheduler import BULK_QUEUE_CAP_SIGS, LATENCY_QUEUE_CAP_SIGS, \
    Scheduler, size_queue_caps  # noqa: F401
from .shapes import MESH_SCAN_CHUNKS, PATH_HOST, PATH_LADDER_SHARDED, \
    PATH_MESH, PATH_PER_SIG, PATH_RLC, PATH_RLC_SHARDED, \
    PATH_SCAN_SHARDED, RLC_MIN_LAUNCH, ShapeRegistry, \
    quorum_sigs  # noqa: F401
from .stats import SchedStats  # noqa: F401
from .surge import AdmissionController  # noqa: F401

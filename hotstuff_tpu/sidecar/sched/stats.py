"""Per-launch telemetry for the verify scheduler.

Counters answer the questions the drain-loop engine could not: how big
are launches actually (coalesce histogram), how much padded capacity is
wasted (pad_waste vs bulk fill), which verify path ran (per_sig / rlc /
rlc_bisect / host / rlc_sharded / ladder_sharded), how long requests sat
queued per class (p50/p99), how often backpressure fired, how mesh
launches distribute over per-shard buckets, how many bulk backlogs
drained as ONE whole-backlog chunked scan instead of per-launch_cap
slices (the ``scan`` section), and how much of the host pack work the
double-buffered dispatch pipeline actually hid behind device execution
(the ``pipeline`` overlap ratio).

Exposed over the wire as the ``OP_STATS`` reply (one JSON object — the
snapshot() dict verbatim), which the harness fetches at teardown into
the LogParser summary and bench.py folds into the headline line.

Writers: the engine thread (launch/path/wait counters) and connection
threads (queue_full rejections, admissions).  One lock guards it all —
every operation is a few integer bumps, invisible next to a device
launch.
"""

from __future__ import annotations

import threading
from time import monotonic

# Rolling window for the ``pipeline`` overlap section: bounded both by
# entry count and by age.  Lifetime totals once lived here — on a
# long-running sidecar they dampened the overlap ratio exactly when a
# surge arrived (hours of healthy history outvoting the collapse in
# front of it), which also starved the surge controller's derate.  The
# window matches the admission controller's recency discipline
# (surge.PACK_WINDOW_S); lifetime totals stay visible under
# ``lifetime_*`` keys for trend tooling.
PIPE_WINDOW = 512
PIPE_WINDOW_S = 30.0


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile of an ascending list (empty -> 0)."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class SchedStats:
    # Bounded queue-wait reservoirs per class: enough resolution for a
    # p99 over a bench window, bounded so a week-long sidecar cannot
    # grow without limit (newest samples win — the interesting tail).
    WAIT_SAMPLES_CAP = 4096

    # graftfleet: distinct tenants tracked in the per-tenant section.
    # A fleet serves committees, not the open internet — 64 is an order
    # of magnitude past any plausible local deployment, and the bound
    # keeps a tenant-id fuzzer from growing the stats dict without
    # limit (overflow tenants fold into "~other").
    TENANT_STATS_CAP = 64
    TENANT_WAIT_SAMPLES_CAP = 1024
    OVERFLOW_TENANT = "~other"

    def __init__(self, clock=monotonic):
        from collections import deque

        self._lock = threading.Lock()
        self._clock = clock
        self.launches = 0
        self.launches_by_class: dict[str, int] = {}
        # coalesce-size histogram: padded-bucket capacity -> launches
        self.coalesce_hist: dict[int, int] = {}
        self.sigs_launched = 0
        self.pad_waste_sigs = 0          # padded slots left empty
        self.bulk_fill_sigs = 0          # padded slots used by bulk fill
        self.paths: dict[str, int] = {}  # per_sig / rlc / rlc_bisect / ...
        self.admitted: dict[str, int] = {}
        self.queue_full: dict[str, int] = {}
        self.carries: dict[str, int] = {}
        # Mesh routing: launches that went to the device mesh, and the
        # per-SHARD padded bucket each landed on (the warmed-shape
        # discipline made visible: every key here must be a bucket the
        # warmup marked, or a cold compile happened mid-traffic).
        self.mesh_launches = 0
        self.shard_bucket_hist: dict[int, int] = {}
        # graftingress bulk-lane class mix: OP_VERIFY_BULK requests are
        # fed by the mempool admission-verify stage (request ctx ==
        # the pinned ingress tag) or by offchain batches; the split is
        # what makes "bulk-lane utilization under signed ingress" a
        # number instead of a guess.
        self.ingress_bulk_requests = 0
        self.ingress_bulk_sigs = 0
        self.offchain_bulk_requests = 0
        self.offchain_bulk_sigs = 0
        # graftscale whole-backlog scans: backlogs drained as ONE
        # chunked mesh program instead of per-launch_cap ladder slices.
        # chunk_hist keys are the scan chunk counts g — like the shard
        # buckets, every key must be a g the warmup marked
        # (ShapeRegistry.mesh_chunks) or a cold compile happened.
        self.scan_launches = 0
        self.scan_sigs = 0
        self.scan_chunk_hist: dict[int, int] = {}
        self.scan_slices_avoided = 0
        # Double-buffered dispatch pipeline: host pack time and the
        # share of it that ran while a launch was already executing on
        # the device (hidden == free; the overlap ratio is the pipeline
        # doing its job).  The reported section is computed over the
        # bounded rolling window; the lifetime accumulators survive for
        # trend tooling only.
        self.pack_s = 0.0
        self.pack_hidden_s = 0.0
        self._pack_window = deque(maxlen=PIPE_WINDOW)  # (t, dur, hidden)
        self._waits = {c: deque(maxlen=self.WAIT_SAMPLES_CAP)
                       for c in ("latency", "bulk")}
        # graftfleet per-tenant section: admissions/sheds per class and
        # a bounded queue-wait reservoir per (tenant, class) — the
        # numbers the fairness invariant is judged on (a victim tenant's
        # latency p99 under a neighboring flood).  Bounded by
        # TENANT_STATS_CAP distinct tenants; see _tenant_locked.
        self._tenants: dict[str, dict] = {}
        # graftsurge: the admission controller (sched/surge.py), attached
        # by the Scheduler.  note_pack/note_launch forward the engine's
        # observations into it (outside this object's lock — the nesting
        # is always stats-caller -> surge lock, never back), and
        # snapshot() folds its counters in as the ``surge`` section.
        self.surge = None

    # -- recording ----------------------------------------------------------

    def _tenant_locked(self, tenant: str) -> dict:
        """The per-tenant record, creating it under the cap (overflow
        tenants share one "~other" bucket so the dict stays bounded)."""
        from collections import deque

        rec = self._tenants.get(tenant)
        if rec is None:
            if len(self._tenants) >= self.TENANT_STATS_CAP:
                tenant = self.OVERFLOW_TENANT
                rec = self._tenants.get(tenant)
            if rec is None:
                rec = self._tenants[tenant] = {
                    "admitted": {},
                    "shed": {},
                    "waits": {c: deque(
                        maxlen=self.TENANT_WAIT_SAMPLES_CAP)
                        for c in ("latency", "bulk")},
                }
        return rec

    def note_tenant_admitted(self, tenant: str, cls: str):
        with self._lock:
            adm = self._tenant_locked(tenant)["admitted"]
            adm[cls] = adm.get(cls, 0) + 1

    def note_tenant_shed(self, tenant: str, cls: str):
        with self._lock:
            shed = self._tenant_locked(tenant)["shed"]
            shed[cls] = shed.get(cls, 0) + 1

    def note_admitted(self, cls: str):
        with self._lock:
            self.admitted[cls] = self.admitted.get(cls, 0) + 1

    def note_queue_full(self, cls: str):
        with self._lock:
            self.queue_full[cls] = self.queue_full.get(cls, 0) + 1

    def note_carry(self, cls: str):
        with self._lock:
            self.carries[cls] = self.carries.get(cls, 0) + 1

    def note_launch(self, launch, capacity: int, now: float):
        """One assembled launch: size/pad/fill accounting + queue waits.
        ``capacity`` is the padded device shape the batch rides in."""
        if self.surge is not None:
            self.surge.note_launch(launch.total_sigs, now)
        with self._lock:
            self.launches += 1
            self.launches_by_class[launch.cls] = \
                self.launches_by_class.get(launch.cls, 0) + 1
            total = launch.total_sigs
            self.sigs_launched += total
            self.coalesce_hist[capacity] = \
                self.coalesce_hist.get(capacity, 0) + 1
            self.pad_waste_sigs += max(0, capacity - total)
            fill = launch.items[len(launch.items) - launch.fill_count:]
            self.bulk_fill_sigs += sum(len(p) for p in fill)
            for p in launch.items:
                waits = self._waits.get(p.cls)
                if waits is not None:
                    waits.append(now - p.enqueued_at)
                tw = self._tenant_locked(
                    getattr(p, "tenant", None) or "default")["waits"]
                if p.cls in tw:
                    tw[p.cls].append(now - p.enqueued_at)

    def note_bulk_source(self, ingress: bool, sigs: int):
        """One offered bulk-lane request, split by feed: ingress-fed
        (mempool admission verify, pinned ctx tag) vs offchain-fed.
        Counted at submit time — offered load, not admitted load — so
        the mix stays honest under backpressure."""
        with self._lock:
            if ingress:
                self.ingress_bulk_requests += 1
                self.ingress_bulk_sigs += sigs
            else:
                self.offchain_bulk_requests += 1
                self.offchain_bulk_sigs += sigs

    def note_path(self, path: str):
        with self._lock:
            self.paths[path] = self.paths.get(path, 0) + 1

    def note_mesh_launch(self, buckets):
        """One scheduler launch dispatched onto the mesh: counted ONCE,
        with every per-slice shard bucket recorded in the histogram.
        ``buckets`` is the list of per-shard padded buckets the launch's
        ladder slices landed on (one entry for an unsliced launch; None
        entries — a registry without a mesh size — are counted but not
        bucketed).  The old shape called this per SLICE, so a sliced
        backlog inflated ``sharded_launches`` past the scheduler's own
        launch count and the two could never be compared."""
        with self._lock:
            self.mesh_launches += 1
            for b in buckets:
                if b is not None:
                    self.shard_bucket_hist[b] = \
                        self.shard_bucket_hist.get(b, 0) + 1

    def note_scan_launch(self, g: int, sigs: int, slices_avoided: int):
        """One whole-backlog chunked mesh scan launch: g chunks drained
        ``sigs`` signatures in ONE dispatch; ``slices_avoided`` is how
        many extra per-launch_cap ladder dispatches the pre-graftscale
        path would have paid for the same backlog."""
        with self._lock:
            self.scan_launches += 1
            self.scan_sigs += sigs
            self.scan_chunk_hist[g] = self.scan_chunk_hist.get(g, 0) + 1
            self.scan_slices_avoided += max(0, slices_avoided)

    def note_pack(self, duration_s: float, hidden: bool,
                  now: float | None = None):
        """One host-side pack stage: ``hidden`` says a launch was
        executing on the device when the pack began, i.e. the pipeline
        overlapped this pack with device compute (the approximation is
        conservative per-launch and exact in the steady state, where
        pack N+1 runs entirely under launch N)."""
        now = self._clock() if now is None else now
        if self.surge is not None:
            self.surge.note_pack(duration_s, hidden, now=now)
        with self._lock:
            self.pack_s += duration_s
            if hidden:
                self.pack_hidden_s += duration_s
            self._pack_window.append((now, duration_s, bool(hidden)))

    # -- reporting ----------------------------------------------------------

    def _pipeline_locked(self) -> dict:
        """The ``pipeline`` section over the bounded rolling window —
        the same keys the LogParser and the surge derate have always
        read, now answering for RECENT pack-boundedness; lifetime
        accumulators ride along under ``lifetime_*``."""
        now = self._clock()
        while self._pack_window and \
                now - self._pack_window[0][0] > PIPE_WINDOW_S:
            self._pack_window.popleft()
        win = sum(d for _, d, _ in self._pack_window)
        win_hidden = sum(d for _, d, h in self._pack_window if h)
        return {
            "pack_ms": round(win * 1e3, 3),
            "pack_hidden_ms": round(win_hidden * 1e3, 3),
            "overlap_ratio": round(win_hidden / win, 3) if win else 0.0,
            "window_s": PIPE_WINDOW_S,
            "lifetime_pack_ms": round(self.pack_s * 1e3, 3),
            "lifetime_overlap_ratio": round(
                self.pack_hidden_s / self.pack_s, 3)
            if self.pack_s else 0.0,
        }

    def snapshot(self) -> dict:
        """JSON-safe dict: the OP_STATS reply body, byte-for-byte."""
        surge = self.surge.snapshot() if self.surge is not None else None
        with self._lock:
            waits = {}
            for cls, samples in self._waits.items():
                vals = sorted(samples)
                waits[cls] = {
                    "n": len(vals),
                    "p50_ms": round(_percentile(vals, 0.50) * 1e3, 3),
                    "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3),
                }
            out = {
                "launches": self.launches,
                "launches_by_class": dict(self.launches_by_class),
                "coalesce_hist": {str(k): v for k, v in
                                  sorted(self.coalesce_hist.items())},
                "sigs_launched": self.sigs_launched,
                "pad_waste_sigs": self.pad_waste_sigs,
                "bulk_fill_sigs": self.bulk_fill_sigs,
                "paths": dict(self.paths),
                "admitted": dict(self.admitted),
                "queue_full": dict(self.queue_full),
                "carries": dict(self.carries),
                "queue_wait": waits,
                "mesh": {
                    "sharded_launches": self.mesh_launches,
                    "shard_buckets": {
                        str(k): v for k, v in
                        sorted(self.shard_bucket_hist.items())},
                },
                "scan": {
                    "launches": self.scan_launches,
                    "sigs": self.scan_sigs,
                    "chunk_hist": {
                        str(k): v for k, v in
                        sorted(self.scan_chunk_hist.items())},
                    "slices_avoided": self.scan_slices_avoided,
                },
                "pipeline": self._pipeline_locked(),
                "tenants": {
                    tenant: {
                        "admitted": dict(rec["admitted"]),
                        "shed": dict(rec["shed"]),
                        "queue_wait": {
                            cls: {
                                "n": len(v),
                                "p50_ms": round(
                                    _percentile(v, 0.50) * 1e3, 3),
                                "p99_ms": round(
                                    _percentile(v, 0.99) * 1e3, 3),
                            }
                            for cls, samples in rec["waits"].items()
                            if (v := sorted(samples))
                        },
                    }
                    for tenant, rec in sorted(self._tenants.items())
                },
                "ingress": {
                    "bulk_requests": self.ingress_bulk_requests,
                    "bulk_sigs": self.ingress_bulk_sigs,
                    "offchain_requests": self.offchain_bulk_requests,
                    "offchain_sigs": self.offchain_bulk_sigs,
                },
            }
            if surge is not None:
                out["surge"] = surge
            return out

"""Warmed-shape registry: which compiled launch shapes exist, and which
verify path a batch of size n should take.

The engine may only launch shapes whose XLA programs were compiled
before the socket bound (sidecar/service._warmup*): a first-time compile
on the engine thread is a silent 30-60 s stall mid-traffic.  This
registry is the single record of what was warmed:

  * ``buckets``   — padded power-of-two batch shapes (8 .. MAX_SUBBATCH)
                    for the per-signature ladder program;
  * ``chunks``    — chunked-scan lengths g (2 .. 16) for bulk backlogs
                    (g * MAX_SUBBATCH signatures in ONE dispatch);
  * ``rlc_buckets`` — padded shapes of the one-MSM RLC program
                    (ops/ed25519.verify_rlc_packed), compiled by
                    ``--warm-rlc``.

``route`` turns (batch size, warmed state) into the launch path — the
policy that finally wires crypto/eddsa.verify_batch_rlc into the
engine's coalesced launch path (the top ROADMAP item): batches of
``RLC_MIN_LAUNCH`` or more signatures whose bucket is RLC-warmed pay one
Straus MSM instead of 2n scalar ladders, and the bisection fallback
inside the RLC path keeps the verdict mask bit-identical to the
per-signature program whenever the combined check fails.

Bucketing arithmetic is delegated to ``crypto/eddsa`` (``next_pow2`` /
``_bucket``) — THE padding rule the graftlint padshape checker pins —
so the registry can never disagree with the dispatch layer about which
shape a size lands on.
"""

from __future__ import annotations

from ...crypto.eddsa import MAX_SUBBATCH, _bucket, next_pow2

# Engine-path RLC floor: below this the combined check's fixed
# Horner/comb tail outweighs the saved ladders (crypto/eddsa.RLC_MIN_MSM
# is the *bisection* floor, a different constant: bisection wants to go
# as low as profitable, the engine wants to start where the MSM wins).
RLC_MIN_LAUNCH = 16

# Verify paths route() can answer (also the stats path-counter keys).
PATH_PER_SIG = "per_sig"
PATH_RLC = "rlc"
PATH_HOST = "host"
PATH_MESH = "mesh"


class ShapeRegistry:
    """Tracks warmed shapes; owned by the engine, read by the scheduler.

    Mutations happen on the warmup path (before the server socket binds)
    or from tests; reads happen on the engine thread.  No lock: the sets
    are only ever grown, and a stale read can at worst route one batch
    down the always-safe per-signature path.
    """

    def __init__(self, use_host: bool = False, mesh: bool = False):
        self.use_host = use_host
        self.mesh = mesh
        self.buckets: set[int] = set()
        self.chunks: set[int] = set()
        self.rlc_buckets: set[int] = set()
        # Per-launch cap in signatures; raised to the bulk cap only after
        # the chunked-scan shapes are warmed (enable_bulk).
        self.launch_cap = MAX_SUBBATCH

    # -- warmup bookkeeping -------------------------------------------------

    def mark_bucket(self, n: int):
        self.buckets.add(_bucket(n))

    def mark_chunks(self, g: int):
        self.chunks.add(g)

    def mark_rlc(self, n: int):
        self.rlc_buckets.add(_bucket(n))

    def enable_bulk(self, max_coalesced: int):
        """Raise the per-launch cap; call only after the chunked-scan
        shapes up to max_coalesced / MAX_SUBBATCH are compiled."""
        self.launch_cap = max_coalesced

    # -- shape queries ------------------------------------------------------

    def bucket_capacity(self, n: int) -> int:
        """Padded device capacity of an n-signature launch: the bucket
        (or chunk-scan) shape the dispatch layer will actually compile —
        the free room pad-fill may use without growing the launch.

        Host mode has NO padding (the host path verifies exactly n
        records, one ref.verify each), and the mesh path buckets
        per-shard (a fill record can bump every shard's padded shape) —
        in both, "pad slots" would be real extra latency work, so the
        capacity is the batch itself and fill never happens."""
        if self.use_host or self.mesh:
            return n
        if n <= MAX_SUBBATCH:
            return _bucket(n)
        g = next_pow2(-(-n // MAX_SUBBATCH))
        return g * MAX_SUBBATCH

    def route(self, n: int) -> str:
        """Verify path for a coalesced batch of n unique records."""
        if self.use_host:
            return PATH_HOST
        if self.mesh:
            return PATH_MESH
        if RLC_MIN_LAUNCH <= n <= MAX_SUBBATCH and \
                _bucket(n) in self.rlc_buckets:
            return PATH_RLC
        return PATH_PER_SIG

    def snapshot(self) -> dict:
        return {
            "launch_cap": self.launch_cap,
            "buckets": sorted(self.buckets),
            "chunks": sorted(self.chunks),
            "rlc_buckets": sorted(self.rlc_buckets),
        }

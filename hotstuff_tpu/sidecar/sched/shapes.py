"""Warmed-shape registry: which compiled launch shapes exist, and which
verify path a batch of size n should take.

The engine may only launch shapes whose XLA programs were compiled
before the socket bound (sidecar/service._warmup*): a first-time compile
on the engine thread is a silent 30-60 s stall mid-traffic.  This
registry is the single record of what was warmed:

  * ``buckets``   — padded power-of-two batch shapes (8 .. MAX_SUBBATCH)
                    for the per-signature ladder program;
  * ``chunks``    — chunked-scan lengths g (2 .. 16) for bulk backlogs
                    (g * MAX_SUBBATCH signatures in ONE dispatch);
  * ``rlc_buckets`` — padded shapes of the one-MSM RLC program
                    (ops/ed25519.verify_rlc_packed), compiled by
                    ``--warm-rlc``;
  * ``shard_buckets`` / ``rlc_shard_buckets`` — PER-SHARD padded row
                    counts of the mesh programs (verify_batch_sharded /
                    verify_rlc_sharded), compiled by the mesh warmup and
                    ``--warm-rlc-sharded``;
  * ``mesh_chunks`` / ``scan_rows`` — chunk counts g (and the per-shard
                    chunk row count) of the whole-backlog mesh scan
                    (verify_sharded_chunked — g * scan_rows rows per
                    shard in ONE dispatch), compiled by the graftscale
                    leg of ``--warm-rlc-sharded``; ``enable_bulk`` on a
                    mesh registry is gated on them.

``route`` turns (batch size, warmed state) into the launch path — the
policy that wires the one-MSM verifiers into the engine's coalesced
launch path: batches of ``RLC_MIN_LAUNCH`` or more signatures whose
bucket (per-shard bucket, on a mesh) is RLC-warmed pay one Straus MSM
instead of 2n scalar ladders, and the bisection fallback inside the RLC
paths keeps the verdict mask bit-identical to the per-signature program
whenever the combined check fails.  Mesh deployments route between
``rlc_sharded`` and ``ladder_sharded`` the same way single-chip ones
route between ``rlc`` and ``per_sig``.

Bucketing arithmetic is delegated: single-chip sizes to ``crypto/eddsa``
(``next_pow2`` / ``_bucket``) and mesh sizes to
``parallel/shard_shapes`` (``shard_bucket`` / ``shard_aligned_rows``) —
THE padding rules the graftlint padshape checker pins — so the registry
can never disagree with the dispatch layer about which shape a size
lands on.
"""

from __future__ import annotations

from ...crypto.eddsa import MAX_SUBBATCH, _bucket, next_pow2
from ...parallel.shard_shapes import (mesh_chunk_count, shard_aligned_rows,
                                      shard_bucket)

# Engine-path RLC floor: below this the combined check's fixed
# Horner/comb tail outweighs the saved ladders (crypto/eddsa.RLC_MIN_MSM
# is the *bisection* floor, a different constant: bisection wants to go
# as low as profitable, the engine wants to start where the MSM wins).
RLC_MIN_LAUNCH = 16

# Largest chunk count the whole-backlog mesh scan warms (graftscale):
# the mesh twin of the single-chip MAX_COALESCED / MAX_SUBBATCH = 16
# scan-length bound — it caps both the compiled (g, rows) program set
# and how long one backlog drain can occupy the engine ahead of a
# consensus-latency QC verify.
MESH_SCAN_CHUNKS = 16

# Verify paths route() can answer (also the stats path-counter keys).
PATH_PER_SIG = "per_sig"
PATH_RLC = "rlc"
PATH_HOST = "host"
PATH_RLC_SHARDED = "rlc_sharded"
PATH_LADDER_SHARDED = "ladder_sharded"
# graftscale: a coalesced backlog bigger than any warmed ladder bucket
# drains as ONE chunked whole-backlog mesh scan when its (g, rows)
# shape is warmed (parallel/sharded_verify.verify_sharded_chunked).
PATH_SCAN_SHARDED = "scan_sharded"
# Legacy mesh route: a registry flagged mesh without a device count
# cannot compute per-shard buckets, so it keeps the old catch-all.
PATH_MESH = "mesh"


def quorum_sigs(committee: int) -> int:
    """Signature count of a quorum certificate for an n-node committee
    with unit stakes: 2n/3 + 1 (the node's own quorum formula,
    native/src/consensus/config.hpp — NOT 2f+1 from n=3f+1, which
    disagrees for n not of that form).  The committee-size-derived
    threshold the giant-committee warmup sizes itself off: a QC-shaped
    latency batch of this many votes must land on a warmed sharded-RLC
    bucket, never the sliced ladder."""
    return 2 * committee // 3 + 1


class ShapeRegistry:
    """Tracks warmed shapes; owned by the engine, read by the scheduler.

    Mutations happen on the warmup path (before the server socket binds)
    or from tests; reads happen on the engine thread.  No lock: the sets
    are only ever grown, and a stale read can at worst route one batch
    down the always-safe per-signature path.
    """

    def __init__(self, use_host: bool = False, mesh: bool = False,
                 n_devices: int = 0, committee: int | None = None):
        self.use_host = use_host
        self.n_devices = int(n_devices or 0)
        self.mesh = bool(mesh) or self.n_devices > 1
        # Committee size served (graftscale): sizes the quorum-shaped
        # warmup floor so a 2f+1 QC batch — ~667 signatures at N=1000 —
        # always lands on a warmed sharded-RLC bucket instead of the
        # sliced ladder (qc_sigs below; None = unknown committee).
        self.committee = int(committee) if committee else None
        self.buckets: set[int] = set()
        self.chunks: set[int] = set()
        self.rlc_buckets: set[int] = set()
        # Per-SHARD padded row counts the mesh programs were compiled at
        # (the mesh analogue of buckets / rlc_buckets).
        self.shard_buckets: set[int] = set()
        self.rlc_shard_buckets: set[int] = set()
        # Whole-backlog mesh scan shapes (graftscale): the per-shard
        # chunk row count the scan programs were compiled at, and the
        # warmed chunk counts g (the mesh analogue of ``chunks``).
        self.scan_rows = 0
        self.mesh_chunks: set[int] = set()
        # Per-launch cap in signatures; raised to the bulk cap only after
        # the chunked-scan shapes are warmed (enable_bulk — on a mesh,
        # gated on the whole-backlog scan shapes instead).
        self.launch_cap = MAX_SUBBATCH

    @property
    def qc_sigs(self) -> int | None:
        """Signature count of one quorum certificate for the served
        committee (None when the committee size is unknown)."""
        if self.committee and self.committee > 1:
            return quorum_sigs(self.committee)
        return None

    # -- warmup bookkeeping -------------------------------------------------

    def mark_bucket(self, n: int):
        self.buckets.add(_bucket(n))
        if self.n_devices > 1:
            # A mesh warmup compiles per-shard shapes, not global ones.
            self.shard_buckets.add(shard_bucket(n, self.n_devices))

    def mark_chunks(self, g: int):
        self.chunks.add(g)

    def mark_rlc(self, n: int):
        self.rlc_buckets.add(_bucket(n))

    def mark_rlc_sharded(self, n: int):
        """Record that the sharded one-MSM program was compiled for the
        per-shard bucket an n-record launch lands on."""
        if self.n_devices > 1:
            self.rlc_shard_buckets.add(shard_bucket(n, self.n_devices))

    def mark_mesh_chunks(self, g: int, rows: int):
        """Record that the whole-backlog mesh scan program was compiled
        for g chunks of ``rows`` per-shard rows (graftscale warmup).
        One ``rows`` value per registry: the warmup compiles every g at
        its top per-shard bucket, and a second rows value would mean two
        scan ladders the router cannot tell apart."""
        if self.n_devices <= 1:
            return
        if self.scan_rows and self.scan_rows != rows:
            raise ValueError(
                f"mesh scan chunk rows already warmed at "
                f"{self.scan_rows}, cannot also warm {rows}")
        self.scan_rows = rows
        self.mesh_chunks.add(g)

    def scan_shape_of(self, n: int):
        """(g, rows) of the warmed whole-backlog scan an n-record
        launch would dispatch as, or None when no warmed scan shape
        covers it (no scan warmup ran, or the backlog outgrows the
        largest warmed chunk count — the caller falls back to the
        sliced ladder path)."""
        if self.n_devices <= 1 or not self.scan_rows \
                or not self.mesh_chunks:
            return None
        g = mesh_chunk_count(n, self.n_devices, self.scan_rows)
        if g in self.mesh_chunks:
            return g, self.scan_rows
        return None

    def scan_capacity(self) -> int:
        """Largest backlog ONE whole-backlog mesh scan can drain
        (0 when no scan shapes are warmed): the launch-cap ceiling
        enable_bulk may raise a mesh registry to.

        Worked suppression: this is capacity arithmetic over shapes the
        warmup ALREADY compiled (every g in mesh_chunks was marked by
        mark_mesh_chunks after its program built) — no launch size is
        derived here, so the shard-alignment rule's cold-compile hazard
        cannot arise; launch sizing goes through scan_shape_of, whose
        mesh_chunk_count call is the pinned helper."""
        if self.n_devices <= 1 or not self.mesh_chunks:
            return 0
        # graftlint: disable=shard-misaligned-launch
        return self.n_devices * max(self.mesh_chunks) * self.scan_rows

    def ladder_cap(self) -> int:
        """Slice size for the sliced-ladder mesh fallback: the largest
        launch whose per-shard bucket the warmup actually compiled
        (device count x top warmed bucket).  The scan-raised launch_cap
        must never leak into ladder slicing — a 16384-sig slice would
        land on a per-shard shape only the SCAN programs know, a cold
        XLA compile on the engine thread mid-traffic.  With no warmed
        buckets at all, a mesh registry floors at MAX_SUBBATCH (the
        pre-graftscale slicing step) — never the raised launch_cap,
        even when a scan-only warmup (--warm-bulk without the RLC leg)
        raised it; single-chip registries keep launch_cap (their
        enable_bulk is ungated and warms the chunk shapes it needs).

        Worked suppression (same rationale as scan_capacity): this is
        capacity arithmetic over buckets the warmup ALREADY compiled —
        every element of shard_buckets was marked after its program
        built; the slice sizes derived from it re-enter
        verify_batch_sharded_pack, whose shard_bucket call is the
        pinned helper."""
        if self.n_devices > 1:
            if self.shard_buckets:
                # graftlint: disable=shard-misaligned-launch
                return self.n_devices * max(self.shard_buckets)
            return min(self.launch_cap, MAX_SUBBATCH)
        return self.launch_cap

    def enable_bulk(self, max_coalesced: int):
        """Raise the per-launch cap; call only after the chunked-scan
        shapes up to max_coalesced / MAX_SUBBATCH are compiled.  On a
        mesh registry the raise is GATED on the whole-backlog scan
        shapes (mark_mesh_chunks): without them a coalesced backlog
        beyond MAX_SUBBATCH would have to slice — or worse, land a
        per-shard shape warmup never compiled — so the cap stays put
        and the coalescer keeps assembling single-bucket launches.
        Raise-only: a small warmed scan capacity must never LOWER the
        cap below its current value."""
        if self.n_devices > 1:
            cap = self.scan_capacity()
            if not cap:
                return
            self.launch_cap = max(self.launch_cap,
                                  min(max_coalesced, cap))
            return
        self.launch_cap = max_coalesced

    # -- shape queries ------------------------------------------------------

    def shard_bucket_of(self, n: int) -> int | None:
        """Per-shard padded row count an n-record mesh launch lands on
        (None when this registry has no mesh size)."""
        if self.n_devices > 1:
            return shard_bucket(n, self.n_devices)
        return None

    def bucket_capacity(self, n: int) -> int:
        """Padded device capacity of an n-signature launch: the bucket
        (or chunk-scan, or shard-aligned mesh) shape the dispatch layer
        will actually compile — the free room pad-fill may use without
        growing the launch.

        Host mode has NO padding (the host path verifies exactly n
        records, one ref.verify each), so there the capacity is the
        batch itself and fill never happens.  Mesh launches pad to the
        shard-aligned row count (per-shard power-of-two bucket x device
        count — parallel/shard_shapes), so their pad-fill room is real
        free capacity too: filling up to it never grows any shard's
        compiled shape."""
        if self.use_host:
            return n
        if self.n_devices > 1:
            return shard_aligned_rows(n, self.n_devices)
        if self.mesh:
            return n  # legacy mesh-without-count: no sizing knowledge
        if n <= MAX_SUBBATCH:
            return _bucket(n)
        g = next_pow2(-(-n // MAX_SUBBATCH))
        return g * MAX_SUBBATCH

    def route(self, n: int) -> str:
        """Verify path for a coalesced batch of n unique records."""
        if self.use_host:
            return PATH_HOST
        if self.n_devices > 1:
            per = shard_bucket(n, self.n_devices)
            if n >= RLC_MIN_LAUNCH and per <= MAX_SUBBATCH and \
                    per in self.rlc_shard_buckets:
                return PATH_RLC_SHARDED
            # A backlog bigger than any warmed ladder bucket drains as
            # ONE whole-backlog scan when its chunk count is warmed;
            # otherwise the ladder path slices it at the launch cap
            # (the pre-graftscale behavior, kept as the safe fallback).
            if per not in self.shard_buckets and \
                    self.scan_shape_of(n) is not None:
                return PATH_SCAN_SHARDED
            return PATH_LADDER_SHARDED
        if self.mesh:
            return PATH_MESH
        if RLC_MIN_LAUNCH <= n <= MAX_SUBBATCH and \
                _bucket(n) in self.rlc_buckets:
            return PATH_RLC
        return PATH_PER_SIG

    def snapshot(self) -> dict:
        return {
            "launch_cap": self.launch_cap,
            "buckets": sorted(self.buckets),
            "chunks": sorted(self.chunks),
            "rlc_buckets": sorted(self.rlc_buckets),
            "n_devices": self.n_devices,
            "shard_buckets": sorted(self.shard_buckets),
            "rlc_shard_buckets": sorted(self.rlc_shard_buckets),
            "scan_rows": self.scan_rows,
            "mesh_chunks": sorted(self.mesh_chunks),
            "committee": self.committee,
        }

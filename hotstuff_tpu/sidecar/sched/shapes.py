"""Warmed-shape registry: which compiled launch shapes exist, and which
verify path a batch of size n should take.

The engine may only launch shapes whose XLA programs were compiled
before the socket bound (sidecar/service._warmup*): a first-time compile
on the engine thread is a silent 30-60 s stall mid-traffic.  This
registry is the single record of what was warmed:

  * ``buckets``   — padded power-of-two batch shapes (8 .. MAX_SUBBATCH)
                    for the per-signature ladder program;
  * ``chunks``    — chunked-scan lengths g (2 .. 16) for bulk backlogs
                    (g * MAX_SUBBATCH signatures in ONE dispatch);
  * ``rlc_buckets`` — padded shapes of the one-MSM RLC program
                    (ops/ed25519.verify_rlc_packed), compiled by
                    ``--warm-rlc``;
  * ``shard_buckets`` / ``rlc_shard_buckets`` — PER-SHARD padded row
                    counts of the mesh programs (verify_batch_sharded /
                    verify_rlc_sharded), compiled by the mesh warmup and
                    ``--warm-rlc-sharded``.

``route`` turns (batch size, warmed state) into the launch path — the
policy that wires the one-MSM verifiers into the engine's coalesced
launch path: batches of ``RLC_MIN_LAUNCH`` or more signatures whose
bucket (per-shard bucket, on a mesh) is RLC-warmed pay one Straus MSM
instead of 2n scalar ladders, and the bisection fallback inside the RLC
paths keeps the verdict mask bit-identical to the per-signature program
whenever the combined check fails.  Mesh deployments route between
``rlc_sharded`` and ``ladder_sharded`` the same way single-chip ones
route between ``rlc`` and ``per_sig``.

Bucketing arithmetic is delegated: single-chip sizes to ``crypto/eddsa``
(``next_pow2`` / ``_bucket``) and mesh sizes to
``parallel/shard_shapes`` (``shard_bucket`` / ``shard_aligned_rows``) —
THE padding rules the graftlint padshape checker pins — so the registry
can never disagree with the dispatch layer about which shape a size
lands on.
"""

from __future__ import annotations

from ...crypto.eddsa import MAX_SUBBATCH, _bucket, next_pow2
from ...parallel.shard_shapes import shard_aligned_rows, shard_bucket

# Engine-path RLC floor: below this the combined check's fixed
# Horner/comb tail outweighs the saved ladders (crypto/eddsa.RLC_MIN_MSM
# is the *bisection* floor, a different constant: bisection wants to go
# as low as profitable, the engine wants to start where the MSM wins).
RLC_MIN_LAUNCH = 16

# Verify paths route() can answer (also the stats path-counter keys).
PATH_PER_SIG = "per_sig"
PATH_RLC = "rlc"
PATH_HOST = "host"
PATH_RLC_SHARDED = "rlc_sharded"
PATH_LADDER_SHARDED = "ladder_sharded"
# Legacy mesh route: a registry flagged mesh without a device count
# cannot compute per-shard buckets, so it keeps the old catch-all.
PATH_MESH = "mesh"


class ShapeRegistry:
    """Tracks warmed shapes; owned by the engine, read by the scheduler.

    Mutations happen on the warmup path (before the server socket binds)
    or from tests; reads happen on the engine thread.  No lock: the sets
    are only ever grown, and a stale read can at worst route one batch
    down the always-safe per-signature path.
    """

    def __init__(self, use_host: bool = False, mesh: bool = False,
                 n_devices: int = 0):
        self.use_host = use_host
        self.n_devices = int(n_devices or 0)
        self.mesh = bool(mesh) or self.n_devices > 1
        self.buckets: set[int] = set()
        self.chunks: set[int] = set()
        self.rlc_buckets: set[int] = set()
        # Per-SHARD padded row counts the mesh programs were compiled at
        # (the mesh analogue of buckets / rlc_buckets).
        self.shard_buckets: set[int] = set()
        self.rlc_shard_buckets: set[int] = set()
        # Per-launch cap in signatures; raised to the bulk cap only after
        # the chunked-scan shapes are warmed (enable_bulk).
        self.launch_cap = MAX_SUBBATCH

    # -- warmup bookkeeping -------------------------------------------------

    def mark_bucket(self, n: int):
        self.buckets.add(_bucket(n))
        if self.n_devices > 1:
            # A mesh warmup compiles per-shard shapes, not global ones.
            self.shard_buckets.add(shard_bucket(n, self.n_devices))

    def mark_chunks(self, g: int):
        self.chunks.add(g)

    def mark_rlc(self, n: int):
        self.rlc_buckets.add(_bucket(n))

    def mark_rlc_sharded(self, n: int):
        """Record that the sharded one-MSM program was compiled for the
        per-shard bucket an n-record launch lands on."""
        if self.n_devices > 1:
            self.rlc_shard_buckets.add(shard_bucket(n, self.n_devices))

    def enable_bulk(self, max_coalesced: int):
        """Raise the per-launch cap; call only after the chunked-scan
        shapes up to max_coalesced / MAX_SUBBATCH are compiled."""
        self.launch_cap = max_coalesced

    # -- shape queries ------------------------------------------------------

    def shard_bucket_of(self, n: int) -> int | None:
        """Per-shard padded row count an n-record mesh launch lands on
        (None when this registry has no mesh size)."""
        if self.n_devices > 1:
            return shard_bucket(n, self.n_devices)
        return None

    def bucket_capacity(self, n: int) -> int:
        """Padded device capacity of an n-signature launch: the bucket
        (or chunk-scan, or shard-aligned mesh) shape the dispatch layer
        will actually compile — the free room pad-fill may use without
        growing the launch.

        Host mode has NO padding (the host path verifies exactly n
        records, one ref.verify each), so there the capacity is the
        batch itself and fill never happens.  Mesh launches pad to the
        shard-aligned row count (per-shard power-of-two bucket x device
        count — parallel/shard_shapes), so their pad-fill room is real
        free capacity too: filling up to it never grows any shard's
        compiled shape."""
        if self.use_host:
            return n
        if self.n_devices > 1:
            return shard_aligned_rows(n, self.n_devices)
        if self.mesh:
            return n  # legacy mesh-without-count: no sizing knowledge
        if n <= MAX_SUBBATCH:
            return _bucket(n)
        g = next_pow2(-(-n // MAX_SUBBATCH))
        return g * MAX_SUBBATCH

    def route(self, n: int) -> str:
        """Verify path for a coalesced batch of n unique records."""
        if self.use_host:
            return PATH_HOST
        if self.n_devices > 1:
            per = shard_bucket(n, self.n_devices)
            if n >= RLC_MIN_LAUNCH and per <= MAX_SUBBATCH and \
                    per in self.rlc_shard_buckets:
                return PATH_RLC_SHARDED
            return PATH_LADDER_SHARDED
        if self.mesh:
            return PATH_MESH
        if RLC_MIN_LAUNCH <= n <= MAX_SUBBATCH and \
                _bucket(n) in self.rlc_buckets:
            return PATH_RLC
        return PATH_PER_SIG

    def snapshot(self) -> dict:
        return {
            "launch_cap": self.launch_cap,
            "buckets": sorted(self.buckets),
            "chunks": sorted(self.chunks),
            "rlc_buckets": sorted(self.rlc_buckets),
            "n_devices": self.n_devices,
            "shard_buckets": sorted(self.shard_buckets),
            "rlc_shard_buckets": sorted(self.rlc_shard_buckets),
        }

"""Deadline-aware two-class batching scheduler for the verify engine.

Replaces the engine's single FIFO coalescing loop with explicit policy,
the shape continuous-batching servers converged on (Orca's per-class
admission + iteration-level scheduling, adapted to signature batches):

Strict latency priority.
    Whenever latency-class work is queued, the next launch is assembled
    from the latency queue only — a QC verify never waits behind a bulk
    backlog, only behind the launch already in flight (the engine's
    pipeline bounds that to PIPELINE_DEPTH launches).

Carry-over within a class.
    Coalescing never splits a request.  A head request that does not fit
    the remaining launch budget simply stays queued and is guaranteed to
    LEAD the next launch of its class (``carries`` telemetry counts how
    often) — the FIFO position is the fairness token, so an over-budget
    bulk batch cannot be displaced forever by smaller arrivals.

Bulk pad-fill (carry-over fairness across classes).
    Launch shapes are padded to power-of-two buckets, so a latency
    launch of n unique records ships ``bucket(n) - n`` dead slots
    anyway.  Those slots are filled with whole bulk requests that fit
    (room is sized off the DEDUPED latency record count — see
    ``_assemble_locked`` — so fill can never grow the compiled shape) —
    the latency launch shape, and therefore its time, is unchanged, and
    bulk traffic keeps draining at least at the pad-waste rate even
    under 100%% sustained latency load.  Strict priority alone would
    starve bulk in exactly that regime; a time-slice would trade
    consensus latency away.  Pad-fill does neither.

Bounded backpressure.
    Both queues are bounded in signature records; ``offer`` never
    blocks.  A full queue is an explicit queue-full reply to the client
    (which falls back to host verify or retries), never a connection
    thread wedged on an unbounded ``put`` — the engine always sees an
    honest queue it can reason about.

The scheduler owns queues and policy only; the device, the verify paths
and the reply fan-out stay in ``sidecar/service.VerifyEngine``.
"""

from __future__ import annotations

import os
import threading
from time import monotonic

from ...crypto.eddsa import MAX_SUBBATCH
from .classes import BULK, LATENCY, ClassQueue, Launch, Pending
from .shapes import ShapeRegistry
from .stats import SchedStats
from .surge import AdmissionController

# Admission caps (signature records queued, not requests).  Latency is
# sized for bursts of full-committee QC verifies; bulk for a few whole
# coalesced launches — beyond that, shedding to the client beats hiding
# an ever-growing backlog inside the sidecar.  These are the STATIC
# defaults; deployments that know their committee size / client rate get
# caps sized from those parameters instead (size_queue_caps below), and
# the HOTSTUFF_TPU_{LATENCY,BULK}_QUEUE_CAP_SIGS env vars override both.
_DEFAULT_LATENCY_CAP_SIGS = 64 * 1024
_DEFAULT_BULK_CAP_SIGS = 128 * 1024

# Per-replica async verify pipeline depth the latency sizing assumes —
# the C++ node's MAXIMUM adaptive in-flight budget (TpuVerifier::
# kInflightBudgetMax; the budget only ever shrinks below this).
_INFLIGHT_PER_REPLICA = 64


def _env_cap(name: str):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        v = int(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def _clamp(v: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, v))


def size_queue_caps(committee: int | None = None,
                    client_rate: int | None = None):
    """``(latency_cap_sigs, bulk_cap_sigs)`` for a deployment.

    Latency demand scales with the committee: on the shared local
    testbed every replica verifies every certificate, so a worst-case
    burst is ``committee`` replicas x ``_INFLIGHT_PER_REPLICA`` pipelined
    requests x ``quorum`` signatures each.  Bulk demand scales with the
    client transaction rate: the cap admits ~2 s of arrivals, past which
    shedding to the client's host path beats an ever-older backlog
    (their verdicts would miss the batch's consensus round anyway).
    Both are clamped to [default/4, 16x default] so a typo'd parameter
    cannot starve or balloon the sidecar, and the explicit env
    overrides (HOTSTUFF_TPU_LATENCY_QUEUE_CAP_SIGS /
    HOTSTUFF_TPU_BULK_QUEUE_CAP_SIGS) win over everything."""
    lat = _env_cap("HOTSTUFF_TPU_LATENCY_QUEUE_CAP_SIGS")
    if lat is None:
        if committee and committee > 1:
            quorum = 2 * committee // 3 + 1
            lat = _clamp(committee * quorum * _INFLIGHT_PER_REPLICA,
                         _DEFAULT_LATENCY_CAP_SIGS // 4,
                         16 * _DEFAULT_LATENCY_CAP_SIGS)
        else:
            lat = _DEFAULT_LATENCY_CAP_SIGS
    blk = _env_cap("HOTSTUFF_TPU_BULK_QUEUE_CAP_SIGS")
    if blk is None:
        if client_rate and client_rate > 0:
            blk = _clamp(2 * client_rate,
                         _DEFAULT_BULK_CAP_SIGS // 4,
                         16 * _DEFAULT_BULK_CAP_SIGS)
        else:
            blk = _DEFAULT_BULK_CAP_SIGS
    return lat, blk


def size_tenant_caps(latency_cap_sigs: int, bulk_cap_sigs: int,
                     committee: int | None = None):
    """``(latency_tenant_cap_sigs, bulk_tenant_cap_sigs)`` — one
    tenant's admission share of each class queue (graftfleet).

    The latency share is sized off the committee exactly like the class
    cap itself (one committee's worst-case pipelined QC burst), so a
    single-committee tenant never notices the share — while a tenant
    flooding past its own committee's plausible demand sheds on its
    share with the rest of the class cap still open to other tenants.
    The bulk share is half the class cap: bulk is best-effort by
    definition, and half leaves a second tenant's worth of admission
    room under any flood.  Shares only ENGAGE once a second tenant has
    been seen (ClassQueue._offer_locked), so pre-fleet deployments are
    byte-identical."""
    if committee and committee > 1:
        quorum = 2 * committee // 3 + 1
        lat = _clamp(committee * quorum * _INFLIGHT_PER_REPLICA,
                     latency_cap_sigs // 4, latency_cap_sigs)
    else:
        lat = latency_cap_sigs
    return lat, max(1, bulk_cap_sigs // 2)


# Back-compat module constants (env-aware at import): the parameterless
# Scheduler() and older embedders read these.
LATENCY_QUEUE_CAP_SIGS, BULK_QUEUE_CAP_SIGS = size_queue_caps()


class Scheduler:
    def __init__(self, shapes: ShapeRegistry | None = None,
                 stats: SchedStats | None = None,
                 latency_cap_sigs: int = LATENCY_QUEUE_CAP_SIGS,
                 bulk_cap_sigs: int = BULK_QUEUE_CAP_SIGS,
                 admission: AdmissionController | None = None,
                 committee: int | None = None):
        self.shapes = shapes if shapes is not None else ShapeRegistry()
        self.stats = stats if stats is not None else SchedStats()
        # graftsurge: the pack-side admission controller (sched/surge.py)
        # derates bulk intake off the pipeline overlap stats and enforces
        # bulk-before-latency shedding; the stats object forwards the
        # engine's note_pack/note_launch observations into it and folds
        # its counters into the OP_STATS ``surge`` section.
        self.admission = admission if admission is not None \
            else AdmissionController()
        self.stats.surge = self.admission
        self._cond = threading.Condition()
        # graftfleet: per-tenant admission shares sized off the
        # committee (they only engage once a second tenant appears —
        # see ClassQueue._offer_locked).
        lat_share, blk_share = size_tenant_caps(
            latency_cap_sigs, bulk_cap_sigs, committee)
        self._queues = {
            LATENCY: ClassQueue(latency_cap_sigs, self._cond,
                                tenant_cap_sigs=lat_share),
            BULK: ClassQueue(bulk_cap_sigs, self._cond,
                             tenant_cap_sigs=blk_share),
        }

    # -- admission (connection threads) -------------------------------------

    def offer(self, request, reply_fn, cls: str = LATENCY,
              is_bls: bool = False, tenant: str | None = None) -> bool:
        """Admit one request; False means queue-full (the caller must
        reply explicitly — nothing was retained; ``retry_after_ms``
        gives the hint the BUSY reply should carry).

        Admission policy (graftsurge) on top of the plain byte caps:
        bulk is shed outright while the latency class is under shed
        pressure (bulk-before-latency — under overload the consensus
        class is the last to lose capacity), and bulk admits against a
        cap derated by the pipeline-overlap controller (a pack-bound
        engine sheds bulk earlier instead of queueing work the pack
        worker cannot drain).  All checks run under the one admission
        lock, so a bulk request can never be admitted concurrently with
        a latency shed — the fairness guarantee the strict parser mode
        asserts.

        graftfleet adds the tenant key: ``tenant`` (the connection's
        HELLO identity, default for legacy clients) selects the lane,
        the per-tenant share is enforced inside the queue, and a
        latency shed is audited for STARVATION — a refusal at the class
        cap while another tenant sits above its own share would mean a
        flooding tenant displaced this one, which per-lane admission
        makes unreachable; ``tenant_starvation`` is the proof counter
        the strict parser reads."""
        pending = Pending(request, reply_fn, cls, is_bls=is_bls,
                          tenant=tenant)
        adm = self.admission
        with self._cond:
            if cls == BULK:
                lat = self._queues[LATENCY]
                if adm.latency_pressure() or (
                        lat.sigs and lat.sigs >= lat.cap_sigs):
                    adm.note_shed(BULK, before_latency=True)
                    self.stats.note_queue_full(cls)
                    self.stats.note_tenant_shed(pending.tenant, cls)
                    return False
                cap = int(self._queues[BULK].cap_sigs * adm.bulk_derate())
                if not self._queues[BULK]._offer_locked(pending,
                                                        cap_sigs=cap):
                    adm.note_shed(BULK)
                    self.stats.note_queue_full(cls)
                    self.stats.note_tenant_shed(pending.tenant, cls)
                    return False
            elif not self._queues[cls]._offer_locked(pending):
                if cls == LATENCY:
                    adm.note_latency_shed()
                    q = self._queues[LATENCY]
                    if q.last_refusal == "class-cap" and \
                            q.lanes.any_over_cap_locked(
                                q.tenant_cap_sigs,
                                exclude=pending.tenant):
                        adm.note_tenant_starvation()
                adm.note_shed(cls)
                self.stats.note_queue_full(cls)
                self.stats.note_tenant_shed(pending.tenant, cls)
                return False
            adm.note_admitted(cls)
            self.stats.note_admitted(cls)
            self.stats.note_tenant_admitted(pending.tenant, cls)
            return True

    def retry_after_ms(self, cls: str) -> int:
        """Hint for a BUSY reply: the time this class's backlog needs to
        drain at the recent launch rate (clamped; see surge.py)."""
        return self.admission.retry_after_ms(cls, self._queues[cls].sigs)

    def wake(self):
        """Unblock a next_launch() waiter (shutdown path)."""
        with self._cond:
            self._cond.notify_all()

    def queued_sigs(self, cls: str) -> int:
        return self._queues[cls].sigs

    def queue_caps(self) -> dict:
        """Admission caps per class (OP_STATS telemetry)."""
        return {cls: q.cap_sigs for cls, q in self._queues.items()}

    def tenant_caps(self) -> dict:
        """Per-tenant admission shares per class (OP_STATS telemetry)."""
        return {cls: q.tenant_cap_sigs for cls, q in self._queues.items()}

    def tenant_occupancy(self) -> dict:
        """{class: {tenant: queued sig records}} — the live lane view
        the fleet OP_STATS section exposes (graftfleet)."""
        with self._cond:
            return {cls: q.lanes.occupancy_locked()
                    for cls, q in self._queues.items()}

    # -- assembly (engine thread) -------------------------------------------

    def next_launch(self, block: bool = True,
                    timeout: float | None = None) -> Launch | None:
        """Assemble the next launch, or None when (a) non-blocking and
        idle, or (b) the timeout expired."""
        return self._next(None, block, timeout)

    def next_tick(self, quota_sigs: int,
                  timeout: float | None = None) -> Launch | None:
        """graftcadence: assemble one cadence tick's quota — the same
        strict-priority, carry-over, pad-fill policy as next_launch,
        but the coalesce run is capped at ``quota_sigs`` (the ring's
        per-tick budget, a warmed bucket) instead of the class launch
        cap.  Pad-fill still pads to the compiled bucket of the deduped
        record count: dead slots are free FLOPs whether the launch came
        from a tick quota or a staged coalesce.  Non-blocking by
        default (the ring paces itself); with a timeout the fully-idle
        ring parks here so a fresh offer wakes it immediately instead
        of eating an idle-backoff interval."""
        return self._next(quota_sigs, timeout is not None, timeout)

    def _next(self, cap: int | None, block: bool,
              timeout: float | None) -> Launch | None:
        deadline = None if timeout is None else monotonic() + timeout
        with self._cond:
            while True:
                launch = self._assemble_locked(cap=cap)
                if launch is not None or not block:
                    return launch
                wait = None if deadline is None \
                    else max(0.0, deadline - monotonic())
                if wait == 0.0 or not self._cond.wait(timeout=wait):
                    if deadline is not None and monotonic() >= deadline:
                        return None

    def _assemble_locked(self, cap: int | None = None) -> Launch | None:
        lat, blk = self._queues[LATENCY], self._queues[BULK]
        if lat:
            if lat._head_locked().is_bls:
                launch = Launch("bls", [lat._pop_locked()], LATENCY)
                # BLS runs one request per launch (nothing coalesces);
                # capacity 1 keeps pad-waste at zero while the launch
                # count and the latency queue-wait reservoir — where a
                # seconds-long pairing backlog shows up — stay honest.
                self.stats.note_launch(launch, 1, monotonic())
                return launch
            items, total = self._coalesce_locked(lat, cap=cap)
            # Fill room comes from the DEDUPED record count, not the raw
            # total: the engine dedups (msg, pk, sig) records before
            # dispatch and launches bucket(unique), so under the headline
            # shared-sidecar load (N replicas submitting the SAME QC,
            # total >> unique) sizing fill off the raw total would grow
            # the compiled shape past the latency batch's own bucket —
            # the exact latency cost pad-fill promises not to incur.
            # On a mesh, bucket_capacity is the SHARD-ALIGNED row count
            # (per-shard power-of-two bucket x device count, via
            # parallel/shard_shapes): launches always divide evenly
            # across the devices — no 375-row shards, no cold XLA
            # compiles mid-run — and fill room is computed against that
            # same shard-aligned capacity, so mesh pad slots drain bulk
            # exactly like single-chip ones.
            # Each fill request is counted at its full record count
            # (worst case: all its records are new), so unique-after-fill
            # can never exceed the latency batch's bucket.  The dedup is
            # computed only when fill is actually on the table (bulk
            # queued, batch within one sub-batch) — it hashes every
            # record while holding the admission lock, so the common
            # pure-consensus case must not pay it per launch.
            fill = []
            if blk and total <= MAX_SUBBATCH:
                uniq = len({rec for p in items
                            for rec in zip(p.request.msgs, p.request.pks,
                                           p.request.sigs)})
                capacity = self.shapes.bucket_capacity(uniq)
                fill = self._fill_locked(blk, capacity - uniq)
            else:
                capacity = self.shapes.bucket_capacity(total)
            launch = Launch("verify", items + fill, LATENCY,
                            fill_count=len(fill))
            self.stats.note_launch(launch, capacity, monotonic())
            return launch
        if blk:
            items, total = self._coalesce_locked(blk, cap=cap)
            launch = Launch("verify", items, BULK)
            self.stats.note_launch(
                launch, self.shapes.bucket_capacity(total), monotonic())
            return launch
        return None

    def _coalesce_locked(self, q: ClassQueue, cap: int | None = None):
        """Pop a FIFO run of same-class Ed25519 requests up to the launch
        cap.  The head always ships (an oversized single request slices
        inside the engine dispatch); a later head that would overflow the
        budget stays queued and leads the next launch (carry-over).

        The default cap is the registry's launch_cap: MAX_SUBBATCH until
        the bulk shapes are warmed, then the single-chip MAX_COALESCED —
        or, on a mesh, the whole-backlog scan capacity the gated
        enable_bulk raised it to (graftscale): everything coalesced here
        then drains as ONE chunked mesh scan instead of per-cap ladder
        slices.  The cadence ring passes its per-tick quota instead
        (never above launch_cap — a tick must stay inside one warmed
        shape)."""
        cap = self.shapes.launch_cap if cap is None \
            else min(cap, self.shapes.launch_cap)
        items = [q._pop_locked()]
        total = len(items[0])
        while (nxt := q._head_locked()) is not None and not nxt.is_bls:
            nxt_len = len(nxt)
            if total + nxt_len > cap:
                self.stats.note_carry(items[0].cls)
                break
            items.append(q._pop_locked())
            total += nxt_len
        return items, total

    def _fill_locked(self, blk: ClassQueue, room: int):
        """Whole bulk requests that fit the latency launch's pad slots."""
        fill = []
        while room > 0:
            h = blk._head_locked()
            if h is None or h.is_bls or len(h) > room:
                break
            p = blk._pop_locked()
            fill.append(p)
            room -= len(p)
        return fill

from .service import main

main()

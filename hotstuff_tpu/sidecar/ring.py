"""graftcadence: the continuous-batching resident verify pipeline.

The staged engine (service.VerifyEngine._run_staged) is request-driven:
coalesce -> pack -> launch -> fetch, one launch at a time with a depth-2
double buffer.  At production rates the dominant cost is the fixed
per-launch host overhead, not device FLOPs — exactly what the OP_STATS
``pipeline.overlap_ratio`` measures.  The continuous-batching insight
from LLM serving (Orca, OSDI'22) transfers directly: keep ONE resident
compiled program per warmed shape fed at a fixed, load-adaptive cadence
instead of dispatching per request.

This module is that loop:

  * :class:`CadenceRing` — a fixed ring of ``k`` slots (depth-k
    generalization of the staged engine's depth-2 pipeline).  Every tick
    the ring collects the oldest in-flight verdict when it must (ring
    full, or idle), then arms one free slot with the scheduler's
    per-tick quota (``Scheduler.next_tick``, pad-filled from the bulk
    backlog exactly like the staged coalesce so a partially-filled tick
    never wastes FLOPs).  Shapes come from the warmed ``ShapeRegistry``
    buckets via the engine's own ``_pack`` — never a fresh compile
    mid-run — and on a mesh the pack routes through the pre-donated
    resident entries (``parallel.sharded_verify.ring_slot_pack``).

  * generation tags — every slot carries a generation counter bumped on
    each arm AND each invalidation (expiry re-resolve, wedge fallback).
    A flight's verdict is applied ONLY if its captured generation still
    matches the slot's; anything else is counted as a generation drop
    and discarded, so a stale fetch can never answer a re-armed slot
    (the graftview TC-verdict generation/expiry machinery is the
    template).

  * :class:`RingDepth` — sizes k in {2, 4, 8} from measured dispatch
    overhead vs per-shape device walls, seeded from the compile
    manifest's measured walls the same way graftguard's LaunchDeadlines
    seeds its warm-boot decision (``from_manifest``).

  * :class:`CadenceStats` — the OP_STATS ``cadence`` section: tick
    rate, occupancy histogram, pad-fill ratio, generation drops,
    queue-wait p50/p99.

Supervision: every cadence dispatch/fetch is a guarded launch under the
``tick:`` deadline class (guard.LaunchDeadlines.TICK_CLASS_PREFIX — the
ring only ever launches warmed shapes, so a cold tick key gets the warm
grace, not the compile budget).  A WedgedLaunch drops the ring back to
the staged engine through the existing degradation ladder: the wedged
flight rides ``_wedge_ladder`` (host masks / BUSY + quarantine +
crash-only reboot), every other in-flight generation is invalidated and
re-resolved on the host, and ``run()`` returns with ``enabled`` False —
``VerifyEngine._run`` then falls through to the staged loop.  The
staged path stays the DEFAULT: the ring runs only behind
``--cadence`` / ``HOTSTUFF_TPU_CADENCE`` until a committed bench
headline shows it winning.

Bit-identity is non-negotiable: the ring feeds batches through the very
same ``VerifyEngine._pack`` the staged path uses (same dedup, same
verdict cache, same RLC bisection per generation), so ring verdicts
equal ``verify_batch`` masks by construction — and tests assert it
through the engine.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque
from time import monotonic

from . import sched as vsched
from .guard import BusyReply, WedgedLaunch

log = logging.getLogger("sidecar.ring")

ENV_CADENCE = "HOTSTUFF_TPU_CADENCE"          # "1"/"true"/"on" => ring
ENV_DEPTH = "HOTSTUFF_TPU_CADENCE_DEPTH"      # pin k (else trained)
ENV_TICK_S = "HOTSTUFF_TPU_CADENCE_TICK_S"    # pin tick interval


def cadence_enabled(default: bool = False) -> bool:
    """True iff the environment opts the sidecar into the cadence ring
    (the staged engine stays the default until the committed ``cadence``
    bench headline shows the ring winning)."""
    raw = os.environ.get(ENV_CADENCE)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "on", "yes")


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * (len(sorted_vals) - 1))))
    return float(sorted_vals[idx])


class RingDepth:
    """Trains the ring depth k in {2, 4, 8} from measured host dispatch
    overhead vs per-shape device walls — the same evidence class
    graftguard's LaunchDeadlines trains its deadlines on, seeded the
    same way (:meth:`from_manifest`).

    Depth covers dispatch: with overhead o and device wall w, the device
    stays busy iff k-1 launches execute while the host stages the next,
    so the ideal k is about 1 + o/w rounded up to the next supported
    depth.  Depth beyond that only adds reply latency (the staged
    engine's depth-2 comment, generalized).  Until MIN_OBSERVATIONS
    walls exist the trainer answers the conservative minimum (2)."""

    DEPTHS = (2, 4, 8)
    MIN_OBSERVATIONS = 8
    SAMPLES_CAP = 256

    def __init__(self, pinned: int | None = None):
        if pinned is None:
            raw = os.environ.get(ENV_DEPTH)
            if raw:
                try:
                    pinned = int(raw)
                except ValueError:
                    pinned = None
        self.pinned = self._clamp(pinned) if pinned else None
        self._lock = threading.Lock()
        self._dispatch: deque = deque(maxlen=self.SAMPLES_CAP)
        self._walls: deque = deque(maxlen=self.SAMPLES_CAP)

    @classmethod
    def _clamp(cls, k: int) -> int:
        for d in cls.DEPTHS:
            if k <= d:
                return d
        return cls.DEPTHS[-1]

    @classmethod
    def from_manifest(cls, manifest, kernel: str, **kw) -> "RingDepth":
        """Seed device-wall evidence from the compile manifest's measured
        per-shape walls (LaunchDeadlines.from_manifest is the template:
        tolerant of a missing/corrupt manifest — an empty one just means
        the trainer starts at the conservative minimum)."""
        d = cls(**kw)
        try:
            walls = manifest.shape_walls(kernel)
        except Exception:
            walls = {}
        d.seed(walls)
        return d

    def seed(self, walls: dict) -> None:
        with self._lock:
            for w in walls.values():
                if isinstance(w, (int, float)) and w > 0:
                    self._walls.append(float(w))

    def observe(self, dispatch_s: float, wall_s: float) -> None:
        """One completed flight: host-side dispatch overhead (guarded
        pack-wait + dispatch call) and the device wall it overlapped."""
        with self._lock:
            if dispatch_s > 0:
                self._dispatch.append(float(dispatch_s))
            if wall_s > 0:
                self._walls.append(float(wall_s))

    def depth(self) -> int:
        if self.pinned:
            return self.pinned
        with self._lock:
            if len(self._dispatch) < self.MIN_OBSERVATIONS or \
                    len(self._walls) < self.MIN_OBSERVATIONS:
                return self.DEPTHS[0]
            o = _percentile(sorted(self._dispatch), 0.5)
            w = _percentile(sorted(self._walls), 0.5)
        if w <= 0:
            return self.DEPTHS[0]
        return self._clamp(1 + int(o / w + 0.999))

    def snapshot(self) -> dict:
        k = self.depth()  # takes the lock itself — stay outside it here
        with self._lock:
            return {
                "k": k,
                "pinned": bool(self.pinned),
                "dispatch_samples": len(self._dispatch),
                "wall_samples": len(self._walls),
            }


class CadenceStats:
    """Ring telemetry behind the OP_STATS ``cadence`` section.  All
    counters are written from the ring (engine) thread; snapshot() is
    called from connection threads, so every touch is lock-guarded.
    Queue waits ride a bounded reservoir like SchedStats' — p50/p99 of
    admission -> cadence dispatch."""

    WAIT_SAMPLES_CAP = 4096

    def __init__(self, clock=monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.ticks = 0
        self.dispatch_ticks = 0
        self.idle_ticks = 0
        self.occupancy_hist: dict = {}
        self.launched_sigs = 0
        self.pad_fill_sigs = 0
        self.generation_drops = 0
        self.expiries = 0
        self.expired_sigs = 0
        self.fallbacks = 0
        self._waits: deque = deque(maxlen=self.WAIT_SAMPLES_CAP)
        self._first_tick_t: float | None = None
        self._last_tick_t: float | None = None

    def note_tick(self, occupied: int, armed: bool) -> None:
        with self._lock:
            now = self._clock()
            if self._first_tick_t is None:
                self._first_tick_t = now
            self._last_tick_t = now
            self.ticks += 1
            if armed:
                self.dispatch_ticks += 1
            else:
                self.idle_ticks += 1
            self.occupancy_hist[occupied] = \
                self.occupancy_hist.get(occupied, 0) + 1

    def note_dispatch(self, total_sigs: int, fill_sigs: int,
                      waits) -> None:
        with self._lock:
            self.launched_sigs += total_sigs
            self.pad_fill_sigs += fill_sigs
            self._waits.extend(waits)

    def note_generation_drop(self) -> None:
        with self._lock:
            self.generation_drops += 1

    def note_expiry(self, sigs: int) -> None:
        with self._lock:
            self.expiries += 1
            self.expired_sigs += sigs

    def note_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1

    def snapshot(self, *, enabled: bool, depth: int) -> dict:
        with self._lock:
            span = 0.0
            if self._first_tick_t is not None and self.ticks > 1:
                span = self._last_tick_t - self._first_tick_t
            waits = sorted(self._waits)
            return {
                "enabled": enabled,
                "depth": depth,
                "ticks": self.ticks,
                "dispatch_ticks": self.dispatch_ticks,
                "idle_ticks": self.idle_ticks,
                "tick_rate_hz": round((self.ticks - 1) / span, 3)
                if span > 0 else 0.0,
                "occupancy_hist": {str(k): v for k, v
                                   in sorted(self.occupancy_hist.items())},
                "pad_fill": {
                    "sigs": self.pad_fill_sigs,
                    "launched_sigs": self.launched_sigs,
                    "ratio": round(self.pad_fill_sigs / self.launched_sigs,
                                   4) if self.launched_sigs else 0.0,
                },
                "generation": {
                    "drops": self.generation_drops,
                    "expiries": self.expiries,
                    "expired_sigs": self.expired_sigs,
                },
                "fallbacks": self.fallbacks,
                "queue_wait": {
                    "n": len(waits),
                    "p50_ms": round(_percentile(waits, 0.5) * 1e3, 3),
                    "p99_ms": round(_percentile(waits, 0.99) * 1e3, 3),
                },
            }


class RingSlot:
    """One buffer position of the ring.  ``generation`` is bumped on
    every arm and every invalidation; a flight holds the generation it
    was armed under and its verdict applies only on exact match —
    Python ints never wrap, and slot REUSE (the ring cycling back to
    index 0) is exactly the case the tag exists for."""

    __slots__ = ("index", "generation")

    def __init__(self, index: int):
        self.index = index
        self.generation = 0


class _Flight:
    """An armed launch in the device pipeline: the slot + generation it
    was armed under, the batch, and the guarded fetch closure."""

    __slots__ = ("slot", "generation", "batch", "fetch", "key",
                 "dispatched_at", "dispatch_s", "sigs")

    def __init__(self, slot, generation, batch, fetch, key,
                 dispatched_at, dispatch_s, sigs):
        self.slot = slot
        self.generation = generation
        self.batch = batch
        self.fetch = fetch
        self.key = key
        self.dispatched_at = dispatched_at
        self.dispatch_s = dispatch_s
        self.sigs = sigs


class CadenceRing:
    """The resident cadence loop.  Runs ON the engine thread
    (``VerifyEngine._run`` calls :meth:`run` before falling back to the
    staged loop), so every engine-side invariant — single consumer,
    reply-once, pack worker streaming — carries over unchanged.

    Tick body (see :meth:`_tick_once`; the graftlint ring rule pins the
    discipline — no unbounded waits, no unwarmed-shape launches):

      1. expire: any flight uncollected past its deadline window is
         re-resolved on the host and its generation invalidated, so the
         late device verdict is provably discarded;
      2. collect: when the ring is full (or nothing new arrived), the
         oldest flight's verdict is fetched under the guard and applied
         iff its generation still matches;
      3. arm: a free slot takes the scheduler's per-tick quota
         (pad-filled from the bulk backlog) through the engine's pack
         worker, dispatched under the ``tick:`` guard class.

    Pacing is load-adaptive between MIN_TICK_S and MAX_TICK_S: armed or
    backlogged ticks run flat-out at MIN_TICK_S; idle ticks back off
    exponentially, and a fully-idle ring parks INSIDE
    ``Scheduler.next_tick``'s bounded wait so a fresh latency request
    wakes it immediately rather than eating a full idle interval."""

    MIN_TICK_S = 0.002
    MAX_TICK_S = 0.25
    # A flight uncollected this many multiples of its guard deadline is
    # expired (host re-resolve + generation bump).  The guard already
    # bounds the FETCH; expiry bounds the verdict of a flight the loop
    # never got back to — the one the guard cannot see.
    EXPIRY_DEADLINES = 2.0
    DEFAULT_EXPIRY_S = 30.0

    def __init__(self, engine, *, depth: RingDepth | None = None,
                 tick_s: float | None = None,
                 expiry_s: float | None = None,
                 clock=monotonic, wait=None):
        self.engine = engine
        self.depth = depth if depth is not None else RingDepth()
        if tick_s is None:
            raw = os.environ.get(ENV_TICK_S)
            if raw:
                try:
                    tick_s = float(raw)
                except ValueError:
                    tick_s = None
        self.pinned_tick_s = tick_s
        self.expiry_s = expiry_s
        self.stats = CadenceStats(clock=clock)
        self.enabled = True
        self._clock = clock
        self._wait = wait if wait is not None else engine._stopped.wait
        self._slots = [RingSlot(i) for i in range(max(RingDepth.DEPTHS))]
        self._next_slot = 0
        self._pending: deque = deque()  # _Flight, oldest first
        self._idle_streak = 0

    # -- public --------------------------------------------------------------

    def snapshot(self) -> dict:
        out = self.stats.snapshot(enabled=self.enabled,
                                  depth=self.depth.depth())
        out["depth_trainer"] = self.depth.snapshot()
        return out

    def run(self) -> None:
        """The cadence loop; returns on engine stop (after draining every
        in-flight verdict) or on wedge fallback (``enabled`` False, all
        generations re-resolved — the staged loop takes over with no
        reply outstanding)."""
        engine = self.engine
        log.info("cadence: ring engaged (depth %d)", self.depth.depth())
        while self.enabled and not engine._stopped.is_set():
            t0 = self._clock()
            armed = self._tick_once(t0)
            occupied = len(self._pending)
            self.stats.note_tick(occupied, armed)
            self._note_occupancy(occupied)
            if not self.enabled or engine._stopped.is_set():
                break
            interval = self._interval(armed, occupied)
            elapsed = self._clock() - t0
            if occupied == 0 and not armed:
                # Fully idle: park in the scheduler's bounded wait so a
                # fresh offer wakes the ring immediately.
                launch = engine._sched.next_tick(self._quota_sigs(),
                                                 timeout=interval)
                if launch is not None and self._take_launch(launch):
                    # The park-path arm IS a dispatch tick — record it so
                    # tick accounting matches what actually launched.
                    self.stats.note_tick(len(self._pending), True)
                    self._note_occupancy(len(self._pending))
            elif interval > elapsed:
                self._wait(interval - elapsed)
        if self.enabled:
            # Clean stop: every accepted request still gets its reply.
            while self._pending:
                self._collect_oldest()
        log.info("cadence: ring disengaged (%s)",
                 "stopped" if self.enabled else "wedge fallback")

    # -- tick body -----------------------------------------------------------

    def _tick_once(self, now: float) -> bool:
        """One cadence tick; True iff a slot was armed this tick."""
        self._expire_overdue(now)
        if not self.enabled:
            return False
        k = self.depth.depth()
        if len(self._pending) >= k:
            self._collect_oldest()
        if not self.enabled:
            return False
        armed = False
        if len(self._pending) < k:
            launch = self.engine._sched.next_tick(self._quota_sigs())
            if launch is not None:
                armed = self._take_launch(launch)
        if not armed and self._pending:
            # Nothing new arrived: make progress on the oldest verdict
            # so light load sees one-tick reply latency, not depth-k.
            self._collect_oldest()
        return armed

    def _quota_sigs(self) -> int:
        return self.engine._shapes.launch_cap

    def _take_launch(self, launch) -> bool:
        """Route one per-tick quota: BLS heads run inline after a full
        drain (a QC aggregate is one check — nothing to keep resident);
        Ed25519 quotas arm a ring slot."""
        engine = self.engine
        engine._trace_queue_waits(launch)
        if launch.kind == "bls":
            while self._pending:
                self._collect_oldest()
                if not self.enabled:
                    return False
            (item,) = launch.items
            with engine._tracer.span("device", kind="bls",
                                     rid=item.request.request_id):
                engine._execute_bls(item)
            return True
        return self._arm(launch)

    def _arm(self, launch) -> bool:
        """Arm the next ring slot with this launch: stream the batch
        through the engine's pack worker, dispatch under the ``tick:``
        guard class, and tag the flight with the slot's new
        generation."""
        engine = self.engine
        batch = launch.items
        key = self._tick_key(batch)
        slot = self._slots[self._next_slot]
        self._next_slot = (self._next_slot + 1) % max(RingDepth.DEPTHS)
        slot.generation += 1
        gen = slot.generation
        fut = engine._pack_pool.submit(engine._pack, batch)
        t0 = self._clock()
        try:
            # pack wait + device dispatch under one guarded deadline —
            # the identical discipline to the staged _dispatch_one.
            fetch = engine._guarded(key, lambda: fut.result()())
        except WedgedLaunch:
            slot.generation += 1  # invalidate before the ladder answers
            self._fallback(batch, key, stage="dispatch")
            return False
        except Exception:
            log.exception("cadence: pack/dispatch failed")
            slot.generation += 1
            for p in batch:
                p.reply_fn([False] * len(p.request.msgs))
            engine._trace_replies(batch)
            return False
        dispatch_s = self._clock() - t0
        sigs = sum(len(p.request.msgs) for p in batch)
        self._pending.append(_Flight(slot, gen, batch, fetch, key,
                                     self._clock(), dispatch_s, sigs))
        fill = launch.items[len(launch.items) - launch.fill_count:]
        now = self._clock()
        self.stats.note_dispatch(
            sigs, sum(len(p.request.msgs) for p in fill),
            [now - p.enqueued_at for p in batch])
        if engine._tracer.enabled:
            engine._tracer.event("dispatch", reqs=len(batch),
                                 cadence=True)
        return True

    def _collect_oldest(self) -> None:
        """Fetch the oldest flight's verdict under the guard and apply
        it iff the generation still matches (stale => counted drop, no
        reply — whoever bumped the generation already answered)."""
        engine = self.engine
        fl = self._pending.popleft()
        try:
            mask = engine._guarded(fl.key, fl.fetch)
        except WedgedLaunch:
            if fl.generation == fl.slot.generation:
                fl.slot.generation += 1
                self._fallback(fl.batch, fl.key, stage="fetch")
            else:
                self.stats.note_generation_drop()
            return
        except Exception:
            if fl.generation != fl.slot.generation:
                self.stats.note_generation_drop()
                return
            log.exception("cadence: fetch failed")
            fl.slot.generation += 1
            for p in fl.batch:
                p.reply_fn([False] * len(p.request.msgs))
            engine._trace_replies(fl.batch)
            return
        if fl.generation != fl.slot.generation:
            # Re-armed or expired since dispatch: the verdict is stale
            # BY TAG, regardless of what the device computed.
            self.stats.note_generation_drop()
            return
        wall = self._clock() - fl.dispatched_at
        self.depth.observe(fl.dispatch_s, wall)
        if engine._tracer.enabled:
            engine._tracer.event("device", dur_ms=wall * 1e3,
                                 reqs=len(fl.batch), sigs=fl.sigs,
                                 cadence=True)
        off = 0
        for p in fl.batch:
            n = len(p.request.msgs)
            p.reply_fn([bool(b) for b in mask[off:off + n]])
            off += n
        engine._trace_replies(fl.batch)

    # -- expiry / fallback ---------------------------------------------------

    def _flight_expiry_s(self, fl) -> float:
        if self.expiry_s is not None:
            return self.expiry_s
        guard = self.engine._guard
        if guard is not None:
            return self.EXPIRY_DEADLINES * guard.deadlines.deadline_s(fl.key)
        return self.DEFAULT_EXPIRY_S

    def _expire_overdue(self, now: float) -> None:
        """Host-re-resolve every flight uncollected past its window and
        invalidate its generation — the late fetch becomes a counted
        drop instead of a double reply."""
        for fl in list(self._pending):
            if fl.generation != fl.slot.generation:
                continue  # already invalidated; drops at collect
            if now - fl.dispatched_at <= self._flight_expiry_s(fl):
                continue
            fl.slot.generation += 1
            self.stats.note_expiry(fl.sigs)
            log.warning("cadence: flight %s expired uncollected; "
                        "re-resolving on host", fl.key)
            self._host_resolve(fl.batch)

    def _host_resolve(self, batch) -> None:
        """Answer a batch without the device: latency-class requests get
        host reference masks (bit-identical by the same property tests
        the wedge ladder leans on), bulk gets BUSY + retry-after."""
        from ..crypto import ref_ed25519 as ref

        engine = self.engine
        for p in batch:
            if p.cls == vsched.BULK:
                p.reply_fn(BusyReply(engine.retry_after_ms(vsched.BULK)))
                continue
            p.reply_fn([bool(ref.verify(pk, m, s))
                        for m, pk, s in zip(p.request.msgs, p.request.pks,
                                            p.request.sigs)])
        engine._trace_replies(batch)

    def _fallback(self, batch, key: str, stage: str) -> None:
        """A cadence launch wedged: ride the engine's existing ladder for
        the wedged batch (host masks / BUSY, quarantine, crash-only
        reboot), re-resolve every OTHER in-flight generation on the
        host, and disengage — VerifyEngine._run falls through to the
        staged loop."""
        self.stats.note_fallback()
        self.enabled = False
        self.engine._wedge_ladder(batch, key, stage=stage)
        for fl in list(self._pending):
            if fl.generation == fl.slot.generation:
                fl.slot.generation += 1
                self._host_resolve(fl.batch)
        # Flights stay referenced nowhere: their device verdicts die with
        # the reboot's teardown; replies are already out exactly once.
        self._pending.clear()

    # -- pacing --------------------------------------------------------------

    def _interval(self, armed: bool, occupied: int) -> float:
        if self.pinned_tick_s is not None:
            return self.pinned_tick_s
        sched = self.engine._sched
        backlog = sched.queued_sigs(vsched.LATENCY) + \
            sched.queued_sigs(vsched.BULK)
        if armed or occupied or backlog:
            self._idle_streak = 0
            return self.MIN_TICK_S
        self._idle_streak += 1
        return min(self.MAX_TICK_S,
                   self.MIN_TICK_S * (2 ** min(self._idle_streak, 10)))

    # -- helpers -------------------------------------------------------------

    def _tick_key(self, batch) -> str:
        """Per-tick guard deadline class: same deduped power-of-two shape
        bucket as the staged key, under the ``tick:`` prefix so the
        guard applies the warm grace (the ring never launches an
        unwarmed shape) instead of the compile budget."""
        staged = self.engine._guard_key(batch)
        return "tick:" + staged.split(":", 1)[1]

    def _note_occupancy(self, occupied: int) -> None:
        adm = getattr(self.engine._sched, "admission", None)
        if adm is not None:
            adm.note_ring_occupancy(occupied, self.depth.depth())

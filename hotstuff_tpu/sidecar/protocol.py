"""Wire protocol between consensus nodes (C++) and the TPU verify sidecar.

The sidecar plays the role the reference gives its in-process
``SignatureService`` + ``Signature::verify_batch`` (crypto/src/lib.rs:210-254):
a node ships the votes of a quorum certificate to a long-lived process that
owns the accelerator, and gets back a per-signature validity mask.  Because
the node data plane is C++ and the device engine is JAX, the boundary is a
localhost TCP socket with length-delimited frames — the same framing idiom
the reference uses between replicas (4-byte length prefix,
network/src/receiver.rs:70).

Frame layout (all integers little-endian unless noted):

    [u32 BIG-endian frame length][payload]

Request payload:
    u8  opcode      1 = VERIFY_BATCH, 2 = PING
    u32 request id  echoed in the reply (lets a client pipeline requests)
    u32 count N     number of signature records (0 for PING)
    u16 msg_len M   byte length of each message (digests: 32)
    [32 bytes context tag — protocol v5, OPTIONAL: the block digest this
     verify serves; all-zero = none; discriminated by frame length]
    N * (M bytes msg | 32 bytes pubkey | 64 bytes signature)

Reply payload:
    u8  opcode echo
    u32 request id echo
    u32 count N
    N bytes of 0/1 validity
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

OP_VERIFY_BATCH = 1
OP_PING = 2
# BLS extension (the reference's bls branch capability): aggregate verify
# over one common message (the QC shape), G1 pks (96 B uncompressed) and
# G2 signatures (192 B uncompressed), plus signing for the node's
# SignatureService when the committee runs scheme=bls.
OP_BLS_VERIFY_AGG = 3
OP_BLS_SIGN = 4
# Per-vote variant used by the C++ node (it cannot aggregate G2 points):
# the sidecar aggregates the signatures itself, then runs the same
# common-message 2-pairing check. Reply: one 0/1 byte.
OP_BLS_VERIFY_VOTES = 5
# Multi-digest variant (the TC shape: per-vote signatures over DISTINCT
# digests, consensus/src/messages.rs:307-313): one RPC, verified as
# prod e(pk_i, H(m_i)) == e(g1, sum sig_i) under a single final
# exponentiation. Reply: one 0/1 byte.
OP_BLS_VERIFY_MULTI = 6
# Protocol v2 (verifysched): request CLASS rides in the opcode, so v1
# clients keep their correct latency-class behavior without a flag day.
# OP_VERIFY_BATCH is the latency class (consensus QC/TC verifies, bounds
# commit latency); OP_VERIFY_BULK is the bulk class (mempool / offchain
# batch verifies — throughput-bound, yields to latency work).  Same
# frame layout as OP_VERIFY_BATCH in both directions.
OP_VERIFY_BULK = 7
# Scheduler-telemetry snapshot: header-only request (count 0, like
# PING); the reply body is one UTF-8 JSON object (the engine's
# stats_snapshot() dict — schema in sidecar/sched/stats.py), framed by
# encode_reply_raw with count = body length.
OP_STATS = 8
# Protocol v3 (graftchaos): configure the sidecar's fault-injection hook.
# The request body is one UTF-8 JSON object (count = body length, msg_len
# 0; spec schema in sidecar/service.ChaosState: bounded reply delay,
# forced connection drops, forced queue-full sheds, clear).  Reply is a
# one-byte mask: [1] applied, [0] refused (server runs without --chaos).
# Only honored behind the explicit --chaos flag — a production sidecar
# cannot be degraded over the wire.
OP_CHAOS = 9
# Protocol v4 (graftsurge): explicit BUSY reply.  When a class queue is
# full (or the surge admission controller sheds), the sidecar answers
# with OP_BUSY — request id echoed, count = 2, body one u16 LE
# retry-after hint in milliseconds — instead of the v2/v3 empty-count
# echo of the request opcode.  Reply-only: a request frame carrying
# OP_BUSY is malformed.  Clients back off for ~the hint (python raises
# SidecarOverloaded with retry_after_ms; the C++ node falls back to host
# verify, its in-flight AIMD already pacing resubmission).
OP_BUSY = 10
# Protocol v6 (graftfleet): optional session HELLO.  A client that wants
# a tenant identity (a node in a shared sidecar fleet) sends OP_HELLO
# once after connecting: count carries the CLIENT's protocol version,
# msg_len the tenant-id byte length, body the tenant id (UTF-8,
# [A-Za-z0-9._-], 1..TENANT_MAX_LEN bytes).  The reply echoes the
# SERVER's protocol version (one byte) followed by the accepted tenant
# id, so a version-skewed pair is visible at session start instead of
# mid-verify.  HELLO is OPTIONAL: a connection that never sends one is
# mapped to DEFAULT_TENANT and behaves exactly like a v5 client — every
# pre-fleet client and test stays valid without a flag day.
OP_HELLO = 11

# Version of this wire protocol, bumped when the opcode set or any frame
# layout changes (v2: OP_VERIFY_BULK + OP_STATS; v3: OP_CHAOS; v4:
# OP_BUSY retry-after replies; v5: the graftscope context tag below; v6:
# the graftfleet OP_HELLO tenant handshake).
# Mirrored by the C++ client's kProtocolVersion; graftlint's wire
# cross-checker pins the pair.  Replies an unknown-opcode ValueError on
# older peers rather than desyncing, so the constant is documentation +
# lint anchor, not a handshake — OP_HELLO echoes it for visibility but
# no version is rejected.
PROTOCOL_VERSION = 6

# graftfleet tenant identity: connections that never send OP_HELLO — the
# unix-era single-node clients — act under this tenant, so the fairness
# layer sees exactly one tenant and scheduling is unchanged.
DEFAULT_TENANT = "default"
TENANT_MAX_LEN = 64
_TENANT_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")

# Protocol v5 (graftscope): OP_VERIFY_BATCH / OP_VERIFY_BULK — and, since
# the BLS trace-parity work, OP_BLS_VERIFY_VOTES / OP_BLS_VERIFY_MULTI —
# requests may carry a 32-byte CONTEXT TAG between the fixed header and
# the records: the block digest whose certificate this verify serves.
# The sidecar tags its admit/queue/pack/dispatch/device/reply spans with
# it, which is what lets obs/trace.py nest the sidecar stage chain
# (device time included) inside that block's verify segment in
# logs/trace.json — for scheme=bls runs exactly like EdDSA ones.
#
# The tag is OPTIONAL and self-describing by frame length: a verify
# payload is either header + N records (legacy, ctx None) or header +
# 32 tag bytes + N records — unambiguous because an Ed25519 record is
# msg_len + 96 >= 96 bytes and a BLS record is >= 288 bytes, so 32
# extra bytes can never alias a record count.
# Writers emit the tag only when they HAVE a block context (the C++
# client's no-context frames stay byte-identical to v4, so a node
# upgraded before its sidecar keeps verifying), an ALL-ZERO tag is
# tolerated and decodes as ctx None, and legacy tag-less frames stay
# valid forever.
CTX_LEN = 32
ZERO_CTX = b"\x00" * CTX_LEN

# Backpressure contract: v2/v3 shed replies were an EMPTY body (count 0)
# for a request that carried records — unambiguous, because a real
# verdict mask always has exactly the request's record count.  v4 sheds
# reply OP_BUSY with a retry-after hint instead; clients keep accepting
# the empty-body form so a version-skewed sidecar still reads as
# overload, never as a verdict.

_HDR = struct.Struct("<BIIH")  # opcode, request id, count, msg_len
_REPLY_HDR = struct.Struct("<BII")
_BUSY_BODY = struct.Struct("<H")  # retry-after hint, ms

MAX_FRAME = 64 * 1024 * 1024

# Fixed record sizes shared with the C++ node (crypto/crypto.hpp,
# crypto/sidecar_client.cpp).  graftlint's wire cross-checker asserts the
# two sides agree — edit BOTH or the gate fails.
DIGEST_LEN = 32       # SHA-512/32 digests: the only msg the node sends
ED_PK_LEN = 32
ED_SIG_LEN = 64
BLS_PK_LEN = 96
BLS_SIG_LEN = 192
BLS_SK_LEN = 48


@dataclass
class VerifyRequest:
    request_id: int
    msgs: list
    pks: list
    sigs: list
    # graftscope (protocol v5): the 32-byte block-digest context tag, or
    # None when the frame carried none (legacy frame or all-zero tag).
    ctx: bytes | None = None


@dataclass
class BlsAggRequest:
    request_id: int
    msg: bytes
    agg_sig: bytes        # 192 B uncompressed G2
    pks: list             # n x 96 B uncompressed G1


@dataclass
class BlsSignRequest:
    request_id: int
    msg: bytes
    sk: bytes             # 48 B big-endian scalar


@dataclass
class BlsVotesRequest:
    request_id: int
    msg: bytes
    pks: list             # n x 96 B uncompressed G1
    sigs: list            # n x 192 B uncompressed G2
    # graftscope (protocol v5): block-digest context tag, as on
    # VerifyRequest — BLS spans join block traces like EdDSA ones.
    ctx: bytes | None = None


@dataclass
class BlsMultiRequest:
    request_id: int
    msgs: list            # n x msg_len digests (distinct per vote)
    pks: list             # n x 96 B uncompressed G1
    sigs: list            # n x 192 B uncompressed G2
    ctx: bytes | None = None


@dataclass
class ChaosRequest:
    request_id: int
    spec: dict            # fault knobs (service.ChaosState.configure)


@dataclass
class HelloRequest:
    request_id: int
    version: int          # the CLIENT's protocol version (informational)
    tenant: str           # validated tenant id ([A-Za-z0-9._-]{1,64})


def validate_tenant(raw) -> str:
    """Tenant-id validation shared by the codec and the server: UTF-8
    (or str), 1..TENANT_MAX_LEN bytes, charset [A-Za-z0-9._-].  Raises
    ValueError on anything else — a tenant id keys scheduler lanes and
    telemetry dicts, so garbage must die at the frame boundary."""
    if isinstance(raw, bytes):
        try:
            tenant = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            raise ValueError(f"bad tenant id: {e}")
    else:
        tenant = raw
    if not tenant or len(tenant.encode("utf-8")) > TENANT_MAX_LEN:
        raise ValueError(
            f"bad tenant id length: 1..{TENANT_MAX_LEN} bytes required")
    if not set(tenant) <= _TENANT_OK:
        raise ValueError("bad tenant id: charset is [A-Za-z0-9._-]")
    return tenant


def encode_request(request_id: int, msgs, pks, sigs,
                   opcode: int = OP_VERIFY_BATCH,
                   ctx: bytes | None = None) -> bytes:
    """``ctx`` (protocol v5) attaches the 32-byte block-digest context
    tag after the header; None emits the legacy tag-less frame (an
    all-zero ctx is legal and decodes back as None)."""
    n = len(msgs)
    assert len(pks) == n and len(sigs) == n
    assert opcode in (OP_VERIFY_BATCH, OP_VERIFY_BULK)
    msg_len = len(msgs[0]) if n else 0
    parts = [_HDR.pack(opcode, request_id, n, msg_len)]
    if ctx is not None:
        assert len(ctx) == CTX_LEN
        parts.append(ctx)
    for m, p, s in zip(msgs, pks, sigs):
        assert len(m) == msg_len and len(p) == ED_PK_LEN \
            and len(s) == ED_SIG_LEN
        parts.append(m)
        parts.append(p)
        parts.append(s)
    payload = b"".join(parts)
    return struct.pack(">I", len(payload)) + payload


def encode_ping(request_id: int = 0) -> bytes:
    payload = _HDR.pack(OP_PING, request_id, 0, 0)
    return struct.pack(">I", len(payload)) + payload


def encode_stats_request(request_id: int = 0) -> bytes:
    """Header-only telemetry request (count 0, like PING)."""
    payload = _HDR.pack(OP_STATS, request_id, 0, 0)
    return struct.pack(">I", len(payload)) + payload


def encode_stats_reply(request_id: int, snapshot: dict) -> bytes:
    """Stats snapshot dict -> raw-reply frame (UTF-8 JSON body)."""
    import json

    body = json.dumps(snapshot, sort_keys=True).encode("utf-8")
    # graftlint: disable=unverified-flow-to-sink (locally-built telemetry snapshot, carries no verdict bits)
    return encode_reply_raw(OP_STATS, request_id, body)


def decode_stats_body(body: bytes) -> dict:
    """Raw OP_STATS reply body -> snapshot dict (ValueError on garbage,
    same contract as decode_request)."""
    import json

    try:
        out = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise ValueError(f"bad stats body: {e}")
    if not isinstance(out, dict):
        raise ValueError("stats body is not a JSON object")
    return out


def encode_busy_reply(request_id: int, retry_after_ms: int) -> bytes:
    """Queue-full shed -> OP_BUSY reply carrying the retry-after hint
    (clamped to the u16 range; 0 means 'immediately' and is legal)."""
    ms = max(0, min(0xFFFF, int(retry_after_ms)))
    return encode_reply_raw(OP_BUSY, request_id, _BUSY_BODY.pack(ms))


def decode_busy_body(body: bytes) -> int:
    """OP_BUSY reply body -> retry-after ms (ValueError on garbage)."""
    if len(body) != _BUSY_BODY.size:
        raise ValueError(f"bad busy body: {len(body)} byte(s)")
    return _BUSY_BODY.unpack(body)[0]


def encode_hello_request(request_id: int, tenant: str,
                         version: int = PROTOCOL_VERSION) -> bytes:
    """Session HELLO (protocol v6): tenant id in the body, the client's
    protocol version riding the count field (header-only otherwise)."""
    body = validate_tenant(tenant).encode("utf-8")
    payload = _HDR.pack(OP_HELLO, request_id, version, len(body)) + body
    return struct.pack(">I", len(payload)) + payload


def encode_hello_reply(request_id: int, tenant: str) -> bytes:
    """HELLO ack: one byte of SERVER protocol version, then the accepted
    tenant id — the version echo that makes wire skew visible at session
    start."""
    body = bytes([PROTOCOL_VERSION]) + tenant.encode("utf-8")
    return encode_reply_raw(OP_HELLO, request_id, body)


def decode_hello_body(body: bytes):
    """HELLO reply body -> (server protocol version, tenant id);
    ValueError on garbage."""
    if not body:
        raise ValueError("empty hello reply body")
    return body[0], validate_tenant(body[1:])


def encode_chaos_request(request_id: int, spec: dict) -> bytes:
    """Chaos-hook configuration -> request frame (UTF-8 JSON body riding
    the count field as its byte length, like the OP_STATS reply)."""
    import json

    body = json.dumps(spec, sort_keys=True).encode("utf-8")
    payload = _HDR.pack(OP_CHAOS, request_id, len(body), 0) + body
    return struct.pack(">I", len(payload)) + payload


def encode_bls_agg_request(request_id: int, msg: bytes, agg_sig: bytes,
                           pks) -> bytes:
    assert len(agg_sig) == BLS_SIG_LEN
    assert all(len(p) == BLS_PK_LEN for p in pks)
    payload = (_HDR.pack(OP_BLS_VERIFY_AGG, request_id, len(pks), len(msg))
               + msg + agg_sig + b"".join(pks))
    return struct.pack(">I", len(payload)) + payload


def encode_bls_sign_request(request_id: int, msg: bytes, sk: bytes) -> bytes:
    assert len(sk) == BLS_SK_LEN
    payload = (_HDR.pack(OP_BLS_SIGN, request_id, 1, len(msg)) + msg + sk)
    return struct.pack(">I", len(payload)) + payload


def encode_bls_votes_request(request_id: int, msg: bytes, pks, sigs,
                             ctx: bytes | None = None) -> bytes:
    """``ctx`` (protocol v5) rides between header and the shared message,
    the same slot as OP_VERIFY_BATCH; None emits the legacy frame."""
    assert len(pks) == len(sigs)
    recs = b"".join(p + s for p, s in zip(pks, sigs))
    parts = [_HDR.pack(OP_BLS_VERIFY_VOTES, request_id, len(pks), len(msg))]
    if ctx is not None:
        assert len(ctx) == CTX_LEN
        parts.append(ctx)
    parts.append(msg)
    parts.append(recs)
    payload = b"".join(parts)
    return struct.pack(">I", len(payload)) + payload


def encode_bls_multi_request(request_id: int, msgs, pks, sigs,
                             ctx: bytes | None = None) -> bytes:
    n = len(msgs)
    assert len(pks) == n and len(sigs) == n
    msg_len = len(msgs[0]) if n else 0
    assert all(len(m) == msg_len for m in msgs)
    recs = b"".join(m + p + s for m, p, s in zip(msgs, pks, sigs))
    parts = [_HDR.pack(OP_BLS_VERIFY_MULTI, request_id, n, msg_len)]
    if ctx is not None:
        assert len(ctx) == CTX_LEN
        parts.append(ctx)
    parts.append(recs)
    payload = b"".join(parts)
    return struct.pack(">I", len(payload)) + payload


# graftlint: sanitizes=frame-structure
def decode_request(payload: bytes):
    """payload (no length prefix) -> (opcode, request dataclass).

    Contract: any malformed frame raises ValueError (callers close the
    connection on it); nothing else escapes."""
    try:
        opcode, request_id, n, msg_len = _HDR.unpack_from(payload, 0)
    except struct.error as e:
        raise ValueError(f"short frame: {e}")
    if opcode not in (OP_VERIFY_BATCH, OP_VERIFY_BULK, OP_PING, OP_STATS,
                      OP_BLS_VERIFY_AGG, OP_BLS_SIGN, OP_BLS_VERIFY_VOTES,
                      OP_BLS_VERIFY_MULTI, OP_CHAOS, OP_HELLO):
        raise ValueError(f"unknown opcode {opcode}")
    if opcode in (OP_PING, OP_STATS):
        return opcode, VerifyRequest(request_id, [], [], [])
    if opcode == OP_HELLO:
        # count = client protocol version, msg_len = tenant byte length;
        # a trailing-garbage or truncated body is malformed like any
        # other frame (never a silent partial tenant id).
        body = payload[_HDR.size:]
        if len(body) != msg_len:
            raise ValueError(
                f"bad hello frame: {len(body)} body byte(s), "
                f"msg_len {msg_len}")
        return opcode, HelloRequest(request_id, n, validate_tenant(body))
    if opcode == OP_CHAOS:
        import json

        body = payload[_HDR.size:]
        if len(body) != n:
            raise ValueError("bad chaos frame")
        try:
            spec = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise ValueError(f"bad chaos body: {e}")
        if not isinstance(spec, dict):
            raise ValueError("chaos body is not a JSON object")
        return opcode, ChaosRequest(request_id, spec)
    if opcode == OP_BLS_VERIFY_AGG:
        off = _HDR.size
        msg = payload[off:off + msg_len]
        off += msg_len
        agg = payload[off:off + BLS_SIG_LEN]
        off += BLS_SIG_LEN
        if len(payload) != off + n * BLS_PK_LEN:
            raise ValueError("bad BLS aggregate frame")
        pks = [payload[off + i * BLS_PK_LEN:off + (i + 1) * BLS_PK_LEN]
               for i in range(n)]
        return opcode, BlsAggRequest(request_id, msg, agg, pks)
    if opcode == OP_BLS_SIGN:
        off = _HDR.size
        msg = payload[off:off + msg_len]
        sk = payload[off + msg_len:off + msg_len + BLS_SK_LEN]
        if len(payload) != off + msg_len + BLS_SK_LEN:
            raise ValueError("bad BLS sign frame")
        return opcode, BlsSignRequest(request_id, msg, sk)
    if opcode == OP_BLS_VERIFY_VOTES:
        off = _HDR.size
        rec = BLS_PK_LEN + BLS_SIG_LEN
        # v5 context tag: frame length discriminates (a BLS record is
        # 288 bytes, so 32 tag bytes can never alias a record count).
        ctx = None
        if len(payload) == off + CTX_LEN + msg_len + n * rec:
            tag = payload[off:off + CTX_LEN]
            ctx = None if tag == ZERO_CTX else tag
            off += CTX_LEN
        msg = payload[off:off + msg_len]
        off += msg_len
        if len(payload) != off + n * rec:
            raise ValueError("bad BLS votes frame")
        pks, sigs = [], []
        for i in range(n):
            base = off + i * rec
            pks.append(payload[base:base + BLS_PK_LEN])
            sigs.append(payload[base + BLS_PK_LEN:base + rec])
        return opcode, BlsVotesRequest(request_id, msg, pks, sigs, ctx=ctx)
    if opcode == OP_BLS_VERIFY_MULTI:
        off = _HDR.size
        rec = msg_len + BLS_PK_LEN + BLS_SIG_LEN
        ctx = None
        if len(payload) == off + CTX_LEN + n * rec:
            tag = payload[off:off + CTX_LEN]
            ctx = None if tag == ZERO_CTX else tag
            off += CTX_LEN
        if len(payload) != off + n * rec:
            raise ValueError("bad BLS multi frame")
        msgs, pks, sigs = [], [], []
        for i in range(n):
            base = off + i * rec
            msgs.append(payload[base:base + msg_len])
            pks.append(payload[base + msg_len:base + msg_len + BLS_PK_LEN])
            sigs.append(payload[base + msg_len + BLS_PK_LEN:base + rec])
        return opcode, BlsMultiRequest(request_id, msgs, pks, sigs, ctx=ctx)
    rec = msg_len + ED_PK_LEN + ED_SIG_LEN
    off = _HDR.size
    # Protocol v5 context tag: frame length discriminates (a record is
    # msg_len + 96 >= 96 bytes, so the 32 tag bytes never alias one).
    ctx = None
    if len(payload) == off + CTX_LEN + n * rec:
        tag = payload[off:off + CTX_LEN]
        ctx = None if tag == ZERO_CTX else tag
        off += CTX_LEN
    elif len(payload) != off + n * rec:
        raise ValueError(
            f"bad frame: expected {off + n * rec} "
            f"(or +{CTX_LEN} tagged) bytes, got {len(payload)}")
    msgs, pks, sigs = [], [], []
    for _ in range(n):
        msgs.append(payload[off:off + msg_len])
        off += msg_len
        pks.append(payload[off:off + ED_PK_LEN])
        off += ED_PK_LEN
        sigs.append(payload[off:off + ED_SIG_LEN])
        off += ED_SIG_LEN
    return opcode, VerifyRequest(request_id, msgs, pks, sigs, ctx=ctx)


def encode_reply(opcode: int, request_id: int, mask) -> bytes:
    body = bytes(bytearray(int(bool(b)) for b in mask))
    payload = _REPLY_HDR.pack(opcode, request_id, len(body)) + body
    return struct.pack(">I", len(payload)) + payload


def encode_reply_raw(opcode: int, request_id: int, body: bytes) -> bytes:
    """Reply whose body is raw bytes (BLS signatures) rather than a 0/1
    mask; same framing, count = body length."""
    payload = _REPLY_HDR.pack(opcode, request_id, len(body)) + body
    return struct.pack(">I", len(payload)) + payload


def decode_reply(payload: bytes):
    opcode, request_id, n = _REPLY_HDR.unpack_from(payload, 0)
    mask = [bool(b) for b in payload[_REPLY_HDR.size:_REPLY_HDR.size + n]]
    return opcode, request_id, mask


def decode_reply_raw(payload: bytes):
    opcode, request_id, n = _REPLY_HDR.unpack_from(payload, 0)
    return opcode, request_id, payload[_REPLY_HDR.size:_REPLY_HDR.size + n]


def read_frame(sock) -> bytes:
    """Blocking read of one length-delimited frame from a socket."""
    hdr = _read_exact(sock, 4)
    (length,) = struct.unpack(">I", hdr)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return _read_exact(sock, length)


def _read_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        # The bound lives on the socket, not here: every CLIENT sets a
        # connect/recv timeout (SidecarClient), while the server-side
        # reader idles between requests by design — its bound is peer
        # close.  The one shared recv in the tree, hence the suppression.
        # graftlint: disable=unbounded-socket-op
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)

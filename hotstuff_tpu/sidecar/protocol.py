"""Wire protocol between consensus nodes (C++) and the TPU verify sidecar.

The sidecar plays the role the reference gives its in-process
``SignatureService`` + ``Signature::verify_batch`` (crypto/src/lib.rs:210-254):
a node ships the votes of a quorum certificate to a long-lived process that
owns the accelerator, and gets back a per-signature validity mask.  Because
the node data plane is C++ and the device engine is JAX, the boundary is a
localhost TCP socket with length-delimited frames — the same framing idiom
the reference uses between replicas (4-byte length prefix,
network/src/receiver.rs:70).

Frame layout (all integers little-endian unless noted):

    [u32 BIG-endian frame length][payload]

Request payload:
    u8  opcode      1 = VERIFY_BATCH, 2 = PING
    u32 request id  echoed in the reply (lets a client pipeline requests)
    u32 count N     number of signature records (0 for PING)
    u16 msg_len M   byte length of each message (digests: 32)
    N * (M bytes msg | 32 bytes pubkey | 64 bytes signature)

Reply payload:
    u8  opcode echo
    u32 request id echo
    u32 count N
    N bytes of 0/1 validity
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

OP_VERIFY_BATCH = 1
OP_PING = 2

_HDR = struct.Struct("<BIIH")  # opcode, request id, count, msg_len
_REPLY_HDR = struct.Struct("<BII")

MAX_FRAME = 64 * 1024 * 1024


@dataclass
class VerifyRequest:
    request_id: int
    msgs: list
    pks: list
    sigs: list


def encode_request(request_id: int, msgs, pks, sigs) -> bytes:
    n = len(msgs)
    assert len(pks) == n and len(sigs) == n
    msg_len = len(msgs[0]) if n else 0
    parts = [_HDR.pack(OP_VERIFY_BATCH, request_id, n, msg_len)]
    for m, p, s in zip(msgs, pks, sigs):
        assert len(m) == msg_len and len(p) == 32 and len(s) == 64
        parts.append(m)
        parts.append(p)
        parts.append(s)
    payload = b"".join(parts)
    return struct.pack(">I", len(payload)) + payload


def encode_ping(request_id: int = 0) -> bytes:
    payload = _HDR.pack(OP_PING, request_id, 0, 0)
    return struct.pack(">I", len(payload)) + payload


def decode_request(payload: bytes):
    """payload (no length prefix) -> (opcode, VerifyRequest)."""
    opcode, request_id, n, msg_len = _HDR.unpack_from(payload, 0)
    if opcode not in (OP_VERIFY_BATCH, OP_PING):
        raise ValueError(f"unknown opcode {opcode}")
    if opcode == OP_PING:
        return opcode, VerifyRequest(request_id, [], [], [])
    rec = msg_len + 32 + 64
    off = _HDR.size
    if len(payload) != off + n * rec:
        raise ValueError(
            f"bad frame: expected {off + n * rec} bytes, got {len(payload)}")
    msgs, pks, sigs = [], [], []
    for _ in range(n):
        msgs.append(payload[off:off + msg_len])
        off += msg_len
        pks.append(payload[off:off + 32])
        off += 32
        sigs.append(payload[off:off + 64])
        off += 64
    return opcode, VerifyRequest(request_id, msgs, pks, sigs)


def encode_reply(opcode: int, request_id: int, mask) -> bytes:
    body = bytes(bytearray(int(bool(b)) for b in mask))
    payload = _REPLY_HDR.pack(opcode, request_id, len(body)) + body
    return struct.pack(">I", len(payload)) + payload


def decode_reply(payload: bytes):
    opcode, request_id, n = _REPLY_HDR.unpack_from(payload, 0)
    mask = [bool(b) for b in payload[_REPLY_HDR.size:_REPLY_HDR.size + n]]
    return opcode, request_id, mask


def read_frame(sock) -> bytes:
    """Blocking read of one length-delimited frame from a socket."""
    hdr = _read_exact(sock, 4)
    (length,) = struct.unpack(">I", hdr)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return _read_exact(sock, length)


def _read_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)

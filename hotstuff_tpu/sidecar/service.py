"""TPU verify sidecar: a long-lived JAX process owning the accelerator.

Architecture mirrors the reference's ``SignatureService`` actor
(crypto/src/lib.rs:226-254) scaled to a process boundary: connection
threads admit requests into the two-class verifysched scheduler
(``sidecar/sched/``); a single device thread asks the scheduler for
launches, dispatches them down the routed verify path (per-signature
ladders, or the one-MSM RLC program for warmed batch shapes), and fans
replies back out.  Request/response framing in ``protocol.py``.

Scheduling policy (details + rationale in sched/scheduler.py):
  * ``latency`` class (consensus QC/TC verifies, all BLS ops) has strict
    priority — it waits behind at most the launches already in flight;
  * ``bulk`` class (OP_VERIFY_BULK mempool/offchain batches) coalesces
    up to the bulk cap, rides the pad slots of latency launches so it
    drains even under sustained latency load, and carries over whole
    requests that miss a launch budget;
  * both queues are bounded — a full queue is an explicit queue-full
    reply (empty mask), never a blocked connection thread;
  * every launch is counted (OP_STATS returns the telemetry snapshot).

Run:  python -m hotstuff_tpu.sidecar --port 7100 [--mesh N]
"""

from __future__ import annotations

import argparse
import logging
import queue
import socket
import socketserver
import threading
from time import monotonic

import numpy as np

from . import protocol as proto
from . import sched as vsched
from .guard import BusyReply, WedgedLaunch, bisect_poison

log = logging.getLogger("sidecar")

from ..crypto.eddsa import MAX_SUBBATCH  # per-program sub-batch cap

# With bulk mode warmed (--warm-bulk), one coalesced launch drains up to
# this many queued signatures as sub-batches of MAX_SUBBATCH scanned inside
# ONE program (ops/ed25519.verify_packed_chunked) — the tunneled device
# charges a fixed 15-20 ms per dispatch, so scanning beats splitting.  The
# cap bounds both the compiled scan lengths (g <= 16, the same shape
# bench.py measures) and how long a bulk backlog can occupy the engine
# ahead of consensus-latency QC verifies.  Without bulk warmup the launch
# cap stays at MAX_SUBBATCH so a live backlog can never trigger a
# first-time XLA compile on the engine thread.
MAX_COALESCED = 16 * MAX_SUBBATCH


# Back-compat alias: direct engine tests (and older embedders) wrap a
# (request, reply_fn) pair this way; scheduling metadata defaults to the
# latency class.
_Pending = vsched.Pending


from base64 import b64encode as _b64encode


def _ctx_tag(request):
    """Protocol v5 block-digest context tag -> the base64 string the C++
    node logs in its TRACE lines (common/bytes.hpp base64_encode:
    standard alphabet, padded — python's b64encode matches), so
    obs/trace.py joins on string equality.  None when untagged.

    Callers must gate on ``tracer.enabled`` (the trace_stage cost
    discipline): the un-traced hot path never pays the encode."""
    ctx = getattr(request, "ctx", None)
    if not ctx:
        return None
    return _b64encode(ctx).decode("ascii")


def _ctx_tags(batch):
    """Distinct context tags across one coalesced launch (sorted for a
    stable span schema); empty when no request carried one."""
    tags = {_ctx_tag(p.request) for p in batch}
    tags.discard(None)
    return sorted(tags)


class ChaosState:
    """Protocol v3 fault-injection hook (OP_CHAOS, behind ``--chaos``).

    Lets the graftchaos harness exercise the *client-side* failure
    handling — C++ host fallback, python SidecarOverloaded, reconnect —
    without process murder, by making a healthy sidecar misbehave in
    three bounded, scripted ways:

      ``delay_ms``  every verify reply is delayed this long (capped at
                    MAX_DELAY_MS; 0 clears) — a slow/contended device
      ``drop``      the next N verify requests close their connection
                    instead of answering — a crashing sidecar, minus the
                    crash
      ``shed``      the next N verify requests get the explicit
                    queue-full backpressure reply — a saturated engine,
                    without needing to actually saturate it
      ``wedge``     the next N device launches HANG past their guard
                    deadline (graftguard): drives the full supervisor
                    ladder — host-fallback replies, quarantine,
                    crash-only reboot, canary — end to end through
                    OP_CHAOS, the fault a real tunneled-compile wedge
                    inflicts, minus the tunnel
      ``clear``     reset everything

    Chaos only touches verify/sign opcodes: PING stays honest so
    readiness probes (and the harness's own boot wait) keep working, and
    OP_STATS/OP_CHAOS stay reachable so a degraded sidecar can still be
    observed and un-degraded.  Delayed replies are rescheduled onto a
    timer — the connection's reader thread never sleeps, so a PING
    pipelined behind a delayed verify still answers immediately.
    """

    # Deliberately BELOW the C++ client's Ed25519 reply deadline
    # (TpuVerifier::kRecvTimeoutMs = 1000): a capped delay must model a
    # SLOW sidecar the client still waits out, never an expired request
    # — past the deadline the fault is indistinguishable from an outage,
    # which ``kill`` already scripts (and which would cascade into the
    # wedged-connection teardown + circuit breaker instead of the
    # scripted slow-reply behavior).
    MAX_DELAY_MS = 750

    def __init__(self):
        self._lock = threading.Lock()
        self.delay_ms = 0
        self.shed_left = 0
        self.drop_left = 0
        self.wedge_left = 0

    def configure(self, spec: dict) -> dict:
        """Apply one OP_CHAOS spec; raises ValueError on unknown keys or
        non-integer values (the connection closes, same contract as any
        malformed frame)."""
        unknown = set(spec) - {"delay_ms", "shed", "drop", "wedge",
                               "clear"}
        if unknown:
            raise ValueError(f"unknown chaos key(s) {sorted(unknown)}")
        vals = {}
        for key in ("delay_ms", "shed", "drop", "wedge"):
            if key in spec:
                v = spec[key]
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    raise ValueError(f"chaos {key} must be an int >= 0")
                vals[key] = v
        with self._lock:
            if spec.get("clear"):
                self.delay_ms = self.shed_left = self.drop_left = 0
                self.wedge_left = 0
            if "delay_ms" in vals:
                self.delay_ms = min(vals["delay_ms"], self.MAX_DELAY_MS)
            if "shed" in vals:
                self.shed_left = vals["shed"]
            if "drop" in vals:
                self.drop_left = vals["drop"]
            if "wedge" in vals:
                self.wedge_left = vals["wedge"]
            applied = {"delay_ms": self.delay_ms, "shed": self.shed_left,
                       "drop": self.drop_left, "wedge": self.wedge_left}
        log.warning("chaos hook configured: %s", applied)
        return applied

    def take_wedge(self) -> bool:
        """Consume one scripted launch wedge (graftguard's OP_CHAOS
        drill); called by the engine at dispatch time, not per request
        — a wedge is a DEVICE fault, so it applies to whatever launch
        is next, exactly like the real thing."""
        with self._lock:
            if self.wedge_left > 0:
                self.wedge_left -= 1
                return True
            return False

    def verify_action(self):
        """Consume the chaos decision for one verify/sign request ->
        (drop: bool, shed: bool, delay_s: float)."""
        with self._lock:
            if self.drop_left > 0:
                self.drop_left -= 1
                return True, False, 0.0
            shed = self.shed_left > 0
            if shed:
                self.shed_left -= 1
            return False, shed, self.delay_ms / 1e3


class VerifyEngine:
    """Owns the device; single consumer thread draining scheduler launches."""

    def __init__(self, mesh_devices: int | None = None, use_host: bool = False,
                 committee: int | None = None,
                 client_rate: int | None = None,
                 tracer=None, guard=None, chaos=None, rewarm_fn=None,
                 cadence: bool = False, ring_factory=None):
        # All launch-shape policy lives in the scheduler subsystem: the
        # shape registry records what the warmup compiled (until
        # enable_bulk, launches cap at MAX_SUBBATCH; _warmup covers every
        # padded bucket up to that cap, so warmed deployments never hit a
        # first-time compile on this thread), and the two-class queues
        # decide what each launch contains.  The registry knows the mesh
        # size, so launch capacities and routes are shard-aligned on
        # multi-chip deployments.  Admission caps are sized from the
        # deployment (committee size drives latency-class demand, client
        # rate drives bulk) with env overrides winning — see
        # sched/scheduler.size_queue_caps.
        self._shapes = vsched.ShapeRegistry(
            use_host=use_host, n_devices=mesh_devices or 0,
            committee=committee)
        lat_cap, bulk_cap = vsched.size_queue_caps(
            committee=committee, client_rate=client_rate)
        self._sched = vsched.Scheduler(shapes=self._shapes,
                                       latency_cap_sigs=lat_cap,
                                       bulk_cap_sigs=bulk_cap,
                                       committee=committee)
        self._use_host = use_host
        # grafttrace: span emission through every engine stage (admit ->
        # queue -> pack -> dispatch -> device -> reply), tagged with the
        # request rid and scheduler class.  The null tracer short-circuits
        # every call, so the un-traced hot path pays only a method call.
        from ..obs.spans import Tracer

        self._tracer = tracer if tracer is not None else Tracer.disabled()
        # Device multi-digest pairing programs compile one shape per vote
        # count (minutes each); only counts warmed via _warmup_bls_multi
        # may launch on device — others verify on host so a surprise TC
        # size can never wedge this thread mid-traffic.
        self._bls_multi_warmed: set[int] = set()
        # graftkern compile accounting: serve() attaches a CompileTracker
        # (utils/xla_cache) on device-mode boots so the warmup's manifest
        # hit/miss counts and wall time ride the OP_STATS ``compile``
        # section; host-mode engines compile nothing and keep None.
        self.compile_tracker = None
        # (msg, pk, sig) -> bool verdict; see _cache_verdict.
        self._verdicts: dict = {}
        self._verdicts_lock = threading.Lock()
        # graftfleet dedup accounting: the verdict cache is keyed on
        # record BYTES, so under a shared fleet a QC gossiped to N
        # tenants' replicas is device-verified once and answered from
        # cache for everyone else.  cache_hits counts records answered
        # from the cross-request cache (connection fast path + pack
        # lookups), inbatch_hits records deduped within one coalesced
        # batch, misses records that actually rode a verify path.  The
        # hit-rate rides OP_STATS (``dedup``) and the strict parser
        # asserts it is non-zero under the greedy-flood drill.
        self._dedup_cache_hits = 0
        self._dedup_inbatch_hits = 0
        self._dedup_misses = 0
        # graftguard: the launch supervisor (sidecar/guard.py).  When
        # attached (serve() always attaches one; direct embedders and
        # legacy tests may run bare), every staged dispatch/fetch wait
        # routes through _guarded under a per-shape deadline, a wedge
        # executes the degradation ladder instead of hanging this
        # thread, and the engine can crash-only reboot the device leg
        # off the warm cache (rewarm_fn) while the host path serves.
        self._guard = guard
        self._chaos = chaos
        self._rewarm_fn = rewarm_fn
        self._reboot_lock = threading.Lock()
        self._device_ok = True
        self._rebooting = False
        # THREAD-LOCAL rewarm marker: while the reboot thread runs
        # rewarm_fn, ITS calls into _verify_submit must hit the DEVICE
        # (that is what re-warming means) even though _device_ok is
        # still False — but live traffic on the pack worker must keep
        # host-routing for the whole window, so the flag cannot be
        # engine-global (an engine-global bool would leak concurrent
        # live launches onto the mid-rewarm device).
        self._rewarm_tls = threading.local()
        self._mesh = None
        if mesh_devices and mesh_devices > 1:
            from ..parallel.mesh import make_mesh

            self._mesh = make_mesh(mesh_devices)
        # Double-buffered dispatch: ONE pack worker stages the host side
        # of launch N+1 (byte decode, prepare_batch, h2d transfer) while
        # launch N executes on the device — the engine thread only ever
        # pays dispatch + fetch.  A single worker keeps pack order equal
        # to scheduler assembly order (the strict-priority guarantee
        # rides on it), and the single staged slot + the in-flight cap
        # bound how much work leaves the bounded class queues.
        from concurrent.futures import ThreadPoolExecutor

        self._pack_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="verify-pack")
        self._inflight_n = 0  # launches executing on device (telemetry)
        self._stopped = threading.Event()
        # graftcadence: the resident continuous-batching ring
        # (sidecar/ring.py).  Opt-in (--cadence / HOTSTUFF_TPU_CADENCE)
        # — the staged loop below stays the default until a committed
        # ``cadence`` bench headline shows the ring winning.  The ring
        # runs ON this engine thread first; a wedge fallback (or a
        # constructor without cadence) lands in the staged loop.
        if ring_factory is not None:
            # Tests inject rings with virtual clocks/waits; the factory
            # runs before the engine thread starts so the ring is in
            # place when _run checks for it.
            self._ring = ring_factory(self)
        elif cadence:
            from .ring import CadenceRing

            self._ring = CadenceRing(self)
        else:
            self._ring = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="verify-engine")
        self._thread.start()

    def submit(self, request, reply_fn, cls: str = vsched.LATENCY,
               is_bls: bool = False, tenant: str | None = None) -> bool:
        """Admit one request into its class queue.  Returns False on
        queue-full — nothing was retained and the CALLER must reply
        (the handler sends the explicit empty-mask backpressure reply);
        never blocks the calling connection thread."""
        if cls == vsched.BULK and not is_bls:
            # graftingress feed mix: an admission-verify batch carries
            # the pinned ingress ctx tag; everything else on the bulk
            # lane is offchain-fed.  Counted on OFFER (before any shed)
            # so the mix stays honest under backpressure.
            from ..crypto.txsign import INGRESS_CTX

            self._sched.stats.note_bulk_source(
                getattr(request, "ctx", None) == INGRESS_CTX,
                len(getattr(request, "msgs", ()) or ()))
        if self._rebooting and cls == vsched.BULK and not is_bls:
            # Crash-only reboot in progress (graftguard): the device leg
            # is re-warming and the host path is reserved for consensus
            # latency — bulk gets an honest BUSY NOW (the handler's
            # queue-full reply carries the retry-after hint), so the C++
            # breaker reads a live, rebooting sidecar, never silence.
            if self._guard is not None:
                self._guard.stats.note_busy()
            return False
        ok = self._sched.offer(request, reply_fn, cls=cls, is_bls=is_bls,
                               tenant=tenant)
        if self._tracer.enabled:
            tags = {}
            ctx = _ctx_tag(request)
            if ctx:
                tags["ctx"] = ctx
            self._tracer.event("admit", rid=request.request_id, cls=cls,
                               ok=ok,
                               n=len(getattr(request, "msgs", ()) or ())
                               or 1, **tags)
        return ok

    def retry_after_ms(self, cls: str) -> int:
        """Hint for a BUSY reply after a shed of class ``cls`` (the
        scheduler's surge controller turns queue depth + drain rate
        into milliseconds)."""
        return self._sched.retry_after_ms(cls)

    def stats_snapshot(self) -> dict:
        """The OP_STATS reply body: scheduler telemetry + warmed shapes."""
        snap = self._sched.stats.snapshot()
        snap["shapes"] = self._shapes.snapshot()
        snap["queue_caps"] = self._sched.queue_caps()
        snap["verdict_cache_entries"] = len(self._verdicts)
        with self._verdicts_lock:
            hits = self._dedup_cache_hits + self._dedup_inbatch_hits
            seen = hits + self._dedup_misses
            snap["dedup"] = {
                "cache_hits": self._dedup_cache_hits,
                "inbatch_hits": self._dedup_inbatch_hits,
                "misses": self._dedup_misses,
                "hit_rate": round(hits / seen, 4) if seen else 0.0,
            }
        snap["tenant_caps"] = self._sched.tenant_caps()
        occupancy = self._sched.tenant_occupancy()
        if any(occupancy.values()):
            snap["tenant_occupancy"] = occupancy
        if self.compile_tracker is not None:
            snap["compile"] = self.compile_tracker.snapshot()
        if self._guard is not None:
            g = self._guard.snapshot()
            g["device_ok"] = self._device_ok
            g["rebooting"] = self._rebooting
            snap["guard"] = g
        if self._ring is not None:
            # graftcadence: tick rate, occupancy hist, pad-fill ratio,
            # generation drops, queue-wait p50/p99 (sidecar/ring.py).
            snap["cadence"] = self._ring.snapshot()
        return snap

    # graftlint: sanitizes=device-verdict
    def cached_verdicts(self, request):
        """[bool] if EVERY (msg, pk, sig) record of this Ed25519 verify
        request already has a cached verdict, else None.  Called from
        connection threads (see _Handler.handle's fast path); the engine
        thread is the only writer, so a concurrent eviction can at worst
        turn a hit into a miss."""
        verdicts = self._verdicts
        out = []
        for rec in zip(request.msgs, request.pks, request.sigs):
            v = verdicts.get(rec)
            if v is None:
                return None
            out.append(v)
        if out:
            with self._verdicts_lock:
                self._dedup_cache_hits += len(out)
        return out

    @staticmethod
    def bls_cache_key(req):
        """Verdict-cache key for a BLS verify request, or None if the op
        is uncacheable (signing).  Validity is a pure function of the
        request's own bytes, so the whole request keys the verdict — a
        pairing costs seconds on host and ~100 ms on device, making the
        N-replicas-one-certificate dedup worth far more here than for
        Ed25519."""
        import hashlib

        def h(tag, *parts):
            # Fixed 32-byte keys: BLS requests embed every pk+sig (~32 KB
            # for a 100-vote TC), which would inflate the FIFO's ~15 MB
            # bound 100x if stored verbatim.  Length-prefixed parts keep
            # the encoding injective before hashing.
            d = hashlib.sha256(tag)
            for p in parts:
                seq = p if isinstance(p, (list, tuple)) else (p,)
                d.update(len(seq).to_bytes(4, "big"))  # list boundary
                for b in seq:
                    d.update(len(b).to_bytes(4, "big"))
                    d.update(b)
            return d.digest()

        if isinstance(req, proto.BlsMultiRequest):
            return ("bm", h(b"bm", req.msgs, req.pks, req.sigs))
        if isinstance(req, proto.BlsVotesRequest):
            return ("bv", h(b"bv", req.msg, req.pks, req.sigs))
        if isinstance(req, proto.BlsAggRequest):
            return ("ba", h(b"ba", req.msg, req.pks, req.agg_sig))
        return None

    # graftlint: sanitizes=device-verdict
    def cached_bls_verdict(self, req):
        """[bool] reply if this BLS verify request's verdict is cached,
        else None.  Connection-thread-safe for the same reason as
        cached_verdicts."""
        key = self.bls_cache_key(req)
        if key is None:
            return None
        v = self._verdicts.get(key)
        return None if v is None else [v]

    def enable_bulk(self):
        """Raise the per-launch cap to MAX_COALESCED; call only after the
        chunked-scan shapes have been compiled (see _warmup_bulk)."""
        self._shapes.enable_bulk(MAX_COALESCED)

    def stop(self):
        self._stopped.set()
        self._sched.wake()  # wake consumer

    # -- consumer ----------------------------------------------------------

    # Ed25519 launches kept in flight before the oldest result is fetched
    # (STAGED path only).  The tunneled device charges a fixed ~15-20 ms
    # per dispatch that OVERLAPS device execution of the previous launch
    # — but only if the engine dispatches launch i+1 before fetching
    # launch i's mask.  Depth 2 covers dispatch ~= execute; deeper only
    # adds reply latency.  On top of the dispatch depth sits ONE pack
    # slot (the pack worker in __init__): while up to two launches
    # execute, the host side of the next launch — byte decode,
    # prepare_batch, h2d — is already staging, so in the steady state
    # the device never waits for host packing.
    # Knob hygiene (VERDICT item 6): this constant is PINNED BY
    # MEASUREMENT, not superseded into an env knob — the cadence ring
    # (sidecar/ring.py) generalizes it to a TRAINED depth k in {2,4,8}
    # (RingDepth, swept in the bench ``cadence`` headline), so anyone
    # needing depth > 2 turns the ring on rather than growing a second
    # depth knob here.
    PIPELINE_DEPTH = 2

    def _run(self):
        """Engine thread body: the cadence ring first when one is
        attached (graftcadence; returns on stop or on wedge fallback
        with every in-flight generation answered), then the staged
        request-driven loop — the DEFAULT path and the ladder's landing
        zone."""
        ring = self._ring
        if ring is not None:
            ring.run()
            if self._stopped.is_set():
                self._pack_pool.shutdown(wait=False)
                return
        self._run_staged()

    def _run_staged(self):
        import collections
        from concurrent import futures as cfut

        packing = collections.deque()   # (batch, Future[dispatch_fn])
        inflight = collections.deque()  # (batch, fetch_fn,
                                        #  dispatched_at, guard_key)
        while not self._stopped.is_set():
            # 1) A FINISHED pack moves onto the device whenever there is
            #    dispatch room.  Unfinished packs are waited out in step
            #    3's bounded slices, never blocked on here — stop() must
            #    stay observable even mid-pack.
            if packing and len(inflight) < self.PIPELINE_DEPTH and \
                    packing[0][1].done():
                self._dispatch_one(packing, inflight)
                continue
            # 2) A free pack slot admits the next scheduler launch.
            if not packing:
                idle = not inflight
                # Bounded wait when idle so a stop() that races the
                # wait's entry is still observed promptly (same poll
                # discipline as serve_forever).
                launch = self._sched.next_launch(timeout=0.25) if idle \
                    else self._sched.next_launch(block=False)
                if launch is not None:
                    self._trace_queue_waits(launch)
                    # BLS requests run individually (a QC aggregate is
                    # one check; there is nothing to coalesce) on the
                    # same device thread, after the whole Ed25519
                    # pipeline drains.
                    if launch.kind == "bls":
                        (item,) = launch.items
                        while inflight:
                            self._drain_one(inflight)
                        tags = {}
                        if self._tracer.enabled:
                            ctx = _ctx_tag(item.request)
                            if ctx:
                                # v5 context tag: scheme=bls device spans
                                # join the tagged block's trace exactly
                                # like EdDSA ones (ROADMAP item-2 parity).
                                tags["ctx"] = ctx
                        with self._tracer.span(
                                "device", kind="bls",
                                rid=item.request.request_id, **tags):
                            # Single-reply discipline: _execute_bls owns
                            # its whole failure surface and replies
                            # EXACTLY once through its idempotent
                            # helper — no backstop reply here (the old
                            # one could double-reply when an exception
                            # escaped after a success path had already
                            # answered, e.g. a wedged-then-completing
                            # pairing).
                            self._execute_bls(item)
                        continue
                    batch = launch.items
                    packing.append(
                        (batch, self._pack_pool.submit(self._pack, batch)))
                    continue
                if idle:
                    continue
            # 3) Pipeline full or queue empty: make progress on the
            #    oldest work — fetch the oldest launch (its execution
            #    overlapped the pack that is still staging), or wait out
            #    the pack in bounded slices so stop() stays observable.
            if inflight:
                self._drain_one(inflight)
            elif packing:
                try:
                    packing[0][1].exception(timeout=0.25)
                except cfut.TimeoutError:
                    pass
        # Shutdown: every accepted request still gets its reply (clients
        # would otherwise block until their recv deadline and report a
        # spurious transport failure).
        while packing:
            self._dispatch_one(packing, inflight)
        while inflight:
            self._drain_one(inflight)
        self._pack_pool.shutdown(wait=False)

    def _trace_queue_waits(self, launch):
        """One ``queue`` span per launched item (duration = admission ->
        launch assembly, the same wait the OP_STATS reservoirs sample)."""
        if not self._tracer.enabled:
            return
        now = monotonic()
        for p in launch.items:
            tags = {}
            ctx = _ctx_tag(p.request)
            if ctx:
                tags["ctx"] = ctx
            self._tracer.event("queue", dur_ms=(now - p.enqueued_at) * 1e3,
                               rid=p.request.request_id, cls=p.cls, **tags)

    def _trace_replies(self, batch):
        if not self._tracer.enabled:
            return
        for p in batch:
            tags = {}
            ctx = _ctx_tag(p.request)
            if ctx:
                tags["ctx"] = ctx
            self._tracer.event("reply", rid=p.request.request_id,
                               cls=p.cls, **tags)

    def _guard_key(self, batch) -> str:
        """Launch-shape key for the guard's per-shape deadlines: the
        power-of-two bucket of the DEDUPED record count — the shape the
        launch actually executes (the pack stage dedups before
        dispatch), so p99 history trained under the shared-sidecar
        headline load (N replicas submitting the SAME QC: raw total >>
        unique) can never tighten the deadline of a genuinely-large
        unique batch that shares a raw total with it.  Sliced launches
        stay self-consistent: the same key always runs the same slice
        count.  The dedup costs one hash pass on the engine thread —
        small next to the launch it sizes, and only the wedge-protected
        path pays it."""
        from ..crypto.eddsa import next_pow2

        uniq = len({rec for p in batch
                    for rec in zip(p.request.msgs, p.request.pks,
                                   p.request.sigs)})
        return f"launch:{next_pow2(max(8, uniq))}"

    def _guarded(self, key: str, thunk):
        """THE deadline helper: every engine-side wait on a staged
        dispatch/fetch future routes through here (graftlint's
        unsupervised-launch rule pins it).  With a guard attached the
        thunk runs on a disposable launch thread under the shape's
        deadline — a WedgedLaunch out of here means the monitor
        declared an overrun and the worker was abandoned.  The chaos
        hook's ``wedge`` knob swaps the thunk for a genuine hang, so
        the scripted drill exercises the identical supervisor path."""
        chaos = self._chaos
        if chaos is not None and self._guard is not None and \
                chaos.take_wedge():
            log.warning("chaos: wedging launch %s", key)

            def thunk():
                # The injected fault IS an unbounded wait: a faithful
                # stand-in for a hung tunneled device call.  It parks
                # the disposable launch thread, never this one.
                # graftlint: disable=unsupervised-launch
                threading.Event().wait()
        if self._guard is None:
            return thunk()
        return self._guard.call(key, thunk)

    def _dispatch_one(self, packing, inflight):
        """Move the oldest staged pack onto the device (engine thread)."""
        batch, fut = packing.popleft()
        key = self._guard_key(batch)
        try:
            # wait for pack, then device dispatch — both can wedge on
            # the tunnel (pack stages the h2d transfer), so both run
            # under the one guarded deadline
            fetch = self._guarded(key, lambda: fut.result()())
        except WedgedLaunch:
            self._wedge_ladder(batch, key, stage="dispatch")
            return
        except Exception:
            log.exception("verify batch pack/dispatch failed")
            for p in batch:
                p.reply_fn([False] * len(p.request.msgs))
            self._trace_replies(batch)
            return
        if self._tracer.enabled:
            tags = {}
            ctxs = _ctx_tags(batch)
            if ctxs:
                tags["ctxs"] = ctxs
            self._tracer.event("dispatch", reqs=len(batch), **tags)
        inflight.append((batch, fetch, monotonic(), key))
        self._inflight_n = len(inflight)

    def _drain_one(self, inflight):
        batch, fetch, dispatched_at, key = inflight.popleft()
        self._inflight_n = len(inflight)
        try:
            mask = self._guarded(key, fetch)
        except WedgedLaunch:
            self._wedge_ladder(batch, key, stage="fetch")
            return
        except Exception:
            log.exception("verify batch failed")
            for p in batch:
                p.reply_fn([False] * len(p.request.msgs))
            self._trace_replies(batch)
            return
        # The device stage spans dispatch -> fetch completion: it
        # includes the tunnel round trip, exactly what the engine pays.
        if self._tracer.enabled:
            tags = {}
            ctxs = _ctx_tags(batch)
            if ctxs:
                tags["ctxs"] = ctxs
            self._tracer.event(
                "device", dur_ms=(monotonic() - dispatched_at) * 1e3,
                reqs=len(batch),
                sigs=sum(len(p.request.msgs) for p in batch), **tags)
        off = 0
        for p in batch:
            n = len(p.request.msgs)
            p.reply_fn([bool(b) for b in mask[off:off + n]])
            off += n
        self._trace_replies(batch)

    # -- graftguard: the wedge degradation ladder ---------------------------

    def _wedge_ladder(self, batch, key: str, stage: str):
        """A launch overran its deadline: execute the degradation ladder
        instead of hanging (graftguard).

        1. every latency-class request in the wedged batch is answered
           from the HOST path — ``ref_ed25519.verify`` per record, the
           reference ``verify_batch`` is property-tested bit-identical
           to, so a wedge changes WHERE the verdict came from, never
           what it is;
        2. bulk-class requests get BusyReply (the handler encodes
           OP_BUSY with the drain-derived retry-after) — throughput
           work re-offers once the device leg is back;
        3. the batch's records are quarantined (repeat offenders feed
           the poison bisection after the reboot);
        4. a crash-only engine reboot begins (async; the host path
           serves meanwhile)."""
        from ..crypto import ref_ed25519 as ref

        guard = self._guard
        log.error("guard: %s of launch %s WEDGED (deadline overrun); "
                  "executing degradation ladder", stage, key)
        records = {rec for p in batch if not p.is_bls
                   for rec in zip(p.request.msgs, p.request.pks,
                                  p.request.sigs)}
        pending = guard.quarantine.note_wedged(records)
        if pending:
            log.error("guard: %d repeat-offender record(s) pending "
                      "poison bisection", pending)

        def answer():
            for p in batch:
                if p.cls == vsched.BULK:
                    guard.stats.note_busy()
                    p.reply_fn(
                        BusyReply(self.retry_after_ms(vsched.BULK)))
                    continue
                mask = [bool(ref.verify(pk, m, s))
                        for m, pk, s in zip(p.request.msgs,
                                            p.request.pks,
                                            p.request.sigs)]
                guard.stats.note_host_fallback(len(mask))
                p.reply_fn(mask)
            self._trace_replies(batch)

        # The host fallback runs OFF the engine thread: a wedged batch
        # at the coalesced cap is tens of seconds of pure-python
        # verification, and the queued consensus verifies behind it —
        # about to be host-routed by the reboot flag — must drain
        # concurrently, not wait out the very head-of-line stall the
        # supervisor exists to kill.  One-shot body, reply_fn is
        # thread-safe (outbox.put_nowait), no loop to stop.
        # graftlint: disable=daemon-thread-without-stop-flag
        threading.Thread(target=answer, daemon=True,
                         name="guard-ladder").start()
        self._begin_reboot()

    def _begin_reboot(self):
        """Start the crash-only engine reboot (idempotent: repeat wedges
        while one is running fold into it).  Device routing flips OFF
        first — from here until the canary passes, _pack routes every
        launch down the host path and bulk admission replies BUSY."""
        with self._reboot_lock:
            if self._rebooting:
                return
            self._rebooting = True
            self._device_ok = False
        t = threading.Thread(target=self._reboot, daemon=True,
                             name="guard-reboot")
        t.start()

    def _reboot(self):
        """Crash-only reboot of the device leg: tear down the compiled-
        program state, re-warm off the populated XLA cache/manifest
        (rewarm_fn — a deserialization, not a recompile: PR 11 measured
        38 s warm vs 149 s cold), and resume device routing only after
        a canary launch passes under the guard's deadline.  Canary
        failures retry up to the guard's max_reboots; past that the
        engine stays on the host path — degraded, live, and visible in
        OP_STATS rather than wedged."""
        guard = self._guard
        t0 = monotonic()
        attempts = 0
        while not self._stopped.is_set():
            attempts += 1
            try:
                self._teardown_device()
                t_warm = monotonic()
                if self._rewarm_fn is not None:
                    # The warmup legs must reach the DEVICE path even
                    # though live routing is host-only right now —
                    # without this, _warm_shapes' engine._verify calls
                    # would "warm" the ladder shapes on the host and
                    # compile nothing, leaving the first post-canary
                    # launch to pay a re-trace under a tight warmed
                    # deadline (a guaranteed re-wedge).  Thread-local:
                    # only THIS thread's verifies force the device;
                    # live traffic keeps host-routing meanwhile.
                    # threading.local: this write is visible ONLY to
                    # the reboot thread — unshared by construction, so
                    # no lock can be needed (that isolation is the fix:
                    # an engine-global flag here leaked live launches
                    # onto the mid-rewarm device).
                    # graftlint: disable=unlocked-shared-write
                    self._rewarm_tls.active = True
                    try:
                        self._rewarm_fn()
                    finally:
                        # graftlint: disable=unlocked-shared-write
                        self._rewarm_tls.active = False
                guard.stats.note_rewarm(monotonic() - t_warm)
                if self._canary():
                    guard.stats.note_canary(True)
                    break
                guard.stats.note_canary(False)
            except Exception:
                log.exception("guard: reboot attempt %d failed", attempts)
                guard.stats.note_canary(False)
            if attempts >= guard.max_reboots:
                log.error("guard: %d reboot attempt(s) failed the canary;"
                          " staying on the host path", attempts)
                with self._reboot_lock:
                    self._rebooting = False
                return
        if self._stopped.is_set():
            return  # engine teardown mid-reboot: nothing left to resume
        # Poison bisection BEFORE resuming device routing: the repeat-
        # offender records must be isolated while the host path still
        # owns live traffic, or the first post-reboot launch could
        # re-wedge on the same poison.
        try:
            self._bisect_quarantine()
        except Exception:
            log.exception("guard: poison bisection failed (pending "
                          "records stay quarantined)")
        with self._reboot_lock:
            self._rebooting = False
            self._device_ok = True
        wall = monotonic() - t0
        guard.stats.note_reboot(wall)
        log.warning("guard: engine rebooted in %.1fs (canary passed "
                    "after %d attempt(s)); device routing resumed",
                    wall, attempts)

    def _teardown_device(self):
        """Crash-only teardown of the device-side state: drop the
        in-process compiled-program caches so the re-warm rebuilds
        every staged entry from the persistent XLA disk cache.  The
        tunneled device client itself re-dials lazily on the next
        dispatch; host-mode engines have nothing to tear down."""
        if self._use_host:
            return
        try:
            import jax

            jax.clear_caches()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            log.exception("guard: jax cache teardown failed (continuing)")

    def _canary(self) -> bool:
        """One tiny known-good launch through the REAL staged verify
        entry, under the guard's deadline: device routing resumes only
        when this completes in time with an all-valid mask."""
        from ..crypto import ref_ed25519 as ref

        sk = bytes(range(32))
        _, pk = ref.generate_keypair(sk)
        msg = b"\x07" * 32
        sig = ref.sign(sk, msg)
        n = 8
        try:
            mask = self._guard.call(
                "canary:8",
                lambda: np.asarray(self._verify_submit(
                    [msg] * n, [pk] * n, [sig] * n, force_device=True)()))
        except WedgedLaunch:
            log.error("guard: canary launch wedged")
            return False
        except Exception:
            log.exception("guard: canary launch failed")
            return False
        return bool(np.asarray(mask).all())

    def _bisect_quarantine(self):
        """Poison-record bisection (the RLC bisection discipline applied
        to wedges): probe subsets of the repeat-offender records
        through guarded device launches until the minimal poison set is
        isolated; confirmed poison records are host-verified forever
        after (_pack's poison lane)."""
        guard = self._guard
        pending = guard.quarantine.pending()
        if not pending:
            return
        log.warning("guard: bisecting %d repeat-offender record(s) for "
                    "poison", len(pending))

        def probe(subset):
            msgs = [r[0] for r in subset]
            pks = [r[1] for r in subset]
            sigs = [r[2] for r in subset]
            try:
                self._guard.call(
                    f"poison-probe:{len(subset)}",
                    lambda: np.asarray(self._verify_submit(
                        msgs, pks, sigs, force_device=True)()))
                return True
            except WedgedLaunch:
                return False
            except Exception:
                # A clean failure means the launch COMPLETED (the device
                # is not wedged); the record merely verifies False.
                return True

        poison = bisect_poison(pending, probe,
                               max_probes=guard.max_bisect_probes)
        n = guard.quarantine.resolve(poison)
        if n:
            log.error("guard: %d poison record(s) quarantined to the "
                      "host path permanently", n)

    def _submit(self, batch):
        """Two-stage form of the launch path (pack + dispatch in one
        call) for embedders without a pack thread; returns fetch() ->
        concatenated mask."""
        return self._pack(batch)()

    def _pack(self, batch):
        """Host-side pack stage of one coalesced batch (runs on the pack
        worker): byte concat, verdict-cache lookups, in-batch dedup,
        route selection, host preparation and the h2d transfers.  Returns
        ``dispatch() -> fetch()`` — dispatch fires the (donated) device
        program from the engine thread; the host path computes eagerly
        here instead.

        Verdict cache: signature validity is a pure function of the
        (msg, pk, sig) bytes, so records already verified are answered
        from a bounded FIFO cache without touching the device.  On a
        shared sidecar (the local testbed runs up to 100 replicas against
        ONE sidecar process) every replica verifies the same QC — the
        cache turns N identical quorum verifications per block into one
        device launch plus N-1 lookups.  (Cache reads here happen off the
        engine thread, same dict-read-under-GIL safety as the connection
        threads' fast path; the engine thread stays the only writer.)"""
        t0 = monotonic()
        hidden = self._inflight_n > 0  # device busy while we pack
        msgs, pks, sigs = [], [], []
        for p in batch:
            msgs += p.request.msgs
            pks += p.request.pks
            sigs += p.request.sigs
        records = list(zip(msgs, pks, sigs))
        cached = [self._verdicts.get(r) for r in records]
        # Dedup WITHIN the batch too: the headline scenario is N replicas
        # verifying the same QC concurrently, whose identical records land
        # in ONE coalesced batch — before anything is cached.  Each unique
        # missed record is dispatched once and fanned out to every index
        # that carried it.
        uniq: dict = {}
        for i, c in enumerate(cached):
            if c is None:
                uniq.setdefault(records[i], []).append(i)
        uniq_records = list(uniq.keys())
        n_cached = sum(1 for c in cached if c is not None)
        if records:
            with self._verdicts_lock:
                self._dedup_cache_hits += n_cached
                self._dedup_inbatch_hits += \
                    len(records) - n_cached - len(uniq_records)
                self._dedup_misses += len(uniq_records)
        # graftguard poison lane: records the bisection confirmed poison
        # are split OUT of the device launch and verified on host right
        # here (pure host work on the pack worker) — a cursed record is
        # still answered and counted, but can never take the device leg
        # down again, and its co-batched neighbors still ride the device.
        guard = self._guard
        poisoned = []
        if guard is not None and guard.quarantine.has_poison():
            device_records = [r for r in uniq_records
                              if not guard.quarantine.is_poisoned(r)]
            if len(device_records) != len(uniq_records):
                poisoned = [r for r in uniq_records
                            if guard.quarantine.is_poisoned(r)]
                # Poison lane LAST so fetch order matches record order.
                uniq_records = device_records + poisoned
                guard.stats.note_poison_host(len(poisoned))
        else:
            device_records = uniq_records
        m_msgs = [r[0] for r in device_records]
        m_pks = [r[1] for r in device_records]
        m_sigs = [r[2] for r in device_records]
        # Route via the warmed-shape registry: batches of RLC_MIN_LAUNCH+
        # unique records whose padded (per-shard, on a mesh) bucket the
        # RLC warmup compiled pay ONE Straus MSM — single-chip via
        # crypto/eddsa.verify_batch_rlc_pack, mesh via
        # parallel/sharded_verify.verify_rlc_sharded_pack — instead of
        # per-signature ladders; the bisection fallbacks keep the verdict
        # mask bit-identical when the combined check fails.  While a
        # crash-only reboot is re-warming the device leg (graftguard),
        # everything routes host — the path the ladder already answers
        # wedged batches from.
        stats = self._sched.stats
        path = vsched.PATH_HOST if not self._device_ok \
            else self._shapes.route(len(device_records))
        if device_records:
            stats.note_path(path)

        def on_bisect():
            stats.note_path("rlc_bisect")

        if not device_records:
            dispatchers = []
        elif path == vsched.PATH_RLC:
            from ..crypto import eddsa

            dispatchers = [eddsa.verify_batch_rlc_pack(
                m_msgs, m_pks, m_sigs, on_bisect=on_bisect)]
        elif path in (vsched.PATH_RLC_SHARDED, vsched.PATH_LADDER_SHARDED,
                      vsched.PATH_SCAN_SHARDED, vsched.PATH_MESH):
            dispatchers = self._pack_sharded(path, m_msgs, m_pks, m_sigs,
                                             on_bisect)
        elif path == vsched.PATH_HOST:
            # Host verification is pure host work — it runs right here on
            # the pack worker (per sub-batch, the pre-scheduler slicing
            # discipline), overlapping whatever the device is doing.
            fetchers = [self._verify_submit(m_msgs[i:i + MAX_SUBBATCH],
                                            m_pks[i:i + MAX_SUBBATCH],
                                            m_sigs[i:i + MAX_SUBBATCH])
                        for i in range(0, len(m_msgs), MAX_SUBBATCH)]
            dispatchers = [(lambda f=f: f) for f in fetchers]
        else:
            # Single-chip per-signature ladders: up to a whole launch-cap
            # window per dispatch, so the per-dispatch tunnel cost is
            # paid once.  A single request larger than the cap (the
            # coalescer only bounds *additional* requests) is still
            # sliced here so no request can force an unwarmed compile
            # shape or an unbounded device allocation.
            from ..crypto import eddsa

            step = self._shapes.launch_cap
            dispatchers = [eddsa.verify_batch_pack(m_msgs[i:i + step],
                                                   m_pks[i:i + step],
                                                   m_sigs[i:i + step])
                           for i in range(0, len(m_msgs), step)]
        if poisoned:
            # Poison lane: quarantined records verify on HOST, eagerly,
            # here on the pack worker (same discipline as PATH_HOST).
            from ..crypto import ref_ed25519 as ref

            res = np.array([bool(ref.verify(pk, m, s))
                            for m, pk, s in poisoned])
            dispatchers.append(lambda res=res: (lambda: res))
        stats.note_pack(monotonic() - t0, hidden)
        if self._tracer.enabled:
            pack_tags = {}
            pack_ctxs = _ctx_tags(batch)
            if pack_ctxs:
                pack_tags["ctxs"] = pack_ctxs
            self._tracer.event("pack", dur_ms=(monotonic() - t0) * 1e3,
                               reqs=len(batch), uniq=len(uniq_records),
                               path=path, hidden=hidden, **pack_tags)

        def dispatch():
            fetchers = [d() for d in dispatchers]

            def fetch():
                fresh = []
                for f in fetchers:
                    fresh.extend(f())
                mask = list(cached)
                for record, ok in zip(uniq_records, fresh):
                    ok = bool(ok)
                    self._cache_verdict(record, ok)
                    for i in uniq[record]:
                        mask[i] = ok
                return mask

            return fetch

        return dispatch

    def _pack_sharded(self, path, msgs, pks, sigs, on_bisect):
        """Pack-stage dispatchers for the mesh routes: RLC launches go
        whole (one MSM across the mesh); scan-routed backlogs go whole
        too (ONE chunked whole-backlog program — graftscale); ladder
        launches slice at the launch cap like the single-chip path.
        Every launch's per-shard buckets (one per slice) land in the
        OP_STATS histogram — counted once per LAUNCH, so the mesh
        launch count stays comparable to the scheduler's own — and scan
        launches land in the ``scan`` section with their chunk count."""
        from ..crypto.eddsa import prepare_batch
        from ..parallel import sharded_verify as shv

        stats = self._sched.stats
        if path == vsched.PATH_RLC_SHARDED:
            stats.note_mesh_launch(
                [self._shapes.shard_bucket_of(len(msgs))])
            return [shv.verify_rlc_sharded_pack(
                self._mesh, prepare_batch(msgs, pks, sigs),
                on_bisect=on_bisect)]
        if path == vsched.PATH_SCAN_SHARDED:
            shape = self._shapes.scan_shape_of(len(msgs))
            if shape is not None:
                # The whole coalesced backlog in ONE dispatch.  The
                # registry only answers this route for chunk counts the
                # warmup marked (mesh_chunks), so an unwarmed scan
                # shape can never compile mid-run; slices_avoided
                # counts the per-MAX_SUBBATCH ladder dispatches the
                # pre-graftscale mesh path would have paid (its launch
                # cap never rose past MAX_SUBBATCH).
                g, rows = shape
                stats.note_scan_launch(
                    g, len(msgs), -(-len(msgs) // MAX_SUBBATCH) - 1)
                return [shv.verify_sharded_chunked_pack(
                    self._mesh, prepare_batch(msgs, pks, sigs),
                    rows=rows)]
            # Defensive fallback (the registry only ever grows, so the
            # shape cannot have vanished since route()): slice below.
        # Slice at the WARMED ladder cap, not launch_cap: enable_bulk
        # raises launch_cap to the scan capacity, and a slice that size
        # would land on a per-shard bucket only the scan programs were
        # compiled for (see ShapeRegistry.ladder_cap).
        step = self._shapes.ladder_cap()
        # graftcadence: while the ring is engaged, every ladder slice
        # arms at the ring's FIXED shard-aligned shape (the ladder-cap
        # bucket — warmed) instead of the slice's own bucket, so each
        # cadence tick re-dispatches ONE resident compiled program
        # (parallel/sharded_verify.ring_slot_pack) with the slack rows
        # dead (present=0) rather than a different shape per fill level.
        ring = self._ring
        ring_rows = None
        if ring is not None and ring.enabled and self._mesh is not None:
            ring_rows = shv.shard_aligned_rows(
                step, self._mesh.devices.size, MAX_SUBBATCH)
        buckets, out = [], []
        for i in range(0, len(msgs), step):
            sl = slice(i, i + step)
            n = len(msgs[sl])
            if ring_rows is not None:
                buckets.append(self._shapes.shard_bucket_of(ring_rows))
                out.append(shv.ring_slot_pack(
                    self._mesh, prepare_batch(msgs[sl], pks[sl], sigs[sl]),
                    ring_rows))
                continue
            buckets.append(self._shapes.shard_bucket_of(n))
            out.append(shv.verify_batch_sharded_pack(
                self._mesh, prepare_batch(msgs[sl], pks[sl], sigs[sl])))
        stats.note_mesh_launch(buckets)
        return out

    # Verdict-cache capacity: ~224 B/record key; 64k entries ~ 15 MB.
    VERDICT_CACHE_CAP = 64 * 1024

    def _cache_verdict(self, record, ok: bool):
        # Bounded FIFO (dicts preserve insertion order); False verdicts
        # are cached too — validity is deterministic in the record bytes,
        # so a poisoned entry can only ever answer for the same forged
        # bytes, and the cap bounds an attacker to evicting, not growing.
        #
        # graftguard changed the threading story that used to make this
        # lock-free: dispatch/fetch closures now execute on the guard's
        # DISPOSABLE launch threads, and an abandoned (wedged) launch
        # may complete late, concurrent with a fresh launch's fetch —
        # two writers.  The explicit lock makes the insert+evict pair
        # atomic; readers (connection threads' fast path, _pack's
        # cached-lookup) stay lockless — a dict read under the GIL can
        # at worst turn a hit into a miss, exactly as before.
        with self._verdicts_lock:
            if record not in self._verdicts:
                while len(self._verdicts) >= self.VERDICT_CACHE_CAP:
                    self._verdicts.pop(next(iter(self._verdicts)))
            self._verdicts[record] = ok

    def _bls_guard_key(self, req) -> str:
        """Launch-shape key for BLS work under the guard's per-shape
        deadlines: kind x pow2 committee size — a 4-vote aggregate and a
        100-vote one are genuinely different pairings (the Miller-loop
        count scales with the key set), so their p99 histories must not
        train each other's deadline."""
        from ..crypto.eddsa import next_pow2

        if isinstance(req, proto.BlsSignRequest):
            return "bls:sign"
        kind = {proto.BlsAggRequest: "agg",
                proto.BlsVotesRequest: "votes",
                proto.BlsMultiRequest: "multi"}[type(req)]
        return f"bls:{kind}:{next_pow2(max(1, len(req.pks)))}"

    def _execute_bls(self, item):
        """Run one BLS request under the launch guard (engine thread).

        The request body executes on one of the guard's DISPOSABLE
        launch threads under the shape's deadline (``_guarded``), so a
        wedged pairing — a hung tunneled device call mid
        ``verify_aggregate`` — trips the BLS arm of the degradation
        ladder instead of parking the engine thread: the client gets the
        TRANSIENT reply (``None`` -> the C++ side reads nullopt and runs
        its own outage handling, e.g. TC re-arm), and the crash-only
        engine reboot begins.  This closes ROADMAP item 3: BLS launches
        no longer sit outside the guard.

        SINGLE-REPLY DISCIPLINE (the PR 14 double-reply hazard, closed):
        ``_execute_bls_inner`` RETURNS its verdict instead of replying —
        replies happen here, on the engine thread, only after the
        guarded call came back clean, so a wedged-then-completing
        pairing's late result is discarded by the guard and can never
        race a ladder reply.  The idempotent ``reply`` helper stays as
        the belt.  _run installs NO backstop reply.

        Reply/caching contract: verdicts are cached ONLY when the inner
        body marks them cacheable — i.e. verdicts that are a pure
        function of the request bytes (decode/subgroup failures,
        completed verifications).  Transient failures (a wedged device, a
        backend exception) must reply ``None`` and NEVER a cacheable
        ``[False]``: the verdict cache is shared by every replica, so one
        poisoned entry would reject a valid certificate fleet-wide.
        """
        req = item.request
        cache_key = self.bls_cache_key(req) \
            if not isinstance(req, proto.BlsSignRequest) else None
        replied = [False]

        def reply(payload, *, cacheable=False):
            # cacheable=True asserts this verdict is a pure function of
            # the request bytes; nothing else may enter the shared cache.
            if replied[0]:
                log.warning(
                    "BLS double-reply suppressed for rid=%s (%s)",
                    req.request_id, type(req).__name__)
                return
            replied[0] = True
            if cacheable and cache_key is not None and payload:
                self._cache_verdict(cache_key, bool(payload[0]))
            item.reply_fn(payload)

        key = self._bls_guard_key(req)
        try:
            payload, cacheable = self._guarded(
                key, lambda: self._execute_bls_inner(req, cache_key))
            reply(payload, cacheable=cacheable)
        except WedgedLaunch:
            # BLS arm of the wedge ladder.  No host re-verify here: the
            # host pairing is the very work that may have wedged, and
            # re-running it inline would re-park the engine thread the
            # guard just saved.  Transient reply only — never a
            # cacheable [False] for a verdict nobody computed.
            log.error("guard: BLS launch %s WEDGED (deadline overrun); "
                      "transient reply, starting crash-only reboot", key)
            reply(None)
            self._begin_reboot()
        except Exception:
            log.exception("BLS request failed")
            # Transient by definition (deterministic failures return
            # cacheable verdicts from the inner body): never cacheable.
            reply(None)
        if not replied[0]:
            # Belt: a path that forgot to answer would leave the client
            # blocked until its recv deadline — reply the transient form.
            log.error("BLS path for rid=%s never replied; replying None",
                      req.request_id)
            reply(None)

    def _execute_bls_inner(self, req, cache_key):
        """The BLS request body; runs on a disposable guard launch
        thread and RETURNS ``(payload, cacheable)`` — it must not touch
        the connection (a wedged call's late completion is discarded by
        the guard; only the engine thread replies)."""
        from ..offchain import bls12381 as bls

        if isinstance(req, proto.BlsSignRequest):
            # Signing is G2 scalar multiplication — host bigint work, no
            # pairing; mirrors the reference keeping signing on CPU.
            sk = int.from_bytes(req.sk, "big")
            return bls.g2_encode(bls.sign(sk, req.msg)), False
        # Verdict cache (same FIFO as Ed25519, keyed on the full request):
        # N replicas verifying one certificate cost one pairing.  Decode
        # failures cache as False — deterministic in the request bytes.
        cached = self._verdicts.get(cache_key) if cache_key else None
        if cached is not None:
            return [cached], False

        if isinstance(req, proto.BlsMultiRequest):
            # TC shape: per-vote signatures over DISTINCT digests in one
            # RPC (round-3 verdict: this used to cost N sidecar
            # round-trips at view-change time).  Same decode policy as
            # the votes path: lax per-sig, subgroup test on the single
            # aggregate, strict cached decode for committee keys.
            try:
                agg = bls.aggregate(
                    [bls.g2_decode_lax(s) for s in req.sigs])
                if not bls.g2_in_subgroup(agg):
                    return [False], True
                pks = [bls.g1_decode(p) for p in req.pks]
            except ValueError:
                return [False], True
            if self._use_host or len(pks) not in self._bls_multi_warmed:
                if not self._use_host:
                    log.warning(
                        "BLS multi shape for %d votes not warmed "
                        "(--warm-bls-multi); verifying on host", len(pks))
                ok = bls.verify_aggregate(pks, req.msgs, agg)
            else:
                from ..ops import bls381 as dbls

                ok = dbls.verify_aggregate_multi(pks, req.msgs, agg)
            return [bool(ok)], True
        try:
            if isinstance(req, proto.BlsVotesRequest):
                # C++ nodes ship per-vote signatures; aggregate them here
                # (host G2 adds), then run the same common-message check.
                # Fresh per-vote sigs get on-curve checks only; the single
                # aggregate gets the [R]P subgroup test — the pairing
                # statement depends only on the aggregate, so this is the
                # same soundness at 1/N the host cost (per-vote subgroup
                # ladders can't be cached the way committee keys can).
                agg = bls.aggregate(
                    [bls.g2_decode_lax(s) for s in req.sigs])
                if not bls.g2_in_subgroup(agg):
                    return [False], True
            else:
                agg = bls.g2_decode(req.agg_sig)
            pks = [bls.g1_decode(p) for p in req.pks]
        except ValueError:
            return [False], True
        if self._use_host:
            ok = bls.verify_aggregate_common(pks, req.msg, agg)
        else:
            from ..ops import bls381 as dbls

            ok = dbls.verify_aggregate_common(pks, req.msg, agg)
        return [bool(ok)], True

    # graftlint: sanitizes=device-verdict
    def _verify_submit(self, msgs, pks, sigs, force_device: bool = False):
        """Dispatch one slice; returns fetch() -> (n,) bool mask.

        While a graftguard reboot is re-warming the device leg
        (``_device_ok`` False), everything verifies on host; the
        canary and poison-bisection probes pass ``force_device`` to
        exercise the device path they exist to validate."""
        if not msgs:
            return lambda: np.zeros((0,), bool)
        if self._use_host or (not self._device_ok and not force_device
                              and not getattr(self._rewarm_tls,
                                              "active", False)):
            from ..crypto import ref_ed25519 as ref

            res = np.array([ref.verify(p, m, s)
                            for m, p, s in zip(msgs, pks, sigs)])
            return lambda: res
        if self._mesh is not None:
            # The staged production entry (dispatched immediately): the
            # warmup path runs through here, so the exact donated mesh
            # program the engine launches is what gets compiled.
            from ..crypto.eddsa import prepare_batch
            from ..parallel.sharded_verify import verify_batch_sharded_pack

            return verify_batch_sharded_pack(self._mesh, prepare_batch(
                msgs, pks, sigs))()
        from ..crypto import eddsa

        return eddsa.verify_batch_submit(msgs, pks, sigs)

    def _verify(self, msgs, pks, sigs) -> np.ndarray:
        return np.asarray(self._verify_submit(msgs, pks, sigs)())


class _Handler(socketserver.BaseRequestHandler):
    """Reader loop per connection; replies go through a dedicated writer
    thread so a client that stops draining its socket stalls only its own
    connection, never the shared verify-engine thread."""

    def handle(self):
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        engine: VerifyEngine = self.server.engine  # type: ignore[attr-defined]
        outbox: "queue.Queue[bytes | None]" = queue.Queue(maxsize=1024)

        def writer():
            while True:
                frame = outbox.get()
                if frame is None:
                    return
                try:
                    sock.sendall(frame)
                except OSError:
                    return

        wt = threading.Thread(target=writer, daemon=True,
                              name="sidecar-conn-writer")
        wt.start()
        # graftfleet: the connection's scheduling tenant.  Set once by a
        # HELLO frame (protocol v6); connections that never HELLO — every
        # pre-v6 client — schedule under the default tenant, so the
        # single-tenant topology behaves exactly as before.
        tenant = proto.DEFAULT_TENANT
        try:
            while True:
                try:
                    payload = proto.read_frame(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    opcode, req = proto.decode_request(payload)
                except Exception:
                    log.exception("bad frame; closing connection")
                    return
                if opcode == proto.OP_HELLO:
                    # Tenant registration.  The reply echoes the server's
                    # protocol version + the accepted tenant id, so the
                    # client can fail fast on a version skew.  Distinct
                    # tenants are bounded server-side: past the cap the
                    # connection is refused (clean close, never a hang)
                    # so a tenant-id fuzzer cannot grow the scheduler's
                    # lane map without limit.
                    if not self.server.register_tenant(req.tenant):
                        log.warning(
                            "HELLO refused: tenant registry full "
                            "(tenant %r); closing connection", req.tenant)
                        return
                    tenant = req.tenant
                    outbox.put(proto.encode_hello_reply(
                        req.request_id, tenant))
                    continue
                if opcode == proto.OP_PING:
                    outbox.put(proto.encode_reply(
                        proto.OP_PING, req.request_id, []))
                    continue
                if opcode == proto.OP_STATS:
                    # Telemetry snapshot, answered on the connection
                    # thread: reading counters must never queue behind
                    # the device work being diagnosed.
                    outbox.put(proto.encode_stats_reply(
                        req.request_id, engine.stats_snapshot()))
                    continue
                chaos: ChaosState | None = \
                    getattr(self.server, "chaos", None)
                if opcode == proto.OP_CHAOS:
                    # [0] = refused (no --chaos): a production sidecar is
                    # not degradable over the wire, and the caller can
                    # tell refusal from success.
                    if chaos is None:
                        outbox.put(proto.encode_reply(
                            opcode, req.request_id, [0]))
                        continue
                    chaos.configure(req.spec)  # ValueError closes conn
                    outbox.put(proto.encode_reply(
                        opcode, req.request_id, [1]))
                    continue
                delay_s = 0.0
                if chaos is not None:
                    # Scripted misbehavior for verify/sign traffic only
                    # (PING/STATS/CHAOS above stay honest).  Decided
                    # BEFORE the verdict-cache fast path so a scripted
                    # shed/drop cannot be masked by a cache hit.
                    # graftlint: disable=unannotated-gate (fault injector, verify-shaped by name only)
                    drop, shed, delay_s = chaos.verify_action()
                    if drop:
                        log.warning("chaos: dropping connection")
                        return
                    if shed:
                        log.warning("chaos: forcing queue-full shed")
                        outbox.put(proto.encode_busy_reply(
                            req.request_id, engine.retry_after_ms(
                                vsched.class_of_opcode(opcode))))
                        continue

                def send(frame, _delay=delay_s):
                    # Delayed replies reschedule onto a timer so THIS
                    # reader thread keeps draining frames (a pipelined
                    # PING behind a delayed verify answers on time).
                    # put_nowait everywhere: a wedged connection drops
                    # its reply and the reader reaps it, never a blocked
                    # thread (the established outbox policy).
                    def enqueue():
                        try:
                            outbox.put_nowait(frame)
                        except queue.Full:
                            pass
                    if _delay:
                        t = threading.Timer(_delay, enqueue)
                        t.daemon = True
                        t.start()
                    else:
                        enqueue()

                # Cache fast path: a fully-cached Ed25519 verify request is
                # answered on THIS connection thread — no engine queue
                # round trip.  At testbed scale (100 replicas, one
                # sidecar) the common request is the 99th replica
                # verifying a QC the engine already judged; four thread
                # hops per cached answer is what saturates the host, not
                # the device.  Dict reads under the GIL are safe against
                # the engine thread's insert/evict writes.
                is_bls = False
                if opcode in (proto.OP_VERIFY_BATCH, proto.OP_VERIFY_BULK):
                    verdicts = engine.cached_verdicts(req)
                    if verdicts is not None:
                        send(proto.encode_reply(
                            opcode, req.request_id, verdicts))
                        continue
                elif opcode in (proto.OP_BLS_VERIFY_AGG,
                                proto.OP_BLS_VERIFY_VOTES,
                                proto.OP_BLS_VERIFY_MULTI):
                    is_bls = True
                    verdicts = engine.cached_bls_verdict(req)
                    if verdicts is not None:
                        send(proto.encode_reply(
                            opcode, req.request_id, verdicts))
                        continue
                elif opcode == proto.OP_BLS_SIGN:
                    is_bls = True

                def reply(result, _rid=req.request_id, _op=opcode,
                          _send=send):
                    if isinstance(result, BusyReply):
                        # graftguard wedge ladder: a bulk request whose
                        # launch wedged gets the honest OP_BUSY with the
                        # drain-derived retry-after, never a fake mask.
                        frame = proto.encode_busy_reply(
                            _rid, result.retry_after_ms)
                    elif _op == proto.OP_BLS_SIGN:
                        frame = proto.encode_reply_raw(
                            _op, _rid, result if result else b"")
                    else:
                        frame = proto.encode_reply(
                            _op, _rid, result if result is not None
                            else [False])
                    _send(frame)

                # Admission is bounded: a full class queue is answered
                # HERE with an explicit OP_BUSY reply carrying the
                # retry-after hint (protocol v4; clients that predate it
                # still read the off-opcode reply as overload, never as
                # a verdict).  Clients back off / shed to host verify;
                # no connection thread ever blocks on a saturated
                # engine.
                cls = vsched.class_of_opcode(opcode)
                if not engine.submit(req, reply, cls=cls, is_bls=is_bls,
                                     tenant=tenant):
                    outbox.put(proto.encode_busy_reply(
                        req.request_id, engine.retry_after_ms(cls)))
        finally:
            outbox.put(None)


class SidecarServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    # graftfleet: distinct tenant ids one server process will register
    # over its lifetime.  A fleet fronts committees, not the open
    # internet; the bound keeps a HELLO fuzzer from growing the
    # scheduler's lane map and the stats dict without limit.
    TENANT_REGISTRY_CAP = 256

    def __init__(self, addr, engine: VerifyEngine,
                 chaos: ChaosState | None = None):
        super().__init__(addr, _Handler)
        self.engine = engine
        self.chaos = chaos
        self._tenants_seen: set = set()
        self._tenants_lock = threading.Lock()

    def register_tenant(self, tenant: str) -> bool:
        """Accept a HELLO tenant id; False once the registry is full
        (re-HELLOs of a known tenant always succeed — a tenant id
        COLLISION is by design: both connections share one lane)."""
        with self._tenants_lock:
            if tenant in self._tenants_seen:
                return True
            if len(self._tenants_seen) >= self.TENANT_REGISTRY_CAP:
                return False
            self._tenants_seen.add(tenant)
            return True


def serve(host: str = "127.0.0.1", port: int = 7100,
          mesh_devices: int | None = None, use_host: bool = False,
          ready_event: threading.Event | None = None,
          warm_max: int = MAX_SUBBATCH, warm_bls: bool = False,
          warm_bls_multi: int = 0, warm_bulk: bool = False,
          warm_rlc: bool = False, warm_rlc_sharded: bool = False,
          chaos: bool = False,
          committee: int | None = None, client_rate: int | None = None,
          trace_path: str | None = None,
          cadence: bool | None = None,
          tcp: str | None = None):
    # graftcadence opt-in: --cadence wins, then HOTSTUFF_TPU_CADENCE;
    # the staged engine stays the default (ring.cadence_enabled).
    from .ring import RingDepth, cadence_enabled

    if cadence is None:
        cadence = cadence_enabled()
    tracer = None
    if trace_path:
        from ..obs.spans import Tracer

        tracer = Tracer(trace_path)
        log.info("grafttrace span emission -> %s", trace_path)
    # graftguard: chaos state is built BEFORE the engine so the wedge
    # knob can reach the dispatch path, and every boot gets a launch
    # supervisor — per-shape deadlines off the compile manifest (device
    # boots) or the defaults (host boots: supervision still catches a
    # hung host stage, and the chaos drill needs it).
    chaos_state = None
    if chaos:
        chaos_state = ChaosState()
        log.warning("chaos hook ENABLED (--chaos): OP_CHAOS requests can "
                    "degrade this sidecar")
    from .guard import LaunchDeadlines, LaunchGuard

    cache_dir = None
    tracker = None
    if not use_host:
        cache_dir = _enable_compilation_cache()
        # graftkern compile accounting: every warmup shape below runs
        # under the tracker, so OP_STATS ``compile`` reports manifest
        # hits/misses + warmup wall time and a second boot against a
        # populated cache proves itself (misses == 0, lower wall).
        from ..utils.xla_cache import CompileTracker

        tracker = CompileTracker(cache_dir=cache_dir)
        guard = LaunchGuard(deadlines=LaunchDeadlines.from_manifest(
            tracker.manifest, tracker.kernel))
    else:
        # Host-crypto boots compile nothing, so the cold 180 s compile
        # budget would be the wrong deadline class — the warm grace
        # (30 s default: a MAX_SUBBATCH host slice is ~10 s of pure
        # python) is what a hung host launch should be judged against.
        guard = LaunchGuard(deadlines=LaunchDeadlines(warm_boot=True))
    engine = VerifyEngine(mesh_devices=mesh_devices, use_host=use_host,
                          committee=committee, client_rate=client_rate,
                          tracer=tracer, guard=guard, chaos=chaos_state,
                          cadence=cadence)
    if cadence:
        log.info("graftcadence: resident ring ENABLED (depth %d)",
                 engine._ring.depth.depth())
        if tracker is not None:
            # Seed the depth trainer from the manifest's measured
            # per-shape walls, the same record LaunchDeadlines reads
            # for its warm-boot decision.
            engine._ring.depth = RingDepth.from_manifest(
                tracker.manifest, tracker.kernel)
    # Warm the jit cache BEFORE binding: until the socket exists, node
    # crypto gets ECONNREFUSED and falls back to host verify instead of
    # connecting into a server whose device thread is still compiling.
    # (A bound-but-compiling socket accepts into the TCP backlog and
    # silently stalls every client for the whole compile — the round-2
    # 0-TPS failure mode.)
    if not use_host:
        engine.compile_tracker = tracker
        _warmup(engine, warm_max)
        if warm_bls:
            tracker.warm("bls:pairing", _warmup_bls)
        if warm_bls_multi:
            tracker.warm(f"bls_multi:{warm_bls_multi}",
                         lambda: _warmup_bls_multi(engine, warm_bls_multi))
        if warm_bulk:
            # Single-chip: the chunked-scan shapes.  Mesh: the
            # whole-backlog chunked mesh scan (graftscale) — the mesh
            # registry gates enable_bulk on those scan shapes, so the
            # cap only rises when the one-dispatch drain really exists.
            _warmup_bulk(engine, warm_max)
            engine.enable_bulk()
        if warm_rlc and not (mesh_devices and mesh_devices > 1):
            # Single-chip only: the mesh path routes through
            # verify_rlc_sharded, whose warmup is --warm-rlc-sharded
            # below (per-SHARD buckets, not global ones).
            _warmup_rlc(engine, warm_max)
        if warm_rlc_sharded and mesh_devices and mesh_devices > 1:
            # Mesh one-MSM warmup: compiles verify_rlc_sharded AND
            # verify_batch_sharded at every per-shard bucket up to the
            # cap, so the scheduler routes coalesced launches of
            # RLC_MIN_LAUNCH+ unique records down the sharded MSM path
            # with its bisection fallback already compiled.
            _warmup_rlc_sharded(engine, warm_max)
        tracker.finish()
        log.info(
            "warmup compile cache: %d hit(s), %d miss(es) in %.1fs "
            "(kernel %s%s)", tracker.hits, tracker.misses,
            tracker.wall_s(), tracker.kernel,
            "" if cache_dir else "; XLA disk cache OFF")

        def _rewarm():
            # graftguard crash-only reboot: re-run the SAME warmup legs
            # this boot ran, against the now-populated XLA disk cache —
            # a deserialization pass (38 s measured warm vs 149 s cold,
            # PR 11), during which the host path owns live traffic.
            # BLS warmups are skipped: the pairing programs are minutes
            # of compile; un-warmed shapes fall back to the host pairing
            # (_bls_multi_warmed), which now runs under the guard's
            # deadline like every other BLS launch.
            _warmup(engine, warm_max)
            if warm_bulk:
                _warmup_bulk(engine, warm_max)
            if warm_rlc and not (mesh_devices and mesh_devices > 1):
                _warmup_rlc(engine, warm_max)
            if warm_rlc_sharded and mesh_devices and mesh_devices > 1:
                _warmup_rlc_sharded(engine, warm_max)

        engine._rewarm_fn = _rewarm
    server = SidecarServer((host, port), engine, chaos=chaos_state)
    log.info("sidecar listening on %s:%d", host, server.server_address[1])
    # graftfleet: --tcp HOST:PORT binds a SECOND listener next to the
    # primary, sharing the same engine, scheduler, verdict cache and
    # chaos hook — the shape a shared fleet member serves remote tenants
    # through while local clients keep the loopback socket.  Both
    # listeners speak the same protocol (HELLO/tenant included); the
    # tenant registry is per-SERVER, so the two listeners' tenants are
    # bounded independently but share the scheduler's lanes.
    tcp_server = None
    tcp_thread = None
    if tcp:
        tcp_host, _, tcp_port = tcp.rpartition(":")
        tcp_server = SidecarServer((tcp_host or "0.0.0.0", int(tcp_port)),
                                   engine, chaos=chaos_state)
        log.info("sidecar fleet listener on %s:%d", tcp_host or "0.0.0.0",
                 tcp_server.server_address[1])
        tcp_thread = threading.Thread(
            target=lambda: tcp_server.serve_forever(poll_interval=0.2),
            daemon=True, name="sidecar-tcp-listener")
        tcp_thread.start()
    if ready_event is not None:
        ready_event.set()
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        engine.stop()
        guard.close()
        server.server_close()
        if tcp_server is not None:
            tcp_server.shutdown()
            tcp_server.server_close()
        if tracer is not None:
            tracer.close()
    return server


def _enable_compilation_cache():
    """Persist XLA compilations across sidecar restarts; the BLS pairing
    program alone is minutes of compile, paid once per cache dir.
    Returns the cache dir (None when disabled) for the CompileTracker's
    OP_STATS ``compile`` section."""
    from ..utils.xla_cache import configure_xla_cache

    return configure_xla_cache()


def _warmup_bls(n_pks: int = 3):
    """Compile the device pairing program before listen() so the first QC
    under scheme=bls doesn't eat a multi-minute compile against the C++
    client's 60 s deadline."""
    from ..offchain import bls12381 as bls
    from ..ops import bls381 as dbls

    t0 = monotonic()
    dbls.selfcheck()
    msg = b"warmup"
    keys = [bls.key_gen(bytes([i]) * 32) for i in range(1, n_pks + 1)]
    agg = bls.aggregate([bls.sign(sk, msg) for sk, _ in keys])
    if not dbls.verify_aggregate_common([pk for _, pk in keys], msg, agg):
        log.error("BLS warmup verify returned False")
    log.info("BLS pairing warmup done in %.1fs", monotonic() - t0)


def _warmup_bls_multi(engine, n_votes: int):
    """Compile the n-vote multi-digest pairing shape (TC verify at quorum
    size n) before listen(); registers the shape so the engine may launch
    it on device. The program compiles one shape per vote count, so the
    harness passes the committee's quorum size."""
    from ..offchain import bls12381 as bls
    from ..ops import bls381 as dbls

    t0 = monotonic()
    keys = [bls.key_gen(bytes([i + 1]) * 32) for i in range(n_votes)]
    msgs = [bytes([i]) * 32 for i in range(n_votes)]
    agg = bls.aggregate([bls.sign(sk, m)
                         for (sk, _), m in zip(keys, msgs)])
    if not dbls.verify_aggregate_multi([pk for _, pk in keys], msgs, agg):
        log.error("BLS multi warmup verify returned False")
    engine._bls_multi_warmed.add(n_votes)
    log.info("BLS multi-digest warmup (%d votes) done in %.1fs",
             n_votes, monotonic() - t0)


def _warmed(engine, key: str, thunk):
    """Run one warmup shape, through the engine's CompileTracker when
    one is attached (device boots) so the manifest hit/miss accounting
    sees every shape; bare otherwise (tests, host mode)."""
    tracker = getattr(engine, "compile_tracker", None)
    if tracker is not None:
        return tracker.warm(key, thunk)
    return thunk()


def _warm_shapes(engine, start: int, stop: int, label: str):
    """Compile padded batch shapes start, 2*start, ... stop through the
    engine's own verify path so the exact jitted callables are cached,
    and record each shape in the scheduler's warmed-shape registry."""
    from ..crypto import ref_ed25519 as ref

    sk = bytes(range(32))
    _, pk = ref.generate_keypair(sk)
    msg = b"\x00" * 32
    sig = ref.sign(sk, msg)
    n = start
    while n <= stop:
        t0 = monotonic()

        def _one(n=n):
            mask = engine._verify([msg] * n, [pk] * n, [sig] * n)
            if not all(mask):
                log.error("%s verify returned false at N=%d", label, n)

        _warmed(engine, f"{label.replace(' ', '_')}:{n}", _one)
        if n <= MAX_SUBBATCH:
            engine._shapes.mark_bucket(n)
        else:
            engine._shapes.mark_chunks(n // MAX_SUBBATCH)
        log.info("%s N=%d done in %.1fs", label, n, monotonic() - t0)
        n *= 2


def _warmup_bulk(engine, warm_max: int = MAX_SUBBATCH):
    """Compile the chunked-scan shapes (g = 2 .. 16 sub-batches) that bulk
    coalescing can hit once enable_bulk() raises the launch cap.  Cached
    across restarts by the persistent compilation cache.  On a mesh
    engine the bulk drain is the whole-backlog chunked mesh scan
    (graftscale), so that is what gets compiled — and what the
    registry's gated enable_bulk requires."""
    if engine._mesh is not None:
        _warmup_mesh_scan(engine, warm_max)
        return
    _warm_shapes(engine, 2 * MAX_SUBBATCH, MAX_COALESCED, "bulk warmup")


def _warmup_mesh_scan(engine, warm_max: int = MAX_SUBBATCH,
                      scan_chunks: int | None = None):
    """Compile the whole-backlog chunked mesh scan
    (parallel/sharded_verify.verify_sharded_chunked) at every chunk
    count the engine may launch — g = 2, 4, ... MESH_SCAN_CHUNKS chunks
    of the top warmed per-shard bucket — through the REAL staged entry,
    and mark each (g, rows) in the registry (mark_mesh_chunks) so the
    router starts choosing ``scan_sharded`` and the gated enable_bulk
    may raise the launch cap to the scan capacity.  A backlog whose
    chunk count is not marked here falls back to the sliced ladder —
    an unwarmed scan shape never compiles mid-run.  ``scan_chunks``
    lowers the warmed chunk-count ceiling (tests trade drain capacity
    for compile wall; production keeps the default)."""
    from ..crypto import eddsa, ref_ed25519 as ref
    from ..parallel import sharded_verify as shv

    n_dev = engine._shapes.n_devices
    if n_dev < 2 or engine._mesh is None:
        log.warning("mesh scan warmup ignored: no device mesh")
        return
    if engine._shapes.mesh_chunks:
        # Already warmed (a --warm-bulk boot runs this before the
        # --warm-rlc-sharded leg does): every rerun thunk would be a
        # compile-cache hit but still pay a full n_dev*g*rows verify
        # per chunk count — skip the duplicate boot wall.
        return
    if scan_chunks is None:
        scan_chunks = vsched.MESH_SCAN_CHUNKS
    sk = bytes(range(32))
    _, pk = ref.generate_keypair(sk)
    msg = b"\x03" * 32
    sig = ref.sign(sk, msg)
    # The committee floor applies here exactly as in the RLC warmup, so
    # every caller (--warm-bulk's mesh leg, --warm-rlc-sharded's scan
    # leg) derives the SAME chunk rows — mark_mesh_chunks enforces one
    # rows value per registry.
    cap = min(max(warm_max, engine._shapes.qc_sigs or 0), MAX_SUBBATCH)
    rows = shv.shard_bucket(cap, n_dev)
    g = 2
    while g <= min(scan_chunks, vsched.MESH_SCAN_CHUNKS):
        n = n_dev * g * rows
        t0 = monotonic()

        def _one(n=n, rows=rows):
            prep = eddsa.prepare_batch([msg] * n, [pk] * n, [sig] * n)
            mask = shv.verify_sharded_chunked_pack(
                engine._mesh, prep, rows=rows)()()
            if not all(mask):
                log.error("mesh scan warmup verify returned false "
                          "at N=%d", n)

        _warmed(engine, f"mesh_scan:{n_dev}x{g}x{rows}", _one)
        engine._shapes.mark_mesh_chunks(g, rows)
        log.info("mesh scan warmup N=%d (%d chunks of %d rows/shard) "
                 "done in %.1fs", n, g, rows, monotonic() - t0)
        g *= 2


def _warmup(engine, warm_max: int = MAX_SUBBATCH):
    """Compile every padded batch shape a live run will hit.

    Requests pad to power-of-two buckets (crypto/eddsa._bucket) and the
    coalescer caps launches at MAX_SUBBATCH, so warming N = 8, 16, ...
    MAX_SUBBATCH covers every shape the engine can launch (a smaller
    warm_max trades boot time for possible mid-traffic compiles). Uses the
    engine's own verify path so the exact jitted callable is cached.
    """
    _warm_shapes(engine, 8, warm_max, "warmup")


def _warmup_rlc_sharded(engine, warm_max: int = MAX_SUBBATCH,
                        scan_chunks: int | None = None):
    """Compile the MESH verify programs at every per-shard bucket the
    engine may launch, and register the shapes so the scheduler's router
    starts choosing the ``rlc_sharded`` path.

    Walks GLOBAL sizes n = n_dev * per_shard for every power-of-two
    per-shard bucket from the floor (parallel/shard_shapes.shard_bucket
    of the smallest batch) up to the launch cap, running each through
    the REAL staged entries — verify_rlc_sharded_pack AND
    verify_batch_sharded_pack — so both the one-MSM program and its
    per-signature bisection/fallback program are compiled for every
    bucket before the socket binds.  Bisection halves land on smaller
    buckets, which this loop has always already compiled (increasing
    order).

    graftscale: the warmup ceiling is raised to the committee's quorum
    size when one is served (``--committee N`` -> ShapeRegistry.qc_sigs
    = 2N/3+1), so a giant-committee QC batch — ~667 signatures at
    N=1000 — always lands on a warmed sharded-RLC bucket and never
    takes the sliced ladder.  Afterwards the whole-backlog chunked
    mesh scan shapes are compiled too (_warmup_mesh_scan) and the
    launch cap rises through the gated enable_bulk, so mesh boots
    (the harness's ``--mesh N --warm-rlc-sharded``) drain coalesced
    bulk backlogs in ONE launch from the first block.
    """
    from ..crypto import eddsa, ref_ed25519 as ref
    from ..parallel import sharded_verify as shv

    n_dev = engine._shapes.n_devices
    if n_dev < 2 or engine._mesh is None:
        log.warning("--warm-rlc-sharded ignored: no device mesh")
        return
    sk = bytes(range(32))
    _, pk = ref.generate_keypair(sk)
    msg = b"\x02" * 32
    sig = ref.sign(sk, msg)
    per = shv.shard_bucket(1, n_dev)          # the smallest bucket
    # Largest routed launch: warm_max, floored at the served quorum so
    # the committee's own QC shape is always covered.
    cap = min(max(warm_max, engine._shapes.qc_sigs or 0), MAX_SUBBATCH)
    top = shv.shard_bucket(cap, n_dev)        # its per-shard bucket
    while per <= top:
        n = n_dev * per
        t0 = monotonic()

        def _one(n=n):
            # One prep serves both programs: neither pack entry mutates
            # the host dict (padding copies before device_put).
            prep = eddsa.prepare_batch([msg] * n, [pk] * n, [sig] * n)
            mask = shv.verify_batch_sharded_pack(engine._mesh, prep)()()
            if not all(mask):
                log.error("sharded warmup verify returned false at N=%d",
                          n)
            mask = shv.verify_rlc_sharded_pack(engine._mesh, prep)()()
            if not all(mask):
                log.error("RLC sharded warmup verify returned false "
                          "at N=%d", n)

        _warmed(engine, f"rlc_sharded:{n_dev}x{per}", _one)
        engine._shapes.mark_bucket(n)
        engine._shapes.mark_rlc_sharded(n)
        log.info("RLC sharded warmup N=%d (per-shard bucket %d) done "
                 "in %.1fs", n, per, monotonic() - t0)
        per *= 2
    # The whole-backlog scan leg: chunk counts over the top bucket just
    # warmed, then the (gated) launch-cap raise — after this, mesh bulk
    # stops slicing at the old MAX_SUBBATCH cap.
    _warmup_mesh_scan(engine, cap, scan_chunks=scan_chunks)
    engine.enable_bulk()


def _warmup_rlc(engine, warm_max: int = MAX_SUBBATCH):
    """Compile the one-MSM RLC program at every padded bucket the engine
    may route to it (RLC_MIN_LAUNCH .. warm_max), and register the shapes
    so the scheduler's router starts choosing the RLC path.

    Runs all-valid batches in INCREASING size through the real
    verify_batch_rlc entry, so the bisection fallback's smaller-bucket
    programs are always already compiled when a larger bucket first
    bisects mid-traffic (the per-signature floor shapes come from
    _warmup, which serve() always runs first).  Starts at the bucket
    floor (8), BELOW the routing threshold: bisection halves sub-batches
    down to RLC_MIN_MSM regardless of what the router admits, so the
    small RLC shapes must exist even though no whole batch routes to
    them."""
    from ..crypto import eddsa, ref_ed25519 as ref

    sk = bytes(range(32))
    _, pk = ref.generate_keypair(sk)
    msg = b"\x01" * 32
    sig = ref.sign(sk, msg)
    n = 8  # == crypto/eddsa._MIN_BUCKET, the smallest padded shape
    while n <= min(warm_max, MAX_SUBBATCH):
        t0 = monotonic()

        def _one(n=n):
            mask = eddsa.verify_batch_rlc([msg] * n, [pk] * n, [sig] * n)
            if not all(mask):
                log.error("RLC warmup verify returned false at N=%d", n)

        _warmed(engine, f"rlc:{n}", _one)
        engine._shapes.mark_rlc(n)
        log.info("RLC warmup N=%d done in %.1fs", n, monotonic() - t0)
        n *= 2


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7100)
    ap.add_argument("--mesh", type=int, default=0,
                    help="shard verify over an N-device mesh (0 = single)")
    ap.add_argument("--host-crypto", action="store_true",
                    help="pure-host verification (debug/fallback)")
    ap.add_argument("--warm", type=int, default=MAX_SUBBATCH,
                    help="largest batch shape to pre-compile before "
                         "listening (power-of-two buckets up to this; "
                         "default covers every launchable shape)")
    ap.add_argument("--warm-bls", action="store_true",
                    help="also pre-compile the BLS pairing program "
                         "(scheme=bls deployments)")
    ap.add_argument("--warm-bls-multi", type=int, default=0, metavar="N",
                    help="also pre-compile the N-vote multi-digest pairing "
                         "shape (the TC verify at quorum size N); unwarmed "
                         "shapes fall back to host pairing")
    ap.add_argument("--warm-bulk", action="store_true",
                    help="also pre-compile the chunked-scan bulk shapes and "
                         "raise the per-launch cap to %d sigs (bulk/offchain "
                         "workloads)" % MAX_COALESCED)
    ap.add_argument("--warm-rlc", action="store_true",
                    help="also pre-compile the one-MSM RLC batch-verify "
                         "shapes so coalesced batches of %d+ signatures "
                         "route through the combined check"
                         % vsched.RLC_MIN_LAUNCH)
    ap.add_argument("--warm-rlc-sharded", action="store_true",
                    help="with --mesh N: pre-compile the mesh-sharded "
                         "one-MSM RLC programs (and their per-signature "
                         "fallback) at every per-shard bucket, so "
                         "coalesced batches of %d+ signatures route "
                         "through the sharded combined check"
                         % vsched.RLC_MIN_LAUNCH)
    ap.add_argument("--tcp", default=None, metavar="HOST:PORT",
                    help="graftfleet: bind a second listener (same "
                         "engine and scheduler) on HOST:PORT for remote "
                         "tenants — fleet members serve shared traffic "
                         "here while local clients keep the primary "
                         "socket; protocol v6 HELLO frames carry the "
                         "tenant id on either listener")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="append grafttrace JSONL spans (admit/queue/"
                         "pack/dispatch/device/reply, tagged rid + "
                         "scheduler class) to PATH; obs/trace.py merges "
                         "them into the run's trace.json")
    ap.add_argument("--cadence", action="store_true",
                    help="run the graftcadence resident verify ring "
                         "(continuous batching: depth-k dispatch at a "
                         "load-adaptive tick, generation-tagged "
                         "verdicts) instead of the staged request-"
                         "driven loop; HOTSTUFF_TPU_CADENCE=1 is the "
                         "env equivalent and the staged engine stays "
                         "the default")
    ap.add_argument("--chaos", action="store_true",
                    help="enable the OP_CHAOS fault-injection hook "
                         "(bounded reply delay, forced connection drops, "
                         "forced queue-full sheds) — graftchaos testbeds "
                         "only, never production")
    ap.add_argument("--committee", type=int, default=0, metavar="N",
                    help="committee size served; sizes the latency-class "
                         "admission cap (0 = static default)")
    ap.add_argument("--client-rate", type=int, default=0, metavar="TPS",
                    help="aggregate client tx rate; sizes the bulk-class "
                         "admission cap (0 = static default)")
    ap.add_argument("-v", "--verbose", action="count", default=0)
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s.%(msecs)03dZ %(levelname)s [%(name)s] %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S")
    serve(args.host, args.port, mesh_devices=args.mesh or None,
          use_host=args.host_crypto, warm_max=args.warm,
          warm_bls=args.warm_bls, warm_bls_multi=args.warm_bls_multi,
          warm_bulk=args.warm_bulk, warm_rlc=args.warm_rlc,
          warm_rlc_sharded=args.warm_rlc_sharded,
          chaos=args.chaos, committee=args.committee or None,
          client_rate=args.client_rate or None,
          trace_path=args.trace,
          cadence=True if args.cadence else None,
          tcp=args.tcp)


if __name__ == "__main__":
    main()

"""Python client for the verify sidecar (test + harness use; the node's
production client is the C++ implementation in native/crypto)."""

from __future__ import annotations

import socket
import threading

from . import protocol as proto


class SidecarClient:
    """Blocking, thread-safe client with request pipelining."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7100,
                 timeout: float | None = 60.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._next_id = 0
        self._results: dict[int, list] = {}
        self._abandoned: set[int] = set()
        self._cond = threading.Condition()

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def ping(self) -> bool:
        rid = self._send(proto.encode_ping)
        self._await(rid)
        return True

    def verify_batch(self, msgs, pks, sigs) -> list:
        """Returns per-signature validity list of bools."""
        if not msgs:
            return []
        rid = self._send(lambda r: proto.encode_request(r, msgs, pks, sigs))
        return [bool(b) for b in self._await(rid)]

    def bls_verify_aggregate(self, msg: bytes, agg_sig: bytes, pks) -> bool:
        """Common-message BLS aggregate verify (pks: 96 B uncompressed G1,
        agg_sig: 192 B uncompressed G2)."""
        rid = self._send(
            lambda r: proto.encode_bls_agg_request(r, msg, agg_sig, pks))
        body = self._await(rid)
        return bool(body and body[0])

    def bls_verify_multi(self, msgs, pks, sigs) -> bool:
        """Multi-digest BLS verify (the TC shape): n (digest, pk, sig)
        triples checked as one product of pairings in ONE round-trip."""
        rid = self._send(
            lambda r: proto.encode_bls_multi_request(r, msgs, pks, sigs))
        body = self._await(rid)
        return bool(body and body[0])

    def bls_sign(self, msg: bytes, sk: bytes) -> bytes:
        """BLS sign via the sidecar's host signer -> 192 B G2 signature.
        Raises on failure (the service replies with an empty body)."""
        rid = self._send(lambda r: proto.encode_bls_sign_request(r, msg, sk))
        sig = bytes(self._await(rid))
        if len(sig) != proto.BLS_SIG_LEN:
            raise RuntimeError("sidecar BLS signing failed")
        return sig

    # -- internals ---------------------------------------------------------

    def _send(self, make_frame):
        with self._send_lock:
            rid = self._next_id
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            frame = make_frame(rid)
            self._sock.sendall(frame)
            return rid

    def _await(self, rid):
        try:
            while True:
                with self._cond:
                    if rid in self._results:
                        return self._results.pop(rid)
                # one thread at a time drains the socket; results are
                # published under the condition so pipelined waiters wake up
                if self._recv_lock.acquire(timeout=0.05):
                    try:
                        with self._cond:
                            if rid in self._results:
                                return self._results.pop(rid)
                        payload = proto.read_frame(self._sock)
                        _, got_rid, body = proto.decode_reply_raw(payload)
                        with self._cond:
                            if got_rid in self._abandoned:
                                self._abandoned.discard(got_rid)
                            else:
                                self._results[got_rid] = body
                                self._cond.notify_all()
                    finally:
                        self._recv_lock.release()
                else:
                    with self._cond:
                        self._cond.wait(timeout=0.05)
        except BaseException:
            # Abandoned request: reap a published result, or mark the rid so
            # the drainer drops its reply when it later arrives — either way
            # long-lived pipelined clients don't leak masks in _results.
            with self._cond:
                if self._results.pop(rid, None) is None:
                    self._abandoned.add(rid)
            raise

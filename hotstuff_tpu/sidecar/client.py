"""Python client for the verify sidecar (test + harness use; the node's
production client is the C++ implementation in native/crypto)."""

from __future__ import annotations

import socket
import threading

from . import protocol as proto


class SidecarOverloaded(RuntimeError):
    """The sidecar's class queue was full and it shed this request
    (explicit OP_BUSY backpressure reply, or the legacy empty-body form
    — see protocol.py).  ``retry_after_ms`` carries the sidecar's hint
    when the reply had one (None on the legacy form).  The caller
    decides: retry after ~the hint, or verify on host."""

    def __init__(self, message: str, retry_after_ms: int | None = None):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms


class SidecarClient:
    """Blocking, thread-safe client with request pipelining."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7100,
                 timeout: float | None = 60.0,
                 tenant: str | None = None):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._next_id = 0
        self._results: dict[int, list] = {}
        self._abandoned: set[int] = set()
        self._cond = threading.Condition()
        self.server_version: int | None = None
        if tenant is not None:
            self.hello(tenant)

    def hello(self, tenant: str) -> str:
        """graftfleet HELLO (protocol v6): register this connection's
        scheduling tenant.  Returns the tenant the server accepted and
        records the server's protocol version in ``server_version``;
        connections that never HELLO schedule under the default tenant."""
        rid = self._send(
            lambda r: proto.encode_hello_request(r, tenant))
        body = bytes(self._await(rid))
        version, accepted = proto.decode_hello_body(body)
        self.server_version = version
        return accepted

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def ping(self) -> bool:
        rid = self._send(proto.encode_ping)
        self._await(rid)
        return True

    def verify_batch(self, msgs, pks, sigs, *, bulk: bool = False,
                     ctx: bytes | None = None) -> list:
        """Returns per-signature validity list of bools.

        ``bulk=True`` tags the request bulk-class on the wire
        (OP_VERIFY_BULK): it coalesces behind consensus-latency verifies
        instead of ahead of them.  Mempool batch verification and
        offchain sweeps should pass it; QC/TC verification must not.

        ``ctx`` (protocol v5, graftscope) attaches the 32-byte block
        digest this verify serves, so the sidecar's stage spans join the
        block's node-side trace in logs/trace.json.

        Raises :class:`SidecarOverloaded` when the sidecar sheds the
        request (its class queue was full)."""
        if not msgs:
            return []
        op = proto.OP_VERIFY_BULK if bulk else proto.OP_VERIFY_BATCH
        rid = self._send(
            lambda r: proto.encode_request(r, msgs, pks, sigs, opcode=op,
                                           ctx=ctx))
        body = self._await(rid)
        if len(body) != len(msgs):
            raise SidecarOverloaded(
                f"sidecar shed {'bulk' if bulk else 'latency'}-class "
                f"verify of {len(msgs)} records (queue full)")
        return [bool(b) for b in body]

    def stats(self) -> dict:
        """Scheduler-telemetry snapshot (the OP_STATS round trip)."""
        rid = self._send(proto.encode_stats_request)
        return proto.decode_stats_body(bytes(self._await(rid)))

    def chaos(self, **spec) -> bool:
        """Configure the sidecar's fault-injection hook (OP_CHAOS):
        ``delay_ms=``, ``shed=``, ``drop=``, ``clear=True`` — see
        service.ChaosState.  Returns True when applied, False when the
        sidecar runs without ``--chaos`` (refusal, not an error: the
        graftchaos injector turns it into a reported plan failure)."""
        rid = self._send(lambda r: proto.encode_chaos_request(r, spec))
        body = self._await(rid)
        return bool(body) and bool(body[0])

    def bls_verify_aggregate(self, msg: bytes, agg_sig: bytes, pks) -> bool:
        """Common-message BLS aggregate verify (pks: 96 B uncompressed G1,
        agg_sig: 192 B uncompressed G2).  Raises SidecarOverloaded on a
        queue-full shed — an overload must never read as 'forged'."""
        rid = self._send(
            lambda r: proto.encode_bls_agg_request(r, msg, agg_sig, pks))
        return self._bls_verdict(self._await(rid))

    def bls_verify_multi(self, msgs, pks, sigs) -> bool:
        """Multi-digest BLS verify (the TC shape): n (digest, pk, sig)
        triples checked as one product of pairings in ONE round-trip.
        Raises SidecarOverloaded on a queue-full shed."""
        rid = self._send(
            lambda r: proto.encode_bls_multi_request(r, msgs, pks, sigs))
        return self._bls_verdict(self._await(rid))

    @staticmethod
    def _bls_verdict(body) -> bool:
        # A real BLS verdict is always exactly one 0/1 byte (errors reply
        # [False], never nothing) — an empty body is the scheduler's
        # explicit queue-full shed, which must surface as overload, not
        # as an invalid certificate.
        if not body:
            raise SidecarOverloaded(
                "sidecar shed BLS verify (queue full)")
        return bool(body[0])

    def bls_sign(self, msg: bytes, sk: bytes) -> bytes:
        """BLS sign via the sidecar's host signer -> 192 B G2 signature.
        A queue-full shed raises :class:`SidecarOverloaded` (v4 OP_BUSY,
        with ``retry_after_ms``); a signing failure replies an empty
        body and raises RuntimeError.  Either way the caller retries."""
        rid = self._send(lambda r: proto.encode_bls_sign_request(r, msg, sk))
        sig = bytes(self._await(rid))
        if len(sig) != proto.BLS_SIG_LEN:
            raise RuntimeError("sidecar BLS signing failed or shed")
        return sig

    # -- internals ---------------------------------------------------------

    def _send(self, make_frame):
        with self._send_lock:
            rid = self._next_id
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            frame = make_frame(rid)
            self._sock.sendall(frame)
            return rid

    @staticmethod
    def _unwrap(opcode, body):
        """Reply -> body, surfacing OP_BUSY sheds as SidecarOverloaded
        with the server's retry-after hint attached."""
        if opcode == proto.OP_BUSY:
            try:
                hint = proto.decode_busy_body(bytes(body))
            except ValueError:
                hint = None
            raise SidecarOverloaded(
                "sidecar shed request (queue full; retry after "
                f"{hint} ms)", retry_after_ms=hint)
        return body

    def _await(self, rid):
        try:
            while True:
                with self._cond:
                    if rid in self._results:
                        return self._unwrap(*self._results.pop(rid))
                # one thread at a time drains the socket; results are
                # published under the condition so pipelined waiters wake up
                if self._recv_lock.acquire(timeout=0.05):
                    try:
                        with self._cond:
                            if rid in self._results:
                                return self._unwrap(
                                    *self._results.pop(rid))
                        payload = proto.read_frame(self._sock)
                        opcode, got_rid, body = \
                            proto.decode_reply_raw(payload)
                        with self._cond:
                            if got_rid in self._abandoned:
                                self._abandoned.discard(got_rid)
                            else:
                                self._results[got_rid] = (opcode, body)
                                self._cond.notify_all()
                    finally:
                        self._recv_lock.release()
                else:
                    with self._cond:
                        self._cond.wait(timeout=0.05)
        except BaseException:
            # Abandoned request: reap a published result, or mark the rid so
            # the drainer drops its reply when it later arrives — either way
            # long-lived pipelined clients don't leak masks in _results.
            with self._cond:
                if self._results.pop(rid, None) is None:
                    self._abandoned.add(rid)
            raise

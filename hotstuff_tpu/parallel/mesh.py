"""Device-mesh helpers for sharded signature verification.

The reference scales quorum-certificate verification only as far as one CPU
core's `verify_batch` (crypto/src/lib.rs:210-223).  The TPU build treats
committee size as the scaling axis (SURVEY.md §5.7): vote batches shard
across chips along the batch dimension, and validity reduces over ICI with a
psum.  These helpers give the rest of the framework one place that knows how
meshes are built.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

BATCH_AXIS = "batch"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (BATCH_AXIS,))

"""THE shard-alignment rule: how a global batch size maps onto per-shard
device shapes on an n-device mesh.

Every mesh launch pads its batch so each shard gets the SAME power-of-two
row count (a per-shard "bucket"), because the sidecar warmup compiles
exactly those per-shard shapes: any other per-shard size (3000 votes on
8 devices -> 375-row shards) is a first-time XLA compile on the engine
thread mid-traffic — the silent 30-60 s stall warmup exists to prevent.
This module is the single home of that arithmetic; the mesh verifiers
(parallel/sharded_verify), the scheduler's shape registry
(sidecar/sched/shapes) and the warmup (sidecar/service) all route
through it, and the graftlint ``shard-misaligned-launch`` rule pins the
discipline mechanically (hotstuff_tpu/analysis/padshape.py).

Pure integer arithmetic — importable without touching a JAX backend.
"""

from __future__ import annotations

from ..crypto.eddsa import _MIN_BUCKET, MAX_SUBBATCH, next_pow2


def shard_bucket(n: int, n_devices: int,
                 max_subbatch: int = MAX_SUBBATCH) -> int:
    """Per-shard padded row count for a global batch of ``n`` records.

    Power-of-two bucket of ceil(n / n_devices), floored at the smallest
    per-shard shape the warmup compiles (_MIN_BUCKET / n_devices rows —
    warmed GLOBAL sizes start at _MIN_BUCKET, so a lone tiny request on a
    small mesh still lands on a warmed shape) and capped at
    ``max_subbatch``; beyond the cap the shard runs as a chunked scan of
    whole ``max_subbatch`` sub-chunks, so the bucket grows in
    power-of-two multiples of ``max_subbatch`` instead.
    """
    if n_devices < 1:
        raise ValueError(f"need a positive device count, got {n_devices}")
    per_shard = -(-max(n, 1) // n_devices)
    if per_shard <= max_subbatch:
        lo = max(1, _MIN_BUCKET // n_devices)
        return min(next_pow2(per_shard, lo), max_subbatch)
    g = next_pow2(-(-per_shard // max_subbatch))
    return g * max_subbatch


def shard_aligned_rows(n: int, n_devices: int,
                       max_subbatch: int = MAX_SUBBATCH) -> int:
    """Global padded row count of an ``n``-record mesh launch: the
    per-shard bucket times the device count — by construction divisible
    by ``n_devices``, and the capacity pad-fill may use without growing
    any shard's compiled shape."""
    return n_devices * shard_bucket(n, n_devices, max_subbatch)


def mesh_chunk_count(n: int, n_devices: int, rows: int) -> int:
    """Chunk count g of a whole-backlog mesh scan
    (parallel/sharded_verify.verify_sharded_chunked): each shard scans g
    chunks of ``rows`` rows inside ONE program, so an ``n``-record
    backlog pads to ``n_devices * g * rows`` total rows.

    ``rows`` is the per-shard chunk row count the scan shapes were
    compiled at (the warmup's top per-shard bucket — a power of two);
    g is the power of two that covers ceil(n / n_devices) rows per
    shard, so the compiled scan lengths stay a small closed set (the
    registry's ``mesh_chunks``) exactly like the single-chip
    ``chunks`` of ops/ed25519.verify_packed_chunked.

    Because g, rows and the per-shard bucket are all powers of two (or
    whole-chunk multiples), ``g * rows == shard_bucket(n)`` whenever
    per-shard demand exceeds ``rows`` — the scan pads to the SAME
    global capacity the aligned-rows rule promises, e.g. 3000 records
    on 8 devices at rows=128 scan as g=4 chunks -> 512 rows/shard, the
    8x512 shape ``shard_aligned_rows`` computes.
    """
    if n_devices < 1:
        raise ValueError(f"need a positive device count, got {n_devices}")
    if rows < 1 or rows & (rows - 1):
        raise ValueError(f"scan chunk rows must be a power of two, "
                         f"got {rows}")
    per_shard = -(-max(n, 1) // n_devices)
    return next_pow2(-(-per_shard // rows))

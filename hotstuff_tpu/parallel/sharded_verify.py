"""Multi-chip Ed25519 quorum verification: shard_map over the batch axis with
a psum-reduced validity count over ICI.

This is the TPU-native answer to the reference's single-threaded
``Signature::verify_batch`` call inside ``QC::verify``
(crypto/src/lib.rs:210-223, consensus/src/messages.rs:180-198): for large
committees the 2f+1 votes of a quorum certificate are data-parallel across
chips; each chip verifies its shard of votes and the chips agree on the QC
verdict via an integer ``psum`` of failure counts (one scalar over ICI).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.6: top-level export, replication checking via check_vma
    from jax import shard_map
    _SHARD_MAP_KW = {"check_vma": False}
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_KW = {"check_rep": False}
from jax.sharding import Mesh, PartitionSpec as Pspec

from ..crypto.eddsa import MAX_SUBBATCH, RLC_MIN_MSM, _rlc_coeffs, next_pow2
from ..ops import ed25519 as E
from ..ops import scalar25519  # noqa: F401  (re-export surface for tests)
from .mesh import BATCH_AXIS
from .shard_shapes import (mesh_chunk_count,  # noqa: F401
                           shard_aligned_rows, shard_bucket)
# (shard_bucket / mesh_chunk_count re-exported: the scheduler's shape
# registry and tests read per-shard buckets and scan chunk counts from
# the same module that launches them)


def _make_shard_body(max_subbatch: int):
    def _shard_body(a, r, s, k, present):
        """present: (B,) int32 — 1 for a real, host-canonical vote; 0 for
        batch padding or votes already rejected on host (non-canonical
        encodings)."""
        bs = a.shape[0]
        if bs > max_subbatch:
            # Per-shard chunked scan, same shape discipline as the
            # single-chip bulk path (ops/ed25519.verify_packed_chunked):
            # every conv stays at <= max_subbatch groups while the whole
            # shard shares one program. Caller pads so bs divides evenly.
            g = bs // max_subbatch

            def body(_, xs):
                aa, rr, ss, kk = xs
                return None, E.verify_compact(aa, rr, ss, kk)

            _, masks = jax.lax.scan(
                body, None,
                tuple(x.reshape(g, max_subbatch, *x.shape[1:])
                      for x in (a, r, s, k)))
            mask = masks.reshape(bs)
        else:
            mask = E.verify_compact(a, r, s, k)
        mask = mask & (present > 0)
        # QC verdict: count of present-but-invalid votes, psum over ICI.
        bad = jnp.sum((present > 0) & ~mask).astype(jnp.int32)
        bad_total = jax.lax.psum(bad, BATCH_AXIS)
        return mask, bad_total
    return _shard_body


def make_sharded_verifier(mesh: Mesh, max_subbatch: int = MAX_SUBBATCH,
                          donate: bool = False):
    """Returns jitted fn over compact byte arrays + present mask (global
    batch B, B % n_devices == 0; shards larger than max_subbatch must
    divide into max_subbatch chunks) -> ((B,) bool mask, () int32 invalid
    vote count).

    Note: ``bad_total`` counts votes with present=1 whose signature fails on
    device; host-side encoding rejections must be folded into ``present`` by
    the caller (verify_batch_sharded does).

    ``donate=True`` donates every input buffer (the engine's production
    launch shape: each per-shard buffer is transferred once at pack time
    and consumed once at dispatch); unsupported on the CPU test backend,
    where the caller gets the plain jit instead (see _cached_*_donated).
    """
    batched = Pspec(BATCH_AXIS)
    # Replication checking off (_SHARD_MAP_KW): the ladder scans carry
    # broadcast constants (identity point, exponent accumulators) that
    # VMA/rep tracking would flag as unvarying vs the varying body
    # outputs; the checking adds nothing here.
    fn = shard_map(
        _make_shard_body(max_subbatch),
        mesh=mesh,
        in_specs=(batched,) * 5,
        out_specs=(batched, Pspec()),
        **_SHARD_MAP_KW,
    )
    if donate:
        return jax.jit(fn, donate_argnums=(0, 1, 2, 3, 4))
    return jax.jit(fn)


@functools.cache
def _cached_verifier(mesh: Mesh, max_subbatch: int = MAX_SUBBATCH):
    return make_sharded_verifier(mesh, max_subbatch)


@functools.cache
def _cached_verifier_donated(mesh: Mesh, max_subbatch: int = MAX_SUBBATCH):
    # Donation is unimplemented on CPU (a warning per launch, nothing
    # else) — share the plain jit there so the test suite compiles each
    # mesh shape once, not twice.
    if jax.default_backend() == "cpu":
        return _cached_verifier(mesh, max_subbatch)
    return make_sharded_verifier(mesh, max_subbatch, donate=True)


def _shard_put(mesh: Mesh, arr: np.ndarray):
    """Host array -> committed device array sharded over the batch axis.
    This is the pack-stage h2d transfer: it runs on the engine's pack
    thread, overlapping the device compute of the launch in flight."""
    from jax.sharding import NamedSharding

    return jax.device_put(arr, NamedSharding(mesh, Pspec(BATCH_AXIS)))


def _pack_sharded_arrays(mesh: Mesh, prep: dict, m: int):
    """Pad the five per-record arrays to the shard-aligned row count and
    ship them to the mesh (pack-stage work: byte padding + h2d)."""
    n = prep["a"].shape[0]
    arrays = dict(prep)
    arrays["present"] = prep["host_ok"].astype(np.int32)
    out = []
    for key in ("a", "r", "s", "k", "present"):
        a = arrays[key]
        if m != n:
            a = np.pad(a, [(0, m - n)] + [(0, 0)] * (a.ndim - 1))
        out.append(_shard_put(mesh, a))
    return out


def verify_batch_sharded_pack(mesh: Mesh, prep: dict, *,
                              max_subbatch: int = MAX_SUBBATCH):
    """Pack stage of a sharded per-signature verify launch.

    Host work (shard-aligned padding + the h2d transfer of every
    per-shard buffer) happens HERE, on the caller's thread; the returned
    ``dispatch()`` fires the donated mesh program and returns
    ``fetch() -> (N,) bool mask`` — the three-stage split the sidecar
    engine's double-buffered pipeline rides (pack launch N+1 while
    launch N executes).  The per-shard row count comes from THE
    shard-alignment rule (parallel/shard_shapes): the padded bucket
    always divides evenly across the mesh, so every launch lands on a
    shape the warmup compiled.
    """
    n = prep["a"].shape[0]
    n_dev = mesh.devices.size
    m = shard_aligned_rows(n, n_dev, max_subbatch)
    dev = _pack_sharded_arrays(mesh, prep, m)

    def dispatch():
        mask_dev, _bad = _cached_verifier_donated(
            mesh, max_subbatch)(*dev)

        def fetch():
            return np.asarray(mask_dev)[:n]

        return fetch

    return dispatch


def ring_slot_pack(mesh: Mesh, prep: dict, rows: int, *,
                   max_subbatch: int = MAX_SUBBATCH):
    """graftcadence: arm ONE cadence-ring slot with this batch at the
    ring's FIXED shard-aligned row count.

    Same ``dispatch() -> fetch()`` contract (and the same donated mesh
    program, hence bit-identical masks) as
    :func:`verify_batch_sharded_pack`, with one difference: the padded
    row count is pinned to ``rows`` — the ring's per-tick quota bucket,
    a shape the warmup compiled — instead of the batch's own bucket.
    Every cadence tick therefore re-dispatches the SAME resident
    compiled program regardless of how full the tick was (partially-
    filled ticks are pad-filled from the bulk backlog upstream; what
    remains is dead rows with ``present = 0``), which is the
    fixed-shape ring discipline: never a fresh compile mid-run.

    "Pre-donated" means the SHAPES are resident, not the bytes:
    donation consumes a buffer per dispatch, so each generation's
    transfer happens at arm time on the pack thread — overlapping the
    in-flight generations' device compute exactly like the staged
    pipeline's h2d — into buffers of the one ring shape.  A batch
    larger than ``rows`` (defensive; the scheduler's tick quota caps
    the coalesce) falls back to its own shard-aligned bucket."""
    n = prep["a"].shape[0]
    n_dev = mesh.devices.size
    m = max(int(rows), shard_aligned_rows(n, n_dev, max_subbatch))
    dev = _pack_sharded_arrays(mesh, prep, m)

    def dispatch():
        mask_dev, _bad = _cached_verifier_donated(
            mesh, max_subbatch)(*dev)

        def fetch():
            return np.asarray(mask_dev)[:n]

        return fetch

    return dispatch


def verify_batch_sharded(mesh: Mesh, prep: dict, *, return_bad_total=False,
                         max_subbatch: int = MAX_SUBBATCH):
    """Run a host-prepared batch (see crypto/eddsa.prepare_batch) across the
    mesh.  Pads the batch so every shard gets the same power-of-two row
    count (shard_shapes.shard_aligned_rows — the sidecar pre-compiles
    exactly those shapes, so any other per-shard size, e.g. 3000 sigs on
    8 devices -> 375-row shards, would hit a first-time XLA compile on
    the engine thread mid-traffic); padding and host-rejected votes are
    excluded from the device-side verdict count."""
    n = prep["a"].shape[0]
    n_dev = mesh.devices.size
    m = shard_aligned_rows(n, n_dev, max_subbatch)
    out = _pack_sharded_arrays(mesh, prep, m)
    mask, bad_total = _cached_verifier(mesh, max_subbatch)(*out)
    mask = np.asarray(mask)[:n]
    if return_bad_total:
        return mask, int(bad_total)
    return mask


# ---------------------------------------------------------------------------
# Whole-backlog chunked mesh scan (graftscale): ONE compiled program that
# drains a bulk backlog across the mesh
# ---------------------------------------------------------------------------
#
# The mesh analogue of ops/ed25519.verify_packed_chunked: each shard
# scans g chunks of ``rows`` packed rows inside one program (the
# tunneled device charges a fixed ~15-20 ms per dispatch, so a backlog
# sliced into per-launch_cap ladder launches pays that cost per slice —
# the scan pays it once for the whole backlog), with the per-shard
# validity counts psum-reduced over ICI like the per-signature path.
# The (g, rows) shape comes from THE shard-alignment rule
# (shard_shapes.mesh_chunk_count over the warmup's top per-shard
# bucket), so every launchable scan length is a shape the
# ``--warm-rlc-sharded`` warmup compiled and the scheduler's registry
# marked (ShapeRegistry.mesh_chunks) — an unwarmed scan length never
# dispatches; the engine falls back to the sliced ladder path instead.


def _make_chunk_scan_body(g: int, rows: int):
    def _chunk_body(packed, present):
        """packed: (g*rows, 128) uint8 rows of A || R || S || k per
        shard; present: (g*rows,) int32 — 1 for a real, host-canonical
        record; 0 for padding or host-rejected rows."""
        def body(_, chunk):
            return None, E.verify_packed(chunk)

        _, masks = jax.lax.scan(body, None,
                                packed.reshape(g, rows, 128))
        mask = masks.reshape(g * rows) & (present > 0)
        bad = jnp.sum((present > 0) & ~mask).astype(jnp.int32)
        return mask, jax.lax.psum(bad, BATCH_AXIS)
    return _chunk_body


def make_chunk_scan_verifier(mesh: Mesh, g: int, rows: int,
                             donate: bool = False):
    """Returns a jitted fn over ((B, 128) packed rows, (B,) int32
    present), B == n_devices * g * rows -> ((B,) bool mask, () int32
    invalid count): each shard verifies its g chunks of ``rows`` rows as
    a lax.scan inside ONE dispatch.  ``donate=True`` donates both input
    buffers (production launches transfer each once, consume each
    once)."""
    batched = Pspec(BATCH_AXIS)
    fn = shard_map(
        _make_chunk_scan_body(g, rows),
        mesh=mesh,
        in_specs=(batched, batched),
        out_specs=(batched, Pspec()),
        **_SHARD_MAP_KW,
    )
    if donate:
        return jax.jit(fn, donate_argnums=(0, 1))
    return jax.jit(fn)


@functools.cache
def _cached_chunk_verifier(mesh: Mesh, g: int, rows: int):
    return make_chunk_scan_verifier(mesh, g, rows)


@functools.cache
def _cached_chunk_verifier_donated(mesh: Mesh, g: int, rows: int):
    # Same CPU-backend sharing as _cached_verifier_donated: one compile
    # per scan shape on the test backend, donation on real devices.
    if jax.default_backend() == "cpu":
        return _cached_chunk_verifier(mesh, g, rows)
    return make_chunk_scan_verifier(mesh, g, rows, donate=True)


def _pack_chunk_arrays(mesh: Mesh, prep: dict, m: int):
    """Shared pack step of the scan entries: pad packed rows + present
    mask to ``m`` total rows and ship both to the mesh."""
    n = prep["a"].shape[0]
    packed = np.asarray(prep["packed"])
    present = prep["host_ok"].astype(np.int32)
    if m != n:
        packed = np.pad(packed, [(0, m - n), (0, 0)])
        present = np.pad(present, [(0, m - n)])
    return _shard_put(mesh, packed), _shard_put(mesh, present)


def verify_sharded_chunked_pack(mesh: Mesh, prep: dict, *,
                                rows: int | None = None,
                                max_subbatch: int = MAX_SUBBATCH):
    """Pack stage of a whole-backlog chunked mesh scan; returns
    ``dispatch() -> fetch() -> (N,) bool mask``, the same three-stage
    contract as :func:`verify_batch_sharded_pack` (and the same mask —
    per-signature verification, just batched into one program).

    Pack (this thread): shard-aligned padding to ``n_devices * g *
    rows`` total rows plus the h2d transfer of the packed rows and the
    present mask.  ``rows`` is the per-shard chunk row count (the
    registry's warmed ``scan_rows``; defaults to the per-shard bucket of
    the batch itself, capped at ``max_subbatch``) and g comes from
    shard_shapes.mesh_chunk_count — the one place the scan's chunk
    arithmetic lives, so dispatch and warmup can never disagree about
    which (g, rows) programs exist.
    """
    n = prep["a"].shape[0]
    n_dev = mesh.devices.size
    if rows is None:
        rows = min(shard_bucket(n, n_dev, max_subbatch), max_subbatch)
    g = mesh_chunk_count(n, n_dev, rows)
    dev_rows, dev_present = _pack_chunk_arrays(mesh, prep,
                                               n_dev * g * rows)

    def dispatch():
        mask_dev, _bad = _cached_chunk_verifier_donated(
            mesh, g, rows)(dev_rows, dev_present)

        def fetch():
            return np.asarray(mask_dev)[:n]

        return fetch

    return dispatch


def verify_sharded_chunked(mesh: Mesh, prep: dict, *,
                           rows: int | None = None,
                           return_bad_total: bool = False,
                           max_subbatch: int = MAX_SUBBATCH):
    """Run a host-prepared backlog (crypto/eddsa.prepare_batch) through
    ONE chunked mesh scan -> (N,) bool mask, matching
    verify_batch_sharded row for row.  Eager twin of
    :func:`verify_sharded_chunked_pack` (same shared pack step) that
    can also surface the psum'd invalid count — the sidecar engine
    uses the staged form behind the scheduler's ``scan_sharded``
    route."""
    n = prep["a"].shape[0]
    n_dev = mesh.devices.size
    if rows is None:
        rows = min(shard_bucket(n, n_dev, max_subbatch), max_subbatch)
    g = mesh_chunk_count(n, n_dev, rows)
    dev_rows, dev_present = _pack_chunk_arrays(mesh, prep,
                                               n_dev * g * rows)
    mask, bad_total = _cached_chunk_verifier(mesh, g, rows)(
        dev_rows, dev_present)
    mask = np.asarray(mask)[:n]
    if return_bad_total:
        return mask, int(bad_total)
    return mask


# ---------------------------------------------------------------------------
# Sharded random-linear-combination verification: the MSM buckets
# themselves shard across the mesh
# ---------------------------------------------------------------------------
#
# The RLC check (crypto/eddsa.verify_batch_rlc) splits mesh-natively:
# window sums of an MSM over disjoint point shards simply point-add
# together, and the fixed-base scalar sum is a limb-wise integer sum that
# commutes with an ICI psum.  The per-shard window sums route through
# the SAME graftkern Pallas kernels as the single-chip path when
# HOTSTUFF_TPU_KERN=pallas — the shard body calls ops/ed25519
# (rlc_partials -> msm_window_sums / scalar25519.mont_mul), and the
# kernel route lives behind those signatures, so mesh launches pick it
# up with zero changes here.  So each chip runs the shard-local half
# (ops/ed25519.rlc_partials — decompression, mod-L scalar products,
# per-point tables, masked tree reduction to 64 window sums), the mesh
# exchanges 64 points + 32 limbs + 1 counter per chip (an all_gather and
# two psums — a few KB over ICI, vs. the votes themselves staying
# sharded), and every chip finishes the tiny replicated tail (Horner,
# comb, projective compare) to the same () bool verdict.


def _rlc_shard_body(packed, z):
    wsums, u_sum, bad = E.rlc_partials(packed, z)
    bad_total = jax.lax.psum(bad, BATCH_AXIS)
    u_total = jax.lax.psum(u_sum, BATCH_AXIS)
    allw = jax.lax.all_gather(wsums, BATCH_AXIS)   # (n_dev, 64, 4, 32)
    n_dev = allw.shape[0]
    n_pad = next_pow2(n_dev)
    if n_pad != n_dev:
        allw = jnp.concatenate(
            [allw, E.identity_ext((n_pad - n_dev, 64))], axis=0)
    combined = E._tree_sum(allw)                   # (64, 4, 32)
    return E.rlc_finish(combined, u_total, bad_total)


def make_sharded_rlc_verifier(mesh: Mesh, donate: bool = False):
    """Returns a jitted fn over ((B, 128) packed rows, (B, 32) coefficient
    rows), B % n_devices == 0 -> () bool combined-RLC verdict, replicated
    across the mesh.  Zero-coefficient rows are excluded (padding).
    ``donate=True`` donates both input buffers (production launches
    transfer each once and consume each once)."""
    batched = Pspec(BATCH_AXIS)
    fn = shard_map(
        _rlc_shard_body,
        mesh=mesh,
        in_specs=(batched, batched),
        out_specs=Pspec(),
        **_SHARD_MAP_KW,
    )
    if donate:
        return jax.jit(fn, donate_argnums=(0, 1))
    return jax.jit(fn)


@functools.cache
def _cached_rlc_verifier(mesh: Mesh):
    return make_sharded_rlc_verifier(mesh)


@functools.cache
def _cached_rlc_verifier_donated(mesh: Mesh):
    # Same CPU-backend sharing as _cached_verifier_donated: one compile
    # per mesh shape on the test backend, donation on real devices.
    if jax.default_backend() == "cpu":
        return _cached_rlc_verifier(mesh)
    return make_sharded_rlc_verifier(mesh, donate=True)


def _pack_rlc_rows(mesh: Mesh, packed: np.ndarray, idx: np.ndarray,
                   n: int, m: int, salt: bytes):
    """Coefficient rows + padding to the shard-aligned row count ``m``
    (callers derive it via shard_aligned_rows) + h2d for one sharded RLC
    launch over ``packed[:n]`` with host-canonical rows ``idx``."""
    z = np.zeros((m, 32), np.uint8)
    if len(idx):
        z[idx] = _rlc_coeffs(np.ascontiguousarray(packed[idx]), salt)
    if m != n:
        packed = np.pad(packed, [(0, m - n), (0, 0)])
    return _shard_put(mesh, packed), _shard_put(mesh, z)


def verify_rlc_sharded_pack(mesh: Mesh, prep: dict, *, salt: bytes = b"",
                            on_bisect=None):
    """Pack stage of a sharded one-MSM RLC verify launch; returns
    ``dispatch() -> fetch() -> (N,) bool mask``, bit-identical to
    :func:`verify_batch_sharded` (and therefore to
    crypto/eddsa.verify_batch).

    Pack (this thread): coefficient PRF, shard-aligned padding
    (shard_shapes.shard_aligned_rows — every shard gets a warmed
    power-of-two bucket), h2d of the packed rows + coefficient rows.
    Dispatch (engine thread): ONE donated mesh program computing the
    combined verdict.  Fetch: when the combined check passes (the steady
    state) the mask is just host_ok; on failure the batch BISECTS with
    fresh per-sub-batch coefficients down to the RLC_MIN_MSM floor,
    below which the per-signature sharded path pinpoints each bad vote —
    ``on_bisect`` (if given) fires once so the scheduler's telemetry
    counts the slow path.  Degenerate batches (fewer than RLC_MIN_MSM
    canonical rows, or per-shard sizes beyond the one-dispatch envelope)
    dispatch the per-signature sharded program instead — same contract,
    same mask.
    """
    n = prep["a"].shape[0]
    host_ok = prep["host_ok"]
    if n == 0:
        return lambda: (lambda: np.zeros((0,), bool))
    n_dev = mesh.devices.size
    idx = np.nonzero(host_ok)[0]
    if len(idx) < RLC_MIN_MSM or shard_bucket(n, n_dev) > MAX_SUBBATCH:
        # Too few canonical rows for the MSM to win, or a quorum beyond
        # the mesh's one-dispatch RLC envelope (same policy as
        # verify_batch_rlc): per-signature sharded, identical mask.
        return verify_batch_sharded_pack(mesh, prep)
    packed = np.asarray(prep["packed"])
    dev_rows, dev_z = _pack_rlc_rows(
        mesh, packed, idx, n, shard_aligned_rows(n, n_dev), salt)

    def dispatch():
        ok_dev = _cached_rlc_verifier_donated(mesh)(dev_rows, dev_z)

        def fetch():
            if bool(np.asarray(ok_dev)):
                return host_ok.copy()
            if on_bisect is not None:
                on_bisect()
            mask = np.zeros((n,), bool)
            mid = len(idx) // 2
            _rlc_sharded_resolve(mesh, packed, idx[:mid], mask,
                                 salt + b"L")
            _rlc_sharded_resolve(mesh, packed, idx[mid:], mask,
                                 salt + b"R")
            return mask

        return fetch

    return dispatch


def _rlc_sharded_resolve(mesh: Mesh, packed: np.ndarray,
                         indices: np.ndarray, out: np.ndarray,
                         salt: bytes) -> None:
    """Resolve ``out[indices]`` for host-canonical rows across the mesh:
    combined sharded RLC check first, bisection with fresh coefficients
    on failure, per-signature sharded floor below RLC_MIN_MSM.  Every
    sub-batch re-pads through the shard-alignment rule, so bisection can
    only ever land on warmed per-shard buckets (smaller than the batch
    that failed)."""
    n = len(indices)
    if n == 0:
        return
    rows = np.ascontiguousarray(packed[indices])
    if n < RLC_MIN_MSM:
        from ..crypto.eddsa import split_packed_rows

        # Through the pack entry, NOT the eager wrapper: the warmup only
        # compiles the donated programs on a real device backend, and a
        # mid-traffic bisection must never pay a cold compile.
        prep = split_packed_rows(rows)
        out[indices] = verify_batch_sharded_pack(mesh, prep)()()
        return
    m = shard_aligned_rows(n, mesh.devices.size)
    dev_rows, dev_z = _pack_rlc_rows(mesh, rows, np.arange(n), n, m, salt)
    # Same donated program the warmup compiled (the buffers above are
    # fresh device arrays consumed exactly once — donation-safe).
    ok = bool(np.asarray(_cached_rlc_verifier_donated(mesh)(
        dev_rows, dev_z)))
    if ok:
        out[indices] = True
        return
    mid = n // 2
    _rlc_sharded_resolve(mesh, packed, indices[:mid], out, salt + b"L")
    _rlc_sharded_resolve(mesh, packed, indices[mid:], out, salt + b"R")


def verify_rlc_sharded(mesh: Mesh, prep: dict, *,
                       salt: bytes = b"") -> np.ndarray:
    """Run a host-prepared batch (crypto/eddsa.prepare_batch) through the
    mesh-sharded RLC check -> (N,) bool mask, matching verify_batch_sharded.

    Eager wrapper over :func:`verify_rlc_sharded_pack` (pack, dispatch
    and fetch in one call) — the sidecar engine uses the staged form;
    the ``--warm-rlc-sharded`` warmup (sidecar/service) pre-compiles
    every per-shard bucket this can launch, and the scheduler's shape
    registry only routes batches onto buckets that warmup marked.
    """
    return verify_rlc_sharded_pack(mesh, prep, salt=salt)()()

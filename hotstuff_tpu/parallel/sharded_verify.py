"""Multi-chip Ed25519 quorum verification: shard_map over the batch axis with
a psum-reduced validity count over ICI.

This is the TPU-native answer to the reference's single-threaded
``Signature::verify_batch`` call inside ``QC::verify``
(crypto/src/lib.rs:210-223, consensus/src/messages.rs:180-198): for large
committees the 2f+1 votes of a quorum certificate are data-parallel across
chips; each chip verifies its shard of votes and the chips agree on the QC
verdict via an integer ``psum`` of failure counts (one scalar over ICI).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.6: top-level export, replication checking via check_vma
    from jax import shard_map
    _SHARD_MAP_KW = {"check_vma": False}
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_KW = {"check_rep": False}
from jax.sharding import Mesh, PartitionSpec as Pspec

from ..crypto.eddsa import _MIN_BUCKET, MAX_SUBBATCH, _rlc_coeffs, next_pow2
from ..ops import ed25519 as E
from ..ops import scalar25519  # noqa: F401  (re-export surface for tests)
from .mesh import BATCH_AXIS


def _make_shard_body(max_subbatch: int):
    def _shard_body(a, r, s, k, present):
        """present: (B,) int32 — 1 for a real, host-canonical vote; 0 for
        batch padding or votes already rejected on host (non-canonical
        encodings)."""
        bs = a.shape[0]
        if bs > max_subbatch:
            # Per-shard chunked scan, same shape discipline as the
            # single-chip bulk path (ops/ed25519.verify_packed_chunked):
            # every conv stays at <= max_subbatch groups while the whole
            # shard shares one program. Caller pads so bs divides evenly.
            g = bs // max_subbatch

            def body(_, xs):
                aa, rr, ss, kk = xs
                return None, E.verify_compact(aa, rr, ss, kk)

            _, masks = jax.lax.scan(
                body, None,
                tuple(x.reshape(g, max_subbatch, *x.shape[1:])
                      for x in (a, r, s, k)))
            mask = masks.reshape(bs)
        else:
            mask = E.verify_compact(a, r, s, k)
        mask = mask & (present > 0)
        # QC verdict: count of present-but-invalid votes, psum over ICI.
        bad = jnp.sum((present > 0) & ~mask).astype(jnp.int32)
        bad_total = jax.lax.psum(bad, BATCH_AXIS)
        return mask, bad_total
    return _shard_body


def make_sharded_verifier(mesh: Mesh, max_subbatch: int = MAX_SUBBATCH):
    """Returns jitted fn over compact byte arrays + present mask (global
    batch B, B % n_devices == 0; shards larger than max_subbatch must
    divide into max_subbatch chunks) -> ((B,) bool mask, () int32 invalid
    vote count).

    Note: ``bad_total`` counts votes with present=1 whose signature fails on
    device; host-side encoding rejections must be folded into ``present`` by
    the caller (verify_batch_sharded does).
    """
    batched = Pspec(BATCH_AXIS)
    # Replication checking off (_SHARD_MAP_KW): the ladder scans carry
    # broadcast constants (identity point, exponent accumulators) that
    # VMA/rep tracking would flag as unvarying vs the varying body
    # outputs; the checking adds nothing here.
    fn = shard_map(
        _make_shard_body(max_subbatch),
        mesh=mesh,
        in_specs=(batched,) * 5,
        out_specs=(batched, Pspec()),
        **_SHARD_MAP_KW,
    )
    return jax.jit(fn)


@functools.cache
def _cached_verifier(mesh: Mesh, max_subbatch: int = MAX_SUBBATCH):
    return make_sharded_verifier(mesh, max_subbatch)


def verify_batch_sharded(mesh: Mesh, prep: dict, *, return_bad_total=False,
                         max_subbatch: int = MAX_SUBBATCH):
    """Run a host-prepared batch (see crypto/eddsa.prepare_batch) across the
    mesh.  Pads the batch to a multiple of the mesh size (and, beyond
    max_subbatch per shard, to whole per-shard chunks); padding and
    host-rejected votes are excluded from the device-side verdict count."""
    n = prep["a"].shape[0]
    n_dev = mesh.devices.size
    # Bucket the per-shard size to a power of two (mirroring
    # crypto/eddsa.verify_prepared_rows): the sidecar pre-compiles exactly
    # the power-of-two shapes, so any other per-shard size (e.g. 3000 sigs
    # on 8 devices -> 375-row shards) would hit a first-time XLA compile on
    # the engine thread mid-traffic — the stall warmup exists to prevent.
    per_shard = -(-n // n_dev)
    if per_shard <= max_subbatch:
        # Floor at the smallest per-shard shape warmup compiles: warmed
        # global sizes start at _MIN_BUCKET, i.e. _MIN_BUCKET/n_dev rows
        # per shard (tiny lone requests on small meshes would otherwise
        # still hit a cold shape).
        lo = max(1, _MIN_BUCKET // n_dev)
        m = n_dev * min(next_pow2(per_shard, lo), max_subbatch)
    else:
        g = next_pow2(-(-per_shard // max_subbatch))
        m = n_dev * max_subbatch * g
    arrays = dict(prep)
    arrays["present"] = prep["host_ok"].astype(np.int32)
    out = []
    for key in ("a", "r", "s", "k", "present"):
        a = arrays[key]
        if m != n:
            a = np.pad(a, [(0, m - n)] + [(0, 0)] * (a.ndim - 1))
        out.append(jnp.asarray(a))
    mask, bad_total = _cached_verifier(mesh, max_subbatch)(*out)
    mask = np.asarray(mask)[:n]
    if return_bad_total:
        return mask, int(bad_total)
    return mask


# ---------------------------------------------------------------------------
# Sharded random-linear-combination verification: the MSM buckets
# themselves shard across the mesh
# ---------------------------------------------------------------------------
#
# The RLC check (crypto/eddsa.verify_batch_rlc) splits mesh-natively:
# window sums of an MSM over disjoint point shards simply point-add
# together, and the fixed-base scalar sum is a limb-wise integer sum that
# commutes with an ICI psum.  So each chip runs the shard-local half
# (ops/ed25519.rlc_partials — decompression, mod-L scalar products,
# per-point tables, masked tree reduction to 64 window sums), the mesh
# exchanges 64 points + 32 limbs + 1 counter per chip (an all_gather and
# two psums — a few KB over ICI, vs. the votes themselves staying
# sharded), and every chip finishes the tiny replicated tail (Horner,
# comb, projective compare) to the same () bool verdict.


def _rlc_shard_body(packed, z):
    wsums, u_sum, bad = E.rlc_partials(packed, z)
    bad_total = jax.lax.psum(bad, BATCH_AXIS)
    u_total = jax.lax.psum(u_sum, BATCH_AXIS)
    allw = jax.lax.all_gather(wsums, BATCH_AXIS)   # (n_dev, 64, 4, 32)
    n_dev = allw.shape[0]
    n_pad = next_pow2(n_dev)
    if n_pad != n_dev:
        allw = jnp.concatenate(
            [allw, E.identity_ext((n_pad - n_dev, 64))], axis=0)
    combined = E._tree_sum(allw)                   # (64, 4, 32)
    return E.rlc_finish(combined, u_total, bad_total)


def make_sharded_rlc_verifier(mesh: Mesh):
    """Returns a jitted fn over ((B, 128) packed rows, (B, 32) coefficient
    rows), B % n_devices == 0 -> () bool combined-RLC verdict, replicated
    across the mesh.  Zero-coefficient rows are excluded (padding)."""
    batched = Pspec(BATCH_AXIS)
    fn = shard_map(
        _rlc_shard_body,
        mesh=mesh,
        in_specs=(batched, batched),
        out_specs=Pspec(),
        **_SHARD_MAP_KW,
    )
    return jax.jit(fn)


@functools.cache
def _cached_rlc_verifier(mesh: Mesh):
    return make_sharded_rlc_verifier(mesh)


def verify_rlc_sharded(mesh: Mesh, prep: dict, *,
                       salt: bytes = b"") -> np.ndarray:
    """Run a host-prepared batch (crypto/eddsa.prepare_batch) through the
    mesh-sharded RLC check -> (N,) bool mask, matching verify_batch_sharded.

    Fast path: ONE mesh dispatch for the combined check; when it passes
    (the steady state — every vote of a sound quorum verifies) the mask
    is just host_ok.  When it fails, the batch falls back to the
    per-signature sharded path to pinpoint the bad votes — the old
    full price, paid only when somebody actually sent a bad vote.
    Per-shard sizes pad to the same power-of-two buckets as
    verify_batch_sharded, which bounds the number of DISTINCT compiled
    shapes; note that no warmup pre-compiles the RLC mesh program yet —
    wiring these shapes into sidecar/service._warmup is the open
    ROADMAP item, and until then the first quorum at each bucket size
    pays its XLA compile.
    """
    n = prep["a"].shape[0]
    host_ok = prep["host_ok"]
    if n == 0:
        return np.zeros((0,), bool)
    n_dev = mesh.devices.size
    per_shard = -(-n // n_dev)
    lo = max(1, _MIN_BUCKET // n_dev)
    m = n_dev * min(next_pow2(per_shard, lo), MAX_SUBBATCH)
    if per_shard > MAX_SUBBATCH:
        # Quorums beyond the mesh's one-dispatch envelope keep the
        # per-signature chunked path (same policy as verify_batch_rlc).
        return verify_batch_sharded(mesh, prep)
    packed = np.asarray(prep["packed"])
    z = np.zeros((m, 32), np.uint8)
    idx = np.nonzero(host_ok)[0]
    if len(idx):
        z[idx] = _rlc_coeffs(np.ascontiguousarray(packed[idx]), salt)
    if m != n:
        packed = np.pad(packed, [(0, m - n), (0, 0)])
    ok = bool(np.asarray(_cached_rlc_verifier(mesh)(
        jnp.asarray(packed), jnp.asarray(z))))
    if ok:
        return host_ok.copy()
    return verify_batch_sharded(mesh, prep)

"""Multi-chip BLS multi-digest verification: shard the product of
pairings across the mesh.

The TC verify shape (per-vote signatures over DISTINCT digests,
consensus/src/messages.rs:307-313) is a product of n+1 pairings under one
final exponentiation.  Miller loops are embarrassingly parallel across
pairing rows, so for large committees the rows shard across chips: each
chip Miller-accumulates its rows and multiplies them into one local Fq12
value, the per-chip partials cross ICI once (an all_gather of a single
12x48 Montgomery element per chip), and every chip finishes the identical
final exponentiation — the whole check is ONE jitted shard_map program
with one tiny collective.

This completes the quorum-size scaling story for scheme=bls the way
parallel/sharded_verify.py does for ed25519.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.6: top-level export, replication checking via check_vma
    from jax import shard_map
    _SHARD_MAP_KW = {"check_vma": False}
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_KW = {"check_rep": False}
from jax.sharding import Mesh, PartitionSpec as Pspec

from ..offchain import bls12381 as host
from ..ops import bls381 as D
from .mesh import BATCH_AXIS


def _fold_product(fs):
    """(k, 12, 48) -> product via scan (constant HLO size regardless of
    committee size; an unrolled loop would inline one fq12_mul tower per
    row — the large-committee regime this module exists for)."""
    def body(acc, x):
        return D.fq12_mul(acc, x), None

    acc, _ = jax.lax.scan(body, fs[0], fs[1:])
    return acc


def _shard_body(lines, present):
    """lines: (rows_local, N_STEPS, 2, 12, 48) Montgomery Miller lines;
    present: (rows_local,) int32 — 0 rows contribute the identity."""
    fs = D.miller_accumulate(lines)  # (rows_local, 12, 48)
    one = D.fq12_one((fs.shape[0],))
    fs = jnp.where((present > 0)[:, None, None], fs, one)
    f = _fold_product(fs)
    partials = jax.lax.all_gather(f, BATCH_AXIS)  # (n_dev, 12, 48)
    total = _fold_product(partials)
    # Final exponentiation replicated per chip (identical inputs/outputs);
    # one verdict lane per shard so out_specs can partition it.
    return D.is_one(D.final_exponentiate(total))[None]


@functools.lru_cache(maxsize=8)
def _cached_checker(mesh: Mesh):
    # check_vma=False: the Miller/final-exp scans carry broadcast constants
    # (Fq12 identity, accumulators) that VMA tracking flags as unvarying vs
    # varying body outputs — same reasoning as sharded_verify.
    fn = shard_map(
        _shard_body, mesh=mesh,
        in_specs=(Pspec(BATCH_AXIS), Pspec(BATCH_AXIS)),
        out_specs=Pspec(BATCH_AXIS),
        **_SHARD_MAP_KW)
    return jax.jit(fn)


def verify_aggregate_multi_sharded(mesh: Mesh, pks, msgs,
                                   agg_sig) -> bool:
    """Distinct-message aggregate verify sharded over `mesh`.

    Same statement as ops/bls381.verify_aggregate_multi —
    prod e(pk_i, H(m_i)) * e(-g1, agg) == 1 — with the n+1 Miller rows
    data-parallel across chips.  Validation and Miller-line precomputation
    are the SHARED multi_pairing_rows, so the two verifiers can never
    accept different inputs; rows pad to a multiple of the mesh size with
    identity-contributing rows."""
    rows = D.multi_pairing_rows(pks, msgs, agg_sig)
    if rows is None:
        return False
    n = len(rows)
    n_dev = mesh.devices.size
    m = ((n + n_dev - 1) // n_dev) * n_dev
    present = np.zeros((m,), np.int32)
    present[:n] = 1
    lines = np.stack(rows + [rows[0]] * (m - n))  # padding rows masked out
    verdicts = _cached_checker(mesh)(jnp.asarray(lines),
                                     jnp.asarray(present))
    # Every shard computed the identical verdict; any lane will do.
    return bool(np.asarray(verdicts).reshape(-1)[0])

"""hotstuff_tpu — TPU-native HotStuff BFT framework with device-accelerated
digital-signature verification.

A brand-new framework with the capabilities of
`mwaurawakati/hotstuff-digital-signature-benchmarking` (reference mounted at
/root/reference), redesigned TPU-first:

- ``ops/``      — JAX/Pallas finite-field + curve primitives (the TPU compute path).
- ``crypto/``   — scheme-level signature API (Ed25519 sign/verify/batch-verify),
                  mirroring the reference's ``crypto`` crate boundary
                  (reference: crypto/src/lib.rs).
- ``parallel/`` — device-mesh sharding of large verification batches
                  (shard_map + psum validity masks over ICI).
- ``sidecar/``  — the long-lived verification service the C++ consensus node
                  talks to (reference analogue: crypto/src/lib.rs:226-254
                  SignatureService, made batch-first and device-backed).
- ``harness/``  — benchmark orchestration + log mining
                  (reference: benchmark/benchmark/*.py).
"""

__version__ = "0.1.0"

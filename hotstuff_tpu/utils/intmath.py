"""Pure-python-int Ed25519 curve constants + x-recovery, shared by the host
reference implementation (crypto/ref_ed25519) and the device module's
compile-time constant setup (ops/ed25519).  No JAX imports."""

from __future__ import annotations

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
BY = (4 * pow(5, P - 2, P)) % P


def next_pow2(n: int, lo: int = 1) -> int:
    """Smallest power-of-two multiple of ``lo`` that is >= n (lo itself a
    power of two).  THE bucketing rule for compiled batch shapes: the
    single-device path, the mesh per-shard path, the MSM point padding
    and the sidecar warmup must all agree on it, or a runtime batch can
    hit a shape warmup never compiled (a mid-traffic XLA compile
    stall)."""
    b = lo
    while b < n:
        b *= 2
    return b


def recover_x(y: int, sign: int) -> int | None:
    """RFC 8032 §5.1.3 x-recovery; None when y is not on the curve or the
    encoding is invalid."""
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x == 0 and sign:
        return None
    if x % 2 != sign:
        x = P - x
    return x


BX = recover_x(BY, 0)  # canonical basepoint x (even)

"""Persistent XLA compilation cache shared by every TPU-touching entrypoint
(sidecar, bench): cold processes reuse compiled programs instead of paying
30-60 s per shape through the tunneled device."""

from __future__ import annotations

import logging
import os

log = logging.getLogger("xla-cache")


def configure_xla_cache() -> str | None:
    """Point jax at the shared on-disk compilation cache; returns the dir,
    or None if this jax build has no such option."""
    import jax

    cache_dir = os.environ.get("HOTSTUFF_TPU_XLA_CACHE",
                               os.path.expanduser("~/.cache/hotstuff_tpu"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # older jax without the option: lazy compiles only
        log.warning("jax compilation cache unavailable")
        return None
    return cache_dir

"""Persistent compiled-program cache shared by every TPU-touching
entrypoint (sidecar, bench): cold processes reuse compiled programs
instead of paying 30-60 s per shape through the tunneled device.

Two layers:

* The XLA compilation cache (:func:`configure_xla_cache`): jax persists
  compiled executables to a shared on-disk dir, so a warm boot's
  "compile" is a fast deserialization.
* The warmed-shape manifest (:class:`CompileManifest`,
  ``results/compile_cache/manifest.json``): records which (shape key,
  kernel-source hash) pairs a warmup has already compiled — keyed on
  the SAME kernel-source hash scheme bench.py uses for its headline
  cache (:func:`kernel_fingerprint`), so a kernel edit invalidates the
  record exactly when it invalidates the programs.  The sidecar's
  warmup walks its shapes through :class:`CompileTracker`, which counts
  manifest hits/misses and per-shape wall time into the OP_STATS
  ``compile`` section; ``scripts/warmup_report.py`` turns the recorded
  runs into the cold-vs-warm boot comparison.
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import time

log = logging.getLogger("xla-cache")

MANIFEST_SCHEMA = "hotstuff-tpu-compile-manifest-v1"
_MAX_RUNS = 50

# The sources whose edits can change what a compiled verify program
# does: a manifest entry (and a cached bench headline) is only
# comparable to a boot built from the same kernel.  The kern glob keeps
# new Pallas modules inside the hash automatically.
KERNEL_SOURCES = (
    "hotstuff_tpu/ops/ed25519.py",
    "hotstuff_tpu/ops/field25519.py",
    "hotstuff_tpu/ops/scalar25519.py",
    "hotstuff_tpu/crypto/eddsa.py",
)
KERNEL_SOURCE_GLOBS = ("hotstuff_tpu/ops/kern/*.py",)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def kernel_fingerprint(extra=()) -> str:
    """Hash of the kernel sources (plus any caller-specific ``extra``
    repo-relative files — bench.py adds itself); namespaces the manifest
    and the bench headline cache so a stale record can only ever answer
    for the code that produced it."""
    root = repo_root()
    rels = list(KERNEL_SOURCES)
    for pattern in KERNEL_SOURCE_GLOBS:
        rels += sorted(
            os.path.relpath(p, root)
            for p in glob.glob(os.path.join(root, pattern)))
    rels += list(extra)
    h = hashlib.sha256()
    for rel in rels:
        try:
            with open(os.path.join(root, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"<missing>")
        h.update(b"\x00")
    return h.hexdigest()[:16]


def configure_xla_cache() -> str | None:
    """Point jax at the shared on-disk compilation cache; returns the
    dir, or None if disabled (HOTSTUFF_TPU_XLA_CACHE set empty) or this
    jax build has no such option."""
    import jax

    raw = os.environ.get("HOTSTUFF_TPU_XLA_CACHE")
    if raw is not None and not raw.strip():
        log.info("XLA compilation cache disabled "
                 "(HOTSTUFF_TPU_XLA_CACHE empty)")
        return None
    cache_dir = raw or os.path.expanduser("~/.cache/hotstuff_tpu")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # older jax without the option: lazy compiles only
        log.warning("jax compilation cache unavailable")
        return None
    return cache_dir


def default_manifest_path() -> str:
    return os.environ.get(
        "HOTSTUFF_TPU_COMPILE_MANIFEST",
        os.path.join(repo_root(), "results", "compile_cache",
                     "manifest.json"))


class CompileManifest:
    """The warmed-shape manifest: which (kernel hash, shape key) pairs
    have been compiled, plus a bounded history of warmup runs.  Load is
    tolerant (a corrupt or missing file starts empty); save is atomic
    (tmp + replace) so a killed sidecar can never leave a torn file."""

    def __init__(self, path: str | None = None):
        self.path = path or default_manifest_path()
        self.data = self._load()

    def _load(self) -> dict:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            if isinstance(data, dict) and \
                    data.get("schema") == MANIFEST_SCHEMA and \
                    isinstance(data.get("kernels"), dict) and \
                    isinstance(data.get("runs"), list):
                return data
        except (OSError, ValueError):
            pass
        return {"schema": MANIFEST_SCHEMA, "kernels": {}, "runs": []}

    def seen(self, kernel: str, key: str,
             cache_dir: str | None = None) -> bool:
        """True when this (kernel, key) pair was warmed before AND — if
        ``cache_dir`` is given — it was warmed against that same XLA
        cache dir, which still exists on disk.  The dir checks keep the
        warm-boot claim honest: a manifest alone cannot prove the
        compiled programs survived (a wiped or different cache dir
        means this boot recompiles everything regardless of what the
        manifest remembers)."""
        entry = self.data["kernels"].get(kernel, {}) \
            .get("shapes", {}).get(key)
        if entry is None:
            return False
        if cache_dir is None:
            return True
        return entry.get("cache_dir") == cache_dir and \
            os.path.isdir(cache_dir)

    def shape_walls(self, kernel: str) -> dict:
        """``{shape key: last_wall_s}`` for every shape warmed under
        this kernel hash — what graftguard's LaunchDeadlines reads to
        decide warm-boot deadlines (empty dict = cold boot: no record
        of any compiled shape for this exact kernel)."""
        shapes = self.data["kernels"].get(kernel, {}).get("shapes", {})
        out = {}
        for key, entry in shapes.items():
            if isinstance(entry, dict) and \
                    isinstance(entry.get("last_wall_s"), (int, float)):
                out[key] = float(entry["last_wall_s"])
        return out

    def cold_wall_s(self) -> float | None:
        """Wall time of the most expensive recorded COLD warmup run —
        the max wall among runs that paid at least one miss (None when
        no such run is on record).  graftguard's acceptance bar compares
        the crash-only reboot's re-warm wall against half of this."""
        walls = [r.get("wall_s") for r in self.data["runs"]
                 if isinstance(r, dict) and r.get("misses")
                 and isinstance(r.get("wall_s"), (int, float))]
        return max(walls) if walls else None

    def record(self, kernel: str, key: str, wall_s: float,
               now: float | None = None,
               cache_dir: str | None = None) -> None:
        shapes = self.data["kernels"].setdefault(
            kernel, {"shapes": {}})["shapes"]
        entry = shapes.setdefault(key, {
            "first_warmed_at": now if now is not None else time.time()})
        entry["last_wall_s"] = round(wall_s, 3)
        entry["cache_dir"] = cache_dir

    def record_run(self, kernel: str, hits: int, misses: int,
                   wall_s: float, now: float | None = None) -> None:
        self.data["runs"].append({
            "t": now if now is not None else time.time(),
            "kernel": kernel,
            "hits": hits,
            "misses": misses,
            "wall_s": round(wall_s, 3),
        })
        del self.data["runs"][:-_MAX_RUNS]

    def save(self) -> None:
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.data, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, self.path)
        except OSError as e:  # manifest is an optimization, never fatal
            log.warning("compile manifest save failed: %r", e)


class CompileTracker:
    """Warmup-time compile accounting against the persistent manifest.

    The sidecar wraps every warmup shape in :meth:`warm`: a shape whose
    (kernel hash, key) pair the manifest already holds is a cache HIT —
    the XLA disk cache deserializes instead of compiling — anything
    else is a MISS that this boot pays for and records.  A second boot
    against a populated cache therefore reports ``misses == 0`` with a
    measurably lower warmup wall time, which is exactly what the
    OP_STATS ``compile`` section (:meth:`snapshot`) and
    ``scripts/warmup_report.py`` surface.  ``clock`` is injectable for
    tests."""

    def __init__(self, cache_dir: str | None = None,
                 manifest_path: str | None = None,
                 clock=None, kernel: str | None = None):
        self.cache_dir = cache_dir
        self._clock = clock or time.monotonic
        self.kernel = kernel or kernel_fingerprint()
        self.manifest = CompileManifest(manifest_path)
        self.hits = 0
        self.misses = 0
        self.shapes: dict[str, dict] = {}
        self._t0 = self._clock()
        self._wall_s: float | None = None

    def warm(self, key: str, thunk):
        """Run one warmup shape under hit/miss + wall-time accounting;
        returns the thunk's result.  A hit requires the manifest entry
        AND the matching, still-present XLA cache dir (a boot with the
        cache disabled or re-pointed counts every shape as a miss —
        it IS recompiling; CompileManifest.seen documents the residual:
        a dir whose files were purged but recreated can still read as
        warm)."""
        hit = self.manifest.seen(self.kernel, key,
                                 cache_dir=self.cache_dir
                                 if self.cache_dir is not None else "")
        t0 = self._clock()
        out = thunk()
        dt = self._clock() - t0
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        self.shapes[key] = {"s": round(dt, 3), "hit": hit}
        self.manifest.record(self.kernel, key, dt,
                             cache_dir=self.cache_dir)
        return out

    def wall_s(self) -> float:
        if self._wall_s is not None:
            return self._wall_s
        return self._clock() - self._t0

    def finish(self) -> None:
        """Close out the warmup: stamp the run into the manifest and
        persist it (idempotent)."""
        if self._wall_s is None:
            self._wall_s = self._clock() - self._t0
            self.manifest.record_run(self.kernel, self.hits, self.misses,
                                     self._wall_s)
            self.manifest.save()

    def snapshot(self) -> dict:
        """The OP_STATS ``compile`` section (JSON-safe)."""
        return {
            "kernel": self.kernel,
            "cache_dir": self.cache_dir,
            "manifest": self.manifest.path,
            "hits": self.hits,
            "misses": self.misses,
            "warm_boot": self.misses == 0 and (self.hits > 0),
            "warmup_wall_s": round(self.wall_s(), 3),
            "shapes": {k: v["s"] for k, v in sorted(self.shapes.items())},
        }

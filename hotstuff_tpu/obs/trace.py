"""Collector/merger: per-process spans -> per-block commit traces.

Inputs (all best-effort; a run that produced no spans yields ``None``):

  * node logs (``node-*.log``) carrying the C++ node's machine-parseable
    ``TRACE stage=<s> block=<digest> round=<r>`` lines (emitted behind
    the parameters-file ``trace`` flag at the consensus hot-path stages:
    ``proposal`` received, ``verify_submit`` to the sidecar,
    ``verify_reply`` from it, block ``commit``);
  * sidecar spans (``sidecar-spans.jsonl``, the obs.spans schema) tagged
    rid + scheduler class;
  * per-host clock offsets (``clock-offsets.json``; absent = one host,
    offset 0), estimated RTT-midpoint style — the harness's existing
    ssh transport answers the probe on remote runs.

Outputs:

  * per-block commit traces (stage -> earliest wall stamp across logs,
    the same earliest-occurrence merge the LogParser's commit metrics
    use) and the **critical-path breakdown**: p50/p99 per consecutive
    stage segment, which LogParser surfaces as "Commit critical path"
    notes and bench.py as the headline ``trace`` field;
  * a Chrome-trace-event JSON artifact (``logs/trace.json``) loadable
    in Perfetto / ``chrome://tracing``.
"""

from __future__ import annotations

import json
import os
from datetime import datetime
from glob import glob
from re import findall
from statistics import median

from .spans import parse_spans

# Consensus hot-path stage chain, in commit order.  Segment names pair
# consecutive stages; blocks missing the verify stages (cached
# certificates, host-path verifies) still contribute to the total.
NODE_STAGES = ("proposal", "verify_submit", "verify_reply", "commit")
SEGMENTS = tuple(f"{a}->{b}" for a, b in zip(NODE_STAGES, NODE_STAGES[1:]))
TOTAL_SEGMENT = "proposal->commit"
# graftscope: the named device sub-segment of verify — the sidecar's
# ctx-joined device span durations, reported next to the node segments
# so "where did verify time go" has a device answer.
DEVICE_SEGMENT = "verify:device"

# The frozen node log grammar (common/log.hpp) around the TRACE payload
# emitted by consensus/core.cpp: timestamp, level, module, then
# "TRACE stage=<s> block=<digest> round=<r>".
_NODE_TRACE_RE = (r"\[(\S+Z) \w+ [^\]]+\] TRACE "
                  r"stage=(\w+) block=(\S+) round=(\d+)")


def _to_posix(ts: str) -> float:
    return datetime.fromisoformat(ts.replace("Z", "+00:00")).timestamp()


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile of an ascending list (empty -> 0), the
    sched/stats.py convention."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


# -- node spans --------------------------------------------------------------


def parse_node_trace(log: str, host: str = "node") -> list:
    """One node log -> TRACE span dicts
    ``{"host", "stage", "t", "block", "round"}`` (invalid stages and
    torn fragments simply don't match the regex — tolerance for free)."""
    spans = []
    for ts, stage, block, rnd in findall(_NODE_TRACE_RE, log):
        if stage not in NODE_STAGES:
            continue
        try:
            t = _to_posix(ts)
        except ValueError:
            continue
        spans.append({"host": host, "stage": stage, "t": t,
                      "block": block, "round": int(rnd)})
    return spans


# -- clock alignment ---------------------------------------------------------


def clock_offset(t_send: float, t_remote: float, t_recv: float) -> float:
    """RTT-midpoint offset estimate for one probe: the remote stamp is
    assumed taken halfway through the round trip, so
    ``offset = t_remote - (t_send + t_recv) / 2`` and
    ``local = remote - offset``.  Error is bounded by RTT/2 plus path
    asymmetry — low milliseconds on the fleets this harness drives."""
    return t_remote - (t_send + t_recv) / 2.0


def estimate_offset(probes) -> float:
    """Median offset over ``(t_send, t_remote, t_recv)`` probe triples
    (median discards the odd delayed round trip)."""
    if not probes:
        return 0.0
    return median(clock_offset(*p) for p in probes)


def probe_host_offset(run_fn, host: str, clock, samples: int = 5) -> float:
    """Estimate one remote host's clock offset through a transport.

    ``run_fn(host, command)`` must execute the command remotely and
    return its stdout (the harness's ssh RemoteRunner satisfies this
    with ``lambda h, c: runner.run(h, c, timeout=...).stdout``);
    ``clock`` is the local wall clock.  Probes that fail to parse are
    skipped — an unreachable host estimates as offset 0 rather than
    killing the trace."""
    probes = []
    for _ in range(samples):
        t_send = clock()
        try:
            out = run_fn(host, "date +%s.%N")
            t_remote = float(str(out).strip().splitlines()[-1])
        except (ValueError, IndexError, OSError, RuntimeError,
                AttributeError, TypeError):
            # Includes transports that answer with nothing (a stubbed
            # or wedged runner): a probe that cannot parse is a skip.
            # A host that has never answered is almost certainly down —
            # stop after ONE failed dial instead of paying the transport
            # timeout `samples` times for a best-effort artifact.
            if not probes:
                break
            continue
        probes.append((t_send, t_remote, clock()))
    return estimate_offset(probes)


def apply_offset(spans, offset_s: float):
    """Shift spans from a skewed host onto the reference clock
    (``local = remote - offset``); returns new dicts, input untouched."""
    if not offset_s:
        return list(spans)
    return [dict(s, t=s["t"] - offset_s) for s in spans]


# -- stitching + critical path -----------------------------------------------


def stitch_blocks(spans) -> dict:
    """Aligned node spans -> ``{(block, round): {stage: t}}`` with the
    earliest stamp winning per stage (the LogParser's merge convention:
    N replicas trace the same block; the fastest observation is the
    committee's critical path, stragglers are their own problem)."""
    traces: dict = {}
    for s in spans:
        key = (s["block"], s["round"])
        stages = traces.setdefault(key, {})
        t = s["t"]
        if s["stage"] not in stages or stages[s["stage"]] > t:
            stages[s["stage"]] = t
    return traces


def critical_path(traces: dict) -> dict:
    """Per-block stage segments -> p50/p99 breakdown::

        {"blocks": N, "complete": M,     # all four stages present
         "segments": {"proposal->commit": {"n", "p50_ms", "p99_ms"},
                      "proposal->verify_submit": {...}, ...}}

    A dropped/partial span (a stage some block never logged) only
    removes that block from the segments needing the stage — every
    segment whose two endpoints exist still counts, so a chaos-killed
    replica degrades the sample count, not the breakdown."""
    seg_samples: dict = {name: [] for name in SEGMENTS + (TOTAL_SEGMENT,)}
    complete = 0
    for stages in traces.values():
        if all(s in stages for s in NODE_STAGES):
            complete += 1
        for name, (a, b) in zip(SEGMENTS, zip(NODE_STAGES,
                                              NODE_STAGES[1:])):
            if a in stages and b in stages:
                seg_samples[name].append((stages[b] - stages[a]) * 1e3)
        if "proposal" in stages and "commit" in stages:
            seg_samples[TOTAL_SEGMENT].append(
                (stages["commit"] - stages["proposal"]) * 1e3)
    segments = {}
    for name, vals in seg_samples.items():
        vals.sort()
        segments[name] = {
            "n": len(vals),
            "p50_ms": round(_percentile(vals, 0.50), 3),
            "p99_ms": round(_percentile(vals, 0.99), 3),
        }
    return {"blocks": len(traces), "complete": complete,
            "segments": segments}


# -- graftscope: per-block node<->sidecar joins ------------------------------


def chain_spans(sidecar_spans) -> dict:
    """ctx-tagged sidecar spans -> ``{block_digest_b64: [spans]}``.

    The sidecar tags per-request spans (admit/queue/reply) with ``ctx``
    and per-launch spans (pack/dispatch/device) with a ``ctxs`` list —
    both carry the protocol-v5 context tag as the SAME base64 string the
    C++ node logs in ``block=`` (common/bytes.hpp base64_encode), so the
    join is plain string equality.  A launch coalescing several blocks'
    requests contributes its spans to every one of their chains."""
    chains: dict = {}
    for s in sidecar_spans:
        tags = []
        ctx = s.get("ctx")
        if isinstance(ctx, str):
            tags.append(ctx)
        ctxs = s.get("ctxs")
        if isinstance(ctxs, (list, tuple)):
            tags.extend(c for c in ctxs if isinstance(c, str))
        for c in tags:
            chains.setdefault(c, []).append(s)
    return chains


def join_blocks(traces: dict, chains: dict):
    """Per-block traces + ctx chains -> ``(join, joined)``.

    ``join`` is the machine-readable accounting::

        {"committed": N,     # blocks with a commit stage
         "with_verify": M,   # of those, blocks whose verify segment
                             # (verify_submit AND verify_reply) traced
         "joined": J,        # of those, blocks whose digest has a
                             # sidecar chain with a device span
         "rate": J / M}      # None when no block traced a verify

    ``joined`` maps ``(block, round) -> chain spans`` for the blocks
    that joined — what the Chrome exporter nests inside the block's
    verify segment.  A block whose chain is missing (fast-path cache
    answer on every replica, a torn span file) degrades the rate, never
    the trace."""
    committed = sum(1 for st in traces.values() if "commit" in st)
    with_verify = 0
    joined: dict = {}
    for key, stages in traces.items():
        if "commit" not in stages:
            continue
        if "verify_submit" not in stages or "verify_reply" not in stages:
            continue
        with_verify += 1
        chain = chains.get(key[0])
        if chain and any(s.get("stage") == "device" for s in chain):
            joined[key] = chain
    rate = round(len(joined) / with_verify, 4) if with_verify else None
    return ({"committed": committed, "with_verify": with_verify,
             "joined": len(joined), "rate": rate}, joined)


def device_subsegment(joined: dict) -> dict:
    """Joined chains -> the ``verify:device`` sub-segment percentiles
    (per-block device milliseconds: the sum of the chain's device span
    durations — one block's QC verify can split across launches)."""
    vals = []
    for chain in joined.values():
        ms = sum(float(s.get("dur_ms") or 0.0) for s in chain
                 if s.get("stage") == "device")
        vals.append(ms)
    vals.sort()
    return {"n": len(vals),
            "p50_ms": round(_percentile(vals, 0.50), 3),
            "p99_ms": round(_percentile(vals, 0.99), 3)}


def sidecar_breakdown(spans) -> dict:
    """Sidecar JSONL spans -> per-stage duration percentiles (same
    shape as the critical-path segments, keyed by span stage)."""
    by_stage: dict = {}
    for s in spans:
        dur = s.get("dur_ms")
        if isinstance(dur, (int, float)):
            by_stage.setdefault(s["stage"], []).append(float(dur))
    out = {}
    for stage, vals in sorted(by_stage.items()):
        vals.sort()
        out[stage] = {"n": len(vals),
                      "p50_ms": round(_percentile(vals, 0.50), 3),
                      "p99_ms": round(_percentile(vals, 0.99), 3)}
    return out


# -- Chrome trace export -----------------------------------------------------

_PID_CONSENSUS = 1
_PID_SIDECAR = 2


def chrome_trace(traces: dict, sidecar_spans=(), joined=None) -> dict:
    """Per-block traces + sidecar spans -> a Chrome trace-event JSON
    object (Perfetto-loadable: complete events, microsecond stamps
    normalized to the earliest span, process-name metadata).

    ``joined`` (graftscope, from :func:`join_blocks`) nests each joined
    block's sidecar stage chain INSIDE that block's row on the consensus
    process: the chain's spans are re-emitted at ``pid`` consensus /
    ``tid`` round (cat ``sidecar``, block in args), so opening a block
    in Perfetto shows device time as a sub-segment of its verify
    segment.  The flat sidecar-process timeline is kept too — it still
    carries the un-joined spans (bulk traffic, zero-tag requests)."""
    events = []
    t0_candidates = [min(stages.values()) for stages in traces.values()
                     if stages]
    t0_candidates += [s["t"] for s in sidecar_spans]
    t_base = min(t0_candidates) if t0_candidates else 0.0

    def us(t):
        return round((t - t_base) * 1e6, 1)

    for (block, rnd), stages in sorted(traces.items(),
                                       key=lambda kv: kv[0][1]):
        for name, (a, b) in zip(SEGMENTS, zip(NODE_STAGES,
                                              NODE_STAGES[1:])):
            if a in stages and b in stages:
                events.append({
                    "name": name, "ph": "X", "cat": "consensus",
                    "ts": us(stages[a]),
                    "dur": max(0.0, us(stages[b]) - us(stages[a])),
                    "pid": _PID_CONSENSUS, "tid": rnd,
                    "args": {"block": block, "round": rnd},
                })
    for (block, rnd), chain in sorted((joined or {}).items(),
                                      key=lambda kv: kv[0][1]):
        for s in chain:
            events.append({
                "name": f"sidecar:{s['stage']}", "ph": "X",
                "cat": "sidecar",
                "ts": us(s["t"]),
                "dur": max(0.0, float(s.get("dur_ms") or 0.0) * 1e3),
                "pid": _PID_CONSENSUS, "tid": rnd,
                "args": {"block": block, "round": rnd,
                         "rid": s.get("rid")},
            })
    for s in sidecar_spans:
        args = {k: v for k, v in s.items()
                if k not in ("stage", "t", "dur_ms")}
        events.append({
            "name": s["stage"], "ph": "X", "cat": "sidecar",
            "ts": us(s["t"]),
            "dur": max(0.0, float(s.get("dur_ms") or 0.0) * 1e3),
            "pid": _PID_SIDECAR, "tid": 0,
            "args": args,
        })
    for pid, name in ((_PID_CONSENSUS, "consensus (merged replicas)"),
                      (_PID_SIDECAR, "verify sidecar")):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": name}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"t_base_s": round(t_base, 6)}}


# -- directory-level entry points (the harness contract) ---------------------


def build_run_trace(directory: str):
    """Mine one logs directory -> ``(summary, chrome)`` or
    ``(None, None)`` when the run traced nothing (trace flag off, or
    pre-grafttrace logs).

    Reads ``node-*.log`` TRACE lines, ``sidecar-spans.jsonl``, and
    ``clock-offsets.json`` (``{"node-3.log": seconds, ...}`` keyed by
    log file name; missing entries are offset 0)."""
    offsets = {}
    try:
        with open(os.path.join(directory, "clock-offsets.json")) as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            offsets = {k: float(v) for k, v in loaded.items()
                       if isinstance(v, (int, float))}
    except (OSError, ValueError):
        pass
    node_spans = []
    for path in sorted(glob(os.path.join(directory, "node-*.log"))):
        name = os.path.basename(path)
        with open(path, "r", errors="replace") as f:
            spans = parse_node_trace(f.read(), host=name)
        node_spans.extend(apply_offset(spans, offsets.get(name, 0.0)))
    sc_spans, malformed = [], 0
    try:
        with open(os.path.join(directory, "sidecar-spans.jsonl"),
                  errors="replace") as f:
            sc_spans, malformed = parse_spans(f.read())
    except OSError:
        pass
    sc_spans = apply_offset(sc_spans,
                            offsets.get("sidecar-spans.jsonl", 0.0))
    if not node_spans and not sc_spans:
        return None, None
    traces = stitch_blocks(node_spans)
    summary = critical_path(traces)
    summary["sidecar"] = sidecar_breakdown(sc_spans)
    summary["malformed_spans"] = malformed
    # graftscope: join the ctx-tagged sidecar chains onto their blocks —
    # device time becomes the verify:device sub-segment and join_rate
    # says what fraction of verify-traced committed blocks carried one.
    join, joined = join_blocks(traces, chain_spans(sc_spans))
    summary["join"] = join
    if joined:
        summary["segments"][DEVICE_SEGMENT] = device_subsegment(joined)
    chrome = chrome_trace(traces, sc_spans, joined=joined)
    summary["chrome_events"] = len(chrome["traceEvents"])
    return summary, chrome


def write_run_trace(directory: str):
    """Build and persist ``<directory>/trace.json``; returns the
    summary (``None`` when the run traced nothing — no file is written,
    so downstream tooling can tell "no trace" from "empty trace")."""
    summary, chrome = build_run_trace(directory)
    if summary is None:
        return None
    tmp = os.path.join(directory, "trace.json.tmp")
    with open(tmp, "w") as f:
        json.dump(chrome, f)
    os.replace(tmp, os.path.join(directory, "trace.json"))
    return summary

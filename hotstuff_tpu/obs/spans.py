"""Span records + the JSONL tracer the sidecar hot path writes through.

One span = one JSON object on its own line::

    {"stage": "pack", "t": 1722600000.123, "dur_ms": 4.2,
     "rid": 17, "cls": "latency", ...}

``t`` is the span's START as wall-clock seconds (the merger aligns
wall clocks across hosts; monotonic stamps cannot be merged), ``dur_ms``
its duration; instantaneous marks carry ``dur_ms: 0``.  Everything else
is free-form tags — the sidecar tags ``rid`` (request id) and ``cls``
(scheduler class) so a request can be followed admit -> queue -> pack ->
dispatch -> device -> reply.

Discipline (enforced mechanically by graftlint's ``unclosed-span``
checker over the obs-instrumented modules):

  * a ``begin_span`` must reach its ``end_span`` on every return path —
    use the ``span()`` context manager, or pair them in a ``finally``;
  * timestamps come from the INJECTED clock only (``clock=`` at
    construction), never an inline ``time.time()`` — virtual-clock
    tests and the trace merger's offset math both depend on one
    substitutable time source per process.

Telemetry is best-effort by contract: a tracer whose sink fails (disk
full, path unwritable) disables itself and the engine keeps verifying —
spans must never take the data plane down with them.
"""

from __future__ import annotations

import json
import threading
from time import time as _wall_clock


class SpanError(ValueError):
    """Malformed span record (parse-side only; writers never raise)."""


class Tracer:
    """Thread-safe append-only JSONL span writer.

    ``Tracer(None)`` (or ``Tracer.disabled()``) is the null tracer:
    every call is a cheap no-op, so instrumented code needs no
    ``if tracing:`` guards at the call sites.
    """

    def __init__(self, path: str | None, clock=_wall_clock):
        self._path = path
        self._clock = clock
        self._lock = threading.Lock()
        self._file = None
        self.enabled = path is not None
        self.dropped = 0  # spans lost to sink failures (telemetry)

    @classmethod
    def disabled(cls) -> "Tracer":
        return cls(None)

    # -- recording -----------------------------------------------------------

    def begin_span(self, stage: str, **tags) -> dict:
        """Open a span; the returned token MUST reach :meth:`end_span`
        on every return path (use :meth:`span` where control flow
        allows)."""
        if not self.enabled:
            return {}
        token = {"stage": stage, "t": self._clock()}
        token.update(tags)
        return token

    def end_span(self, token: dict, **tags):
        """Close a span begun by :meth:`begin_span` and write it."""
        if not self.enabled or not token:
            return
        rec = dict(token)
        rec.update(tags)
        rec["dur_ms"] = round((self._clock() - rec["t"]) * 1e3, 3)
        self._write(rec)

    def span(self, stage: str, **tags):
        """``with tracer.span("pack", rid=7): ...`` — begin/end pairing
        the interpreter guarantees."""
        return _SpanCtx(self, stage, tags)

    def event(self, stage: str, dur_ms: float | None = None, **tags):
        """One-shot record: an instantaneous mark, or a span whose
        duration was measured elsewhere (cross-thread stages carry a
        start stamp in their bookkeeping instead of an open token)."""
        if not self.enabled:
            return
        rec = {"stage": stage, "t": self._clock(),
               "dur_ms": round(dur_ms, 3) if dur_ms is not None else 0.0}
        rec.update(tags)
        self._write(rec)

    def now(self) -> float:
        """The tracer's clock (for cross-thread duration bookkeeping —
        the one sanctioned way instrumented code reads time)."""
        return self._clock()

    # -- sink ----------------------------------------------------------------

    def _write(self, rec: dict):
        try:
            line = json.dumps(rec, sort_keys=True)
        except (TypeError, ValueError):
            self.dropped += 1
            return
        with self._lock:
            if not self.enabled:
                return
            try:
                if self._file is None:
                    self._file = open(self._path, "a", encoding="utf-8")
                self._file.write(line + "\n")
                self._file.flush()
            except OSError:
                # Sink gone: disable forever, never stall the engine.
                self.enabled = False
                self.dropped += 1
                try:
                    if self._file is not None:
                        self._file.close()
                except OSError:
                    pass
                self._file = None

    def close(self):
        with self._lock:
            self.enabled = False
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


class _SpanCtx:
    __slots__ = ("_tracer", "_stage", "_tags", "_token")

    def __init__(self, tracer: Tracer, stage: str, tags: dict):
        self._tracer = tracer
        self._stage = stage
        self._tags = tags
        self._token = {}

    def __enter__(self):
        self._token = self._tracer.begin_span(self._stage, **self._tags)
        return self._token

    def __exit__(self, exc_type, exc, tb):
        self._tracer.end_span(self._token,
                              **({"error": True} if exc_type else {}))
        return False


def parse_jsonl(text: str, valid):
    """JSONL text -> ``(records, malformed)`` with ``valid(rec)`` as the
    per-record predicate (records are always dicts by the time it runs).

    This is THE torn-line tolerance contract for the whole obs package
    (spans and metrics share it): concurrent writers, or a chaos SIGKILL
    mid-line, can tear lines; torn/garbage lines are skipped and
    counted, never raised — the same contract as the LogParser's log
    sanitizer."""
    records = []
    malformed = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            malformed += 1
            continue
        if not isinstance(rec, dict) or not valid(rec):
            malformed += 1
            continue
        records.append(rec)
    return records, malformed


def parse_spans(text: str):
    """JSONL span text -> ``(spans, malformed)`` (torn lines skipped and
    counted; see :func:`parse_jsonl`)."""
    return parse_jsonl(
        text,
        lambda rec: "stage" in rec and isinstance(rec.get("t"),
                                                  (int, float)))
